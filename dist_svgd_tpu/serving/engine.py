"""Predictive engine: jitted per-model posterior-predictive kernels over a
checkpointed ensemble, behind a shape-bucketed compile cache.

The models' one-shot batch helpers (``models/logreg.py:
posterior_predictive_prob``, ``models/bnn.py:predict``, the GMM density) have
no request path: every distinct request-batch shape would trace a fresh XLA
program, and a multi-process checkpoint has no single file to load.  The
engine closes both gaps:

- **Checkpoint cold start** (:meth:`PredictiveEngine.from_checkpoint`): a
  single ``save_state`` dir loads via ``load_state``; a ``CheckpointManager``
  root restores the newest *loadable* step (corrupt/partial newest dirs are
  skipped — ``utils/checkpoint.py:restore_latest``); a list of paths is
  treated as one multi-process save and reassembled into the global ensemble
  via ``assemble_full_state``.
- **Shape-bucketed compile cache**: a request batch of ``b`` rows pads up to
  the next power-of-two bucket (≥ ``min_bucket``) and runs the bucket's
  cached jitted kernel, so at most ``log2(max_bucket/min_bucket)+1`` programs
  are ever traced regardless of traffic mix.  Hits/misses are counted
  (:meth:`stats`) — steady-state traffic must be all hits.
- **Mesh-sharded dispatch** (round 12): pass ``plan``/``mesh`` and the
  ensemble is placed with ``NamedSharding(mesh, PartitionSpec('shards',
  None))`` while every bucket kernel compiles through the unified
  :class:`~dist_svgd_tpu.parallel.plan.Plan` entrypoint — replicated
  request batches in, particle-sharded reduction inside, replicated
  outputs out.  The mesh that trains the ensemble now serves it; without
  a mesh the plan degrades to exactly the old single-device ``jit``.
  Hot reload re-places every new generation through the same plan, so a
  swap can never silently de-shard the served ensemble.
- **Buffer donation + low-precision** (round 12): dispatch inputs are
  pre-placed replicated and donated (``donate=False`` opts out), and an
  opt-in ``dtype=jnp.bfloat16`` stores + computes the ensemble in bf16
  while keeping f32 request/response surfaces (outputs are upcast in the
  kernel; numerics pinned vs the f32 path in tests/test_plan.py).

Padding is exact, not approximate: every per-row output depends only on that
row (row-wise matmul + elementwise + particle-axis reduction), so the served
values are bitwise-equal to a direct full-batch call on the same ensemble
(pinned by ``tests/test_serving.py:test_end_to_end_bitwise``).
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from dist_svgd_tpu.models import bnn as bnn_model
from dist_svgd_tpu.models.logreg import posterior_predictive_prob
from dist_svgd_tpu.parallel.plan import Plan
from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry import trace as _trace
from dist_svgd_tpu.telemetry import usage as _usage

_LOG_2PI = math.log(2.0 * math.pi)

MODELS = ("logreg", "bnn", "gmm")


class EnsembleRejected(RuntimeError):
    """A hot reload was refused: the candidate ensemble's diagnostics
    regressed past the engine's :class:`~dist_svgd_tpu.telemetry.
    diagnostics.ReloadPolicy` thresholds.  ``reasons`` lists the failed
    checks; ``report`` carries the candidate's health statistics."""

    def __init__(self, reasons, report):
        super().__init__("ensemble rejected: " + "; ".join(reasons))
        self.reasons = list(reasons)
        self.report = report


def bucket_for(rows: int, min_bucket: int) -> int:
    """Smallest power-of-two ≥ ``rows``, clamped up to ``min_bucket``."""
    if rows <= 0:
        raise ValueError(f"rows must be positive, got {rows}")
    return max(min_bucket, 1 << (rows - 1).bit_length())


def _looks_like_manager_root(path: str) -> bool:
    from dist_svgd_tpu.utils.checkpoint import _STEP_DIR_RE

    return any(
        _STEP_DIR_RE.match(name) and os.path.isdir(os.path.join(path, name))
        for name in os.listdir(path)
    )


class PredictiveEngine:
    """Low-latency posterior-predictive evaluation of one particle ensemble.

    Args:
        model: ``'logreg'`` (class-probability mean + variance over the
            ensemble, the ``posterior_predictive_prob`` semantics — α decoded
            but unused, reference quirk), ``'bnn'`` (regression mean + std on
            the original target scale, ``models/bnn.py:unpack`` layout), or
            ``'gmm'`` (ensemble KDE log-density — the particle set *is* the
            posterior sample, so the served density is the mixture of
            ``N(θ_p, kde_bandwidth²·I)`` over particles).
        particles: ``(n, d)`` ensemble array (any array-like).
        n_features / n_hidden: BNN layout parameters (``n_features`` is
            required for ``'bnn'``; ``d`` must equal ``num_params``).
        y_mean / y_std: BNN target destandardisation (the training drivers
            standardise targets; serving reports original-scale values).
        kde_bandwidth: GMM KDE kernel width.
        min_bucket / max_bucket: padding-bucket range, each rounded UP to a
            power of two (so ``warmup()`` provably covers every reachable
            bucket).  Requests larger than the rounded ``max_bucket`` are
            rejected — the batcher splits oversize requests *before* the
            engine sees them.
        plan / mesh: mesh-sharded dispatch (round 12).  ``plan`` is a
            :class:`~dist_svgd_tpu.parallel.plan.Plan`; ``mesh`` is the
            shorthand (a 1-D ``'shards'``-axis ``Mesh``, wrapped into a
            plan).  The ensemble is particle-sharded across the plan's
            devices and every bucket kernel compiles with explicit
            in/out shardings; omit both (or pass a mesh-less plan) for
            the single-device path.  A particle count the mesh doesn't
            divide replicates with a warning instead of failing.
        dtype: opt-in low-precision serve path (``jnp.bfloat16``): the
            ensemble is stored and the kernels compute in this dtype;
            request/response surfaces stay f32 (inputs cast inside the
            kernel, outputs upcast before the fetch).  Default ``None``
            keeps the checkpoint's dtype untouched.
        donate: donate the dispatch input buffer to XLA
            (``donate_argnums``) so steady-state ``/predict`` stops
            re-allocating it per call; served values are unchanged (the
            bitwise E2E pin covers this path).  Reload warm-up buffers
            ride the same compiled programs and are donated too.
        registry: ``telemetry.MetricsRegistry`` for the compile-cache
            hit/miss/reload counters (default: the process-wide registry).
            :meth:`stats` keeps per-instance counts alongside.
        tenant: multi-tenant identity (round 14).  When set (the
            :class:`~dist_svgd_tpu.serving.registry.ModelRegistry` sets
            it), every engine metric carries a ``tenant=`` label so one
            Prometheus scrape separates the tenants; unset engines keep
            the unlabelled series — single-tenant deployments are
            unchanged.
        kernel_cache: optional shared
            :class:`~dist_svgd_tpu.serving.registry.KernelBucketLRU` —
            the process-wide bound on compiled kernel buckets across
            tenants.  Every bucket use is reported to it; when the bound
            overflows, the least-recently-used bucket anywhere in the
            process is dropped (this engine's :meth:`_evict_bucket`
            callback), so a cold tenant cannot permanently pin compile
            cache while a hot tenant's buckets, touched every request,
            are never the LRU victim.  ``None`` (default) keeps the
            engine's own cache unbounded, exactly as before.
        reload_policy: optional :class:`~dist_svgd_tpu.telemetry.
            diagnostics.ReloadPolicy` — every :meth:`reload` candidate is
            health-checked (score-free ensemble diagnostics: kernel ESS,
            collapse indicators) against absolute floors and the
            currently-served ensemble's numbers; a regressed candidate
            raises :class:`EnsembleRejected` (and dumps a flight-recorder
            postmortem when one is installed) instead of being swapped in
            — a diverged training run cannot silently poison serving.
    """

    def __init__(
        self,
        model: str,
        particles,
        *,
        n_features: Optional[int] = None,
        n_hidden: int = 50,
        y_mean: float = 0.0,
        y_std: float = 1.0,
        kde_bandwidth: float = 1.0,
        min_bucket: int = 8,
        max_bucket: int = 4096,
        plan: Optional[Plan] = None,
        mesh=None,
        dtype=None,
        donate: bool = True,
        registry: Optional[_metrics.MetricsRegistry] = None,
        reload_policy=None,
        tenant: Optional[str] = None,
        kernel_cache=None,
    ):
        if model not in MODELS:
            raise ValueError(f"unknown model {model!r}; expected one of {MODELS}")
        if min_bucket < 1 or max_bucket < min_bucket:
            raise ValueError(
                f"need 1 <= min_bucket <= max_bucket, got {min_bucket}/{max_bucket}"
            )
        if plan is not None and mesh is not None:
            raise ValueError("pass plan= or mesh=, not both")
        self._plan = plan if plan is not None else Plan(mesh)
        self._donate = bool(donate)
        self._compute_dtype = jnp.dtype(dtype) if dtype is not None else None
        if (self._compute_dtype is not None
                and not jnp.issubdtype(self._compute_dtype, jnp.floating)):
            raise ValueError(
                f"dtype must be a float dtype, got {self._compute_dtype}"
            )
        # normalise both ends up to powers of two: a non-pow2 max_bucket
        # (e.g. --max-batch 100) would otherwise admit requests whose bucket
        # (128) warmup() never traced — an in-window recompile that breaks
        # the steady-state contract
        min_bucket = 1 << (min_bucket - 1).bit_length()
        max_bucket = 1 << (max_bucket - 1).bit_length()
        self._particles = self._place_ensemble(particles)
        self.model = model
        n, d = self._particles.shape
        if model == "logreg":
            if d < 2:
                raise ValueError("logreg particles need d >= 2 (log α, w)")
            self._feature_dim = d - 1
        elif model == "bnn":
            if n_features is None:
                raise ValueError("model='bnn' requires n_features")
            want = bnn_model.num_params(n_features, n_hidden)
            if d != want:
                raise ValueError(
                    f"bnn particles have d={d}, but num_params(n_features="
                    f"{n_features}, n_hidden={n_hidden}) = {want}"
                )
            self._feature_dim = n_features
        else:  # gmm: queries live in particle space
            self._feature_dim = d
        self._n_features = n_features
        self._n_hidden = n_hidden
        self._y_mean = float(y_mean)
        self._y_std = float(y_std)
        if kde_bandwidth <= 0:
            raise ValueError("kde_bandwidth must be positive")
        self._kde_bandwidth = float(kde_bandwidth)
        self.min_bucket = int(min_bucket)
        self.max_bucket = int(max_bucket)
        # bucket -> jitted kernel; guarded for concurrent predict() callers
        # (the batcher serialises dispatches, but the engine is also usable
        # directly from request threads).  reload() swaps (_particles,
        # _kernels) as a pair under the same lock, so every predict sees a
        # consistent ensemble/kernel view — the hot-reload atomicity
        self._kernels: Dict[int, Any] = {}
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._reloads = 0
        self._evictions = 0
        # generation identity (round 21): every resident ensemble carries a
        # monotonically-minted id.  The cold-start ensemble is generation 1;
        # each admitted reload / staged candidate mints the next id.  After
        # an admitted swap the PREVIOUS generation stays resident (particles
        # + its compiled kernel dict), so rollback() is one lock-guarded
        # pointer exchange — never a checkpoint re-load.
        self._generation_id = 1
        self._next_generation = 2
        self._prev_particles: Optional[jax.Array] = None
        self._prev_kernels: Optional[Dict[int, Any]] = None
        self._prev_tag: Optional[str] = None
        self._prev_generation: Optional[int] = None
        self._prev_health: Optional[Dict[str, Any]] = None
        self._rollbacks = 0
        # candidate generation (round 21, progressive delivery): staged by
        # stage_candidate(), served only via predict(generation='candidate')
        # — the rollout controller's per-generation dispatch seam.  Promotion
        # is the same pointer-exchange discipline as reload's admitted swap.
        self._cand_particles: Optional[jax.Array] = None
        self._cand_kernels: Optional[Dict[int, Any]] = None
        self._cand_tag: Optional[str] = None
        self._cand_generation: Optional[int] = None
        #: Tenant identity on every metric series (empty dict = unlabelled,
        #: the single-tenant series — backward compatible).
        self.tenant = tenant
        self._tlabels = {} if tenant is None else {"tenant": str(tenant)}
        self._kernel_cache = kernel_cache
        reg = registry if registry is not None else _metrics.default_registry()
        self.registry = reg
        self._m_hits = reg.counter(
            "svgd_engine_bucket_hits_total", "padding-bucket kernel-cache hits")
        self._m_misses = reg.counter(
            "svgd_engine_bucket_misses_total",
            "padding-bucket kernel-cache misses (one XLA trace each)")
        self._m_reloads = reg.counter(
            "svgd_engine_reloads_total", "hot ensemble swaps")
        self._m_reload_wall = reg.histogram(
            "svgd_engine_reload_wall_s",
            "wall per hot ensemble swap (policy judge + kernel rebuild + "
            "warm + pointer exchange) — the freshness budget's reload leg")
        self._m_reload_rejects = reg.counter(
            "svgd_engine_reload_rejected_total",
            "hot reloads refused by the ensemble-health policy")
        self._m_evictions = reg.counter(
            "svgd_registry_evictions_total",
            "compiled kernel buckets evicted by the shared LRU")
        self._m_rollbacks = reg.counter(
            "svgd_engine_rollbacks_total",
            "O(1) swaps back to the resident previous generation")
        self._reload_policy = reload_policy
        self._reload_rejects = 0
        # served ensemble's health baseline (computed lazily at the first
        # policied reload; refreshed on every admitted swap)
        self._health_report: Optional[Dict[str, Any]] = None
        self._ensemble_tag: Optional[str] = None
        #: Manager-root step this ensemble was cold-started from (set by
        #: :meth:`from_checkpoint`; ``None`` for direct/array construction).
        self.checkpoint_step: Optional[int] = None

    # ------------------------------------------------------------------ #
    # construction from checkpoints

    @classmethod
    def from_checkpoint(
        cls,
        source: Union[str, Sequence[str]],
        model: str,
        *,
        key: str = "particles",
        **kwargs,
    ) -> "PredictiveEngine":
        """Build an engine from any of the repo's checkpoint layouts.

        ``source`` may be: a single checkpoint dir (``save_state`` layout), a
        ``CheckpointManager`` root (``step_<t>/`` children — the newest
        *loadable* step is restored, skipping corrupt/partial ones), or a
        list/tuple of per-process paths from ONE multi-host save (reassembled
        with ``assemble_full_state``).  ``key`` selects the ensemble entry
        (``'particles'`` in every sampler ``state_dict``).
        """
        from dist_svgd_tpu.utils.checkpoint import (
            CheckpointManager,
            assemble_full_state,
            load_state,
        )

        loaded_step = None
        if isinstance(source, (list, tuple)):
            state = assemble_full_state(list(source))
        else:
            path = os.fspath(source)
            if not os.path.isdir(path):
                raise FileNotFoundError(f"checkpoint path {path!r} is not a directory")
            if _looks_like_manager_root(path):
                loaded_step, state = CheckpointManager(path).restore_latest(
                    with_step=True
                )
                if state is None:
                    raise ValueError(
                        f"no restorable checkpoint under manager root {path!r}"
                    )
            else:
                state = load_state(path)
        if state.get(key) is None:
            raise KeyError(
                f"checkpoint has no {key!r} entry (keys: {sorted(state)})"
            )
        engine = cls(model, np.asarray(state[key]), **kwargs)
        # which step this ensemble came from (None for non-manager layouts):
        # CheckpointHotReloader's baseline — a corrupt newest dir or a save
        # racing the cold start must not be marked "already served"
        engine.checkpoint_step = loaded_step
        return engine

    # ------------------------------------------------------------------ #
    # kernels

    @property
    def particles(self) -> jax.Array:
        """The served ensemble (read-only by convention)."""
        return self._particles

    @property
    def n_particles(self) -> int:
        return int(self._particles.shape[0])

    @property
    def feature_dim(self) -> int:
        """Expected per-row input width for :meth:`predict`."""
        return self._feature_dim

    @property
    def plan(self) -> Plan:
        """The sharding plan dispatch compiles under."""
        return self._plan

    def _place_ensemble(self, particles) -> jax.Array:
        """Validate, (optionally) cast to the compute dtype, and place on
        the plan's devices — used by both cold start and :meth:`reload`,
        so a hot swap can never de-shard or de-cast the served ensemble."""
        arr = jnp.asarray(particles)
        if arr.ndim != 2:
            raise ValueError(
                f"particles must be (n, d), got shape {arr.shape}"
            )
        if (self._compute_dtype is not None
                and arr.dtype != self._compute_dtype):
            arr = arr.astype(self._compute_dtype)
        return self._plan.shard_ensemble(arr)

    def _input_dtype(self, particle_dtype):
        """Request-surface dtype for dispatch inputs: the ensemble's own
        dtype, except sub-f32 compute dtypes keep an f32 wire format (the
        kernel casts inside — callers never build bf16 numpy arrays)."""
        return (jnp.float32 if jnp.dtype(particle_dtype).itemsize < 4
                else particle_dtype)

    def _build_kernel(self, particles):
        """The padded-batch predictive program over ``particles`` (traced
        per bucket; the ensemble is closed over, so a hot reload builds a
        fresh kernel set instead of mutating served ones)."""
        if self.model == "logreg":

            def kernel(x):
                probs = posterior_predictive_prob(particles, x)  # (n, b)
                return {
                    "mean": jnp.mean(probs, axis=0),
                    "var": jnp.var(probs, axis=0),
                }

        elif self.model == "bnn":
            nf, nh = self._n_features, self._n_hidden
            y_mean, y_std = self._y_mean, self._y_std

            def kernel(x):
                preds = jax.vmap(
                    lambda t: bnn_model.predict(t, x, nf, nh)
                )(particles)  # (n, b)
                mean = jnp.mean(preds, axis=0) * y_std + y_mean
                ens_var = jnp.var(preds, axis=0) * y_std**2
                # predictive std folds in the mean observation-noise
                # variance E[1/γ] over the ensemble (original scale)
                noise = jnp.mean(jnp.exp(-particles[:, -2])) * y_std**2
                return {"mean": mean, "std": jnp.sqrt(ens_var + noise)}

        else:  # gmm — ensemble KDE density
            h = self._kde_bandwidth
            d = self._feature_dim

            def kernel(x):
                sq = jnp.sum(
                    (x[:, None, :] - particles[None, :, :]) ** 2, axis=-1
                )  # (b, n)
                logk = -0.5 * sq / (h * h) - d * math.log(h) - 0.5 * d * _LOG_2PI
                log_density = jax.scipy.special.logsumexp(
                    logk, axis=1
                ) - math.log(particles.shape[0])
                return {"log_density": log_density}

        low_precision = jnp.dtype(particles.dtype).itemsize < 4

        def dispatch(x):
            # the wire format stays f32 around a low-precision compute
            # dtype: cast in, compute in particles.dtype, upcast out —
            # callers (and the response JSON) never see bf16
            if low_precision:
                x = x.astype(particles.dtype)
            out = kernel(x)
            if low_precision:
                out = {k: v.astype(jnp.float32) for k, v in out.items()}
            return out

        # one compile entrypoint for both worlds (parallel/plan.py): with
        # a mesh the bucket program partitions the particle-axis reduction
        # across devices (replicated in/out shardings); without one this
        # is exactly the old single-device jit.  The padded input buffer
        # is donated so steady-state dispatch stops re-allocating it.
        # Audit declarations: serve outputs are per-row reductions, so the
        # donated request buffer is structurally unaliasable (the XP003
        # exemption); an f32 ensemble pins the whole program f32 (XP005
        # arms — the opt-in bf16 path legitimately computes low-precision
        # and does not pin).
        return self._plan.compile(
            dispatch, donate_argnums=(0,) if self._donate else (),
            label=f"serve.{self.model}",
            audit=dict(pinned_f32=not low_precision))

    def _record_compile(self, generation: str) -> None:
        """Feed one kernel-cache miss to the process usage meter (cost
        ledger) — a no-op unless metering is enabled.  Steady-state serve
        windows are gated at zero of these (cost_attribution drill)."""
        meter = _usage.get_meter()
        if meter is not None:
            meter.record_compile(
                tenant=self.tenant,
                generation=None if generation == "serving" else generation)

    def _kernel_for(self, bucket: int, generation: str = "serving"):
        """Returns ``(fn, dtype)`` snapshotted under one lock acquisition:
        a concurrent :meth:`reload` can never hand a caller the new
        ensemble's dtype with the old ensemble's kernel (or vice versa).

        ``generation='candidate'`` resolves against the staged candidate
        instead (the rollout controller's split/shadow dispatch).  Candidate
        buckets are never reported to the shared :class:`KernelBucketLRU`:
        a transient candidate's churn must not evict the incumbent's
        steady-state buckets (the candidate's kernels die with
        ``drop_candidate`` or become the accounted set at promotion)."""
        if generation == "candidate":
            with self._lock:
                if self._cand_particles is None:
                    raise RuntimeError(
                        "no candidate generation staged; stage_candidate() "
                        "first (or the rollout already resolved)"
                    )
                fn = self._cand_kernels.get(bucket)
                if fn is None:
                    self._misses += 1
                    miss = True
                    fn = self._cand_kernels[bucket] = self._build_kernel(
                        self._cand_particles)
                else:
                    self._hits += 1
                    miss = False
                dtype = self._input_dtype(self._cand_particles.dtype)
            (self._m_misses if miss else self._m_hits).inc(**self._tlabels)
            if miss:
                self._record_compile(generation)
            return fn, dtype
        with self._lock:
            fn = self._kernels.get(bucket)
            if fn is None:
                self._misses += 1
                miss = True
                fn = self._kernels[bucket] = self._build_kernel(self._particles)
            else:
                self._hits += 1
                miss = False
            dtype = self._input_dtype(self._particles.dtype)
        # registry write outside the engine lock (its own lock suffices)
        (self._m_misses if miss else self._m_hits).inc(**self._tlabels)
        if miss:
            self._record_compile(generation)
        if self._kernel_cache is not None:
            # report the use outside the engine lock: the shared LRU may
            # evict another engine's bucket (its _evict_bucket takes THAT
            # engine's lock) — lock order is always cache -> engine, never
            # engine -> cache, so tenants cannot deadlock each other
            self._kernel_cache.touch(self, bucket)
        return fn, dtype

    def _evict_bucket(self, bucket: int) -> bool:
        """Shared-LRU eviction callback: drop one compiled bucket kernel.
        The NEXT request on that bucket recompiles (a counted miss) — by
        construction only a least-recently-used bucket lands here, so a
        hot tenant's steady-state traffic never recompiles (regression-
        pinned under the retrace sentry in tests/test_registry.py)."""
        with self._lock:
            existed = self._kernels.pop(bucket, None) is not None
            if existed:
                self._evictions += 1
        if existed:
            self._m_evictions.inc(**self._tlabels)
        return existed

    # ------------------------------------------------------------------ #
    # serving

    def predict(self, x, generation: str = "serving") -> Dict[str, np.ndarray]:
        """Evaluate one request batch ``x`` of shape ``(b, feature_dim)``.

        Pads to the power-of-two bucket, runs the bucket's cached jitted
        kernel, slices the padding back off.  Returns plain numpy arrays of
        leading dimension ``b`` (the device→host fetch doubles as the fence
        the batcher's device-time split relies on).

        ``generation='candidate'`` (round 21) dispatches against the staged
        candidate generation instead of the serving incumbent — the rollout
        controller's shadow-mirror and canary-split path.  Raises
        ``RuntimeError`` when no candidate is staged (a split batch racing a
        rollback falls back to the incumbent upstream).
        """
        if generation not in ("serving", "candidate"):
            raise ValueError(
                f"generation must be 'serving' or 'candidate', "
                f"got {generation!r}"
            )
        x = np.asarray(x)
        if x.ndim != 2 or x.shape[1] != self._feature_dim:
            raise ValueError(
                f"expected (b, {self._feature_dim}) inputs, got shape {x.shape}"
            )
        b = x.shape[0]
        if b > self.max_bucket:
            raise ValueError(
                f"request of {b} rows exceeds max_bucket={self.max_bucket}; "
                "split it upstream (MicroBatcher max_batch does this)"
            )
        bucket = bucket_for(b, self.min_bucket)
        traced = _trace.enabled()
        tags = None
        if traced:
            tags = {"rows": b, "bucket": bucket, "model": self.model}
            # the batcher sets the thread's trace context when the whole
            # coalesced batch belongs to one request trace — tag it so a
            # cross-process stitch can attribute engine time to the trace
            ctx = _trace.get_trace_context()
            if ctx is not None:
                tags["trace"] = ctx
        with _trace.span("engine.predict", tags):
            fn, dtype = self._kernel_for(bucket, generation)
            if bucket != b:
                # pad on HOST: a device-side jnp.concatenate compiles one XLA
                # program per distinct (b, bucket) pair — steady-state traffic
                # with mixed request sizes recompiles forever while the bucket
                # cache reports all hits (caught by jaxlint's retrace_sentry,
                # docs/notes.md round 9).  Host padding keeps the device
                # seeing only bucket shapes.
                with _trace.span("engine.pad"):
                    xp = np.zeros((bucket, x.shape[1]), dtype=x.dtype)
                    xp[:b] = x
                    x = xp
            with _trace.span("engine.dispatch",
                             {"bucket": bucket} if traced else None):
                # pre-place the input replicated on the plan's devices: a
                # buffer already matching in_shardings is donatable as-is
                # (a mismatched one would be resharded first and the
                # donation silently lost)
                out = fn(self._plan.replicate(jnp.asarray(x, dtype=dtype)))
                # slice AFTER the host fetch: a device-array v[:b] is a
                # compiled slice program per (bucket, b) shape pair — same
                # silent-retrace class as the pad above.  The fetch doubles
                # as the span's device fence.
                return {k: np.asarray(v)[:b] for k, v in out.items()}

    def warmup(self, batch_sizes: Optional[List[int]] = None) -> List[int]:
        """Pre-trace kernels so first requests don't pay XLA compiles.

        Defaults to every bucket from ``min_bucket`` up to ``max_bucket``.
        Returns the bucket list compiled.
        """
        if batch_sizes is None:
            buckets = []
            bkt = self.min_bucket
            while bkt <= self.max_bucket:
                buckets.append(bkt)
                bkt *= 2
        else:
            buckets = sorted({bucket_for(b, self.min_bucket) for b in batch_sizes})
        for bkt in buckets:
            self.predict(np.zeros((bkt, self._feature_dim), np.float32))
        return buckets

    # ------------------------------------------------------------------ #
    # hot reload (round 8: train-while-serving)

    def reload(self, particles, *, warm: bool = True,
               tag: Optional[str] = None) -> Dict[str, Any]:
        """Atomically swap the served ensemble.

        A fresh kernel is built per currently-compiled bucket over the NEW
        particle array and (with ``warm=True``) pre-traced **before** the
        swap — the compile cost is paid off the request path, and the
        steady-state no-recompile contract survives the reload.  The swap
        itself is one lock-guarded pointer exchange of the
        ``(_particles, _kernels)`` pair: each ``predict`` call snapshots
        both under the same lock, so every micro-batch is served entirely
        by one ensemble generation (in-flight dispatches finish on the old
        one; the next batch sees the new one).

        The particle count may change (more training steps, a bigger
        ensemble); the feature layout may not — a reload can never
        repurpose a server to a different model shape.  Returns a summary
        dict; ``tag`` labels the generation in :meth:`stats`.

        Each call runs inside a ``reload`` span (the hot-reload lane's
        child leg) and an admitted swap's wall lands in the
        ``svgd_engine_reload_wall_s`` histogram — the freshness budget's
        reload leg is attributed, not inferred.
        """
        t0 = time.perf_counter()
        with _trace.span("reload", {"tag": tag}):
            info = self._reload_inner(particles, warm=warm, tag=tag)
        self._m_reload_wall.observe(time.perf_counter() - t0)
        return info

    def _reload_inner(self, particles, *, warm: bool,
                      tag: Optional[str]) -> Dict[str, Any]:
        particles = jnp.asarray(particles)
        if particles.ndim != 2 or particles.shape[1] != self._particles.shape[1]:
            raise ValueError(
                f"reload particles {particles.shape} incompatible with the "
                f"served layout (n, {self._particles.shape[1]})"
            )
        new_report = None
        if self._reload_policy is not None:
            new_report = self._reload_policy.evaluate(particles)
            if self._health_report is None:
                # first policied reload: baseline the ensemble currently
                # serving (off the request path; reload already is)
                baseline = self._reload_policy.evaluate(self._particles)
                with self._lock:
                    if self._health_report is None:
                        self._health_report = baseline
            reasons = self._reload_policy.judge(new_report,
                                                self._health_report)
            if reasons:
                with self._lock:
                    self._reload_rejects += 1
                    serving_gen = self._generation_id
                # generation = the incumbent that KEPT serving (the refused
                # candidate never minted an id)
                self._m_reload_rejects.inc(generation=str(serving_gen),
                                           **self._tlabels)
                _trace.instant("engine.reload_rejected", {"tag": tag})
                rec = _trace.flight_recorder()
                if rec is not None:
                    try:
                        rec.record("reload_rejected", tag=tag,
                                   reasons=reasons, **new_report)
                        rec.dump("reload_rejected",
                                 {"tag": tag, "reasons": reasons,
                                  "candidate": new_report,
                                  "baseline": self._health_report})
                    except Exception:
                        # a failing dump (unwritable dir, full disk) must
                        # not replace EnsembleRejected — the hot reloader
                        # only handles that one (the supervisor's
                        # _postmortem discipline)
                        pass
                raise EnsembleRejected(reasons, new_report)
        # place the admitted generation exactly like the cold start did
        # (shard + compute-dtype cast): a reload must never de-shard or
        # de-cast the served ensemble (pinned in tests/test_plan.py)
        particles = self._place_ensemble(particles)
        warm_dtype = self._input_dtype(particles.dtype)
        new_kernels: Dict[int, Any] = {}
        with self._lock:
            buckets = sorted(self._kernels)
        while True:
            # build + warm outside the lock (seconds of jit tracing must
            # not block the request path) for every bucket not yet staged
            for b in buckets:
                if b not in new_kernels:
                    fn = self._build_kernel(particles)
                    if warm:
                        fn(self._plan.replicate(
                            jnp.zeros((b, self._feature_dim), warm_dtype)))
                    new_kernels[b] = fn
            with self._lock:
                # a predict may have compiled a NEW bucket while we warmed
                # — swapping now would drop it and recompile on the request
                # path; re-stage until the staged set covers the live set
                # (bounded: the bucket lattice is finite, log2(max/min)+1)
                missing = [b for b in self._kernels if b not in new_kernels]
                if not missing:
                    # keep the outgoing generation RESIDENT (particles +
                    # compiled kernels): rollback() is then one pointer
                    # exchange, never a checkpoint re-load (round 21)
                    self._prev_particles = self._particles
                    self._prev_kernels = self._kernels
                    self._prev_tag = self._ensemble_tag
                    self._prev_generation = self._generation_id
                    self._prev_health = self._health_report
                    self._particles = particles
                    self._kernels = new_kernels
                    self._reloads += 1
                    self._ensemble_tag = tag
                    self._generation_id = self._next_generation
                    self._next_generation += 1
                    gen = self._generation_id
                    if new_report is not None:
                        self._health_report = new_report
                    break
                buckets = missing
        # the generation label tells WHICH generation each swap installed —
        # the mid-rollout fleet is inspectable from the counter series alone
        self._m_reloads.inc(generation=str(gen), **self._tlabels)
        _trace.instant("engine.reload", {"tag": tag})
        return {"n_particles": int(particles.shape[0]),
                "warmed_buckets": sorted(new_kernels), "tag": tag,
                "generation_id": gen}

    # ------------------------------------------------------------------ #
    # generations (round 21: progressive delivery)

    def rollback(self) -> Dict[str, Any]:
        """Swap back to the still-resident previous generation — O(1).

        One lock-guarded pointer exchange of the full
        ``(particles, kernels, tag, generation, health)`` pairs; **no
        checkpoint I/O ever happens on this path** (regression-pinned in
        tests/test_rollout.py).  The pairs *exchange* rather than pop, so a
        mistaken rollback is itself recoverable by a second call.  Buckets
        compiled only after the original swap recompile lazily on the
        request path (a counted miss) — the previous generation kept the
        kernel set it retired with.

        Raises ``RuntimeError`` when no previous generation is resident
        (cold-started engine with no admitted reload yet).
        """
        with self._lock:
            if self._prev_particles is None:
                raise RuntimeError(
                    "no previous generation resident; nothing to roll back to"
                )
            self._particles, self._prev_particles = (
                self._prev_particles, self._particles)
            self._kernels, self._prev_kernels = (
                self._prev_kernels, self._kernels)
            self._ensemble_tag, self._prev_tag = (
                self._prev_tag, self._ensemble_tag)
            self._generation_id, self._prev_generation = (
                self._prev_generation, self._generation_id)
            self._health_report, self._prev_health = (
                self._prev_health, self._health_report)
            self._rollbacks += 1
            gen = self._generation_id
            tag = self._ensemble_tag
            n = int(self._particles.shape[0])
        self._m_rollbacks.inc(generation=str(gen), **self._tlabels)
        _trace.instant("engine.rollback", {"tag": tag, "generation": gen})
        return {"generation_id": gen, "tag": tag, "n_particles": n}

    def stage_candidate(self, particles, *, warm: bool = True,
                        tag: Optional[str] = None) -> Dict[str, Any]:
        """Stage a candidate generation WITHOUT swapping it into serving.

        The candidate gets its own kernel set, built and (``warm=True``)
        pre-traced off the request path over every currently-compiled
        bucket — exactly :meth:`reload`'s staging discipline, minus the
        pointer exchange and minus the reload policy (the rollout
        controller judges the candidate on LIVE shadow/canary windows
        instead of a one-shot pre-serve health check).  Dispatch against
        it with ``predict(x, generation='candidate')``; install it with
        :meth:`promote_candidate`; discard with :meth:`drop_candidate`.
        A second stage_candidate supersedes the first (its kernels are
        dropped).  Returns ``{generation_id, warmed_buckets, tag}``.
        """
        particles = jnp.asarray(particles)
        if particles.ndim != 2 or particles.shape[1] != self._particles.shape[1]:
            raise ValueError(
                f"candidate particles {particles.shape} incompatible with "
                f"the served layout (n, {self._particles.shape[1]})"
            )
        particles = self._place_ensemble(particles)
        warm_dtype = self._input_dtype(particles.dtype)
        new_kernels: Dict[int, Any] = {}
        with self._lock:
            buckets = sorted(self._kernels)
        while True:
            for b in buckets:
                if b not in new_kernels:
                    fn = self._build_kernel(particles)
                    if warm:
                        fn(self._plan.replicate(
                            jnp.zeros((b, self._feature_dim), warm_dtype)))
                    new_kernels[b] = fn
            with self._lock:
                missing = [b for b in self._kernels if b not in new_kernels]
                if not missing:
                    self._cand_particles = particles
                    self._cand_kernels = new_kernels
                    self._cand_tag = tag
                    self._cand_generation = self._next_generation
                    self._next_generation += 1
                    gen = self._cand_generation
                    break
                buckets = missing
        _trace.instant("engine.stage_candidate",
                       {"tag": tag, "generation": gen})
        return {"generation_id": gen, "warmed_buckets": sorted(new_kernels),
                "tag": tag}

    def promote_candidate(self) -> Dict[str, Any]:
        """Install the staged candidate as the serving generation — O(1).

        The same pointer-exchange discipline as :meth:`reload`'s admitted
        swap: the outgoing incumbent stays resident for :meth:`rollback`,
        the candidate slot empties, and the swap counts as a reload (so
        the drills' ``expected_compiles = reloads × buckets`` accounting
        holds — the candidate's kernels were compiled once, at staging).
        The served health baseline resets: the next policied reload
        re-baselines against the promoted generation's own diagnostics.
        """
        with self._lock:
            if self._cand_particles is None:
                raise RuntimeError("no candidate generation staged")
            self._prev_particles = self._particles
            self._prev_kernels = self._kernels
            self._prev_tag = self._ensemble_tag
            self._prev_generation = self._generation_id
            self._prev_health = self._health_report
            self._particles = self._cand_particles
            self._kernels = self._cand_kernels
            self._ensemble_tag = self._cand_tag
            self._generation_id = self._cand_generation
            self._health_report = None
            self._cand_particles = None
            self._cand_kernels = None
            self._cand_tag = None
            self._cand_generation = None
            self._reloads += 1
            gen = self._generation_id
            tag = self._ensemble_tag
            n = int(self._particles.shape[0])
        self._m_reloads.inc(generation=str(gen), **self._tlabels)
        _trace.instant("engine.promote", {"tag": tag, "generation": gen})
        return {"generation_id": gen, "tag": tag, "n_particles": n}

    def drop_candidate(self) -> bool:
        """Discard the staged candidate (rollout rollback before any
        promotion — the incumbent never stopped serving).  Returns whether
        a candidate was staged.  O(1), no checkpoint I/O."""
        with self._lock:
            existed = self._cand_particles is not None
            gen = self._cand_generation
            self._cand_particles = None
            self._cand_kernels = None
            self._cand_tag = None
            self._cand_generation = None
        if existed:
            _trace.instant("engine.drop_candidate", {"generation": gen})
        return existed

    def stats(self) -> Dict[str, Any]:
        """Compile-cache and ensemble identity counters for ``/metrics``."""
        with self._lock:
            return {
                "model": self.model,
                "tenant": self.tenant,
                "n_particles": self.n_particles,
                "feature_dim": self._feature_dim,
                "dtype": str(self._particles.dtype),
                "donate_inputs": self._donate,
                "plan": self._plan.describe(),
                "bucket_hits": self._hits,
                "bucket_misses": self._misses,
                # bounded-cache visibility (round 14): how many compiled
                # bucket kernels this engine holds right now, and how many
                # the shared LRU has taken back from it
                "bucket_cache_size": len(self._kernels),
                "bucket_evictions": self._evictions,
                "compiled_buckets": sorted(self._kernels),
                "reloads": self._reloads,
                "reload_rejects": self._reload_rejects,
                "ensemble_tag": self._ensemble_tag,
                "ensemble_health": self._health_report,
                # generation identity (round 21): which generation serves,
                # which is resident for O(1) rollback, which is staged
                "generation_id": self._generation_id,
                "previous_generation_id": self._prev_generation,
                "candidate_generation_id": self._cand_generation,
                "candidate_tag": self._cand_tag,
                "rollbacks": self._rollbacks,
            }


class CheckpointHotReloader:
    """Watch a ``CheckpointManager`` root; hot-swap the engine's ensemble
    when training writes a newer step.

    Composes a supervised trainer (``resilience.RunSupervisor`` writing
    periodic checkpoints) with a live server into train-while-serving: the
    server cold-starts from the newest step, the reloader polls the root,
    and each newer restorable step is loaded off the request path and
    swapped in between micro-batches (:meth:`PredictiveEngine.reload`).
    A corrupt/partial newest step dir is simply skipped by the restore
    fallback — the server keeps serving the previous generation.

    Drive it explicitly with :meth:`poll_once` (tests, single-threaded
    drivers) or as a background thread via :meth:`start`/``with`` (the
    poll interval waits on an event, so :meth:`stop` returns promptly).

    Args:
        engine: the live :class:`PredictiveEngine`.
        root: the manager root being written by the trainer.
        key: ensemble entry in the checkpoint state dict.
        interval_s: background-thread poll cadence.
        baseline_step: the step already being served — newer steps trigger
            a swap.  Default ``'auto'`` uses the step the engine actually
            cold-started from (``engine.checkpoint_step``, recorded by
            ``from_checkpoint`` on a manager root — a save racing the cold
            start, or a corrupt newest dir the restore fell back past, is
            then correctly treated as *not yet served*); falls back to the
            root's current latest when the engine wasn't built from a
            manager root.  Pass ``None`` to force the first poll to load
            whatever is restorable, or an explicit step number.
        rollout: optional progressive-delivery controller
            (:class:`~dist_svgd_tpu.rollout.RolloutController`, duck-typed
            on ``offer``).  When set, a newer step is **offered as a
            candidate** instead of swapped directly — the rollout drives
            it through shadow/canary stages and promotes or rolls back on
            live SLO windows; the serving watermark is stamped at
            *promotion*, not at offer.
        logger: optional ``JsonlLogger`` — one record per swap.
    """

    def __init__(self, engine: PredictiveEngine, root: str, *,
                 key: str = "particles", interval_s: float = 5.0,
                 baseline_step="auto", rollout=None, logger=None):
        from dist_svgd_tpu.utils.checkpoint import CheckpointManager

        self.engine = engine
        self._mgr = CheckpointManager(os.fspath(root))
        self._key = key
        self._interval_s = float(interval_s)
        self.rollout = rollout
        self._logger = logger
        if baseline_step == "auto":
            baseline_step = getattr(engine, "checkpoint_step", None)
            if baseline_step is None:
                baseline_step = self._mgr.latest_step()
        self.loaded_step: Optional[int] = baseline_step
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> Optional[int]:
        """Check the root once; swap if a newer restorable step exists.
        Returns the newly served step, or ``None`` when nothing changed."""
        latest = self._mgr.latest_step()
        if latest is None or (self.loaded_step is not None
                              and latest <= self.loaded_step):
            return None
        step, state = self._mgr.restore_latest(with_step=True)
        if step is None or (self.loaded_step is not None
                            and step <= self.loaded_step):
            # every newer dir was corrupt/partial: keep serving the
            # current generation and try again next poll
            return None
        arr = state.get(self._key)
        if arr is None:
            raise KeyError(
                f"checkpoint step_{step} has no {self._key!r} entry "
                f"(keys: {sorted(state)})"
            )
        wm = state.get("stream_watermark")
        if self.rollout is not None:
            # progressive delivery (round 21): the new generation enters a
            # staged rollout instead of an atomic cutover.  The step is
            # marked seen either way — a superseded/deferred candidate is a
            # rollout decision, not a reason to re-offer the same step
            # forever.  The serving watermark is stamped by the rollout at
            # PROMOTION (candidate traffic is not "served" freshness-wise).
            offered = self.rollout.offer(
                np.asarray(arr), tag=f"step_{step}",
                watermark=(float(np.asarray(wm)) if wm is not None else None))
            self.loaded_step = step
            if self._logger is not None:
                self._logger.log(event="rollout_offer", step=step,
                                 accepted=bool(offered))
            return step if offered else None
        try:
            info = self.engine.reload(np.asarray(arr), tag=f"step_{step}")
        except EnsembleRejected as e:
            # the engine's health policy refused this generation: keep
            # serving the current one, but mark the step seen so the
            # poller doesn't re-evaluate the same bad checkpoint forever
            # (a later, healthier step will be picked up normally)
            self.loaded_step = step
            if self._logger is not None:
                self._logger.log(event="hot_reload_rejected", step=step,
                                 reasons=e.reasons)
            return None
        self.loaded_step = step
        if wm is not None:
            # streaming checkpoints stamp their data watermark: once this
            # generation serves, predictions reflect events up to `wm` —
            # the serving half of the freshness SLO's gauge pair.  Stamped
            # twice: the tenant-keyed series the FreshnessObjective reads
            # (exact label match — unchanged), plus a generation-labelled
            # series so a mid-rollout fleet shows WHICH generation's data
            # is serving (round 21)
            gauge = self.engine.registry.gauge(
                "svgd_serving_watermark",
                "event-time data watermark of the served ensemble",
            )
            gauge.set(float(np.asarray(wm)), **self.engine._tlabels)
            gauge.set(float(np.asarray(wm)),
                      generation=str(info["generation_id"]),
                      **self.engine._tlabels)
        if self._logger is not None:
            self._logger.log(event="hot_reload", step=step, **info)
        return step

    def start(self) -> "CheckpointHotReloader":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="ckpt-hot-reload", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # keep watching: one bad poll must not
                # kill the reloader thread (the server stays on the old
                # generation either way)
                try:
                    if self._logger is not None:
                        self._logger.log(event="hot_reload_error",
                                         error=f"{type(e).__name__}: {e}")
                except Exception:  # a closed/broken logger must not kill
                    pass           # the watcher either
            self._stop.wait(self._interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # a poll hung (e.g. a slow restore over a network fs): keep
                # the reference so start() can't spawn a duplicate poller
                # and a later stop() can retry the join
                try:
                    if self._logger is not None:
                        self._logger.log(
                            event="hot_reload_stop_timeout",
                            detail="poller still joining; reference kept",
                        )
                except Exception:
                    pass
                return
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
