"""Sharded SVGD sampler over a TPU mesh.

TPU-native counterpart of the reference's ``DistSampler``
(dsvgd/distsampler.py:8-205).  The reference runs one Python process per rank,
each owning a particle block and a data slice, exchanging state through
``torch.distributed`` collectives.  Here a *single* SPMD program drives the
whole mesh: the global ``(n, d)`` particle array is sharded along a 1-D mesh
axis, and every exchange strategy is a collective inside one jitted step
(``lax.all_gather`` / ``lax.psum`` / data-rotation for the ring — see
``parallel/exchange.py``).  When the host has fewer devices than shards the
identical per-shard code runs under ``vmap(axis_name=...)`` — exact semantics,
one device.

Reference parity notes (SURVEY.md §7.4):

- particles not divisible by ``num_shards`` are dropped, like
  dsvgd/distsampler.py:42-45; same for data rows (experiments/logreg.py:35).
- the default update is Jacobi (simultaneous) rather than the reference's
  in-place Gauss–Seidel sweep — deliberate, documented deviation with the
  same fixed point (SURVEY.md §3.2); ``update_rule='gauss_seidel'`` opts in
  to the reference's literal distributed sweep for trajectory-level parity
  verification.
- the Wasserstein ``previous_particles`` snapshot reproduces the reference's
  exact (warty) semantics: in exchanged modes each rank's "previous" set is
  the all-gathered array with only *its own* block post-update
  (dsvgd/distsampler.py:202-205 snapshots ``self._particles``, whose other
  blocks are stale pre-update values from that step's gather); in
  ``partitions`` mode each rank snapshots the block it just updated and next
  step compares the *newly adopted* block against it, which under the
  data-rotation formulation pairs device ``b``'s block with the snapshot of
  block ``(b+1) mod S``.
"""

from __future__ import annotations

import warnings
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dist_svgd_tpu.ops.approx import (
    APPROX_METHOD_CODES,
    RFF_REDRAW_MODES,
    approx_preferred,
    as_kernel_approx,
    is_gram_free,
    nystrom_landmark_indices,
)
from dist_svgd_tpu.ops.kernels import RBF, AdaptiveRBF
from dist_svgd_tpu.ops.ot import wasserstein_grad_lp, wasserstein_grad_sinkhorn
from dist_svgd_tpu.parallel.exchange import (
    ALL_PARTICLES,
    ALL_SCORES,
    PARTITIONS,
    make_shard_step,
    make_shard_step_sinkhorn_w2,
)
from dist_svgd_tpu.parallel.mesh import AXIS, bind_shard_fn, make_mesh
from dist_svgd_tpu.parallel.plan import Plan
from dist_svgd_tpu.telemetry import profile as _profile
from dist_svgd_tpu.telemetry import trace as _trace
from dist_svgd_tpu.utils import checkpoint as _ckpt
from dist_svgd_tpu.utils.rng import minibatch_key


#: Above this global particle count, ``w2_pairing='auto'`` routes the
#: exchanged-mode Wasserstein term to the ``partitions``-style block pairing
#: instead of the reference's global mixed-snapshot pairing.  Measured cliff
#: (docs/notes.md round-4 large-n table, one v5e chip): the global pairing's
#: ~4 resident lane-padded ``(n, d)`` buffers (gathered set, snapshot stack,
#: their scan-carry doubles) run 3.78 s/step at n=400k but fall off an HBM
#: cliff to 67.8 s/step at 600k; the block pairing's carried state is
#: ``(n/S, d)`` per shard and scales to n = 1M+ on one chip.
W2_GLOBAL_PAIRING_MAX_N = 400_000

#: Default pairwise-interaction throughput estimate feeding the
#: ``dispatch_budget`` auto-chunking heuristic (:meth:`DistSampler.
#: run_steps`): the measured single-chip φ rate at the 1M-particle row
#: (1e12 pairs / 4.21 s — docs/notes.md large-n table, one v5e).  Pass
#: ``pairs_per_sec`` explicitly for other hardware; the budget maths is a
#: planning estimate, not a guarantee.
DISPATCH_PAIRS_PER_SEC = 2.4e11

#: ``state_dict`` encoding of the resolved ``w2_pairing`` (orbax/
#: tensorstore cannot serialise unicode arrays, so the checkpoint stores an
#: index into this tuple).
W2_PAIRING_CODES = ("global", "block")


def _chunk_sizes(total: int, per: int):
    """Split ``total`` units into full chunks of ``per`` plus a remainder —
    the dispatch-chain schedule for hop and scan chunking (at most two
    distinct sizes, so at most two compiled programs per chunk kind)."""
    per = max(1, min(int(per), total))
    sizes = [per] * (total // per)
    if total % per:
        sizes.append(total % per)
    return sizes


def _data_rows(data) -> int:
    leaves = jax.tree_util.tree_leaves(data)
    return leaves[0].shape[0] if leaves else 0


class DistSampler:
    """Distributed SVGD sampler.

    Option composition: most options combine freely; the full supported /
    rejected matrix (mode × update_rule × exchange_impl × exchange_every ×
    W2 × median_step × batch_size × shard_data) lives in one table in
    ``docs/PARITY.md`` ("Feature-composition matrix") with the rationale
    for every rejected cell — each rejection below also raises a clear
    ``ValueError`` naming its constraint.

    Args:
        num_shards: mesh size S (the reference's world size).  The reference's
            per-process ``rank`` argument has no SPMD counterpart — one program
            owns all shards.
        logp: ``logp(theta, data_local)`` scalar log-density where
            ``data_local`` is the shard's slice of ``data`` (or ``None``).
            This replaces the reference's per-rank closure
            ``lambda x: logp(rank, x)`` (experiments/logreg.py:68).
        kernel: kernel for :func:`dist_svgd_tpu.ops.svgd.phi`; ``None`` means
            the reference's ``RBF(bandwidth=1)``.  The string ``'median'``
            resolves an RBF at the median-heuristic bandwidth of the initial
            ``particles`` (:func:`~dist_svgd_tpu.ops.kernels.
            median_bandwidth`) once, at construction.  The string
            ``'median_step'`` (an :class:`~dist_svgd_tpu.ops.kernels.
            AdaptiveRBF`) re-resolves the bandwidth from each step's
            interaction set *inside* the jitted step (the gathered global
            set in the ``all_*`` modes — identical on every shard — or the
            owned block in ``partitions``); Jacobi only.  Under
            ``exchange_impl='ring'`` the same bandwidth is resolved from a
            gathered ≤``max_points``-row strided subsample (the gather
            path's exact subsample, so ring ≡ gather holds) without
            materialising the global set.
        particles: ``(n, d)`` global initial particle array.  Truncated to
            ``S · (n // S)`` rows (reference drop policy).
        data: optional pytree of arrays with a common leading data axis.
            Replicated to every device and sliced per-shard, matching the
            reference where every rank loads the full dataset and slices its
            contiguous block (experiments/logreg.py:28,41-51).
        N_local / N_global: importance-scaling sizes; derived from ``data``
            when omitted (``N_local = N // S`` rows per shard, remainder
            dropped).  The ``N_global / N_local`` factor is applied exactly
            where the reference applies it: on scores that were *not*
            all-reduced (dsvgd/distsampler.py:96-99).
        exchange_particles / exchange_scores: strategy flags with the
            reference's constraint (scores ⇒ particles,
            dsvgd/distsampler.py:26).  (True, True) = ``all_scores``,
            (True, False) = ``all_particles``, (False, False) =
            ``partitions``.
        include_wasserstein: add the W2/JKO proximal term each step.
        update_rule: ``'jacobi'`` (vectorised, TPU-native default) or
            ``'gauss_seidel'`` — the reference's literal in-place distributed
            sweep (dsvgd/distsampler.py:194-200), each shard sweeping its own
            block inside its private view via ``lax.scan``; small-n parity
            verification mode (see ``parallel/exchange.py:make_shard_step``).
            Requires ``exchange_impl='gather'`` and no ``batch_size``;
            composes with the scanned Sinkhorn-W2 path (``run_steps``
            carries the snapshot through the GS sweep the same way the
            eager path does) as well as :meth:`make_step`.
        wasserstein_solver: ``'lp'`` (host LP, exact reference parity) or
            ``'sinkhorn'`` (on-device entropic OT, jit-fused fast path;
            ``sinkhorn_eps`` / ``sinkhorn_iters`` configure it, and
            ``sinkhorn_tol`` adds an early exit once the per-iteration
            change of the log-scalings drops below it — plan entries stable
            to ~``tol`` relatively, dual potentials to ``tol·reg`` in cost
            units, so precision tracks ``eps``; see
            :func:`dist_svgd_tpu.ops.ot.sinkhorn_plan`.  With the
            absorption-stabilised solver, the default ``1e-2`` measured
            74.5 ms/step at the 10k-particle north star vs 438 for the
            round-1 log-domain fixed-200 path (5.9× total) at 3.6e-5 max
            trajectory deviation; ``sinkhorn_tol=None`` restores the
            fixed-count loop (docs/notes.md)).  ``sinkhorn_warm_start``
            (default on) carries each shard's dual potential ``g`` across
            SVGD steps and starts every solve from its soft c-transform
            pair — particles move O(ε·φ) per step, so the carried dual is
            near-optimal and the ``tol`` exit fires on the first block
            (measured 16.6 ms/step vs 73.6 cold at the north star — 4.4×,
            9.4× vs the round-1 fixed-200 path, at 2.9e-5 max trajectory
            deviation; docs/notes.md); ``False`` restores the per-step
            cold start.
        mesh: ``'auto'`` (build a real mesh if the host has ≥ S devices, else
            vmap emulation), an explicit ``jax.sharding.Mesh``, or ``None``
            to force emulation.
        exchange_impl: ``'gather'`` (``lax.all_gather``/``psum`` collectives)
            or ``'ring'`` (``lax.ppermute`` block rotation with blockwise φ
            accumulation — same semantics, O(n/S) per-device memory; see
            ``parallel/exchange.py``).  Only affects the ``all_*`` modes.
        exchange_every: gather cadence T (default 1 = the reference's
            per-step exchange).  T > 1 selects the **lagged** variant the
            reference timed but never implemented (its notes.md:134
            "laggedlocal"): one all-gather per T steps, interactions
            against the stale set with the live own block patched in —
            T-fold fewer collectives (``parallel/exchange.py:
            make_shard_step_lagged``).  ``all_particles`` + ``'gather'`` +
            Jacobi, no W2; drive through :meth:`run_steps` with
            ``num_steps`` a multiple of T.
        shard_data: shard the data rows over the mesh instead of replicating
            the full set to every device (``all_*`` modes only).  Rows are
            truncated to ``S · (rows // S)`` (reference drop policy).
        batch_size: per-step per-shard minibatch size: each shard scores a
            fresh without-replacement sample of its rows, scaled
            ``rows_per_shard / batch_size`` (unbiased; see
            ``parallel/exchange.py``).  BASELINE.json config 4.
        log_prior: optional separate prior ``log_prior(theta)``; when given,
            ``logp`` is pure likelihood and the prior gradient is added once,
            unscaled (see ``parallel/exchange.py``).
        phi_impl: φ backend — ``'auto'`` (Pallas fused-tile φ on TPU with an
            RBF kernel at Gram-bound sizes, XLA otherwise), ``'xla'``,
            ``'pallas'`` (force), or ``'pallas_bf16'`` (bf16-Gram variant);
            see :func:`dist_svgd_tpu.ops.pallas_svgd.resolve_phi_fn`.
        kernel_approx: ``None`` (exact Gram φ — the default), ``'rff'``,
            ``'nystrom'``, or a :class:`~dist_svgd_tpu.ops.approx.
            KernelApprox` with explicit ``num_features``/``num_landmarks``
            dials — the sub-quadratic φ (``ops/approx.py``), O(n·R·d) /
            O(n·L·d) instead of O(n²), for particle counts the exact
            kernel cannot touch.  A drop-in ``phi_fn`` at the
            ``resolve_phi_fn`` seam, so it shards, ring/gather-exchanges,
            dispatch-budget-chunks, and composes with the W2 term
            unchanged.  With ``phi_impl='auto'`` the (n, R) crossover is
            resolved ONCE here from the global shape (the same decision at
            any shard count — shard invariance) and pinned for every φ
            call: exact below it, approximate above; see
            :attr:`kernel_approx_active`.  The RFF bank key derives from
            ``seed`` (``utils/rng.py:approx_bank_key``) and rides
            :meth:`state_dict`, so resumed/resharded runs re-derive the
            identical bank.  Requires an RBF-family kernel and the Jacobi
            update rule; ``'rff'`` additionally requires a bandwidth
            frozen before the bank is built — ``kernel='median'`` composes
            (resolved here, before construction), ``'median_step'`` is
            refused in one line (``'nystrom'`` composes with it).
        donate_carries: donate the training-step carries (particles, W2
            snapshots, Sinkhorn duals, intra-step chunk accumulators) to
            XLA at every scanned/chunked dispatch — the carry buffers stop
            re-allocating per dispatch (ROADMAP item 1's last slice).
            Bitwise-identical trajectories either way (pinned in
            tests/test_approx.py); ``False`` restores the undonated path
            (the A/B baseline — ``tools/profile_step_floor.py
            --donate-ab``).  Off, automatically, for the eager
            :meth:`make_step` path, whose pre-update array outlives the
            dispatch.
        w2_pairing: which sets the Wasserstein term pairs, in the exchanged
            (``all_*``) modes.  ``'global'`` is the reference's literal
            (warty) semantics: each shard pairs its block against the full
            mixed-snapshot global set (module docstring) — per-shard
            ``(n, d)`` carried state and ``(n/S, n)`` solves, which fall off
            a measured HBM cliff past :data:`W2_GLOBAL_PAIRING_MAX_N`
            particles (3.78 s/step at 400k → 67.8 at 600k on one v5e;
            docs/notes.md).  ``'block'`` is the ``partitions``-style pairing
            (block ``b`` against the last-step snapshot of block ``(b+1) mod
            S``) with φ still interacting globally — ``(n/S, d)`` state,
            ``(n/S, n/S)`` solves, scales to n = 1M+.  ``'auto'`` (default)
            picks ``'global'`` up to the threshold and routes to ``'block'``
            above it with a logged warning.  Ignored when the W2 term is off
            (any value is accepted unused); with the term on, ``partitions``
            mode's pairing is inherently block-level (``'global'`` raises
            there).  The *resolved* pairing is recorded in
            :meth:`state_dict` and exposed as :attr:`w2_pairing`, so runs
            straddling the auto-route boundary stay distinguishable after
            the fact; pin the value explicitly for reproducible
            experiments.
        seed: root PRNG seed for the per-step minibatch streams.
    """

    def __init__(
        self,
        num_shards: int,
        logp: Callable,
        kernel,
        particles,
        data=None,
        N_local: Optional[int] = None,
        N_global: Optional[int] = None,
        exchange_particles: bool = True,
        exchange_scores: bool = True,
        include_wasserstein: bool = True,
        update_rule: str = "jacobi",
        wasserstein_solver: str = "lp",
        sinkhorn_eps: float = 0.05,
        sinkhorn_iters: int = 200,
        sinkhorn_tol: Optional[float] = 1e-2,
        sinkhorn_warm_start: bool = True,
        mesh="auto",
        exchange_impl: str = "gather",
        exchange_every: int = 1,
        shard_data: bool = False,
        batch_size: Optional[int] = None,
        log_prior: Optional[Callable] = None,
        phi_impl: str = "auto",
        w2_pairing: str = "auto",
        seed=0,
        kernel_approx=None,
        donate_carries: bool = True,
    ):
        assert not (exchange_scores and not exchange_particles), (
            "must exchange particles to also exchange scores"
        )
        if wasserstein_solver not in ("lp", "sinkhorn"):
            raise ValueError(f"unknown wasserstein_solver {wasserstein_solver!r}")
        if exchange_impl not in ("gather", "ring"):
            raise ValueError(f"unknown exchange_impl {exchange_impl!r}")
        if exchange_every < 1:
            raise ValueError(f"exchange_every must be >= 1, got {exchange_every}")
        if exchange_every > 1:
            # lagged exchange is defined for the gathered all_particles mode
            # only (exchange.py:make_shard_step_lagged docstring); the W2
            # term's previous-snapshot bookkeeping is per step, not per
            # refresh, and the GS sweep exists for reference parity
            if not (exchange_particles and not exchange_scores):
                raise ValueError(
                    "exchange_every > 1 requires the all_particles mode"
                )
            if exchange_impl != "gather":
                raise ValueError(
                    "exchange_every > 1 requires exchange_impl='gather'"
                )
            if include_wasserstein:
                raise ValueError(
                    "exchange_every > 1 is incompatible with the Wasserstein term"
                )
            if update_rule != "jacobi":
                raise ValueError(
                    "exchange_every > 1 requires update_rule='jacobi'"
                )
        if shard_data and not exchange_particles:
            raise ValueError("shard_data is unsupported in partitions mode")
        if update_rule not in ("jacobi", "gauss_seidel"):
            raise ValueError(f"unknown update_rule {update_rule!r}")
        if update_rule == "gauss_seidel" and exchange_impl == "ring":
            raise ValueError(
                "update_rule='gauss_seidel' requires exchange_impl='gather'"
            )

        self._num_shards = int(num_shards)
        self._update_rule = update_rule
        self._logp = logp
        if kernel == "median":
            from dist_svgd_tpu.ops.kernels import median_bandwidth

            kernel = RBF(float(median_bandwidth(jnp.asarray(particles))))
        if kernel == "median_step":
            kernel = AdaptiveRBF()
        if isinstance(kernel, AdaptiveRBF):
            # per-step median of the interaction set: the gather paths (and
            # partitions, where the interaction set *is* the owned block)
            # resolve it per φ call; the ring implementation resolves the
            # SAME value once per step from a gathered strided subsample
            # (parallel/exchange.py:_ring_median_bandwidth — the gather
            # path's exact subsample, so ring ≡ gather still holds).  The
            # literal GS sweep exists for reference parity (fixed bandwidth)
            if update_rule != "jacobi":
                raise ValueError(
                    "kernel='median_step' requires update_rule='jacobi'"
                )
        self._kernel = kernel if kernel is not None else RBF(1.0)
        self._exchange_particles = exchange_particles
        self._exchange_scores = exchange_scores
        self._include_wasserstein = include_wasserstein
        self._wasserstein_solver = wasserstein_solver
        self._sinkhorn_eps = sinkhorn_eps
        self._sinkhorn_iters = sinkhorn_iters
        self._sinkhorn_tol = sinkhorn_tol
        self._sinkhorn_warm_start = bool(sinkhorn_warm_start)

        particles = jnp.asarray(particles)
        n = particles.shape[0]
        self._particles_per_shard = n // self._num_shards
        self._num_particles = self._particles_per_shard * self._num_shards
        # NOTE: drops particles if not divisible by num_shards (reference
        # behaviour, dsvgd/distsampler.py:42-45).
        if donate_carries:
            # the scanned runs donate the particle carry, and an identity
            # slice below can alias the CALLER's array — copy once here so
            # caller buffers are never invalidated (same discipline as
            # Sampler.run's initial_particles copy)
            particles = jnp.array(particles)
        self._particles = particles[: self._num_particles]
        self._d = particles.shape[1]

        self._exchange_impl = exchange_impl
        self._shard_data = shard_data
        self._batch_size = batch_size
        self._log_prior = log_prior
        self._phi_impl = phi_impl
        self._data = None if data is None else jax.tree_util.tree_map(jnp.asarray, data)
        # Physical slice size per shard is always rows // S (reference drop
        # policy); N_local/N_global are pure importance-scale factors like the
        # reference's constructor args (dsvgd/distsampler.py:96-99), defaulting
        # to the derived slice sizes.
        rows = _data_rows(self._data) if self._data is not None else 0
        self._rows_per_shard = rows // self._num_shards
        self._N_local = int(N_local) if N_local is not None else self._rows_per_shard
        if N_global is not None:
            self._N_global = int(N_global)
        else:
            self._N_global = self._N_local * self._num_shards
        if self._N_local:
            self._score_scale = float(self._N_global) / float(self._N_local)
        else:
            self._score_scale = 1.0

        if exchange_particles:
            self._mode = ALL_SCORES if exchange_scores else ALL_PARTICLES
        else:
            self._mode = PARTITIONS

        # Wasserstein pairing resolution (docstring; round-5: the measured
        # exchanged-mode W2 memory cliff gets an auto-route, not a silent
        # 20× regression)
        if w2_pairing not in ("auto", "global", "block"):
            raise ValueError(f"unknown w2_pairing {w2_pairing!r}")
        if not include_wasserstein:
            # fully inert without the W2 term (docstring): any valid value —
            # including 'global' in partitions mode — is accepted and
            # unused, so generic config code can pass the same kwargs with
            # W2 off (ADVICE round 5)
            self._w2_pairing = (
                "block" if self._mode == PARTITIONS else "global"
            )
        elif self._mode == PARTITIONS:
            if w2_pairing == "global":
                raise ValueError(
                    "w2_pairing='global' is undefined in partitions mode — "
                    "its W2 pairing is inherently block-level (the (b+1) "
                    "ring roll, module docstring)"
                )
            self._w2_pairing = "block"
        elif w2_pairing == "auto":
            if (self._num_particles > W2_GLOBAL_PAIRING_MAX_N
                    and self._num_shards > 1):
                warnings.warn(
                    f"n={self._num_particles} exceeds the exchanged-mode "
                    f"global-W2-pairing ceiling ({W2_GLOBAL_PAIRING_MAX_N}): "
                    "routing the Wasserstein term to w2_pairing='block' "
                    "(partitions-style block snapshots; (n/S, n/S) solves). "
                    "Pass w2_pairing='global' to force the reference pairing "
                    "and accept the measured HBM cliff (67.8 s/step at 600k "
                    "vs 3.78 at 400k — docs/notes.md).",
                    stacklevel=2,
                )
                self._w2_pairing = "block"
            else:
                self._w2_pairing = "global"
        else:
            self._w2_pairing = w2_pairing
            if (w2_pairing == "global"
                    and self._num_particles > W2_GLOBAL_PAIRING_MAX_N):
                warnings.warn(
                    f"w2_pairing='global' forced at n={self._num_particles} "
                    f"> {W2_GLOBAL_PAIRING_MAX_N}: expect the measured HBM "
                    "cliff (docs/notes.md round-4 large-n table)",
                    stacklevel=2,
                )
        # block-sized snapshots + (b+1) roll — partitions natively, or the
        # exchanged modes under block pairing; S=1 degenerates to global
        self._block_w2 = (
            (self._mode == PARTITIONS or self._w2_pairing == "block")
            and self._num_shards > 1
        )

        self._mesh = make_mesh(self._num_shards) if mesh == "auto" else mesh
        if (isinstance(self._kernel, AdaptiveRBF)
                and exchange_impl == "ring"
                and self._mode != PARTITIONS
                and self._mesh is not None):
            from dist_svgd_tpu.parallel.mesh import SHARD_MAP_LEGACY

            if SHARD_MAP_LEGACY:
                raise ValueError(
                    "kernel='median_step' with exchange_impl='ring' on a "
                    "shard_map mesh crashes this jax version's XLA sharding "
                    "propagation (SIGABRT in TileAssignment::Reshape — the "
                    "ring median bandwidth is a collective-derived scalar "
                    "feeding a ppermute loop); use mesh=None (the exact vmap "
                    "emulation), exchange_impl='gather', or kernel='median'"
                )
        # Under vmap emulation all S lanes run as ONE batched kernel, so the
        # phi 'auto' thresholds should see S x the per-lane pair count; on a
        # real mesh each device runs a single lane (resolve_phi_fn docstring)
        self._phi_batch_hint = self._num_shards if self._mesh is None else 1

        # Sub-quadratic kernel approximation (constructor docstring).  The
        # 'auto' crossover is resolved ONCE from the GLOBAL shape and
        # pinned: resolve_phi_fn's per-call-shape crossover would let the
        # ring's small per-hop blocks pick a different backend than the
        # gather's global set, silently breaking ring ≡ gather and shard
        # invariance.  Exchanged modes pin the same decision at any S
        # (k_eff = m = n); the partitions decision depends on the block
        # size, so the pinned flag ALSO rides state_dict and a resumed
        # run adopts the saved pin (load_state_dict) instead of
        # re-deciding at the new topology.  Validation (RBF-only, AdaptiveRBF+rff refusal,
        # pallas incompatibility, missing-key) runs through the ONE policy
        # seam so this constructor cannot drift from direct resolve users.
        self._approx = as_kernel_approx(kernel_approx)
        self._approx_active = False
        if self._approx is not None:
            if update_rule != "jacobi":
                raise ValueError(
                    "kernel_approx requires update_rule='jacobi': the "
                    "Gauss-Seidel sweep exists for literal reference "
                    "parity, which an approximate kernel cannot provide"
                )
            if self._approx.method == "rff":
                from dist_svgd_tpu.utils.rng import approx_bank_key

                self._approx = self._approx.with_key(approx_bank_key(seed))
            from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn

            resolve_phi_fn(self._kernel, phi_impl, self._phi_batch_hint,
                           self._approx)  # validation only
            if phi_impl == "auto":
                m_interact = (self._num_particles
                              if self._mode != PARTITIONS
                              else self._particles_per_shard)
                self._approx_active = approx_preferred(
                    self._num_particles, m_interact,
                    self._approx.feature_count)
            else:
                self._approx_active = True  # 'xla' = always approximate

        if shard_data and self._data is not None:
            # truncate to divisible row count before the mesh split (the
            # replicated path drops the remainder at slice time instead)
            keep = self._rows_per_shard * self._num_shards
            self._data = jax.tree_util.tree_map(lambda a: a[:keep], self._data)

        # Unified compile entrypoint (ROADMAP item 5): every jitted program
        # this sampler builds — the eager step, the scan runs, the chunked
        # executors — compiles through the SAME Plan that serves the
        # predictive engine, so one explicit-sharding path covers any mesh
        # size (and an elastic resume at a new shard count recompiles once,
        # through the same entrypoint, instead of per-step).  Without a
        # real mesh (vmap emulation) the plan degrades to plain jit.
        self._plan = Plan(self._mesh)
        self._data_spec = 0 if shard_data else None
        self._donate = bool(donate_carries)
        self._exchange_every = int(exchange_every)
        self._build_step_programs()
        #: Execution report of the most recent :meth:`run_steps` call —
        #: ``execution`` mode, ``num_dispatches``, ``dispatches_per_step``,
        #: the resolved chunking knobs, ``max_dispatch_wall_s`` (when timed),
        #: and the resolved ``w2_pairing``.  Bench harnesses record it.
        self.last_run_stats = None
        self._batch_key = minibatch_key(seed)

        # Wasserstein "previous particles" state.  In exchanged modes this is
        # a per-shard (S, n, d) stack (each shard's own warty mixed snapshot);
        # in partitions mode a (S, n_loc, d) stack of owned-block snapshots;
        # None until the first step, like the reference
        # (dsvgd/distsampler.py:50, :186-188).  numpy when written by the
        # eager path, a device array when written by the scanned path.
        self._previous = None
        self._t = 0  # make_step call counter (drives the partitions rotation)
        self._sinkhorn_batched = None  # lazily-built jitted vmap solver
        # Carried Sinkhorn dual potential g, per shard — warm-starts each
        # step's W2 solve from the previous step's optimum (ops/ot.py:
        # sinkhorn_plan docstring).  None until the first solve; zeros are
        # the cold start.
        self._w2_g = None

    def _phi_kwargs(self) -> dict:
        """The ``(phi_impl, kernel_approx)`` pair every step builder gets.

        With the approximation pinned active, the builders see
        ``phi_impl='xla'`` + the spec — resolve_phi_fn's always-approximate
        combination — so every φ call site (gather, ring hops, chunk
        programs, the W2 step) uses the approximate backend uniformly;
        pinned inactive, the original exact configuration."""
        if self._approx is not None and self._approx_active:
            return {"phi_impl": "xla", "kernel_approx": self._approx}
        return {"phi_impl": self._phi_impl, "kernel_approx": None}

    def _audit_meta(self, *, expect_donation=False, particles_arg=0,
                    gram_free=None) -> dict:
        """Program-card declarations for a compile site (``audit=`` kwarg
        of ``Plan.compile_sharded`` — see ``analysis/audit.py``).  φ-free
        sites (elementwise finishers) pass ``gram_free=True`` outright;
        φ-bearing sites inherit the resolved backend's contract
        (``ops.approx.is_gram_free``); W2/Sinkhorn sites, whose cost
        blocks legitimately materialize, pass ``gram_free=False``."""
        if gram_free is None:
            gram_free = is_gram_free(
                self._phi_impl,
                self._approx is not None and self._approx_active)
        return dict(gram_free=gram_free, expect_donation=expect_donation,
                    particles_arg=particles_arg)

    def _build_step_programs(self) -> None:
        """(Re)build every bound/compiled step program from the current
        kernel + approximation configuration.  Called once from
        ``__init__`` and again by :meth:`load_state_dict` when a restored
        checkpoint carries a different RFF bank key (the saved bank wins —
        bitwise resume beats the constructed seed)."""
        step = make_shard_step(
            logp=self._logp,
            kernel=self._kernel,
            mode=self._mode,
            num_shards=self._num_shards,
            n_local_data=self._rows_per_shard,
            score_scale=self._score_scale,
            ring=(self._exchange_impl == "ring"),
            shard_data=self._shard_data,
            batch_size=self._batch_size,
            log_prior=self._log_prior,
            update_rule=self._update_rule,
            phi_batch_hint=self._phi_batch_hint,
            **self._phi_kwargs(),
        )
        self._bound_step = bind_shard_fn(
            step,
            self._num_shards,
            self._mesh,
            in_specs=(0, self._data_spec, 0, None, None, None, None),
            out_specs=(0,),
        )
        # the eager step is NOT donated: make_step's W2 bookkeeping reads
        # the pre-update array after the dispatch (donation lives on the
        # scanned/chunked paths, whose carries this object owns)
        self._step = self._plan.compile_sharded(
            self._bound_step,
            in_specs=(0, self._data_spec, 0, None, None, None, None),
            out_specs=(0,),
            label="dist.step",
            audit=self._audit_meta(),
        )
        self._bound_lagged = None
        self._bound_lagged_record = None  # built lazily on first record run
        if self._exchange_every > 1:
            self._bound_lagged = self._bind_lagged(record=False)
        self._scan_cache = {}
        self._bound_w2_step = None  # lazily built by _run_steps_w2
        # Chunked-executor caches (run_steps(dispatch_budget=...)): the
        # per-shard hop-chunk builders and their bound/jitted programs,
        # keyed by (kind, num_hops, rotate_last) — at most a handful of
        # distinct programs per sampler (_chunk_sizes yields ≤ 2 sizes).
        self._chunk_builders = None
        self._chunk_cache = {}
        self._sinkhorn_batched = None  # lazily-built jitted vmap solver

    def _bind_lagged(self, record: bool):
        """Bind the lagged macro-step (``record=True`` additionally emits the
        per-sub-step pre-update history stack, sharded along its particle
        axis)."""
        from dist_svgd_tpu.parallel.exchange import make_shard_step_lagged

        lagged = make_shard_step_lagged(
            logp=self._logp,
            kernel=self._kernel,
            num_shards=self._num_shards,
            n_local_data=self._rows_per_shard,
            score_scale=self._score_scale,
            exchange_every=self._exchange_every,
            shard_data=self._shard_data,
            batch_size=self._batch_size,
            log_prior=self._log_prior,
            phi_batch_hint=self._phi_batch_hint,
            record=record,
            **self._phi_kwargs(),
        )
        return bind_shard_fn(
            lagged,
            self._num_shards,
            self._mesh,
            in_specs=(0, 0 if self._shard_data else None, 0, None, None, None, None),
            out_specs=(0, 1) if record else (0,),
        )

    # ------------------------------------------------------------------ #
    # State views

    @property
    def particles(self) -> jax.Array:
        """Global ``(n, d)`` particle array, logical block order."""
        return self._particles

    @property
    def t(self) -> int:
        """Absolute step counter (drives the ``partitions`` rotation and
        the per-step minibatch key fold; rides :meth:`state_dict`, so a
        resumed run continues on the same absolute grid)."""
        return int(self._t)

    @property
    def num_particles(self) -> int:
        return self._num_particles

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def w2_pairing(self) -> str:
        """The **resolved** Wasserstein pairing (``'global'`` or ``'block'``)
        after ``'auto'`` routing — record this alongside experiment configs:
        two runs straddling the :data:`W2_GLOBAL_PAIRING_MAX_N` auto-switch
        boundary optimise different W2 functionals.  Also written into
        :meth:`state_dict` and the bench/large-n JSON records."""
        return self._w2_pairing

    @property
    def kernel_approx(self):
        """The resolved :class:`~dist_svgd_tpu.ops.approx.KernelApprox`
        (RFF bank key bound), or ``None`` when running the exact kernel."""
        return self._approx

    @property
    def kernel_approx_active(self) -> bool:
        """Whether φ actually runs the approximate backend after the
        ``phi_impl='auto'`` global-shape crossover (constructor docstring)
        — record it with experiment configs, like :attr:`w2_pairing`."""
        return self._approx is not None and self._approx_active

    def approx_residual(self, max_points: int = 512, registry=None) -> dict:
        """Measure the feature-space φ residual of the configured
        approximation on the CURRENT ensemble (exact vs approximate φ over
        a ≤``max_points`` strided subsample) and publish it as
        ``svgd_diag_phi_approx_*`` gauges, so drift guards and SLOs watch
        approximation health next to KSD/ESS.  Probe scores are the
        full-data (unscaled) ``∇log p`` plus the prior — representative of
        every exchange mode's score magnitude without reproducing any one
        mode's scaling.  O(max_points²) on host-visible state; run it at
        diagnostics cadence, not per step."""
        from dist_svgd_tpu.ops.approx import (
            phi_residual_report,
            record_phi_residual,
        )

        if self._approx is None:
            raise ValueError(
                "approx_residual needs kernel_approx (exact runs have no "
                "approximation residual to measure)"
            )
        particles = jnp.asarray(self._particles)
        n = particles.shape[0]
        if n > max_points:
            stride = -(-n // max_points)
            particles = particles[::stride]
        scores = jax.vmap(jax.grad(self._logp, argnums=0),
                          in_axes=(0, None))(particles, self._data)
        if self._log_prior is not None:
            scores = scores + jax.vmap(jax.grad(self._log_prior))(particles)
        if isinstance(self._kernel, RBF):
            kernel = self._kernel
        else:  # AdaptiveRBF: probe at the current per-step median bandwidth
            from dist_svgd_tpu.ops.kernels import median_bandwidth_approx

            kernel = RBF(float(median_bandwidth_approx(particles)))
        report = phi_residual_report(particles, scores, kernel, self._approx,
                                     max_points=max_points)
        report["active"] = bool(self._approx_active)
        record_phi_residual(report, registry=registry)
        return report

    def owned_block_index(self, rank: int, t: Optional[int] = None) -> int:
        """Logical block index owned by (= updated against the data slice of)
        shard ``rank`` at step counter ``t`` (default: now): ``(rank − t) mod
        S`` under the ring rotation (dsvgd/distsampler.py:148-150), ``rank``
        otherwise.  Pass an explicit ``t`` to interpret recorded history
        snapshots (``run_steps(record=True)``)."""
        if self._mode == PARTITIONS:
            return (rank - (self._t if t is None else t)) % self._num_shards
        return rank

    def owned_block(self, rank: int) -> jax.Array:
        """The block currently updated against data shard ``rank`` — the SPMD
        equivalent of the reference's per-rank ``.particles`` view
        (dsvgd/distsampler.py:53-56 with the ring's rotating ownership
        ranges, :148-150)."""
        s = self._particles_per_shard
        b = self.owned_block_index(rank)
        return self._particles[b * s : (b + 1) * s]

    # ------------------------------------------------------------------ #
    # Wasserstein bookkeeping (host side; see module docstring for the
    # reference's exact snapshot semantics being replicated)

    def _blocks(self, arr) -> np.ndarray:
        return np.asarray(arr).reshape(self._num_shards, self._particles_per_shard, self._d)

    def _prev_shape(self) -> tuple:
        """Shape of the Wasserstein ``previous`` snapshot stack (see the
        state comment in ``__init__``): block-sized under block pairing
        (``partitions``, or exchanged modes with ``w2_pairing='block'``),
        global-sized under the reference's mixed-snapshot pairing."""
        if self._block_w2:
            return (self._num_shards, self._particles_per_shard, self._d)
        return (self._num_shards, self._num_particles, self._d)

    def _g_shape(self) -> tuple:
        """Shape of the carried Sinkhorn dual stack: one ``g`` per shard,
        sized to that shard's ``previous`` measure (the solve's column
        marginal)."""
        return self._prev_shape()[:2]

    def _wasserstein_grad(self) -> jnp.ndarray:
        """Per-shard W2 gradient, stacked to global ``(n, d)``."""
        cur = self._blocks(self._particles)
        grads = np.zeros_like(cur)
        if self._block_w2:
            # Device b's block pairs with the snapshot taken (last step) of
            # block (b+1) mod S — the ring-ownership pairing (partitions
            # natively, exchanged modes under w2_pairing='block').
            prev_for = np.roll(self._previous, -1, axis=0)
        else:
            prev_for = self._previous  # (S, n, d) mixed snapshots
        if self._wasserstein_solver == "lp":
            for b in range(self._num_shards):
                grads[b] = wasserstein_grad_lp(cur[b], prev_for[b])
            return jnp.asarray(grads.reshape(self._num_particles, self._d))
        # sinkhorn: one jitted vmap over the stacked blocks — a single device
        # call computes every shard's gradient (no per-block host round-trips)
        if self._sinkhorn_batched is None:
            warm = self._sinkhorn_warm_start
            # the carried dual donates (the cur/prev stacks are rebuilt
            # from sampler state each step and must not)
            self._sinkhorn_batched = self._plan.compile_sharded(
                jax.vmap(
                    lambda c, p, g: wasserstein_grad_sinkhorn(
                        c, p, eps=self._sinkhorn_eps,
                        iters=self._sinkhorn_iters, tol=self._sinkhorn_tol,
                        g_init=g if warm else None, return_g=True,
                    )
                ),
                donate_argnums=(2,) if self._donate else (),
                label="dist.sinkhorn",
                audit=self._audit_meta(expect_donation=self._donate,
                                       particles_arg=None, gram_free=False),
            )
        if self._w2_g is None:
            g0 = jnp.zeros(self._g_shape(), dtype=jnp.asarray(cur).dtype)
        else:
            g0 = jnp.asarray(self._w2_g)
        out, self._w2_g = self._sinkhorn_batched(
            jnp.asarray(cur), jnp.asarray(prev_for), g0
        )
        return out.reshape(self._num_particles, self._d)

    def _snapshot_previous(self, pre_update: np.ndarray) -> None:
        post = self._blocks(self._particles)
        if self._block_w2:
            self._previous = post.copy()  # owned-block snapshots
        else:
            pre_blocks = self._blocks(pre_update)
            # Shard r's snapshot: gathered pre-update set with only its own
            # block updated (reference dsvgd/distsampler.py:202-203).
            prev = np.broadcast_to(
                pre_blocks.reshape(1, self._num_particles, self._d),
                (self._num_shards, self._num_particles, self._d),
            ).copy()
            s = self._particles_per_shard
            for r in range(self._num_shards):
                prev[r, r * s : (r + 1) * s] = post[r]
            self._previous = prev

    # ------------------------------------------------------------------ #
    # Checkpoint / resume (utils/checkpoint.py; SURVEY.md §5)

    def _mesh_is_multiprocess(self) -> bool:
        return self._mesh is not None and (
            len({d.process_index for d in self._mesh.devices.flat}) > 1
        )

    def state_dict(self) -> dict:
        """Resume state: particles, the Wasserstein ``previous`` snapshot, and
        the step counter (drives the ``partitions`` rotation *and* the
        per-step minibatch key fold).  Restoring via :meth:`load_state_dict`
        continues the exact uninterrupted trajectory.

        Multi-host: on a mesh spanning several processes the global arrays
        are not fully addressable, so each process's dict holds only **its
        own** contiguous row block (plus its ``*_start`` offset) — every
        process saves to its own path and, under the *same* layout, restores
        its own checkpoint (``parallel/multihost.py:host_addressable_block``).
        A federation with a **different process count** restores the same
        save by assembling every per-process block back into the global
        state first (:func:`dist_svgd_tpu.utils.checkpoint.
        assemble_full_state` — the mesh size, hence every global shape, is
        process-layout-independent) and loading that; a single
        foreign-layout block alone is rejected with a clear error
        (``tests/test_multihost.py::test_cross_process_count_restore``)."""
        from dist_svgd_tpu.parallel.multihost import host_addressable_block

        particles, p_start = host_addressable_block(self._particles)
        state = {
            "particles": particles,
            "particles_start": np.asarray(p_start, dtype=np.int64),
            "t": np.asarray(self._t, dtype=np.int64),
            # the RESOLVED pairing (after 'auto' routing), as an index into
            # W2_PAIRING_CODES — runs straddling the auto-switch boundary
            # stay distinguishable after the fact (ADVICE round 5)
            "w2_pairing": np.asarray(
                W2_PAIRING_CODES.index(self._w2_pairing), dtype=np.int8
            ),
            # the minibatch stream's root key: shard-layout-free (per-step
            # keys fold (root, t)), so a resharded resume re-derives every
            # later key deterministically from this saved root
            "rng_batch_key": np.asarray(self._batch_key),
        }
        # topology manifest (elastic capacity): loaders compare it against
        # the requested topology BEFORE any array op, and reshard_state
        # reshapes the save for a different mesh (utils/checkpoint.py).
        # The process layout (how many processes held the mesh, shards per
        # granule) is stamped from the mesh itself — global values, bitwise
        # identical in every process's save (assemble_full_state contract)
        process_count, granule_shards = 1, None
        if self._mesh is not None and self._mesh.size == self._num_shards:
            from dist_svgd_tpu.parallel.multihost import mesh_process_layout

            process_count, granule_shards = mesh_process_layout(self._mesh)
        state.update(_ckpt.topology_manifest(
            self._num_shards, self._num_particles, self._d,
            self._rows_per_shard,
            process_count=process_count, granule_shards=granule_shards,
        ))
        if self._approx is not None:
            # the approximation identity: method + dial + (rff) the bank
            # key / (nystrom) the landmark indices of the gathered-set
            # selection.  All layout-free — reshard_state passes them
            # through verbatim, and a resharded resume re-derives the
            # identical bank/landmarks (utils/checkpoint.py)
            state["approx_method"] = np.asarray(
                APPROX_METHOD_CODES.index(self._approx.method), dtype=np.int8
            )
            state["approx_dial"] = np.asarray(
                self._approx.accuracy_dial, dtype=np.int64
            )
            state["approx_active"] = np.asarray(
                int(self._approx_active), dtype=np.int8
            )
            if self._approx.method == "rff":
                state["approx_bank_key"] = np.asarray(self._approx.key)
                # the bank lifetime is part of the trajectory: a per-step
                # redraw run resumed as a per-run-bank sampler (or vice
                # versa) would silently switch φ randomness mid-trajectory
                state["approx_rff_redraw"] = np.asarray(
                    RFF_REDRAW_MODES.index(self._approx.rff_redraw),
                    dtype=np.int8,
                )
            else:
                m_interact = (self._num_particles
                              if self._mode != PARTITIONS
                              else self._particles_per_shard)
                state["approx_landmark_idx"] = nystrom_landmark_indices(
                    m_interact, self._approx.num_landmarks
                ).astype(np.int64)
        if self._previous is None:
            state["previous"] = None
        else:
            prev, prev_start = host_addressable_block(self._previous)
            state["previous"] = prev
            state["previous_start"] = np.asarray(prev_start, dtype=np.int64)
        if self._w2_g is None:
            state["w2_g"] = None
        else:
            # the carried Sinkhorn dual: without it a resumed W2 run would
            # cold-start its first solve and drift within the tol band
            g, g_start = host_addressable_block(self._w2_g)
            state["w2_g"] = g
            state["w2_g_start"] = np.asarray(g_start, dtype=np.int64)
        return state

    def _restore_global(self, name: str, rows: np.ndarray, ck_start: int,
                        want: tuple):
        """Rebuild a ``P(AXIS)``-sharded global array of shape ``want`` from
        a checkpoint entry that is either the full array (single-process
        save) or this process's block (per-process multi-host save)."""
        from dist_svgd_tpu.parallel import multihost

        if not self._mesh_is_multiprocess():
            if rows.shape != want:
                raise ValueError(
                    f"checkpoint {name} {rows.shape} != sampler {want}"
                )
            return jnp.asarray(rows)
        # only axis 0 is mesh-sharded, for every global array in this framework
        start, count = multihost.process_local_rows(want[0], self._mesh)
        local_shape = (count,) + want[1:]
        if rows.shape == want and ck_start == 0:
            local = rows[start : start + count]  # full save → slice our block
        elif rows.shape == local_shape and ck_start == start:
            local = rows
        else:
            raise ValueError(
                f"checkpoint {name} {rows.shape} (start {ck_start}) matches "
                f"neither the global {want} nor this process's block "
                f"{local_shape} at row {start} — was it saved by a different "
                "process or mesh layout?"
            )
        return multihost.make_global_from_local(local, self._mesh, want)

    def _reshard_previous(self, prev_arr: np.ndarray) -> np.ndarray:
        """Convert a single-process checkpoint's Wasserstein ``previous``
        stack saved under a **different** shard count (or exchange-mode
        family) to this sampler's layout — exactly, by reconstructing the
        shard-independent pre/post-update global states the stacks encode:

        - the post-update global is the concatenation of each shard's own
          block (exchanged stacks carry it inside the mixed snapshots;
          ``partitions`` stacks ARE it);
        - exchanged stacks at ``S_old ≥ 2`` additionally carry every
          pre-update row (each block's pre value sits in any *other*
          shard's snapshot), so the ``S_new`` mixed stack can be rebuilt
          verbatim.

        A target layout needing pre-update rows that the save does not
        contain (``partitions``/S=1 save → exchanged S>1 restore) raises.
        The carried dual cannot be resharded (its pairing is per-block) —
        the caller zeroes it instead.  The stack math is shared with the
        checkpoint-level reshard (:func:`dist_svgd_tpu.utils.checkpoint.
        reshard_previous_stack`); this wrapper just supplies the sampler's
        target layout.
        """
        return _ckpt.reshard_previous_stack(
            prev_arr, self._num_particles, self._d, self._prev_shape()
        )

    def load_state_dict(self, state: dict) -> None:
        """Restore :meth:`state_dict` state.  Single-process restores accept
        checkpoints saved under a different ``num_shards`` (reshard-on-
        restore): the ``previous`` snapshot stack is rebuilt exactly for the
        new layout (:meth:`_reshard_previous`) and the carried Sinkhorn dual
        — whose per-block pairing does not survive a layout change — is
        dropped, so the first resumed W2 solve starts from zeroed duals (the
        safe soft-transform start; trajectory within the solver's tol band).
        Multi-host restores under a different *process* layout go through
        :func:`~dist_svgd_tpu.utils.checkpoint.assemble_full_state` (see
        :meth:`state_dict`); a different *shard count* on a multi-process
        mesh still requires the saving mesh size.

        When the checkpoint carries a topology manifest it is compared
        against this sampler BEFORE any array op: a particle-count or
        dimension mismatch raises
        :class:`~dist_svgd_tpu.utils.checkpoint.TopologyMismatch` naming
        both shapes (instead of the raw reshape/broadcast error deep in
        jax it used to die with); a shard-count difference alone proceeds
        into the reshard-on-restore path above (multi-process meshes
        excepted — their blocks need
        :func:`~dist_svgd_tpu.utils.checkpoint.reshard_state` on the
        assembled state first)."""
        # manifest gate first: n/d can never convert, and a foreign shard
        # count on a multi-process mesh cannot reshard in-place
        man = _ckpt.check_topology(
            state,
            {"n_particles": self._num_particles, "d": self._d},
            context="checkpoint",
        )
        if (man is not None and man["n_shards"] != self._num_shards
                and self._mesh_is_multiprocess()):
            raise _ckpt.TopologyMismatch(
                f"checkpoint was saved at {man['n_shards']} shards but this "
                f"multi-process mesh runs {self._num_shards}: per-process "
                "blocks cannot reshard in place — assemble the full state "
                "(utils.checkpoint.assemble_full_state) and convert it with "
                "utils.checkpoint.reshard_state(state, "
                f"{self._num_shards}) first"
            )
        self._particles = self._restore_global(
            "particles",
            np.asarray(state["particles"]),
            int(state.get("particles_start", 0)),
            (self._num_particles, self._d),
        )
        prev = state.get("previous")
        resharded = False
        if prev is not None:
            want = self._prev_shape()
            prev_arr = np.asarray(prev)
            if self._mesh_is_multiprocess():
                prev = self._restore_global(
                    "previous", prev_arr, int(state.get("previous_start", 0)), want
                )
            else:
                # host array, as the eager LP path keeps it; rebuilt when the
                # save used a different shard layout
                resharded = prev_arr.shape != want
                prev = self._reshard_previous(prev_arr)
        self._previous = prev
        g = state.get("w2_g")  # absent in pre-warm-start checkpoints → cold
        if g is not None:
            want = self._g_shape()
            g_arr = np.asarray(g)
            if self._mesh_is_multiprocess():
                g = self._restore_global(
                    "w2_g", g_arr, int(state.get("w2_g_start", 0)), want
                )
            elif resharded:
                # the dual's per-block pairing does not survive a reshard:
                # cold-start the first solve instead (load_state_dict doc)
                g = None
            elif g_arr.shape != want:
                # NOT a reshard (the snapshot matched) — a mismatched dual
                # alone means a corrupt/mixed-up checkpoint: fail fast
                raise ValueError(
                    f"checkpoint 'w2_g' dual {g_arr.shape} != expected {want} "
                    "(corrupt or mismatched checkpoint?)"
                )
            else:
                g = g_arr
        self._w2_g = g
        code = state.get("w2_pairing")  # absent in older checkpoints
        # with the W2 term off the pairing is an inert placeholder on both
        # sides — a mismatch means nothing, so stay silent
        if code is not None and self._include_wasserstein:
            saved = W2_PAIRING_CODES[int(np.asarray(code))]
            if saved != self._w2_pairing:
                warnings.warn(
                    f"checkpoint was written under w2_pairing='{saved}' but "
                    f"this sampler resolved '{self._w2_pairing}': the "
                    "trajectory before and after the restore optimises "
                    "different W2 functionals (reshard-on-restore converts "
                    "the state exactly, but the objective changes)",
                    stacklevel=2,
                )
        key = state.get("rng_batch_key")  # absent in pre-elastic checkpoints
        if key is not None:
            # the saved minibatch root: layout-free (per-step keys fold
            # (root, t)), so a resharded resume re-derives the exact stream
            self._batch_key = jnp.asarray(np.asarray(key))
        acode = state.get("approx_method")
        if (acode is None) != (self._approx is None):
            want = (self._approx.method if self._approx is not None
                    else "exact")
            saved = ("exact" if acode is None
                     else APPROX_METHOD_CODES[int(np.asarray(acode))])
            raise ValueError(
                f"checkpoint was written with kernel_approx={saved!r} but "
                f"this sampler runs {want!r}: resuming would silently "
                "switch φ backends mid-trajectory — construct the sampler "
                "with the checkpoint's kernel_approx (or retrain)"
            )
        if acode is not None:
            saved_method = APPROX_METHOD_CODES[int(np.asarray(acode))]
            saved_dial = int(np.asarray(state["approx_dial"]))
            if (saved_method != self._approx.method
                    or saved_dial != self._approx.accuracy_dial):
                raise ValueError(
                    f"checkpoint kernel_approx is {saved_method!r} at dial "
                    f"{saved_dial} but this sampler runs "
                    f"{self._approx.method!r} at "
                    f"{self._approx.accuracy_dial}: the accuracy dial is "
                    "part of the trajectory — match the saved configuration"
                )
            redraw_code = state.get("approx_rff_redraw")
            # absent in pre-redraw checkpoints, which could only have been
            # written by a per-run-bank sampler
            saved_redraw = (RFF_REDRAW_MODES[int(np.asarray(redraw_code))]
                            if redraw_code is not None else "run")
            if (self._approx.method == "rff"
                    and saved_redraw != self._approx.rff_redraw):
                raise ValueError(
                    f"checkpoint was written with rff_redraw="
                    f"{saved_redraw!r} but this sampler runs "
                    f"{self._approx.rff_redraw!r}: the bank lifetime is "
                    "part of the trajectory — match the saved configuration"
                )
            rebuild = False
            bank = state.get("approx_bank_key")
            if bank is not None and not np.array_equal(
                    np.asarray(bank), np.asarray(self._approx.key)):
                # the SAVED bank wins: bitwise resume of the original
                # trajectory beats the key this construction's seed derived
                self._approx = self._approx.with_key(
                    jnp.asarray(np.asarray(bank)))
                rebuild = True
            active = state.get("approx_active")
            if (active is not None
                    and bool(int(np.asarray(active))) != self._approx_active):
                # the SAVED crossover pin wins too: in partitions mode the
                # 'auto' decision depends on the block size, so a resharded
                # resume could re-pin the other backend — a silent
                # φ-backend switch mid-trajectory, exactly what the
                # method/dial refusals above exist to prevent
                self._approx_active = bool(int(np.asarray(active)))
                rebuild = True
            if rebuild:
                self._build_step_programs()
        self._t = int(state["t"])

    # ------------------------------------------------------------------ #

    def run_steps(
        self,
        num_steps: int,
        step_size: float,
        record: bool = False,
        h: float = 1.0,
        dispatch_budget: Optional[float] = None,
        pairs_per_sec: Optional[float] = None,
        hops_per_dispatch: Optional[int] = None,
        max_passes_per_dispatch: Optional[int] = None,
        time_dispatches: bool = False,
    ):
        """``num_steps`` distributed SVGD steps, monolithic or **chunked**.

        With the chunking knobs at their defaults this is the classic
        single-dispatch scanned path (:meth:`_run_steps_scan` — one jitted
        ``lax.scan`` over the per-shard step, the fast default).  The knobs
        exist because past ~2M particles ONE step is a single ≳60 s
        dispatch (φ alone is 4e12 pairs) and the TPU tunnel's execution
        watchdog kills it (docs/notes.md large-n table): the chunked
        executor re-expresses the same trajectory as a host-driven chain of
        bounded dispatches with the partial state carried between them, so
        no single dispatch exceeds the budget — the SVGD analogue of
        gradient-accumulation microbatching, at the measured ~0.2 ms
        marginal cost per chained dispatch.

        ``dispatch_budget`` (seconds) auto-selects the execution from n, S,
        and a pairs/sec throughput estimate (``pairs_per_sec``, default
        :data:`DISPATCH_PAIRS_PER_SEC` — the measured v5e rate):

        - whole run fits the budget → **monolithic** (unchanged fast path);
        - a single step fits → **scan chunks**: the scan is split into
          ``steps_per_dispatch``-step dispatches;
        - a single step exceeds the budget → **intra-step** chunking: the
          ring exchange's S ppermute hops run ``hops_per_dispatch`` at a
          time (partial φ accumulator + visiting block carried across
          dispatches — ``parallel/exchange.py:make_chunked_ring_step_fns``),
          and each Sinkhorn W2 solve is split into
          ``max_passes_per_dispatch``-iteration resumable dual-advance
          dispatches (``ops/ot.py:sinkhorn_dual_advance``; the carried
          duals make this exact at convergence), replacing the ad-hoc
          ``sinkhorn_iters`` budget protocol.  Requires
          ``exchange_impl='ring'`` when the φ pass itself must split.

        Pass ``hops_per_dispatch`` / ``max_passes_per_dispatch`` explicitly
        to force intra-step chunking without the heuristic (mutually
        exclusive with ``dispatch_budget``).  ``time_dispatches=True``
        fences every dispatch (``block_until_ready``) and records the max
        per-dispatch wall — measurement mode; leave it off to let chained
        dispatches pipeline.  Every call writes :attr:`last_run_stats`
        (execution mode, dispatch counts, resolved knobs, max dispatch
        wall, resolved ``w2_pairing``) for bench harnesses.

        ``record=True`` histories are **HBM-budget chunked** automatically:
        when the ``(num_steps, n, d)`` pre-update stack would exceed
        ``utils/history.py:RECORD_HBM_BUDGET_BYTES`` (lane padding counted),
        the scan splits into ``record_chunk_steps``-sized dispatches whose
        history chunks are fetched to host overlapped with the next chunk's
        scan, and the returned history is a host ``np.ndarray`` (identical
        trajectory — the step counter and minibatch stream carry across
        chunks in sampler state).

        Chunked trajectories match the monolithic path to float tolerance
        — the hop chunks replay the identical accumulation order, and split
        Sinkhorn solves agree at convergence (tests/test_chunked.py).
        Intra-step constraints: no lagged exchange (``exchange_every > 1``
        plans at whole-cadence granularity instead), fixed-bandwidth
        kernels for the hop split, ``wasserstein_solver='sinkhorn'`` for
        the pass split.
        """
        explicit = (hops_per_dispatch is not None
                    or max_passes_per_dispatch is not None)
        for name, val in (("hops_per_dispatch", hops_per_dispatch),
                          ("max_passes_per_dispatch",
                           max_passes_per_dispatch)):
            if val is not None and val < 1:
                raise ValueError(f"{name} must be >= 1, got {val}")
        if dispatch_budget is not None and explicit:
            raise ValueError(
                "pass either dispatch_budget (auto-chunking) or explicit "
                "hops_per_dispatch / max_passes_per_dispatch, not both"
            )
        if dispatch_budget is None and not explicit:
            if record:
                rc = self._record_chunk()
                if rc < num_steps:
                    # HBM-budget history chunking (round 8; the logreg
                    # driver's round-5 pattern, generalised): bound the
                    # device history stack at (rc, n, d) and fetch each
                    # chunk to host while the next one's scan runs
                    return self._run_steps_record_chunks(
                        num_steps, step_size, h, rc, time_dispatches, None,
                        "record_chunks",
                    )
            with _trace.span("train.step_chunk",
                             {"steps": num_steps, "execution": "monolithic"}
                             if _trace.enabled() else None):
                out = self._run_steps_scan(num_steps, step_size, record, h)
            self.last_run_stats = self._stats(
                "monolithic", num_steps, 1, None)
            return out
        if explicit:
            plan = {"execution": "intra_step",
                    "hops_per_dispatch": hops_per_dispatch,
                    "max_passes_per_dispatch": max_passes_per_dispatch}
        else:
            if dispatch_budget <= 0:
                raise ValueError(
                    f"dispatch_budget must be positive, got {dispatch_budget}"
                )
            plan = self._plan_dispatches(num_steps, dispatch_budget,
                                         pairs_per_sec)
        if plan["execution"] == "monolithic":
            if record:
                rc = self._record_chunk()
                if rc < num_steps:
                    return self._run_steps_record_chunks(
                        num_steps, step_size, h, rc, time_dispatches,
                        dispatch_budget, "record_chunks",
                    )
            with _trace.span("train.step_chunk",
                             {"steps": num_steps, "execution": "monolithic"}
                             if _trace.enabled() else None):
                out = self._run_steps_scan(num_steps, step_size, record, h)
            self.last_run_stats = self._stats(
                "monolithic", num_steps, 1, None,
                dispatch_budget_s=dispatch_budget)
            return out
        if plan["execution"] == "scan_chunks":
            return self._run_steps_scan_chunks(
                num_steps, step_size, record, h,
                plan["steps_per_dispatch"], time_dispatches, dispatch_budget,
            )
        return self._run_steps_intra(
            num_steps, step_size, record, h,
            plan.get("hops_per_dispatch"),
            plan.get("max_passes_per_dispatch"),
            time_dispatches, dispatch_budget,
        )

    def _stats(self, execution, num_steps, num_dispatches, max_wall, **extra):
        stats = {
            "execution": execution,
            "num_steps": num_steps,
            "num_dispatches": num_dispatches,
            "dispatches_per_step": round(
                num_dispatches / max(num_steps, 1), 4),
            "max_dispatch_wall_s": max_wall,
            "w2_pairing": self._w2_pairing,
        }
        stats.update(extra)
        return stats

    def _plan_dispatches(self, num_steps, budget, pairs_per_sec) -> dict:
        """The ``dispatch_budget`` heuristic (see :meth:`run_steps`): model
        per-step work in pairwise interactions, convert through the
        pairs/sec estimate, and pick the coarsest execution whose largest
        dispatch fits the budget."""
        pps = float(pairs_per_sec if pairs_per_sec is not None
                    else DISPATCH_PAIRS_PER_SEC)
        if pps <= 0:
            raise ValueError(f"pairs_per_sec must be positive, got {pps}")
        n = float(self._num_particles)
        S = self._num_shards
        exchanged = self._mode != PARTITIONS
        phi_pairs = n * n if exchanged else n * n / S
        w2_pass_pairs = 0.0
        w2_passes = 0
        if self._include_wasserstein and self._wasserstein_solver == "sinkhorn":
            # per scaling pass: S solves of (n/S, n/S) under the block
            # pairing, (n/S, n) under the global one; plus the 2 soft-
            # c-transform start passes and ~1 finish pass per solve
            w2_pass_pairs = n * n / S if self._block_w2 else n * n
            w2_passes = self._sinkhorn_iters + 3
        step_pairs = phi_pairs + w2_pass_pairs * w2_passes
        t_step = step_pairs / pps
        if num_steps * t_step <= budget:
            return {"execution": "monolithic"}
        if t_step <= budget:
            k = max(1, int(budget // t_step))
            if self._exchange_every > 1:
                # lagged exchange: chunk at whole-cadence granularity
                k = max(self._exchange_every,
                        k - k % self._exchange_every)
            return {"execution": "scan_chunks",
                    "steps_per_dispatch": min(k, num_steps)}
        # one step exceeds the budget: split inside the step
        if self._exchange_every > 1:
            raise ValueError(
                f"one lagged macro-step (~{t_step:.1f} s estimated at "
                f"{pps:.2e} pairs/s) exceeds dispatch_budget={budget} s, "
                "and the lagged exchange has no intra-step seam (one "
                "macro-step IS the gather-amortisation unit) — raise the "
                "budget or drop exchange_every"
            )
        hpd = None
        if self._exchange_impl == "ring" and exchanged:
            hop_pairs = phi_pairs / S
            hpd = max(1, min(S, int(budget * pps // max(hop_pairs, 1.0))))
        elif phi_pairs / pps > budget:
            raise ValueError(
                f"one step's φ pass alone ({phi_pairs:.2e} pairs ≈ "
                f"{phi_pairs / pps:.1f} s at {pps:.2e} pairs/s) exceeds "
                f"dispatch_budget={budget} s, and only the ring exchange "
                "has an intra-step seam to split at — construct with "
                "exchange_impl='ring' (all_* modes), raise num_shards, or "
                "raise the budget"
            )
        max_passes = None
        if w2_pass_pairs:
            # every resumed chunk pays the 2 soft-c-transform start passes
            # (and the last one the finish) on top of its scaling passes —
            # budget the chunk for start + scaling, not scaling alone
            max_passes = max(1, min(self._sinkhorn_iters,
                                    int(budget * pps // w2_pass_pairs) - 3))
        return {"execution": "intra_step", "hops_per_dispatch": hpd,
                "max_passes_per_dispatch": max_passes}

    def _dispatch_runner(self, time_dispatches: bool,
                         span_name: str = "train.dispatch"):
        """Dispatch-counting (and optionally fencing/timing) wrapper used by
        every chunked execution path.  While the span tracer is enabled every
        dispatch records a ``train.dispatch`` span tagged with the dispatched
        program (scan chunk, ring-hop chunk, Sinkhorn dual advance, ...) —
        unfenced unless ``time_dispatches`` already fences, so chained
        dispatches keep pipelining and the span honestly shows *dispatch*
        latency in that mode (the tag says which).  An enabled dispatch
        profiler fences every plan dispatch regardless — the ``fenced``
        tag reflects it, and the pipelining caveat applies for as long as
        profiling is on."""
        import time as _time

        rec = {"count": 0, "max_wall": None}

        def run(fn, *args):
            tags = None
            if _trace.enabled():
                tags = {"fn": getattr(fn, "__name__", type(fn).__name__),
                        "fenced": (bool(time_dispatches)
                                   or _profile.profiler_enabled())}
            with _trace.span(span_name, tags):
                t0 = _time.perf_counter() if time_dispatches else None
                out = fn(*args)
                rec["count"] += 1
                if time_dispatches:
                    # profile.fence, not block_until_ready: when the
                    # dispatch profiler is enabled it already fenced this
                    # output — fence exactly once per dispatch
                    _profile.fence(out)
                    wall = _time.perf_counter() - t0
                    rec["max_wall"] = (wall if rec["max_wall"] is None
                                       else max(rec["max_wall"], wall))
            return out

        return run, rec

    def _record_chunk(self) -> int:
        """Steps per recorded dispatch under the HBM history budget
        (``utils/history.py:record_chunk_steps``; runtime module-attr lookup
        so tests can monkeypatch the sizing).  Lagged exchange chunks at
        whole-cadence granularity."""
        from dist_svgd_tpu.utils import history as _history

        rc = _history.record_chunk_steps(self._num_particles, self._d)
        if self._exchange_every > 1 and rc < self._exchange_every:
            # one lagged macro-step is the indivisible recording unit (its
            # scan emits a (T, n, d) history stack whole), so the chunk
            # cannot drop below T even when the budget says it should —
            # warn instead of silently overshooting the budget
            warnings.warn(
                f"record=True history chunk forced up from {rc} to the "
                f"lagged exchange cadence {self._exchange_every}: one "
                f"macro-step's (T={self._exchange_every}, n="
                f"{self._num_particles}, d) snapshot stack is the "
                "indivisible recording unit and exceeds the HBM history "
                "budget (utils/history.py:RECORD_HBM_BUDGET_BYTES) — "
                "expect elevated device memory, or drop exchange_every / "
                "record at this scale",
                stacklevel=3,
            )
            return self._exchange_every
        if self._exchange_every > 1:
            rc -= rc % self._exchange_every
        return rc

    def _run_steps_record_chunks(self, num_steps, step_size, h,
                                 steps_per_dispatch, time_dispatches, budget,
                                 execution):
        """Recorded trajectory in HBM-budget-sized scan dispatches.  Each
        chunk's pre-update history is fetched to **host** while the next
        chunk's scan runs (the D2H copy is issued after the next dispatch,
        so it rides the transfer engine concurrently on a normal TPU host —
        the logreg driver's round-5 overlap pattern, now built in).  The
        returned history is a host ``np.ndarray``: keeping it on device
        would defeat the budget the chunking enforces."""
        run, rec = self._dispatch_runner(time_dispatches, "train.step_chunk")
        hists = []
        pending = None
        for k in _chunk_sizes(num_steps, steps_per_dispatch):
            out = run(self._run_steps_scan, k, step_size, True, h)
            if pending is not None:
                hists.append(np.asarray(pending))  # overlapped host copy
            pending = out[1]
        if pending is not None:
            hists.append(np.asarray(pending))
        self.last_run_stats = self._stats(
            execution, num_steps, rec["count"], rec["max_wall"],
            steps_per_dispatch=steps_per_dispatch, dispatch_budget_s=budget,
            record_hbm_chunked=True,
        )
        return self._particles, np.concatenate(hists, axis=0)

    def _run_steps_scan_chunks(self, num_steps, step_size, record, h,
                               steps_per_dispatch, time_dispatches, budget):
        """Budgeted middle tier: the monolithic scan split into
        ``steps_per_dispatch``-step dispatches (at most two distinct scan
        lengths — the chunk and the remainder — so at most two compiled
        programs).  Semantics identical to one long scan: the step counter
        and minibatch key stream continue across chunks, and recorded
        histories concatenate without duplicates (each scan emits pre-update
        snapshots only)."""
        if record:
            # the history stack must ALSO fit the HBM budget, and chunked
            # recorded histories live on host (host concat either way)
            return self._run_steps_record_chunks(
                num_steps, step_size, h,
                min(steps_per_dispatch, self._record_chunk()),
                time_dispatches, budget, "scan_chunks",
            )
        run, rec = self._dispatch_runner(time_dispatches, "train.step_chunk")
        for k in _chunk_sizes(num_steps, steps_per_dispatch):
            run(self._run_steps_scan, k, step_size, record, h)
        self.last_run_stats = self._stats(
            "scan_chunks", num_steps, rec["count"], rec["max_wall"],
            steps_per_dispatch=steps_per_dispatch, dispatch_budget_s=budget,
        )
        return self._particles

    # ------------------------------------------------------------------ #
    # Intra-step chunked execution (bounded multi-dispatch stepping)

    def _chunk_fn(self, kind, *args):
        """Bound + jitted chunk program for the intra-step executor, cached
        per (kind, static args) — the host loop reuses a handful of
        programs regardless of step count."""
        key = (kind,) + args
        fn = self._chunk_cache.get(key)
        if fn is not None:
            return fn
        if self._chunk_builders is None:
            from dist_svgd_tpu.parallel.exchange import (
                make_chunked_ring_step_fns,
            )

            self._chunk_builders = make_chunked_ring_step_fns(
                logp=self._logp,
                kernel=self._kernel,
                mode=self._mode,
                num_shards=self._num_shards,
                n_local_data=self._rows_per_shard,
                score_scale=self._score_scale,
                shard_data=self._shard_data,
                batch_size=self._batch_size,
                log_prior=self._log_prior,
                phi_batch_hint=self._phi_batch_hint,
                **self._phi_kwargs(),
            )
        b = self._chunk_builders
        data_spec = self._data_spec
        # Chunk-carry donation (ROADMAP item 1): the executor-owned carries
        # — partial φ accumulators, travelling scores, and the rotated
        # visiting/score pairs of the exact-φ pass — donate, so the relay
        # chain stops re-allocating them per dispatch.  The particle block
        # and the FIRST dispatch's visiting block alias self._particles
        # (reused across chunks and by later passes) and never donate.
        don = {
            "local": (2,),            # acc (zeros-seeded)
            "score": (1,),            # vscores (zeros-seeded)
            "exact_phi": (1, 2, 3),   # visiting/vscores from the score
                                      # pass, acc zeros-seeded
            "add_prior": (1,),        # vscores (consumed)
            "finish": (1, 2),         # acc + w_grad (both step-local)
        }[kind] if self._donate else ()
        if kind == "local":
            num_hops, rotate_last = args
            fn = self._plan.compile_sharded(bind_shard_fn(
                b["local_hops"](num_hops, rotate_last),
                self._num_shards, self._mesh,
                in_specs=(0, 0, 0, data_spec, None, None),
                out_specs=(0, 0),
            ), donate_argnums=don, label="dist.chunk.local",
                audit=self._audit_meta(expect_donation=self._donate))
        elif kind == "score":
            (num_hops,) = args
            fn = self._plan.compile_sharded(bind_shard_fn(
                b["score_hops"](num_hops),
                self._num_shards, self._mesh,
                in_specs=(0, 0, data_spec, None, None),
                out_specs=(0, 0),
            ), donate_argnums=don, label="dist.chunk.score",
                audit=self._audit_meta(expect_donation=self._donate))
        elif kind == "exact_phi":
            num_hops, rotate_last = args
            fn = self._plan.compile_sharded(bind_shard_fn(
                b["exact_phi_hops"](num_hops, rotate_last),
                self._num_shards, self._mesh,
                in_specs=(0, 0, 0, 0),
                out_specs=(0, 0, 0),
            ), donate_argnums=don, label="dist.chunk.exact_phi",
                audit=self._audit_meta(expect_donation=self._donate,
                                       gram_free=False))
        elif kind == "add_prior":
            # row-wise elementwise: applies to the merged global arrays
            # directly, no binding needed (same for 'finish'); both are
            # φ-free, so gram-freedom holds whatever the kernel backend
            fn = self._plan.compile_sharded(
                b["add_prior"], donate_argnums=don,
                label="dist.chunk.add_prior",
                audit=self._audit_meta(expect_donation=self._donate,
                                       gram_free=True))
        elif kind == "finish":
            fn = self._plan.compile_sharded(
                b["finish"], donate_argnums=don, label="dist.chunk.finish",
                audit=self._audit_meta(expect_donation=self._donate,
                                       gram_free=True))
        else:  # pragma: no cover - internal
            raise ValueError(f"unknown chunk kind {kind!r}")
        self._chunk_cache[key] = fn
        return fn

    def _w2_chunk_fn(self, kind, iters, cold):
        """Jitted vmapped Sinkhorn chunk over the per-shard block stack:
        ``'advance'`` resumes the duals only (``sinkhorn_dual_advance``),
        ``'final'`` pays the gradient finish.  ``cold=True`` starts from
        the hard c-transform (``g_init=None``) — the first chunk of a step
        under ``sinkhorn_warm_start=False``."""
        key = ("w2", kind, iters, cold)
        fn = self._chunk_cache.get(key)
        if fn is not None:
            return fn
        from dist_svgd_tpu.ops.ot import sinkhorn_dual_advance

        eps, tol = self._sinkhorn_eps, self._sinkhorn_tol
        if kind == "advance":
            def per(c, p, g):
                return sinkhorn_dual_advance(
                    c, p, eps=eps, iters=iters, tol=tol,
                    g_init=None if cold else g,
                )
        else:
            def per(c, p, g):
                return wasserstein_grad_sinkhorn(
                    c, p, eps=eps, iters=iters, tol=tol,
                    g_init=None if cold else g, return_g=True,
                )

        # the threaded dual g is the chain's carry — donated like every
        # executor-owned carry (the cur/prev inputs are reused across
        # chunks and stay undonated)
        fn = self._plan.compile_sharded(
            jax.vmap(per),
            donate_argnums=(2,) if self._donate else (),
            label=f"dist.w2_chunk.{kind}",
            audit=self._audit_meta(expect_donation=self._donate,
                                   particles_arg=None, gram_free=False),
        )
        self._chunk_cache[key] = fn
        return fn

    def _chunked_wasserstein_grad(self, max_passes, run):
        """Per-step W2 gradient as a chain of bounded solve dispatches (the
        device-side analogue of :meth:`_wasserstein_grad`): ``ceil(iters /
        max_passes) − 1`` dual-advance dispatches threading ``g``, then one
        gradient-finish dispatch.  The carried dual stays on device; so does
        the snapshot roll."""
        dtype = self._particles.dtype
        S = self._num_shards
        cur = self._particles.reshape(S, self._particles_per_shard, self._d)
        prev = jnp.asarray(self._previous, dtype=dtype)
        prev_for = jnp.roll(prev, -1, axis=0) if self._block_w2 else prev
        if self._w2_g is not None:
            g = jnp.asarray(self._w2_g, dtype=dtype)
        else:
            g = jnp.zeros(self._g_shape(), dtype=dtype)
        total = self._sinkhorn_iters
        splits = (_chunk_sizes(total, max_passes)
                  if max_passes is not None else [total])
        # warm start: g_init is the carried/zeros dual (the safe soft-
        # transform start _wasserstein_grad uses); cold: the first chunk
        # starts from the hard c-transform, later chunks must thread g
        cold0 = not self._sinkhorn_warm_start
        for i, k in enumerate(splits[:-1]):
            g = run(self._w2_chunk_fn("advance", k, cold0 and i == 0),
                    cur, prev_for, g)
        grad, g = run(
            self._w2_chunk_fn("final", splits[-1],
                              cold0 and len(splits) == 1),
            cur, prev_for, g,
        )
        self._w2_g = g
        return grad.reshape(self._num_particles, self._d)

    def _snapshot_previous_device(self, pre_update) -> None:
        """Device-side form of :meth:`_snapshot_previous` (the chunked
        executor keeps W2 state on device between dispatches; forcing a
        host round-trip per step would serialise the dispatch chain)."""
        if self._block_w2:
            self._previous = self._particles.reshape(self._prev_shape())
            return
        n, s = self._num_particles, self._particles_per_shard
        # shard r's snapshot: pre-update rows everywhere except its own
        # block, which is post-update (reference dsvgd/distsampler.py:202-3)
        owner = (jnp.arange(n) // s)[None, :] == jnp.arange(
            self._num_shards)[:, None]
        self._previous = jnp.where(
            owner[:, :, None], self._particles[None], pre_update[None]
        )

    def _chunked_phi_step(self, run, w_grad, t_arr, key, eps_arr, h_arr,
                          hops_per_dispatch):
        """One ring-φ step as a chain of hop-chunk dispatches (see
        ``parallel/exchange.py:make_chunked_ring_step_fns`` for the carry
        contracts)."""
        S = self._num_shards
        sizes = _chunk_sizes(S, hops_per_dispatch)
        parts = self._particles
        if self._mode == ALL_SCORES:
            visiting, vscores = parts, jnp.zeros_like(parts)
            for k in sizes:  # score pass: every hop rotates
                visiting, vscores = run(
                    self._chunk_fn("score", k),
                    visiting, vscores, self._data, t_arr, key,
                )
            vscores = run(self._chunk_fn("add_prior"), visiting, vscores)
            acc = jnp.zeros_like(parts)
            for i, k in enumerate(sizes):
                visiting, vscores, acc = run(
                    self._chunk_fn("exact_phi", k, i < len(sizes) - 1),
                    parts, visiting, vscores, acc,
                )
        else:
            visiting, acc = parts, jnp.zeros_like(parts)
            for i, k in enumerate(sizes):
                visiting, acc = run(
                    self._chunk_fn("local", k, i < len(sizes) - 1),
                    parts, visiting, acc, self._data, t_arr, key,
                )
        return run(self._chunk_fn("finish"), parts, acc, w_grad,
                   eps_arr, h_arr)

    def _run_steps_intra(self, num_steps, step_size, record, h,
                         hops_per_dispatch, max_passes, time_dispatches,
                         budget):
        """Bounded multi-dispatch stepping: every logical step is a host-
        driven chain of dispatches — budgeted W2 solve chunks, ring hop
        chunks, and the elementwise finish — with the carried state
        (visiting block, φ accumulator, Sinkhorn duals, W2 snapshots)
        threaded between them.  Trajectory-equivalent to the eager/scanned
        paths (tests/test_chunked.py)."""
        if self._exchange_every > 1:
            raise ValueError(
                "intra-step chunking is undefined for the lagged exchange "
                "(exchange_every > 1): one macro-step IS the amortisation "
                "unit — use dispatch_budget, which chunks at whole-cadence "
                "granularity"
            )
        ring_hops = (self._exchange_impl == "ring"
                     and self._mode != PARTITIONS)
        if hops_per_dispatch is not None and not ring_hops:
            raise ValueError(
                "hops_per_dispatch requires exchange_impl='ring' in an "
                "all_* mode: the gather step has no hop seam to split at, "
                "and the partitions step is already block-local"
            )
        if max_passes is not None and (
                not self._include_wasserstein
                or self._wasserstein_solver != "sinkhorn"):
            raise ValueError(
                "max_passes_per_dispatch splits the per-step Sinkhorn "
                "solve and requires include_wasserstein=True with "
                "wasserstein_solver='sinkhorn' (the host-LP solve has no "
                "pass seam)"
            )
        run, rec = self._dispatch_runner(time_dispatches)
        dtype = self._particles.dtype
        eps_arr = jnp.asarray(step_size, dtype)
        h_arr = jnp.asarray(h, dtype)
        history = [] if record else None
        pending_snap = None  # previous step's device snapshot: fetched to
        # host one step late, so the D2H copy overlaps the NEXT step's
        # dispatch chain instead of fencing it, and at most one snapshot
        # is ever resident on device — the intra-step regime exists
        # because n is huge, where a full (num_steps, n, d) device stack
        # (lane-padded) would dwarf the HBM history budget
        for _ in range(num_steps):
            self._t += 1
            t_arr = jnp.asarray(self._t, dtype=jnp.int32)
            key = jax.random.fold_in(self._batch_key, self._t)
            if record:
                if pending_snap is not None:
                    history.append(np.asarray(pending_snap))
                pending_snap = self._particles
            if self._include_wasserstein and self._previous is not None:
                if self._wasserstein_solver == "sinkhorn":
                    w_grad = self._chunked_wasserstein_grad(
                        max_passes, run).astype(dtype)
                else:  # host LP: no pass seam, one host solve per step
                    w_grad = self._wasserstein_grad().astype(dtype)
                    rec["count"] += 1
            else:
                w_grad = jnp.zeros_like(self._particles)
            pre_update = self._particles if self._include_wasserstein else None
            if ring_hops:
                self._particles = self._chunked_phi_step(
                    run, w_grad, t_arr, key, eps_arr, h_arr,
                    hops_per_dispatch
                    if hops_per_dispatch is not None else self._num_shards,
                )
            else:
                self._particles = run(
                    self._step, self._particles, self._data, w_grad,
                    t_arr, key, eps_arr, h_arr,
                )
            if self._include_wasserstein:
                self._snapshot_previous_device(pre_update)
        # this-process execution report, deliberately NOT checkpointed: a
        # resumed process has dispatched nothing yet, so resetting to the
        # constructor's None is the honest value
        self.last_run_stats = self._stats(  # jaxlint: disable=JL006
            "intra_step", num_steps, rec["count"], rec["max_wall"],
            hops_per_dispatch=hops_per_dispatch,
            max_passes_per_dispatch=max_passes,
            dispatch_budget_s=budget,
        )
        if record:
            if pending_snap is not None:
                history.append(np.asarray(pending_snap))
            # host history, like every chunked record path (run_steps doc)
            return self._particles, np.stack(history)
        return self._particles

    def _run_steps_scan(
        self,
        num_steps: int,
        step_size: float,
        record: bool = False,
        h: float = 1.0,
    ):
        """``num_steps`` distributed SVGD steps as ONE device dispatch — a
        jitted ``lax.scan`` over the per-shard step, so per-step host→device
        latency (~15 ms through a TPU tunnel, docs/notes.md) is paid once per
        call instead of once per step.  Semantically identical to ``num_steps``
        calls of :meth:`make_step`: the step counter (``partitions`` rotation)
        and the per-step minibatch key fold advance exactly as the eager path
        does.  Exception: with ``exchange_every > 1`` this method is the
        *only* driver (``make_step`` raises — one gather is amortised over a
        block of steps, so ``num_steps`` must be a multiple of the cadence;
        sub-step minibatch keys fold ``(key, i)`` within each block;
        ``record=True`` emits the inner scan's per-sub-step pre-update
        snapshots, so the history keeps the per-step convention).

        With ``record=True`` returns ``(final, history)`` where ``history`` is
        the ``(num_steps, n, d)`` device array of pre-update snapshots (the
        reference's history convention: the state *before* each step,
        experiments/logreg.py:78-87 — append ``final`` for the trailing
        post-update snapshot); otherwise returns the final particle array.

        Compile-cost note: one scan program is compiled (and cached on this
        sampler, never evicted) **per distinct** ``(num_steps, record)``
        pair (per ``(num_steps, record, lagged)`` triple, though ``lagged``
        is fixed per sampler).  Callers that vary ``num_steps`` freely — coprime cadences,
        adaptive loops — should decompose their schedule into a bounded set
        of lengths (e.g. power-of-two chunks, at most log2(K) programs; see
        ``experiments/covertype.py`` and ``experiments/logreg.py:
        record_chunk_steps``) or they will pay a fresh multi-second compile for
        every new length.

        With the Wasserstein/JKO term enabled the ``previous`` snapshots ride
        the scan carry on device (``parallel/exchange.py:
        make_shard_step_sinkhorn_w2`` — same warty snapshot semantics as the
        eager path); this requires ``wasserstein_solver='sinkhorn'``, and
        the *global* W2 pairing additionally requires the gather exchange
        implementation (its snapshot is the gathered set).  Under
        ``w2_pairing='block'`` the ring implementation composes — the fully
        O(n/S)-memory exchanged W2 step (round 5).  The host-LP solver
        stays :meth:`make_step`-only.  ``h`` is the W2 weight (reference
        ``delta += h·w_grad``); it is inert when the term is disabled.
        """
        if self._include_wasserstein:
            # ring is a no-op in partitions mode (constructor docstring);
            # in the all_* modes it composes with the BLOCK W2 pairing
            # (round 5: block-sized snapshots need no gathered set — the
            # fully O(n/S)-memory exchanged W2 step) but not with the
            # global pairing, whose snapshot IS the gathered set
            needs_gather = (
                self._mode != PARTITIONS
                and self._exchange_impl != "gather"
                and self._w2_pairing != "block"
                # S=1: every pairing degenerates to the same whole-array
                # snapshot, which the ring step builds without a gather
                and self._num_shards > 1
            )
            if self._wasserstein_solver != "sinkhorn" or needs_gather:
                raise ValueError(
                    "run_steps with the Wasserstein term requires "
                    "wasserstein_solver='sinkhorn', and the global W2 "
                    "pairing requires exchange_impl='gather' (its snapshot "
                    "is the gathered set; pass w2_pairing='block' to "
                    "compose with the ring implementation).  The host-LP "
                    "snapshot path is make_step-only"
                )
            return self._run_steps_w2(num_steps, step_size, h, record)
        lagged = self._exchange_every > 1
        if lagged:
            if num_steps % self._exchange_every:
                raise ValueError(
                    f"num_steps ({num_steps}) must be a multiple of "
                    f"exchange_every ({self._exchange_every})"
                )
            if record and self._bound_lagged_record is None:
                self._bound_lagged_record = self._bind_lagged(record=True)
        dtype = self._particles.dtype
        run = self._scan_cache.get((num_steps, record, lagged))
        if run is None:
            if lagged:
                bound = self._bound_lagged_record if record else self._bound_lagged
            else:
                bound = self._bound_step
            stride = self._exchange_every if lagged else 1

            def scan_run(particles, data, t0, batch_key, eps, h):
                def body(parts, t):
                    new = bound(parts, data, jnp.zeros_like(parts), t,
                                jax.random.fold_in(batch_key, t), eps, h)
                    if lagged and record:
                        # the macro emits the per-sub-step history itself
                        # ((stride, n, d) pre-update snapshots)
                        return new
                    return new, (parts if record else None)

                # lagged: each scan iteration advances `stride` steps, `t`
                # being the first sub-step's 1-based counter
                ts = t0 + 1 + stride * jnp.arange(
                    num_steps // stride, dtype=jnp.int32
                )
                out, hist = jax.lax.scan(body, particles, ts)
                if lagged and record:
                    # (num_steps/stride, stride, n, d) → per-step history
                    hist = hist.reshape((num_steps,) + particles.shape)
                return (out, hist) if record else out

            # plan-routed compile: particles sharded in/out along the mesh
            # axis (history along its particle axis 1), everything else
            # replicated — plain jit under the vmap emulation.  The carry
            # is donated (ROADMAP item 1): the input particle buffer
            # aliases the output instead of re-allocating per dispatch —
            # this object owns it and replaces it right after the call
            run = self._plan.compile_sharded(
                scan_run,
                in_specs=(0, self._data_spec, None, None, None, None),
                out_specs=(0, 1) if record else (0,),
                donate_argnums=(0,) if self._donate else (),
                label="dist.scan",
                audit=self._audit_meta(expect_donation=self._donate),
            )
            self._scan_cache[(num_steps, record, lagged)] = run
        out = run(
            self._particles,
            self._data,
            jnp.asarray(self._t, dtype=jnp.int32),
            self._batch_key,
            jnp.asarray(step_size, dtype=dtype),
            jnp.asarray(0.0, dtype=dtype),
        )
        self._t += num_steps
        if record:
            self._particles, history = out
            return self._particles, history
        self._particles = out
        return self._particles

    def _run_steps_w2(self, num_steps: int, step_size, h, record: bool):
        """Scanned trajectory with the Sinkhorn W2 term: the per-shard
        ``previous`` snapshot stack rides the scan carry (device-side form of
        the host bookkeeping in :meth:`_snapshot_previous`)."""
        dtype = self._particles.dtype
        if self._bound_w2_step is None:
            step = make_shard_step_sinkhorn_w2(
                logp=self._logp,
                kernel=self._kernel,
                mode=self._mode,
                num_shards=self._num_shards,
                n_local_data=self._rows_per_shard,
                score_scale=self._score_scale,
                shard_data=self._shard_data,
                batch_size=self._batch_size,
                log_prior=self._log_prior,
                sinkhorn_eps=self._sinkhorn_eps,
                sinkhorn_iters=self._sinkhorn_iters,
                sinkhorn_tol=self._sinkhorn_tol,
                sinkhorn_warm_start=self._sinkhorn_warm_start,
                phi_batch_hint=self._phi_batch_hint,
                update_rule=self._update_rule,
                w2_pairing=self._w2_pairing,
                ring=(self._exchange_impl == "ring"
                      and self._mode != PARTITIONS),
                **self._phi_kwargs(),
            )
            self._bound_w2_step = bind_shard_fn(
                step,
                self._num_shards,
                self._mesh,
                in_specs=(0, 0, 0, 0 if self._shard_data else None,
                          None, None, None, None, None),
                out_specs=(0, 0, 0),
            )

        run = self._scan_cache.get(("w2", num_steps, record))
        if run is None:
            bound = self._bound_w2_step

            def scan_run(particles, prev, g_dual, w0, data, t0, batch_key,
                         eps, h):
                def body(carry, ti):
                    parts, prv, g = carry
                    t, i = ti
                    # no W2 on a first-ever step (reference: the term waits
                    # for a previous snapshot, dsvgd/distsampler.py:186-188);
                    # every later scan iteration has one from the carry
                    w_on = jnp.where((i == 0) & (w0 == 0.0), 0.0, 1.0).astype(
                        parts.dtype
                    )
                    new, new_prev, new_g = bound(
                        parts, prv, g, data, t,
                        jax.random.fold_in(batch_key, t), eps, h, w_on,
                    )
                    return (new, new_prev, new_g), (parts if record else None)

                ts = t0 + 1 + jnp.arange(num_steps, dtype=jnp.int32)
                (out, prev_out, g_out), hist = jax.lax.scan(
                    body, (particles, prev, g_dual),
                    (ts, jnp.arange(num_steps, dtype=jnp.int32)),
                )
                return out, prev_out, g_out, hist

            # plan-routed: particle array and the per-shard snapshot/dual
            # stacks sharded along their leading axes, history along axis 1.
            # ALL three carries (particles, W2 snapshots, Sinkhorn duals)
            # donate: this object owns each and replaces it after the call
            run = self._plan.compile_sharded(
                scan_run,
                in_specs=(0, 0, 0, None, self._data_spec, None, None,
                          None, None),
                out_specs=(0, 0, 0, 1 if record else None),
                donate_argnums=(0, 1, 2) if self._donate else (),
                label="dist.w2_scan",
                audit=self._audit_meta(expect_donation=self._donate,
                                       gram_free=False),
            )
            self._scan_cache[("w2", num_steps, record)] = run

        have_prev = self._previous is not None
        prev0 = (
            jnp.asarray(self._previous, dtype=dtype)
            if have_prev
            else jnp.zeros(self._prev_shape(), dtype=dtype)
        )
        g0 = (
            jnp.asarray(self._w2_g, dtype=dtype)
            if self._w2_g is not None
            else jnp.zeros(self._g_shape(), dtype=dtype)
        )
        out, prev_out, g_out, hist = run(
            self._particles,
            prev0,
            g0,
            jnp.asarray(1.0 if have_prev else 0.0, dtype=dtype),
            self._data,
            jnp.asarray(self._t, dtype=jnp.int32),
            self._batch_key,
            jnp.asarray(step_size, dtype=dtype),
            jnp.asarray(h, dtype=dtype),
        )
        self._t += num_steps
        self._particles = out
        # keep the snapshot stack on device — the next run_steps consumes it
        # there, and a forced D2H sync per call would defeat the one-dispatch
        # goal; host consumers (state_dict, the eager LP path) np.asarray it
        self._previous = prev_out
        self._w2_g = g_out
        if record:
            return self._particles, hist
        return self._particles

    def make_step(self, step_size: float, h: float = 1.0) -> jax.Array:
        """Perform one distributed SVGD step — reference API
        (dsvgd/distsampler.py:172-205).  Returns the global particle array.
        """
        if self._exchange_every > 1:
            raise ValueError(
                "exchange_every > 1 amortises one gather over a block of "
                "steps; drive it through run_steps(num_steps) with "
                "num_steps a multiple of exchange_every"
            )
        self._t += 1
        dtype = self._particles.dtype
        if self._include_wasserstein and self._previous is not None:
            w_grad = self._wasserstein_grad().astype(dtype)
        else:
            w_grad = jnp.zeros_like(self._particles)

        pre_update = np.asarray(self._particles) if self._include_wasserstein else None
        self._particles = self._step(
            self._particles,
            self._data,
            w_grad,
            jnp.asarray(self._t, dtype=jnp.int32),
            jax.random.fold_in(self._batch_key, self._t),
            jnp.asarray(step_size, dtype=dtype),
            jnp.asarray(h, dtype=dtype),
        )
        if self._include_wasserstein:
            self._snapshot_previous(pre_update)
        return self._particles
