"""1-D Gaussian-mixture sanity experiment — the reference's minimum
end-to-end slice (experiments/gmm.py:1-47): sample 50 particles for 500
iterations at step size 1.0 from the (unnormalised, code-weighted 1/3+1/3)
mixture of N(-2,1) and N(2,1), then write KDE snapshots at timesteps
{0, 50, 75, 100, 150, 500} to ``figures/gmm.png``.

The whole run is one jitted ``lax.scan`` on the default device (TPU when
available), against the reference's per-pair autograd double loop.
"""

import os

import numpy as np

from paths import FIGURES_DIR

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.gmm import gmm_logp

SEED = 42  # reference: torch.manual_seed(42), experiments/gmm.py:11
D = 1
N = 50
NUM_ITER = 500
STEP_SIZE = 1.0
SNAPSHOT_TIMESTEPS = (0, 50, 75, 100, 150, 500)


def run(seed: int = SEED):
    sampler = dt.Sampler(D, gmm_logp)
    return sampler.sample(N, NUM_ITER, STEP_SIZE, seed=seed)


def plot(df, out_path: str):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from scipy.stats import gaussian_kde

    fig, axes = plt.subplots(1, len(SNAPSHOT_TIMESTEPS), figsize=(9, 2))
    for ax, t in zip(axes, SNAPSHOT_TIMESTEPS):
        vals = np.stack(df[df["timestep"] == t]["value"].values)[:, 0]
        grid = np.linspace(vals.min() - 1.5, vals.max() + 1.5, 200)
        dens = gaussian_kde(vals)(grid)
        ax.fill_between(grid, dens, alpha=0.4)
        ax.plot(grid, dens)
        ax.set_title(f"Timestep {t}", fontsize=8)
        ax.set_yticks([])
    fig.tight_layout()
    fig.savefig(out_path, dpi=150)
    return out_path


if __name__ == "__main__":
    df = run()
    out = plot(df, os.path.join(FIGURES_DIR, "gmm.png"))
    final = np.stack(df[df["timestep"] == NUM_ITER]["value"].values)
    print(f"wrote {out}")
    print(f"final particles: mean={final.mean():+.3f} std={final.std():.3f} "
          f"(mixture truth: 0, ~2.24)")
