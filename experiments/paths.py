"""Repo-relative path constants — counterpart of the reference's
``definitions.py:1-7`` — plus the sys.path bootstrap that lets the experiment
scripts import ``dist_svgd_tpu`` when run directly
(``python experiments/gmm.py``)."""

import os
import sys

EXPERIMENTS_DIR = os.path.dirname(os.path.abspath(__file__))
ROOT_DIR = os.path.dirname(EXPERIMENTS_DIR)
FIGURES_DIR = os.path.join(EXPERIMENTS_DIR, "figures")
DATA_DIR = os.path.join(EXPERIMENTS_DIR, "data")
RESULTS_DIR = os.path.join(EXPERIMENTS_DIR, "results")

if ROOT_DIR not in sys.path:
    sys.path.insert(0, ROOT_DIR)

for _d in (FIGURES_DIR, DATA_DIR, RESULTS_DIR):
    os.makedirs(_d, exist_ok=True)
