"""Resilient covertype training: kill-mid-run → resume → serve, with zero
trajectory deviation.

The full fault-tolerance story on the repo's flagship minibatched workload,
one command, five stages:

1. **reference** — an uninterrupted *supervised* run
   (``resilience.RunSupervisor`` driving a sharded minibatched covertype
   ``DistSampler`` with periodic checkpointing) to ``--niter`` steps;
2. **kill** — the identical run is interrupted by an injected preemption at
   ``--kill-step`` (pass ``--real-signals`` to instead install SIGTERM/
   SIGINT handlers and kill the process yourself): the supervisor
   checkpoints at the boundary and reports ``preempted``;
3. **resume** — a fresh supervisor restores the latest checkpoint and runs
   to completion; the final particle state must be **bitwise identical** to
   the reference run's (``max_abs_dev`` printed, asserted 0.0);
4. **serve** — a ``PredictiveEngine`` cold-starts from an *early* step of
   the kill run's checkpoint root and serves held-out rows;
5. **hot reload** — a ``CheckpointHotReloader`` watching the same root
   picks up the resumed run's newer checkpoints and swaps the served
   ensemble between micro-batches; served means are re-checked against a
   direct ``posterior_predictive_prob`` call on the final ensemble
   (train-while-serving, no restart, no recompile in the request window).

Prints one JSON line with the per-stage evidence.
"""

import json
import os
import shutil
import tempfile

import click
import numpy as np

from paths import RESULTS_DIR  # noqa: F401  (bootstraps sys.path)

from dist_svgd_tpu.utils.platform import select_backend


@click.command()
@click.option("--nrows", type=int, default=20_000)
@click.option("--nproc", type=click.IntRange(1, 32), default=4)
@click.option("--nparticles", type=int, default=512)
@click.option("--niter", type=int, default=60)
@click.option("--stepsize", type=float, default=1e-4)
@click.option("--batch-size", type=int, default=256)
@click.option("--checkpoint-every", type=int, default=20)
@click.option("--segment-steps", type=int, default=10)
@click.option("--kill-step", type=int, default=30,
              help="injected preemption step (honoured at the next segment "
                   "boundary, like a real SIGTERM)")
@click.option("--seed", type=int, default=0)
@click.option("--root", default=None,
              help="checkpoint root (default: a temp dir, removed on exit)")
@click.option("--real-signals/--injected-signals", default=False,
              help="install real SIGTERM/SIGINT handlers on the kill run "
                   "instead of injecting the preemption")
@click.option("--requests", type=int, default=32)
@click.option("--backend", type=click.Choice(["auto", "tpu", "cpu"]),
              default="auto")
def cli(nrows, nproc, nparticles, niter, stepsize, batch_size,
        checkpoint_every, segment_steps, kill_step, seed, root, real_signals,
        requests, backend):
    select_backend(backend)
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import (
        ensemble_test_accuracy,
        make_logreg_split,
        posterior_predictive_prob,
    )
    from dist_svgd_tpu.resilience import FaultPlan, PreemptAt, RunSupervisor
    from dist_svgd_tpu.serving import CheckpointHotReloader, PredictiveEngine
    from dist_svgd_tpu.utils.datasets import load_covertype
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    x, t = load_covertype(nrows, seed=0)
    n_test = max(nrows // 10, 1)
    x_train, t_train = jnp.asarray(x[:-n_test]), jnp.asarray(t[:-n_test])
    x_test, t_test = x[-n_test:].astype(np.float32), t[-n_test:]
    d = 1 + x.shape[1]
    likelihood, prior = make_logreg_split()
    n_used = (nparticles // nproc) * nproc
    rows_per_shard = x_train.shape[0] // nproc
    batch = min(batch_size, rows_per_shard) if batch_size else None

    def make_sampler():
        return dt.DistSampler(
            nproc, likelihood, None,
            init_particles_per_shard(seed, n_used, d, nproc),
            data=(x_train, t_train),
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=False, shard_data=True, batch_size=batch,
            log_prior=prior, seed=seed,
        )

    cleanup = root is None
    root = root or tempfile.mkdtemp(prefix="resilient_covertype_")
    out = {"nrows": nrows, "nproc": nproc, "nparticles": n_used,
           "niter": niter, "checkpoint_every": checkpoint_every,
           "segment_steps": segment_steps, "root": root}
    try:
        # 1. reference: uninterrupted supervised run
        ref = make_sampler()
        sup_ref = RunSupervisor(
            ref, niter, stepsize,
            checkpoint_dir=os.path.join(root, "reference"),
            checkpoint_every=checkpoint_every, segment_steps=segment_steps,
        )
        ref_report = sup_ref.run()
        out["reference"] = {k: ref_report[k]
                            for k in ("status", "t", "checkpoints")}
        final_ref = np.asarray(sup_ref.particles)

        # 2. kill mid-run (injected preemption, or real signals + your kill)
        kill_root = os.path.join(root, "killed")
        ds_kill = make_sampler()
        sup_kill = RunSupervisor(
            ds_kill, niter, stepsize, checkpoint_dir=kill_root,
            checkpoint_every=checkpoint_every, segment_steps=segment_steps,
            faults=None if real_signals else FaultPlan(PreemptAt(kill_step)),
        )
        if real_signals:
            sup_kill.install_signal_handlers()
            click.echo(f"PID {os.getpid()}: send SIGTERM to preempt", err=True)
        kill_report = sup_kill.run()
        out["kill"] = {k: kill_report[k] for k in ("status", "t")}

        # 4 (starts before 3 — that is the point): serve the preemption
        # checkpoint while the resumed trainer is still to come.  Cold
        # start from the kill root's newest step (= the signal-triggered
        # save), pre-trace the buckets, attach the watcher with that step
        # as its baseline.
        engine = PredictiveEngine.from_checkpoint(kill_root, "logreg",
                                                  max_bucket=64)
        engine.warmup()
        served_before = engine.predict(x_test[:requests])["mean"]
        reloader = CheckpointHotReloader(engine, kill_root)

        # 3. resume → bitwise-identical final state.  The supervisor writes
        # its periodic checkpoints into the SAME root the engine watches —
        # train-while-serving.
        ds_res = make_sampler()
        sup_res = RunSupervisor(
            ds_res, niter, stepsize, checkpoint_dir=kill_root,
            checkpoint_every=checkpoint_every, segment_steps=segment_steps,
        )
        res_report = sup_res.run(resume=True)
        final_res = np.asarray(sup_res.particles)
        max_dev = float(np.max(np.abs(final_ref - final_res)))
        out["resume"] = {
            "status": res_report["status"],
            "resumed_from": res_report["resumed_from"],
            "max_abs_dev_vs_uninterrupted": max_dev,
            "bitwise_identical": bool(np.array_equal(final_ref, final_res)),
        }
        assert out["resume"]["bitwise_identical"], (
            f"resumed trajectory deviates: max abs dev {max_dev}"
        )

        # 5. hot reload: the watcher sees the resumed run's newer
        # checkpoints and swaps the served ensemble between micro-batches
        swapped_step = reloader.poll_once()
        served_after = engine.predict(x_test[:requests])["mean"]
        direct = np.asarray(jnp.mean(posterior_predictive_prob(
            jnp.asarray(final_res), jnp.asarray(x_test[:requests])
        ), axis=0))
        out["serve"] = {
            "cold_start_particles": engine.n_particles,
            "hot_reload_step": swapped_step,
            "reloads": engine.stats()["reloads"],
            "ensemble_tag": engine.stats()["ensemble_tag"],
            "served_vs_direct_max_abs_dev": float(
                np.max(np.abs(served_after - direct))
            ),
            "served_drift_on_reload": float(
                np.max(np.abs(served_after - served_before))
            ),
            "served_test_acc": float(np.mean(
                (served_after > 0.5) == (t_test[:requests] > 0)
            )),
            "test_acc_final": float(ensemble_test_accuracy(
                jnp.asarray(final_res), jnp.asarray(x_test),
                jnp.asarray(t_test),
            )),
        }
        print(json.dumps(out), flush=True)
    finally:
        if cleanup:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    cli()
