#!/usr/bin/env bash
# Full configuration sweep — port of the reference's grid.sh:1-13:
# 7 datasets x 100 folds x {1,2,4,8} shards x 3 exchange modes x +/-wasserstein.
set -u
cd "$(dirname "$0")/.."
for dataset in banana diabetis german image splice titanic waveform; do
  for fold in $(seq 1 100); do
    for nproc in 1 2 4 8; do
      for exchange in partitions all_particles all_scores; do
        time python experiments/logreg.py --dataset=$dataset --fold=$fold --nproc=$nproc --nparticles=50 --niter=500 \
          --exchange=$exchange --no-wasserstein --plots
        time python experiments/logreg.py --dataset=$dataset --fold=$fold --nproc=$nproc --nparticles=50 --niter=500 \
          --exchange=$exchange --wasserstein --plots
      done
    done
  done
done
