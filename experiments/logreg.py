"""Distributed Bayesian logistic regression — the reference's flagship
experiment (experiments/logreg.py:23-147), rebuilt as a single SPMD program.

The reference spawns one OS process per rank, each initialising
``torch.distributed`` over TCP and running its own sampler
(experiments/logreg.py:94-140).  Here one process drives all shards through
``DistSampler``: shards map to mesh devices (or to vmap lanes when the host
has fewer devices), the rendezvous env-var machinery disappears, and the
``--master_addr/--master_port`` flags are kept for CLI-surface compatibility
as documented no-ops.

Flag surface mirrors the reference CLI (experiments/logreg.py:105-118), plus
``--backend {auto,tpu,cpu}`` per the BASELINE.json north star and
``--wasserstein-solver {lp,sinkhorn}`` selecting between the exact-parity
eager host-LP W2 path and the scanned on-device Sinkhorn path (whole
trajectory per dispatch — the fast way to run the reference's flagship
``--wasserstein`` sweep config).

Per-shard outputs keep the reference's exact conventions: a pandas pickle
``shard-<rank>.pkl`` per shard with columns ``timestep``/``value``, snapshots
of the shard's *owned* block taken before each step plus one final post-update
snapshot (experiments/logreg.py:78-92).
"""

import os
import shutil

import click
import numpy as np
import pandas as pd

from paths import DATA_DIR, RESULTS_DIR  # noqa: F401  (bootstraps sys.path)

from logreg_plots import get_results_dir, make_plots

from dist_svgd_tpu.utils.platform import select_backend


# HBM-budget-sized history chunking moved into the library (round 8): the
# samplers auto-chunk recorded trajectories through utils/history.py, so
# every driver — logreg, covertype, bnn, gmm — gets it.  Re-exported here
# for tools/record_overhead.py and the sizing tests.
from dist_svgd_tpu.utils.history import (  # noqa: F401
    RECORD_CHUNK_MAX,
    RECORD_HBM_BUDGET_BYTES,
    record_chunk_steps,
)


def run(num_shards, dataset_name, fold, nparticles, niter, stepsize, exchange,
        wasserstein, wasserstein_solver="lp", update_rule="jacobi"):
    """One SPMD run over ``num_shards`` shards; writes per-shard pickles."""
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    fold_data = load_benchmark(
        dataset_name, fold, mat_path=os.path.join(DATA_DIR, "benchmarks.mat")
    )
    x_train = jnp.asarray(fold_data.x_train)
    t_train = jnp.asarray(fold_data.t_train.reshape(-1))
    d = 1 + x_train.shape[1]  # particle layout (log α, w), logreg.py:37

    # NOTE: drops particles when not divisible by num_shards — the
    # reference's policy (dsvgd/distsampler.py:42-45); grid.sh runs 50
    # particles on 4 and 8 shards, so truncation is load-bearing.  The
    # results-dir name keeps the *requested* count, like the reference.
    n_used = (nparticles // num_shards) * num_shards
    # per-shard independent init streams — the SPMD equivalent of the
    # reference's per-rank torch.manual_seed(rank) (experiments/logreg.py:24)
    particles = init_particles_per_shard(0, n_used, d, num_shards)

    sampler = dt.DistSampler(
        num_shards,
        logreg_logp,
        None,  # reference RBF(bandwidth=1) kernel
        particles,
        data=(x_train, t_train),
        exchange_particles=exchange in ("all_particles", "all_scores"),
        exchange_scores=exchange == "all_scores",
        include_wasserstein=wasserstein,
        wasserstein_solver=wasserstein_solver,
        update_rule=update_rule,
    )

    # history: reference records each rank's owned block before every step
    # plus a final post-update snapshot (experiments/logreg.py:78-87).
    shard_blocks = [[] for _ in range(num_shards)]
    per = n_used // num_shards

    def slice_snapshot(global_now, t=None):
        """Append each rank's owned block at step counter ``t`` (default: the
        sampler's current counter) — ownership per
        DistSampler.owned_block_index."""
        for r in range(num_shards):
            b = sampler.owned_block_index(r, t)
            shard_blocks[r].append(global_now[b * per : (b + 1) * per])

    if wasserstein and wasserstein_solver == "lp":
        # eager reference loop, one dispatch per step: the host-LP W2 (exact
        # reference parity) needs per-step host snapshots and cannot live in
        # a jitted scan.  Every other combination — including GS + sinkhorn
        # W2 (round 4) — runs scanned below
        for _ in range(niter):
            slice_snapshot(np.asarray(sampler.particles))
            sampler.make_step(stepsize, h=10.0)  # h=10 matches logreg.py:83
        slice_snapshot(np.asarray(sampler.particles))
    else:
        # whole trajectory (with pre-update history) in scanned dispatches.
        # The samplers HBM-budget-chunk recorded histories themselves now
        # (round 8; `DistSampler.run_steps` docstring — chunk sizing via
        # utils/history.py:record_chunk_steps, each chunk's D2H copy
        # overlapped with the next chunk's scan).  Note the axon-relay
        # caveat still applies to the pool: its tunnel serialises D2H with
        # execution server-side (~46 MB/s, zero overlap — docs/notes.md
        # round-5, tools/record_overhead.py); that is a property of the
        # relay, not of the chunking.  With --wasserstein-solver sinkhorn
        # the W2 snapshot state rides the scan carry on device, so the
        # reference's flagship --wasserstein sweep config runs at scan
        # speed instead of ~15 ms of tunnel dispatch per step.
        h = 10.0 if wasserstein else 1.0  # h inert when the term is off
        if niter:
            final, hist = sampler.run_steps(niter, stepsize, record=True, h=h)
            snaps = np.concatenate(
                [np.asarray(hist), np.asarray(final)[None]]
            )
        else:  # niter=0: single t=0 snapshot, no dispatch
            snaps = np.asarray(sampler.particles)[None]
        for t in range(niter + 1):
            slice_snapshot(snaps[t], t)

    results_dir = get_results_dir(
        dataset_name, fold, num_shards, nparticles, stepsize, exchange,
        wasserstein, update_rule,
    )
    for r in range(num_shards):
        rows = [
            pd.Series([t, block[i]], index=["timestep", "value"])
            for t, block in enumerate(shard_blocks[r])
            for i in range(block.shape[0])
        ]
        pd.DataFrame(rows).to_pickle(os.path.join(results_dir, f"shard-{r}.pkl"))
    return sampler


@click.command()
@click.option("--dataset", type=click.Choice([
    "banana", "diabetis", "german", "image", "splice", "titanic", "waveform"]),
    default="banana")
@click.option("--fold", type=int, default=42)
@click.option("--nproc", type=click.IntRange(0, 32), default=1,
              help="number of shards (the reference's world size)")
@click.option("--nparticles", type=int, default=10)
@click.option("--niter", type=int, default=100)
@click.option("--stepsize", type=float, default=1e-3)
@click.option("--exchange", type=click.Choice(["partitions", "all_particles", "all_scores"]),
              default="partitions")
@click.option("--wasserstein/--no-wasserstein", default=False)
@click.option("--update-rule", type=click.Choice(["jacobi", "gauss_seidel"]),
              default="jacobi",
              help="jacobi = vectorised TPU-native update; gauss_seidel = "
                   "the reference's literal in-place sweep (exact reference "
                   "trajectories, small-n verification speed)")
@click.option("--wasserstein-solver", type=click.Choice(["lp", "sinkhorn"]),
              default="lp",
              help="W2 solver: 'lp' = host LP, exact reference parity, eager "
                   "dispatch per step; 'sinkhorn' = on-device entropic OT, "
                   "whole trajectory in scanned dispatches")
@click.option("--master_addr", default="127.0.0.1", type=str,
              help="no-op under SPMD; kept for reference CLI compatibility")
@click.option("--master_port", default=29500, type=int,
              help="no-op under SPMD; kept for reference CLI compatibility")
@click.option("--backend", type=click.Choice(["auto", "tpu", "cpu"]), default="auto",
              help="device backend for the jitted step")
@click.option("--plots/--no-plots", default=True)
@click.pass_context
def cli(ctx, dataset, fold, nproc, nparticles, niter, stepsize, exchange,
        wasserstein, update_rule, wasserstein_solver, master_addr, master_port,
        backend, plots):
    select_backend(backend)
    # normalise nproc=0 to a single shard up front so the results dir, the
    # run, and the plots all agree on the same config name
    nproc = max(nproc, 1)

    # clean out any previous results (reference behaviour, logreg.py:120-124)
    results_dir = get_results_dir(dataset, fold, nproc, nparticles, stepsize,
                                  exchange, wasserstein, update_rule)
    if os.path.isdir(results_dir):
        shutil.rmtree(results_dir)
    os.makedirs(results_dir)

    run(nproc, dataset, fold, nparticles, niter, stepsize, exchange,
        wasserstein, wasserstein_solver, update_rule)

    if plots:
        ctx.invoke(
            make_plots, dataset=dataset, fold=fold, nproc=nproc,
            nparticles=nparticles, stepsize=stepsize, exchange=exchange,
            wasserstein=wasserstein, update_rule=update_rule,
        )


if __name__ == "__main__":
    cli()
