"""Evaluation / plotting for the Bayesian-logreg experiment.

Counterpart of the reference's ``experiments/logreg_plots.py:19-127`` with
matplotlib PNGs in place of the visdom server (a dead-weight external
dependency — SURVEY.md §5 metrics row): the test-accuracy-vs-iteration curve
against an sklearn ``LogisticRegression`` baseline, plus (for banana) particle
scatter and α histograms.

Reference quirks handled deliberately (SURVEY.md §7.4):
- results-dir naming doubles as the config record and must keep the exact
  reference format for sweep compatibility (logreg_plots.py:19-22);
- the posterior-predictive ``prob`` decodes α but uses only w
  (logreg_plots.py:44-48) — replicated via models.logreg;
- the reference gates the banana scatter/histogram plots on the string
  literal comparison ``'dataset' == 'banana'`` which is always False
  (logreg_plots.py:116, dead code) — fixed here to compare the variable, as
  clearly intended.
"""

import os
from glob import glob

import click
import numpy as np
import pandas as pd

from paths import DATA_DIR, FIGURES_DIR, RESULTS_DIR

from dist_svgd_tpu.models.logreg import posterior_predictive_prob
from dist_svgd_tpu.utils.datasets import load_benchmark

TIMESTEPS_BETWEEN_KDE_PLOTS = 10


def get_results_dir(dataset_name, fold, nproc, nparticles, stepsize, exchange,
                    wasserstein, update_rule="jacobi"):
    """Config-encoded results dir — exact reference naming
    (logreg_plots.py:19-22).  The non-reference ``update_rule`` knob is
    appended only when non-default, so reference-config names stay
    byte-identical while a gauss_seidel verification run never collides
    with its jacobi counterpart."""
    subdir = "logreg_{}_{}-nshards={}-nparticles={}-exchange={}-wasserstein={}-stepsize={:.0e}".format(
        dataset_name, fold, nproc, nparticles, exchange, wasserstein, stepsize
    )
    if update_rule != "jacobi":
        subdir += f"-update_rule={update_rule}"
    return os.path.join(RESULTS_DIR, subdir)


def _mat_path():
    return os.path.join(DATA_DIR, "benchmarks.mat")


def sklearn_baseline_accuracy(fold_data) -> float:
    """Reference baseline: sklearn LogisticRegression fit on the same fold
    (logreg_plots.py:37-39)."""
    from sklearn.linear_model import LogisticRegression

    clf = LogisticRegression()
    clf.fit(fold_data.x_train, fold_data.t_train.reshape(-1))
    return float(clf.score(fold_data.x_test, fold_data.t_test.reshape(-1)))


def test_accuracy_curve(df, fold_data):
    """Per-timestep ensemble posterior-predictive-mean accuracy
    (reference logreg_plots.py:42-57 semantics: mean σ(x·w) over particles,
    threshold 0.5, compare t > 0)."""
    t_test = fold_data.t_test.reshape(-1) > 0
    rows = []
    for t, group in df.groupby("timestep"):
        particles = np.stack(group["value"].values)
        probs = np.asarray(posterior_predictive_prob(particles, fold_data.x_test))
        acc = float(((probs.mean(axis=0) > 0.5) == t_test).mean())
        rows.append((int(t), acc))
    rows.sort()
    return np.asarray(rows)


def plot_test_acc(df, plot_title, dataset_name, fold, out_path):
    fold_data = load_benchmark(dataset_name, fold, mat_path=_mat_path())
    baseline = sklearn_baseline_accuracy(fold_data)
    curve = test_accuracy_curve(df, fold_data)

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(6, 4))
    ax.plot(curve[:, 0], curve[:, 1], label="dsvgd")
    ax.axhline(baseline, color="tab:orange", ls="--", label="sklearn logreg")
    ax.set_xlabel("Iteration")
    ax.set_ylabel("Test accuracy")
    ax.set_title(plot_title, fontsize=8)
    ax.legend()
    fig.tight_layout()
    fig.savefig(out_path, dpi=150)
    plt.close(fig)
    return curve, baseline


def plot_w_scatters(df, plot_title, out_dir):
    """Particle (w1, w2) scatter per sampled timestep
    (reference logreg_plots.py:69-80)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    for t in range(0, int(df["timestep"].max()), TIMESTEPS_BETWEEN_KDE_PLOTS):
        vals = np.stack(df[df["timestep"] == t]["value"].values)
        fig, ax = plt.subplots(figsize=(4, 4))
        ax.scatter(vals[:, 1], vals[:, 2], s=8)
        ax.set_xlim(-1.5, 1.5)
        ax.set_ylim(-3, 2)
        ax.set_xlabel("w1")
        ax.set_ylabel("w2")
        ax.set_title(plot_title(t), fontsize=7)
        fig.tight_layout()
        fig.savefig(os.path.join(out_dir, f"particles_w1_w2_t{t}.png"), dpi=120)
        plt.close(fig)


def plot_alpha_hist(df, plot_title, out_dir):
    """Histogram of the (log) α component (reference logreg_plots.py:82-93)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    for t in range(0, int(df["timestep"].max()), TIMESTEPS_BETWEEN_KDE_PLOTS):
        vals = np.stack(df[df["timestep"] == t]["value"].values)[:, 0]
        fig, ax = plt.subplots(figsize=(4, 3))
        ax.hist(vals, bins=20, range=(-2, 2))
        ax.set_xlabel("alpha")
        ax.set_title(plot_title(t), fontsize=7)
        fig.tight_layout()
        fig.savefig(os.path.join(out_dir, f"particles_alpha_t{t}.png"), dpi=120)
        plt.close(fig)


@click.command()
@click.option("--dataset", type=click.Choice([
    "banana", "diabetis", "german", "image", "splice", "titanic", "waveform"]),
    default="banana")
@click.option("--fold", type=int, default=42)
@click.option("--nproc", type=click.IntRange(0, 32), default=1)
@click.option("--nparticles", type=int, default=10)
@click.option("--stepsize", type=float, default=1e-3)
@click.option("--exchange", type=click.Choice(["partitions", "all_particles", "all_scores"]),
              default="partitions")
@click.option("--wasserstein/--no-wasserstein", default=False)
@click.option("--update-rule", type=click.Choice(["jacobi", "gauss_seidel"]),
              default="jacobi")
def make_plots(dataset, fold, nproc, nparticles, stepsize, exchange, wasserstein,
               update_rule="jacobi", **kwargs):
    """Aggregate shard-*.pkl results and write evaluation PNGs
    (reference make_plots, logreg_plots.py:95-124)."""
    results_dir = get_results_dir(dataset, fold, nproc, nparticles, stepsize,
                                  exchange, wasserstein, update_rule)
    df = pd.concat(map(pd.read_pickle, glob(os.path.join(results_dir, "shard-*.pkl"))))

    cfg = "logreg_{}_{} {} nshards={} nparticles={} exchange={} wasserstein={} stepsize={:.0e}".format(
        dataset, fold, "test_acc", nproc, nparticles, exchange, wasserstein, stepsize)
    fig_base = os.path.basename(results_dir)
    curve, baseline = plot_test_acc(
        df, cfg, dataset, fold, os.path.join(FIGURES_DIR, fig_base + "-test_acc.png"))
    print(f"final dsvgd accuracy {curve[-1, 1]:.4f} vs sklearn {baseline:.4f}")

    if dataset == "banana":  # reference had dead `'dataset' == 'banana'` here
        out_dir = os.path.join(FIGURES_DIR, fig_base)
        os.makedirs(out_dir, exist_ok=True)
        title_w = lambda t: f"{fig_base} particles_w1_w2 t={t}"
        plot_w_scatters(df, title_w, out_dir)
        title_a = lambda t: f"{fig_base} particles_alpha t={t}"
        plot_alpha_hist(df, title_a, out_dir)


if __name__ == "__main__":
    make_plots()
