"""Two-layer Bayesian-NN regression on the UCI suite — BASELINE.json config 5
("2-layer Bayesian NN regression (UCI), 500 particles, weight-vector SVGD").

No reference counterpart exists (the reference's models are GMM and logreg);
this driver follows the reference's experiment-script shape
(experiments/logreg.py:105-147): click CLI, per-shard result pickles under a
config-named results dir, optional sharding via ``DistSampler``.

Protocol (the standard SVGD BNN setup): 90/10 train/test split, features and
targets z-scored by train statistics, minibatched stochastic scores with a
separate (unscaled) prior, ensemble posterior-predictive RMSE and
log-likelihood reported on the original target scale.
"""

import json
import os
import time

import click
import numpy as np

from paths import DATA_DIR, RESULTS_DIR  # noqa: F401  (bootstraps sys.path)

from dist_svgd_tpu.utils.platform import select_backend


def get_results_dir(
    dataset, split, nproc, nparticles, n_hidden, niter, stepsize, batch_size,
    exchange, seed, bandwidth="1.0", phi_impl="auto", exchange_every=1,
):
    """Config-encoded results dir — every CLI knob that changes the run is in
    the name, so sweep configurations never overwrite each other (reference
    naming convention, experiments/logreg_plots.py:19-22)."""
    name = (
        f"bnn-{dataset}-{split}-{nproc}-{nparticles}-{n_hidden}-{niter}-"
        f"{stepsize}-{batch_size}-{exchange}-{seed}"
    )
    # suffix keyed on the *resolved* semantics (not the spelling), so
    # --bandwidth 1 / 1.0 / 1.00 all land in the default dir
    if bandwidth in ("median", "median_step") or float(bandwidth) != 1.0:
        name += f"-h={bandwidth}"
    if phi_impl != "auto":
        name += f"-phi={phi_impl}"
    if exchange_every != 1:
        name += f"-T={exchange_every}"
    path = os.path.join(RESULTS_DIR, name)
    os.makedirs(path, exist_ok=True)
    return path


def resolve_bandwidth_kernel(bandwidth: str):
    """CLI ``--bandwidth`` → sampler kernel arg: ``'median'`` (heuristic,
    resolved from the initial particles — the sensible default for the d=753
    weight-vector space where the reference's h=1 puts every pairwise kernel
    value near exp(-d)), ``'median_step'`` (re-resolved from the current
    particles every step, inside the scan), a float, or the reference's
    fixed 1.0 → ``None``."""
    if bandwidth in ("median", "median_step"):
        return bandwidth
    h = float(bandwidth)
    if h == 1.0:
        return None  # reference RBF(1)
    from dist_svgd_tpu.ops.kernels import RBF

    return RBF(h)


def run(
    dataset="boston",
    split=0,
    nproc=1,
    nparticles=500,
    n_hidden=50,
    niter=1000,
    stepsize=1e-3,
    batch_size=100,
    exchange="all_particles",
    seed=0,
    bandwidth="1.0",
    phi_impl="auto",
    exchange_every=1,
):
    """Train; returns (final_particles, metrics dict)."""
    import jax
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models import bnn
    from dist_svgd_tpu.utils.datasets import load_uci_regression
    from dist_svgd_tpu.utils.rng import as_key

    # pure-argument validation before any data load (as covertype.py)
    if exchange_every > 1:
        if nproc == 1:
            raise ValueError(
                "--exchange-every > 1 is a distributed exchange cadence; "
                "it requires --nproc > 1"
            )
        if exchange != "all_particles":
            raise ValueError(
                "--exchange-every > 1 requires --exchange all_particles"
            )
        if niter % exchange_every:
            raise ValueError(
                f"--niter ({niter}) must be a multiple of "
                f"--exchange-every ({exchange_every})"
            )

    sp = load_uci_regression(dataset, split, data_path=DATA_DIR)
    x_tr = jnp.asarray(sp.x_train)
    y_tr = jnp.asarray(sp.y_train)
    n_features = x_tr.shape[1]
    d = bnn.num_params(n_features, n_hidden)

    n_used = (nparticles // nproc) * nproc  # reference drop policy
    particles = bnn.init_particles(as_key(seed), n_used, n_features, n_hidden)
    likelihood, prior = bnn.make_bnn_split(n_features, n_hidden)
    batch = min(batch_size, x_tr.shape[0] // nproc) if batch_size else None

    kernel = resolve_bandwidth_kernel(bandwidth)

    t0 = time.perf_counter()
    if nproc == 1:
        sampler = dt.Sampler(
            d, likelihood, kernel=kernel, data=(x_tr, y_tr), batch_size=batch,
            log_prior=prior, phi_impl=phi_impl,
        )
        final, _ = sampler.run(
            n_used, niter, stepsize, seed=seed, record=False,
            initial_particles=particles,
        )
    else:
        sampler = dt.DistSampler(
            nproc,
            likelihood,
            kernel,
            particles,
            data=(x_tr, y_tr),
            exchange_particles=exchange in ("all_particles", "all_scores"),
            exchange_scores=exchange == "all_scores",
            include_wasserstein=False,
            batch_size=batch,
            log_prior=prior,
            phi_impl=phi_impl,
            exchange_every=exchange_every,
            seed=seed,
        )
        sampler.run_steps(niter, stepsize)  # one scanned dispatch
        final = sampler.particles
    final = jax.block_until_ready(final)
    wall = time.perf_counter() - t0

    rmse = float(
        bnn.ensemble_rmse(
            final, jnp.asarray(sp.x_test), sp.y_test, n_features, n_hidden,
            y_mean=sp.y_mean, y_std=sp.y_std,
        )
    )
    ll = float(
        bnn.ensemble_test_loglik(
            final, jnp.asarray(sp.x_test), sp.y_test, n_features, n_hidden,
            y_mean=sp.y_mean, y_std=sp.y_std,
        )
    )
    metrics = {
        "dataset": dataset,
        "split": split,
        "nproc": nproc,
        "nparticles": n_used,
        "n_hidden": n_hidden,
        "niter": niter,
        "stepsize": stepsize,
        "batch_size": batch,
        "exchange": exchange,
        "bandwidth": bandwidth,
        "phi_impl": phi_impl,
        "exchange_every": exchange_every,
        "resolved_bandwidth": (
            sampler._kernel.bandwidth
            if hasattr(sampler._kernel, "bandwidth") else None
        ),
        "test_rmse": rmse,
        "test_loglik": ll,
        "wall_s": round(wall, 3),
        "updates_per_sec": round(n_used * niter / wall, 1),
    }
    return np.asarray(final), metrics


@click.command()
@click.option("--dataset", default="boston")
@click.option("--split", type=int, default=0)
@click.option("--nproc", type=click.IntRange(1, 32), default=1,
              help="number of shards (the reference's world size)")
@click.option("--nparticles", type=int, default=500)
@click.option("--n-hidden", type=int, default=50)
@click.option("--niter", type=int, default=1000)
@click.option("--stepsize", type=float, default=1e-3)
@click.option("--batch-size", type=int, default=100)
@click.option("--exchange", type=click.Choice(["all_particles", "all_scores"]),
              default="all_particles")
@click.option("--seed", type=int, default=0)
@click.option("--bandwidth", default="1.0",
              help="RBF bandwidth: a float (reference default 1.0), 'median' "
                   "(per-run median heuristic — the better default at d=753 "
                   "where h=1 collapses every kernel value), or 'median_step' "
                   "(re-resolved from the current particles every step)")
@click.option("--backend", type=click.Choice(["auto", "tpu", "cpu"]), default="auto")
@click.option("--phi-impl", type=click.Choice(["auto", "xla", "pallas", "pallas_bf16"]),
              default="auto",
              help="phi backend (ops/pallas_svgd.py:resolve_phi_fn)")
@click.option("--exchange-every", type=click.IntRange(1), default=1,
              help="gather cadence T: T > 1 = lagged exchange (all_particles "
                   "only, --nproc > 1, --niter a multiple of T)")
def cli(dataset, split, nproc, nparticles, n_hidden, niter, stepsize, batch_size,
        exchange, seed, bandwidth, backend, phi_impl, exchange_every):
    select_backend(backend)
    final, metrics = run(
        dataset, split, nproc, nparticles, n_hidden, niter, stepsize,
        batch_size, exchange, seed, bandwidth, phi_impl, exchange_every,
    )
    results_dir = get_results_dir(
        dataset, split, nproc, nparticles, n_hidden, niter, stepsize,
        batch_size, exchange, seed, bandwidth, phi_impl, exchange_every,
    )
    np.save(os.path.join(results_dir, "particles.npy"), final)
    with open(os.path.join(results_dir, "metrics.json"), "w") as fh:
        json.dump(metrics, fh, indent=2)
    print(json.dumps(metrics))


if __name__ == "__main__":
    cli()
