"""Large-scale Bayesian logistic regression on Covertype with minibatched
stochastic scores — BASELINE.json config 4 ("Bayesian logistic regression, 10k
particles, Covertype dataset with minibatched ∇logp").

No reference counterpart exists (the reference's logreg driver loads the small
`benchmarks.mat` folds and always scores the full local slice); this driver
exercises the framework pieces the config calls for: the 54-feature
covertype-style dataset (`utils/datasets.py:load_covertype`), particles
sharded over the mesh, per-shard per-step minibatches (``batch_size``, the
writeup's stochastic-score approximation, writeup.tex:214-231), data sharded
over devices (``shard_data=True``) instead of replicated, and a separate
unscaled prior (``log_prior``).

Particle layout is the reference's logreg convention ``(log α, w)``, d = 55
(experiments/logreg.py:37).
"""

import json
import os
import sys
import time

import click
import numpy as np

from paths import DATA_DIR, RESULTS_DIR  # noqa: F401  (bootstraps sys.path)

from dist_svgd_tpu.utils.platform import select_backend


def get_results_dir(
    nrows, nproc, nparticles, niter, stepsize, batch_size, exchange, shard_data,
    seed, phi_impl="auto", bandwidth="1.0", exchange_every=1,
):
    """Every run-changing CLI knob is in the name, so configurations never
    share results or checkpoints; non-default-only suffixes keep
    pre-existing names stable."""
    name = (
        f"covertype-{nrows}-{nproc}-{nparticles}-{niter}-{stepsize}-"
        f"{batch_size}-{exchange}-{'shard' if shard_data else 'repl'}-{seed}"
    )
    if phi_impl != "auto":
        name += f"-phi={phi_impl}"
    if bandwidth in ("median", "median_step") or float(bandwidth) != 1.0:
        name += f"-h={bandwidth}"
    if exchange_every != 1:
        name += f"-T={exchange_every}"
    path = os.path.join(RESULTS_DIR, name)
    os.makedirs(path, exist_ok=True)
    return path


def resolve_phi_impl(phi_impl, batch_size, nparticles, nproc):
    """The covertype driver's φ policy: ``'auto'`` resolves to the bf16x3
    fast tier (``'pallas_bf16'``) when — and only when — all three hold:

    (a) the run is minibatched: the stochastic score's sampling noise
        (~6% per entry at the B=256/6250 default) is ~40× the bf16x3 φ
        tier's 1.4e-3 max rel error, so the config accepts far more noise
        by design than the tier adds (measured 1.53× end-to-end at
        identical test accuracy — docs/notes.md round-3 covertype section);
    (b) a TPU is the backend (elsewhere Pallas runs the interpreter);
    (c) the per-shard interaction size clears the library's big-d auto
        gate (``PALLAS_MIN_PAIRS_BIG_D`` — covertype's d=55 is a big-d
        shape, where the Pallas tiers win at every measured size and the
        gate only guards trivial smoke-scale shapes; docs/notes.md
        round-3 big-d section).

    Shared by the CLI (which resolves *before* deriving results/checkpoint
    dir names, so a resolved run always carries the ``-phi=pallas_bf16``
    suffix and never collides with an exact-f32 ``auto`` run's dirs or
    checkpoints) and by ``bench_suite`` config 4.  Full-batch runs and the
    library-level ``'auto'`` stay exact f32.
    """
    if phi_impl != "auto" or not batch_size:
        return phi_impl
    from dist_svgd_tpu.ops.pallas_svgd import (
        PALLAS_MIN_PAIRS_BIG_D,
        pallas_available,
    )

    n = (nparticles // nproc) * nproc
    if pallas_available() and (n // nproc) * n >= PALLAS_MIN_PAIRS_BIG_D:
        return "pallas_bf16"
    return phi_impl


def run(
    nrows=50_000,
    nproc=8,
    nparticles=10_000,
    niter=200,
    stepsize=1e-4,
    batch_size=256,
    exchange="all_particles",
    shard_data=True,
    seed=0,
    checkpoint_every=0,
    checkpoint_dir=None,
    resume=False,
    log_every=0,
    metrics_path=None,
    profile_dir=None,
    phi_impl="auto",
    bandwidth="1.0",
    exchange_every=1,
):
    """Train; returns (final_particles, metrics dict).

    ``checkpoint_every > 0`` saves sampler state every K steps under
    ``checkpoint_dir`` (utils/checkpoint.py); ``resume=True`` restores the
    latest checkpoint there and continues the exact trajectory (sharded path
    only — the single-process path is one fused scan).  ``checkpoint_dir``
    defaults to ``<results dir>-ckpt``, which encodes every config knob, so
    different configurations never share checkpoints.

    ``log_every > 0`` writes per-step JSONL scalars (utils/metrics.py) to
    ``metrics_path`` (or stdout when None); ``profile_dir`` wraps the loop in
    a ``jax.profiler`` trace.  Sharded path only — the single-process path is
    one fused scan with no per-step host hook.
    """
    import jax
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import ensemble_test_accuracy, make_logreg_split
    from dist_svgd_tpu.utils.datasets import load_covertype
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    # φ policy (see resolve_phi_impl): idempotent here for programmatic
    # callers; the CLI already resolved before deriving dir names, so the
    # default checkpoint_dir below is keyed by the *resolved* backend and a
    # bf16x3 run can never silently resume an exact-f32 checkpoint
    phi_impl = resolve_phi_impl(phi_impl, batch_size, nparticles, nproc)

    x, t = load_covertype(nrows, seed=0)
    n_test = max(nrows // 10, 1)
    x_train, t_train = jnp.asarray(x[:-n_test]), jnp.asarray(t[:-n_test])
    x_test, t_test = x[-n_test:], t[-n_test:]
    d = 1 + x.shape[1]

    # likelihood-only logp + separate prior: with minibatching only the data
    # term should carry the N/B scale (see Sampler/make_shard_step docstrings)
    likelihood, prior = make_logreg_split()
    # shared CLI bandwidth -> kernel mapping (at d=55 the reference's h=1
    # collapses every off-diagonal kernel value the same way it does at the
    # BNN's d=753 -- docs/notes.md)
    from bnn import resolve_bandwidth_kernel

    kernel = resolve_bandwidth_kernel(bandwidth)

    n_used = (nparticles // nproc) * nproc
    particles = init_particles_per_shard(seed, n_used, d, nproc)
    # 0 disables minibatching; clamp to the per-shard row count (as bnn.py)
    rows_per_shard = x_train.shape[0] // nproc
    batch = min(batch_size, rows_per_shard) if batch_size else None

    start = 0  # resumed-from step (sharded path may overwrite)

    def _finish(final, wall, niter, start):
        acc = float(ensemble_test_accuracy(
            final, jnp.asarray(x_test), jnp.asarray(t_test)
        ))
        metrics = {
            "dataset": "covertype",
            "nrows": nrows,
            "nproc": nproc,
            "nparticles": n_used,
            "niter": niter,
            "stepsize": stepsize,
            "batch_size": batch,
            "exchange": exchange,
            "shard_data": shard_data,
            "phi_impl": phi_impl,
            "bandwidth": bandwidth,
            "exchange_every": exchange_every,
            "test_acc": acc,
            "wall_s": round(wall, 3),
            # the sharded paths pre-compile and reset the clock; the
            # nproc==1 path times one fused run including its XLA compile —
            # this flag keeps cross-mode wall_s comparisons honest
            "compile_excluded": nproc > 1,
            # throughput counts only the steps *this* process ran (resume
            # skips the first `start` steps, so n_used*niter/wall would
            # overstate it)
            "steps_run": niter - start,
            "resumed_from": start,
            "updates_per_sec": round(n_used * max(niter - start, 0) / wall, 1)
            if niter > start else 0.0,
        }
        return np.asarray(final), metrics

    if exchange_every > 1:
        if nproc == 1:
            raise ValueError(
                "--exchange-every > 1 is a distributed exchange cadence; "
                "it requires --nproc > 1"
            )
        if checkpoint_every or resume or log_every or profile_dir:
            raise ValueError(
                "--exchange-every > 1 runs as one scanned dispatch; "
                "checkpointing/logging/profiling cadences are "
                "unsupported with it"
            )
        if niter % exchange_every:
            raise ValueError(
                f"--niter ({niter}) must be a multiple of "
                f"--exchange-every ({exchange_every})"
            )
    t0 = time.perf_counter()
    if nproc == 1:
        sampler = dt.Sampler(
            d, likelihood, kernel=kernel, data=(x_train, t_train),
            batch_size=batch, log_prior=prior, phi_impl=phi_impl,
        )
        final, _ = sampler.run(
            n_used, niter, stepsize, seed=seed, record=False,
            initial_particles=particles,
        )
    else:
        sampler = dt.DistSampler(
            nproc,
            likelihood,
            kernel,
            particles,
            data=(x_train, t_train),
            exchange_particles=exchange in ("all_particles", "all_scores"),
            exchange_scores=exchange == "all_scores",
            include_wasserstein=False,
            shard_data=shard_data,
            batch_size=batch,
            log_prior=prior,
            phi_impl=phi_impl,
            exchange_every=exchange_every,
            seed=seed,
        )
        if exchange_every > 1:
            # the lagged macro amortises one gather over exchange_every
            # steps and is driven exclusively through run_steps, so the
            # per-step event schedule below (make_step at log/ckpt points)
            # does not apply -- run the whole trajectory as one dispatch
            # (argument validation happened before data load, top of run())
            state0 = sampler.state_dict()
            jax.block_until_ready(sampler.run_steps(niter, stepsize))  # compile
            sampler.load_state_dict(state0)
            t0 = time.perf_counter()
            sampler.run_steps(niter, stepsize)
            final = jax.block_until_ready(sampler.particles)
            wall = time.perf_counter() - t0
            return _finish(final, wall, niter, 0)
        mgr = None
        if checkpoint_every or resume:
            from dist_svgd_tpu.utils.checkpoint import CheckpointManager

            if checkpoint_dir is None:
                checkpoint_dir = get_results_dir(
                    nrows, nproc, nparticles, niter, stepsize, batch_size,
                    exchange, shard_data, seed, phi_impl, bandwidth,
                ) + "-ckpt"
            # every=0 with resume means restore-only (no new checkpoints)
            mgr = CheckpointManager(checkpoint_dir, every=checkpoint_every or max(niter, 1))
            if resume:
                state = mgr.restore_latest()
                if state is not None:
                    sampler.load_state_dict(state)
                    start = int(state["t"])
            else:
                mgr.clear()  # a previous run's step dirs would poison retention/resume
        from dist_svgd_tpu.utils.metrics import (
            JsonlLogger,
            StepTimer,
            particle_stats,
            profiler_trace,
        )

        def next_after(i, every):
            """First multiple of ``every`` strictly past step index ``i``."""
            return (i // every + 1) * every if every else niter

        def schedule(i):
            """The loop's dispatch decomposition, as data: everything up to
            the next log/checkpoint event is batched into scanned
            ``('chunk', k)`` dispatches; the event step itself is an eager
            ``('event', i)`` so `prev` (the pre-step snapshot particle_stats
            drifts against) keeps its exact per-step meaning.  Chunks are
            powers of two: ``run_steps`` compiles one scan program per
            distinct length, so coprime cadences (e.g. --log-every 10
            --checkpoint-every 7) would otherwise compile a fresh
            multi-second scan for every gap length; this bounds it at
            log2(niter) programs total.  Single source of truth for both the
            pre-compile warm-up and the timed loop."""
            while i < niter:
                event = min(niter, next_after(i, log_every),
                            next_after(i, checkpoint_every))
                gap = event - i - 1
                while gap > 0:
                    chunk = 1 << (gap.bit_length() - 1)
                    yield ("chunk", chunk)
                    i += chunk
                    gap -= chunk
                yield ("event", i)
                i += 1

        # Pre-compile every program the schedule will use (each distinct
        # chunk length, plus the eager event step), so no multi-second XLA
        # compile lands inside a timed lap; then restore the pre-warm-up
        # state and start the clock fresh.
        needed = {k for kind, k in schedule(start) if kind == "chunk"}
        if start < niter:
            state0 = sampler.state_dict()
            for k in sorted(needed):
                sampler.run_steps(k, stepsize)
            sampler.make_step(stepsize)
            sampler.load_state_dict(state0)

        t0 = time.perf_counter()  # exclude setup + warm-up from metrics wall
        timer = StepTimer()
        last_logged = start  # first lap after a resume may span < log_every steps
        with JsonlLogger(
            path=metrics_path,
            stream=None if metrics_path or not log_every else sys.stdout,
        ) as logger, profiler_trace(profile_dir):
            for kind, val in schedule(start):
                if kind == "chunk":
                    sampler.run_steps(val, stepsize)
                    continue
                i = val
                log_now = log_every and (i + 1) % log_every == 0
                prev = sampler.particles if log_now else None
                out = sampler.make_step(stepsize)
                i += 1
                if log_now:
                    lap = timer.mark(out)
                    steps_in_lap = i - last_logged
                    last_logged = i
                    logger.log(
                        step=i,
                        wall_s=round(lap, 4),
                        updates_per_sec=round(n_used * steps_in_lap / lap, 1),
                        **particle_stats(out, prev),
                    )
                if checkpoint_every and mgr.should_save(i):
                    mgr.save(i, sampler.state_dict())
        final = sampler.particles
    final = jax.block_until_ready(final)
    wall = time.perf_counter() - t0
    return _finish(final, wall, niter, start)


@click.command()
@click.option("--nrows", type=int, default=50_000)
@click.option("--nproc", type=click.IntRange(1, 32), default=8,
              help="number of shards (the reference's world size)")
@click.option("--nparticles", type=int, default=10_000)
@click.option("--niter", type=int, default=200)
@click.option("--stepsize", type=float, default=1e-4)
@click.option("--batch-size", type=int, default=256,
              help="per-shard per-step minibatch rows for the stochastic score")
@click.option("--exchange", type=click.Choice(["all_particles", "all_scores"]),
              default="all_particles")
@click.option("--shard-data/--replicate-data", default=True)
@click.option("--seed", type=int, default=0)
@click.option("--checkpoint-every", type=int, default=0,
              help="save sampler state every K steps (0 = off; sharded path only)")
@click.option("--resume/--no-resume", default=False,
              help="restore the latest checkpoint and continue")
@click.option("--log-every", type=int, default=0,
              help="write per-step JSONL metrics every K steps (0 = off)")
@click.option("--profile-dir", type=str, default=None,
              help="jax.profiler trace output dir (TensorBoard-readable)")
@click.option("--backend", type=click.Choice(["auto", "tpu", "cpu"]), default="auto")
@click.option("--phi-impl", type=click.Choice(["auto", "xla", "pallas", "pallas_bf16"]),
              default="auto",
              help="phi backend (ops/pallas_svgd.py:resolve_phi_fn). THIS "
                   "DRIVER's 'auto' resolves to pallas_bf16 on TPU when "
                   "minibatching (stochastic-score noise ~40x the bf16x3 "
                   "phi error; measured 1.53x — docs/notes.md); pass --phi-"
                   "impl xla/pallas for the exact-f32 paths")
@click.option("--bandwidth", default="1.0",
              help="RBF bandwidth: a float (reference default 1.0), 'median' "
                   "(per-run heuristic), or 'median_step' (re-resolved from "
                   "the current particles every step, inside the scan)")
@click.option("--exchange-every", type=click.IntRange(1), default=1,
              help="gather cadence T: T > 1 = lagged exchange (one all-gather "
                   "per T steps, stale interactions with the live own block "
                   "patched in; all_particles only, --nproc > 1, --niter a "
                   "multiple of T, runs as one dispatch -- logging/"
                   "checkpointing/profiling cadences are unsupported)")
def cli(nrows, nproc, nparticles, niter, stepsize, batch_size, exchange,
        shard_data, seed, checkpoint_every, resume, log_every, profile_dir,
        backend, phi_impl, bandwidth, exchange_every):
    select_backend(backend)
    # resolve BEFORE dir-name derivation: results and checkpoint dirs are
    # keyed by the effective backend (resolve_phi_impl docstring)
    phi_impl = resolve_phi_impl(phi_impl, batch_size, nparticles, nproc)
    results_dir = get_results_dir(
        nrows, nproc, nparticles, niter, stepsize, batch_size, exchange,
        shard_data, seed, phi_impl, bandwidth, exchange_every,
    )
    ckpt_dir = results_dir + "-ckpt" if checkpoint_every else None
    final, metrics = run(
        nrows, nproc, nparticles, niter, stepsize, batch_size, exchange,
        shard_data, seed, checkpoint_every, ckpt_dir, resume,
        log_every, os.path.join(results_dir, "metrics.jsonl") if log_every else None,
        profile_dir, phi_impl, bandwidth, exchange_every,
    )
    np.save(os.path.join(results_dir, "particles.npy"), final)
    with open(os.path.join(results_dir, "metrics.json"), "w") as fh:
        json.dump(metrics, fh, indent=2)
    print(json.dumps(metrics))


if __name__ == "__main__":
    cli()
