"""Benchmark suite over BASELINE.json's five configs (SURVEY.md §7.2 item 7).

``bench.py`` at the repo root reports the single headline metric; this suite
measures **every** BASELINE.json config plus a world-size scaling table that
mirrors the shape of the reference's only published timing table
(reference ``notes.md:120-135``, reproduced in ``BASELINE.md``):

1. Bayesian logistic regression, 100 particles, single process.
2. 1-D Gaussian-mixture posterior, 256 particles.
3. Bayesian logistic regression, 10k particles, sharded over 8 shards.
4. Bayesian logistic regression, 10k particles, Covertype, minibatched
   scores, data sharded over the mesh.
5. 2-layer Bayesian NN regression (UCI), 500 particles, weight-vector SVGD.

Each config prints one JSON line ``{"config": ..., "updates_per_sec": ...}``;
``--table`` additionally prints markdown tables.  On a host with fewer
devices than shards the sharded configs run the identical SPMD program under
vmap emulation (one device) and are labelled ``"emulated": true`` — honest
single-chip numbers, not a multi-chip claim.

Timing protocol: compile/warm up with the same shapes first, then time
execution only, fenced with ``block_until_ready`` (SURVEY.md §5 tracing row).
"""

import json
import time

import click
import numpy as np

from paths import DATA_DIR  # noqa: F401  (bootstraps sys.path)

from dist_svgd_tpu.utils.platform import select_backend

from bench import (  # single sources of truth
    REFERENCE_BEST_UPDATES_PER_SEC,
    _fence,
    _timed_chain,
)


def _platform():
    import jax

    return jax.devices()[0].platform


def _emulated(num_shards: int) -> bool:
    import jax

    return len(jax.devices()) < num_shards


def _time_sampler_run(sampler, n, iters, step_size, initial_particles=None):
    """Warm up (compiles the scan for this iteration count), then time with
    bench.py's protocol: state-chained reps (each run continues from the
    previous output) under one trailing scalar fetch —
    ``block_until_ready`` through the axon tunnel is not a reliable fence."""
    state = {"out": initial_particles}

    def run_one():
        state["out"] = sampler.run(
            n, iters, step_size, seed=0, record=False,
            initial_particles=state["out"],
        )[0]
        return state["out"]

    _fence(run_one())
    return _timed_chain(run_one)


def _time_dist_steps(sampler, iters, step_size, **run_kwargs):
    """Time the scanned K-step path (one dispatch — how the framework is
    meant to be driven for throughput; ``DistSampler.run_steps``), bench.py
    timing protocol (``run_steps`` is stateful, so reps chain naturally).
    ``run_kwargs`` pass through to ``run_steps`` (e.g. the W2 weight ``h``)."""
    _fence(sampler.run_steps(iters, step_size, **run_kwargs))  # compile, untimed
    return _timed_chain(lambda: sampler.run_steps(iters, step_size, **run_kwargs))


def _result(config, n, iters, wall, **extra):
    res = {
        "config": config,
        "n_particles": n,
        "n_iters": iters,
        "wall_s": round(wall, 4),
        "updates_per_sec": round(n * iters / wall, 1),
        "vs_reference_best": round(n * iters / wall / REFERENCE_BEST_UPDATES_PER_SEC, 2),
        "platform": _platform(),
    }
    res.update(extra)
    return res


# --------------------------------------------------------------------- #
# The five BASELINE.json configs


def bench_logreg_single(iters):
    """Config 1: BayesLR banana, 100 particles, single process."""
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import make_logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark

    fold = load_benchmark("banana", 42)
    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))
    d = 1 + fold.x_train.shape[1]
    sampler = dt.Sampler(d, logp)
    wall = _time_sampler_run(sampler, 100, iters, 3e-3)
    return _result("1:logreg-single-100p", 100, iters, wall, dataset="banana")


def bench_gmm(iters):
    """Config 2: 1-D GMM posterior, 256 particles."""
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp

    sampler = dt.Sampler(1, gmm_logp)
    wall = _time_sampler_run(sampler, 256, iters, 1.0)
    return _result("2:gmm-256p", 256, iters, wall)


def bench_logreg_sharded(iters, num_shards=8, n_particles=10_000):
    """Config 3: BayesLR, 10k particles sharded over 8 shards
    (``all_particles`` exchange — the BASELINE.json north-star mode)."""
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    fold = load_benchmark("banana", 42)
    data = (jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1)))
    d = 1 + fold.x_train.shape[1]
    particles = init_particles_per_shard(0, n_particles, d, num_shards)
    sampler = dt.DistSampler(
        num_shards, logreg_logp, None, particles, data=data,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False,
    )
    wall = _time_dist_steps(sampler, iters, 3e-3)
    return _result(
        "3:logreg-sharded-10kp", sampler.num_particles, iters, wall,
        num_shards=num_shards, emulated=_emulated(num_shards), dataset="banana",
    )


def bench_covertype_minibatch(iters, num_shards=8, n_particles=10_000,
                              n_rows=50_000, batch_size=256,
                              acceptance=False):
    """Config 4: BayesLR, 10k particles, Covertype, minibatched scores,
    data sharded (not replicated) over the mesh.

    ``acceptance=True`` additionally runs the sklearn-baseline acceptance
    (round-4 protocol, mirroring the reference's LogisticRegression line,
    /root/reference/experiments/logreg_plots.py:37-39): the target is the
    sklearn accuracy on the driver's exact train/test split − 0.01, and the
    row reports steps-to-target at the driver's stepsize with the
    ``median_step`` kernel (the configuration whose accuracy the covertype
    driver records as its best).  A regression that trades accuracy for
    updates/sec turns ``steps_to_target`` into ``null`` — a red row.
    """
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import (
        ensemble_test_accuracy,
        logreg_likelihood,
        logreg_prior,
        make_logreg_split,
    )
    from dist_svgd_tpu.utils.datasets import load_covertype
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    x, t = load_covertype(n_rows)
    data = (jnp.asarray(x), jnp.asarray(t))
    d = 1 + x.shape[1]
    particles = init_particles_per_shard(0, n_particles, d, num_shards)
    # the covertype driver's phi policy, shared (experiments/covertype.py:
    # resolve_phi_impl): bf16x3 only when minibatched + TPU + Gram-bound
    from covertype import resolve_phi_impl

    phi_impl = resolve_phi_impl("auto", batch_size, n_particles, num_shards)
    sampler = dt.DistSampler(
        num_shards, logreg_likelihood, None, particles, data=data,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False, shard_data=True,
        batch_size=batch_size, log_prior=logreg_prior, phi_impl=phi_impl,
    )
    wall = _time_dist_steps(sampler, iters, 1e-4)
    extra = {}
    if acceptance:
        # same split as experiments/covertype.py:run (last tenth is test)
        n_test = max(n_rows // 10, 1)
        from sklearn.linear_model import LogisticRegression

        sk = LogisticRegression(max_iter=200).fit(x[:-n_test], t[:-n_test])
        baseline = float(sk.score(x[-n_test:], t[-n_test:]))
        target = baseline - 0.01
        lik, prior = make_logreg_split()
        acc_sampler = dt.DistSampler(
            num_shards, lik, "median_step",
            init_particles_per_shard(0, n_particles, d, num_shards),
            data=(jnp.asarray(x[:-n_test]), jnp.asarray(t[:-n_test])),
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=False, shard_data=True,
            batch_size=batch_size, log_prior=prior, phi_impl=phi_impl,
        )
        xte, tte = jnp.asarray(x[-n_test:]), jnp.asarray(t[-n_test:])
        eval_every, cap, steps, acc = 100, 1500, 0, 0.0
        reached = None
        while steps < cap:
            acc_sampler.run_steps(eval_every, 1e-4)
            steps += eval_every
            acc = float(ensemble_test_accuracy(acc_sampler.particles, xte, tte))
            if acc >= target:
                reached = steps
                break
        extra = {
            "sklearn_acc": round(baseline, 4),
            "target_acc": round(target, 4),
            "steps_to_target": reached,
            "final_acc": round(acc, 4),
            "acceptance_kernel": "median_step",
        }
    return _result(
        "4:covertype-minibatch-10kp", sampler.num_particles, iters, wall,
        num_shards=num_shards, emulated=_emulated(num_shards),
        n_rows=n_rows, batch_size=batch_size, phi_impl=phi_impl, **extra,
    )


def bench_bnn(iters, n_particles=500, dataset="boston", batch_size=100,
              acceptance=False):
    """Config 5: 2-layer Bayesian NN regression (UCI), 500 particles.

    ``acceptance=True`` adds the sklearn-baseline acceptance (round-4
    protocol): the target is the ``BayesianRidge`` test RMSE on the same
    split — the Bayesian *linear* baseline, the regression analog of the
    reference's LogisticRegression acceptance line — and the row reports
    the first eval step at which the ensemble posterior-predictive RMSE
    beats it (the 2-layer net must outperform a linear model on this
    nonlinear target or something is deeply wrong).  A
    ``GradientBoostingRegressor`` RMSE is reported as stretch context.
    """
    import jax

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models import bnn
    from dist_svgd_tpu.utils.datasets import load_uci_regression
    from dist_svgd_tpu.utils.rng import as_key

    split = load_uci_regression(dataset, 0)
    n_features = split.x_train.shape[1]
    likelihood, prior = bnn.make_bnn_split(n_features)
    d = bnn.num_params(n_features)
    init = bnn.init_particles(as_key(0), n_particles, n_features)
    sampler = dt.Sampler(
        d, likelihood, data=(split.x_train, split.y_train),
        batch_size=min(batch_size, split.x_train.shape[0]), log_prior=prior,
    )
    wall = _time_sampler_run(sampler, n_particles, iters, 1e-3,
                             initial_particles=init)
    extra = {}
    if acceptance:
        import numpy as np
        from sklearn.ensemble import GradientBoostingRegressor
        from sklearn.linear_model import BayesianRidge

        def sk_rmse(model):
            pred = model.fit(split.x_train, split.y_train).predict(split.x_test)
            pred = pred * split.y_std + split.y_mean
            return float(np.sqrt(np.mean((pred - split.y_test) ** 2)))

        target = sk_rmse(BayesianRidge())
        gbr = sk_rmse(GradientBoostingRegressor(random_state=0))
        acc_sampler = dt.Sampler(
            d, likelihood, data=(split.x_train, split.y_train),
            batch_size=min(batch_size, split.x_train.shape[0]),
            log_prior=prior, kernel="median_step",
        )
        parts = bnn.init_particles(as_key(1), n_particles, n_features)
        eval_every, cap, steps, rmse = 50, 2000, 0, float("inf")
        reached = None
        while steps < cap:
            # seed=steps: each chunk must draw FRESH minibatch keys — the
            # default fixed seed would replay the same eval_every-draw noise
            # stream every chunk instead of a real stochastic trajectory
            parts, _ = acc_sampler.run(
                n_particles, eval_every, 1e-3, record=False,
                initial_particles=parts, seed=steps,
            )
            steps += eval_every
            rmse = float(bnn.ensemble_rmse(
                parts, split.x_test, split.y_test, n_features,
                y_mean=split.y_mean, y_std=split.y_std,
            ))
            if rmse <= target:
                reached = steps
                break
        extra = {
            "bayesridge_rmse": round(target, 4),
            "gbr_rmse_context": round(gbr, 4),
            "steps_to_target": reached,
            "final_rmse": round(rmse, 4),
            "acceptance_kernel": "median_step",
        }
    return _result(
        "5:bnn-uci-500p", n_particles, iters, wall,
        dataset=dataset, d=d, batch_size=batch_size, **extra,
    )


# --------------------------------------------------------------------- #
# World-size scaling table (the reference table's shape, notes.md:128-132)


def scaling_table_10k(iters, world_sizes=(1, 2, 4, 8), n_particles=10_000,
                      wasserstein=False):
    """Compute-bound scaling curve: banana logreg at 10k particles in
    ``partitions`` mode, world sizes 1/2/4/8 (``wasserstein=True`` adds the
    scanned Sinkhorn W2 term at h=10 — :func:`scaling_table_w2`).

    This is the config where shards genuinely help even on one chip: the
    ``partitions`` interaction set is the owned block (n/S particles), so the
    per-step pair count is n²/S — the same mechanism behind the reference's
    superlinear table (its per-pair inner loop shrank with S,
    notes.md:120-135).  The ``all_*`` modes are work-conserving under
    emulation (each shard still interacts with all n particles), hence flat;
    on real multi-chip hardware they scale by dividing that constant total
    work across chips."""
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    fold = load_benchmark("banana", 42)
    data = (jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1)))
    d = 1 + fold.x_train.shape[1]
    rows = []
    for ws in world_sizes:
        particles = init_particles_per_shard(0, n_particles, d, ws)
        sampler = dt.DistSampler(
            ws, logreg_logp, None, particles, data=data,
            exchange_particles=False, exchange_scores=False,
            include_wasserstein=wasserstein, wasserstein_solver="sinkhorn",
        )
        wall = _time_dist_steps(sampler, iters, 3e-3,
                                h=10.0 if wasserstein else 1.0)
        label = "scaling10k-w2" if wasserstein else "scaling10k"
        rows.append(_result(
            f"{label}:ws{ws}", sampler.num_particles, iters, wall,
            num_shards=ws, emulated=_emulated(ws), exchange="partitions",
            **({"wasserstein": True, "w2_pairing": sampler.w2_pairing}
               if wasserstein else {}),
        ))
    return rows


# --------------------------------------------------------------------- #
# Chunked-vs-monolithic A/B (bounded multi-dispatch stepping)


def bench_chunked_ab(iters, num_shards=8, n_particles=10_000):
    """A/B of the bounded multi-dispatch executor against the monolithic
    scan at a size where BOTH clear the watchdog — this measures the pure
    *chunking overhead* (per-dispatch relay cost × dispatches/step), the
    price the 2M+ rows pay to exist at all (tools/large_n.py measures those;
    docs/notes.md large-n table).

    Config: banana logreg, ring ``all_particles`` exchange (the
    implementation with an intra-step hop seam), ``hops_per_dispatch=1`` —
    the finest chunking, hence the worst-case overhead.  Emits one row per
    execution; the chunked row records ``dispatches_per_step`` and
    ``max_dispatch_wall_s`` from ``DistSampler.last_run_stats``."""
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    fold = load_benchmark("banana", 42)
    data = (jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1)))
    d = 1 + fold.x_train.shape[1]

    def build():
        return dt.DistSampler(
            num_shards, logreg_logp, None,
            init_particles_per_shard(0, n_particles, d, num_shards),
            data=data, exchange_particles=True, exchange_scores=False,
            include_wasserstein=False, exchange_impl="ring",
        )

    rows = []
    for label, kwargs in (
        ("monolithic", {}),
        ("chunked", dict(hops_per_dispatch=1)),
    ):
        sampler = build()
        # the timed runs never fence per dispatch (that would serialise the
        # chained dispatches and bill the relay round-trips to the chunked
        # leg); per-dispatch walls come from one extra fenced run below
        wall = _time_dist_steps(sampler, iters, 3e-3, **kwargs)
        stats = sampler.last_run_stats or {}
        extra = {"execution": label, "exchange_impl": "ring"}
        if label == "chunked":
            sampler.run_steps(iters, 3e-3, hops_per_dispatch=1,
                              time_dispatches=True)
            stats = sampler.last_run_stats or {}
            extra.update(
                dispatches_per_step=stats.get("dispatches_per_step"),
                max_dispatch_wall_s=stats.get("max_dispatch_wall_s"),
                hops_per_dispatch=1,
            )
        rows.append(_result(
            f"chunked-ab:{label}", sampler.num_particles, iters, wall,
            num_shards=num_shards, emulated=_emulated(num_shards), **extra,
        ))
    return rows


def scaling_table_w2(iters, world_sizes=(1, 2, 4, 8), n_particles=10_000):
    """World-size scaling of the **Wasserstein step itself** (round 5):
    the 10k-particle ``partitions`` table with the scanned Sinkhorn W2
    term on (h=10, the reference driver's weight).

    Under the block-(b+1) pairing both the φ interaction set AND each W2
    solve are block-sized, so per-step work is n²/S for *both* terms —
    the whole step scales with S even on one chip under vmap emulation,
    unlike the work-conserving ``all_*`` φ.  This is the single-chip
    demonstration of the mechanism that lets the 1M-particle W2 rows ride
    S chips: per-device work (and memory) set by n/S, not n.  Measured
    21.19/4.57/2.75/1.98 ms/step at ws 1/2/4/8 (docs/notes.md round-5)."""
    return scaling_table_10k(iters, world_sizes, n_particles,
                             wasserstein=True)


def scaling_table(iters, world_sizes=(1, 2, 4, 8), n_particles=50):
    """Banana logreg, 50 particles — the reference's exact headline workload —
    at world sizes 1/2/4/8, mirroring reference notes.md:128-132.  The
    reference's wall-clock at this config: 2007.11 / 538.59 / 157.17 /
    59.353 s for 500 iterations."""
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    fold = load_benchmark("banana", 42)
    data = (jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1)))
    d = 1 + fold.x_train.shape[1]
    rows = []
    for ws in world_sizes:
        # reference drop policy: 50 particles on 4/8 shards truncates
        # (dsvgd/distsampler.py:42-45)
        n_used = (n_particles // ws) * ws
        particles = init_particles_per_shard(0, n_used, d, ws)
        sampler = dt.DistSampler(
            ws, logreg_logp, None, particles, data=data,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=False,
        )
        wall = _time_dist_steps(sampler, iters, 3e-3)
        rows.append(_result(
            f"scaling:ws{ws}", sampler.num_particles, iters, wall,
            num_shards=ws, emulated=_emulated(ws),
        ))
    return rows


# --------------------------------------------------------------------- #


def _markdown(results, scaling):
    lines = [
        "| config | n | iters | wall (s) | updates/sec | × ref best (421/s) |",
        "|---|---|---|---|---|---|",
    ]
    for r in results:
        lines.append(
            f"| {r['config']} | {r['n_particles']} | {r['n_iters']} "
            f"| {r['wall_s']} | {r['updates_per_sec']} | {r['vs_reference_best']} |"
        )
    acc = [r for r in results if "steps_to_target" in r]
    if acc:
        lines += [
            "",
            "| config | baseline target | steps-to-target | final |",
            "|---|---|---|---|",
        ]
        for r in acc:
            tgt = r.get("target_acc", r.get("bayesridge_rmse"))
            fin = r.get("final_acc", r.get("final_rmse"))
            reached = r["steps_to_target"]
            lines.append(
                f"| {r['config']} | {tgt} "
                f"| {'UNREACHED' if reached is None else reached} | {fin} |"
            )
    if scaling:
        lines += [
            "",
            "| world size | wall (s) | updates/sec | reference wall (s) |",
            "|---|---|---|---|",
        ]
        ref = {1: 2007.11, 2: 538.59, 4: 157.17, 8: 59.353}
        for r in scaling:
            ws = r["num_shards"]
            lines.append(
                f"| {ws} | {r['wall_s']} | {r['updates_per_sec']} "
                f"| {ref.get(ws, '—')} |"
            )
    return "\n".join(lines)


_CONFIGS = {
    "1": bench_logreg_single,
    "2": bench_gmm,
    "3": bench_logreg_sharded,
    "4": bench_covertype_minibatch,
    "5": bench_bnn,
}


@click.command()
@click.option("--configs", default="1,2,3,4,5",
              help="comma-separated subset of {1..5}, or 'all'")
@click.option("--iters", default=100, help="timed iterations per config")
@click.option("--scaling/--no-scaling", default=True,
              help="also run the world-size scaling table")
@click.option("--scaling-iters", default=500,
              help="iterations for the scaling table (reference used 500)")
@click.option("--scaling-10k/--no-scaling-10k", default=False,
              help="also run the compute-bound 10k-particle partitions-mode "
                   "scaling table (docs/notes.md)")
@click.option("--scaling-w2/--no-scaling-w2", default=False,
              help="also run the 10k-particle partitions+W2 scaling table "
                   "(the W2 step's own n²/S mechanism; docs/notes.md)")
@click.option("--chunked-ab/--no-chunked-ab", default=False,
              help="also run the bounded multi-dispatch chunked-vs-"
                   "monolithic A/B (ring exchange, hops_per_dispatch=1 — "
                   "the chunking-overhead measurement; docs/notes.md)")
@click.option("--table", is_flag=True, help="print markdown tables at the end")
@click.option("--backend", default="auto",
              type=click.Choice(["auto", "tpu", "cpu"]))
@click.option("--acceptance", default="auto",
              type=click.Choice(["auto", "on", "off"]),
              help="sklearn-baseline acceptance (target + steps-to-target) "
                   "for configs 4/5; 'auto' runs it on TPU only (the CPU "
                   "fallback is a smoke run, not an acceptance run)")
def cli(configs, iters, scaling, scaling_iters, scaling_10k, scaling_w2,
        chunked_ab, table, backend, acceptance):
    select_backend(backend)
    acc_on = acceptance == "on" or (
        acceptance == "auto" and _platform() == "tpu"
    )
    wanted = list(_CONFIGS) if configs == "all" else configs.split(",")
    results = []
    for key in wanted:
        key = key.strip()
        fn = _CONFIGS.get(key)
        if fn is None:
            raise click.BadParameter(f"unknown config {key!r}")
        res = fn(iters, acceptance=acc_on) if key in ("4", "5") else fn(iters)
        results.append(res)
        print(json.dumps(res), flush=True)
    srows = []
    if scaling:
        srows = scaling_table(scaling_iters)
        for r in srows:
            print(json.dumps(r), flush=True)
    if scaling_10k:
        for r in scaling_table_10k(iters):
            print(json.dumps(r), flush=True)
    if scaling_w2:
        for r in scaling_table_w2(iters):
            print(json.dumps(r), flush=True)
    if chunked_ab:
        for r in bench_chunked_ab(iters):
            print(json.dumps(r), flush=True)
    if table:
        print()
        print(_markdown(results, srows))


if __name__ == "__main__":
    cli()
