"""Streaming covertype: continuous-ingest training with hot-serving.

The covertype workload replayed as a timestamped stream
(``streaming.CovertypeReplayStream``: one ``--batch-rows`` slice per
``--period`` seconds of event time) into a ``StreamingSupervisor`` —
each segment ingests due batches into the fixed-shape ``RowRing``
corpus, drift-checks the posterior against the new data (after a
calibrate-then-arm warm-up), trains incrementally, checkpoints, and
publishes to a live ``PredictiveEngine`` through a
``CheckpointHotReloader``.  An injected ``DriftAt`` label flip
(``--drift-at``) demonstrates the KSD guard escalating a segment to a
full re-fit instead of serving the stale posterior.

Event time runs on an injected manual clock (one segment per period),
so 'hours' of stream replay in seconds; freshness lag and the streaming
SLOs are evaluated on that event timeline.  Prints one JSON line.
"""

import json
import shutil
import tempfile

import click
import numpy as np

from paths import RESULTS_DIR  # noqa: F401  (bootstraps sys.path)

from dist_svgd_tpu.utils.platform import select_backend


class ManualClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@click.command()
@click.option("--nrows", type=int, default=6_000)
@click.option("--nparticles", type=int, default=128)
@click.option("--batch-rows", type=int, default=256,
              help="stream rows per event-time period")
@click.option("--corpus-rows", type=int, default=1024,
              help="RowRing capacity (the sliding training window)")
@click.option("--batch-size", type=int, default=128,
              help="minibatch rows per SVGD step")
@click.option("--period", type=float, default=60.0,
              help="event-time seconds between stream batches")
@click.option("--steps-per-segment", type=int, default=10)
@click.option("--refit-factor", type=int, default=4)
@click.option("--segments", type=int, default=12)
@click.option("--warmup-segments", type=int, default=14,
              help="segments training + calibrating the drift baseline "
                   "before the guard is armed")
@click.option("--ksd-factor", type=float, default=2.0)
@click.option("--stepsize", type=float, default=0.05)
@click.option("--drift-at", type=int, default=2,
              help="ordinal (relative to arming) whose labels start "
                   "flipping; -1 disables the injected drift")
@click.option("--drift-frac", type=float, default=1.0)
@click.option("--max-lag-s", type=float, default=600.0,
              help="freshness SLO threshold on the event timeline")
@click.option("--seed", type=int, default=0)
@click.option("--root", default=None,
              help="checkpoint root (default: a temp dir, removed on exit)")
@click.option("--backend", type=click.Choice(["auto", "tpu", "cpu"]),
              default="auto")
def cli(nrows, nparticles, batch_rows, corpus_rows, batch_size, period,
        steps_per_segment, refit_factor, segments, warmup_segments,
        ksd_factor, stepsize, drift_at, drift_frac, max_lag_s, seed, root,
        backend):
    select_backend(backend)
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import make_logreg_split
    from dist_svgd_tpu.resilience import DriftAt, GuardConfig
    from dist_svgd_tpu.serving import CheckpointHotReloader, PredictiveEngine
    from dist_svgd_tpu.streaming import (
        CovertypeReplayStream,
        RowRing,
        StreamBuffer,
        StreamingSupervisor,
    )
    from dist_svgd_tpu.telemetry import MetricsRegistry
    from dist_svgd_tpu.telemetry.diagnostics import (
        DiagnosticsConfig,
        PosteriorDiagnostics,
    )
    from dist_svgd_tpu.telemetry.slo import default_streaming_slos
    from dist_svgd_tpu.utils.datasets import load_covertype
    from dist_svgd_tpu.utils.rng import as_key, init_particles

    # the test slice is the tail of the SAME seeded load the replay
    # stream performs; the segment loop below caps ordinals so the
    # stream never ingests past it — held out by construction
    n_test = max(nrows // 10, 1)
    x_all, t_all = load_covertype(nrows, seed=seed)
    x_test = np.asarray(x_all[nrows - n_test:], np.float32)
    t_test = np.asarray(t_all[nrows - n_test:])
    max_ordinals = (nrows - n_test) // batch_rows

    registry = MetricsRegistry()
    clock = ManualClock(0.0)
    stream = CovertypeReplayStream(
        n_rows=nrows, batch_rows=batch_rows, seed=seed,
        period_s=period, start_time=period)
    buffer = StreamBuffer(stream, capacity=64, registry=registry,
                          clock=clock)
    ring = RowRing(corpus_rows, stream.dim)
    likelihood, prior = make_logreg_split()
    d = stream.dim + 1
    sampler = dt.Sampler(
        d, likelihood, kernel=dt.RBF(1.0),
        data=(np.zeros((corpus_rows, stream.dim), np.float32),
              np.ones((corpus_rows,), np.float64)),
        batch_size=min(batch_size, corpus_rows), log_prior=prior)
    diag = PosteriorDiagnostics(
        DiagnosticsConfig(every_steps=1, row_chunk=512, max_points=512),
        registry=registry)

    cleanup = root is None
    root = root or tempfile.mkdtemp(prefix="streaming_covertype_")
    out = {"nrows": nrows, "nparticles": nparticles,
           "batch_rows": batch_rows, "corpus_rows": corpus_rows,
           "period_s": period, "steps_per_segment": steps_per_segment,
           "root": root}
    try:
        engine = PredictiveEngine(
            "logreg",
            np.asarray(init_particles(as_key(seed), nparticles, d)),
            max_bucket=max(64, n_test), registry=registry)
        reloader = CheckpointHotReloader(engine, root, key="particles")
        sup = StreamingSupervisor(
            sampler, stepsize, buffer=buffer, ring=ring,
            steps_per_segment=steps_per_segment,
            refit_steps=refit_factor * steps_per_segment,
            drift_diagnostics=diag, reloader=reloader,
            checkpoint_dir=root, checkpoint_every=steps_per_segment,
            segment_steps=steps_per_segment, n=nparticles, seed=seed,
            registry=registry, clock=clock, sleep=lambda s: None)

        # warm-up + calibrate-then-arm (tools/freshness_drill.py protocol)
        sup.drift_guard = GuardConfig(max_ksd=float("inf"))
        g_ksd = registry.gauge("svgd_diag_ksd")
        base_ksds = []
        for _ in range(warmup_segments):
            clock.advance(period)
            sup.run_segment_once()
            if g_ksd.has():
                base_ksds.append(float(g_ksd.value()))
        ksd_baseline = max(base_ksds[-4:]) if base_ksds else float("inf")
        sup.drift_guard = GuardConfig(max_ksd=ksd_baseline * ksd_factor)
        if drift_at >= 0:
            stream.faults = (DriftAt(buffer.next_ordinal + drift_at,
                                     kind="label_flip",
                                     magnitude=drift_frac),)
        out["calibration"] = {"ksd_baseline": round(ksd_baseline, 3),
                              "ksd_threshold": round(
                                  ksd_baseline * ksd_factor, 3)}

        for _ in range(segments):
            if buffer.next_ordinal >= max_ordinals:
                break  # stop short of the held-out tail
            clock.advance(period)
            sup.run_segment_once()
        served = engine.predict(x_test)["mean"]
        slo_doc = default_streaming_slos(
            registry, max_lag_s=max_lag_s).evaluate()
        out["stream"] = {
            "segments": int(registry.counter(
                "svgd_stream_segments_total").value()),
            "t": sup.t,
            "ordinals": buffer.next_ordinal,
            "rows_ingested": int(registry.counter(
                "svgd_stream_rows_total").value()),
            "dropped": buffer.dropped,
            "refits": int(registry.counter(
                "svgd_stream_refits_total").value()),
            "watermark": buffer.watermark,
        }
        # after a full label-flip drift the refit tracks the NEW concept,
        # so the served ensemble scores against the flipped labels — both
        # views printed so the adaptation is visible in the evidence line
        pred = np.asarray(served) > 0.5
        out["serve"] = {
            "reloads": engine.stats()["reloads"],
            "ensemble_tag": engine.stats()["ensemble_tag"],
            "served_test_acc": float(np.mean(pred == (t_test > 0))),
            "served_test_acc_flipped_concept": float(
                np.mean(pred == (t_test < 0))),
        }
        out["slo"] = {name: {"status": o["status"],
                             "burn_rate": o["burn_rate"]}
                      for name, o in slo_doc["objectives"].items()}
        out["slo_status"] = slo_doc["status"]
        print(json.dumps(out), flush=True)
    finally:
        if cleanup:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    cli()
