"""Covertype train → checkpoint → serve demo: the full posterior-predictive
serving path on the repo's flagship minibatched workload.

Three stages, one command:

1. **train**: a sharded covertype logreg ensemble via ``covertype.run`` with
   checkpointing on (skipped with ``--no-train`` when the checkpoint dir
   already holds a restorable step);
2. **cold start**: ``PredictiveEngine.from_checkpoint`` on the
   ``CheckpointManager`` root — the newest *loadable* step wins, padding
   buckets pre-traced;
3. **serve**: an in-process :class:`PredictionServer` self-test — concurrent
   mixed-size HTTP requests over held-out rows, served class-probability
   means checked against a direct ``posterior_predictive_prob`` call on the
   restored ensemble — then, with ``--serve``, stays up for external curl
   traffic until interrupted.

Prints one JSON line: test accuracy from the *served* predictions, the
serving metrics snapshot (occupancy, latency split, bucket-cache hit rate),
and the bound URL.
"""

import json
import threading
import urllib.request

import click
import numpy as np

from paths import RESULTS_DIR  # noqa: F401  (bootstraps sys.path)

import covertype
from dist_svgd_tpu.utils.platform import select_backend


@click.command()
@click.option("--nrows", type=int, default=20_000)
@click.option("--nproc", type=click.IntRange(1, 32), default=8)
@click.option("--nparticles", type=int, default=1024)
@click.option("--niter", type=int, default=100)
@click.option("--stepsize", type=float, default=1e-4)
@click.option("--batch-size", type=int, default=256)
@click.option("--seed", type=int, default=0)
@click.option("--train/--no-train", "do_train", default=True,
              help="--no-train serves the existing checkpoint as-is")
@click.option("--checkpoint-dir", default=None,
              help="CheckpointManager root (default: the covertype results "
                   "dir convention + '-ckpt')")
@click.option("--requests", type=int, default=64,
              help="self-test request count (concurrent, mixed sizes)")
@click.option("--max-batch", type=int, default=128)
@click.option("--max-wait-ms", type=float, default=2.0)
@click.option("--port", type=int, default=0,
              help="0 binds an ephemeral port for the self-test")
@click.option("--serve/--no-serve", default=False,
              help="stay up for external traffic after the self-test")
@click.option("--backend", type=click.Choice(["auto", "tpu", "cpu"]), default="auto")
def cli(nrows, nproc, nparticles, niter, stepsize, batch_size, seed, do_train,
        checkpoint_dir, requests, max_batch, max_wait_ms, port, serve, backend):
    select_backend(backend)
    import jax.numpy as jnp

    from dist_svgd_tpu.models.logreg import posterior_predictive_prob
    from dist_svgd_tpu.serving import PredictionServer, PredictiveEngine
    from dist_svgd_tpu.utils.datasets import load_covertype

    if checkpoint_dir is None:
        checkpoint_dir = covertype.get_results_dir(
            nrows, nproc, nparticles, niter, stepsize, batch_size,
            "all_particles", True, seed,
            covertype.resolve_phi_impl("auto", batch_size, nparticles, nproc),
        ) + "-ckpt"
    if do_train:
        # checkpoint_every=niter → exactly one save, at the final step
        covertype.run(
            nrows=nrows, nproc=nproc, nparticles=nparticles, niter=niter,
            stepsize=stepsize, batch_size=batch_size, seed=seed,
            checkpoint_every=niter, checkpoint_dir=checkpoint_dir,
        )

    engine = PredictiveEngine.from_checkpoint(
        checkpoint_dir, "logreg", max_bucket=max_batch
    )
    engine.warmup()

    # the same held-out convention as covertype.run
    x, t = load_covertype(nrows, seed=0)
    n_test = max(nrows // 10, 1)
    x_test, t_test = x[-n_test:].astype(np.float32), t[-n_test:]

    with PredictionServer(
        engine, port=port, max_batch=max_batch, max_wait_ms=max_wait_ms
    ) as srv:
        # self-test: concurrent mixed-size requests covering the test rows
        rng = np.random.default_rng(seed)
        sizes = rng.choice((1, 4, 16), size=requests).tolist()
        slices, cursor = [], 0
        for s in sizes:
            slices.append((cursor, min(cursor + s, len(x_test))))
            cursor = min(cursor + s, len(x_test))
        slices = [(a, b) for a, b in slices if b > a]
        served = np.full(len(x_test), np.nan, np.float64)
        request_errors = []

        def fire(a, b):
            try:
                req = urllib.request.Request(
                    srv.url + "/predict",
                    json.dumps({"inputs": x_test[a:b].tolist()}).encode(),
                    {"Content-Type": "application/json"},
                )
                out = json.loads(urllib.request.urlopen(req, timeout=60).read())
                served[a:b] = out["outputs"]["mean"]
            except Exception as e:  # surfaced below — a quiet thread death
                request_errors.append(f"rows {a}:{b}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=fire, args=ab) for ab in slices]
        for th in threads:
            th.start()
        for th in threads:
            th.join()

        covered = ~np.isnan(served)
        if not covered.any():
            raise SystemExit(json.dumps({
                "error": "every self-test request failed",
                "request_errors": request_errors[:5],
            }))
        direct = np.asarray(jnp.mean(
            posterior_predictive_prob(
                engine.particles, jnp.asarray(x_test[covered])
            ), axis=0,
        ))
        max_dev = float(np.max(np.abs(served[covered] - direct)))
        acc = float(np.mean((served[covered] > 0.5) == (t_test[covered] > 0)))
        print(json.dumps({
            "checkpoint_dir": checkpoint_dir,
            "url": srv.url,
            "rows_served": int(covered.sum()),
            "request_errors": request_errors,
            "served_test_acc": round(acc, 4),
            "served_vs_direct_max_abs_dev": max_dev,
            "metrics": srv.metrics(),
        }), flush=True)
        if serve:
            click.echo(f"serving on {srv.url} — Ctrl-C to drain and exit", err=True)
            try:
                threading.Event().wait()
            except KeyboardInterrupt:
                pass


if __name__ == "__main__":
    cli()
