"""Bounded multi-dispatch chunked stepping (the 2M dispatch-duration
ceiling breaker — VERDICT r05 top_next item 1, docs/notes.md large-n
table): a host-driven chain of bounded dispatches with the partial φ
accumulator, visiting block, travelling scores, and Sinkhorn duals carried
between them must reproduce the monolithic trajectories.

Pinned here: ring-hop chunking (``hops_per_dispatch ∈ {1, 2, S}``) equals
the monolithic ring step in both ``all_*`` modes, the resumable Sinkhorn
dual-advance chunks equal the unsplit solve at convergence, the chunked W2
step equals the monolithic scanned path, the ``dispatch_budget`` planner's
three tiers, the ``Sampler``-level scan chunking (minibatch-stream
identity, history stitching), and the executor's constraint errors."""

import importlib.util
import os

import numpy as np
import jax.numpy as jnp
import pytest

from dist_svgd_tpu import DistSampler, Sampler
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.models.logreg import logreg_logp
from dist_svgd_tpu.ops.ot import (
    sinkhorn_dual_advance,
    wasserstein_grad_sinkhorn,
)

from test_distsampler import make_gaussian_problem

S = 4


def build(particles, data, exch_s=False, w2=False, impl="ring", iters=40,
          **kw):
    return DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=True, exchange_scores=exch_s,
        include_wasserstein=w2, wasserstein_solver="sinkhorn",
        sinkhorn_iters=iters, exchange_impl=impl, **kw,
    )


# --------------------------------------------------------------------- #
# Ring-hop chunking parity


@pytest.mark.parametrize("exch_s", [False, True])
@pytest.mark.parametrize("hpd", [1, 2, S])
def test_ring_hop_chunks_match_monolithic(exch_s, hpd):
    """Chunked hop dispatches replay the monolithic ring pass's exact
    accumulation order — trajectories are bitwise-or-roundoff equal for
    every chunk size, in both all_* modes."""
    rng = np.random.default_rng(17)
    particles, data, _ = make_gaussian_problem(rng, n=16, d=3, num_shards=S)
    mono = build(particles, data, exch_s=exch_s)
    want = np.asarray(mono.run_steps(3, 0.05))
    chunked = build(particles, data, exch_s=exch_s)
    got = np.asarray(chunked.run_steps(3, 0.05, hops_per_dispatch=hpd))
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-14)
    stats = chunked.last_run_stats
    assert stats["execution"] == "intra_step"
    # all_particles: ceil(S/hpd) hop dispatches + finish per step;
    # all_scores additionally pays the score pass + prior add
    hop_chunks = -(-S // hpd)
    per_step = (2 * hop_chunks + 2) if exch_s else (hop_chunks + 1)
    assert stats["num_dispatches"] == 3 * per_step
    assert stats["dispatches_per_step"] == per_step


def test_ring_hop_chunks_with_minibatch():
    """Every chunk of a step re-derives the SAME per-shard minibatch (the
    (key, r) fold is per step, not per dispatch) — parity holds under
    stochastic scores."""
    rng = np.random.default_rng(23)
    particles, data, _ = make_gaussian_problem(rng, n=16, d=3, n_rows=32,
                                               num_shards=S)
    mono = build(particles, data, batch_size=4)
    want = np.asarray(mono.run_steps(3, 0.05))
    chunked = build(particles, data, batch_size=4)
    got = np.asarray(chunked.run_steps(3, 0.05, hops_per_dispatch=1))
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-14)


def test_chunked_record_history_matches():
    """record=True under the intra-step executor emits the same pre-update
    snapshot stack as the monolithic scan."""
    rng = np.random.default_rng(29)
    particles, data, _ = make_gaussian_problem(rng, n=16, d=3, num_shards=S)
    mono = build(particles, data)
    want_final, want_hist = mono.run_steps(4, 0.05, record=True)
    chunked = build(particles, data)
    got_final, got_hist = chunked.run_steps(4, 0.05, record=True,
                                            hops_per_dispatch=2)
    np.testing.assert_allclose(np.asarray(got_final),
                               np.asarray(want_final), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(got_hist),
                               np.asarray(want_hist), rtol=1e-12)


# --------------------------------------------------------------------- #
# Resumable Sinkhorn chunks


def test_sinkhorn_dual_advance_split_equals_unsplit():
    """A solve of I iterations split into g-threaded dual-advance chunks
    plus a gradient finish equals the unsplit solve at convergence (each
    resume's soft-c-transform start is an exact log-domain iteration, so
    the split solve can only be AHEAD of the unsplit one)."""
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(30, 3)))
    y = jnp.asarray(rng.normal(size=(30, 3)) + 0.1)
    g0 = jnp.zeros(30, dtype=x.dtype)
    want, g_want = wasserstein_grad_sinkhorn(x, y, iters=240, tol=None,
                                             g_init=g0, return_g=True)
    g = g0
    for _ in range(3):
        g = sinkhorn_dual_advance(x, y, iters=60, tol=None, g_init=g)
    got, g_got = wasserstein_grad_sinkhorn(x, y, iters=60, tol=None,
                                           g_init=g, return_g=True)
    # measured convergence of the gap: 5.5e-7 at 120 total iterations,
    # 7.3e-10 at 240, 1.1e-13 at 400 — the split solve contracts to the
    # same fixpoint; pin at the 240-iteration level with margin
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                               rtol=1e-5, atol=1e-6)


def test_sinkhorn_dual_advance_iters_zero_is_start_pair():
    """iters=0 returns the bare start pair's g — the degenerate chunk."""
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(12, 2)))
    y = jnp.asarray(rng.normal(size=(12, 2)))
    g = sinkhorn_dual_advance(x, y, iters=0)
    assert g.shape == (12,)
    assert bool(jnp.isfinite(g).all())


def test_chunked_w2_matches_monolithic():
    """The chunked W2 step (ring φ hops + split Sinkhorn solves, state on
    device between dispatches) tracks the monolithic scanned path within
    the solver's tol band."""
    rng = np.random.default_rng(31)
    particles, data, _ = make_gaussian_problem(rng, n=16, d=3, num_shards=S)
    kw = dict(w2=True, iters=80, w2_pairing="block", sinkhorn_tol=None)
    mono = build(particles, data, **kw)
    want = np.asarray(mono.run_steps(4, 0.05, h=0.5))
    chunked = build(particles, data, **kw)
    got = np.asarray(chunked.run_steps(
        4, 0.05, h=0.5, hops_per_dispatch=1, max_passes_per_dispatch=20,
    ))
    # measured 7.4e-6 max abs (1.2e-5 rel) at this config — the split
    # solves converge to the same dual fixpoint
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-8)
    # the carried state converges identically: one more step from each
    # driver stays in lockstep
    np.testing.assert_allclose(
        np.asarray(chunked.run_steps(1, 0.05, h=0.5,
                                     hops_per_dispatch=1,
                                     max_passes_per_dispatch=20)),
        np.asarray(mono.run_steps(1, 0.05, h=0.5)),
        rtol=1e-4, atol=1e-8,
    )


def test_chunked_w2_cold_start_matches_eager():
    """sinkhorn_warm_start=False: the chunked first chunk starts from the
    hard c-transform like the eager path's per-step cold solve."""
    rng = np.random.default_rng(37)
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, num_shards=S)
    eager = build(particles, data, w2=True, iters=60, w2_pairing="block",
                  sinkhorn_warm_start=False)
    for _ in range(3):
        want = eager.make_step(0.05, h=0.5)
    chunked = build(particles, data, w2=True, iters=60, w2_pairing="block",
                    sinkhorn_warm_start=False)
    got = chunked.run_steps(3, 0.05, h=0.5, hops_per_dispatch=1,
                            max_passes_per_dispatch=30)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-6)


# --------------------------------------------------------------------- #
# dispatch_budget planner


def test_budget_selects_monolithic_when_run_fits():
    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    ds = build(particles, data)
    ds.run_steps(2, 0.05, dispatch_budget=1e9)
    assert ds.last_run_stats["execution"] == "monolithic"
    assert ds.last_run_stats["num_dispatches"] == 1


def test_budget_selects_scan_chunks_when_step_fits():
    rng = np.random.default_rng(3)
    n = 8 * S
    particles, data, _ = make_gaussian_problem(rng, n=n, num_shards=S)
    mono = build(particles, data)
    want = np.asarray(mono.run_steps(5, 0.05))
    ds = build(particles, data)
    # t_step = n²/pps = 1 s → 2-step chunks under a 2 s budget
    got = np.asarray(ds.run_steps(5, 0.05, dispatch_budget=2.0,
                                  pairs_per_sec=float(n * n)))
    stats = ds.last_run_stats
    assert stats["execution"] == "scan_chunks"
    assert stats["steps_per_dispatch"] == 2
    assert stats["num_dispatches"] == 3  # 2 + 2 + 1
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_budget_selects_intra_step_past_the_boundary():
    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    mono = build(particles, data)
    want = np.asarray(mono.run_steps(2, 0.05))
    ds = build(particles, data)
    got = np.asarray(ds.run_steps(2, 0.05, dispatch_budget=1.0,
                                  pairs_per_sec=1.0))
    stats = ds.last_run_stats
    assert stats["execution"] == "intra_step"
    assert stats["hops_per_dispatch"] == 1
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_budget_scan_chunks_record_and_w2_state_flow():
    """Scan chunking composes with record=True and the carried W2 state:
    histories concatenate duplicate-free and the trajectory equals one
    long scan."""
    rng = np.random.default_rng(41)
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, num_shards=S)
    mono = build(particles, data, w2=True, iters=40, w2_pairing="block")
    want_final, want_hist = mono.run_steps(6, 0.05, h=0.5, record=True)
    ds = build(particles, data, w2=True, iters=40, w2_pairing="block")
    n = 8
    t_step_pairs = float(n * n + (40 + 3) * n * n / S)
    got_final, got_hist = ds.run_steps(
        6, 0.05, h=0.5, record=True,
        dispatch_budget=2.0, pairs_per_sec=t_step_pairs,  # 2-step chunks
    )
    assert ds.last_run_stats["execution"] == "scan_chunks"
    assert got_hist.shape == want_hist.shape
    np.testing.assert_allclose(np.asarray(got_final),
                               np.asarray(want_final), rtol=1e-8)
    np.testing.assert_allclose(np.asarray(got_hist),
                               np.asarray(want_hist), rtol=1e-8)


def test_budget_gather_raises_without_an_intra_step_seam():
    """A budget only the ring exchange could honor must error with
    guidance, not silently exceed itself."""
    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    ds = build(particles, data, impl="gather")
    with pytest.raises(ValueError, match="ring"):
        ds.run_steps(2, 0.05, dispatch_budget=1.0, pairs_per_sec=1.0)


def test_executor_constraint_errors():
    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    ds = build(particles, data)
    with pytest.raises(ValueError, match="not both"):
        ds.run_steps(1, 0.05, dispatch_budget=1.0, hops_per_dispatch=1)
    with pytest.raises(ValueError, match="positive"):
        ds.run_steps(1, 0.05, dispatch_budget=0.0)
    gather = build(particles, data, impl="gather")
    with pytest.raises(ValueError, match="hop seam"):
        gather.run_steps(1, 0.05, hops_per_dispatch=1)
    no_w2 = build(particles, data)
    with pytest.raises(ValueError, match="sinkhorn"):
        no_w2.run_steps(1, 0.05, max_passes_per_dispatch=4)
    lagged = DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False, exchange_every=2,
    )
    with pytest.raises(ValueError, match="lagged"):
        lagged.run_steps(2, 0.05, hops_per_dispatch=1)
    with pytest.raises(ValueError, match="median"):
        adaptive = build(particles, data)
        adaptive._kernel = __import__(
            "dist_svgd_tpu.ops.kernels", fromlist=["AdaptiveRBF"]
        ).AdaptiveRBF()
        adaptive._chunk_builders = None
        adaptive.run_steps(1, 0.05, hops_per_dispatch=1)


# --------------------------------------------------------------------- #
# Sampler-level scan chunking


def test_sampler_dispatch_budget_matches_monolithic():
    s1 = Sampler(1, gmm_logp)
    want_final, want_hist = s1.run(32, 7, 0.3, seed=0)
    s2 = Sampler(1, gmm_logp)
    got_final, got_hist = s2.run(32, 7, 0.3, seed=0, dispatch_budget=3.0,
                                 pairs_per_sec=32.0 * 32.0)
    assert s2.last_run_stats["execution"] == "scan_chunks"
    assert s2.last_run_stats["num_dispatches"] == 3
    np.testing.assert_allclose(np.asarray(got_final),
                               np.asarray(want_final), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(got_hist),
                               np.asarray(want_hist), rtol=1e-12)


def test_sampler_budget_minibatch_stream_is_chunk_invariant():
    """The per-chunk key-fold offset makes the chunked minibatch stream
    identical to the monolithic one — the caveat the manual chunking
    pattern had to handle by varying seeds disappears."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(40, 2))
    y = rng.normal(size=40)

    def logp(th, data):
        xx, yy = data
        return -jnp.sum((yy - xx @ th) ** 2) - 0.1 * jnp.sum(th * th)

    data = (jnp.asarray(x), jnp.asarray(y))
    a = Sampler(2, logp, data=data, batch_size=8)
    want, _ = a.run(24, 6, 1e-3, seed=3, record=False)
    b = Sampler(2, logp, data=data, batch_size=8)
    got, _ = b.run(24, 6, 1e-3, seed=3, record=False, dispatch_budget=1.0,
                   pairs_per_sec=24.0 * 24.0 * 2)
    assert b.last_run_stats["num_dispatches"] > 1
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-12)


def test_sampler_single_step_over_budget_warns():
    s = Sampler(1, gmm_logp)
    with pytest.warns(UserWarning, match="no internal seam"):
        s.run(16, 2, 0.3, record=False, dispatch_budget=0.5,
              pairs_per_sec=1.0)
    assert s.last_run_stats["steps_per_dispatch"] == 1


# --------------------------------------------------------------------- #
# tools/large_n.py ring pairing resolution (ADVICE round 5: must track the
# library threshold, not a hardcoded copy)


def _load_large_n():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "large_n.py")
    spec = importlib.util.spec_from_file_location("_large_n_tool", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_large_n_ring_pairing_resolution_tracks_library_threshold():
    mod = _load_large_n()
    from dist_svgd_tpu.distsampler import W2_GLOBAL_PAIRING_MAX_N as MAX_N

    assert mod.resolve_ring_pairing(MAX_N, "all_particles", "ring",
                                    "auto") == "block"
    assert mod.resolve_ring_pairing(MAX_N + 1, "all_particles", "ring",
                                    "auto") == "auto"
    # the comparison reads the imported constant, not a hardcoded copy
    mod.W2_GLOBAL_PAIRING_MAX_N = 10
    assert mod.resolve_ring_pairing(11, "all_particles", "ring",
                                    "auto") == "auto"
    assert mod.resolve_ring_pairing(10, "all_particles", "ring",
                                    "auto") == "block"
    # non-ring / partitions / explicit pairings pass through untouched
    assert mod.resolve_ring_pairing(5, "all_particles", "gather",
                                    "auto") == "auto"
    assert mod.resolve_ring_pairing(5, "partitions", "ring", "auto") == "auto"
    assert mod.resolve_ring_pairing(5, "all_particles", "ring",
                                    "block") == "block"
