"""telemetry/slo.py edge semantics: timestamp staleness (never-set
watermark, backwards clock, exactly-at-threshold) and the streaming
freshness objective's watermark-pair reading.  All clocks injected —
no sleeps, no real time."""

import numpy as np  # noqa: F401  (kept for parity with the suite idiom)

import pytest

from dist_svgd_tpu.telemetry import MetricsRegistry
from dist_svgd_tpu.telemetry.slo import (
    FreshnessObjective,
    SloEngine,
    StalenessObjective,
    default_streaming_slos,
)


# --------------------------------------------------------------------- #
# staleness: a unix-timestamp gauge must be at most max_age_s old


def test_staleness_never_set_gauge_is_no_data_not_breach():
    reg = MetricsRegistry()
    obj = StalenessObjective("ckpt_fresh", "svgd_ckpt_ts", max_age_s=60.0)
    row = obj.evaluate(reg, now_s=1000.0)
    assert row["status"] == "no_data" and row["burn_rate"] == 0.0
    # gauge exists but was never .set(): still no_data
    reg.gauge("svgd_ckpt_ts")
    assert obj.evaluate(reg, now_s=1000.0)["status"] == "no_data"
    # the engine's overall verdict stays ok on no_data objectives
    eng = SloEngine(reg, [obj], clock=lambda: 1000.0)
    assert eng.evaluate()["status"] == "ok"


def test_staleness_backwards_watermark_clamps_to_zero_age():
    reg = MetricsRegistry()
    reg.gauge("svgd_ckpt_ts").set(2000.0)  # stamped ahead of "now"
    obj = StalenessObjective("ckpt_fresh", "svgd_ckpt_ts", max_age_s=60.0)
    row = obj.evaluate(reg, now_s=1000.0)
    assert row["status"] == "ok"
    assert row["age_s"] == 0.0 and row["burn_rate"] == 0.0


def test_staleness_exactly_at_threshold_is_ok_past_is_breach():
    reg = MetricsRegistry()
    reg.gauge("svgd_ckpt_ts").set(1000.0)
    obj = StalenessObjective("ckpt_fresh", "svgd_ckpt_ts", max_age_s=60.0)
    at = obj.evaluate(reg, now_s=1060.0)  # age == max_age_s exactly
    assert at["status"] == "ok" and at["burn_rate"] == 1.0
    past = obj.evaluate(reg, now_s=1060.5)
    assert past["status"] == "breach" and past["burn_rate"] > 1.0
    # the injected engine clock drives the same verdict end to end
    now = {"t": 1060.0}
    eng = SloEngine(reg, [obj], clock=lambda: now["t"])
    assert eng.evaluate()["status"] == "ok"
    now["t"] = 1061.0
    assert eng.evaluate()["status"] == "breach"
    assert reg.counter("svgd_slo_breaches_total").value(
        slo="ckpt_fresh") == 1.0


def test_staleness_rejects_nonpositive_threshold():
    with pytest.raises(ValueError, match="max_age_s"):
        StalenessObjective("x", "g", max_age_s=0.0)


# --------------------------------------------------------------------- #
# freshness: served watermark within max_lag_s of the ingest watermark


def test_freshness_no_data_until_both_watermarks_set():
    reg = MetricsRegistry()
    obj = FreshnessObjective("freshness", 60.0)
    assert obj.evaluate(reg, now_s=0.0)["status"] == "no_data"
    reg.gauge("svgd_stream_watermark").set(100.0)
    assert obj.evaluate(reg, now_s=0.0)["status"] == "no_data"
    reg.gauge("svgd_serving_watermark").set(80.0)
    row = obj.evaluate(reg, now_s=0.0)
    assert row["status"] == "ok" and row["lag_s"] == 20.0


def test_freshness_served_ahead_of_ingest_clamps_fresh():
    # a replayed/idle stream can leave serving ahead of ingest — that is
    # perfectly fresh, not negative lag
    reg = MetricsRegistry()
    reg.gauge("svgd_stream_watermark").set(100.0)
    reg.gauge("svgd_serving_watermark").set(500.0)
    row = FreshnessObjective("freshness", 60.0).evaluate(reg, now_s=0.0)
    assert row["status"] == "ok"
    assert row["lag_s"] == 0.0 and row["burn_rate"] == 0.0


def test_freshness_exactly_at_threshold_is_ok_past_is_breach():
    reg = MetricsRegistry()
    reg.gauge("svgd_stream_watermark").set(160.0)
    reg.gauge("svgd_serving_watermark").set(100.0)
    obj = FreshnessObjective("freshness", 60.0)
    at = obj.evaluate(reg, now_s=0.0)  # lag == max_lag_s exactly
    assert at["status"] == "ok" and at["burn_rate"] == 1.0
    reg.gauge("svgd_stream_watermark").set(160.5)
    past = obj.evaluate(reg, now_s=0.0)
    assert past["status"] == "breach" and past["lag_s"] == 60.5


def test_freshness_labeled_served_gauge_judged_under_own_labels():
    reg = MetricsRegistry()
    reg.gauge("svgd_stream_watermark").set(100.0)
    reg.gauge("svgd_serving_watermark").set(90.0, tenant="a")
    # unlabelled objective does not see tenant-labelled series → no_data
    plain = FreshnessObjective("freshness", 60.0)
    assert plain.evaluate(reg, now_s=0.0)["status"] == "no_data"
    scoped = FreshnessObjective("freshness", 60.0,
                                labels={"tenant": "a"})
    row = scoped.evaluate(reg, now_s=0.0)
    assert row["status"] == "ok" and row["lag_s"] == 10.0


def test_freshness_rejects_nonpositive_threshold():
    with pytest.raises(ValueError, match="max_lag_s"):
        FreshnessObjective("freshness", 0.0)


def test_default_streaming_slos_zero_drop_budget_breaches_on_loss():
    reg = MetricsRegistry()
    reg.gauge("svgd_stream_watermark").set(10.0)
    reg.gauge("svgd_serving_watermark").set(10.0)
    reg.counter("svgd_stream_batches_total").inc(10)
    eng = default_streaming_slos(reg, max_lag_s=60.0, clock=lambda: 0.0)
    doc = eng.evaluate()
    assert doc["status"] == "ok"
    assert set(doc["objectives"]) == {"freshness", "stream_drop_rate"}
    # one dropped batch against the ZERO budget breaches immediately
    reg.counter("svgd_stream_dropped_total").inc()
    reg.counter("svgd_stream_batches_total").inc()
    doc = eng.evaluate()
    assert doc["objectives"]["stream_drop_rate"]["status"] == "breach"
    assert doc["status"] == "breach"
