"""Experiment-driver smoke tests: the CLIs run end-to-end in a subprocess
(fresh interpreter, CPU backend) and produce the reference's artifacts."""

import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_script(args, timeout=110):
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    })
    return subprocess.run(
        [sys.executable] + args, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout,
    )


@pytest.mark.slow
def test_logreg_cli_grid_style_config(tmp_path):
    """grid.sh's awkward case: 50 particles on 4 shards (not divisible —
    must truncate, not crash) with plots."""
    res = run_script([
        "experiments/logreg.py", "--dataset", "banana", "--fold", "3",
        "--nproc", "4", "--nparticles", "50", "--niter", "5",
        "--stepsize", "3e-3", "--exchange", "all_particles",
        "--no-wasserstein", "--plots",
    ])
    assert res.returncode == 0, res.stderr[-2000:]
    results_dir = os.path.join(
        REPO, "experiments", "results",
        "logreg_banana_3-nshards=4-nparticles=50-exchange=all_particles-wasserstein=False-stepsize=3e-03",
    )
    pkls = sorted(os.listdir(results_dir))
    assert pkls == [f"shard-{r}.pkl" for r in range(4)]
    df = pd.read_pickle(os.path.join(results_dir, "shard-0.pkl"))
    assert list(df.columns) == ["timestep", "value"]
    # 50 // 4 * 4 = 48 → 12 per shard, niter+1 snapshots
    assert len(df) == 12 * 6
    assert "accuracy" in res.stdout


@pytest.mark.slow
def test_logreg_cli_nproc_zero_normalised():
    res = run_script([
        "experiments/logreg.py", "--dataset", "titanic", "--fold", "1",
        "--nproc", "0", "--nparticles", "6", "--niter", "2",
        "--exchange", "partitions", "--no-wasserstein", "--no-plots",
    ])
    assert res.returncode == 0, res.stderr[-2000:]
    results_dir = os.path.join(
        REPO, "experiments", "results",
        "logreg_titanic_1-nshards=1-nparticles=6-exchange=partitions-wasserstein=False-stepsize=1e-03",
    )
    assert os.path.exists(os.path.join(results_dir, "shard-0.pkl"))


@pytest.mark.slow
def test_covertype_cli_minibatched_sharded():
    """BASELINE config 4 shape at toy scale: sharded particles, sharded data,
    per-shard minibatched scores, separate prior."""
    res = run_script([
        "experiments/covertype.py", "--nrows", "800", "--nproc", "4",
        "--nparticles", "64", "--niter", "10", "--stepsize", "1e-3",
        "--batch-size", "32", "--backend", "cpu",
    ], timeout=220)
    assert res.returncode == 0, res.stderr[-2000:]
    import json

    metrics = json.loads(res.stdout.strip().splitlines()[-1])
    assert metrics["nparticles"] == 64
    assert metrics["shard_data"] is True
    assert 0.0 <= metrics["test_acc"] <= 1.0
    results_dir = os.path.join(
        REPO, "experiments", "results",
        "covertype-800-4-64-10-0.001-32-all_particles-shard-0",
    )
    assert os.path.exists(os.path.join(results_dir, "metrics.json"))
    parts = np.load(os.path.join(results_dir, "particles.npy"))
    assert parts.shape == (64, 55)
    assert np.isfinite(parts).all()


@pytest.mark.slow
def test_bnn_cli_writes_metrics():
    res = run_script([
        "experiments/bnn.py", "--dataset", "yacht", "--nparticles", "32",
        "--n-hidden", "8", "--niter", "10", "--nproc", "2", "--backend", "cpu",
    ], timeout=220)
    assert res.returncode == 0, res.stderr[-2000:]
    import json

    metrics = json.loads(res.stdout.strip().splitlines()[-1])
    assert np.isfinite(metrics["test_rmse"])


def _import_logreg_driver():
    sys.path.insert(0, os.path.join(REPO, "experiments"))
    import logreg
    from logreg_plots import get_results_dir

    return logreg, get_results_dir


def _driver_run_final(logreg, get_results_dir, solver, **over):
    """Run the logreg driver in-process and return the last-timestep particle
    values of every shard, stacked."""
    cfg = dict(
        num_shards=2, dataset_name="banana", fold=7, nparticles=8, niter=6,
        stepsize=3e-3, exchange="all_particles", wasserstein=True,
        wasserstein_solver=solver,
    )
    cfg.update(over)
    results_dir = get_results_dir(
        cfg["dataset_name"], cfg["fold"], cfg["num_shards"], cfg["nparticles"],
        cfg["stepsize"], cfg["exchange"], cfg["wasserstein"],
        cfg.get("update_rule", "jacobi"),
    )
    os.makedirs(results_dir, exist_ok=True)
    logreg.run(**cfg)
    frames = [
        pd.read_pickle(os.path.join(results_dir, f"shard-{r}.pkl"))
        for r in range(cfg["num_shards"])
    ]
    last = [df[df["timestep"] == df["timestep"].max()] for df in frames]
    return np.stack([np.stack(df["value"].values) for df in last])


def test_logreg_driver_sinkhorn_solver_tracks_lp():
    """--wasserstein --wasserstein-solver sinkhorn drives whole trajectories
    through the scanned on-device path and stays close to the eager host-LP
    parity path at small n (VERDICT r1 item 4; reference h=10.0 behaviour of
    experiments/logreg.py:83 preserved in both)."""
    logreg, get_results_dir = _import_logreg_driver()
    lp = _driver_run_final(logreg, get_results_dir, "lp")
    sk = _driver_run_final(logreg, get_results_dir, "sinkhorn")
    assert lp.shape == sk.shape
    np.testing.assert_allclose(sk, lp, atol=2e-2)
    assert not np.allclose(sk, 0.0)


def test_logreg_driver_gs_sinkhorn_scanned_tracks_lp():
    """--update-rule gauss_seidel --wasserstein now drives the SCANNED
    sinkhorn path (round-4 GS+W2 composition) and must stay close to the
    eager host-LP GS parity path — the driver-level pin of the composition
    cell (the sampler-level pin is
    test_distsampler.py::test_run_steps_wasserstein_gauss_seidel_matches_eager)."""
    logreg, get_results_dir = _import_logreg_driver()
    lp = _driver_run_final(logreg, get_results_dir, "lp",
                           update_rule="gauss_seidel")
    sk = _driver_run_final(logreg, get_results_dir, "sinkhorn",
                           update_rule="gauss_seidel")
    assert lp.shape == sk.shape
    np.testing.assert_allclose(sk, lp, atol=2e-2)
    assert not np.allclose(sk, 0.0)


def test_logreg_driver_record_chunking_is_semantics_neutral(monkeypatch):
    """Chunked trajectory recording (record_chunk_steps) must reproduce the
    single-dispatch history exactly (ADVICE r1: bound the (niter, n, d)
    device history buffer; round 5: the chunk is HBM-budget-sized and the
    D2H copy of chunk k overlaps chunk k+1's scan; round 8: the chunking
    lives in the samplers — patch the library sizing, and the driver's
    single run_steps call must route through it)."""
    from dist_svgd_tpu.utils import history

    logreg, get_results_dir = _import_logreg_driver()
    kw = dict(wasserstein=False, niter=6)
    whole = _driver_run_final(logreg, get_results_dir, "lp", **kw)
    monkeypatch.setattr(history, "record_chunk_steps",
                        lambda n, d: 4)  # 6 = 4 + 2 → two chunks
    chunked = _driver_run_final(logreg, get_results_dir, "lp", **kw)
    np.testing.assert_array_equal(whole, chunked)


def test_record_chunk_steps_sizing():
    """The HBM-budget sizing accounts for TPU lane padding (a (n, d≤128)
    snapshot is physically n×128 floats) and clamps to [1, max]."""
    logreg, _ = _import_logreg_driver()
    # tiny n: budget allows far more than the cap → clamped to the cap
    assert logreg.record_chunk_steps(100, 3) == logreg.RECORD_CHUNK_MAX
    # n=100k, d=3: 100_000 × 128 × 4 B = 51.2 MB/step → 2 GiB holds 41
    assert logreg.record_chunk_steps(100_000, 3) == 41
    # d > 128 pads to d, not 128
    assert (logreg.record_chunk_steps(100_000, 256)
            == (logreg.RECORD_HBM_BUDGET_BYTES // (100_000 * 256 * 4)))
    # pathological n never sizes to zero
    assert logreg.record_chunk_steps(10**9, 3) == 1


@pytest.mark.parametrize("sampler_kwargs,h", [
    pytest.param({"include_wasserstein": False}, 1.0, id="north_star"),
    # the large-n auto-route target (exchanged φ + block W2 pairing, round
    # 5): the pairing swap is a memory-layout decision, not an accuracy
    # trade (throughput/fidelity evidence in docs/notes.md; this is the
    # convergence side).  h=10 is the reference driver's W2 weight
    pytest.param({"include_wasserstein": True,
                  "wasserstein_solver": "sinkhorn", "sinkhorn_iters": 50,
                  "w2_pairing": "block"}, 10.0,
                 id="block_w2", marks=pytest.mark.slow),
])
def test_logreg_convergence_reaches_sklearn_baseline(sampler_kwargs, h):
    """SURVEY.md §4's quantitative acceptance test (the convergence half of
    the primary metric, reference experiments/logreg_plots.py:37-57): the
    sharded sampler's ensemble posterior-predictive accuracy reaches the
    sklearn LogisticRegression baseline − 0.01 within a fixed step budget —
    the same target ``bench.py`` measures steps-to at the 10k-particle
    scale."""
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import ensemble_test_accuracy, logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    sklearn = pytest.importorskip("sklearn.linear_model")

    fold = load_benchmark("banana", 42)
    clf = sklearn.LogisticRegression()
    clf.fit(fold.x_train, fold.t_train.reshape(-1))
    baseline = float(clf.score(fold.x_test, fold.t_test.reshape(-1)))

    d = 1 + fold.x_train.shape[1]
    sampler = dt.DistSampler(
        4, logreg_logp, None, init_particles_per_shard(0, 256, d, 4),
        data=(jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1))),
        exchange_particles=True, exchange_scores=False, **sampler_kwargs,
    )
    sampler.run_steps(200, 0.1, h=h)
    acc = float(ensemble_test_accuracy(
        sampler.particles, jnp.asarray(fold.x_test),
        jnp.asarray(fold.t_test.reshape(-1)),
    ))
    assert acc >= baseline - 0.01, (acc, baseline)


@pytest.mark.slow
def test_gmm_experiment_writes_figure():
    # tiny config via import (same process would fight the conftest backend;
    # subprocess keeps it faithful to `python experiments/gmm.py`)
    code = (
        "import gmm, os; df = gmm.run(seed=42); "
        "p = gmm.plot(df, os.path.join(gmm.FIGURES_DIR, 'gmm_test.png')); print(p)"
    )
    res = run_script(["-c", f"import sys; sys.path.insert(0, 'experiments'); {code}"])
    assert res.returncode == 0, res.stderr[-2000:]
    fig = os.path.join(REPO, "experiments", "figures", "gmm_test.png")
    assert os.path.exists(fig)
    os.remove(fig)


@pytest.mark.slow
def test_bench_suite_all_configs():
    """The five-config BASELINE.json suite runs end-to-end (tiny iteration
    counts) and reports one JSON line per config plus the scaling table."""
    import json

    res = run_script([
        "experiments/bench_suite.py", "--configs", "all", "--iters", "2",
        "--scaling-iters", "2", "--table",
    ], timeout=300)
    assert res.returncode == 0, res.stderr[-2000:]
    lines = [l for l in res.stdout.splitlines() if l.startswith("{")]
    rows = [json.loads(l) for l in lines]
    configs = [r["config"] for r in rows]
    assert [c.split(":")[0] for c in configs[:5]] == ["1", "2", "3", "4", "5"]
    assert [r["num_shards"] for r in rows[5:]] == [1, 2, 4, 8]
    for r in rows:
        assert r["updates_per_sec"] > 0
    assert "| config |" in res.stdout  # markdown table
