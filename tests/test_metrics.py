"""Metrics/observability (utils/metrics.py; SURVEY.md §5): JSONL logging,
fenced timing, jitted particle diagnostics, profiler context."""

import io
import json
import time

import numpy as np
import jax.numpy as jnp
import pytest

from dist_svgd_tpu.utils.metrics import (
    JsonlLogger,
    StepTimer,
    particle_stats,
    profiler_trace,
)


def test_jsonl_logger_file_and_stream(tmp_path):
    path = str(tmp_path / "m.jsonl")
    buf = io.StringIO()
    with JsonlLogger(path=path, stream=buf) as lg:
        lg.log(step=1, value=2.5)
        lg.log(step=2, arr=np.arange(3), npfloat=np.float32(1.5))
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 2
    rec = json.loads(lines[1])
    assert rec["step"] == 2
    assert rec["arr"] == [0, 1, 2]
    assert rec["npfloat"] == 1.5
    assert "ts" in rec
    assert buf.getvalue().strip().splitlines() == lines


def test_jsonl_logger_appends(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with JsonlLogger(path=path) as lg:
        lg.log(a=1)
    with JsonlLogger(path=path) as lg:
        lg.log(a=2)
    assert len(open(path).read().strip().splitlines()) == 2


def test_particle_stats_values():
    parts = jnp.asarray([[3.0, 4.0], [0.0, 0.0]])
    prev = jnp.asarray([[3.0, 4.0], [1.0, 0.0]])
    out = particle_stats(parts, prev)
    assert out["particle_mean_norm"] == pytest.approx(2.5)
    assert out["particle_norm_std"] == pytest.approx(2.5)
    assert out["particle_mean"] == pytest.approx((3.0 + 4.0) / 4)
    assert out["mean_update"] == pytest.approx(0.5)
    assert out["max_update"] == pytest.approx(1.0)


def test_particle_stats_without_prev():
    out = particle_stats(jnp.ones((4, 2)))
    assert "mean_update" not in out
    assert out["particle_mean_norm"] == pytest.approx(np.sqrt(2.0))


def test_step_timer_rates():
    t = StepTimer()
    time.sleep(0.01)
    lap = t.mark(jnp.ones(4) * 2)  # fences on the value
    assert lap >= 0.01
    assert t.total == pytest.approx(sum(t.laps))
    assert t.updates_per_sec(100) == pytest.approx(len(t.laps) * 100 / t.total)


def test_step_timer_empty():
    assert StepTimer().updates_per_sec(10) == 0.0


def test_profiler_trace_noop_and_real(tmp_path):
    with profiler_trace(None):
        pass  # no-op path
    logdir = str(tmp_path / "trace")
    with profiler_trace(logdir):
        jnp.ones(8).block_until_ready()
    import os

    assert os.path.isdir(logdir)


# --------------------------------------------------------------------- #
# JsonlLogger lifecycle (round 8: crash-log integrity for supervised runs)


def test_jsonl_logger_context_manager_closes_on_crash(tmp_path):
    path = str(tmp_path / "crash.jsonl")
    with pytest.raises(RuntimeError, match="boom"):
        with JsonlLogger(path=path) as logger:
            logger.log(a=1)
            raise RuntimeError("boom")
    # the line written before the crash is intact on disk (per-line flush)
    lines = [json.loads(l) for l in open(path)]
    assert [l["a"] for l in lines] == [1]


def test_jsonl_logger_close_is_idempotent_and_log_after_close_raises(tmp_path):
    logger = JsonlLogger(path=str(tmp_path / "x.jsonl"))
    logger.log(a=1)
    assert not logger.closed
    logger.close()
    logger.close()  # idempotent
    assert logger.closed
    with pytest.raises(ValueError, match="after close"):
        logger.log(a=2)


def test_jsonl_logger_fsync_and_flush(tmp_path):
    path = str(tmp_path / "f.jsonl")
    with JsonlLogger(path=path, fsync=True) as logger:
        logger.log(a=1)
        logger.flush()
        # durable before close: a concurrent reader sees the whole line
        assert json.loads(open(path).read().strip())["a"] == 1


def test_jsonl_logger_stream_not_closed_by_close():
    stream = io.StringIO()
    logger = JsonlLogger(stream=stream)
    logger.log(a=1)
    logger.close()
    assert logger.closed
    assert not stream.closed  # caller-owned stream survives
    assert json.loads(stream.getvalue().strip())["a"] == 1


def test_jsonl_logger_threaded_lines_whole(tmp_path):
    import threading

    path = str(tmp_path / "t.jsonl")
    with JsonlLogger(path=path) as logger:
        threads = [
            threading.Thread(
                target=lambda i=i: [logger.log(i=i, k=j) for j in range(20)]
            )
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    lines = [json.loads(l) for l in open(path)]  # every line parses whole
    assert len(lines) == 80


def test_jsonl_logger_null_sink_stays_open():
    """JsonlLogger() with neither path nor stream is a valid null sink:
    log() writes nowhere but still returns the stamped record, until an
    explicit close()."""
    logger = JsonlLogger()
    assert not logger.closed
    rec = logger.log(a=1)
    assert rec["a"] == 1 and "ts" in rec
    logger.close()
    with pytest.raises(ValueError, match="after close"):
        logger.log(a=2)
