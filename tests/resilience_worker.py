"""Worker process for the resilience fault drills (slow tier).

Not a test module.  Two modes:

- ``single``: one jax process running a supervised DistSampler (vmap
  emulation) with real SIGTERM/SIGINT handlers installed — the parent test
  kills it mid-run (SIGTERM → graceful preemption checkpoint; SIGKILL →
  nothing) and relaunches with ``--resume`` to verify the bitwise-exact
  recovery (tests/test_fault_drill.py).
- ``fed``: one rank of a multi-process federation (jax.distributed) running
  a supervised DistSampler over a shared mesh with per-process checkpoint
  roots — the kill-one-worker → resume drill.  Requires a jax whose CPU
  backend implements multiprocess collectives (skipped on legacy jax via
  ``needs_cpu_multiprocess``).

The run is paced by sleeping a few hundred ms at every segment boundary
(duck-typed through the supervisor's fault hook) so the parent can land a
real signal mid-run deterministically; tier-1 never runs this file.
"""

import argparse
import json
import os
import sys
import time

# drill geometry shared with test_fault_drill.py: 40 steps, checkpoints
# every 8, segments of 4
N, D, STEPS, EVERY, SEGMENT, EPS = 32, 2, 40, 8, 4, 0.05


class Pacer:
    """Duck-typed FaultPlan: real-sleeps at every segment boundary so the
    parent's signal lands mid-run (slow tier only)."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def fire_due(self, ctx) -> None:
        time.sleep(self.seconds)


def build_sampler(mesh=None, particles=None):
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    num_shards = mesh.size if mesh is not None else 2
    if particles is None:
        particles = init_particles_per_shard(0, N, D, num_shards)
    return dt.DistSampler(
        num_shards, lambda th, _: gmm_logp(th), None, particles,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False, mesh=mesh if mesh is not None else "auto",
    )


def run_single(args):
    import _jax_env

    _jax_env.setup_cpu(device_count=2)
    import numpy as np

    from dist_svgd_tpu.resilience import RunSupervisor

    ds = build_sampler()
    sup = RunSupervisor(
        ds, STEPS, EPS, checkpoint_dir=os.path.join(args.outdir, "ckpt"),
        checkpoint_every=EVERY, segment_steps=SEGMENT,
        faults=Pacer(args.pace),
    )
    sup.install_signal_handlers()
    report = sup.run(resume=args.resume)
    np.save(os.path.join(args.outdir, "final.npy"), np.asarray(sup.particles))
    with open(os.path.join(args.outdir, "report.json"), "w") as fh:
        json.dump(report, fh)


def run_fed(args):
    import _jax_env

    _jax_env.setup_cpu(device_count=args.devcount)
    import numpy as np

    from dist_svgd_tpu.parallel import multihost
    from dist_svgd_tpu.resilience import RunSupervisor
    from dist_svgd_tpu.utils.checkpoint import load_state

    assert multihost.initialize(
        coordinator_address=args.coordinator, num_processes=args.nprocs,
        process_id=args.rank,
    )
    mesh = multihost.make_particle_mesh()
    start, count = multihost.process_local_rows(N, mesh)
    full = np.random.default_rng(0).normal(size=(N, D)).astype(np.float32)
    particles = multihost.make_global_particles(
        full[start:start + count], mesh, n_global=N
    )
    ds = build_sampler(mesh=mesh, particles=particles)
    root = os.path.join(args.outdir, f"ckpt_rank{args.rank}")
    if args.resume_from is not None:
        # the federation resumes from the newest step present in EVERY
        # rank's root (the parent computes it): load that exact step
        ds.load_state_dict(load_state(
            os.path.join(root, f"step_{args.resume_from}")
        ))
        # same absolute segment grid as the killed run — the bitwise-resume
        # invariant needs the identical sequence of run_steps calls
        sup = RunSupervisor(ds, STEPS, EPS, segment_steps=SEGMENT,
                            faults=Pacer(args.pace))
    else:
        sup = RunSupervisor(
            ds, STEPS, EPS, checkpoint_dir=root, checkpoint_every=EVERY,
            segment_steps=SEGMENT, faults=Pacer(args.pace),
        )
        sup.install_signal_handlers()
    report = sup.run()
    rows = np.concatenate([
        np.asarray(s.data) for s in sorted(
            ds.particles.addressable_shards,
            key=lambda s: s.index[0].start or 0,
        )
    ])
    np.save(os.path.join(args.outdir, f"rows_{args.rank}.npy"), rows)
    with open(os.path.join(args.outdir, f"report_{args.rank}.json"), "w") as fh:
        json.dump(report, fh)


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", choices=("single", "fed"))
    ap.add_argument("outdir")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--pace", type=float, default=0.25,
                    help="seconds slept per segment boundary")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--nprocs", type=int, default=2)
    ap.add_argument("--coordinator", default="127.0.0.1:0")
    ap.add_argument("--devcount", type=int, default=2)
    ap.add_argument("--resume-from", type=int, default=None)
    args = ap.parse_args()
    if args.mode == "single":
        run_single(args)
    else:
        run_fed(args)


if __name__ == "__main__":
    main()
