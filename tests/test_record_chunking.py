"""HBM-budget history chunking, generalised into the samplers (round 8):
``Sampler.run`` / ``DistSampler.run_steps`` with ``record=True`` auto-split
into ``utils/history.py:record_chunk_steps``-sized dispatches whose chunks
are fetched to host — identical trajectories and histories, bounded device
history buffer, every driver (logreg/covertype/bnn/gmm) gets it for free."""

import numpy as np
import pytest

import jax.numpy as jnp

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.utils import history
from dist_svgd_tpu.utils.history import (
    RECORD_CHUNK_MAX,
    RECORD_HBM_BUDGET_BYTES,
    record_chunk_steps,
)
from dist_svgd_tpu.utils.rng import init_particles_per_shard


def test_record_chunk_steps_sizing_lib():
    """The sizing lives in the library now (the logreg driver re-exports
    it); lane padding + clamping semantics unchanged."""
    assert record_chunk_steps(100, 3) == RECORD_CHUNK_MAX
    assert record_chunk_steps(100_000, 3) == 41
    assert (record_chunk_steps(100_000, 256)
            == RECORD_HBM_BUDGET_BYTES // (100_000 * 256 * 4))
    assert record_chunk_steps(10 ** 9, 3) == 1


def make_dist(**kw):
    parts = init_particles_per_shard(0, 32, 2, 4)
    kw.setdefault("exchange_particles", True)
    kw.setdefault("exchange_scores", False)
    kw.setdefault("include_wasserstein", False)
    return dt.DistSampler(4, lambda th, _: gmm_logp(th), None, parts, **kw)


def test_distsampler_record_chunks_match_monolithic(monkeypatch):
    want_final, want_hist = make_dist().run_steps(7, 0.05, record=True)
    monkeypatch.setattr(history, "record_chunk_steps", lambda n, d: 3)
    ds = make_dist()
    got_final, got_hist = ds.run_steps(7, 0.05, record=True)
    assert ds.last_run_stats["execution"] == "record_chunks"
    assert ds.last_run_stats["record_hbm_chunked"]
    assert ds.last_run_stats["num_dispatches"] == 3  # 3 + 3 + 1
    assert isinstance(got_hist, np.ndarray)  # host history when chunked
    np.testing.assert_array_equal(np.asarray(want_hist), got_hist)
    np.testing.assert_array_equal(np.asarray(want_final),
                                  np.asarray(got_final))


def test_distsampler_record_chunks_compose_with_w2(monkeypatch):
    """The W2 scan path carries prev/duals in sampler state, so recorded
    chunking composes with it unchanged."""
    def make_w2():
        return make_dist(include_wasserstein=True,
                         wasserstein_solver="sinkhorn")

    want_final, want_hist = make_w2().run_steps(6, 0.05, record=True, h=1.0)
    monkeypatch.setattr(history, "record_chunk_steps", lambda n, d: 2)
    ds = make_w2()
    got_final, got_hist = ds.run_steps(6, 0.05, record=True, h=1.0)
    np.testing.assert_allclose(np.asarray(want_hist), got_hist,
                               rtol=1e-12, atol=1e-14)
    np.testing.assert_allclose(np.asarray(want_final),
                               np.asarray(got_final),
                               rtol=1e-12, atol=1e-14)


def test_distsampler_record_chunks_lagged_cadence(monkeypatch):
    """Lagged exchange chunks at whole-cadence granularity (each chunk a
    multiple of exchange_every)."""
    def make_lagged():
        return make_dist(exchange_every=3)

    want_final, want_hist = make_lagged().run_steps(9, 0.05, record=True)
    monkeypatch.setattr(history, "record_chunk_steps", lambda n, d: 4)
    ds = make_lagged()
    got_final, got_hist = ds.run_steps(9, 0.05, record=True)
    # 4 rounds down to 3 (the cadence): chunks 3 + 3 + 3
    assert ds.last_run_stats["num_dispatches"] == 3
    np.testing.assert_array_equal(np.asarray(want_hist), got_hist)
    np.testing.assert_array_equal(np.asarray(want_final),
                                  np.asarray(got_final))


def test_sampler_record_chunks_match_monolithic(monkeypatch):
    logp = lambda th: -0.5 * jnp.sum(th ** 2)
    want_final, want_hist = dt.Sampler(2, logp).run(8, 7, 0.1, seed=1)
    monkeypatch.setattr(history, "record_chunk_steps", lambda n, d: 3)
    s = dt.Sampler(2, logp)
    got_final, got_hist = s.run(8, 7, 0.1, seed=1)
    assert s.last_run_stats["execution"] == "scan_chunks"
    assert isinstance(got_hist, np.ndarray)
    assert got_hist.shape == (8, 8, 2)  # pre-update snapshots + final
    np.testing.assert_array_equal(np.asarray(want_hist), got_hist)
    np.testing.assert_array_equal(np.asarray(want_final),
                                  np.asarray(got_final))


def test_sampler_record_chunks_minibatch_stream(monkeypatch):
    """Chunk boundaries stay invisible to the minibatch key stream (the i0
    offset), recorded or not."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(40, 2)).astype(np.float32))
    logp = lambda th, b: -0.5 * jnp.sum(th ** 2) + 0.0 * jnp.sum(b)

    def make_s():
        return dt.Sampler(3, logp, data=x, batch_size=5)

    want_final, want_hist = make_s().run(8, 7, 1e-2, seed=2)
    monkeypatch.setattr(history, "record_chunk_steps", lambda n, d: 2)
    got_final, got_hist = make_s().run(8, 7, 1e-2, seed=2)
    np.testing.assert_array_equal(np.asarray(want_hist),
                                  np.asarray(got_hist))
    np.testing.assert_array_equal(np.asarray(want_final),
                                  np.asarray(got_final))


def test_sampler_dispatch_budget_record_returns_host_history():
    """dispatch_budget + record: chunk histories are host-fetched too (a
    chunked recorded run must not keep the whole stack in HBM)."""
    logp = lambda th: -0.5 * jnp.sum(th ** 2)
    s = dt.Sampler(2, logp)
    want_final, want_hist = s.run(8, 6, 0.1, seed=1)
    s2 = dt.Sampler(2, logp)
    got_final, got_hist = s2.run(
        8, 6, 0.1, seed=1, dispatch_budget=1.0,
        pairs_per_sec=8 * 8 / 0.5,  # one ~0.5 s step estimate → 2-step chunks
    )
    assert s2.last_run_stats["execution"] == "scan_chunks"
    assert isinstance(got_hist, np.ndarray)
    np.testing.assert_array_equal(np.asarray(want_hist), got_hist)
    np.testing.assert_array_equal(np.asarray(want_final),
                                  np.asarray(got_final))


def test_intra_step_record_history_is_host_side():
    """The intra-step executor's recorded history is host-fetched (one
    device snapshot resident at a time) and still matches the monolithic
    trajectory — the HBM-budget contract holds in the large-n tier too."""
    want_final, want_hist = make_dist(exchange_impl="ring").run_steps(
        4, 0.05, record=True)  # ring monolithic: same accumulation order
    ds = make_dist(exchange_impl="ring")
    got_final, got_hist = ds.run_steps(4, 0.05, record=True,
                                       hops_per_dispatch=2)
    assert ds.last_run_stats["execution"] == "intra_step"
    assert isinstance(got_hist, np.ndarray)
    np.testing.assert_allclose(np.asarray(want_hist), got_hist,
                               rtol=1e-12, atol=1e-14)
    np.testing.assert_allclose(np.asarray(want_final),
                               np.asarray(got_final),
                               rtol=1e-12, atol=1e-14)
