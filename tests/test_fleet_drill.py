"""tools/fleet_drill.py: the fleet_failover row — fake-mode drill in
tier-1 (schema + the zero-lost / clean-partition contracts), the
real-subprocess kill/partition/restart drill slow-marked."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import fleet_drill


@pytest.fixture(scope="module")
def fake_row():
    return fleet_drill.run_drill(mode="fake", rate_hz=150.0,
                                 steady_s=0.4, kill_s=0.6, partition_s=0.5)


def test_fake_drill_row_schema(fake_row):
    row = fake_row
    for key in ("metric", "value", "unit", "mode", "replicas", "requests",
                "lost_requests", "shed_requests", "detect_s",
                "detect_probe_intervals", "readmit_s", "p99_steady_ms",
                "p99_kill_ms", "p99_partition_ms", "retries", "hedges",
                "failovers", "misroutes", "ejections", "readmissions",
                "partition_replica_alive", "partition_flight_trips",
                "trace_stitch_coverage", "stitch_served_routes",
                "stitch_retry_trees", "stitch_orphans",
                "federation_scrape_ms", "federation_scrapes",
                "federation_scrapes_skipped",
                "federation_scrape_errors", "federation_monotone",
                "federated_requests_total",
                "probe_interval_s", "open_cooldown_s", "status_counts",
                "wall_s"):
        assert key in row, key
    assert row["metric"] == "fleet_failover"
    assert row["mode"] == "fake"
    assert row["replicas"] == 3
    assert row["requests"] > 0


def test_fake_drill_acceptance(fake_row):
    """The ISSUE-11 availability drill, measured: killing one of three
    replicas under open-loop load loses ZERO non-shed requests, detection
    lands within 2 probe intervals, the partitioned replica stays alive
    and flight-clean, and the restart re-admits through half-open."""
    row = fake_row
    ok, why = fleet_drill.row_ok(row)
    assert ok, why
    assert row["value"] == 1.0
    assert row["lost_requests"] == 0
    assert row["misroutes"] == 0
    assert row["detect_probe_intervals"] <= 2.0
    assert row["readmit_s"] > 0
    assert row["readmissions"] >= 1
    assert row["retries"] >= 1          # the kill was absorbed, not missed
    assert row["partition_replica_alive"] is True
    assert row["partition_flight_trips"] == 0


def test_fake_drill_observability_acceptance(fake_row):
    """ISSUE-12 acceptance on CPU: every non-shed served request stitches
    into exactly one router→replica tree (coverage 1.0, the kill-phase
    retries as sibling attempts), the federation scraped through the kill
    (visible errors) and stayed monotone across the restarted replica's
    counter reset."""
    row = fake_row
    assert row["trace_stitch_coverage"] == 1.0
    assert row["stitch_served_routes"] > 0
    assert row["stitch_retry_trees"] >= 1   # the kill produced siblings
    assert row["stitch_orphans"] == 0
    assert row["federation_scrapes"] >= 4
    # the dead replica degraded VISIBLY while survivors federated: its
    # scrape either failed (pre-detection) or was skipped (circuit open)
    assert (row["federation_scrape_errors"]
            + row["federation_scrapes_skipped"]) >= 1
    assert row["federation_monotone"] is True
    assert row["federation_scrape_ms"] > 0
    assert row["federated_requests_total"] > 0


def test_row_ok_catches_every_gate():
    good = {"lost_requests": 0, "misroutes": 0, "detect_s": 0.1,
            "readmit_s": 0.2, "readmissions": 1,
            "partition_replica_alive": True, "partition_flight_trips": 0,
            "mode": "fake", "trace_stitch_coverage": 1.0,
            "federation_monotone": True}
    assert fleet_drill.row_ok(dict(good)) == (True, [])
    for key, bad in (("lost_requests", 3), ("misroutes", 1),
                     ("detect_s", None), ("readmit_s", None),
                     ("readmissions", 0),
                     ("partition_replica_alive", False),
                     ("partition_flight_trips", 2),
                     ("trace_stitch_coverage", 0.97),
                     ("trace_stitch_coverage", None),
                     ("federation_monotone", False)):
        row = dict(good)
        row[key] = bad
        ok, why = fleet_drill.row_ok(row)
        assert not ok and why, key
    # real mode carries no stitch gate (a SIGKILLed replica takes its
    # trace buffer with it) but keeps the monotone-federation gate
    real = dict(good, mode="real", trace_stitch_coverage=None)
    assert fleet_drill.row_ok(real) == (True, [])
    real["federation_monotone"] = False
    ok, why = fleet_drill.row_ok(real)
    assert not ok and why


def test_drill_cli_exits_clean():
    assert fleet_drill.main(["--mode", "fake", "--rate", "120"]) == 0


@pytest.mark.slow
def test_real_subprocess_drill():
    """Real sockets, real SIGKILL, real restart: three PredictionServer
    subprocesses (CPU jax) behind the router.  The partition is cut
    router-side (HttpTransport deny-list) so the replica process is
    provably untouched."""
    row = fleet_drill.run_drill(mode="real", rate_hz=80.0)
    ok, why = fleet_drill.row_ok(row)
    assert ok, (why, row)
    assert row["lost_requests"] == 0
    assert row["partition_replica_alive"] is True
