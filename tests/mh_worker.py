"""Worker process for the multi-process multi-host federation tests
(test_multihost.py::test_two_process_federation_matches_oracle and
::test_four_process_federation_matches_oracle).

Not a test module.  Invoked as:
    python mh_worker.py <rank> <nprocs> <coordinator> <outdir> <devcount> <legs>
Each process owns ``devcount`` virtual CPU devices; the federation forms one
``nprocs * devcount``-device mesh.  ``legs`` is a comma-separated subset of
{gather, ring, lagged, ckpt, ckpt_restore, subset} selecting which exchange
paths to run (the 4-process test keeps a lighter set to bound rendezvous
wall-clock).  ``ckpt_restore`` resumes a PREVIOUS federation's per-process
checkpoints under this (different) process layout via
``assemble_full_state`` — the cross-process-count restore leg.
Runs scanned DistSampler steps on a deterministically-initialised global
particle array and saves this process's resulting rows.
"""

import os
import sys


def main():
    rank, nprocs, coordinator, outdir = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    devcount = int(sys.argv[5]) if len(sys.argv) > 5 else 4
    legs = set((sys.argv[6] if len(sys.argv) > 6 else
                "gather,ring,lagged,ckpt").split(","))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import _jax_env

    # x64 on, matching conftest: the oracle in the pytest process runs under
    # x64, and the comparison must not straddle two precision regimes
    _jax_env.setup_cpu(device_count=devcount)

    import jax
    import numpy as np

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp
    from dist_svgd_tpu.parallel import multihost

    assert multihost.initialize(
        coordinator_address=coordinator, num_processes=nprocs, process_id=rank
    )
    assert jax.process_count() == nprocs

    mesh = multihost.make_particle_mesh()
    n, d = 32, 2
    start, count = multihost.process_local_rows(n, mesh)
    # same seed in every process ⇒ a well-defined global init to slice from
    full = np.random.default_rng(0).normal(size=(n, d)).astype(np.float32)
    particles = multihost.make_global_particles(
        full[start : start + count], mesh, n_global=n
    )

    def save_local_rows(arr, name):
        """Persist this process's shards of a global array in row order."""
        rows = np.concatenate(
            [np.asarray(s.data) for s in sorted(
                arr.addressable_shards, key=lambda s: s.index[0].start or 0
            )]
        )
        np.save(os.path.join(outdir, name), rows)

    np.save(os.path.join(outdir, f"range_{rank}.npy"), np.array([start, count]))

    if "gather" in legs:
        ds = dt.DistSampler(
            mesh.size, lambda th, _: gmm_logp(th), None, particles,
            exchange_particles=True, exchange_scores=True,
            include_wasserstein=False, mesh=mesh,
        )
        save_local_rows(ds.run_steps(5, 0.1), f"rows_{rank}.npy")

    if "ring" in legs:
        # --- ppermute-ring exchange implementation: blockwise φ accumulation
        # whose per-hop rotations genuinely cross the process boundary every
        # step (unlike the gather mode above, whose collectives XLA may fuse,
        # this is S explicit ring hops per pass — the long-context motif)
        ring = dt.DistSampler(
            mesh.size, lambda th, _: gmm_logp(th), None, particles,
            exchange_particles=True, exchange_scores=True,
            include_wasserstein=False, exchange_impl="ring", mesh=mesh,
        )
        save_local_rows(ring.run_steps(4, 0.1), f"ring_rows_{rank}.npy")

    if "lagged" in legs:
        # --- lagged exchange (exchange_every): the mode exists precisely for
        # multi-host meshes (one gather per T steps over DCN); run it in the
        # real federation so its collective actually crosses the process
        # boundary at every refresh
        lag = dt.DistSampler(
            mesh.size, lambda th, _: gmm_logp(th), None, particles,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=False, exchange_every=2, mesh=mesh,
        )
        save_local_rows(lag.run_steps(4, 0.1), f"lagged_rows_{rank}.npy")

    if "subset" in legs:
        # --- subset mesh over the federation: fewer shards than devices, so
        # make_particle_mesh's equal-per-granule `take()` path picks an
        # equal share of every process's devices (the branch a full-size
        # mesh never exercises)
        sub_shards = mesh.size // devcount  # one shard per process
        sub_mesh = multihost.make_particle_mesh(sub_shards)
        s_start, s_count = multihost.process_local_rows(n, sub_mesh)
        sub_particles = multihost.make_global_particles(
            full[s_start : s_start + s_count], sub_mesh, n_global=n
        )
        sub = dt.DistSampler(
            sub_shards, lambda th, _: gmm_logp(th), None, sub_particles,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=False, mesh=sub_mesh,
        )
        save_local_rows(sub.run_steps(4, 0.1), f"subset_rows_{rank}.npy")
        np.save(os.path.join(outdir, f"subset_range_{rank}.npy"),
                np.array([s_start, s_count]))

    def make_w2_sampler():
        return dt.DistSampler(
            mesh.size, lambda th, _: gmm_logp(th), None, particles,
            exchange_particles=True, exchange_scores=True,
            include_wasserstein=True, wasserstein_solver="sinkhorn",
            sinkhorn_iters=50, mesh=mesh,
        )

    if "ckpt" in legs:
        # --- multi-host checkpoint/resume (VERDICT r1 item 7): save mid-run,
        # restore into a FRESH sampler in this same federation, finish, and
        # match the uninterrupted trajectory — with the W2 term on, so the
        # non-fully-addressable `previous` snapshot stack round-trips too.
        from dist_svgd_tpu.utils.checkpoint import load_state, save_state

        # One sampler plays both roles: run 3, checkpoint, run 2 more — its
        # final state IS the uninterrupted trajectory (the save is read-only).
        straight = make_w2_sampler()
        straight.run_steps(3, 0.1, h=0.5)
        ckpt = os.path.join(outdir, f"ckpt_rank{rank}")
        # per-process path: each process persists only its own addressable block
        save_state(ckpt, straight.state_dict())
        straight.run_steps(2, 0.1, h=0.5)
        want_rows, w_start = multihost.host_addressable_block(straight.particles)
        # the uninterrupted tail also serves as the cross-process-count
        # restore leg's oracle (a later federation under a different layout
        # overwrites range_{rank}.npy, so the want block gets its own range)
        np.save(os.path.join(outdir, f"ckpt_want_rows_{rank}.npy"), want_rows)
        np.save(os.path.join(outdir, f"ckpt_want_range_{rank}.npy"),
                np.array([int(w_start), want_rows.shape[0]]))

        state = load_state(ckpt)
        assert state["particles"].shape[0] == count, (
            state["particles"].shape, count)
        resumed = make_w2_sampler()
        resumed.load_state_dict(state)
        resumed.run_steps(2, 0.1, h=0.5)
        got_rows, _ = multihost.host_addressable_block(resumed.particles)
        np.testing.assert_allclose(got_rows, want_rows, rtol=1e-6, atol=1e-7)

    if "ckpt_restore" in legs:
        # --- cross-process-count restore (round-5, VERDICT r04 item 7):
        # resume a DIFFERENT federation's per-process saves under this
        # layout.  Any single old file must be cleanly rejected (its row
        # range matches neither the global nor this process's block);
        # assembling ALL of them reconstructs the exact global state, which
        # load_state_dict re-slices for this layout.
        import glob

        from dist_svgd_tpu.utils.checkpoint import assemble_full_state, load_state

        paths = sorted(glob.glob(os.path.join(outdir, "ckpt_rank*")))
        assert len(paths) not in (0, nprocs), (
            "ckpt_restore needs a previous federation's saves under a "
            f"different process count, found {len(paths)}"
        )
        single = make_w2_sampler()
        try:
            single.load_state_dict(load_state(paths[0]))
        except ValueError as e:
            assert "matches neither" in str(e), e
        else:
            raise AssertionError(
                "restoring one foreign-layout block must raise"
            )
        resumed = make_w2_sampler()
        resumed.load_state_dict(assemble_full_state(paths))
        resumed.run_steps(2, 0.1, h=0.5)
        rows, r_start = multihost.host_addressable_block(resumed.particles)
        np.save(os.path.join(outdir, f"cross_rows_{rank}.npy"), rows)
        np.save(os.path.join(outdir, f"cross_range_{rank}.npy"),
                np.array([int(r_start), rows.shape[0]]))


if __name__ == "__main__":
    main()
