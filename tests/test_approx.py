"""Sub-quadratic φ (ISSUE 13): random-feature / Nyström kernel
approximations as first-class sampler options, plus training-carry
donation.

Pins: exact-vs-approx φ agreement inside the declared error budget (dial
sweep), the budget calibration itself, shard invariance (1 vs 8 emulated
shards bitwise-on-seed in the gather mode), ring ≈ gather, chunked ≡
monolithic, checkpoint/reshard compatibility (bank key + landmark indices
ride ``state_dict``), composition refusals in one line each, the
``svgd_diag_phi_approx_*`` residual gauges, zero steady-state recompiles,
and donated ≡ undonated bitwise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.ops.approx import (
    KernelApprox,
    approx_preferred,
    as_kernel_approx,
    default_error_budget,
    error_pin_probe,
    make_approx_phi_fn,
    nystrom_landmark_indices,
    phi_rel_error,
    phi_residual_report,
)
from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn
from dist_svgd_tpu.ops.svgd import phi as phi_exact
from dist_svgd_tpu.utils import checkpoint as ck
from dist_svgd_tpu.utils.rng import approx_bank_key, init_particles

D = 2
N = 128


def dist_logp(theta, _data):
    return gmm_logp(theta)


def make_dist(num_shards, n=N, seed=0, p0=None, **kw):
    kw.setdefault("exchange_particles", True)
    kw.setdefault("exchange_scores", False)
    kw.setdefault("include_wasserstein", False)
    if p0 is None:
        p0 = init_particles(seed, n, D)
    return dt.DistSampler(num_shards, dist_logp, kw.pop("kernel", None), p0,
                          seed=seed, **kw)


# --------------------------------------------------------------------- #
# φ agreement at small n: the explicit error budget, dial sweep


@pytest.mark.parametrize("n,d", [(256, 3), (512, 8)])
def test_rff_error_inside_budget_and_improves_with_dial(n, d):
    x, s, kernel = error_pin_probe(n, d, seed=0)
    exact = phi_exact(x, x, s, kernel)
    errs = {}
    for num_features in (256, 4096):
        spec = KernelApprox("rff", num_features=num_features).with_key(
            approx_bank_key(0))
        err = phi_rel_error(exact, make_approx_phi_fn(kernel, spec)(x, x, s))
        assert err <= default_error_budget(spec, d), (num_features, err)
        errs[num_features] = err
    # the accuracy dial works: 16x the features cuts the error
    assert errs[4096] < errs[256]


@pytest.mark.parametrize("n,d", [(256, 3), (512, 8)])
def test_nystrom_error_inside_budget_and_exact_at_full_rank(n, d):
    x, s, kernel = error_pin_probe(n, d, seed=1)
    exact = phi_exact(x, x, s, kernel)
    errs = {}
    for num_landmarks in (64, n):
        spec = KernelApprox("nystrom", num_landmarks=num_landmarks)
        err = phi_rel_error(exact, make_approx_phi_fn(kernel, spec)(x, x, s))
        assert err <= default_error_budget(spec, d), (num_landmarks, err)
        errs[num_landmarks] = err
    # every row a landmark => exact recovery (up to the ridge)
    assert errs[n] < 1e-4
    assert errs[n] < errs[64]


def test_rff_bank_is_shared_and_deterministic():
    """Same key -> bitwise-identical φ; different key -> a different bank."""
    x, s, kernel = error_pin_probe(128, 3, seed=0)
    a = make_approx_phi_fn(kernel, KernelApprox("rff", 256).with_key(
        approx_bank_key(7)))(x, x, s)
    b = make_approx_phi_fn(kernel, KernelApprox("rff", 256).with_key(
        approx_bank_key(7)))(x, x, s)
    c = make_approx_phi_fn(kernel, KernelApprox("rff", 256).with_key(
        approx_bank_key(8)))(x, x, s)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_landmark_indices_strided_and_capped():
    idx = nystrom_landmark_indices(100, 32)
    assert len(idx) <= 32 and idx[0] == 0
    assert np.all(np.diff(idx) == idx[1] - idx[0])  # even stride
    np.testing.assert_array_equal(nystrom_landmark_indices(16, 32),
                                  np.arange(16))


# --------------------------------------------------------------------- #
# the resolve_phi_fn seam: crossover policy + refusals


def test_auto_crossover_picks_exact_below_and_approx_above():
    x, s, kernel = error_pin_probe(256, 3, seed=0)
    spec = KernelApprox("rff", num_features=4096).with_key(approx_bank_key(0))
    # 256 x 256 pairs << (256+256) x 8192 feature work -> exact
    assert not approx_preferred(256, 256, spec.feature_count)
    fn = resolve_phi_fn(kernel, "auto", 1, spec)
    np.testing.assert_array_equal(np.asarray(fn(x, x, s)),
                                  np.asarray(phi_exact(x, x, s, kernel)))
    # tiny dial at the same shape -> approximate wins
    small = KernelApprox("rff", num_features=16).with_key(approx_bank_key(0))
    assert approx_preferred(256, 256, small.feature_count)
    fn2 = resolve_phi_fn(kernel, "auto", 1, small)
    want = make_approx_phi_fn(kernel, small)(x, x, s)
    np.testing.assert_array_equal(np.asarray(fn2(x, x, s)), np.asarray(want))


def test_crossover_is_shard_invariant_through_batch_hint():
    # k_eff = k x batch_hint makes the decision a function of the global
    # shape: (n/S rows, hint S) == (n rows, hint 1)
    f = KernelApprox("rff", num_features=512).feature_count
    n = 4096
    for s_count in (1, 2, 8):
        assert (approx_preferred(n // s_count * s_count, n, f)
                == approx_preferred(n, n, f))


def test_refusals_are_one_line_each():
    with pytest.raises(ValueError, match="re-drawn|decalibrate"):
        resolve_phi_fn(dt.AdaptiveRBF(), "auto", 1, "rff")
    with pytest.raises(ValueError, match="no Pallas tier"):
        resolve_phi_fn(RBF(1.0), "pallas", 1, "nystrom")
    with pytest.raises(ValueError, match="bank key"):
        resolve_phi_fn(RBF(1.0), "xla", 1, "rff")  # no key bound
    with pytest.raises(ValueError, match="unknown kernel_approx"):
        as_kernel_approx("fourier")
    with pytest.raises(ValueError, match="RBF"):
        make_approx_phi_fn(lambda a, b: 1.0, KernelApprox("nystrom"))
    with pytest.raises(ValueError, match="jacobi"):
        dt.Sampler(D, gmm_logp, update_rule="gauss_seidel",
                   kernel_approx="nystrom")
    with pytest.raises(ValueError, match="jacobi"):
        make_dist(2, update_rule="gauss_seidel", kernel_approx="nystrom",
                  exchange_scores=False)
    with pytest.raises(ValueError, match="re-drawn|decalibrate"):
        make_dist(2, kernel="median_step", kernel_approx="rff")


def test_adaptive_bandwidth_composes_with_nystrom():
    ds = make_dist(2, kernel="median_step",
                   kernel_approx=KernelApprox("nystrom", num_landmarks=16),
                   phi_impl="xla")
    out = np.asarray(ds.run_steps(2, 0.05))
    assert np.all(np.isfinite(out))


# --------------------------------------------------------------------- #
# samplers: bandwidth freeze ordering, shard invariance, ring/chunked


def test_sampler_median_freezes_bandwidth_before_bank():
    """kernel='median' + rff: the bank must be built at the resolved median
    bandwidth — pinned by reproducing the run manually with the same bank
    at the median bandwidth (a bandwidth-1 bank diverges)."""
    s = dt.Sampler(D, gmm_logp, kernel="median", kernel_approx="rff",
                   phi_impl="xla")
    final, _ = s.run(N, 2, 0.05, seed=3, record=False)
    h = s._kernel.bandwidth
    assert h != 1.0  # the median actually resolved

    parts = init_particles(3, N, D)
    kernel = RBF(h)
    spec = KernelApprox("rff").with_key(approx_bank_key(3))
    fn = make_approx_phi_fn(kernel, spec)
    score = jax.vmap(jax.grad(gmm_logp))
    for _ in range(2):
        parts = parts + 0.05 * fn(parts, parts, score(parts))
    np.testing.assert_allclose(np.asarray(final), np.asarray(parts),
                               rtol=1e-5, atol=1e-7)


def test_sampler_auto_small_n_equals_exact():
    a, _ = dt.Sampler(D, gmm_logp).run(N, 3, 0.05, seed=0, record=False)
    s = dt.Sampler(D, gmm_logp, kernel_approx="rff")
    b, _ = s.run(N, 3, 0.05, seed=0, record=False)
    assert not s.kernel_approx_active  # 128² pairs << feature work
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_shard_invariance_bitwise_on_seed():
    """1 vs 8 emulated (vmap) shards, gather mode, same seed: the shared
    bank and the globally-pinned crossover make the trajectories BITWISE
    equal.  On the real shard_map mesh (8 host devices) per-device matmul
    partitioning re-associates float sums, so that backend pins to
    accumulation-order tolerance instead."""
    p0 = init_particles(0, N, D)
    outs = []
    for s_count in (1, 8):
        ds = make_dist(s_count, p0=p0, mesh=None, kernel_approx="rff",
                       phi_impl="xla")
        ds.run_steps(3, 0.05)
        outs.append(np.asarray(ds.particles))
    np.testing.assert_array_equal(outs[0], outs[1])

    ds = make_dist(8, p0=p0, kernel_approx="rff", phi_impl="xla")
    ds.run_steps(3, 0.05)
    np.testing.assert_allclose(outs[0], np.asarray(ds.particles),
                               rtol=0, atol=1e-5)


def test_ring_matches_gather_for_both_methods():
    p0 = init_particles(1, N, D)
    for method in ("rff", "nystrom"):
        spec = (KernelApprox("rff", num_features=256) if method == "rff"
                else KernelApprox("nystrom", num_landmarks=16))
        runs = []
        for impl in ("gather", "ring"):
            ds = make_dist(4, p0=p0, exchange_impl=impl, kernel_approx=spec,
                           phi_impl="xla")
            ds.run_steps(3, 0.05)
            runs.append(np.asarray(ds.particles))
        # ring accumulates per-block φ contributions (RFF: linear in the
        # interaction set — float-order only; Nyström: per-block landmark
        # sets, a blockwise approximation of the same dial)
        rtol = 1e-5 if method == "rff" else 0.3
        np.testing.assert_allclose(runs[0], runs[1], rtol=0, atol=rtol)


def test_chunked_equals_monolithic_with_approx():
    p0 = init_particles(2, N, D)
    ds = make_dist(4, p0=p0, exchange_impl="ring", kernel_approx="rff",
                   phi_impl="xla")
    mono = np.asarray(ds.run_steps(4, 0.05))
    ds2 = make_dist(4, p0=p0, exchange_impl="ring", kernel_approx="rff",
                    phi_impl="xla")
    chunked = np.asarray(ds2.run_steps(4, 0.05, hops_per_dispatch=1))
    assert ds2.last_run_stats["execution"] == "intra_step"
    np.testing.assert_allclose(mono, chunked, rtol=0, atol=1e-6)


def test_w2_sinkhorn_composes_with_approx():
    p0 = init_particles(4, N, D)
    ds = make_dist(4, p0=p0, include_wasserstein=True,
                   wasserstein_solver="sinkhorn",
                   kernel_approx=KernelApprox("nystrom", num_landmarks=16),
                   phi_impl="xla")
    out = np.asarray(ds.run_steps(3, 0.05, h=1.0))
    assert np.all(np.isfinite(out))
    assert ds.kernel_approx_active


# --------------------------------------------------------------------- #
# checkpoint / reshard compatibility


def test_state_dict_carries_bank_key_and_resume_is_bitwise():
    p0 = init_particles(0, N, D)
    a = make_dist(4, p0=p0, seed=7, kernel_approx="rff", phi_impl="xla")
    a.run_steps(3, 0.05)
    st = a.state_dict()
    assert st["approx_method"] is not None
    np.testing.assert_array_equal(np.asarray(st["approx_bank_key"]),
                                  np.asarray(approx_bank_key(7)))
    a.run_steps(3, 0.05)
    want = np.asarray(a.particles)

    b = make_dist(4, p0=p0, seed=7, kernel_approx="rff", phi_impl="xla")
    b.load_state_dict(st)
    b.run_steps(3, 0.05)
    np.testing.assert_array_equal(want, np.asarray(b.particles))

    # a foreign construction seed ADOPTS the saved bank: still bitwise
    c = make_dist(4, p0=p0, seed=99, kernel_approx="rff", phi_impl="xla")
    c.load_state_dict(st)
    c.run_steps(3, 0.05)
    np.testing.assert_array_equal(want, np.asarray(c.particles))


def test_nystrom_state_dict_carries_landmark_indices():
    ds = make_dist(4, kernel_approx=KernelApprox("nystrom", num_landmarks=32),
                   phi_impl="xla")
    st = ds.state_dict()
    np.testing.assert_array_equal(np.asarray(st["approx_landmark_idx"]),
                                  nystrom_landmark_indices(N, 32))


def test_approx_config_mismatches_refused():
    st = make_dist(4, seed=7, kernel_approx="rff", phi_impl="xla").state_dict()
    with pytest.raises(ValueError, match="nystrom.*rff|rff.*nystrom"):
        make_dist(4, kernel_approx="nystrom", phi_impl="xla").load_state_dict(st)
    with pytest.raises(ValueError, match="dial"):
        make_dist(4, kernel_approx=KernelApprox("rff", num_features=64),
                  phi_impl="xla").load_state_dict(st)
    with pytest.raises(ValueError, match="exact"):
        make_dist(4).load_state_dict(st)
    with pytest.raises(ValueError, match="exact"):
        make_dist(4, kernel_approx="rff",
                  phi_impl="xla").load_state_dict(make_dist(4).state_dict())


def test_reshard_state_passes_approx_entries_through():
    st = make_dist(4, seed=7, kernel_approx="rff", phi_impl="xla").state_dict()
    out = ck.reshard_state(dict(st), 2)
    np.testing.assert_array_equal(np.asarray(out["approx_bank_key"]),
                                  np.asarray(st["approx_bank_key"]))
    assert int(np.asarray(out["approx_method"])) == int(
        np.asarray(st["approx_method"]))


# --------------------------------------------------------------------- #
# residual gauges (the svgd_diag_* posterior-health channel)


def test_residual_report_and_gauges():
    from dist_svgd_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    ds = make_dist(4, kernel_approx="rff", phi_impl="xla")
    ds.run_steps(2, 0.05)
    report = ds.approx_residual(max_points=64, registry=reg)
    assert report["phi_approx_within_budget"] == 1.0
    assert report["n_eval"] <= 64
    text = reg.exposition()
    assert "svgd_diag_phi_approx_rel_err" in text
    assert "svgd_diag_phi_residual_total 1" in text

    with pytest.raises(ValueError, match="kernel_approx"):
        make_dist(4).approx_residual()


def test_sampler_residual_probe():
    from dist_svgd_tpu.telemetry import MetricsRegistry

    s = dt.Sampler(D, gmm_logp, kernel_approx="nystrom", phi_impl="xla")
    report = s.approx_residual(max_points=64, registry=MetricsRegistry())
    assert report["phi_approx_within_budget"] == 1.0


def test_sampler_residual_probe_does_not_mutate_live_state():
    """Review-caught: the probe must not rebind the live run's bank or
    re-pin its crossover from the probe subsample's tiny shape."""
    s = dt.Sampler(D, gmm_logp, kernel_approx=KernelApprox("rff", 16),
                   phi_impl="xla")
    s.run(N, 2, 0.05, seed=7, record=False)
    key_before = np.asarray(s.kernel_approx.key)
    assert s.kernel_approx_active
    report = s.approx_residual(max_points=32, seed=0)
    assert report["active"] is True  # reports the LIVE pin, not the probe's
    np.testing.assert_array_equal(key_before, np.asarray(s.kernel_approx.key))
    assert s.kernel_approx_active


def test_sampler_residual_probe_uses_median_bandwidth_for_adaptive():
    """Review-caught: a median_step run must be probed at the current
    median bandwidth, not RBF(1.0) — mirror DistSampler.approx_residual."""
    from dist_svgd_tpu.ops.kernels import median_bandwidth_approx
    from dist_svgd_tpu.ops.svgd import phi as phi_exact_fn

    s = dt.Sampler(D, gmm_logp, kernel="median_step",
                   kernel_approx=KernelApprox("nystrom", num_landmarks=16),
                   phi_impl="xla")
    probe = 4.0 * init_particles(0, 64, D)  # median bandwidth far from 1
    report = s.approx_residual(particles=probe, max_points=64)
    h = float(median_bandwidth_approx(probe))
    scores = jax.vmap(jax.grad(gmm_logp))(probe)
    want = phi_rel_error(
        phi_exact_fn(probe, probe, scores, RBF(h)),
        make_approx_phi_fn(RBF(h), KernelApprox("nystrom",
                                                num_landmarks=16))(
            probe, probe, scores))
    assert np.isclose(report["phi_approx_rel_err"], want, rtol=1e-6)


def test_load_adopts_saved_crossover_pin_in_partitions_mode():
    """Review-caught: the partitions-mode 'auto' crossover depends on the
    block size, so a resharded resume must adopt the SAVED pin instead of
    silently flipping φ backends at the new topology."""
    spec = KernelApprox("rff", num_features=16)  # F=32: active at S=2
    p0 = init_particles(0, N, D)

    def mk(s_count):
        return make_dist(s_count, p0=p0, exchange_particles=False,
                         kernel_approx=spec, phi_impl="auto")

    a = mk(2)
    assert a.kernel_approx_active  # 128·64 ≥ (128+64)·32
    st = a.state_dict()
    assert int(np.asarray(st["approx_active"])) == 1
    b = mk(8)
    assert not b.kernel_approx_active  # 128·16 < (128+16)·32 at S=8
    b.load_state_dict(ck.reshard_state(dict(st), 8))
    assert b.kernel_approx_active  # the saved pin won
    b.run_steps(2, 0.05)  # and the rebuilt programs run


def test_residual_report_shape_contract():
    x, s, kernel = error_pin_probe(256, 3, seed=0)
    spec = KernelApprox("rff", 1024).with_key(approx_bank_key(0))
    r = phi_residual_report(x, s, kernel, spec, max_points=64)
    assert r["n_eval"] == 64
    assert 0 <= r["phi_approx_rel_err"] <= r["phi_approx_budget"]


# --------------------------------------------------------------------- #
# steady state + donation


def test_zero_steady_state_recompiles_with_approx():
    from tools.jaxlint.sentry import retrace_sentry

    ds = make_dist(4, kernel_approx="rff", phi_impl="xla")
    ds.run_steps(2, 0.05)  # warm/compile
    with retrace_sentry("approx steady state") as sentry:
        for _ in range(3):
            ds.run_steps(2, 0.05)
    if sentry.supported:
        assert sentry.compiles == 0


@pytest.mark.parametrize("wasserstein", [False, True])
def test_distsampler_donation_bitwise(wasserstein):
    p0 = init_particles(0, N, D)
    runs = []
    for donate in (True, False):
        kw = dict(include_wasserstein=wasserstein)
        if wasserstein:
            kw["wasserstein_solver"] = "sinkhorn"
        ds = make_dist(4, p0=p0, donate_carries=donate, **kw)
        ds.run_steps(3, 0.05, h=1.0)
        ds.run_steps(3, 0.05, h=1.0)  # second call consumes donated state
        runs.append(np.asarray(ds.particles))
    np.testing.assert_array_equal(runs[0], runs[1])


def test_donation_does_not_invalidate_caller_buffers():
    p0 = init_particles(0, N, D)
    ds = make_dist(4, p0=p0, donate_carries=True)
    ds.run_steps(2, 0.05)
    np.asarray(p0)  # caller's array survives (constructor copied)

    s = dt.Sampler(D, gmm_logp, donate_carries=True)
    mine = init_particles(1, N, D)
    s.run(N, 2, 0.05, record=False, initial_particles=mine)
    out1 = np.asarray(mine)  # run() copied before donating
    s.run(N, 2, 0.05, record=False, initial_particles=mine)
    np.testing.assert_array_equal(out1, np.asarray(mine))


def test_sampler_donation_bitwise_with_record_and_chunks(monkeypatch):
    from dist_svgd_tpu.utils import history as _history

    # force the record path into chunked dispatches so the chunk chain's
    # carry donation is exercised too
    monkeypatch.setattr(_history, "record_chunk_steps", lambda n, d: 2)
    outs = []
    for donate in (True, False):
        s = dt.Sampler(D, gmm_logp, donate_carries=donate)
        final, hist = s.run(64, 5, 0.05, seed=0, record=True)
        outs.append((np.asarray(final), np.asarray(hist)))
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    np.testing.assert_array_equal(outs[0][1], outs[1][1])


def test_intra_step_chunk_donation_bitwise():
    p0 = init_particles(3, N, D)
    runs = []
    for donate in (True, False):
        ds = make_dist(4, p0=p0, exchange_impl="ring", donate_carries=donate)
        ds.run_steps(3, 0.05, hops_per_dispatch=2)
        runs.append(np.asarray(ds.particles))
    np.testing.assert_array_equal(runs[0], runs[1])


# --------------------------------------------------------------------- #
# perf-gate helpers (the TPU row's CPU-testable logic)


def test_approx_row_ok_gates():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    import large_n

    good = {"within_budget": True, "sentry_supported": True, "recompiles": 0,
            "wall_per_step_s": 0.5, "kernel_approx_active": True}
    ok, why = large_n.approx_row_ok(good)
    assert ok and not why
    for bad, frag in (
        (dict(good, within_budget=False), "budget"),
        (dict(good, recompiles=2), "recompile"),
        (dict(good, wall_per_step_s=float("nan")), "wall"),
        (dict(good, kernel_approx_active=False), "not active"),
    ):
        ok, why = large_n.approx_row_ok(bad)
        assert not ok and any(frag in w for w in why), (bad, why)


# --------------------------------------------------------------------- #
# per-step RFF bank re-draw (round 18): rff_redraw='step'


def test_rff_redraw_validation_and_identity():
    spec = KernelApprox("rff", num_features=64, rff_redraw="step")
    assert spec.rff_redraw == "step"
    assert spec.with_key(approx_bank_key(0)).rff_redraw == "step"
    # the bank lifetime is part of the compile-cache identity
    run_spec = KernelApprox("rff", num_features=64)
    assert spec.cache_token() != run_spec.cache_token()
    with pytest.raises(ValueError):
        KernelApprox("rff", rff_redraw="epoch")
    with pytest.raises(ValueError):
        KernelApprox("nystrom", rff_redraw="step")


def test_rff_step_phi_needs_bound_index():
    from dist_svgd_tpu.ops.approx import bind_phi_step

    spec = KernelApprox("rff", num_features=128,
                        rff_redraw="step").with_key(approx_bank_key(0))
    fn = make_approx_phi_fn(RBF(2.0), spec)
    assert fn.needs_step
    x, s, _ = error_pin_probe(64, D)
    with pytest.raises(ValueError, match="bind_phi_step"):
        fn(x, x, s)
    out0 = bind_phi_step(fn, 0)(x, x, s)
    out0b = bind_phi_step(fn, 0)(x, x, s)
    out1 = bind_phi_step(fn, 1)(x, x, s)
    assert np.array_equal(np.asarray(out0), np.asarray(out0b))
    assert not np.array_equal(np.asarray(out0), np.asarray(out1))
    # every step's fresh bank stays inside the declared budget
    exact = phi_exact(x, x, s, RBF(2.0))
    budget = default_error_budget(spec, D)
    for t in (0, 1, 7):
        err = phi_rel_error(exact, bind_phi_step(fn, t)(x, x, s))
        assert err <= budget
    # bind_phi_step is a no-op passthrough for step-free backends
    run_fn = make_approx_phi_fn(
        RBF(2.0), KernelApprox("rff", num_features=128,
                               key=approx_bank_key(0)))
    assert bind_phi_step(run_fn, 3) is run_fn


def test_median_step_rff_refusal_lifted_only_for_step_redraw():
    """The PR-12 one-line refusal stands at rff_redraw='run'; 'step'
    composes (the follow-up that PR named)."""
    from dist_svgd_tpu.ops.kernels import AdaptiveRBF

    with pytest.raises(ValueError, match="rff_redraw"):
        resolve_phi_fn(AdaptiveRBF(), "xla", 1,
                       KernelApprox("rff", key=approx_bank_key(0)))
    fn = resolve_phi_fn(
        AdaptiveRBF(), "xla", 1,
        KernelApprox("rff", num_features=64,
                     rff_redraw="step").with_key(approx_bank_key(0)))
    assert fn.needs_step


def test_median_step_rff_step_runs_and_is_deterministic():
    spec = KernelApprox("rff", num_features=64, rff_redraw="step")
    s1 = dt.Sampler(D, gmm_logp, kernel="median_step", phi_impl="xla",
                    kernel_approx=spec)
    f1, _ = s1.run(64, 5, 1e-2, seed=0, record=False)
    s2 = dt.Sampler(D, gmm_logp, kernel="median_step", phi_impl="xla",
                    kernel_approx=KernelApprox("rff", num_features=64,
                                               rff_redraw="step"))
    f2, _ = s2.run(64, 5, 1e-2, seed=0, record=False)
    assert np.array_equal(np.asarray(f1), np.asarray(f2))
    assert np.isfinite(np.asarray(f1)).all()


def test_step_redraw_differs_from_run_bank_and_segments_compose():
    """A re-drawn bank changes the trajectory vs the frozen bank, and a
    segmented drive (step_offset) folds the identical (bank_root, t)
    stream as the monolithic run — bitwise."""
    step_spec = KernelApprox("rff", num_features=64, rff_redraw="step")
    run_spec = KernelApprox("rff", num_features=64)
    fa, _ = dt.Sampler(D, gmm_logp, kernel=RBF(2.0), phi_impl="xla",
                       kernel_approx=run_spec).run(64, 5, 1e-2, seed=0,
                                                   record=False)
    fb, _ = dt.Sampler(D, gmm_logp, kernel=RBF(2.0), phi_impl="xla",
                       kernel_approx=step_spec).run(64, 5, 1e-2, seed=0,
                                                    record=False)
    assert not np.array_equal(np.asarray(fa), np.asarray(fb))
    mono, _ = dt.Sampler(D, gmm_logp, kernel=RBF(2.0), phi_impl="xla",
                         kernel_approx=step_spec).run(64, 6, 1e-2, seed=0,
                                                      record=False)
    seg = dt.Sampler(D, gmm_logp, kernel=RBF(2.0), phi_impl="xla",
                     kernel_approx=step_spec)
    p1, _ = seg.run(64, 3, 1e-2, seed=0, record=False)
    p2, _ = seg.run(64, 3, 1e-2, seed=0, record=False,
                    initial_particles=p1, step_offset=3)
    assert np.array_equal(np.asarray(mono), np.asarray(p2))


def test_step_redraw_distsampler_ring_gather_and_shard_invariance():
    """median_step × per-step-redraw RFF across the exchange seams:
    ring ≡ gather and 1-vs-4-shard bitwise invariance under the vmap
    emulation (``mesh=None`` — the legacy-XLA median_step+ring shard_map
    gate is orthogonal to the redraw and stays refused)."""
    spec = lambda: KernelApprox("rff", num_features=64, rff_redraw="step")
    p0 = init_particles(0, N, D)
    pg = make_dist(4, p0=p0, mesh=None, kernel="median_step",
                   phi_impl="xla", exchange_impl="gather",
                   kernel_approx=spec()).run_steps(4, 1e-2)
    pr = make_dist(4, p0=p0, mesh=None, kernel="median_step",
                   phi_impl="xla", exchange_impl="ring",
                   kernel_approx=spec()).run_steps(4, 1e-2)
    assert np.allclose(np.asarray(pg), np.asarray(pr), atol=1e-5)
    p1 = make_dist(1, p0=p0, mesh=None, kernel="median_step",
                   phi_impl="xla", exchange_impl="gather",
                   kernel_approx=spec()).run_steps(4, 1e-2)
    assert np.array_equal(
        np.asarray(p1).reshape(N, D),
        np.asarray(pg).reshape(N, D))  # bitwise shard invariance


def test_step_redraw_rides_state_dict_and_mismatch_refused():
    spec = KernelApprox("rff", num_features=64, rff_redraw="step")
    d = make_dist(2, kernel=RBF(2.0), phi_impl="xla", kernel_approx=spec)
    d.run_steps(2, 1e-2)
    state = d.state_dict()
    assert int(np.asarray(state["approx_rff_redraw"])) == 1
    d2 = make_dist(2, kernel=RBF(2.0), phi_impl="xla",
                   kernel_approx=KernelApprox("rff", num_features=64,
                                              rff_redraw="step"))
    d2.load_state_dict(state)
    d2.run_steps(1, 1e-2)
    mismatch = make_dist(2, kernel=RBF(2.0), phi_impl="xla",
                         kernel_approx=KernelApprox("rff", num_features=64))
    with pytest.raises(ValueError, match="rff_redraw"):
        mismatch.load_state_dict(state)
    # a pre-redraw checkpoint (field absent) restores as 'run' — and is
    # refused by a 'step' sampler
    legacy = {k: v for k, v in state.items() if k != "approx_rff_redraw"}
    run_sampler = make_dist(2, kernel=RBF(2.0), phi_impl="xla",
                            kernel_approx=KernelApprox("rff",
                                                       num_features=64))
    run_sampler.load_state_dict(legacy)
    step_sampler = make_dist(2, kernel=RBF(2.0), phi_impl="xla",
                             kernel_approx=KernelApprox(
                                 "rff", num_features=64,
                                 rff_redraw="step"))
    with pytest.raises(ValueError, match="rff_redraw"):
        step_sampler.load_state_dict(legacy)


def test_step_redraw_chunked_ring_hops_and_all_scores_refusal():
    spec = KernelApprox("rff", num_features=64, rff_redraw="step")
    p0 = init_particles(0, N, D)
    mono = make_dist(2, p0=p0, kernel=RBF(2.0), phi_impl="xla",
                     exchange_impl="ring", kernel_approx=spec
                     ).run_steps(2, 1e-2)
    chunked = make_dist(2, p0=p0, kernel=RBF(2.0), phi_impl="xla",
                        exchange_impl="ring", kernel_approx=spec
                        ).run_steps(2, 1e-2, hops_per_dispatch=1)
    assert np.array_equal(np.asarray(mono), np.asarray(chunked))
    from dist_svgd_tpu.parallel.exchange import make_chunked_ring_step_fns

    with pytest.raises(ValueError, match="rff_redraw"):
        make_chunked_ring_step_fns(
            dist_logp, RBF(2.0), "all_scores", 2, 0, 1.0,
            phi_impl="xla",
            kernel_approx=spec.with_key(approx_bank_key(0)))


def test_step_redraw_residual_report_probes_folded_bank():
    spec = KernelApprox("rff", num_features=256,
                        rff_redraw="step").with_key(approx_bank_key(0))
    x, s, kernel = error_pin_probe(96, D)
    r0 = phi_residual_report(x, s, kernel, spec, step=0)
    r5 = phi_residual_report(x, s, kernel, spec, step=5)
    assert r0["phi_approx_rel_err"] != r5["phi_approx_rel_err"]
    assert r0["phi_approx_within_budget"] == 1.0
    assert r5["phi_approx_within_budget"] == 1.0
