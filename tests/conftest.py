"""Test harness configuration.

Distributed-without-hardware (SURVEY.md §4): the TPU analog of the
reference's local-multiprocess fixture is XLA host emulation — 8 virtual CPU
devices, so the same Mesh/shard_map code paths run on any machine.

Environment note: this image boots every interpreter with an `axon` TPU PJRT
plugin pre-registered via sitecustomize and `JAX_PLATFORMS=axon` exported.
Tests must run on the virtual CPU mesh, so we (a) force the platform to cpu
through jax.config (the env var may be pre-set to axon), and (b) drop the
axon backend factory before any client initialises — leaving it registered
makes CPU-only init block on the TPU tunnel.

float64 is enabled so vectorised implementations can be compared against the
numpy oracle at tight tolerances.

**Hardware tier** (`DSVGD_TPU_TESTS=1 pytest tests -m tpu`): skips the CPU
forcing, leaves the real TPU backend in place, and runs ONLY the
``tpu``-marked tests (tests/test_tpu_kernels.py) — the real-Mosaic pinning of
the Pallas kernels that `interpret=True` cannot give.  In the default CPU
mode, ``tpu``-marked tests auto-skip; in TPU mode, everything else is
deselected (the CPU-mesh suite must not run against the tunnel).
"""

import json
import os

import pytest

TPU_TIER = os.environ.get("DSVGD_TPU_TESTS") == "1"

#: Per-test call-phase wall clock, collected for every test that ran this
#: session.  tests/test_wall_budget.py (reordered to run LAST below) FAILs
#: the tier if any non-slow test exceeds the budget — one runaway test is
#: how a 15-minute tier-1 budget dies quietly.
DURATIONS = {}
WALL_BUDGET_S = 15.0
#: Known-heavy tests with an explicit, named allowance.  Adding a line here
#: is a reviewed decision; the default budget never creeps to absorb one
#: outlier.  The 3-arm mini storm replays the same trace through three
#: controller configurations end to end — inherently ~3x a normal test.
WALL_BUDGET_ALLOW_S = {
    "tests/test_workload_replay.py::test_mini_storm_adaptive_arm_schema_and_gates": 25.0,
}
DURATIONS_ARTIFACT = os.path.join(os.path.dirname(__file__),
                                  ".test_durations.json")


def pytest_runtest_logreport(report):
    if report.when == "call":
        entry = DURATIONS.setdefault(
            report.nodeid, {"duration": 0.0,
                            "slow": "slow" in report.keywords})
        entry["duration"] += report.duration


def pytest_sessionfinish(session, exitstatus):
    # the --durations report, as a machine-readable artifact: slowest
    # first, so a budget regression names its culprit without a rerun
    rows = sorted(
        ({"test": nid, **meta} for nid, meta in DURATIONS.items()),
        key=lambda r: -r["duration"])
    try:
        with open(DURATIONS_ARTIFACT, "w") as f:
            json.dump({"wall_budget_s": WALL_BUDGET_S, "tests": rows}, f,
                      indent=1)
    except OSError:
        pass  # a read-only checkout must not fail the run

if not TPU_TIER:
    import _jax_env

    _jax_env.setup_cpu(device_count=8)

    import jax  # noqa: E402

    assert len(jax.devices("cpu")) >= 8, "expected 8 virtual CPU devices for mesh tests"


def pytest_collection_modifyitems(config, items):
    if TPU_TIER:
        skip = pytest.mark.skip(
            reason="DSVGD_TPU_TESTS=1 runs only the -m tpu hardware tier"
        )
        for item in items:
            if "tpu" not in item.keywords:
                item.add_marker(skip)
    else:
        skip = pytest.mark.skip(
            reason="real-TPU tier: run DSVGD_TPU_TESTS=1 pytest -m tpu on a TPU host"
        )
        for item in items:
            if "tpu" in item.keywords:
                item.add_marker(skip)
    # the wall-budget assertion must observe every other test's duration,
    # so it runs last regardless of collection order
    tail = [i for i in items if "test_wall_budget" in i.nodeid]
    if tail:
        items[:] = [i for i in items
                    if "test_wall_budget" not in i.nodeid] + tail
