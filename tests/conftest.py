"""Test harness configuration.

Distributed-without-hardware (SURVEY.md §4): the TPU analog of the
reference's local-multiprocess fixture is XLA host emulation — 8 virtual CPU
devices, so the same Mesh/shard_map code paths run on any machine.

Environment note: this image boots every interpreter with an `axon` TPU PJRT
plugin pre-registered via sitecustomize and `JAX_PLATFORMS=axon` exported.
Tests must run on the virtual CPU mesh, so we (a) force the platform to cpu
through jax.config (the env var may be pre-set to axon), and (b) drop the
axon backend factory before any client initialises — leaving it registered
makes CPU-only init block on the TPU tunnel.

float64 is enabled so vectorised implementations can be compared against the
numpy oracle at tight tolerances.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from jax._src import xla_bridge  # noqa: E402

xla_bridge._backend_factories.pop("axon", None)

assert len(jax.devices("cpu")) >= 8, "expected 8 virtual CPU devices for mesh tests"
