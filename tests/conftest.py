"""Test harness configuration.

Distributed-without-hardware (SURVEY.md §4): the TPU analog of the
reference's local-multiprocess fixture is XLA host emulation — 8 virtual CPU
devices, so the same Mesh/shard_map code paths run on any machine.

Environment note: this image boots every interpreter with an `axon` TPU PJRT
plugin pre-registered via sitecustomize and `JAX_PLATFORMS=axon` exported.
Tests must run on the virtual CPU mesh, so we (a) force the platform to cpu
through jax.config (the env var may be pre-set to axon), and (b) drop the
axon backend factory before any client initialises — leaving it registered
makes CPU-only init block on the TPU tunnel.

float64 is enabled so vectorised implementations can be compared against the
numpy oracle at tight tolerances.

**Hardware tier** (`DSVGD_TPU_TESTS=1 pytest tests -m tpu`): skips the CPU
forcing, leaves the real TPU backend in place, and runs ONLY the
``tpu``-marked tests (tests/test_tpu_kernels.py) — the real-Mosaic pinning of
the Pallas kernels that `interpret=True` cannot give.  In the default CPU
mode, ``tpu``-marked tests auto-skip; in TPU mode, everything else is
deselected (the CPU-mesh suite must not run against the tunnel).
"""

import os

import pytest

TPU_TIER = os.environ.get("DSVGD_TPU_TESTS") == "1"

if not TPU_TIER:
    import _jax_env

    _jax_env.setup_cpu(device_count=8)

    import jax  # noqa: E402

    assert len(jax.devices("cpu")) >= 8, "expected 8 virtual CPU devices for mesh tests"


def pytest_collection_modifyitems(config, items):
    if TPU_TIER:
        skip = pytest.mark.skip(
            reason="DSVGD_TPU_TESTS=1 runs only the -m tpu hardware tier"
        )
        for item in items:
            if "tpu" not in item.keywords:
                item.add_marker(skip)
    else:
        skip = pytest.mark.skip(
            reason="real-TPU tier: run DSVGD_TPU_TESTS=1 pytest -m tpu on a TPU host"
        )
        for item in items:
            if "tpu" in item.keywords:
                item.add_marker(skip)
