"""Test harness configuration.

Distributed-without-hardware (SURVEY.md §4): the TPU analog of the
reference's local-multiprocess fixture is XLA host emulation — 8 virtual CPU
devices, so the same Mesh/shard_map code paths run on any machine.

Environment note: this image boots every interpreter with an `axon` TPU PJRT
plugin pre-registered via sitecustomize and `JAX_PLATFORMS=axon` exported.
Tests must run on the virtual CPU mesh, so we (a) force the platform to cpu
through jax.config (the env var may be pre-set to axon), and (b) drop the
axon backend factory before any client initialises — leaving it registered
makes CPU-only init block on the TPU tunnel.

float64 is enabled so vectorised implementations can be compared against the
numpy oracle at tight tolerances.
"""

import _jax_env

_jax_env.setup_cpu(device_count=8)

import jax  # noqa: E402

assert len(jax.devices("cpu")) >= 8, "expected 8 virtual CPU devices for mesh tests"
