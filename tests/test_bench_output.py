"""The bench driver's stdout contract (round-5): ONE compact JSON line that
always fits the driver's 2,000-byte stdout tail and still carries every
headline + acceptance field.  Round 4's record lost its own headline number
to exactly this (VERDICT r04 "what's weak" item 1): the full JSON line grew
past the tail window and the front-printed ``value`` was truncated away.
These tests pin the compact summary against a record bulkier than any real
one, so convergence-table growth can never silently re-break the evidence
chain."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def _full_record(n_datasets=12, tier="16 passed, 250 deselected in 201.14s"):
    """A synthetic full bench record, deliberately bulkier than BENCH_r04's
    (which was ~2.9 kB and already overflowed the tail)."""
    conv = {}
    for i in range(n_datasets):
        conv[f"dataset_{i:02d}"] = {
            "sklearn_acc": 0.889, "target_acc": 0.879, "fold": i,
            "stepsize": 0.3, "seeds": 5, "unreached": 0,
            "steps_median": 10, "steps_min": 5, "steps_max": 15,
        }
    for label in bench.FLAGSHIP_CONV_ROWS:
        conv[label] = {
            "dataset": "banana", "sklearn_acc": 0.889, "target_acc": 0.879,
            "fold": 42, "stepsize": 0.3, "seeds": 5, "unreached": 1,
            "steps_median": 10, "steps_min": 10, "steps_max": 20,
        }
    return {
        "metric": "particle_updates_per_sec (BayesLR banana, 10k particles, "
                  "8-shard all_particles north star)",
        "value": 17514005.0,
        "unit": "updates/sec",
        "vs_baseline": 41601.0,
        "platform": "tpu",
        "n_particles": 10_000,
        "n_iters_measured": 500,
        "num_shards": 8,
        "emulated_shards": True,
        "wall_s": 0.285,
        "pairs_per_sec": 1.75e11,
        "phi_roofline_pairs_per_sec": 1.7514e11,
        "fraction_of_phi_roofline": 0.999,
        "covertype_acceptance": {"sklearn_acc": 0.8757, "target_acc": 0.8657,
                                 "steps_to_target": 300, "final_acc": 0.8761},
        "bnn_acceptance": {"bayesridge_rmse": 4.79, "steps_to_target": 150,
                           "final_rmse": 4.41},
        "covertype_bf16x3_updates_per_sec": 5310000.0,
        "covertype_f32_updates_per_sec": 3830000.0,
        "covertype_bf16x3_speedup": 1.39,
        "w2_sinkhorn_updates_per_sec": 775000.0,
        "w2_sinkhorn_ms_per_step": 12.91,
        "w2_streaming_100k_ms_per_step": 963.41,
        "single_device_updates_per_sec": 18884014.7,
        "single_device_wall_s": 0.265,
        "ref_headline_config_wall_s": 0.003,
        "ref_headline_config_ref_wall_s": 2007.11,
        "steps_to_target_acc_median": 10,
        "steps_to_target_acc_spread": [5, 15],
        "steps_to_target_acc_per_dataset_medians": [10] * n_datasets,
        "wall_to_target_acc_s": 0.008,
        "convergence": conv,
        "tpu_test_tier": tier,
    }


def test_compact_summary_fits_the_driver_tail_and_parses():
    out = _full_record()
    assert len(json.dumps(out)) > bench._MAX_STDOUT_BYTES  # the hazard is real
    line = json.dumps(bench._compact_summary(out))
    assert len(line) <= bench._MAX_STDOUT_BYTES
    back = json.loads(line)
    # the driver's metric contract, plus the round-5 evidence fields
    assert back["metric"] == "particle_updates_per_sec"
    assert back["value"] == 17514005.0
    assert back["unit"] == "updates/sec"
    assert back["vs_baseline"] == 41601.0
    assert back["fraction_of_phi_roofline"] == 0.999
    assert back["tpu_test_tier"].startswith("16 passed")
    assert back["covertype_acceptance"]["steps_to_target"] == 300
    assert back["bnn_acceptance"]["steps_to_target"] == 150
    # convergence compressed, not copied: per-row medians for the flagship
    # configs, totals for the dataset table
    assert back["convergence_rows"] == 15
    assert back["convergence_unreached_total"] == 3
    assert back["flagship_steps_median"] == {
        "w2": 10, "partitions": 10, "partitions_w2": 10,
    }


def test_compact_summary_drops_optional_keys_under_pressure():
    # a pathological record: enormous tier string (cannot be dropped — it is
    # the hardware evidence) squeezes the optional keys out instead
    out = _full_record(tier="NOT GREEN (exit 1): " + "x" * 1500)
    compact = bench._compact_summary(out)
    line = json.dumps(compact)
    assert len(line) <= bench._MAX_STDOUT_BYTES
    back = json.loads(line)
    for key in ("metric", "value", "unit", "vs_baseline", "tpu_test_tier",
                "steps_to_target_acc_median", "convergence_unreached_total"):
        assert key in back
    assert "detail" not in back  # first key dropped under pressure


def test_compact_summary_cpu_fallback_record():
    # the CPU-fallback record has no convergence dict and no TPU-only rows
    out = {
        "metric": "particle_updates_per_sec (...)", "value": 1.0,
        "unit": "updates/sec", "vs_baseline": 0.002, "platform": "cpu",
        "n_particles": 10_000, "num_shards": 8, "wall_s": 1.0,
        "steps_to_target_acc_median": None,
    }
    back = json.loads(json.dumps(bench._compact_summary(out)))
    assert back["value"] == 1.0
    assert back["convergence_rows"] is None
    assert back["convergence_unreached_total"] is None
    assert back["flagship_steps_median"] is None
