"""Resilience subsystem (dist_svgd_tpu/resilience/): supervised segmented
runs, bitwise-exact resume, retry/backoff, numerical guards with rollback +
step-size backoff, deterministic fault injection.  Everything runs on CPU
with injected faults, an injectable sleep, and (where needed) a manual
clock — no real signals or waits (the real-signal drills live in the slow
tier, tests/test_fault_drill.py)."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.resilience import (
    FaultPlan,
    GuardConfig,
    GuardViolation,
    HardKillAt,
    InjectNaNAt,
    PreemptAt,
    RaiseAt,
    RestartBudgetExhausted,
    RetryPolicy,
    RunSupervisor,
    SimulatedHardKill,
    SlowSegmentAt,
    TransientDispatchError,
    check_state,
)
from dist_svgd_tpu.utils.checkpoint import CheckpointManager
from dist_svgd_tpu.utils.metrics import JsonlLogger
from dist_svgd_tpu.utils.rng import init_particles_per_shard


def no_sleep(_s):
    pass


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def make_dist(n=32, num_shards=4, **kw):
    parts = init_particles_per_shard(0, n, 2, num_shards)
    kw.setdefault("exchange_particles", True)
    kw.setdefault("exchange_scores", False)
    kw.setdefault("include_wasserstein", False)
    return dt.DistSampler(num_shards, lambda th, _: gmm_logp(th), None,
                          parts, **kw)


def supervise(sampler, tmp_path, name, steps=12, eps=0.05, every=4, **kw):
    kw.setdefault("segment_steps", every)
    kw.setdefault("sleep", no_sleep)
    return RunSupervisor(sampler, steps, eps,
                         checkpoint_dir=os.path.join(str(tmp_path), name),
                         checkpoint_every=every, **kw)


def reference_final(tmp_path, steps=12, **kw):
    sup = supervise(make_dist(), tmp_path, "reference", steps=steps, **kw)
    assert sup.run()["status"] == "completed"
    return np.asarray(sup.particles)


# --------------------------------------------------------------------- #
# resume exactness (the acceptance pin, both sampler kinds)


# tier-1 keeps one preempt point (3 — mid-segment, the interesting
# non-boundary case); the boundary-exact and late variants are the same
# code path at ~2 s apiece and run in the slow tier (runtime-budget audit,
# round 11)
@pytest.mark.parametrize("preempt_step", [
    3,
    pytest.param(4, marks=pytest.mark.slow),
    pytest.param(7, marks=pytest.mark.slow),
])
def test_distsampler_preempt_resume_bitwise(tmp_path, preempt_step):
    """An injected preemption at an arbitrary step (honoured at the next
    boundary, like a real SIGTERM) then resume-from-latest reproduces the
    uninterrupted supervised run's final state BITWISE — the absolute
    segment grid guarantees the same sequence of run_steps programs."""
    want = reference_final(tmp_path)
    sup1 = supervise(make_dist(), tmp_path, "killed",
                     faults=FaultPlan(PreemptAt(preempt_step)))
    r1 = sup1.run()
    assert r1["status"] == "preempted"
    assert r1["t"] < 12 and r1["t"] >= preempt_step
    # signal-triggered checkpoint at the stop boundary
    mgr = CheckpointManager(os.path.join(str(tmp_path), "killed"))
    assert mgr.latest_step() == r1["t"]
    sup2 = supervise(make_dist(), tmp_path, "killed")
    r2 = sup2.run(resume=True)
    assert r2["status"] == "completed"
    assert r2["resumed_from"] == r1["t"]
    np.testing.assert_array_equal(want, np.asarray(sup2.particles))


def test_sampler_minibatched_preempt_resume_bitwise(tmp_path):
    """Single-device path: the minibatch key stream continues across
    segments (step_offset), so supervised == monolithic and the resumed
    run matches both bitwise."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 3)).astype(np.float32))
    t = jnp.asarray((rng.random(64) > 0.5).astype(np.float32))

    def make_s():
        return dt.Sampler(
            4, lambda th, batch: -0.5 * jnp.sum(th ** 2)
            + 0.0 * jnp.sum(batch[0]), data=(x, t), batch_size=8,
        )

    mono, _ = make_s().run(16, 12, 1e-2, seed=3, record=False)
    sup1 = supervise(make_s(), tmp_path, "a", n=16, seed=3, eps=1e-2)
    sup1.run()
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(sup1.particles))
    sup2 = supervise(make_s(), tmp_path, "b", n=16, seed=3, eps=1e-2,
                     faults=FaultPlan(PreemptAt(5)))
    assert sup2.run()["status"] == "preempted"
    sup3 = supervise(make_s(), tmp_path, "b", n=16, seed=3, eps=1e-2)
    assert sup3.run(resume=True)["status"] == "completed"
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(sup3.particles))


def test_sampler_step_offset_continues_stream():
    """Sampler.run(step_offset=k) is the resumable-drive primitive: two
    chunked calls reproduce the monolithic minibatch trajectory bitwise."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(40, 2)).astype(np.float32))
    s = dt.Sampler(3, lambda th, b: -0.5 * jnp.sum(th ** 2)
                   + 0.0 * jnp.sum(b), data=x, batch_size=5)
    whole, _ = s.run(8, 10, 1e-2, seed=7, record=False)
    part, _ = s.run(8, 6, 1e-2, seed=7, record=False)
    part, _ = s.run(8, 4, 1e-2, seed=7, record=False,
                    initial_particles=part, step_offset=6)
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(part))


def test_sampler_median_kernel_frozen_across_segments(tmp_path):
    """kernel='median' resolves ONCE from the run-initial particles: the
    supervised segmented run must match the monolithic run (which resolves
    from the same initial particles), and a resumed run re-pins the
    checkpointed bandwidth instead of re-resolving."""
    def make_s():
        return dt.Sampler(2, lambda th: -0.5 * jnp.sum(th ** 2),
                          kernel="median")

    mono, _ = make_s().run(10, 12, 0.1, seed=0, record=False)
    sup = supervise(make_s(), tmp_path, "m", n=10, seed=0, eps=0.1)
    sup.run()
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(sup.particles))
    sup2 = supervise(make_s(), tmp_path, "m2", n=10, seed=0, eps=0.1,
                     faults=FaultPlan(PreemptAt(5)))
    sup2.run()
    sup3 = supervise(make_s(), tmp_path, "m2", n=10, seed=0, eps=0.1)
    sup3.run(resume=True)
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(sup3.particles))


@pytest.mark.slow  # host-LP W2 is the exotic make_step-only path (~2.4 s)
def test_distsampler_w2_lp_supervised_resume(tmp_path):
    """The eager host-LP W2 path (make_step-only) supervises through the
    harness's make_step loop; preempt + resume stays bitwise (the W2
    previous-snapshot and step counter ride state_dict)."""
    def make_w2():
        return make_dist(n=8, num_shards=2, include_wasserstein=True,
                         wasserstein_solver="lp")

    ref = supervise(make_w2(), tmp_path, "wref", steps=6, every=2)
    ref.run()
    want = np.asarray(ref.particles)
    k1 = supervise(make_w2(), tmp_path, "wkill", steps=6, every=2,
                   faults=FaultPlan(PreemptAt(3)))
    assert k1.run()["status"] == "preempted"
    k2 = supervise(make_w2(), tmp_path, "wkill", steps=6, every=2)
    assert k2.run(resume=True)["status"] == "completed"
    np.testing.assert_array_equal(want, np.asarray(k2.particles))


# --------------------------------------------------------------------- #
# retry / backoff / budget


def test_retry_exponential_backoff_and_replay(tmp_path):
    want = reference_final(tmp_path)
    slept = []
    sup = supervise(make_dist(), tmp_path, "retry",
                    faults=FaultPlan(RaiseAt(4), RaiseAt(4)),
                    sleep=slept.append,
                    retry=RetryPolicy(max_restarts=3, backoff_base_s=0.5,
                                      backoff_factor=2.0))
    r = sup.run()
    assert r["status"] == "completed"
    assert r["restarts"] == 2
    assert slept == [0.5, 1.0]  # exponential in consecutive failures
    # the replayed trajectory is the uninterrupted one exactly
    np.testing.assert_array_equal(want, np.asarray(sup.particles))


def test_restart_budget_exhausted(tmp_path):
    sup = supervise(make_dist(), tmp_path, "budget",
                    faults=FaultPlan(RaiseAt(0), RaiseAt(0), RaiseAt(0)),
                    retry=RetryPolicy(max_restarts=2, backoff_base_s=0.0))
    with pytest.raises(RestartBudgetExhausted) as ei:
        sup.run()
    assert isinstance(ei.value.last_error, TransientDispatchError)


def test_backoff_delay_capped():
    rp = RetryPolicy(backoff_base_s=1.0, backoff_factor=10.0, max_backoff_s=5.0)
    assert rp.delay_s(1) == 1.0
    assert rp.delay_s(2) == 5.0


# --------------------------------------------------------------------- #
# guards: NaN rollback + step-size backoff


def test_nan_injection_rolls_back_and_backs_off(tmp_path):
    log_path = os.path.join(str(tmp_path), "events.jsonl")
    with JsonlLogger(path=log_path) as logger:
        sup = supervise(make_dist(), tmp_path, "nan",
                        guard=GuardConfig(backoff_factor=0.5),
                        faults=FaultPlan(InjectNaNAt(4)), logger=logger)
        r = sup.run()
    assert r["status"] == "completed"
    assert r["restarts"] == 1
    assert r["step_size"] == pytest.approx(0.025)  # 0.05 backed off once
    assert np.isfinite(np.asarray(sup.particles)).all()
    events = [json.loads(l) for l in open(log_path)]
    kinds = [e["event"] for e in events]
    assert "guard_violation" in kinds and "rollback" in kinds
    gv = next(e for e in events if e["event"] == "guard_violation")
    assert gv["nonfinite_entries"] > 0
    assert gv["new_step_size"] == pytest.approx(0.025)


def test_check_state_unit():
    ok = np.zeros((4, 2)) + 0.5
    report = check_state(ok, config=GuardConfig(max_particle_norm=10.0))
    assert report["nonfinite_entries"] == 0
    with pytest.raises(GuardViolation, match="non-finite"):
        check_state(np.array([[np.nan, 1.0]]))
    with pytest.raises(GuardViolation, match="norm exceeds"):
        check_state(np.full((3, 2), 100.0),
                    config=GuardConfig(max_particle_norm=1.0))
    # per-step displacement: 4 units over 2 steps = 2/step > 1
    with pytest.raises(GuardViolation, match="displacement"):
        check_state(np.full((2, 2), 4.0), prev=np.zeros((2, 2)), steps=2,
                    config=GuardConfig(max_step_norm=1.0))
    # NaN norms trip the norm guard even with the finite check off
    with pytest.raises(GuardViolation, match="norm exceeds"):
        check_state(np.array([[np.nan, 1.0]]),
                    config=GuardConfig(check_finite=False,
                                       max_particle_norm=10.0))


def test_guard_displacement_via_supervisor(tmp_path):
    """max_step_norm snapshots the pre-segment state and trips on a huge
    step size, backing ε off until the run completes."""
    sup = supervise(make_dist(), tmp_path, "diverge", eps=50.0, steps=4,
                    guard=GuardConfig(max_step_norm=1.0, backoff_factor=0.1),
                    retry=RetryPolicy(max_restarts=5, backoff_base_s=0.0))
    r = sup.run()
    assert r["status"] == "completed"
    assert r["restarts"] >= 1
    assert r["step_size"] < 50.0


# --------------------------------------------------------------------- #
# hard kill, corrupt-newest resume, slow-segment watchdog


def test_hard_kill_propagates_then_resume_bitwise(tmp_path):
    want = reference_final(tmp_path)
    sup = supervise(make_dist(), tmp_path, "hk",
                    faults=FaultPlan(HardKillAt(6)))
    with pytest.raises(SimulatedHardKill):
        sup.run()
    killed_at = sup.t
    assert killed_at < 12
    sup2 = supervise(make_dist(), tmp_path, "hk")
    r2 = sup2.run(resume=True)
    assert r2["resumed_from"] <= killed_at  # steps since last save replay
    np.testing.assert_array_equal(want, np.asarray(sup2.particles))


def test_resume_skips_corrupt_newest_checkpoint(tmp_path):
    """PR 2's corrupt-newest fallback, extended to the training path: a
    resume whose newest step dir was half-written falls back to the
    previous step, replays, and still lands bitwise on the uninterrupted
    final state."""
    want = reference_final(tmp_path)
    sup = supervise(make_dist(), tmp_path, "cc",
                    faults=FaultPlan(PreemptAt(6)))
    r = sup.run()
    assert r["status"] == "preempted" and r["t"] == 8
    # corrupt the newest step dir in place (half-written save shape)
    root = os.path.join(str(tmp_path), "cc")
    newest = os.path.join(root, "step_8")
    for name in os.listdir(newest):
        os.remove(os.path.join(newest, name))
    with open(os.path.join(newest, "garbage"), "w") as fh:
        fh.write("not a checkpoint")
    sup2 = supervise(make_dist(), tmp_path, "cc")
    with pytest.warns(UserWarning, match="skipping unloadable checkpoint"):
        r2 = sup2.run(resume=True)
    assert r2["status"] == "completed"
    assert r2["resumed_from"] == 4  # fell back past the corrupt step_8
    np.testing.assert_array_equal(want, np.asarray(sup2.particles))


def test_slow_segment_watchdog_manual_clock(tmp_path):
    clock = ManualClock()
    log_path = os.path.join(str(tmp_path), "slow.jsonl")
    with JsonlLogger(path=log_path) as logger:
        sup = supervise(make_dist(), tmp_path, "slow",
                        faults=FaultPlan(SlowSegmentAt(4, 9.0)),
                        clock=clock, slow_segment_warn_s=5.0, logger=logger)
        r = sup.run()
    assert r["status"] == "completed"
    events = [json.loads(l) for l in open(log_path)]
    slow = [e for e in events if e["event"] == "slow_segment"]
    assert len(slow) == 1 and slow[0]["wall_s"] >= 9.0
    assert r["max_segment_wall_s"] >= 9.0


# --------------------------------------------------------------------- #
# supervisor plumbing


def test_segment_and_checkpoint_events_logged(tmp_path):
    log_path = os.path.join(str(tmp_path), "ev.jsonl")
    with JsonlLogger(path=log_path) as logger:
        sup = supervise(make_dist(), tmp_path, "ev", logger=logger)
        r = sup.run()
    events = [json.loads(l) for l in open(log_path)]
    kinds = [e["event"] for e in events]
    assert kinds.count("segment") == r["segments"] == 3
    # initial baseline + one per cadence boundary (4, 8, 12)
    assert kinds.count("checkpoint") == r["checkpoints"] == 4
    assert kinds[-1] == "completed"
    assert r["checkpoint_overhead_frac"] >= 0


def test_fresh_run_clears_stale_root(tmp_path):
    root = os.path.join(str(tmp_path), "stale")
    mgr = CheckpointManager(root, every=4)
    mgr.save(999, {"particles": np.zeros((4, 2)), "t": np.asarray(999)})
    sup = supervise(make_dist(), tmp_path, "stale")
    sup.run()  # resume=False clears the stale step_999
    assert CheckpointManager(root).latest_step() == 12


def test_supervisor_argument_validation(tmp_path):
    with pytest.raises(ValueError, match="num_steps"):
        RunSupervisor(make_dist(), 0, 0.05)
    with pytest.raises(ValueError, match="requires n"):
        RunSupervisor(dt.Sampler(2, lambda th: -jnp.sum(th ** 2)), 4, 0.05)
    with pytest.raises(ValueError, match="not both"):
        RunSupervisor(make_dist(), 4, 0.05,
                      checkpoint_dir=str(tmp_path),
                      manager=CheckpointManager(str(tmp_path)))
    with pytest.raises(ValueError, match="segment_steps"):
        RunSupervisor(make_dist(), 4, 0.05, segment_steps=0)


def test_unmanaged_run_rolls_back_to_start(tmp_path):
    """No checkpointing: retry still recovers (in-memory run-start
    snapshot) and the trajectory stays the reference one."""
    want = reference_final(tmp_path)
    sup = RunSupervisor(make_dist(), 12, 0.05, segment_steps=4,
                        faults=FaultPlan(RaiseAt(8)), sleep=no_sleep)
    r = sup.run()
    assert r["status"] == "completed" and r["restarts"] == 1
    np.testing.assert_array_equal(want, np.asarray(sup.particles))


def test_fault_plan_fire_once_and_order():
    fired = []

    class Probe:
        def __init__(self, step, tag):
            self.step = step
            self.fired = False
            self.tag = tag

        def fire(self, ctx):
            fired.append(self.tag)

    class Ctx:
        t = 10

    plan = FaultPlan(Probe(5, "b"), Probe(1, "a"))
    plan.fire_due(Ctx())
    plan.fire_due(Ctx())  # spent faults stay spent
    assert fired == ["a", "b"]
    assert plan.exhausted


def test_rerun_resets_counters_and_budget(tmp_path):
    """A preempted supervisor re-run on the SAME object starts with fresh
    totals and a fresh restart budget (the preempt→resume pattern)."""
    sup = supervise(make_dist(), tmp_path, "rerun",
                    faults=FaultPlan(RaiseAt(0), PreemptAt(5)),
                    retry=RetryPolicy(max_restarts=1, backoff_base_s=0.0))
    r1 = sup.run()
    assert r1["status"] == "preempted" and r1["restarts"] == 1
    sup._faults = FaultPlan(RaiseAt(8))  # run 2 needs budget for one retry
    r2 = sup.run(resume=True)
    assert r2["status"] == "completed"
    assert r2["restarts"] == 1  # budget was NOT depleted by run 1
    # only run 2's work is counted: the RaiseAt fires before its segment
    # dispatches, so one successful segment (8→12) after the rollback
    assert r2["segments"] == 1
    assert r2["resumed_from"] == 8


# --------------------------------------------------------------------- #
# shared backoff (resilience/backoff.py, round 15): one implementation
# behind both the supervisor's RetryPolicy and the fleet router


def test_capped_delay_is_the_retrypolicy_schedule():
    """Extracting the schedule into backoff.capped_delay changed nothing:
    RetryPolicy.delay_s delegates and stays bit-identical."""
    from dist_svgd_tpu.resilience.backoff import capped_delay

    rp = RetryPolicy(backoff_base_s=0.5, backoff_factor=3.0,
                     max_backoff_s=10.0)
    for k in range(1, 8):
        assert rp.delay_s(k) == capped_delay(k, 0.5, 3.0, 10.0)
    assert capped_delay(1, 1.0, 2.0, 60.0) == 1.0
    assert capped_delay(4, 1.0, 2.0, 60.0) == 8.0
    assert capped_delay(50, 1.0, 2.0, 60.0) == 60.0  # capped
    assert capped_delay(0, 1.0, 2.0, 60.0) == 1.0   # clamps to 1-based


def test_backoff_jitter_bounded_and_deterministic():
    import random

    from dist_svgd_tpu.resilience.backoff import Backoff, capped_delay

    bo = Backoff(base_s=0.1, factor=2.0, max_s=5.0, jitter_frac=0.25,
                 rng=random.Random(7))
    for k in range(1, 12):
        d = bo.delay_s(k)
        exact = capped_delay(k, 0.1, 2.0, 5.0)
        assert (1 - 0.25) * exact <= d <= min((1 + 0.25) * exact, 5.0)
        assert d <= 5.0  # the cap survives jitter
    # deterministic under an injected seed
    a = [Backoff(jitter_frac=0.3, rng=random.Random(3)).delay_s(k)
         for k in range(1, 6)]
    b = [Backoff(jitter_frac=0.3, rng=random.Random(3)).delay_s(k)
         for k in range(1, 6)]
    assert a == b
    # jitter_frac=0 is the exact schedule (what the supervisor uses)
    zero = Backoff(base_s=1.0, factor=2.0, max_s=60.0)
    assert [zero.delay_s(k) for k in (1, 2, 3)] == [1.0, 2.0, 4.0]


def test_backoff_validation():
    from dist_svgd_tpu.resilience.backoff import Backoff

    with pytest.raises(ValueError, match="jitter_frac"):
        Backoff(jitter_frac=1.0)
    with pytest.raises(ValueError, match="factor"):
        Backoff(factor=0.5)
    with pytest.raises(ValueError, match="max_s"):
        Backoff(base_s=2.0, max_s=1.0)
    with pytest.raises(ValueError, match="base_s"):
        Backoff(base_s=-1.0)
