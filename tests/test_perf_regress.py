"""Noise-aware perf gating (tools/perf_regress.py, round 8): the pure
median+MAD judging helpers run on CPU; the measuring half needs the TPU and
is exercised by running the tool there."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

from perf_regress import (  # noqa: E402
    WINDOWED_ROWS,
    _mad,
    _median,
    incumbent_history,
    judge_row,
    missing_rows,
    record_result,
)


def test_median_and_mad():
    assert _median([3.0, 1.0, 2.0]) == 2.0
    assert _median([1.0, 2.0, 3.0, 4.0]) == 2.5
    assert _mad([1.0, 2.0, 3.0]) == 1.0
    assert _mad([5.0]) == 0.0


def test_incumbent_history_legacy_scalar_seeds_window():
    incumbents = {"north_star_ups": 100.0}
    assert incumbent_history(incumbents, "north_star_ups") == [100.0]
    assert incumbent_history(incumbents, "missing") == []


def test_incumbent_history_prefers_window():
    incumbents = {"k": 100.0, "_history": {"k": [90.0, 110.0, 100.0]}}
    assert incumbent_history(incumbents, "k") == [90.0, 110.0, 100.0]


def test_judge_row_no_incumbent():
    status, info = judge_row(50.0, [], 0.35, True)
    assert status == "NO_INCUMBENT"


def test_judge_row_tight_window_uses_tol():
    """A quiet window (MAD ≈ 0) keeps the plain tol band — the legacy
    single-point behaviour."""
    hist = [100.0, 100.0, 100.0]
    assert judge_row(100.0, hist, 0.35, True)[0] == "PASS"
    assert judge_row(70.0, hist, 0.35, True)[0] == "WARN"   # > tol/2 below
    assert judge_row(60.0, hist, 0.35, True)[0] == "FAIL"
    # lower-is-better orientation (ms/step rows)
    assert judge_row(160.0, hist, 0.35, False)[0] == "FAIL"
    assert judge_row(100.0, hist, 0.35, False)[0] == "PASS"


def test_judge_row_noisy_window_widens_band():
    """Pool noise is distinguishable from regression: a window whose own
    relative MAD exceeds tol/mad_scale widens the band, so a value inside
    the window's historical spread cannot FAIL."""
    hist = [60.0, 100.0, 140.0, 80.0, 120.0]  # median 100, MAD 20
    # band = max(0.35, 3*20/100) = 0.6 → FAIL only below 40
    status, info = judge_row(45.0, hist, 0.35, True)
    assert status != "FAIL"
    assert info["band"] == pytest.approx(0.6)
    assert judge_row(35.0, hist, 0.35, True)[0] == "FAIL"


def test_judge_row_band_capped():
    hist = [1.0, 100.0, 1000.0]
    _, info = judge_row(50.0, hist, 0.35, True)
    assert info["band"] <= 0.9


def test_record_result_window_and_median():
    incumbents = {"k": 100.0}
    for v in (90.0, 110.0, 120.0):
        record_result(incumbents, "k", v, window=3)
    # legacy scalar seeded the window, then trimmed to the newest 3
    assert incumbents["_history"]["k"] == [90.0, 110.0, 120.0]
    assert incumbents["k"] == 110.0  # scalar refreshed to the median


def test_record_result_fresh_key():
    incumbents = {}
    record_result(incumbents, "new", 5.0, window=8)
    assert incumbents["_history"]["new"] == [5.0]
    assert incumbents["new"] == 5.0


def test_record_result_rejects_bad_window():
    with pytest.raises(ValueError, match="window"):
        record_result({}, "k", 1.0, window=0)


def test_missing_rows_empty_incumbents_lists_every_windowed_row():
    assert missing_rows({}) == list(WINDOWED_ROWS)


def test_missing_rows_respects_history_and_legacy_scalars():
    inc = {"_history": {"north_star_ups": [100.0]}, "config1_ups": 5.0}
    missing = missing_rows(inc)
    assert "north_star_ups" not in missing    # window counts
    assert "config1_ups" not in missing       # legacy scalar counts
    assert "multihost_updates_per_s" in missing
    # order is the row print order, not alphabetical
    assert missing == [k for k in WINDOWED_ROWS if k in set(missing)]


def test_windowed_rows_include_the_multihost_gates():
    assert "multihost_ring_hop_wall_ms" in WINDOWED_ROWS
    assert "multihost_updates_per_s" in WINDOWED_ROWS
    assert len(WINDOWED_ROWS) == len(set(WINDOWED_ROWS))


def test_windowed_rows_include_the_rollout_gates():
    from perf_regress import UNCONDITIONAL_ROW_KEYS

    assert "rollout_promote_s" in WINDOWED_ROWS
    assert "shadow_overhead_frac" in WINDOWED_ROWS
    assert "rollout_promote_s" in UNCONDITIONAL_ROW_KEYS
    assert "shadow_overhead_frac" in UNCONDITIONAL_ROW_KEYS


def test_rollout_row_ok_gates():
    """Every unconditional canary_rollout gate fires on its own failure
    mode; a fully-green row passes."""
    import rollout_drill

    green = {
        "good": {"promoted": True, "stages": [0.02, 0.1, 0.5, 1.0]},
        "bad": {"rolled_back": True, "peak_fraction": 0.0,
                "max_exposure": 0.10, "checkpoint_reloads": 0,
                "incumbent_bitwise": True,
                "serving_generation_unchanged": True},
        "client": {"offered": 10, "completed": 10, "shed": 0,
                   "errors": 0, "lost": 0},
        "steady_state_recompiles": 0,
        "shadow_overhead_frac": 0.001, "shadow_overhead_max": 0.05,
    }
    ok, why = rollout_drill.row_ok(green)
    assert ok and why == []
    breakages = [
        (("good", "promoted"), False, "never reached full exposure"),
        (("client", "lost"), 2, "lost"),
        (("client", "errors"), 1, "errored"),
        (("steady_state_recompiles",), 3, "steady-state compile"),
        (("bad", "rolled_back"), False, "never rolled back"),
        (("bad", "peak_fraction"), 0.5, "exposure"),
        (("bad", "checkpoint_reloads"), 1, "checkpoint"),
        (("bad", "incumbent_bitwise"), False, "bitwise"),
        (("bad", "serving_generation_unchanged"), False, "generation"),
        (("shadow_overhead_frac",), 0.06, "critical path"),
    ]
    for path, value, needle in breakages:
        row = {k: (dict(v) if isinstance(v, dict) else v)
               for k, v in green.items()}
        if len(path) == 1:
            row[path[0]] = value
        else:
            row[path[0]][path[1]] = value
        ok, why = rollout_drill.row_ok(row)
        assert not ok
        assert any(needle in w for w in why), (path, why)


def test_window_metrics_classifies_mirrors_separately():
    """Shadow-mirrored dispatches are their own category: never client
    ok/shed/error/lost, never offered, never in goodput or latency."""
    from workload_replay import window_metrics

    recs = [
        {"t": 0.1, "rows": 4, "tenant": "a", "status": "ok",
         "lat_ms": 5.0},
        {"t": 0.2, "rows": 4, "tenant": "a", "status": "mirror",
         "lat_ms": None},
        {"t": 0.3, "rows": 4, "tenant": "a", "status": "shed",
         "lat_ms": None},
        {"t": 0.4, "rows": 4, "tenant": "a", "status": "mirror",
         "lat_ms": None},
        {"t": 0.5, "rows": 4, "tenant": "a", "status": "lost",
         "lat_ms": None},
    ]
    w = window_metrics(recs, 0.0, 1.0, good_ms=50.0)
    assert w["mirrors"] == 2
    assert w["offered"] == 3  # client traffic only
    assert w["completed"] == 1 and w["shed"] == 1 and w["lost"] == 1
    assert w["good"] == 1
    # the client accounting identity holds with mirrors excluded
    assert (w["completed"] + w["shed"] + w["errors"] + w["lost"]
            == w["offered"])


def test_mirror_counts_reads_rollout_counters():
    from workload_replay import mirror_counts

    from dist_svgd_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    assert mirror_counts(reg, "a") == {
        "mirrors": 0, "mirror_dropped": 0, "mirror_errors": 0}
    reg.counter("svgd_rollout_mirrors_total", "m").inc(3, tenant="a")
    reg.counter("svgd_rollout_mirror_dropped_total", "d").inc(1,
                                                              tenant="a")
    assert mirror_counts(reg, "a") == {
        "mirrors": 3, "mirror_dropped": 1, "mirror_errors": 0}
    assert mirror_counts(reg) == {
        "mirrors": 0, "mirror_dropped": 0, "mirror_errors": 0}
