"""Multi-host support (parallel/multihost.py), exercised single-process on
the 8-virtual-CPU-device fixture (SURVEY.md §4's distributed-without-hardware
stance: the mesh/sharding code paths are identical multi-host; only the
rendezvous differs)."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.parallel.mesh import AXIS, SHARD_MAP_LEGACY
from dist_svgd_tpu.parallel import multihost

# The CPU federation legs need cross-process collectives on the CPU backend,
# which jax < 0.5 does not implement (XlaRuntimeError: "Multiprocess
# computations aren't implemented on the CPU backend") — the capability the
# whole federation fixture exists to exercise.
needs_cpu_multiprocess = pytest.mark.skipif(
    SHARD_MAP_LEGACY,
    reason="jax < 0.5 CPU backend lacks multiprocess collectives",
)


def test_initialize_is_noop_single_process():
    # The test process has long since started the XLA backend, so auto-detect
    # cannot rendezvous any more: initialize() must degrade to single-process
    # loudly (RuntimeWarning), not crash.
    with pytest.warns(RuntimeWarning, match="continuing single-process"):
        assert multihost.initialize() is False
    assert jax.process_count() == 1


def test_initialize_explicit_coordinator_raises_when_too_late():
    # An explicit multi-host request that cannot be honored must never be
    # silently downgraded.
    with pytest.raises(RuntimeError):
        multihost.initialize(
            coordinator_address="definitely-not-a-host:1",
            num_processes=2,
            process_id=0,
        )


def test_make_particle_mesh_defaults_to_all_devices():
    mesh = multihost.make_particle_mesh()
    assert mesh.axis_names == (AXIS,)
    assert mesh.shape[AXIS] == len(jax.devices())


def test_make_particle_mesh_subset_and_overflow():
    mesh = multihost.make_particle_mesh(4)
    assert mesh.shape[AXIS] == 4
    with pytest.raises(ValueError, match="need"):
        multihost.make_particle_mesh(len(jax.devices()) + 1)


def test_process_local_rows_covers_everything_single_process():
    mesh = multihost.make_particle_mesh(8)
    start, count = multihost.process_local_rows(64, mesh)
    assert (start, count) == (0, 64)


def test_make_global_particles_row_sharded():
    mesh = multihost.make_particle_mesh(8)
    rows = np.arange(16 * 3, dtype=np.float64).reshape(16, 3)
    arr = multihost.make_global_particles(rows, mesh, n_global=16)
    assert arr.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(arr), rows)
    # rows are actually split over the mesh devices
    assert len(arr.sharding.device_set) == 8


def test_make_global_from_local_single_process():
    """The any-rank sibling of make_global_particles (used by the multi-host
    checkpoint restore for the (S, ., d) snapshot stack): single-process it
    is a sharded device_put of the full array, and a block that is not the
    whole array must be rejected (one process owns all rows here)."""
    mesh = multihost.make_particle_mesh(8)
    arr = np.arange(8 * 4 * 2, dtype=np.float64).reshape(8, 4, 2)
    out = multihost.make_global_from_local(arr, mesh, (8, 4, 2))
    np.testing.assert_array_equal(np.asarray(out), arr)
    assert len(out.sharding.device_set) == 8
    with pytest.raises(ValueError, match="single-process local block"):
        multihost.make_global_from_local(arr[:4], mesh, (8, 4, 2))


def test_replicate_places_full_value_everywhere():
    mesh = multihost.make_particle_mesh(8)
    val = np.arange(10.0)
    arr = multihost.replicate(val, mesh)
    np.testing.assert_array_equal(np.asarray(arr), val)
    assert arr.sharding.is_fully_replicated


def test_importing_framework_does_not_start_backend():
    """Multi-host contract: ``jax.distributed.initialize()`` must be the
    first JAX call, so importing any part of the framework (including the
    module-level ``gmm_logp`` parity instance) must not initialise the XLA
    backend.  Checked in a subprocess — this pytest process started its
    backend long ago."""
    import subprocess

    code = (
        "import jax\n"
        "from jax._src import xla_bridge as xb\n"
        "import dist_svgd_tpu\n"
        "from dist_svgd_tpu.models.gmm import gmm_logp\n"
        "from dist_svgd_tpu.models.logreg import logreg_logp\n"
        "import dist_svgd_tpu.models.bnn\n"
        "import dist_svgd_tpu.utils.datasets, dist_svgd_tpu.utils.checkpoint\n"
        "import dist_svgd_tpu.utils.metrics\n"
        "from dist_svgd_tpu.parallel import multihost\n"
        "assert not xb.backends_are_initialized(), 'import started the backend'\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=repo,
        capture_output=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]


def _run_federation(tmp_path, nprocs: int, devcount: int, legs: str):
    """Spawn ``nprocs`` mh_worker.py processes federated over a fresh local
    coordinator port; assert they all exit cleanly."""
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = os.path.join(os.path.dirname(__file__), "mh_worker.py")
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), str(nprocs),
             f"127.0.0.1:{port}", str(tmp_path), str(devcount), legs],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(nprocs)
    ]
    try:
        logs = [p.communicate(timeout=540)[0].decode() for p in procs]
    finally:
        # a worker that crashed pre-rendezvous leaves its peer blocked in
        # initialize(); never leak it past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log[-2000:]}"


def _assemble(tmp_path, nprocs: int, n: int, d: int, rows_tpl: str,
              range_tpl: str = "range_{}.npy") -> np.ndarray:
    got = np.empty((n, d), dtype=np.float32)
    for r in range(nprocs):
        start, count = np.load(tmp_path / range_tpl.format(r))
        got[start : start + count] = np.load(tmp_path / rows_tpl.format(r))
    return got


@needs_cpu_multiprocess
def test_two_process_federation_matches_oracle(tmp_path):
    """REAL multi-process coverage: two OS processes, 4 virtual CPU devices
    each, federated by ``jax.distributed`` into one 8-shard mesh.  Exercises
    the branches a single process cannot — cross-process rendezvous,
    ``make_array_from_process_local_data``, per-process ``process_local_rows``
    — and checks the distributed trajectory against a single-process oracle.
    """
    _run_federation(tmp_path, 2, 4, "gather,ring,lagged,ckpt")

    n, d = 32, 2
    got = _assemble(tmp_path, 2, n, d, "rows_{}.npy")

    full = np.random.default_rng(0).normal(size=(n, d)).astype(np.float32)
    ref = dt.DistSampler(
        8, lambda th, _: gmm_logp(th), None, full,
        exchange_particles=True, exchange_scores=True,
        include_wasserstein=False, mesh=multihost.make_particle_mesh(8),
    )
    want = np.asarray(ref.run_steps(5, 0.1))
    np.testing.assert_allclose(got, want, rtol=2e-6, atol=2e-7)

    # ppermute-ring exchange across the process boundary: every hop of the
    # two-pass all_scores ring rotates blocks between the two processes
    got_p = _assemble(tmp_path, 2, n, d, "ring_rows_{}.npy")
    ref_p = dt.DistSampler(
        8, lambda th, _: gmm_logp(th), None, full,
        exchange_particles=True, exchange_scores=True,
        include_wasserstein=False, exchange_impl="ring",
        mesh=multihost.make_particle_mesh(8),
    )
    want_p = np.asarray(ref_p.run_steps(4, 0.1))
    np.testing.assert_allclose(got_p, want_p, rtol=2e-6, atol=2e-7)

    # lagged exchange across the process boundary (one gather per T=2 steps)
    got_l = _assemble(tmp_path, 2, n, d, "lagged_rows_{}.npy")
    ref_l = dt.DistSampler(
        8, lambda th, _: gmm_logp(th), None, full,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False, exchange_every=2,
        mesh=multihost.make_particle_mesh(8),
    )
    want_l = np.asarray(ref_l.run_steps(4, 0.1))
    np.testing.assert_allclose(got_l, want_l, rtol=2e-6, atol=2e-7)


@needs_cpu_multiprocess
def test_four_process_federation_matches_oracle(tmp_path):
    """4-process federation, 2 virtual CPU devices per process — the
    granule-major hybrid mesh with >1 device per granule
    (``make_particle_mesh``'s ``create_hybrid_device_mesh`` branch, which
    the 2×4 fixture also hits but never at this granule count), plus a
    subset mesh (4 shards over 8 devices) exercising the equal-per-process
    ``take()`` selection.  Both trajectories must equal the single-process
    oracle — mesh layout is an execution detail, not semantics."""
    _run_federation(tmp_path, 4, 2, "gather,subset")

    n, d = 32, 2
    full = np.random.default_rng(0).normal(size=(n, d)).astype(np.float32)

    got = _assemble(tmp_path, 4, n, d, "rows_{}.npy")
    ref = dt.DistSampler(
        8, lambda th, _: gmm_logp(th), None, full,
        exchange_particles=True, exchange_scores=True,
        include_wasserstein=False, mesh=multihost.make_particle_mesh(8),
    )
    want = np.asarray(ref.run_steps(5, 0.1))
    np.testing.assert_allclose(got, want, rtol=2e-6, atol=2e-7)

    got_s = _assemble(tmp_path, 4, n, d, "subset_rows_{}.npy",
                      "subset_range_{}.npy")
    ref_s = dt.DistSampler(
        4, lambda th, _: gmm_logp(th), None, full,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False, mesh=multihost.make_particle_mesh(4),
    )
    want_s = np.asarray(ref_s.run_steps(4, 0.1))
    np.testing.assert_allclose(got_s, want_s, rtol=2e-6, atol=2e-7)


@needs_cpu_multiprocess
def test_cross_process_count_restore(tmp_path):
    """Cross-process-count restore (round-5, VERDICT r04 item 7): a
    4-process federation saves mid-trajectory (W2 on — the carried snapshot
    stack and dual ride along); a 2-process federation then resumes it.
    The mesh size (8 shards) — and therefore every global array — is
    process-layout-independent, so ``assemble_full_state`` over all four
    per-process blocks reconstructs the exact global state and the new
    layout re-slices it.  Any *single* foreign-layout block must raise the
    clear mismatch error instead (asserted inside the worker).  The resumed
    tail must equal the uninterrupted 4-process trajectory bit-for-bit
    (same program, different partitioning — mesh layout is an execution
    detail, not semantics)."""
    _run_federation(tmp_path, 4, 2, "ckpt")           # save at t=3, want at t=5
    _run_federation(tmp_path, 2, 4, "ckpt_restore")   # resume t=3 → t=5

    n, d = 32, 2
    want = _assemble(tmp_path, 4, n, d, "ckpt_want_rows_{}.npy",
                     "ckpt_want_range_{}.npy")
    got = _assemble(tmp_path, 2, n, d, "cross_rows_{}.npy",
                    "cross_range_{}.npy")
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_distsampler_runs_on_multihost_mesh():
    """The full driver recipe: build the granule-major mesh, assemble the global
    particle array from (this process's) local rows, run sharded steps."""
    mesh = multihost.make_particle_mesh(8)
    rng = np.random.default_rng(7)
    n, d = 32, 2
    start, count = multihost.process_local_rows(n, mesh)
    local = rng.normal(size=(count, d))
    particles = multihost.make_global_particles(local, mesh, n_global=n)

    sampler = dt.DistSampler(
        8, lambda th, _: gmm_logp(th), None, particles,
        exchange_particles=True, exchange_scores=True,
        include_wasserstein=False, mesh=mesh,
    )
    out = sampler.make_step(0.1)
    assert out.shape == (n, d)
    assert np.isfinite(np.asarray(out)).all()

    # equals the emulated (mesh=None) path on the same inputs
    ref = dt.DistSampler(
        8, lambda th, _: gmm_logp(th), None, local,
        exchange_particles=True, exchange_scores=True,
        include_wasserstein=False, mesh=None,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref.make_step(0.1)), rtol=1e-12, atol=1e-12
    )


# ---- round-19 cross-host additions ---------------------------------- #


def test_multiprocess_gap_matrix():
    """The capability probe: single-process and TPU requests are never
    gapped; an explicit CPU federation is gapped exactly on legacy jax,
    and the reason names both the installed and the required version."""
    assert multihost.multiprocess_gap(1) is None
    assert multihost.multiprocess_gap(None) is None
    assert multihost.multiprocess_gap(4, platform="tpu") is None
    gap = multihost.multiprocess_gap(2)
    if SHARD_MAP_LEGACY:
        assert gap is not None
        assert jax.__version__ in gap
        assert "jax>=0.5" in gap
    else:
        assert gap is None


@pytest.mark.skipif(
    not SHARD_MAP_LEGACY,
    reason="the up-front refusal only fires on the legacy-jax CPU gap",
)
def test_initialize_refuses_doomed_multiprocess_cpu():
    # An explicit CPU rendezvous that XLA would kill mid-run must refuse
    # BEFORE contacting the coordinator, naming the version gap — not a
    # connect timeout, not a mid-run XlaRuntimeError.
    with pytest.raises(RuntimeError, match="refusing the 2-process"):
        multihost.initialize(
            coordinator_address="127.0.0.1:1",
            num_processes=2,
            process_id=0,
        )


def test_mesh_process_layout_single_process():
    assert multihost.mesh_process_layout(
        multihost.make_particle_mesh(8)) == (1, (8,))
    assert multihost.mesh_process_layout(
        multihost.make_particle_mesh(1)) == (1, (1,))


def test_dcn_boundary_crossings_counts_granule_edges():
    class Dev:
        def __init__(self, p):
            self.process_index = p

    # degenerate sizes never cross
    assert multihost.dcn_boundary_crossings([]) == 0
    assert multihost.dcn_boundary_crossings([Dev(0)]) == 0
    # granule-major 2x2: exactly one boundary + the wrap
    assert multihost.dcn_boundary_crossings(
        [Dev(0), Dev(0), Dev(1), Dev(1)]) == 2
    # interleaved placement pays DCN on EVERY hop — the failure mode the
    # granule-major mesh ordering exists to avoid
    assert multihost.dcn_boundary_crossings(
        [Dev(0), Dev(1), Dev(0), Dev(1)]) == 4
    # in-process mesh: one granule, zero crossings
    assert multihost.dcn_boundary_crossings(
        multihost.make_particle_mesh(8)) == 0


def test_global_local_roundtrip_nondividing_rows():
    """Rows that do not divide the mesh must be REJECTED at placement (on
    legacy jax uneven row sharding raises at device_put — a silent pad
    would corrupt the checkpoint row accounting), while a ragged
    non-power-of-two mesh that does divide round-trips exactly."""
    rows = np.arange(10 * 2, dtype=np.float64).reshape(10, 2)
    with pytest.raises(ValueError, match="divisible"):
        multihost.make_global_from_local(
            rows, multihost.make_particle_mesh(8), (10, 2))
    mesh = multihost.make_particle_mesh(5)
    arr = multihost.make_global_from_local(rows, mesh, (10, 2))
    block, start = multihost.host_addressable_block(arr)
    assert start == 0
    np.testing.assert_array_equal(block, rows)


def test_global_local_roundtrip_single_device_mesh():
    """W=1 degeneracy: a one-device mesh is the trivial federation — the
    same driver recipe must round-trip unchanged."""
    mesh = multihost.make_particle_mesh(1)
    rows = np.arange(6 * 3, dtype=np.float64).reshape(6, 3)
    start, count = multihost.process_local_rows(6, mesh)
    assert (start, count) == (0, 6)
    arr = multihost.make_global_particles(rows, mesh, n_global=6)
    block, b_start = multihost.host_addressable_block(arr)
    assert b_start == 0
    np.testing.assert_array_equal(block, rows)


def test_ring_hops_per_step_accounting():
    from dist_svgd_tpu.parallel.exchange import (
        ALL_PARTICLES,
        ring_hops_per_step,
    )

    assert ring_hops_per_step(ALL_PARTICLES, 8) == {
        "hops": 7, "arrays_per_hop": 1}
    assert ring_hops_per_step("all_scores", 8) == {
        "hops": 15, "arrays_per_hop": 2}
    assert ring_hops_per_step("partitions", 8) == {
        "hops": 0, "arrays_per_hop": 0}
    assert ring_hops_per_step("all_particles", 1) == {
        "hops": 0, "arrays_per_hop": 0}
    with pytest.raises(ValueError):
        ring_hops_per_step("nonsense", 8)
