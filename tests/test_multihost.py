"""Multi-host support (parallel/multihost.py), exercised single-process on
the 8-virtual-CPU-device fixture (SURVEY.md §4's distributed-without-hardware
stance: the mesh/sharding code paths are identical multi-host; only the
rendezvous differs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.parallel.mesh import AXIS
from dist_svgd_tpu.parallel import multihost


def test_initialize_is_noop_single_process():
    # The test process has long since started the XLA backend, so auto-detect
    # cannot rendezvous any more: initialize() must degrade to single-process
    # loudly (RuntimeWarning), not crash.
    with pytest.warns(RuntimeWarning, match="continuing single-process"):
        assert multihost.initialize() is False
    assert jax.process_count() == 1


def test_initialize_explicit_coordinator_raises_when_too_late():
    # An explicit multi-host request that cannot be honored must never be
    # silently downgraded.
    with pytest.raises(RuntimeError):
        multihost.initialize(
            coordinator_address="definitely-not-a-host:1",
            num_processes=2,
            process_id=0,
        )


def test_make_particle_mesh_defaults_to_all_devices():
    mesh = multihost.make_particle_mesh()
    assert mesh.axis_names == (AXIS,)
    assert mesh.shape[AXIS] == len(jax.devices())


def test_make_particle_mesh_subset_and_overflow():
    mesh = multihost.make_particle_mesh(4)
    assert mesh.shape[AXIS] == 4
    with pytest.raises(ValueError, match="need"):
        multihost.make_particle_mesh(len(jax.devices()) + 1)


def test_process_local_rows_covers_everything_single_process():
    mesh = multihost.make_particle_mesh(8)
    start, count = multihost.process_local_rows(64, mesh)
    assert (start, count) == (0, 64)


def test_make_global_particles_row_sharded():
    mesh = multihost.make_particle_mesh(8)
    rows = np.arange(16 * 3, dtype=np.float64).reshape(16, 3)
    arr = multihost.make_global_particles(rows, mesh, n_global=16)
    assert arr.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(arr), rows)
    # rows are actually split over the mesh devices
    assert len(arr.sharding.device_set) == 8


def test_replicate_places_full_value_everywhere():
    mesh = multihost.make_particle_mesh(8)
    val = np.arange(10.0)
    arr = multihost.replicate(val, mesh)
    np.testing.assert_array_equal(np.asarray(arr), val)
    assert arr.sharding.is_fully_replicated


def test_distsampler_runs_on_multihost_mesh():
    """The full driver recipe: build the host-major mesh, assemble the global
    particle array from (this process's) local rows, run sharded steps."""
    mesh = multihost.make_particle_mesh(8)
    rng = np.random.default_rng(7)
    n, d = 32, 2
    start, count = multihost.process_local_rows(n, mesh)
    local = rng.normal(size=(count, d))
    particles = multihost.make_global_particles(local, mesh, n_global=n)

    sampler = dt.DistSampler(
        8, lambda th, _: gmm_logp(th), None, particles,
        exchange_particles=True, exchange_scores=True,
        include_wasserstein=False, mesh=mesh,
    )
    out = sampler.make_step(0.1)
    assert out.shape == (n, d)
    assert np.isfinite(np.asarray(out)).all()

    # equals the emulated (mesh=None) path on the same inputs
    ref = dt.DistSampler(
        8, lambda th, _: gmm_logp(th), None, local,
        exchange_particles=True, exchange_scores=True,
        include_wasserstein=False, mesh=None,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref.make_step(0.1)), rtol=1e-12, atol=1e-12
    )
