"""Model log-densities vs independent references (torch distributions) and
numeric gradients."""

import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu.models.gmm import gmm_logp, make_gmm_logp
from dist_svgd_tpu.models.logreg import (
    ensemble_test_accuracy,
    logreg_logp,
    make_logreg_logp,
    posterior_predictive_prob,
)


@pytest.fixture
def rng():
    return np.random.default_rng(17)


def test_gmm_logp_matches_manual():
    """log(1/3·N(-2,1) + 1/3·N(2,1)) — code weights, not the comment's 2/3
    (reference quirk, experiments/gmm.py:20-21)."""
    for v in (-2.0, 0.0, 1.7):
        want = math.log(
            (1 / 3) * math.exp(-0.5 * (v + 2) ** 2) / math.sqrt(2 * math.pi)
            + (1 / 3) * math.exp(-0.5 * (v - 2) ** 2) / math.sqrt(2 * math.pi)
        )
        got = float(gmm_logp(jnp.asarray([v])))
        assert got == pytest.approx(want, rel=1e-10)


def test_gmm_custom_weights_and_grad(rng):
    logp = make_gmm_logp(means=(-1.0, 3.0), scales=(0.5, 2.0), weights=(0.25, 0.75))
    x = jnp.asarray([0.3])
    g = float(jax.grad(logp)(x)[0])
    eps = 1e-6
    num = (float(logp(x + eps)) - float(logp(x - eps))) / (2 * eps)
    assert g == pytest.approx(num, rel=1e-4)


def test_logreg_logp_matches_torch(rng):
    """Independent check against the torch distributions the reference calls
    (experiments/logreg.py:38-39,53-57)."""
    torch = pytest.importorskip("torch")
    from torch.distributions.gamma import Gamma
    from torch.distributions.multivariate_normal import MultivariateNormal

    n_rows, k = 7, 3
    x = rng.normal(size=(n_rows, k))
    t = np.where(rng.normal(size=(n_rows, 1)) > 0, 1.0, -1.0)
    theta = rng.normal(size=(1 + k,))

    got = float(logreg_logp(jnp.asarray(theta), (jnp.asarray(x), jnp.asarray(t))))

    tx = torch.from_numpy(x)
    tt = torch.from_numpy(t)
    th = torch.from_numpy(theta)
    alpha = torch.exp(th[0])
    w = th[1:]
    want = Gamma(1.0, 1.0).log_prob(alpha)
    want = want + MultivariateNormal(torch.zeros(k), torch.eye(k) / alpha).log_prob(w)
    want = want - torch.log(1.0 + torch.exp(-1.0 * torch.mv(tt * tx, w))).sum()
    # torch.zeros/torch.eye default to float32, so torch's prior terms carry
    # ~1e-7 error; our float64 closed forms are the tighter computation.
    assert got == pytest.approx(float(want), rel=1e-6)


def test_make_logreg_logp_closure_equals_explicit_data(rng):
    x = rng.normal(size=(5, 2))
    t = np.where(rng.normal(size=5) > 0, 1.0, -1.0)
    theta = jnp.asarray(rng.normal(size=3))
    closed = make_logreg_logp(x, t)
    assert float(closed(theta)) == pytest.approx(
        float(logreg_logp(theta, (jnp.asarray(x), jnp.asarray(t)))), rel=1e-12
    )


def test_posterior_predictive_ignores_alpha(rng):
    """Reference quirk (logreg_plots.py:44-48): α decoded but unused."""
    x_test = rng.normal(size=(4, 2))
    p1 = np.concatenate([np.full((3, 1), -5.0), rng.normal(size=(3, 2))], axis=1)
    p2 = p1.copy()
    p2[:, 0] = +5.0  # wildly different alpha must not change predictions
    np.testing.assert_allclose(
        np.asarray(posterior_predictive_prob(jnp.asarray(p1), jnp.asarray(x_test))),
        np.asarray(posterior_predictive_prob(jnp.asarray(p2), jnp.asarray(x_test))),
    )


def test_ensemble_accuracy_perfect_separation():
    x_test = np.array([[1.0, 0.0], [-1.0, 0.0]])
    t_test = np.array([1.0, -1.0])
    particles = np.array([[0.0, 5.0, 0.0]])  # w = (5, 0) → classifies by sign(x0)
    acc = float(ensemble_test_accuracy(jnp.asarray(particles), jnp.asarray(x_test), jnp.asarray(t_test)))
    assert acc == 1.0


def test_logreg_split_equals_joint(rng):
    """likelihood + prior from make_logreg_split sums to logreg_logp exactly."""
    from dist_svgd_tpu.models.logreg import make_logreg_split

    x = jnp.asarray(rng.normal(size=(12, 4)))
    t = jnp.asarray(np.where(rng.normal(size=12) > 0, 1.0, -1.0))
    theta = jnp.asarray(rng.normal(size=5))
    lik, prior = make_logreg_split()
    joint = float(logreg_logp(theta, (x, t)))
    assert float(lik(theta, (x, t))) + float(prior(theta)) == pytest.approx(joint, rel=1e-12)
