"""Cross-process trace propagation + stitching (round 16): the trace
context, process-identity export headers, batcher/server trace threading,
and ``tools/trace_report.py --stitch`` (golden tree, orphan tolerance,
missing-anchor exit-2 contract)."""

import json
import os
import sys

import numpy as np
import pytest

from dist_svgd_tpu.serving import fleet
from dist_svgd_tpu.telemetry import trace as trace_mod
from dist_svgd_tpu.telemetry.metrics import MetricsRegistry
from dist_svgd_tpu.telemetry.trace import Tracer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import trace_report


@pytest.fixture
def global_tracer():
    tracer = trace_mod.enable()
    try:
        yield tracer
    finally:
        trace_mod.disable()


# --------------------------------------------------------------------- #
# trace context + process identity primitives


def test_trace_context_is_per_thread_and_restorable():
    import threading

    assert trace_mod.get_trace_context() is None
    prev = trace_mod.set_trace_context("abc")
    assert prev is None and trace_mod.get_trace_context() == "abc"
    seen = {}

    def other():
        seen["ctx"] = trace_mod.get_trace_context()

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert seen["ctx"] is None  # thread-local, never inherited
    trace_mod.set_trace_context(prev)
    assert trace_mod.get_trace_context() is None


def test_mint_trace_id_shape_and_uniqueness():
    ids = {trace_mod.mint_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(len(i) == 16 and int(i, 16) >= 0 for i in ids)


def test_chrome_export_carries_process_header(tmp_path):
    tracer = Tracer(registry=MetricsRegistry())
    tracer.set_process("replica", "r7")
    with tracer.span("a"):
        pass
    path = str(tmp_path / "t.json")
    tracer.export_chrome(path)
    doc = json.load(open(path))
    proc = doc["otherData"]["process"]
    assert proc["role"] == "replica" and proc["name"] == "r7"
    assert proc["pid"] == os.getpid()
    assert proc["anchor_trace_s"] == 0.0
    assert isinstance(proc["anchor_unix_s"], float)
    # the loader surfaces it
    loaded, spans, _ = trace_report.load_export(path)
    assert loaded["name"] == "r7" and len(spans) == 1


def test_set_process_only_if_default_never_clobbers():
    tracer = Tracer(registry=MetricsRegistry())
    tracer.set_process("router", "the-router")
    tracer.set_process("replica", "imposter", only_if_default=True)
    meta = tracer.process_meta()
    assert meta["role"] == "router" and meta["name"] == "the-router"


def test_tracer_drop_and_lane_metrics():
    reg = MetricsRegistry()
    tracer = Tracer(max_events=2, registry=reg)
    for i in range(5):
        with tracer.span("s"):
            pass
    assert tracer.dropped_events == 3
    # a saturated buffer is a scrapeable counter, not a silent property
    assert reg.counter("svgd_trace_dropped_total").value() == 3
    tracer2 = Tracer(registry=reg)
    tracer2.lane_tree("a", 0.0, 1.0)
    tracer2.lane_tree("b", 0.0, 1.0)  # overlaps → second lane
    tracer2.lane_tree("c", 2.0, 3.0)  # fits lane 0
    assert reg.gauge("svgd_trace_lanes").value() == 2


# --------------------------------------------------------------------- #
# batcher / engine propagation


def test_batcher_threads_trace_through_lane_tree(global_tracer, ):
    from dist_svgd_tpu.serving import MicroBatcher, PredictiveEngine

    rng = np.random.default_rng(0)
    parts = rng.normal(size=(16, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16,
                           registry=MetricsRegistry())
    eng.warmup()
    bat = MicroBatcher(eng.predict, max_batch=8, max_wait_ms=1.0,
                       registry=MetricsRegistry())
    try:
        x = rng.normal(size=(2, 4)).astype(np.float32)
        bat.submit(x, trace="feedbeef00000001").result(timeout=10)
        bat.submit(x).result(timeout=10)  # tracer on → id auto-minted
    finally:
        bat.close(drain=True)
    spans = [e for e in global_tracer.chrome_events() if e["ph"] == "X"]
    reqs = [e for e in spans if e["name"] == "serve.request"]
    traces = [r["args"].get("trace") for r in reqs]
    assert "feedbeef00000001" in traces
    assert all(t for t in traces)  # the trace-less submit minted its own
    # the engine's span picked the id up from the dispatch trace context
    eng_spans = [e for e in spans if e["name"] == "engine.predict"]
    assert "feedbeef00000001" in {e["args"].get("trace")
                                  for e in eng_spans}


def test_http_server_extracts_fleet_trace_header(global_tracer):
    import urllib.request

    from dist_svgd_tpu.serving import (MicroBatcher, PredictionServer,
                                       PredictiveEngine)

    rng = np.random.default_rng(0)
    parts = rng.normal(size=(16, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16,
                           registry=MetricsRegistry())
    eng.warmup()
    srv = PredictionServer(eng, port=0, max_wait_ms=1.0,
                           registry=MetricsRegistry()).start()
    try:
        req = urllib.request.Request(
            srv.url + "/predict",
            json.dumps({"inputs": [[0.1, 0.2, 0.3, 0.4]]}).encode(),
            {"Content-Type": "application/json",
             "X-Fleet-Trace": "cafe000000000002"})
        assert json.loads(urllib.request.urlopen(
            req, timeout=10).read())["outputs"]
    finally:
        srv.shutdown()
    spans = [e for e in global_tracer.chrome_events() if e["ph"] == "X"]
    for name in ("http.predict", "serve.request"):
        tagged = [e for e in spans if e["name"] == name
                  and e["args"].get("trace") == "cafe000000000002"]
        assert tagged, name


# --------------------------------------------------------------------- #
# stitching


def _run_fleet_and_export(tmp_path, n_requests=6, kill_one=True):
    """Route through a 2-replica loopback fleet under the global tracer,
    export router + replica traces, return (paths, served routes)."""
    tracer = trace_mod.enable()
    tracer.set_process("router", "router")
    rep_tracers = {r: Tracer(registry=MetricsRegistry())
                   for r in ("ra", "rb")}
    reps = {r: fleet.LoopbackReplica(r, tenants=["t0"],
                                     tracer=rep_tracers[r])
            for r in ("ra", "rb")}
    transport = fleet.FakeTransport(reps)
    router = fleet.FleetRouter(list(reps), transport=transport,
                               registry=MetricsRegistry(),
                               probe_interval_s=10.0)
    body = json.dumps({"inputs": [[0.1, 0.2]], "tenant": "t0"}).encode()
    served = 0
    for _ in range(n_requests):
        if router.route("t0", body).status == 200:
            served += 1
    if kill_one:
        victim = router.route("t0", body).replica
        served += 1
        transport.kill(victim)
        res = router.route("t0", body)  # retries to the survivor
        assert res.status == 200 and res.attempts > 1
        served += 1
    router.shutdown()
    tracer = trace_mod.disable()
    router_path = str(tmp_path / "router.json")
    tracer.export_chrome(router_path)
    paths = [router_path]
    for r, rt in rep_tracers.items():
        p = str(tmp_path / f"{r}.json")
        rt.export_chrome(p)
        paths.append(p)
    return paths, served


def test_stitch_golden_tree_with_retry_siblings(tmp_path):
    paths, served = _run_fleet_and_export(tmp_path)
    report = trace_report.stitch_files(paths)
    assert report["served_routes"] == served
    assert report["coverage"] == 1.0
    assert report["orphan_replica_traces"] == 0
    # the killed-replica request shows as ONE tree with sibling attempts:
    # a failed leg (transport error) and the serving leg with its
    # replica-side serve.request and a non-negative wire gap
    retry = [t for t in report["trees"] if len(t["attempts"]) > 1]
    assert report["retry_trees"] >= 1 and retry
    tree = retry[0]
    errors = [a for a in tree["attempts"] if "error" in a]
    serving = [a for a in tree["attempts"] if "serve" in a]
    assert errors and serving
    assert serving[0]["serve"]["wire_gap_ms"] >= 0.0
    # per-hop rows exist for every level of the stitched tree
    for hop in ("fleet.route", "fleet.attempt", "fleet.wire",
                "serve.request", "serve.dispatch"):
        assert report["hops"][hop]["count"] >= 1, hop


def test_stitch_duplicate_client_trace_ids_stay_separate_trees(tmp_path):
    """A client replaying one X-Fleet-Trace id across requests (the
    front door passes it through verbatim) must yield one tree PER
    route — never a merged pseudo-retry tree."""
    tracer = trace_mod.enable()
    tracer.set_process("router", "router")
    rep_tracer = Tracer(registry=MetricsRegistry())
    reps = {"ra": fleet.LoopbackReplica("ra", tenants=["t0"],
                                        tracer=rep_tracer)}
    transport = fleet.FakeTransport(reps)
    router = fleet.FleetRouter(["ra"], transport=transport,
                               registry=MetricsRegistry(),
                               probe_interval_s=10.0)
    body = json.dumps({"inputs": [[0.1, 0.2]], "tenant": "t0"}).encode()
    for _ in range(3):
        assert router.route("t0", body, trace="5717CKed00000bad").status \
            == 200
    router.shutdown()
    tracer = trace_mod.disable()
    paths = [str(tmp_path / "router.json"), str(tmp_path / "ra.json")]
    tracer.export_chrome(paths[0])
    rep_tracer.export_chrome(paths[1])
    report = trace_report.stitch_files(paths)
    assert report["router_routes"] == 3
    assert report["served_routes"] == 3
    assert report["coverage"] == 1.0
    # three single-attempt trees, NOT one three-attempt "retry" tree
    assert report["retry_trees"] == 0
    assert all(len(t["attempts"]) == 1 for t in report["trees"])


def test_stitch_orphan_replica_spans_reported_not_fatal(tmp_path):
    paths, _served = _run_fleet_and_export(tmp_path, kill_one=False)
    # a replica export whose ROUTER file is missing: fabricate a second
    # fleet's replica-only export and stitch it alongside
    stray = Tracer(registry=MetricsRegistry())
    stray.set_process("replica", "stray")
    stray.lane_tree("serve.request", 0.0, 0.001,
                    {"trace": "dead000000000009", "replica": "stray"})
    stray_path = str(tmp_path / "stray.json")
    stray.export_chrome(stray_path)
    report = trace_report.stitch_files(paths + [stray_path])
    assert report["coverage"] == 1.0  # the real fleet still fully joins
    assert report["orphan_replica_traces"] == 1


def test_stitch_missing_anchor_exits_2_with_one_line(tmp_path, capsys):
    paths, _ = _run_fleet_and_export(tmp_path, kill_one=False)
    # an old-format export: no otherData.process header at all
    legacy = str(tmp_path / "legacy.json")
    doc = json.load(open(paths[1]))
    del doc["otherData"]
    json.dump(doc, open(legacy, "w"))
    rc = trace_report.main(["--stitch", paths[0], legacy])
    err = capsys.readouterr().err
    assert rc == 2
    assert err.count("\n") == 1 and "process-identity header" in err
    # an anchor-less header is diagnosed just as cleanly
    doc = json.load(open(paths[1]))
    del doc["otherData"]["process"]["anchor_unix_s"]
    json.dump(doc, open(legacy, "w"))
    rc = trace_report.main(["--stitch", paths[0], legacy])
    err = capsys.readouterr().err
    assert rc == 2 and "clock anchor" in err


def test_stitch_requires_a_router_export(tmp_path, capsys):
    paths, _ = _run_fleet_and_export(tmp_path, kill_one=False)
    rc = trace_report.main(["--stitch", paths[1], paths[2]])
    err = capsys.readouterr().err
    assert rc == 2 and "router" in err


def test_stitch_cli_json_and_human(tmp_path, capsys):
    paths, served = _run_fleet_and_export(tmp_path)
    rc = trace_report.main(["--stitch"] + paths + ["--json", "--top", "2"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["coverage"] == 1.0 and len(doc["trees"]) <= 2
    rc = trace_report.main(["--stitch"] + paths)
    out = capsys.readouterr().out
    assert rc == 0 and "coverage 1.0000" in out and "fleet.wire" in out


def test_single_file_report_still_works_with_new_exports(tmp_path, capsys):
    paths, _ = _run_fleet_and_export(tmp_path, kill_one=False)
    rc = trace_report.main([paths[0], "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert "fleet.route" in doc["spans"]
