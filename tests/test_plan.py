"""Mesh-sharded serving (round 12): the unified ``parallel/plan.py``
compile entrypoint, the sharded ``PredictiveEngine`` dispatch path (pinned
against the single-device engine on the emulated 8-device CPU mesh),
reload-preserves-sharding, input-buffer donation, the opt-in bf16 serve
path, and the multi-lane ``MicroBatcher``.
"""

import threading
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dist_svgd_tpu.parallel.mesh import AXIS
from dist_svgd_tpu.parallel.plan import Plan, make_plan
from dist_svgd_tpu.serving import MicroBatcher, PredictiveEngine


@pytest.fixture
def rng():
    return np.random.default_rng(11)


@pytest.fixture(scope="module")
def plan8():
    plan = make_plan(8)
    assert plan.is_sharded, "conftest guarantees 8 virtual CPU devices"
    return plan


# --------------------------------------------------------------------- #
# Plan: construction, placement, compile


def test_make_plan_degrades_gracefully():
    assert make_plan(1).num_shards == 1
    assert not make_plan(1).is_sharded
    # more shards than devices: same graceful fallback make_mesh gives
    assert make_plan(10_000).num_shards == 1
    assert make_plan().num_shards == len(jax.devices())
    with pytest.raises(ValueError, match="num_shards"):
        make_plan(0)


def test_plan_rejects_foreign_axis():
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("replicas",))
    with pytest.raises(ValueError, match=AXIS):
        Plan(mesh)


def test_shard_ensemble_placement(plan8, rng):
    parts = rng.normal(size=(64, 3)).astype(np.float32)
    placed = plan8.shard_ensemble(parts)
    assert placed.sharding.spec == P(AXIS, None)
    np.testing.assert_array_equal(np.asarray(placed), parts)
    # single-device plan: pass-through, no committed placement forced
    solo = Plan(None).shard_ensemble(parts)
    np.testing.assert_array_equal(np.asarray(solo), parts)


def test_shard_ensemble_uneven_replicates_with_warning(plan8, rng):
    parts = rng.normal(size=(10, 3)).astype(np.float32)  # 10 % 8 != 0
    with pytest.warns(UserWarning, match="not divisible"):
        placed = plan8.shard_ensemble(parts)
    assert placed.sharding.spec == P()  # replicated, still correct
    np.testing.assert_array_equal(np.asarray(placed), parts)


def test_plan_compile_matches_plain_jit(plan8, rng):
    """The pjit layer is semantics-free: a closed-over sharded ensemble
    reduction compiled with explicit in/out shardings returns what the
    single-device jit of the same function returns."""
    parts = rng.normal(size=(32, 4)).astype(np.float32)
    sharded_parts = plan8.shard_ensemble(parts)

    def reduce_fn(p):
        def fn(x):
            return {"m": jnp.mean(x @ p.T, axis=1),
                    "v": jnp.var(x @ p.T, axis=1)}
        return fn

    x = rng.normal(size=(6, 4)).astype(np.float32)
    got = plan8.compile(reduce_fn(sharded_parts))(plan8.replicate(jnp.asarray(x)))
    want = Plan(None).compile(reduce_fn(jnp.asarray(parts)))(jnp.asarray(x))
    for k in ("m", "v"):
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-5, atol=1e-7)
        # outputs come back replicated — callers never see mesh layout
        assert got[k].sharding.spec == P()


# --------------------------------------------------------------------- #
# engine: sharded ≡ single-device agreement (the ISSUE-7 pin)


def _engines(model, parts, plan, **kw):
    single = PredictiveEngine(model, parts, min_bucket=4, max_bucket=16, **kw)
    sharded = PredictiveEngine(model, parts, min_bucket=4, max_bucket=16,
                               plan=plan, **kw)
    return single, sharded


def test_sharded_engine_matches_single_logreg(plan8, rng):
    parts = rng.normal(size=(64, 5)).astype(np.float32)
    single, sharded = _engines("logreg", parts, plan8)
    assert sharded.stats()["plan"]["sharded"] is True
    assert sharded.particles.sharding.spec == P(AXIS, None)
    for b in (1, 3, 7, 16):
        x = rng.normal(size=(b, 4)).astype(np.float32)
        a, s = single.predict(x), sharded.predict(x)
        for k in ("mean", "var"):
            np.testing.assert_allclose(s[k], a[k], rtol=1e-5, atol=1e-7)


def test_sharded_engine_matches_single_bnn(plan8, rng):
    from dist_svgd_tpu.models.bnn import num_params

    parts = rng.normal(size=(64, num_params(3, 4))).astype(np.float32)
    single, sharded = _engines("bnn", parts, plan8, n_features=3, n_hidden=4,
                               y_mean=1.5, y_std=2.0)
    x = rng.normal(size=(5, 3)).astype(np.float32)
    a, s = single.predict(x), sharded.predict(x)
    np.testing.assert_allclose(s["mean"], a["mean"], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(s["std"], a["std"], rtol=1e-5, atol=1e-6)


def test_sharded_engine_matches_single_gmm(plan8, rng):
    parts = rng.normal(size=(64, 3)).astype(np.float32)
    single, sharded = _engines("gmm", parts, plan8, kde_bandwidth=0.8)
    x = rng.normal(size=(6, 3)).astype(np.float32)
    np.testing.assert_allclose(
        sharded.predict(x)["log_density"], single.predict(x)["log_density"],
        rtol=1e-5, atol=1e-6)


def test_sharded_engine_steady_state_no_recompiles(plan8, rng):
    """The bucket-cache contract survives sharding: post-warmup mixed-size
    traffic triggers neither bucket misses nor raw XLA compiles (the
    retrace sentry sees pjit compiles exactly like jit ones)."""
    from tools.jaxlint.sentry import retrace_sentry

    parts = rng.normal(size=(64, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16,
                           plan=plan8)
    eng.warmup()
    misses = eng.stats()["bucket_misses"]
    with retrace_sentry("sharded steady state") as sentry:
        for b in (1, 2, 5, 9, 16, 3, 11):
            eng.predict(rng.normal(size=(b, 4)).astype(np.float32))
    assert eng.stats()["bucket_misses"] == misses
    if sentry.supported:
        assert sentry.compiles == 0


def test_engine_mesh_shorthand_and_arg_conflict(plan8, rng):
    parts = rng.normal(size=(64, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=8,
                           mesh=plan8.mesh)
    assert eng.stats()["plan"]["num_shards"] == 8
    with pytest.raises(ValueError, match="not both"):
        PredictiveEngine("logreg", parts, plan=plan8, mesh=plan8.mesh)


# --------------------------------------------------------------------- #
# reload keeps the topology (the de-shard regression)


def test_reload_preserves_sharding(plan8, rng):
    parts1 = rng.normal(size=(64, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts1, min_bucket=4, max_bucket=8,
                           plan=plan8)
    x = rng.normal(size=(5, 4)).astype(np.float32)
    eng.predict(x)
    # the hot-reload path hands the engine a HOST numpy array (what the
    # checkpoint watcher loads): the swap must re-place it on the mesh
    parts2 = rng.normal(size=(128, 5)).astype(np.float32)
    eng.reload(parts2, tag="gen2")
    assert eng.particles.sharding.spec == P(AXIS, None)
    ref = PredictiveEngine("logreg", parts2, min_bucket=4, max_bucket=8)
    np.testing.assert_allclose(eng.predict(x)["mean"],
                               ref.predict(x)["mean"], rtol=1e-5, atol=1e-7)


def test_reload_preserves_compute_dtype(rng):
    parts1 = rng.normal(size=(32, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts1, min_bucket=4, max_bucket=8,
                           dtype=jnp.bfloat16)
    eng.reload(rng.normal(size=(32, 5)).astype(np.float32))
    assert eng.stats()["dtype"] == "bfloat16"


# --------------------------------------------------------------------- #
# buffer donation (ROADMAP item 2, serve slice)


def test_donated_dispatch_unchanged_and_repeatable(rng):
    """Donation must be invisible in served values: identical requests
    give bitwise-identical responses call after call (the donated input
    buffer is rebuilt per call, never reused by the caller)."""
    parts = rng.normal(size=(32, 5)).astype(np.float32)
    donated = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=8)
    plain = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=8,
                             donate=False)
    assert donated.stats()["donate_inputs"] is True
    assert plain.stats()["donate_inputs"] is False
    x = rng.normal(size=(5, 4)).astype(np.float32)
    first = donated.predict(x)
    for _ in range(3):
        again = donated.predict(x)
        np.testing.assert_array_equal(again["mean"], first["mean"])
    np.testing.assert_array_equal(plain.predict(x)["mean"], first["mean"])


def test_donation_nag_suppressed_at_dispatch(plan8, rng):
    """The deliberate not-usable-donation nag (CPU backends, reduction
    outputs smaller than inputs) is suppressed by the plan's compiled
    wrapper around each donating program's lowering call — serving must
    not spam one warning per compiled bucket.  ``simplefilter('always')``
    overrides every ambient filter (incl. pytest.ini's ignore), so a
    captured nag here means the plan-layer suppression broke."""
    parts = rng.normal(size=(64, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=8,
                           plan=plan8)
    solo = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=8)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng.warmup()
        eng.predict(rng.normal(size=(3, 4)).astype(np.float32))
        solo.warmup()
    assert not [w for w in caught
                if "donated buffers" in str(w.message)], caught


# --------------------------------------------------------------------- #
# opt-in bf16 serve path


def test_bf16_engine_numerics_pinned_vs_f32(rng):
    """The low-precision path keeps an f32 wire format and lands within
    bf16's ~3 significant digits of the f32 engine (documented tolerance:
    rtol 5e-2, atol 2e-2 on logreg probabilities in [0, 1])."""
    parts = rng.normal(size=(128, 5)).astype(np.float32)
    f32 = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=8)
    bf16 = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=8,
                            dtype=jnp.bfloat16)
    assert bf16.stats()["dtype"] == "bfloat16"
    x = rng.normal(size=(7, 4)).astype(np.float32)
    a, b = f32.predict(x), bf16.predict(x)
    assert b["mean"].dtype == np.float32  # upcast inside the kernel
    np.testing.assert_allclose(b["mean"], a["mean"], rtol=5e-2, atol=2e-2)
    np.testing.assert_allclose(b["var"], a["var"], rtol=2e-1, atol=2e-2)


def test_bf16_sharded_composes(plan8, rng):
    parts = rng.normal(size=(64, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=8,
                           plan=plan8, dtype=jnp.bfloat16)
    assert eng.particles.sharding.spec == P(AXIS, None)
    assert eng.particles.dtype == jnp.bfloat16
    out = eng.predict(rng.normal(size=(3, 4)).astype(np.float32))
    assert out["mean"].dtype == np.float32 and out["mean"].shape == (3,)


def test_engine_rejects_non_float_dtype(rng):
    with pytest.raises(ValueError, match="float dtype"):
        PredictiveEngine("logreg",
                         rng.normal(size=(8, 3)).astype(np.float32),
                         dtype=jnp.int32)


# --------------------------------------------------------------------- #
# multi-lane batcher


def _echo(calls):
    def dispatch(x):
        calls.append(x.shape[0])
        return {"val": x[:, 0].copy()}
    return dispatch


def test_batcher_lanes_drain_shared_queue(rng):
    calls = []
    bat = MicroBatcher(_echo(calls), max_batch=4, lanes=3, max_wait_ms=1.0,
                       autostart=False)
    futs = [bat.submit(np.full((2, 1), i, np.float32)) for i in range(6)]
    bat.start()
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result(timeout=10)["val"], [i, i])
    st = bat.stats()
    assert st["lanes"] == 3
    assert sum(st["lane_batches"].values()) == st["batches"]
    assert sum(st["lane_requests"].values()) == st["requests"] == 6
    assert sum(st["lane_rows"].values()) == st["rows"] == 12
    bat.close()


def test_batcher_lane_metrics_labelled(rng):
    from dist_svgd_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    bat = MicroBatcher(_echo([]), max_batch=8, lanes=2, max_wait_ms=1.0,
                       registry=reg, autostart=False)
    futs = [bat.submit(np.ones((2, 1), np.float32)) for _ in range(4)]
    bat.start()
    for f in futs:
        f.result(timeout=10)
    bat.close()
    total = sum(
        reg.counter("svgd_serve_lane_batches_total").value(
            batcher=bat.metrics_instance, lane=f"l{i}")
        for i in range(2)
    )
    assert total == bat.stats()["batches"] > 0
    # the in-flight gauge exists per active lane and reads 0 when drained
    for i in range(2):
        if reg.gauge("svgd_serve_lane_inflight_rows").has(
                batcher=bat.metrics_instance, lane=f"l{i}"):
            assert reg.gauge("svgd_serve_lane_inflight_rows").value(
                batcher=bat.metrics_instance, lane=f"l{i}") == 0


def test_batcher_validates_lanes():
    with pytest.raises(ValueError, match="lanes"):
        MicroBatcher(lambda x: {}, lanes=0, autostart=False)


def test_split_requests_across_lanes_resolve_once(rng):
    """Regression (round-12 review): the chunks of one oversize request
    can finish in DIFFERENT lanes concurrently — reassembly must count
    and resolve the request exactly once (pre-fix, both lanes could
    observe completion: double-counted stats and an InvalidStateError
    killing a lane thread)."""
    import time as _time

    def slow_echo(x):
        _time.sleep(0.002)  # widen the window where both lanes are live
        return {"val": x[:, 0].copy()}

    n_req = 24
    bat = MicroBatcher(slow_echo, max_batch=8, lanes=2, max_wait_ms=0.0,
                       autostart=False)
    futs = [bat.submit(np.arange(16, dtype=np.float32)[:, None])
            for _ in range(n_req)]  # every request splits into 2 chunks
    bat.start()
    for f in futs:
        np.testing.assert_array_equal(f.result(timeout=30)["val"],
                                      np.arange(16))
    st = bat.stats()
    assert st["requests"] == n_req  # exactly once each, no double count
    assert sum(st["lane_requests"].values()) == n_req
    # both lane threads survived (an InvalidStateError would have killed
    # one: close() would then hang on a dead lane's unfinished queue)
    assert all(t.is_alive() for t in bat._threads)
    bat.close()


def test_lanes_over_sharded_engine_concurrent_correctness(plan8, rng):
    """The full tentpole topology in one box: 8-way-sharded ensemble
    behind 2 dispatch lanes under concurrent submitters — every response
    matches the single-device engine."""
    parts = rng.normal(size=(64, 5)).astype(np.float32)
    sharded = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16,
                               plan=plan8)
    sharded.warmup()
    ref = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16)
    bat = MicroBatcher(sharded.predict, max_batch=16, lanes=2,
                       max_wait_ms=1.0)
    xs = [rng.normal(size=(1 + i % 5, 4)).astype(np.float32)
          for i in range(12)]
    errs = []

    def fire(x, out):
        try:
            out.append(bat.submit(x).result(timeout=30))
        except Exception as e:  # pragma: no cover - failure surface
            errs.append(e)

    outs = [[] for _ in xs]
    threads = [threading.Thread(target=fire, args=(x, o))
               for x, o in zip(xs, outs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    bat.close()
    assert not errs
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(o[0]["mean"], ref.predict(x)["mean"],
                                   rtol=1e-5, atol=1e-7)


def test_server_reports_topology_and_serves_sharded(plan8, rng):
    """HTTP front end over the full topology: /healthz reports devices +
    lanes, and /predict round-trips through the sharded engine."""
    import json
    import urllib.request

    from dist_svgd_tpu.serving import PredictionServer

    parts = rng.normal(size=(64, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16,
                           plan=plan8)
    ref = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16)
    x = rng.normal(size=(3, 4)).astype(np.float32)
    with PredictionServer(eng, port=0, lanes=2, max_batch=16,
                          max_wait_ms=1.0) as srv:
        health = json.loads(urllib.request.urlopen(
            srv.url + "/healthz", timeout=10).read())
        assert health["devices"] == 8 and health["lanes"] == 2
        req = urllib.request.Request(
            srv.url + "/predict",
            json.dumps({"inputs": x.tolist()}).encode(),
            {"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(
            req, timeout=10).read())["outputs"]
        np.testing.assert_allclose(out["mean"], ref.predict(x)["mean"],
                                   rtol=1e-5, atol=1e-7)


# --------------------------------------------------------------------- #
# serve_bench emits the serve_sharded row


def test_serve_bench_sharded_row_schema():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import serve_bench

    row = serve_bench.run_bench(
        model="logreg", n_particles=64, n_features=4, clients=4, requests=30,
        rows=(1, 4), max_batch=16, max_wait_ms=1.0, devices=8, lanes=2,
    )
    assert row["metric"] == "serve_sharded"
    assert row["devices"] == 8 and row["lanes"] == 2
    assert row["value"] > 0
    assert row["recompiles"] == 0
    assert row["sentry_compiles"] in (0, None)
    fairness = row["lane_fairness"]
    assert fairness["lanes"] == 2
    assert set(fairness["requests"]) == {"l0", "l1"}
    assert sum(fairness["requests"].values()) >= 30  # + open-loop none here
    assert set(fairness["inflight_rows_last"]) == {"l0", "l1"}
    import json as _json

    _json.dumps(row)
