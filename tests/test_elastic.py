"""Elastic capacity (ISSUE 8): reshardable checkpoints, mesh shrink/grow
resume under the supervisor's restart budget, and device-loss drills.

The reshard-equivalence suite pins: a run checkpointed at N=8 shards and
resumed at M ∈ {4, 2, 1} (and the grow direction 2 → 8) reproduces the
never-resharded run's trajectory — posterior stats (KSD/ESS) and the
replicated hyperparameters (step counter, step size, RNG root, pairing
code) bitwise, particles to float accumulation-order tolerance (the
per-shard φ reductions re-associate across shard counts, measured ~1e-7
at this scale).  Everything runs tier-1 on CPU with injected topology
faults — no real device loss.
"""

import os

import numpy as np
import pytest

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.resilience import (
    DeviceLossAt,
    FaultPlan,
    MeshGrowAt,
    MeshShrinkAt,
    ReshardPolicy,
    RestartBudgetExhausted,
    RetryPolicy,
    RunSupervisor,
    TopologyFault,
)
from dist_svgd_tpu.utils import checkpoint as ck
from dist_svgd_tpu.utils.rng import init_particles_per_shard

N = 64
D = 2
#: particle tolerance across shard counts (accumulation-order float noise;
#: bitwise is pinned for the replicated hyperparameters instead)
ATOL = 1e-5


def make_dist(num_shards, n=N, seed=0, **kw):
    kw.setdefault("exchange_particles", True)
    kw.setdefault("exchange_scores", False)
    kw.setdefault("include_wasserstein", False)
    return dt.DistSampler(
        num_shards, lambda th, _: gmm_logp(th), None,
        init_particles_per_shard(seed, n, D, num_shards), **kw)


def factory(num_shards):
    return make_dist(num_shards)


def supervise(sampler, tmp_path, name, steps=12, every=4, seg=2, **kw):
    kw.setdefault("segment_steps", seg)
    kw.setdefault("sleep", lambda s: None)
    return RunSupervisor(sampler, steps, 0.05,
                         checkpoint_dir=os.path.join(str(tmp_path), name),
                         checkpoint_every=every, **kw)


def diag_stats(particles, num_shards):
    import jax

    from dist_svgd_tpu.telemetry import MetricsRegistry
    from dist_svgd_tpu.telemetry.diagnostics import (
        DiagnosticsConfig,
        PosteriorDiagnostics,
    )

    diag = PosteriorDiagnostics(
        DiagnosticsConfig(every_steps=1, score_fn=jax.grad(gmm_logp),
                          row_chunk=64, max_points=64),
        registry=MetricsRegistry())
    return diag.compute(particles, num_shards=num_shards, step=0)


# --------------------------------------------------------------------- #
# topology manifest + TopologyMismatch (satellite 1)


def test_state_dict_carries_manifest_and_rng_root():
    ds = make_dist(4)
    st = ds.state_dict()
    man = ck.read_manifest(st)
    assert man["n_shards"] == 4
    assert man["n_particles"] == N and man["d"] == D
    assert man["data_rows_per_shard"] == 0
    np.testing.assert_array_equal(man["particles_per_shard"],
                                  np.full(4, N // 4))
    np.testing.assert_array_equal(np.asarray(st["rng_batch_key"]),
                                  np.asarray(ds._batch_key))


def test_manifest_survives_save_load_and_expect_check(tmp_path):
    ds = make_dist(8)
    ds.run_steps(4, 0.05)
    path = ck.save_state(os.path.join(str(tmp_path), "cp"), ds.state_dict(),
                         backend="npz")
    # matching expectation loads fine
    st = ck.load_state(path, expect_topology={"n_shards": 8,
                                              "n_particles": N, "d": D})
    assert ck.read_manifest(st)["n_shards"] == 8
    # a mismatch raises BEFORE any array op, naming both shapes and the fix
    with pytest.raises(ck.TopologyMismatch, match="n_shards=8.*n_shards=4"):
        ck.load_state(path, expect_topology={"n_shards": 4})
    with pytest.raises(ck.TopologyMismatch, match="reshard_state"):
        ck.load_state(path, expect_topology={"n_shards": 4})


def test_assemble_full_state_checks_topology_before_concat(tmp_path):
    ds = make_dist(2)
    p = ck.save_state(os.path.join(str(tmp_path), "cp"), ds.state_dict(),
                      backend="npz")
    with pytest.raises(ck.TopologyMismatch, match="n_particles"):
        ck.assemble_full_state([p], expect_topology={"n_particles": N * 2})
    out = ck.assemble_full_state([p], expect_topology={"n_particles": N})
    assert out["particles"].shape == (N, D)


def test_load_state_dict_topology_mismatch_one_line():
    """A wrong-n load used to die with a raw reshape/shape error — now a
    one-line TopologyMismatch naming both topologies fires first."""
    big = make_dist(4, n=2 * N)
    small = make_dist(4)
    with pytest.raises(ck.TopologyMismatch,
                       match=rf"n_particles={2 * N}.*n_particles={N}"):
        small.load_state_dict(big.state_dict())
    # the single-device harness checks the same manifest
    from dist_svgd_tpu.resilience.supervisor import _SamplerHarness

    s = dt.Sampler(D, gmm_logp)
    h16 = _SamplerHarness(s, 16)
    h32 = _SamplerHarness(s, 32)
    with pytest.raises(ck.TopologyMismatch, match="n_particles"):
        h16.load_state_dict(h32.state_dict())


def test_corrupt_manifest_reads_as_none():
    ds = make_dist(4)
    st = ds.state_dict()
    st["topo_particles_per_shard"] = np.asarray([1, 2, 3])  # wrong S, sum
    assert ck.read_manifest(st) is None
    st2 = ds.state_dict()
    st2["topo_n_shards"] = np.asarray("eight")
    assert ck.read_manifest(st2) is None


# --------------------------------------------------------------------- #
# reshard_state (tentpole 1)


def test_reshard_state_regroups_without_permutation():
    ds = make_dist(8)
    ds.run_steps(6, 0.05)
    st = ds.state_dict()
    rs = ck.reshard_state(st, 4)
    # particles are a pure reinterpretation — same rows, same order
    np.testing.assert_array_equal(np.asarray(st["particles"]),
                                  np.asarray(rs["particles"]))
    man = ck.read_manifest(rs)
    assert man["n_shards"] == 4
    np.testing.assert_array_equal(man["particles_per_shard"],
                                  np.full(4, N // 4))
    assert int(np.asarray(rs["topo_resharded_from"])) == 8
    # replicated hyperparameters ride through bitwise
    assert int(np.asarray(rs["t"])) == int(np.asarray(st["t"]))
    np.testing.assert_array_equal(rs["rng_batch_key"], st["rng_batch_key"])


def test_reshard_state_invalidates_duals_and_reshapes_previous():
    ds = make_dist(4, include_wasserstein=True, wasserstein_solver="sinkhorn")
    ds.run_steps(4, 0.05, h=1.0)
    st = ds.state_dict()
    assert st["w2_g"] is not None and st["previous"] is not None
    rs = ck.reshard_state(st, 2)
    assert "w2_g" not in rs  # explicitly invalidated: loader cold-starts
    assert np.asarray(rs["previous"]).shape == (2, N, D)
    ds2 = make_dist(2, include_wasserstein=True,
                    wasserstein_solver="sinkhorn")
    ds2.load_state_dict(rs)
    assert ds2._w2_g is None
    ds2.run_steps(4, 0.05, h=1.0)  # and the resumed solve runs


def test_reshard_state_nondividing_takes_replicate_fallback():
    """Satellite 2: an M that doesn't divide n takes Plan.shard_ensemble's
    replicate-and-warn fallback (same warning text) instead of crashing."""
    from dist_svgd_tpu.parallel.plan import nondividing_replicate_warning

    ds = make_dist(8)
    st = ds.state_dict()
    with pytest.warns(UserWarning,
                      match="replicating instead of sharding"):
        rs = ck.reshard_state(st, 7)
    assert ck.read_manifest(rs)["n_shards"] == 1
    # and it IS the same warning shard_ensemble emits
    assert "replicating instead of sharding" in nondividing_replicate_warning(
        N, 7)


def test_reshard_state_without_manifest_warns_and_infers():
    ds = make_dist(8)
    st = {k: v for k, v in ds.state_dict().items()
          if not k.startswith("topo_")}
    with pytest.warns(UserWarning, match="no readable topology manifest"):
        rs = ck.reshard_state(st, 4)
    assert ck.read_manifest(rs)["n_shards"] == 4
    make_dist(4).load_state_dict(rs)


def test_reshard_state_rejects_per_process_block():
    ds = make_dist(4)
    st = ds.state_dict()
    st["particles_start"] = np.asarray(16, dtype=np.int64)
    with pytest.raises(ValueError, match="assemble_full_state"):
        ck.reshard_state(st, 2)


# --------------------------------------------------------------------- #
# reshard equivalence suite (satellite 3)


def run_supervised(sampler, tmp_path, name, steps=12, **kw):
    sup = supervise(sampler, tmp_path, name, steps=steps, **kw)
    report = sup.run()
    assert report["status"] == "completed"
    return sup, report


@pytest.mark.parametrize("m", [4, 2, 1])
def test_reshard_equivalence_shrink(tmp_path, m):
    """N=8 to step k, reshard to M at an injected shrink, continue to 2k:
    KSD/ESS and the replicated hyperparameters pin bitwise against the
    never-resharded run; particles to accumulation-order tolerance."""
    base, rb = run_supervised(make_dist(8), tmp_path, "base")
    want = np.asarray(base.particles)
    sup, r = run_supervised(
        make_dist(8), tmp_path, f"m{m}",
        reshard=ReshardPolicy(factory),
        faults=FaultPlan(MeshShrinkAt(6, m)))
    assert r["num_shards"] == m and r["reshards"] == 1
    ev = r["reshard_events"][0]
    assert ev["from_shards"] == 8 and ev["to_shards"] == m
    assert ev["t_detected"] == 6 and ev["resumed_from"] == 4
    assert ev["steps_lost"] == 2
    assert ev["reshard_wall_s"] >= 0 and ev["recovery_wall_s"] is not None
    got = np.asarray(sup.particles)
    np.testing.assert_allclose(want, got, rtol=0, atol=ATOL)
    # replicated hyperparameters: bitwise
    assert r["t"] == rb["t"]
    assert sup.step_size == base.step_size
    st_b, st_e = base._harness.state_dict(), sup._harness.state_dict()
    np.testing.assert_array_equal(st_b["rng_batch_key"], st_e["rng_batch_key"])
    np.testing.assert_array_equal(st_b["w2_pairing"], st_e["w2_pairing"])
    # posterior stats: KSD/ESS over the (tolerance-equal) finals
    db = diag_stats(want, 8)
    de = diag_stats(got, m)
    assert np.isclose(db["ksd"], de["ksd"], rtol=1e-4)
    assert np.isclose(db["ess"], de["ess"], rtol=1e-4)


def test_reshard_equivalence_grow(tmp_path):
    base, rb = run_supervised(make_dist(2), tmp_path, "gbase")
    want = np.asarray(base.particles)
    sup, r = run_supervised(
        make_dist(2), tmp_path, "grow",
        reshard=ReshardPolicy(factory),
        faults=FaultPlan(MeshGrowAt(6, 8)))
    assert r["num_shards"] == 8 and r["reshards"] == 1
    got = np.asarray(sup.particles)
    np.testing.assert_allclose(want, got, rtol=0, atol=ATOL)
    db, de = diag_stats(want, 2), diag_stats(got, 8)
    assert np.isclose(db["ksd"], de["ksd"], rtol=1e-4)
    assert np.isclose(db["ess"], de["ess"], rtol=1e-4)


def test_reshard_equivalence_with_kernel_approx(tmp_path):
    """Approx-kernel resume (ISSUE 13 satellite): a ``kernel_approx='rff'``
    run checkpointed at 8 shards and resumed at 4 after an injected shrink
    pins to the never-resharded run — the RFF bank key rides the
    checkpoint through ``reshard_state``, so the resumed φ uses the
    identical feature bank (the bank keys pin bitwise)."""
    kw = dict(kernel_approx="rff", phi_impl="xla")
    base, rb = run_supervised(make_dist(8, **kw), tmp_path, "abase")
    want = np.asarray(base.particles)

    sup, r = run_supervised(
        make_dist(8, **kw), tmp_path, "am4",
        reshard=ReshardPolicy(lambda s: make_dist(s, **kw)),
        faults=FaultPlan(MeshShrinkAt(6, 4)))
    assert r["num_shards"] == 4 and r["reshards"] == 1
    np.testing.assert_allclose(want, np.asarray(sup.particles),
                               rtol=0, atol=ATOL)
    st_b = base._harness.state_dict()
    st_e = sup._harness.state_dict()
    np.testing.assert_array_equal(st_b["approx_bank_key"],
                                  st_e["approx_bank_key"])
    assert int(np.asarray(st_e["approx_method"])) == int(
        np.asarray(st_b["approx_method"]))


def test_reshard_equivalence_corrupt_manifest_fallback(tmp_path):
    """A checkpoint whose manifest was corrupted still reshards (with the
    inference warning) and reproduces the baseline within tolerance."""
    base, _ = run_supervised(make_dist(8), tmp_path, "cbase")
    want = np.asarray(base.particles)
    st = ck.load_state(os.path.join(str(tmp_path), "cbase", "step_4"))
    st["topo_particles_per_shard"] = np.asarray([1, 2, 3])  # corrupt
    assert ck.read_manifest(st) is None
    with pytest.warns(UserWarning, match="no readable topology manifest"):
        rs = ck.reshard_state(st, 4)
    ds = make_dist(4)
    ds.load_state_dict(rs)
    for _ in range(4):
        ds.run_steps(2, float(np.asarray(st["sup_step_size"])))
    np.testing.assert_allclose(want, np.asarray(ds.particles),
                               rtol=0, atol=ATOL)


# --------------------------------------------------------------------- #
# elastic supervisor (tentpole 3)


def test_device_loss_picks_largest_divisor(tmp_path):
    """Losing 1 of 8 devices leaves 7, which doesn't divide n=64: the
    default policy lands on 4 (largest divisor ≤ 7), keeping every
    particle sharded."""
    sup, r = run_supervised(
        make_dist(8), tmp_path, "loss",
        reshard=ReshardPolicy(factory),
        faults=FaultPlan(DeviceLossAt(6)))
    assert r["num_shards"] == 4
    assert r["reshard_events"][0]["requested_shards"] == 4


def test_device_loss_surviving_strategy_replicates(tmp_path):
    """The 'surviving' strategy asks for the raw survivor count (7), which
    takes the replicate-and-warn fallback down to 1 shard."""
    with pytest.warns(UserWarning, match="replicating instead of sharding"):
        sup, r = run_supervised(
            make_dist(8), tmp_path, "surv",
            reshard=ReshardPolicy(factory,
                                  device_loss_strategy="surviving"),
            faults=FaultPlan(DeviceLossAt(6)))
    assert r["num_shards"] == 1
    assert r["reshard_events"][0]["requested_shards"] == 7


def test_back_to_back_topology_faults_close_superseded_window(tmp_path):
    """A second transition firing before the first replay regains its
    detection step supersedes the first recovery window: the first event
    honestly reports recovery_wall_s=None (and no internal clock leaks
    into the report)."""
    sup, r = run_supervised(
        make_dist(8), tmp_path, "double", every=4, seg=4,
        reshard=ReshardPolicy(factory),
        faults=FaultPlan(MeshShrinkAt(6, 4), MeshShrinkAt(8, 2)))
    assert r["reshards"] == 2 and r["num_shards"] == 2
    first, second = r["reshard_events"]
    assert first["to_shards"] == 4 and second["to_shards"] == 2
    assert first["recovery_wall_s"] is None  # superseded before regaining
    assert second["recovery_wall_s"] is not None
    for ev in (first, second):
        assert "_clock0" not in ev


def test_same_count_reshard_keeps_duals():
    """reshard_state to the SAME shard count is not a layout change: the
    warm-start duals stay valid and must survive."""
    ds = make_dist(4, include_wasserstein=True, wasserstein_solver="sinkhorn")
    ds.run_steps(4, 0.05, h=1.0)
    st = ds.state_dict()
    rs = ck.reshard_state(st, 4)
    np.testing.assert_array_equal(np.asarray(rs["w2_g"]),
                                  np.asarray(st["w2_g"]))
    assert ck.read_manifest(rs)["n_shards"] == 4


def test_topology_fault_without_policy_propagates(tmp_path):
    sup = supervise(make_dist(8), tmp_path, "nopol",
                    faults=FaultPlan(MeshShrinkAt(6, 4)))
    with pytest.raises(TopologyFault):
        sup.run()


def test_reshard_spends_shared_restart_budget(tmp_path):
    """Topology transitions draw on the SAME budget as transient retries:
    with max_restarts=0 the first shrink exhausts it."""
    sup = supervise(make_dist(8), tmp_path, "budget",
                    reshard=ReshardPolicy(factory),
                    retry=RetryPolicy(max_restarts=0, backoff_base_s=0),
                    faults=FaultPlan(MeshShrinkAt(6, 4)))
    with pytest.raises(RestartBudgetExhausted):
        sup.run()


def test_elastic_telemetry_and_flight_record(tmp_path):
    from dist_svgd_tpu.telemetry import MetricsRegistry
    from dist_svgd_tpu.telemetry.trace import FlightRecorder

    reg = MetricsRegistry()
    rec = FlightRecorder(capacity=32)
    sup, r = run_supervised(
        make_dist(8), tmp_path, "telem", registry=reg, recorder=rec,
        reshard=ReshardPolicy(factory),
        faults=FaultPlan(MeshShrinkAt(6, 4)))
    assert reg.counter("svgd_elastic_reshards_total").value(
        direction="shrink") == 1
    assert reg.counter("svgd_elastic_steps_lost_total").value() == 2
    assert reg.gauge("svgd_elastic_shards").value() == 4
    assert reg.counter("svgd_train_restarts_total").value(
        kind="topology") == 1
    kinds = [e["kind"] for e in rec.events()]
    assert "topology_transition" in kinds


def test_post_reshard_zero_steady_state_recompiles(tmp_path):
    """After the one reshard compile, steady-state segments at the new
    topology compile nothing (the retrace-sentry contract the drill and
    perf_regress gate)."""
    from tools.jaxlint.sentry import retrace_sentry

    sup, _ = run_supervised(
        make_dist(8), tmp_path, "steady",
        reshard=ReshardPolicy(factory),
        faults=FaultPlan(MeshShrinkAt(6, 4)))
    cont = RunSupervisor(sup.sampler, 16, 0.05, segment_steps=2,
                         sleep=lambda s: None)
    with retrace_sentry("post-reshard steady state") as sentry:
        assert cont.run()["status"] == "completed"
    if not sentry.supported:
        pytest.skip("jax.monitoring events unavailable")
    assert sentry.compiles == 0, sentry.report()


def test_reshard_policy_validation():
    with pytest.raises(ValueError, match="device_loss_strategy"):
        ReshardPolicy(factory, device_loss_strategy="bogus")
    pol = ReshardPolicy(factory)
    assert pol.target_for_device_loss(7, 64) == 4
    assert pol.target_for_device_loss(0, 64) == 1
    assert pol.target_for_device_loss(6, 60) == 6
    with pytest.raises(TypeError, match="DistSampler"):
        ReshardPolicy(lambda s: dt.Sampler(D, gmm_logp)).build(2)
    with pytest.raises(ValueError, match="honour"):
        ReshardPolicy(lambda s: make_dist(2)).build(4)


def test_serve_from_resharded_checkpoint(tmp_path):
    """The serving engine cold-starts from a post-reshard manager root (the
    manifest rides the same dict) and serves the full ensemble."""
    from dist_svgd_tpu.serving.engine import PredictiveEngine

    sup, _ = run_supervised(
        make_dist(8), tmp_path, "serve",
        reshard=ReshardPolicy(factory),
        faults=FaultPlan(MeshShrinkAt(6, 4)))
    eng = PredictiveEngine.from_checkpoint(
        os.path.join(str(tmp_path), "serve"), model="gmm")
    assert eng.n_particles == N and eng.checkpoint_step == 12
    out = eng.predict(np.asarray(sup.particles)[:4])
    assert np.isfinite(out["log_density"]).all()


# --------------------------------------------------------------------- #
# drill row (tentpole 4)


def test_elastic_drill_row_schema(tmp_path):
    from tools import elastic_drill

    row = elastic_drill.run_drill(
        n=N, shards_from=8, shards_to=4, num_steps=12, checkpoint_every=4,
        segment_steps=2, shards_grow_from=2, root=str(tmp_path))
    assert row["metric"] == "elastic_resume"
    for key in ("steps_lost", "reshard_wall_s", "recovery_wall_s",
                "elastic_final_max_dev", "ksd_baseline", "ksd_elastic",
                "ess_frac_baseline", "post_reshard_recompiles",
                "grow_ok", "fallback_ok", "serve_ok",
                "resumed_within_tolerance", "hyperparams_bitwise"):
        assert key in row, key
    assert row["shards_from"] == 8 and row["shards_to"] == 4
    assert row["steps_lost"] == 2
    assert elastic_drill.drill_ok(row), row


@pytest.mark.slow
def test_elastic_drill_default_shape(tmp_path):
    from tools import elastic_drill

    row = elastic_drill.run_drill(n=1024, root=str(tmp_path))
    assert elastic_drill.drill_ok(row), row
    assert row["post_reshard_recompiles"] == 0 or not row["sentry_supported"]
