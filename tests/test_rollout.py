"""Progressive delivery (dist_svgd_tpu/rollout/): deterministic hash
splits, prediction divergence, the staged shadow → canary → promote /
rollback state machine on an injectable clock, O(1) checkpoint-free
rollback to the resident incumbent, the batcher's split/mirror seam,
registry arm/disarm lifecycle, the hot-reloader's offer-as-candidate
path, and ``BadGenerationAt``.
"""

import threading
import time

import numpy as np
import pytest

from dist_svgd_tpu.resilience import BadGenerationAt
from dist_svgd_tpu.rollout import (
    RolloutController,
    RolloutPlan,
    prediction_divergence,
)
from dist_svgd_tpu.rollout.controller import _hash_unit
from dist_svgd_tpu.serving import ModelRegistry, PredictiveEngine
from dist_svgd_tpu.serving.engine import CheckpointHotReloader
from dist_svgd_tpu.telemetry import MetricsRegistry
from dist_svgd_tpu.utils.checkpoint import CheckpointManager


@pytest.fixture
def rng():
    return np.random.default_rng(21)


class ManualClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _engine(rng, n=16, k=4, **kw):
    parts = rng.normal(size=(n, 1 + k)).astype(np.float32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("max_bucket", 4)
    kw.setdefault("registry", MetricsRegistry())
    eng = PredictiveEngine("logreg", parts, **kw)
    eng.warmup()
    return eng, parts


def _controller(eng, clock, **plan_kw):
    plan_kw.setdefault("shadow_fraction", 0.5)
    plan_kw.setdefault("shadow_min_mirrors", 2)
    plan_kw.setdefault("shadow_hold_s", 1.0)
    plan_kw.setdefault("canary_stages", (0.5, 1.0))
    plan_kw.setdefault("stage_hold_s", 1.0)
    plan_kw.setdefault("stage_min_requests", 1)
    return RolloutController(eng, plan=RolloutPlan(**plan_kw), clock=clock)


def _observe_divergence(reg, value, times=1):
    h = reg.histogram("svgd_rollout_divergence")
    for _ in range(times):
        h.observe(value)


def _observe_candidate_latency(reg, seconds, times=1):
    h = reg.histogram("svgd_serve_request_latency_seconds")
    for _ in range(times):
        h.observe(seconds, generation="candidate")


# --------------------------------------------------------------------- #
# plan validation, hash split, divergence


def test_plan_validates():
    with pytest.raises(ValueError, match="shadow_fraction"):
        RolloutPlan(shadow_fraction=0.0)
    with pytest.raises(ValueError, match="strictly increasing"):
        RolloutPlan(canary_stages=(0.5, 0.5, 1.0))
    with pytest.raises(ValueError, match="last canary stage"):
        RolloutPlan(canary_stages=(0.1, 0.5))
    with pytest.raises(ValueError, match="breach_streak"):
        RolloutPlan(breach_streak=0)
    with pytest.raises(ValueError, match="on_active"):
        RolloutPlan(on_active="explode")
    d = RolloutPlan().describe()
    assert d["canary_stages"] == [0.01, 0.10, 0.50, 1.0]


def test_hash_split_deterministic_and_monotone():
    """The per-request hash is stable across calls and processes, split
    vs mirror use independent streams, and a request assigned to the
    candidate at fraction f stays there at every fraction > f (stage
    advances never flap an assignment back to the incumbent)."""
    units = [_hash_unit(7, "split", k) for k in range(2000)]
    assert units == [_hash_unit(7, "split", k) for k in range(2000)]
    assert all(0.0 <= u < 1.0 for u in units)
    # roughly uniform: the 1% stage actually admits ~1% of traffic
    assert 0.05 < sum(u < 0.1 for u in units) / 2000 < 0.15
    # different salts decorrelate split and mirror decisions
    mirrors = [_hash_unit(7, "mirror", k) for k in range(2000)]
    assert mirrors != units
    for f_lo, f_hi in ((0.01, 0.10), (0.10, 0.50), (0.50, 1.0)):
        lo = {k for k, u in enumerate(units) if u < f_lo}
        hi = {k for k, u in enumerate(units) if u < f_hi}
        assert lo <= hi


def test_prediction_divergence():
    a = {"mean": np.array([0.5, 0.5]), "var": np.array([0.1, 0.1])}
    b = {"mean": np.array([0.5, 0.7]), "var": np.array([0.1, 0.1])}
    assert prediction_divergence(a, a) == 0.0
    assert prediction_divergence(a, b) == pytest.approx(0.05)
    # no shared keys -> NaN (counted against the divergence budget by
    # the histogram's overflow bucket, never silently green)
    assert np.isnan(prediction_divergence({"x": np.ones(2)},
                                          {"y": np.ones(2)}))
    bad = {"mean": np.array([np.nan, 0.5]), "var": np.array([0.1, 0.1])}
    assert np.isnan(prediction_divergence(bad, a))


# --------------------------------------------------------------------- #
# the controller state machine (manual clock, metrics-driven windows)


def test_controller_promotes_through_stages(rng):
    eng, parts = _engine(rng)
    reg = eng.registry
    clock = ManualClock()
    ro = _controller(eng, clock)
    cand = parts + np.float32(1e-3)
    assert ro.offer(cand, tag="good", watermark=123.0)
    assert ro.state == "shadow" and ro.active
    # held but starved: no mirrors yet -> the shadow stage must hold
    clock.advance(1.5)
    assert ro.step()["action"] == "hold"
    _observe_divergence(reg, 1e-4, times=3)
    clock.advance(0.1)
    d = ro.step()
    assert d["action"] == "advance" and d["fraction"] == 0.5
    _observe_candidate_latency(reg, 0.002, times=2)
    clock.advance(1.1)
    d = ro.step()
    assert d["action"] == "advance" and d["fraction"] == 1.0
    _observe_candidate_latency(reg, 0.002, times=2)
    clock.advance(1.1)
    d = ro.step()
    assert d["action"] == "promote" and d["watermark"] == 123.0
    assert d["promote_s"] == pytest.approx(3.8, abs=0.2)
    st = eng.stats()
    assert st["generation_id"] == 2
    assert st["previous_generation_id"] == 1
    assert st["candidate_generation_id"] is None
    # promotion stamped the freshness watermark on BOTH series: the
    # tenant-keyed one the FreshnessObjective reads, plus the
    # generation-labelled identity series
    g = reg.gauge("svgd_serving_watermark")
    assert g.value() == 123.0
    assert g.value(generation="2") == 123.0
    # the promoted ensemble now serves
    x = rng.normal(size=(3, 4)).astype(np.float32)
    ref = PredictiveEngine("logreg", cand, min_bucket=4, max_bucket=4,
                           registry=MetricsRegistry())
    np.testing.assert_array_equal(eng.predict(x)["mean"],
                                  ref.predict(x)["mean"])
    assert not ro.active
    ro.close()


def test_controller_rolls_back_on_divergence_without_checkpoint_io(rng):
    """A breaching candidate is dropped in O(1): the resident incumbent
    keeps serving bitwise-identically and the checkpoint-consuming seam
    (``engine.reload``) is never called — the zero-I/O rollback pin."""
    eng, parts = _engine(rng)
    reg = eng.registry
    clock = ManualClock()
    x = rng.normal(size=(3, 4)).astype(np.float32)
    before = {k: np.array(v, copy=True) for k, v in eng.predict(x).items()}
    reloads = []
    orig = eng.reload
    eng.reload = lambda *a, **k: (reloads.append(1), orig(*a, **k))[1]
    ro = _controller(eng, clock, max_divergence=0.05, breach_streak=1)
    assert ro.offer(parts * np.float32(1e6), tag="bad")
    _observe_divergence(reg, 0.9, times=3)
    clock.advance(0.1)
    d = ro.step()
    assert d["action"] == "rollback"
    assert d["objectives"] == ["shadow_divergence"]
    assert d["at_stage"] == "shadow"
    assert not ro.active
    st = eng.stats()
    assert st["generation_id"] == 1
    assert st["candidate_generation_id"] is None
    after = eng.predict(x)
    for k in before:
        np.testing.assert_array_equal(before[k], after[k])
    assert not reloads
    del eng.reload
    assert ro.status()["rollbacks"] == 1
    ro.close()


def test_controller_breach_streak_rides_out_one_bad_window(rng):
    eng, parts = _engine(rng)
    reg = eng.registry
    clock = ManualClock()
    ro = _controller(eng, clock, max_divergence=0.05, breach_streak=2)
    ro.offer(parts + np.float32(1e-3))
    _observe_divergence(reg, 0.9)
    clock.advance(0.1)
    assert ro.step()["action"] == "breach"  # streak 1 of 2: no rollback
    assert ro.active
    _observe_divergence(reg, 1e-4, times=2)  # window recovers
    clock.advance(1.0)
    assert ro.step()["action"] == "advance"  # streak reset by green
    ro.close()


def test_offer_supersede_and_defer(rng):
    eng, parts = _engine(rng)
    clock = ManualClock()
    ro = _controller(eng, clock, on_active="supersede")
    assert ro.offer(parts + np.float32(1e-3), tag="first")
    gen_first = eng.stats()["candidate_generation_id"]
    assert ro.offer(parts + np.float32(2e-3), tag="second")
    assert eng.stats()["candidate_generation_id"] != gen_first
    assert ro.status()["supersedes"] == 1
    ro.close()
    eng2, parts2 = _engine(np.random.default_rng(3))
    ro2 = _controller(eng2, ManualClock(), on_active="defer")
    assert ro2.offer(parts2 + np.float32(1e-3), tag="first")
    assert not ro2.offer(parts2 + np.float32(2e-3), tag="second")
    assert ro2.status()["tag"] == "first"
    ro2.close()


def test_engine_rollback_is_a_pair_exchange(rng):
    """Satellite 1: the previous generation stays resident; rollback is
    a swap (a second rollback recovers the newer generation) and never
    touches checkpoint I/O."""
    eng, parts = _engine(rng)
    new = parts + np.float32(0.5)
    eng.reload(new, tag="gen2")
    assert eng.stats()["generation_id"] == 2
    assert eng.stats()["previous_generation_id"] == 1
    x = rng.normal(size=(2, 4)).astype(np.float32)
    out_gen2 = {k: np.array(v, copy=True)
                for k, v in eng.predict(x).items()}
    info = eng.rollback()
    assert info["generation_id"] == 1
    assert eng.stats()["previous_generation_id"] == 2
    info = eng.rollback()  # EXCHANGE, not a one-shot: gen2 comes back
    assert info["generation_id"] == 2
    after = eng.predict(x)
    for k in out_gen2:
        np.testing.assert_array_equal(out_gen2[k], after[k])


# --------------------------------------------------------------------- #
# batcher split/mirror seam + registry lifecycle


def _wait(pred, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while not pred():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.01)
    return True


def test_batcher_split_mirror_and_generation_labels(rng):
    """Live traffic through the registry's batcher: mirrors flow off the
    client path and are never client requests; canary-split requests
    land on the candidate's OWN label set; promotion serves the
    candidate ensemble."""
    metrics = MetricsRegistry()
    reg = ModelRegistry(metrics=metrics, max_batch=4, max_wait_ms=0.5)
    parts = rng.normal(size=(16, 5)).astype(np.float32)
    reg.add_tenant("prod", "logreg", particles=parts,
                   min_bucket=4, max_bucket=4)
    reg.warm()
    clock = ManualClock()
    ro = reg.begin_rollout(
        "prod", controller=RolloutController(
            reg.tenant("prod").engine, metrics=metrics, clock=clock,
            plan=RolloutPlan(shadow_fraction=0.9, shadow_min_mirrors=1,
                             shadow_hold_s=0.0, canary_stages=(0.5, 1.0),
                             stage_hold_s=0.0, stage_min_requests=1,
                             max_divergence=1.0, p99_ms=1e5)))
    cand = parts + np.float32(1e-3)
    assert ro.offer(cand, tag="good")
    x = rng.normal(size=(4, 4)).astype(np.float32)
    n_client = 0
    for _ in range(12):
        reg.submit("prod", x).result(timeout=10)
        n_client += 1
    m_mirrors = metrics.counter("svgd_rollout_mirrors_total")
    assert _wait(lambda: m_mirrors.value(tenant="prod") >= 1)
    req_counter = metrics.counter("svgd_serve_requests_total")
    # shadow: every client request resolved on the incumbent series —
    # mirrored dispatches are NOT client requests
    assert req_counter.value(tenant="prod") == n_client
    assert req_counter.value(tenant="prod", generation="candidate") == 0
    clock.advance(0.1)
    assert ro.step()["action"] == "advance"  # canary 0.5
    for _ in range(24):
        reg.submit("prod", x).result(timeout=10)
        n_client += 1
    # the 0.5 split sent a deterministic subset to the candidate's own
    # label set; incumbent + candidate account for every client request
    cand_served = req_counter.value(tenant="prod", generation="candidate")
    assert cand_served > 0
    assert req_counter.value(tenant="prod") + cand_served == n_client
    clock.advance(0.1)
    assert ro.step()["action"] == "advance"  # canary 1.0
    reg.submit("prod", x).result(timeout=10)
    n_client += 1
    assert _wait(lambda: req_counter.value(
        tenant="prod", generation="candidate") > cand_served)
    clock.advance(0.1)
    assert ro.step()["action"] == "promote"
    # post-promote traffic serves the candidate ensemble on the plain
    # tenant series again
    ref = PredictiveEngine("logreg", cand, min_bucket=4, max_bucket=4,
                           registry=MetricsRegistry())
    np.testing.assert_array_equal(
        reg.submit("prod", x).result(timeout=10)["mean"],
        ref.predict(x)["mean"])
    reg.end_rollout("prod")
    reg.close()


def test_registry_rollout_lifecycle(rng):
    metrics = MetricsRegistry()
    reg = ModelRegistry(metrics=metrics, max_wait_ms=0.5)
    for name in ("a", "b"):
        reg.add_tenant(name, "logreg",
                       particles=rng.normal(size=(8, 5)).astype(np.float32),
                       min_bucket=4, max_bucket=4)
    ro = reg.begin_rollout("a")
    assert reg.begin_rollout("a") is ro  # idempotent for the same tenant
    with pytest.raises(RuntimeError, match="already armed"):
        reg.begin_rollout("b")
    assert reg.rollout_status()["tenant"] == "a"
    eng = reg.tenant("a").engine
    ro.offer(np.asarray(eng.particles) + np.float32(1e-3))
    assert eng.stats()["candidate_generation_id"] is not None
    reg.end_rollout("a")  # disarm drops the in-flight candidate
    assert eng.stats()["candidate_generation_id"] is None
    assert reg.rollout_status() is None
    assert reg.batcher.rollout is None
    # removing the rollout tenant disarms too
    ro2 = reg.begin_rollout("b")
    assert reg.rollout_status()["tenant"] == "b"
    reg.remove_tenant("b")
    assert reg.rollout_status() is None
    assert reg.batcher.rollout is None
    assert not ro2.active
    reg.close()


def test_tenant_summary_and_stats_carry_generation_identity(rng):
    reg = ModelRegistry(metrics=MetricsRegistry(), max_wait_ms=0.5)
    reg.add_tenant("prod", "logreg",
                   particles=rng.normal(size=(8, 5)).astype(np.float32),
                   min_bucket=4, max_bucket=4)
    row = reg.tenant("prod").summary()
    assert row["generation_id"] == 1
    assert row["previous_generation_id"] is None
    assert row["candidate_generation_id"] is None
    reg.tenant("prod").engine.reload(
        rng.normal(size=(8, 5)).astype(np.float32), tag="gen2")
    row = reg.tenant("prod").summary()
    assert row["generation_id"] == 2
    assert row["previous_generation_id"] == 1
    reg.close()


# --------------------------------------------------------------------- #
# hot-reloader offer path (the streaming publish leg's seam)


def test_reloader_offers_candidate_instead_of_swapping(tmp_path, rng):
    eng, parts = _engine(rng)
    root = str(tmp_path / "ckpt")
    mgr = CheckpointManager(root, every=1, backend="npz")
    new = parts + np.float32(0.25)
    mgr.save(2, {"particles": new,
                 "stream_watermark": np.float64(777.0)})
    clock = ManualClock()
    ro = _controller(eng, clock)
    reloader = CheckpointHotReloader(eng, root, rollout=ro,
                                     baseline_step=1)
    assert reloader.poll_once() == 2
    st = eng.stats()
    # offered, NOT swapped: serving generation unchanged, candidate
    # resident, freshness watermark NOT stamped until promotion
    assert st["generation_id"] == 1
    assert st["candidate_generation_id"] is not None
    assert reloader.loaded_step == 2
    assert not eng.registry.gauge("svgd_serving_watermark").has()
    assert reloader.poll_once() is None  # step marked seen
    # walk it to promotion: the rollout stamps the offered watermark
    _observe_divergence(eng.registry, 1e-4, times=3)
    clock.advance(1.1)
    assert ro.step()["action"] == "advance"
    _observe_candidate_latency(eng.registry, 0.001)
    clock.advance(1.1)
    assert ro.step()["action"] == "advance"
    _observe_candidate_latency(eng.registry, 0.001)
    clock.advance(1.1)
    d = ro.step()
    assert d["action"] == "promote" and d["watermark"] == 777.0
    assert eng.registry.gauge("svgd_serving_watermark").value() == 777.0
    ro.close()


# --------------------------------------------------------------------- #
# BadGenerationAt


def test_bad_generation_at_validates():
    with pytest.raises(ValueError, match="kind"):
        BadGenerationAt(0, kind="melt")
    with pytest.raises(ValueError, match="until"):
        BadGenerationAt(5, until=5)
    with pytest.raises(ValueError, match="magnitude"):
        BadGenerationAt(0, kind="saturate", magnitude=1.0)


def test_bad_generation_at_window_and_purity(rng):
    fault = BadGenerationAt(2, kind="saturate", magnitude=1e6, until=4)
    assert [fault.active(i) for i in range(6)] == [
        False, False, True, True, False, False]
    parts = rng.normal(size=(8, 5)).astype(np.float32)
    ref = parts.copy()
    out1 = fault.apply(parts)
    out2 = fault.apply(parts)
    np.testing.assert_array_equal(parts, ref)  # pure: input untouched
    np.testing.assert_array_equal(out1, out2)  # deterministic
    assert np.all(np.isfinite(out1))  # passes admission health checks
    np.testing.assert_allclose(out1, parts * 1e6, rtol=1e-6)
    scr = BadGenerationAt(0, kind="scramble").apply(parts)
    assert scr.shape == parts.shape
    assert np.all(np.isfinite(scr))
    np.testing.assert_array_equal(scr, -parts[:, ::-1])
