"""Metrics federation (round 16): full-fidelity registry dumps, clamped
delta merging, the router-side federation sweep (exactness, restart
clamping, visible scrape failures, replica-label cardinality), the
federated ``/metrics``+``/slo``+``/fleet`` routes, and the
``tools/fleet_status.py`` CLI."""

import json
import os
import sys
import urllib.request

import pytest

from dist_svgd_tpu.serving import fleet
from dist_svgd_tpu.telemetry.metrics import (
    MetricsRegistry,
    combined_exposition,
    dump_delta,
)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


def _loaded_registry(n_requests, latency_s=0.004, tenant="t0"):
    reg = MetricsRegistry()
    c = reg.counter("svgd_serve_requests_total", "requests fully resolved")
    h = reg.histogram("svgd_serve_request_latency_seconds", "latency")
    g = reg.gauge("svgd_serve_queue_depth_rows", "depth")
    for _ in range(n_requests):
        c.inc(tenant=tenant)
        h.observe(latency_s, tenant=tenant)
    g.set(n_requests, batcher="b0")
    return reg


# --------------------------------------------------------------------- #
# dump / delta / ingest units


def test_dump_roundtrip_is_exact():
    src = _loaded_registry(9)
    dst = MetricsRegistry()
    dst.ingest(src.dump())
    assert dst.counter("svgd_serve_requests_total").value(tenant="t0") == 9
    hist = dst.histogram("svgd_serve_request_latency_seconds")
    s = hist.summary(tenant="t0")
    assert s["count"] == 9
    # raw bucket counts travelled, so quantiles agree exactly with the
    # source's (same fixed lattice, same interpolation)
    src_hist = src.histogram("svgd_serve_request_latency_seconds")
    assert hist.quantile(0.99, tenant="t0") == pytest.approx(
        src_hist.quantile(0.99, tenant="t0"))
    assert dst.gauge("svgd_serve_queue_depth_rows").value(batcher="b0") == 9


def test_dump_delta_clamps_counter_and_histogram_resets():
    before = _loaded_registry(10)
    dump0 = before.dump()
    # a restart: fresh registry with LESS traffic than before
    after = _loaded_registry(3)
    delta = dump_delta(dump0, after.dump())
    counters = delta["metrics"]["svgd_serve_requests_total"]["series"]
    assert all(s["value"] == 0 for s in counters)
    hists = delta["metrics"]["svgd_serve_request_latency_seconds"]["series"]
    assert all(s["count"] == 0 and sum(s["counts"]) == 0 for s in hists)
    # gauges pass through current values (last write wins at ingest)
    gauges = delta["metrics"]["svgd_serve_queue_depth_rows"]["series"]
    assert gauges[0]["value"] == 3
    # and a normal increment windows exactly
    more = _loaded_registry(13)
    delta2 = dump_delta(dump0, more.dump())
    assert delta2["metrics"]["svgd_serve_requests_total"][
        "series"][0]["value"] == 3


def test_dump_delta_masked_restart_still_clamps():
    """A restart hidden by growth — the new lifetime already has MORE
    total observations than the old one, but individual buckets shrank —
    must still read as a reset: per-bucket clamping there would emit a
    delta whose bucket sum disagrees with its count."""
    before = MetricsRegistry()
    h = before.histogram("h", "x")
    for _ in range(100):
        h.observe(0.004)       # old lifetime: 100 obs in one bucket
    after = MetricsRegistry()
    h2 = after.histogram("h", "x")
    for _ in range(150):
        h2.observe(0.5)        # new lifetime: more obs, DIFFERENT bucket
    delta = dump_delta(before.dump(), after.dump())
    s = delta["metrics"]["h"]["series"][0]
    assert s["count"] == 0 and sum(s["counts"]) == 0 and s["sum"] == 0.0


def test_ingest_rejects_mismatched_bucket_boundaries():
    src = MetricsRegistry()
    src.histogram("h", "x", buckets=(0.1, 0.2, 0.4)).observe(0.15)
    dst = MetricsRegistry()
    dst.histogram("h", "x", buckets=(0.1, 0.3, 0.9)).observe(0.15)
    # same bucket COUNT, different boundaries: merging would silently
    # skew quantiles — must refuse instead
    with pytest.raises(ValueError, match="lattice"):
        dst.ingest(src.dump())


def test_failed_scrape_does_not_consume_the_window():
    """A dump the registry cannot ingest must not advance the replica's
    delta window: the failed window's counts arrive with the NEXT good
    scrape instead of being dropped forever."""
    reg = _loaded_registry(5)

    class FlakyDumpReplica:
        poison = False

        def handle(self, method, path, body, headers):
            if path == "/metrics.dump" and self.poison:
                return fleet._json_reply(200, {"metrics": {
                    "svgd_serve_requests_total": {"kind": "zebra",
                                                  "series": []}}})
            if path == "/metrics.dump":
                return fleet._json_reply(200, reg.dump())
            return fleet._json_reply(200, {"status": "ok"})

    rep = FlakyDumpReplica()
    transport = fleet.FakeTransport({"r0": rep})
    rs = fleet.ReplicaSet(["r0"], transport, registry=MetricsRegistry())
    fed = fleet.MetricsFederation(rs, transport, registry=rs.registry)
    fed.scrape_once()
    c = fed.fleet_registry.counter("svgd_serve_requests_total")
    assert c.value(tenant="t0") == 5
    reg.counter("svgd_serve_requests_total").inc(3, tenant="t0")
    rep.poison = True
    out = fed.scrape_once()
    assert "r0" in out["errors"]
    assert c.value(tenant="t0") == 5  # prior contribution stands
    rep.poison = False
    fed.scrape_once()
    assert c.value(tenant="t0") == 8  # the failed window was NOT dropped
    assert fed.monotone is True


def test_replica_slo_verdicts_stay_replica_labelled_only():
    """A replica's own svgd_slo_* verdict mirrors must never roll up into
    the unlabelled series — that's where the ROUTER's fleet SLO engine
    writes, and summing per-engine breach counts into it would corrupt
    the fleet verdict series."""
    reg = _loaded_registry(3)
    reg.counter("svgd_slo_breaches_total", "x").inc(5, slo="serve_p99")
    rep = fleet.LoopbackReplica("r0", registry=reg)
    transport = fleet.FakeTransport({"r0": rep})
    rs = fleet.ReplicaSet(["r0"], transport, registry=MetricsRegistry())
    fed = fleet.MetricsFederation(rs, transport, registry=rs.registry)
    fed.scrape_once()
    c = fed.fleet_registry.counter("svgd_slo_breaches_total")
    assert c.value(slo="serve_p99", replica="r0") == 5
    assert c.value(slo="serve_p99") == 0  # no rollup: the router's series
    # ordinary serving counters still roll up
    assert fed.fleet_registry.counter(
        "svgd_serve_requests_total").value(tenant="t0") == 3


def test_router_slo_verdict_cached_against_window_slicing():
    reps = {"r0": fleet.LoopbackReplica("r0", registry=_loaded_registry(4))}
    transport = fleet.FakeTransport(reps)
    router = fleet.FleetRouter(["r0"], transport=transport,
                               registry=MetricsRegistry(),
                               probe_interval_s=10.0,
                               slo_min_interval_s=60.0)
    try:
        first = router.evaluate_slo()
        assert first["objectives"]["serve_p99"]["window_count"] == 4
        # more traffic lands, but a second poll inside the interval must
        # return the CACHED verdict — not consume a sliver window
        reps["r0"].registry.histogram(
            "svgd_serve_request_latency_seconds").observe(0.004, tenant="t0")
        assert router.evaluate_slo() is first
        router.slo_min_interval_s = 0.0
        fresh = router.evaluate_slo()
        assert fresh is not first
    finally:
        router.shutdown()


def test_trace_header_one_spelling():
    from dist_svgd_tpu import telemetry

    assert fleet.TRACE_HEADER == telemetry.TRACE_HEADER == "X-Fleet-Trace"


def test_dump_delta_first_scrape_is_cumulative():
    reg = _loaded_registry(4)
    delta = dump_delta(None, reg.dump())
    assert delta["metrics"]["svgd_serve_requests_total"][
        "series"][0]["value"] == 4


def test_histogram_merge_rejects_mismatched_buckets():
    reg = MetricsRegistry()
    h = reg.histogram("h", "x", buckets=(0.1, 0.2, 0.4))
    with pytest.raises(ValueError, match="cannot merge"):
        h.merge_series([1, 2], 0.3, 3)


def test_combined_exposition_merges_blocks_and_keeps_distinct_series():
    a = MetricsRegistry()
    b = MetricsRegistry()
    a.counter("shared", "from a").inc(1)
    b.counter("shared", "from b").inc(99)            # same series id
    b.counter("shared", "from b").inc(7, replica="r0")  # distinct series
    b.counter("only_b", "x").inc(2)
    text = combined_exposition(a, b)
    # ONE block per name; on the identical series identity the earlier
    # registry wins, but the later registry's DISTINCT series survive —
    # a router that traces must not hide the replicas' federated
    # svgd_trace_* series behind its own same-named metric
    assert text.count("# TYPE shared counter") == 1
    assert "shared 1" in text and "shared 99" not in text
    assert 'shared{replica="r0"} 7' in text
    assert "only_b 2" in text


# --------------------------------------------------------------------- #
# the federation sweep


def _fed_fleet(n=2, counts=(5, 7)):
    reps = {}
    for i in range(n):
        rid = f"r{i}"
        reps[rid] = fleet.LoopbackReplica(
            rid, registry=_loaded_registry(counts[i]))
    transport = fleet.FakeTransport(reps)
    rs = fleet.ReplicaSet(list(reps), transport,
                          registry=MetricsRegistry())
    return reps, transport, rs


def test_federated_counters_equal_sum_of_replica_snapshots():
    reps, transport, rs = _fed_fleet(counts=(5, 7))
    fed = fleet.MetricsFederation(rs, transport, registry=rs.registry)
    out = fed.scrape_once()
    assert out["errors"] == {}
    c = fed.fleet_registry.counter("svgd_serve_requests_total")
    # the rollup equals the exact sum; per-replica series carry identity
    assert c.value(tenant="t0") == 12
    assert c.value(tenant="t0", replica="r0") == 5
    assert c.value(tenant="t0", replica="r1") == 7
    h = fed.fleet_registry.histogram("svgd_serve_request_latency_seconds")
    assert h.summary(tenant="t0")["count"] == 12
    # scraping again with no new traffic adds nothing (windowed deltas)
    fed.scrape_once()
    assert c.value(tenant="t0") == 12


def test_federation_survives_replica_restart_clamped():
    reps, transport, rs = _fed_fleet(counts=(5, 7))
    fed = fleet.MetricsFederation(rs, transport, registry=rs.registry)
    fed.scrape_once()
    # restart r0: FRESH registry (counters reset), some new traffic
    transport.set_replica(
        "r0", fleet.LoopbackReplica("r0", registry=_loaded_registry(2)))
    fed.scrape_once()
    c = fed.fleet_registry.counter("svgd_serve_requests_total")
    # the reset window clamps to zero — never a negative rate — and the
    # rollup stays monotone
    assert c.value(tenant="t0") == 12
    assert fed.monotone is True
    # post-restart traffic federates again
    reps2 = transport._replicas["r0"]
    reps2.registry.counter("svgd_serve_requests_total").inc(4, tenant="t0")
    fed.scrape_once()
    assert c.value(tenant="t0") == 16
    assert fed.monotone is True


def test_scrape_failure_is_counted_and_prior_contribution_stands():
    reps, transport, rs = _fed_fleet(counts=(5, 7))
    fed = fleet.MetricsFederation(rs, transport, registry=rs.registry)
    fed.scrape_once()
    transport.kill("r0")
    out = fed.scrape_once()
    assert "r0" in out["errors"] and out["scraped"] == ["r1"]
    errs = rs.registry.counter("svgd_fleet_scrape_errors_total")
    assert errs.value(replica="r0") == 1
    assert errs.value(replica="r1") == 0
    # r0's previously-federated 5 requests are still in the rollup
    c = fed.fleet_registry.counter("svgd_serve_requests_total")
    assert c.value(tenant="t0") == 12
    assert fed.stats()["scrape_errors"] == {"r0": 1}


def test_replica_label_rides_the_cardinality_guard():
    """A flapping fleet (many distinct replica identities) must aggregate
    into the reserved ``other`` rollup, never grow without bound."""
    ids = [f"flap{i}" for i in range(8)]
    reps = {rid: fleet.LoopbackReplica(rid, registry=_loaded_registry(1))
            for rid in ids}
    transport = fleet.FakeTransport(reps)
    rs = fleet.ReplicaSet(ids, transport, registry=MetricsRegistry())
    fed = fleet.MetricsFederation(
        rs, transport, registry=rs.registry,
        fleet_registry=MetricsRegistry(max_label_sets=4))
    with pytest.warns(RuntimeWarning, match="max_label_sets"):
        fed.scrape_once()
    c = fed.fleet_registry.counter("svgd_serve_requests_total")
    label_sets = c.label_sets()
    # the bound plus the reserved rollup series itself
    assert len(label_sets) <= 5
    # the overflow landed in the rollup series, not on the floor
    assert c.value(tenant="other", replica="other") > 0
    # exposition stays bounded and well-formed
    text = fed.fleet_registry.exposition()
    assert text.count("svgd_serve_requests_total{") <= 5


# --------------------------------------------------------------------- #
# the router's federated HTTP plane


def _http_router(tenants=("t0", "t1")):
    reps = {f"r{i}": fleet.LoopbackReplica(f"r{i}", tenants=list(tenants))
            for i in range(2)}
    transport = fleet.FakeTransport(reps)
    router = fleet.FleetRouter(
        list(reps), transport=transport, registry=MetricsRegistry(),
        probe_interval_s=5.0, port=0).start()
    return router, reps, transport


def _get(url, path):
    with urllib.request.urlopen(url.rstrip("/") + path, timeout=5) as r:
        return r.status, r.read()


def test_router_metrics_exposes_federated_series():
    router, reps, transport = _http_router()
    try:
        for i in range(6):
            t = "t0" if i % 2 else "t1"
            res = router.route(t, json.dumps(
                {"inputs": [[0.1, 0.2]], "tenant": t}).encode())
            assert res.status == 200
        status, body = _get(router.url, "/metrics")
        text = body.decode()
        assert status == 200
        # the router's own series...
        assert "svgd_fleet_requests_total" in text
        # ...plus the federated replica-labelled series and the rollup
        assert 'svgd_serve_requests_total{replica="r0"' in text \
            or 'svgd_serve_requests_total{replica="r1"' in text
        assert 'svgd_serve_requests_total{tenant="t0"}' in text
        # one TYPE block per name (combined_exposition dedup)
        assert text.count("# TYPE svgd_serve_requests_total counter") == 1
    finally:
        router.shutdown()


def test_router_slo_evaluates_federated_window():
    router, reps, transport = _http_router()
    try:
        for _ in range(8):
            router.route("t0", json.dumps(
                {"inputs": [[0.1, 0.2]], "tenant": "t0"}).encode())
        status, body = _get(router.url, "/slo")
        doc = json.loads(body)
        assert status == 200
        assert doc["status"] in ("ok", "breach")
        p99 = doc["objectives"]["serve_p99"]
        # the window saw the federated (cross-replica) observations
        assert p99["status"] == "ok" and p99["window_count"] == 8
    finally:
        router.shutdown()


def test_fleet_route_and_status_doc():
    router, reps, transport = _http_router()
    try:
        for _ in range(4):
            router.route("t0", json.dumps(
                {"inputs": [[0.1, 0.2]], "tenant": "t0"}).encode())
        status, body = _get(router.url, "/fleet")
        doc = json.loads(body)
        assert status == 200
        assert doc["role"] == "fleet-router"
        assert set(doc["replicas"]) == {"r0", "r1"}
        assert doc["federation"]["scrapes"] >= 1
        assert doc["federation"]["monotone"] is True
        assert doc["tenants"]["t0"]["requests"] == 4
        assert doc["tenants"]["t0"]["requests_total"] == 4
        assert "p99_ms" in doc["tenants"]["t0"]
        assert doc["slo"]["status"] in ("ok", "breach")
    finally:
        router.shutdown()


def test_fleet_status_cli_against_live_router(capsys):
    import fleet_status

    router, reps, transport = _http_router()
    try:
        for _ in range(5):
            router.route("t0", json.dumps(
                {"inputs": [[0.1, 0.2]], "tenant": "t0"}).encode())
        rc = fleet_status.main(["--url", router.url, "--interval-s", "0.05",
                                "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["healthy"] is True
        assert out["metric"] == "fleet_status"
        assert out["replicas"]["r0"]["state"] == "closed"
        assert out["tenants"]["t0"]["requests"] == 5
        # the two-poll window derived a (possibly zero) rate, not null
        assert out["tenants"]["t0"]["rps"] is not None
        # human rendering exits through the same health verdict
        rc = fleet_status.main(["--url", router.url, "--interval-s", "0"])
        human = capsys.readouterr().out
        assert rc == 0 and "replicas closed" in human
    finally:
        router.shutdown()


def test_fleet_status_cli_unreachable_exits_2(capsys):
    import fleet_status

    rc = fleet_status.main(["--url", "http://127.0.0.1:9",
                            "--interval-s", "0", "--timeout-s", "0.2"])
    err = capsys.readouterr().err
    assert rc == 2
    assert err.count("\n") == 1 and "fleet_status:" in err
