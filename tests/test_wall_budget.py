"""Tier-1 wall-time guard: no non-slow test may exceed the per-test
budget.  conftest.py collects every call-phase duration and reorders this
module to run LAST, so by the time the assertion runs it has seen the
whole session.  The same data lands in ``tests/.test_durations.json``
(slowest first) for post-mortems.

The tier-1 suite runs under one ~15-minute budget; a single test quietly
growing past ~15 s is how that budget dies — this turns the creep into a
named FAIL instead of an eventual suite timeout."""

from conftest import DURATIONS, WALL_BUDGET_ALLOW_S, WALL_BUDGET_S


def test_no_nonslow_test_exceeds_wall_budget():
    over = {
        nid: round(meta["duration"], 2)
        for nid, meta in DURATIONS.items()
        if not meta["slow"]
        and meta["duration"] > WALL_BUDGET_ALLOW_S.get(nid, WALL_BUDGET_S)
    }
    assert not over, (
        f"non-slow tests over the {WALL_BUDGET_S:.0f}s wall budget "
        f"(mark them slow, make them faster, or grant a named allowance "
        f"in conftest.WALL_BUDGET_ALLOW_S): {over}"
    )
