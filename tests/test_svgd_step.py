"""Fused φ and step vs the literal-semantics oracle (SURVEY.md §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.svgd import phi, svgd_step, svgd_step_sequential

from _oracle import gauss_seidel_sweep, jacobi_sweep, phi_hat


def gaussian_score(mu, prec):
    def score(x):
        return -prec * (np.asarray(x) - mu)

    return score


def make_logp(mu, prec):
    def logp(x):
        return -0.5 * prec * jnp.sum((x - mu) ** 2)

    return logp


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def test_phi_matches_oracle(rng):
    n, m, d = 4, 6, 3
    updated = rng.normal(size=(n, d))
    interacting = rng.normal(size=(m, d))
    scores = rng.normal(size=(m, d))

    got = np.asarray(phi(jnp.asarray(updated), jnp.asarray(interacting), jnp.asarray(scores), RBF(1.0)))
    for i in range(n):
        want = phi_hat(updated[i], interacting, lambda j, xj: scores[j])
        np.testing.assert_allclose(got[i], want, rtol=1e-10, atol=1e-12)


def test_phi_generic_kernel_equals_fused_rbf(rng):
    """The autograd fallback path and the analytic RBF path must agree."""
    upd = jnp.asarray(rng.normal(size=(5, 2)))
    inter = jnp.asarray(rng.normal(size=(5, 2)))
    scores = jnp.asarray(rng.normal(size=(5, 2)))

    def plain(a, b):
        return jnp.exp(-jnp.sum((a - b) ** 2))

    fused = np.asarray(phi(upd, inter, scores, RBF(1.0)))
    generic = np.asarray(phi(upd, inter, scores, plain))
    np.testing.assert_allclose(fused, generic, rtol=1e-10)


def test_jacobi_step_matches_oracle(rng):
    n, d = 6, 2
    parts = rng.normal(size=(n, d))
    mu, prec = 1.5, 0.7
    score = gaussian_score(mu, prec)
    scores = jnp.asarray(np.stack([score(p) for p in parts]))

    got = np.asarray(svgd_step(jnp.asarray(parts), scores, 0.1, RBF(1.0)))
    want = jacobi_sweep(parts, score, 0.1)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)


def test_sequential_step_matches_gauss_seidel_oracle(rng):
    """lax.scan Gauss–Seidel mode reproduces the reference's in-place sweep
    exactly (dsvgd/sampler.py:62-68 semantics)."""
    n, d = 5, 2
    parts = rng.normal(size=(n, d))
    mu, prec = -0.5, 1.3

    got = np.asarray(
        svgd_step_sequential(jnp.asarray(parts), jax.grad(make_logp(mu, prec)), 0.05, RBF(1.0))
    )
    want = gauss_seidel_sweep(parts, gaussian_score(mu, prec), 0.05)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11)


def test_gauss_seidel_and_jacobi_share_fixed_point(rng):
    """Different trajectories, same fixed point (SURVEY.md §3.2): run both to
    near-convergence on a 1-D Gaussian and compare moments."""
    n, d = 30, 1
    parts = jnp.asarray(rng.normal(size=(n, d)))
    logp = make_logp(2.0, 1.0)
    score_fn = jax.grad(logp)
    batched = jax.vmap(score_fn)

    @jax.jit
    def run_jacobi(p):
        return jax.lax.fori_loop(0, 300, lambda _, q: svgd_step(q, batched(q), 0.3, RBF(1.0)), p)

    @jax.jit
    def run_gs(p):
        return jax.lax.fori_loop(
            0, 300, lambda _, q: svgd_step_sequential(q, score_fn, 0.3, RBF(1.0)), p
        )

    jac = run_jacobi(parts)
    gs = run_gs(parts)

    assert float(jnp.mean(jac)) == pytest.approx(float(jnp.mean(gs)), abs=0.05)
    assert float(jnp.std(jac)) == pytest.approx(float(jnp.std(gs)), abs=0.05)


def test_svgd_step_extra_grad_placement(rng):
    """δ += h·w_grad before θ += ε·δ (dsvgd/distsampler.py:194-200)."""
    parts = jnp.asarray(rng.normal(size=(4, 2)))
    scores = jnp.zeros_like(parts)
    extra = jnp.asarray(rng.normal(size=(4, 2)))
    base = svgd_step(parts, scores, 0.1, RBF(1.0))
    with_extra = svgd_step(parts, scores, 0.1, RBF(1.0), extra_grad=extra, extra_weight=10.0)
    np.testing.assert_allclose(
        np.asarray(with_extra - base), 0.1 * 10.0 * np.asarray(extra), rtol=1e-9, atol=1e-12
    )
