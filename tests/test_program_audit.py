"""Program auditor gate (round 22): registry capture, XP red paths, and
the ``tools/program_audit.py`` card gate.

Three layers, mirroring the jaxlint test layout:

- **registry semantics** — ``analysis.registry`` captures first-call
  avals through the ``Plan.compile`` seam, scopes via ``use_registry``,
  weakrefs the compiled plans (a dead plan yields no card), and bounds
  its own memory.
- **red paths** — an injected fixture plan that materializes a Gram
  matrix under a ``gram_free`` declaration fires exactly one XP001; a
  plan whose declared donation was stripped fires exactly one XP003;
  and feeding either into :func:`tools.program_audit.gate` flips the
  gated row to FAIL *naming the exact rule* (the ISSUE-19 acceptance
  drill).
- **the committed artifact** — ``tools/program_cards.json`` must exist,
  cover every suite builder (``--list-missing`` empty — parity with
  ``perf_regress --list-missing``), and judge a real builder's fresh
  cards PASS with zero XP findings (the zero-finding baseline).

Everything runs on the tier-1 CPU mesh; the only compiles are a handful
of toy jits plus ONE real builder (``sampler_exact``), keeping every
test far under the 15 s wall budget.
"""

import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dist_svgd_tpu.analysis import (
    ProgramCard,
    audit_entry,
    audit_registry,
    default_registry,
    use_registry,
    xp_findings,
)
from dist_svgd_tpu.parallel.plan import Plan
from tools import program_audit

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_plan_compile_tracks_and_captures_first_call_avals():
    with use_registry() as reg:
        plan = Plan()
        f = plan.compile(lambda x: x * 2.0, label="t.double")
        (entry,) = reg.entries()
        assert entry.label == "t.double"
        assert not entry.captured
        f(jnp.zeros((5, 3), jnp.float32))
        assert entry.captured
        (aval,) = entry.avals
        assert aval.shape == (5, 3) and aval.dtype == jnp.float32
        # steady state: repeat calls don't re-capture or grow anything
        f(jnp.ones((5, 3), jnp.float32))
        assert len(reg.entries()) == 1


def test_use_registry_scopes_and_restores_the_default():
    outer = default_registry()
    with use_registry() as reg:
        assert default_registry() is reg
        plan = Plan()
        f = plan.compile(lambda x: x + 1, label="t.scoped")
        assert [e.label for e in reg.entries()] == ["t.scoped"]
        del f
    assert default_registry() is outer
    assert "t.scoped" not in [e.label for e in outer.entries()]


def test_dead_plan_yields_no_card():
    with use_registry() as reg:
        plan = Plan()
        f = plan.compile(lambda x: x - 1.0, label="t.dies")
        f(jnp.zeros((4,), jnp.float32))
        (entry,) = reg.entries()
        assert entry.alive
        del f
        import gc

        gc.collect()
        # the registry holds only a weakref: the entry dies with the plan,
        # audits to no card, and is pruned from subsequent listings
        assert not entry.alive
        assert audit_entry(entry) is None
        cards, findings = audit_registry(reg)
        assert reg.entries() == []
    assert cards == [] and findings == []


def test_registry_capacity_is_bounded():
    with use_registry() as reg:
        reg._capacity = 3
        plan = Plan()
        fns = [plan.compile((lambda i: lambda x: x + i)(i), label=f"t.{i}")
               for i in range(5)]
        assert len(reg.entries()) == 3
        # FIFO eviction keeps the newest plans
        assert [e.label for e in reg.entries()] == [f"t.{i}" for i in (2, 3, 4)]
        del fns


# ---------------------------------------------------------------------------
# red paths (the ISSUE-19 acceptance drills)
# ---------------------------------------------------------------------------


def _gram_fixture_cards():
    """A plan that *declares* gram-free but lowers an n×n Gram matrix."""

    def gram_step(x):
        g = jnp.exp(-jnp.sum((x[:, None, :] - x[None, :, :]) ** 2, -1))
        return g @ x

    with use_registry() as reg:
        plan = Plan()
        f = plan.compile(gram_step, label="t.gram",
                         audit=dict(gram_free=True))
        f(jnp.zeros((24, 2), jnp.float32))
        return audit_registry(reg)


def test_materialized_gram_fires_exactly_one_xp001():
    cards, findings = _gram_fixture_cards()
    (card,) = cards
    assert card.nxn_buffers > 0 and card.n_particles == 24
    assert [f.rule for f in findings] == ["XP001"]
    (f,) = findings
    assert f.path == "plan://t.gram"
    assert "24" in f.message  # names the offending dimension


def _stripped_donation_cards():
    """Donation declared through the audit contract but stripped from the
    compile call — the silent-drop failure mode XP003 exists to catch."""
    with use_registry() as reg:
        plan = Plan()
        f = plan.compile(lambda x: x + 1.0, donate_argnums=(),
                         label="t.nodon", audit=dict(expect_donation=True))
        f(jnp.zeros((8, 2), jnp.float32))
        return audit_registry(reg)


def test_stripped_donation_fires_exactly_one_xp003():
    cards, findings = _stripped_donation_cards()
    (card,) = cards
    assert card.donated_leaves == 0
    assert [f.rule for f in findings] == ["XP003"]
    assert findings[0].path == "plan://t.nodon"


@pytest.mark.parametrize("fixture,rule", [
    (_gram_fixture_cards, "XP001"),
    (_stripped_donation_cards, "XP003"),
])
def test_gate_row_flips_fail_naming_the_rule(fixture, rule):
    cards, findings = fixture()
    baseline = {"cards": {program_audit.baseline_key(c): c.as_dict()
                          for c in cards}}
    rows, kept, ok = program_audit.gate(
        cards, findings, baseline, builders=("?",))
    assert not ok
    (row,) = [r for r in rows if r["status"] == "FAIL"]
    assert any(rule in reason for reason in row["reasons"])


def test_healthy_plan_zero_findings():
    with use_registry() as reg:
        plan = Plan()
        f = plan.compile(lambda x: x * 0.5, donate_argnums=(0,),
                         label="t.ok", audit=dict(gram_free=True,
                                                  expect_donation=True))
        f(jnp.zeros((24, 2), jnp.float32))
        cards, findings = audit_registry(reg)
    (card,) = cards
    assert findings == []
    assert card.donation_ok and card.nxn_buffers == 0


# ---------------------------------------------------------------------------
# gate arithmetic (pure, no compiles)
# ---------------------------------------------------------------------------


def _card_dict(**over):
    base = dict(collectives={"all_gather": 1}, donation_ok=True,
                donation_markers=1, nxn_buffers=0, num_shards=2)
    base.update(over)
    return base


def test_compare_card_flags_each_regression_axis():
    base = _card_dict()
    assert program_audit.compare_card(_card_dict(), base) == []
    assert any("all_gather" in r for r in program_audit.compare_card(
        _card_dict(collectives={"all_gather": 2}), base))
    assert any("donation aliasing dropped" in r
               for r in program_audit.compare_card(
                   _card_dict(donation_ok=False), base))
    assert any("markers" in r for r in program_audit.compare_card(
        _card_dict(donation_markers=0), base))
    assert any("nxn" in r for r in program_audit.compare_card(
        _card_dict(nxn_buffers=3), base))
    assert any("num_shards" in r for r in program_audit.compare_card(
        _card_dict(num_shards=1), base))
    # fewer collectives / MORE markers are improvements, not regressions
    assert program_audit.compare_card(
        _card_dict(collectives={}, donation_markers=2), base) == []


def test_gate_subset_run_does_not_flag_unbuilt_builders_missing():
    baseline = {"cards": {
        "a/lbl(x)": dict(_card_dict(), builder="a"),
        "b/lbl(x)": dict(_card_dict(), builder="b"),
    }}
    rows, kept, ok = program_audit.gate([], [], baseline, builders=("a",))
    assert [r["status"] for r in rows] == ["MISSING"]
    assert rows[0]["card"] == "a/lbl(x)"
    assert not ok
    # full scope flags both
    rows, _, _ = program_audit.gate([], [], baseline, builders=("a", "b"))
    assert sorted(r["card"] for r in rows) == ["a/lbl(x)", "b/lbl(x)"]


# ---------------------------------------------------------------------------
# the committed artifact
# ---------------------------------------------------------------------------


def _baseline():
    with open(program_audit.CARDS_PATH) as fh:
        return json.load(fh)


def test_baseline_artifact_covers_every_builder():
    doc = _baseline()
    assert program_audit.missing_builders(doc) == []
    for key, card in doc["cards"].items():
        assert key.startswith(card["builder"] + "/")
        for field in program_audit.GATED_FIELDS:
            assert field in card, (key, field)


def test_list_missing_parity_with_perf_regress(tmp_path, capsys):
    # empty artifact: every builder is a dormant gate, same contract as
    # perf_regress's windowed rows with no incumbent history
    empty = tmp_path / "cards.json"
    empty.write_text(json.dumps({"cards": {}}))
    rc = program_audit.main(["--list-missing", "--cards-path", str(empty)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["missing"] == list(program_audit.BUILDER_NAMES)
    assert set(doc["gates"]) == set(program_audit.BUILDER_NAMES)
    # committed artifact: nothing missing, and perf_regress --list-missing
    # cross-reports the same answer in its own document
    out = subprocess.run(
        [sys.executable, os.path.join("tools", "perf_regress.py"),
         "--list-missing"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    pr_doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert pr_doc["program_audit_missing"] == []
    assert {"missing", "gates"} <= set(pr_doc) and {"missing", "gates"} <= set(doc)


def test_sampler_exact_builder_passes_against_committed_baseline():
    cards, findings = program_audit.run_suite(["sampler_exact"])
    assert findings == []
    rows, kept, ok = program_audit.gate(cards, findings, _baseline(),
                                        builders=("sampler_exact",))
    assert ok, rows
    assert all(r["status"] == "PASS" for r in rows)
    (card,) = cards
    assert card.meta["builder"] == "sampler_exact"
    assert card.key in {k.split("/", 1)[1] for k in _baseline()["cards"]}


def test_full_suite_zero_findings_and_gate_green():
    """The ISSUE-19 acceptance drill in one breath: every suite builder's
    cards lower clean (zero XP findings on package plans) and judge PASS
    against the committed baseline — the tier-1 enforcement of the
    program-card artifact."""
    cards, findings = program_audit.run_suite()
    assert findings == []
    rows, kept, ok = program_audit.gate(cards, findings, _baseline())
    assert ok, [r for r in rows if r["status"] != "PASS"]
    assert len(cards) == len(_baseline()["cards"])
    # every builder contributed at least one card
    owners = {c.meta["builder"] for c in cards}
    assert owners == set(program_audit.BUILDER_NAMES)


def test_tampered_baseline_fails_deterministically():
    cards, findings = program_audit.run_suite(["sampler_exact"])
    doc = copy.deepcopy(_baseline())
    key = program_audit.baseline_key(cards[0])
    # pretend the incumbent had one more donation marker: the "current
    # build silently dropped aliasing" signature
    doc["cards"][key]["donation_markers"] += 1
    rows, _, ok = program_audit.gate(cards, findings, doc,
                                     builders=("sampler_exact",))
    assert not ok
    (row,) = [r for r in rows if r["status"] == "FAIL"]
    assert any("markers" in reason for reason in row["reasons"])
