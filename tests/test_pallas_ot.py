"""Fused Sinkhorn kernels (ops/pallas_ot.py) vs the XLA path (ops/ot.py).

Runs under the Pallas interpreter on CPU — same kernels, exact semantics
(the TPU leg is tools/w2_bench.py / tools/tpu_phi_check.py)."""

import numpy as np
import jax.numpy as jnp
import pytest

from dist_svgd_tpu.ops.kernels import squared_distances
from dist_svgd_tpu.ops.ot import sinkhorn_plan, wasserstein_grad_sinkhorn
from dist_svgd_tpu.ops.pallas_ot import (
    ctransform_reduce,
    kexp,
    plan_grad,
    sinkhorn_grad_fused,
)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _pts(rng, k, m, d=3):
    x = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(m, d)) + 0.3, jnp.float32)
    return x, y


def test_ctransform_min_matches_dense(rng):
    x, y = _pts(rng, 37, 53)  # ragged: exercises sentinel-padded columns
    p = jnp.asarray(rng.normal(size=53), jnp.float32)
    got = np.asarray(ctransform_reduce(x, y, p, 1.0, soft=False, interpret=True))
    want = np.min(np.asarray(squared_distances(x, y)) - np.asarray(p)[None, :], axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_ctransform_lse_matches_dense(rng):
    import scipy.special

    x, y = _pts(rng, 41, 29)
    p = jnp.asarray(rng.normal(size=29), jnp.float32)
    got = np.asarray(ctransform_reduce(x, y, p, 1.0, soft=True, interpret=True))
    e = np.asarray(p)[None, :] - np.asarray(squared_distances(x, y))
    want = scipy.special.logsumexp(e, axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_kexp_matches_dense(rng):
    x, y = _pts(rng, 21, 45)
    f = jnp.asarray(rng.normal(size=21), jnp.float32)
    g = jnp.asarray(rng.normal(size=45), jnp.float32)
    got = np.asarray(kexp(x, y, f, g, 1.0, interpret=True))
    c = np.asarray(squared_distances(x, y))
    want = np.exp(np.asarray(f)[:, None] + np.asarray(g)[None, :] - c)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_plan_grad_matches_dense(rng):
    x, y = _pts(rng, 33, 27)
    f = jnp.asarray(rng.normal(size=33) * 0.5, jnp.float32)
    g = jnp.asarray(rng.normal(size=27) * 0.5, jnp.float32)
    got = np.asarray(plan_grad(x, y, f, g, 1.0, interpret=True))
    c = np.asarray(squared_distances(x, y))
    p = np.exp(np.asarray(f)[:, None] + np.asarray(g)[None, :] - c)
    want = np.asarray(x) * p.sum(axis=1)[:, None] - p @ np.asarray(y)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("tol", [None, 1e-2])
@pytest.mark.parametrize("warm", [False, True])
def test_fused_grad_matches_xla_path(rng, tol, warm):
    """End-to-end: the fused solve equals the XLA solve (same algorithm,
    different memory movement) on cold and warm starts, fixed and tol
    exits."""
    x, y = _pts(rng, 24, 40)
    g_init = None
    if warm:
        # a realistic warm carry: the converged dual of a nearby problem
        _, g_init = wasserstein_grad_sinkhorn(
            x + 0.01, y, eps=0.05, iters=100, return_g=True
        )
    want, want_g = wasserstein_grad_sinkhorn(
        x, y, eps=0.05, iters=60, tol=tol, g_init=g_init, return_g=True
    )
    got, got_g = sinkhorn_grad_fused(
        x, y, eps=0.05, iters=60, tol=tol, g_init=g_init, return_g=True,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                               rtol=1e-4, atol=1e-4)


def test_fused_grad_outlier_row_safe(rng):
    """The outlier regression from tests/test_ot.py, on the fused path."""
    x = np.asarray(rng.normal(size=(64, 2)))
    x[0] = 40.0
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(rng.normal(size=(32, 2)), jnp.float32)
    grad = np.asarray(sinkhorn_grad_fused(
        x, y, eps=0.01, iters=400, tol=1e-2, interpret=True
    ))
    assert np.all(np.isfinite(grad))
    assert np.all(grad[0] > 0.5)


def test_public_impl_dispatch_matches(rng):
    """wasserstein_grad_sinkhorn(impl='pallas') (interpreter off-TPU)
    equals impl='xla' through the public API, including the carried g."""
    x, y = _pts(rng, 20, 30)
    want, want_g = wasserstein_grad_sinkhorn(
        x, y, eps=0.05, iters=80, tol=1e-3, return_g=True, impl="xla"
    )
    got, got_g = wasserstein_grad_sinkhorn(
        x, y, eps=0.05, iters=80, tol=1e-3, return_g=True, impl="pallas"
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                               rtol=1e-4, atol=1e-4)
    with pytest.raises(ValueError):
        wasserstein_grad_sinkhorn(x, y, impl="nope")
    with pytest.raises(ValueError):
        big_d = jnp.asarray(np.zeros((4, 12)), jnp.float32)
        wasserstein_grad_sinkhorn(big_d, big_d, impl="pallas")


def test_fused_matches_plan_based_grad(rng):
    """Cross-check against the plan route: grad from the materialised
    sinkhorn_plan at identical settings."""
    x, y = _pts(rng, 16, 16)
    plan = np.asarray(sinkhorn_plan(x, y, eps=0.05, iters=200))
    want = np.asarray(x) * plan.sum(axis=1)[:, None] - plan @ np.asarray(y)
    got = np.asarray(sinkhorn_grad_fused(
        x, y, eps=0.05, iters=200, interpret=True
    ))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_fused_sinkhorn_under_shard_map(rng):
    """sinkhorn_grad_fused traced inside shard_map over a real (virtual-CPU)
    mesh — the composition the scanned W2 path uses on a TPU mesh (the
    production 'auto' dispatch picks XLA on CPU, so this forces the fused
    path through the interpreter)."""
    import jax

    from dist_svgd_tpu.parallel.mesh import bind_shard_fn, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs a 4-device mesh")
    S = 4
    x = jnp.asarray(rng.normal(size=(S * 8, 3)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(S * 16, 3)) + 0.2, jnp.float32)
    mesh = make_mesh(S)
    assert mesh is not None

    def shard_fn(block, prev):
        return sinkhorn_grad_fused(
            block, prev, eps=0.05, iters=40, interpret=True
        )

    bound = bind_shard_fn(shard_fn, S, mesh, in_specs=(0, 0), out_specs=(0,))
    got = np.asarray(jax.jit(bound)(x, y))
    want = np.concatenate([
        np.asarray(wasserstein_grad_sinkhorn(
            x[r * 8:(r + 1) * 8], y[r * 16:(r + 1) * 16],
            eps=0.05, iters=40, impl="xla",
        ))
        for r in range(S)
    ])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_kmat_vec_matches_dense(rng):
    """Streaming P@rhs (vector and multi-column) vs the dense product,
    including the transpose call convention."""
    from dist_svgd_tpu.ops.pallas_ot import kmat_vec

    x, y = _pts(rng, 23, 41)
    f = jnp.asarray(rng.normal(size=23) * 0.5, jnp.float32)
    g = jnp.asarray(rng.normal(size=41) * 0.5, jnp.float32)
    c = np.asarray(squared_distances(x, y))
    p = np.exp(np.asarray(f)[:, None] + np.asarray(g)[None, :] - c)
    v = jnp.asarray(rng.normal(size=41), jnp.float32)
    got = np.asarray(kmat_vec(x, y, f, g, v, 1.0, interpret=True))
    np.testing.assert_allclose(got, p @ np.asarray(v), rtol=1e-5, atol=1e-5)
    # multi-column rhs
    R = jnp.asarray(rng.normal(size=(41, 3)), jnp.float32)
    got = np.asarray(kmat_vec(x, y, f, g, R, 1.0, interpret=True))
    np.testing.assert_allclose(got, p @ np.asarray(R), rtol=1e-5, atol=1e-5)
    # transpose convention: P^T u via swapped roles and potentials
    u = jnp.asarray(rng.normal(size=23), jnp.float32)
    got = np.asarray(kmat_vec(y, x, g, f, u, 1.0, interpret=True))
    np.testing.assert_allclose(got, p.T @ np.asarray(u), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("tol", [None, 1e-2])
@pytest.mark.parametrize("warm", [False, True])
def test_streaming_grad_matches_xla_path(rng, tol, warm):
    """The O(n*d)-memory streaming solve equals the XLA solve — same
    algorithm, the kernel matrix just never exists.  With a ``tol`` exit
    the streaming loop runs at ``absorb_every=1`` (blocks are pure exit-
    granularity loss when every matvec rebuilds tiles —
    sinkhorn_grad_streaming docstring), so the matching XLA reference is
    the ``absorb_every=1`` solve; fixed-count runs honor the argument and
    match the default-block reference."""
    from dist_svgd_tpu.ops.pallas_ot import sinkhorn_grad_streaming

    x, y = _pts(rng, 24, 40)
    g_init = None
    if warm:
        _, g_init = wasserstein_grad_sinkhorn(
            x + 0.01, y, eps=0.05, iters=100, return_g=True
        )
    ref_absorb = 1 if tol is not None else 10
    want, want_g = wasserstein_grad_sinkhorn(
        x, y, eps=0.05, iters=60, tol=tol, g_init=g_init, return_g=True,
        impl="xla", absorb_every=ref_absorb,
    )
    got, got_g = sinkhorn_grad_streaming(
        x, y, eps=0.05, iters=60, tol=tol, g_init=g_init, return_g=True,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                               rtol=1e-4, atol=1e-4)


def test_kernels_multi_tile_grids(rng, monkeypatch, request):
    """Force tiny tiles so every kernel runs a REAL multi-tile grid (several
    row tiles × several column sweeps) under the interpreter — pinning the
    per-row-tile scratch-cache protocol (``_row_tile``/``fc_ref`` refresh at
    ``j == 0``) that single-tile shapes never exercise.  A stale cache (row
    block i−1's transposed coordinates or potential leaking into row block
    i) shows up as wrong rows here."""
    from dist_svgd_tpu.ops import pallas_ot as po

    import jax

    monkeypatch.setattr(po, "_BLOCK_K", 16)
    monkeypatch.setattr(po, "_BLOCK_M", 16)
    monkeypatch.setattr(po, "_KEXP_BLOCK_K", 16)
    # the kernels are module-level jax.jit functions that read the tile
    # globals at TRACE time: stale traces for these shapes would silently
    # ignore the patch — and tiny-tile traces must not outlive it either,
    # so the trailing clear runs even when an assertion fails
    jax.clear_caches()
    request.addfinalizer(jax.clear_caches)
    k, m, d = 50, 70, 3  # 4 × 5 grids with ragged edges
    x = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(m, d)), jnp.float32)
    f = jnp.asarray(rng.normal(size=(k,)), jnp.float32)
    g = jnp.asarray(rng.normal(size=(m,)), jnp.float32)
    sq = np.asarray(
        ((np.asarray(x)[:, None, :] - np.asarray(y)[None, :, :]) ** 2).sum(-1)
    )
    p_dense = np.exp(np.asarray(f)[:, None] + np.asarray(g)[None, :] - sq)

    got_k = np.asarray(po.kexp(x, y, f, g, 1.0, interpret=True))
    np.testing.assert_allclose(got_k, p_dense, rtol=1e-5, atol=1e-7)

    v = jnp.asarray(rng.normal(size=(m,)), jnp.float32)
    got_mv = np.asarray(po.kmat_vec(x, y, f, g, v, 1.0, interpret=True))
    np.testing.assert_allclose(got_mv, p_dense @ np.asarray(v),
                               rtol=1e-5, atol=1e-5)

    got_ct = np.asarray(po.ctransform_reduce(x, y, g, 1.0, True,
                                             interpret=True))
    want_ct = np.log(np.exp(np.asarray(g)[None, :] - sq).sum(1))
    np.testing.assert_allclose(got_ct, want_ct, rtol=1e-5, atol=1e-5)

    got_pg = np.asarray(po.plan_grad(x, y, f, g, 1.0, interpret=True))
    want_pg = (np.asarray(x) * p_dense.sum(1)[:, None]
               - p_dense @ np.asarray(y))
    np.testing.assert_allclose(got_pg, want_pg, rtol=1e-5, atol=1e-5)


def test_streaming_warm_early_exit_at_converged_dual(rng):
    """A carried dual whose soft-transform change is already within tol
    skips the scaling loop entirely (the start pair is one exact log-domain
    iteration and delta0 IS its exit statistic — _solve_setup docstring):
    the result equals the start-pair gradient, i.e. the XLA ``iters=0``
    warm gradient from the same carried dual."""
    from dist_svgd_tpu.ops.pallas_ot import sinkhorn_grad_streaming

    x, y = _pts(rng, 24, 40)
    _, g = wasserstein_grad_sinkhorn(
        x, y, eps=0.05, iters=400, tol=1e-5, return_g=True
    )  # converged dual for this exact pairing
    got = sinkhorn_grad_streaming(
        x, y, eps=0.05, iters=60, tol=1e-2, g_init=g, interpret=True
    )
    want = wasserstein_grad_sinkhorn(x, y, eps=0.05, iters=0, g_init=g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_auto_dispatch_reaches_streaming_under_vmap(rng, monkeypatch):
    """The production entry to the streaming solve: impl dispatch past the
    (monkeypatched) HBM-cliff threshold, per-lane under jax.vmap — the
    nested kmat_vec-inside-fori-inside-while structure a batching
    regression would break."""
    import jax

    from dist_svgd_tpu.ops import ot
    from dist_svgd_tpu.ops import pallas_ot

    monkeypatch.setattr(ot, "FUSED_SINKHORN_STREAM_MIN_PAIRS", 1)
    calls = []
    orig = pallas_ot.sinkhorn_grad_streaming

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(pallas_ot, "sinkhorn_grad_streaming", spy)
    S = 3
    x = jnp.asarray(rng.normal(size=(S, 10, 3)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(S, 20, 3)) + 0.2, jnp.float32)
    got = np.asarray(jax.vmap(
        lambda c, p: wasserstein_grad_sinkhorn(
            c, p, eps=0.05, iters=40, tol=1e-2, impl="pallas"
        )
    )(x, y))
    assert calls, "dispatch did not reach the streaming path"
    want = np.stack([
        np.asarray(wasserstein_grad_sinkhorn(
            x[r], y[r], eps=0.05, iters=40, tol=1e-2, impl="xla",
            absorb_every=1,  # the streaming tol-exit granularity (see
        ))                   # test_streaming_grad_matches_xla_path)
        for r in range(S)
    ])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_streaming_under_shard_map(rng):
    """sinkhorn_grad_streaming traced inside shard_map over a real
    (virtual-CPU) mesh — mirrors test_fused_sinkhorn_under_shard_map."""
    import jax

    from dist_svgd_tpu.ops.pallas_ot import sinkhorn_grad_streaming
    from dist_svgd_tpu.parallel.mesh import bind_shard_fn, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs a 4-device mesh")
    S = 4
    x = jnp.asarray(rng.normal(size=(S * 8, 3)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(S * 16, 3)) + 0.2, jnp.float32)
    mesh = make_mesh(S)
    assert mesh is not None

    def shard_fn(block, prev):
        return sinkhorn_grad_streaming(
            block, prev, eps=0.05, iters=40, interpret=True
        )

    bound = bind_shard_fn(shard_fn, S, mesh, in_specs=(0, 0), out_specs=(0,))
    got = np.asarray(jax.jit(bound)(x, y))
    want = np.concatenate([
        np.asarray(wasserstein_grad_sinkhorn(
            x[r * 8:(r + 1) * 8], y[r * 16:(r + 1) * 16],
            eps=0.05, iters=40, impl="xla",
        ))
        for r in range(S)
    ])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
