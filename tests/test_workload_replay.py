"""Trace-driven workload replay (tools/workload_replay.py): seeded trace
determinism, the open-loop replay's record classification, the storm
metric helpers, and the serve_storm gate logic.  The mini end-to-end
storm keeps its phases short (the full-size A/B is the bench's job, not
tier-1's); the 3-arm variant is slow-marked.
"""

import os
import sys
from concurrent.futures import Future

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import workload_replay as wr  # noqa: E402

from dist_svgd_tpu.serving.batcher import Overloaded  # noqa: E402


def _cfg(**kw):
    base = dict(duration_s=5.0, base_rps=120.0, seed=3,
                bursts=((2.0, 1.0, 2.5),), tenants=("a", "b", "c"),
                flash_crowds=((2.0, 1.0, 2, 0.7),))
    base.update(kw)
    return wr.TraceConfig(**base)


# --------------------------------------------------------------------- #
# trace model


def test_trace_determinism_and_seed_sensitivity():
    """Same config ⇒ identical arrival schedule, sizes, tenant mix, and
    pool picks (the serve_storm A/B's identical-trace contract); a
    different seed ⇒ a different trace."""
    e1 = wr.generate_trace(_cfg())
    e2 = wr.generate_trace(_cfg())
    assert len(e1) == len(e2)
    assert all(a.t == b.t and a.rows == b.rows and a.tenant == b.tenant
               and a.pick == b.pick for a, b in zip(e1, e2))
    e3 = wr.generate_trace(_cfg(seed=4))
    assert len(e3) != len(e1) or any(
        a.t != b.t for a, b in zip(e1, e3))


def test_trace_shape_burst_flash_and_heavy_tail():
    events = wr.generate_trace(_cfg(duration_s=6.0, base_rps=200.0))
    pre = sum(1 for e in events if e.t < 2.0) / 2.0
    burst = sum(1 for e in events if 2.0 <= e.t < 3.0)
    assert burst > 1.6 * pre  # the 2.5x burst window is denser
    crowd = [e.tenant for e in events if 2.0 <= e.t < 3.0]
    assert crowd.count("c") / len(crowd) > 0.5  # flash mass shifted to c
    outside = [e.tenant for e in events if e.t < 2.0]
    assert outside.count("a") > outside.count("c")  # zipf rank order
    sizes = [e.rows for e in events]
    assert sizes.count(1) > sizes.count(32)  # power-law tail


def test_trace_regular_arrivals_and_rate_envelope():
    cfg = _cfg(arrival="regular", tenants=(), flash_crowds=(),
               diurnal_amp=0.0)
    events = wr.generate_trace(cfg)
    # deterministic spacing at the instantaneous rate: counts match the
    # envelope's integral almost exactly
    pre = sum(1 for e in events if e.t < 2.0)
    assert abs(pre - 240) <= 2
    assert cfg.rate_at(2.5) == pytest.approx(300.0)
    assert cfg.rate_at(4.0) == pytest.approx(120.0)
    assert cfg.peak_rate() == pytest.approx(300.0)


def test_trace_config_validation():
    with pytest.raises(ValueError):
        wr.TraceConfig(duration_s=0)
    with pytest.raises(ValueError):
        wr.TraceConfig(arrival="bursty")
    with pytest.raises(ValueError):
        wr.TraceConfig(bursts=((0.0, -1.0, 2.0),))
    with pytest.raises(ValueError):
        wr.TraceConfig(flash_crowds=((0.0, 1.0, 0, 0.5),))  # no tenants
    with pytest.raises(ValueError):
        wr.TraceConfig(tenants=("a",),
                       flash_crowds=((0.0, 1.0, 3, 0.5),))  # bad index


# --------------------------------------------------------------------- #
# replay mechanics


def test_replay_classifies_ok_shed_error_lost():
    events = [wr.ReplayEvent(0.001 * i, 1, None, i) for i in range(4)]

    def submit(ev):
        fut = Future()
        if ev.pick == 0:
            fut.set_result({"y": np.zeros((1, 1))})
        elif ev.pick == 1:
            raise Overloaded("full")
        elif ev.pick == 2:
            fut.set_exception(RuntimeError("boom"))
        # pick == 3: never resolves -> lost
        return fut

    records = wr.replay(events, submit, drain_timeout_s=0.2)
    statuses = [r["status"] for r in records]
    assert statuses == ["ok", "shed", "error", "lost"]
    assert records[0]["lat_ms"] >= 0.0
    assert records[1]["lat_ms"] is None
    assert "boom" in records[2]["error"]


def test_window_metrics_and_breach_and_recover():
    records = [
        # healthy first second
        {"t": 0.2, "rows": 1, "tenant": None, "status": "ok", "lat_ms": 5.0},
        {"t": 0.7, "rows": 1, "tenant": None, "status": "ok", "lat_ms": 8.0},
        # second 1: p99 breaches + a shed
        {"t": 1.2, "rows": 1, "tenant": None, "status": "ok",
         "lat_ms": 90.0},
        {"t": 1.5, "rows": 2, "tenant": None, "status": "shed",
         "lat_ms": None},
        # second 2: starvation (offered, nothing completed)
        {"t": 2.5, "rows": 1, "tenant": None, "status": "shed",
         "lat_ms": None},
        # second 3: healthy again
        {"t": 3.4, "rows": 1, "tenant": None, "status": "ok",
         "lat_ms": 6.0},
    ]
    m = wr.window_metrics(records, 0.0, 4.0, good_ms=25.0)
    assert m["offered"] == 6 and m["completed"] == 4
    assert m["good"] == 3 and m["shed"] == 2
    assert m["goodput_rps"] == pytest.approx(0.8)
    assert wr.p99_breach_seconds(records, 25.0, 4.0) == 2
    # burst ended at t=1: second 2 is starved, second 3 is the first
    # healthy one -> 2 s to recover
    assert wr.time_to_recover(records, 1.0, 25.0, 4.0) == pytest.approx(2.0)
    # never recovering reads as the full remaining window
    bad = [dict(r, lat_ms=500.0) for r in records if r["status"] == "ok"]
    assert wr.time_to_recover(bad, 1.0, 25.0, 4.0) == pytest.approx(3.0)


def test_storm_ok_gates():
    row = {"lost_requests": 0, "recompiles": 0, "sentry_compiles": 0,
           "arms": {"adaptive": {"phases": {"steady": {
               "offered": 10, "completed": 8, "shed": 2, "errors": 0,
               "lost": 0}}}}}
    ok, why = wr.storm_ok(row)
    assert ok and why == []
    bad = dict(row, lost_requests=2)
    ok, why = wr.storm_ok(bad)
    assert not ok and "lost" in why[0]
    bad = dict(row, recompiles=1)
    assert not wr.storm_ok(bad)[0]
    bad = dict(row, sentry_compiles=3)
    assert not wr.storm_ok(bad)[0]
    leaky = {"lost_requests": 0, "recompiles": 0, "sentry_compiles": 0,
             "arms": {"adaptive": {"phases": {"steady": {
                 "offered": 10, "completed": 7, "shed": 2, "errors": 0,
                 "lost": 0}}}}}
    ok, why = wr.storm_ok(leaky)
    assert not ok and "accounted" in why[0]


def test_run_storm_requires_two_tenants():
    with pytest.raises(ValueError):
        wr.run_storm(tenants=1)


def test_default_lanes_max_is_host_derived():
    assert 1 <= wr.default_lanes_max() <= 4


# --------------------------------------------------------------------- #
# end-to-end storms (tiny)


def _storm_kw(**kw):
    base = dict(n_particles=256, n_features=8, seed=5,
                steady_s=1.2, burst_s=1.2, recover_s=1.2,
                max_batch=32, max_queue_rows=128,
                rows_sizes=(1, 2, 4), flash_rows_sizes=(8, 16),
                tenants=2, calib_requests=90, interval_s=0.1)
    base.update(kw)
    return base


def test_mini_storm_adaptive_arm_schema_and_gates():
    """A tiny adaptive-only storm end to end: every admitted request
    resolves, zero steady-state recompiles under the sentry, and the row
    carries the full gated schema.  (The adaptive-vs-static A/B verdict
    is the full-size bench's claim — a 1-second mini phase is noise.)"""
    row = wr.run_storm(include_static=False, **_storm_kw())
    ok, why = wr.storm_ok(row)
    assert ok, why
    assert row["metric"] == "serve_storm"
    assert row["lost_requests"] == 0
    assert row["recompiles"] == 0
    assert row["sentry_compiles"] in (0, None)
    assert row["ab"] is None
    for key in ("storm_goodput_2x", "storm_p99_breach_s",
                "storm_recover_s", "capacity_rows_per_s", "trace",
                "bounds", "p99_target_ms"):
        assert key in row
    arm = row["arms"]["adaptive"]
    assert arm["adaptive"] is True
    assert "controller" in arm
    assert set(arm["phases"]) == {"steady", "burst_polite", "recover"}
    offered = sum(p["offered"] for p in arm["phases"].values())
    assert offered > 0
    assert row["trace"]["hog_burst_rps"] > 0


@pytest.mark.slow
def test_full_storm_three_arms():
    """The 3-arm storm (static_base / static_burst / adaptive) on the
    identical trace: per-arm schema, identical offered counts, and the
    A/B block present.  Slow-marked: ~3 replay walls plus settles."""
    row = wr.run_storm(**_storm_kw(steady_s=2.0, burst_s=2.0,
                                   recover_s=2.0, tenants=3))
    ok, why = wr.storm_ok(row)
    assert ok, why
    assert set(row["arms"]) == {"static_base", "static_burst", "adaptive"}
    offered = {name: arm["hog"]["offered"] + sum(
        p["offered"] for p in arm["phases"].values())
        for name, arm in row["arms"].items()}
    assert len(set(offered.values())) == 1  # the identical trace
    ab = row["ab"]
    assert set(ab) >= {"best_static_polite_goodput_rps", "adaptive_wins",
                       "goodput_ratio", "breach_delta_s"}
    assert isinstance(ab["adaptive_wins"], bool)


def test_fleet_replay_flash_crowd_sheds_without_losses():
    """A flash crowd replayed through the round-15 FleetRouter front door
    (fake transport, bounded per-replica row budgets): the overload must
    surface as fleet-level 429s that replay books as `shed` — never as a
    lost or errored request, because an admitted request always resolves
    and a shed is the replica protecting itself, not failing."""
    router, close = wr.build_fake_fleet(
        2, max_replica_rows=8, tenants=("t0", "t1"))
    cfg = wr.TraceConfig(
        duration_s=1.2, base_rps=150.0, seed=3,
        bursts=((0.2, 0.6, 8.0),), rows_sizes=(4, 8),
        tenants=("t0", "t1"))
    events = wr.generate_trace(cfg)
    pools = {r: [np.zeros((r, 4), dtype=np.float32)] for r in (4, 8)}
    transport = wr.make_router_submit(router)
    try:
        records = wr.replay(events, transport(pools))
    finally:
        transport.shutdown(wait=False)
        close()
    m = wr.window_metrics(records, 0.0, cfg.duration_s, good_ms=1000.0)
    assert m["offered"] > 50
    assert m["shed"] > 0      # the burst hit the row budgets
    assert m["lost"] == 0
    assert m["errors"] == 0
    assert m["completed"] + m["shed"] == m["offered"]
