"""Lagged (stale) exchange — ``DistSampler(exchange_every=T)``.

The reference timed a "laggedlocal" variant (its notes.md:134, reproduced in
BASELINE.md: 226 s vs 59 s for per-step exchange at its headline config) but
never shipped an implementation (SURVEY.md §2.3).  These tests pin the
semantics this framework defines for it (lagged-remote, live-local —
``parallel/exchange.py:make_shard_step_lagged``).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu import DistSampler, RBF
from dist_svgd_tpu.models.gmm import gmm_logp


@pytest.fixture
def rng():
    return np.random.default_rng(31)


def _logp(th, _=None):
    return gmm_logp(th)


def _make(init, T, **kw):
    return DistSampler(
        4, _logp, None, init,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False, exchange_every=T, **kw,
    )


def test_exchange_every_one_macro_equals_standard_step(rng):
    """The lagged macro itself at T=1 ≡ one per-step all_particles step.

    ``DistSampler(exchange_every=1)`` deliberately never builds the lagged
    path (the standard step IS the T=1 semantics), so this drives
    ``make_shard_step_lagged`` directly to pin its base case."""
    from dist_svgd_tpu.parallel.exchange import make_shard_step_lagged
    from dist_svgd_tpu.parallel.mesh import bind_shard_fn, make_mesh

    init = jnp.asarray(rng.normal(size=(16, 2)))
    macro = make_shard_step_lagged(
        logp=_logp, kernel=RBF(1.0),
        num_shards=4, n_local_data=0, score_scale=1.0, exchange_every=1,
    )
    bound = bind_shard_fn(
        macro, 4, make_mesh(4),
        in_specs=(0, None, 0, None, None, None, None), out_specs=(0,),
    )
    key = jnp.zeros((2,), dtype=jnp.uint32)
    got = np.asarray(bound(
        init, None, jnp.zeros_like(init), jnp.int32(1), key,
        jnp.float64(0.2), jnp.float64(0.0),
    ))
    ref = DistSampler(
        4, _logp, None, init,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False,
    )
    want = np.asarray(ref.make_step(0.2))
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_lagged_matches_loop_oracle(rng):
    """T=2: the scanned lagged trajectory equals a numpy/loop re-derivation
    of the defined semantics — refresh the stale global set every T steps,
    update each block against (stale set with own block live), data-free
    target so scores are exact."""
    S, n, d, T = 4, 16, 2, 2
    init = rng.normal(size=(n, d))
    ds = _make(jnp.asarray(init), T)
    ds.run_steps(4, 0.1)
    got = np.asarray(ds.particles)

    # oracle: same math in explicit loops on float64
    score = jax.vmap(jax.grad(gmm_logp))
    blocks = [init[i * 4:(i + 1) * 4].copy() for i in range(S)]
    h = 1.0
    for refresh in range(2):  # 4 steps = 2 macro blocks of T=2
        stale = np.concatenate(blocks)
        for _ in range(T):
            new_blocks = []
            for r in range(S):
                view = stale.copy()
                view[r * 4:(r + 1) * 4] = blocks[r]
                s = np.asarray(score(jnp.asarray(view)))
                d2 = ((view[None, :, :] - blocks[r][:, None, :]) ** 2).sum(-1)
                kt = np.exp(-d2 / h)
                drive = kt @ s
                repulse = (2 / h) * (blocks[r] * kt.sum(1, keepdims=True) - kt @ view)
                new_blocks.append(blocks[r] + 0.1 * (drive + repulse) / n)
            blocks = new_blocks
    want = np.concatenate(blocks)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_lagged_differs_from_fresh_but_converges_same_fixpoint(rng):
    """T=4 trajectories differ from per-step exchange, but both samplers
    reach the same GMM spread (same fixed point)."""
    init = jnp.asarray(rng.normal(size=(32, 1)))
    lag = _make(init, 4)
    lag.run_steps(200, 0.3)
    fresh = DistSampler(
        4, _logp, None, init,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False,
    )
    fresh.run_steps(200, 0.3)
    a, b = np.asarray(lag.particles), np.asarray(fresh.particles)
    assert not np.allclose(a, b)  # different trajectories
    # both approximate the 1/3 N(-2,1) + 1/3 N(2,1) mixture spread (~2.24)
    assert abs(a.std() - b.std()) < 0.25
    assert 1.7 < a.std() < 2.8


def test_lagged_minibatch_runs(rng):
    """exchange_every composes with per-shard minibatched scores."""
    init = jnp.asarray(rng.normal(size=(16, 2)))
    x = jnp.asarray(rng.normal(size=(32, 2)))

    def lik(th, data):
        return -0.5 * jnp.sum((data[0] @ th) ** 2)

    ds = DistSampler(
        4, lik, None, init, data=(x,),
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=False, exchange_every=2, batch_size=4,
    )
    out = ds.run_steps(4, 0.05)
    assert np.all(np.isfinite(np.asarray(out)))


def test_lagged_validation(rng):
    init = jnp.asarray(rng.normal(size=(16, 2)))
    with pytest.raises(ValueError, match="all_particles"):
        DistSampler(4, _logp, None, init, exchange_particles=True,
                    exchange_scores=True, include_wasserstein=False,
                    exchange_every=2)
    with pytest.raises(ValueError, match="gather"):
        _make(init, 2, exchange_impl="ring")
    with pytest.raises(ValueError, match="Wasserstein"):
        DistSampler(4, _logp, None, init, exchange_particles=True,
                    exchange_scores=False, include_wasserstein=True,
                    wasserstein_solver="sinkhorn", exchange_every=2)
    with pytest.raises(ValueError, match="jacobi"):
        _make(init, 2, update_rule="gauss_seidel")
    with pytest.raises(ValueError, match=">= 1"):
        _make(init, 0)
    ds = _make(init, 2)
    with pytest.raises(ValueError, match="run_steps"):
        ds.make_step(0.1)
    with pytest.raises(ValueError, match="multiple"):
        ds.run_steps(3, 0.1)


def test_lagged_record_history(rng):
    """record=True under lagged exchange: the history is the per-sub-step
    pre-update global state — history[0] is the initial set, history[k] the
    state entering step k, and appending the final state reproduces the
    non-record trajectory at every step boundary."""
    T, n = 2, 16
    init = rng.normal(size=(n, 2))
    ds = _make(jnp.asarray(init), T)
    final, hist = ds.run_steps(6, 0.1, record=True)
    hist = np.asarray(hist)
    assert hist.shape == (6, n, 2)
    np.testing.assert_allclose(hist[0], init, rtol=1e-12)

    # re-running without record in two 2-step chunks and one more reproduces
    # the recorded states at steps 2 and 4 plus the final state
    ds2 = _make(jnp.asarray(init), T)
    ds2.run_steps(2, 0.1)
    np.testing.assert_allclose(hist[2], np.asarray(ds2.particles), rtol=1e-9)
    ds2.run_steps(2, 0.1)
    np.testing.assert_allclose(hist[4], np.asarray(ds2.particles), rtol=1e-9)
    ds2.run_steps(2, 0.1)
    np.testing.assert_allclose(
        np.asarray(final), np.asarray(ds2.particles), rtol=1e-9
    )

    # intra-block rows move too (real per-sub-step snapshots, not repeats)
    assert not np.allclose(hist[1], hist[0])
    assert not np.allclose(hist[3], hist[2])
