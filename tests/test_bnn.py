"""Bayesian-NN regression model (BASELINE.json config 5): layout round-trips,
density cross-checks against torch distributions, numeric gradients, sharded
parity, and a small end-to-end convergence run."""

import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu import DistSampler, Sampler
from dist_svgd_tpu.models import bnn
from dist_svgd_tpu.utils.datasets import load_uci_regression


@pytest.fixture
def rng():
    return np.random.default_rng(23)


def _tiny_problem(rng, n_rows=16, n_features=3, n_hidden=4):
    x = rng.normal(size=(n_rows, n_features))
    y = np.sin(x @ rng.normal(size=n_features)) + 0.05 * rng.normal(size=n_rows)
    return jnp.asarray(x), jnp.asarray(y), n_features, n_hidden


def test_pack_unpack_roundtrip(rng):
    n_features, n_hidden = 5, 7
    d = bnn.num_params(n_features, n_hidden)
    theta = jnp.asarray(rng.normal(size=d))
    p = bnn.unpack(theta, n_features, n_hidden)
    flat = jnp.concatenate(
        [p.w1.reshape(-1), p.b1, p.w2, p.b2[None], p.log_gamma[None], p.log_lambda[None]]
    )
    np.testing.assert_allclose(np.asarray(flat), np.asarray(theta))


def test_predict_matches_manual(rng):
    x, _, n_features, n_hidden = _tiny_problem(rng)
    d = bnn.num_params(n_features, n_hidden)
    theta = jnp.asarray(rng.normal(size=d))
    p = bnn.unpack(theta, n_features, n_hidden)
    want = np.maximum(np.asarray(x) @ np.asarray(p.w1) + np.asarray(p.b1), 0.0) @ np.asarray(
        p.w2
    ) + float(p.b2)
    got = np.asarray(bnn.predict(theta, x, n_features, n_hidden))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_logp_matches_torch(rng):
    """Cross-check the full joint density against torch.distributions."""
    torch = pytest.importorskip("torch")
    from torch.distributions.gamma import Gamma
    from torch.distributions.normal import Normal

    x, y, n_features, n_hidden = _tiny_problem(rng)
    d = bnn.num_params(n_features, n_hidden)
    theta = rng.normal(size=d)
    got = float(bnn.bnn_logp(jnp.asarray(theta), (x, y), n_features, n_hidden))

    th = torch.tensor(theta)
    log_gamma, log_lambda = th[-2], th[-1]
    gamma, lam = log_gamma.exp(), log_lambda.exp()
    w = th[:-2]
    pred = torch.tensor(np.asarray(bnn.predict(jnp.asarray(theta), x, n_features, n_hidden)))
    yt = torch.tensor(np.asarray(y))
    want = Normal(pred, (1.0 / gamma).sqrt()).log_prob(yt).sum()
    want = want + Normal(0.0, (1.0 / lam).sqrt()).log_prob(w).sum()
    # log-precision densities include the change-of-variables Jacobian
    want = want + Gamma(bnn.A0, bnn.B0).log_prob(gamma) + log_gamma
    want = want + Gamma(bnn.A0, bnn.B0).log_prob(lam) + log_lambda
    assert got == pytest.approx(float(want), rel=1e-8)


def test_split_equals_joint(rng):
    """likelihood + prior from make_bnn_split sums to bnn_logp exactly."""
    x, y, n_features, n_hidden = _tiny_problem(rng)
    d = bnn.num_params(n_features, n_hidden)
    theta = jnp.asarray(rng.normal(size=d))
    lik, prior = bnn.make_bnn_split(n_features, n_hidden)
    joint = float(bnn.bnn_logp(theta, (x, y), n_features, n_hidden))
    assert float(lik(theta, (x, y))) + float(prior(theta)) == pytest.approx(joint, rel=1e-10)


def test_score_matches_numeric_grad(rng):
    x, y, n_features, n_hidden = _tiny_problem(rng)
    d = bnn.num_params(n_features, n_hidden)
    theta = jnp.asarray(rng.normal(size=d) * 0.5)
    logp = bnn.make_bnn_logp(n_features, n_hidden)
    g = np.asarray(jax.grad(logp)(theta, (x, y)))
    eps = 1e-6
    for i in rng.choice(d, size=6, replace=False):
        e = np.zeros(d)
        e[i] = eps
        num = (
            float(logp(theta + e, (x, y))) - float(logp(theta - e, (x, y)))
        ) / (2 * eps)
        assert g[i] == pytest.approx(num, rel=2e-4, abs=1e-6)


def test_init_particles_shapes_and_scale():
    key = jax.random.PRNGKey(0)
    parts = bnn.init_particles(key, 12, 5, 4)
    assert parts.shape == (12, bnn.num_params(5, 4))
    assert np.isfinite(np.asarray(parts)).all()
    # weight entries are small (fan-in scaled), log-precisions are O(log Gamma draws)
    assert float(jnp.abs(parts[:, :-2]).mean()) < 1.0


def test_uci_loader_split_and_standardization():
    sp = load_uci_regression("boston", split=3)
    assert sp.x_train.shape[1] == 13
    assert sp.x_train.shape[0] + sp.x_test.shape[0] == 1000
    # train features/targets are z-scored
    np.testing.assert_allclose(sp.x_train.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(sp.x_train.std(axis=0), 1.0, atol=1e-4)
    assert abs(sp.y_train.mean()) < 1e-5
    # test targets stay on the original scale
    assert abs(float(np.mean(sp.y_test)) - sp.y_mean) < 3 * sp.y_std
    # splits differ but are deterministic
    sp2 = load_uci_regression("boston", split=3)
    np.testing.assert_array_equal(sp.x_train, sp2.x_train)
    sp3 = load_uci_regression("boston", split=4)
    assert not np.array_equal(sp.x_train, sp3.x_train)


def test_uci_loader_unknown_name():
    with pytest.raises(ValueError, match="unknown UCI"):
        load_uci_regression("nope")


def test_sharded_bnn_matches_single_device(rng):
    """all_scores sharded BNN step == single-device full computation
    (the SURVEY §4 property test, on the BNN model)."""
    x, y, n_features, n_hidden = _tiny_problem(rng, n_rows=16)
    d = bnn.num_params(n_features, n_hidden)
    n = 8
    parts = jnp.asarray(rng.normal(size=(n, d)) * 0.3)
    lik, prior = bnn.make_bnn_split(n_features, n_hidden)

    single = Sampler(d, lambda t: lik(t, (x, y)) + prior(t))
    ref, _ = single.run(n, 3, 1e-2, record=False, initial_particles=parts, dtype=jnp.float64)

    # the split log_prior path adds the prior gradient once (not psum-summed
    # S times, which is what happens when the prior lives inside logp — the
    # reference's all_scores quirk, dsvgd/distsampler.py:93)
    dist = DistSampler(
        4, lik, None, parts.astype(jnp.float64), data=(x, y),
        exchange_particles=True, exchange_scores=True, include_wasserstein=False,
        log_prior=prior,
    )
    for _ in range(3):
        out = dist.make_step(1e-2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-8, atol=1e-10)


@pytest.mark.slow
def test_bnn_convergence_beats_prior():
    """End-to-end: 200 SVGD steps on a small split must beat the untrained
    ensemble's RMSE and a predict-the-mean baseline."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "experiments"))
    from bnn import run as bnn_run

    sp = load_uci_regression("yacht", 0)
    baseline_rmse = float(np.sqrt(np.mean((np.asarray(sp.y_test) - sp.y_mean) ** 2)))

    _, m0 = bnn_run("yacht", 0, nproc=1, nparticles=64, n_hidden=16, niter=0,
                    stepsize=1e-3, batch_size=0)
    _, m = bnn_run("yacht", 0, nproc=1, nparticles=64, n_hidden=16, niter=200,
                   stepsize=5e-3, batch_size=0)
    assert m["test_rmse"] < baseline_rmse
    assert m["test_rmse"] < m0["test_rmse"]
