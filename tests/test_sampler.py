"""Single-device Sampler: API schema, timestep convention, convergence."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu import Sampler
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.utils.history import history_to_dataframe

from _oracle import gauss_seidel_sweep


def quad_logp(x):
    return -0.5 * jnp.sum((x - 2.0) ** 2)


def test_sample_schema_and_timestep_convention():
    """Columns timestep/particle/value; snapshots pre-update at 0..T-1 plus a
    final post-update snapshot at T (dsvgd/sampler.py:62-73)."""
    s = Sampler(2, quad_logp)
    n, T = 5, 7
    df = s.sample(n, T, 0.1, seed=0)
    assert list(df.columns) == ["timestep", "particle", "value"]
    assert len(df) == (T + 1) * n
    assert df.timestep.min() == 0 and df.timestep.max() == T
    assert df.value.iloc[0].shape == (2,)

    # timestep-0 snapshot is exactly the initial N(0,1) draw
    final, hist = s.run(n, T, 0.1, seed=0)
    from dist_svgd_tpu.utils.rng import init_particles, as_key

    np.testing.assert_allclose(
        np.asarray(hist[0]), np.asarray(init_particles(as_key(0), n, 2)), rtol=1e-12
    )
    np.testing.assert_allclose(np.asarray(hist[-1]), np.asarray(final), rtol=1e-12)


def test_gauss_seidel_sampler_matches_oracle():
    rng = np.random.default_rng(23)
    init = rng.normal(size=(4, 1))
    s = Sampler(1, quad_logp, update_rule="gauss_seidel")
    _, hist = s.run(4, 2, 0.1, initial_particles=jnp.asarray(init))

    want = np.array(init)
    for _ in range(2):
        want = gauss_seidel_sweep(want, lambda x: -(np.asarray(x) - 2.0), 0.1)
    np.testing.assert_allclose(np.asarray(hist[-1]), want, rtol=1e-9)


def test_gaussian_convergence():
    """Particles approximate N(2, 1) after enough steps."""
    s = Sampler(1, quad_logp)
    final, _ = s.run(64, 400, 0.3, seed=1, record=False)
    assert float(jnp.mean(final)) == pytest.approx(2.0, abs=0.15)
    assert float(jnp.std(final)) == pytest.approx(1.0, abs=0.2)


def test_gmm_convergence_moments():
    """GMM sanity check (reference experiments/gmm.py): equal-weight mixture of
    N(-2,1), N(2,1) has mean 0, variance 5."""
    s = Sampler(1, gmm_logp)
    final, _ = s.run(96, 600, 0.5, seed=42, record=False)
    assert float(jnp.mean(final)) == pytest.approx(0.0, abs=0.35)
    assert float(jnp.var(final)) == pytest.approx(5.0, abs=1.2)


def test_history_dataframe_no_particle_column():
    hist = np.zeros((2, 3, 1))
    df = history_to_dataframe(hist, include_particle_column=False)
    assert list(df.columns) == ["timestep", "value"]


def test_determinism_same_seed():
    s = Sampler(1, gmm_logp)
    a, _ = s.run(16, 50, 0.5, seed=7, record=False)
    b, _ = s.run(16, 50, 0.5, seed=7, record=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
