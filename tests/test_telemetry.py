"""Unified telemetry (dist_svgd_tpu/telemetry/): metrics registry semantics
+ Prometheus exposition golden, span tracer nesting across threads, the
disabled-mode zero-allocation no-op, Chrome-trace export validity, the
serving/resilience integration (queue-depth gauge, shed counter, request
lane trees, supervisor counters), and tools/trace_report summarisation."""

import json
import os
import sys
import threading

import numpy as np
import pytest

from dist_svgd_tpu.telemetry import (
    LATENCY_BUCKETS_S,
    MetricsRegistry,
    Tracer,
)
from dist_svgd_tpu.telemetry import trace as trace_mod

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


@pytest.fixture
def global_tracer():
    """Enable the global tracer for one test; always disable after (other
    tests pin the zero-cost disabled path)."""
    tracer = trace_mod.enable()
    try:
        yield tracer
    finally:
        trace_mod.disable()


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


# --------------------------------------------------------------------- #
# metrics registry


def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help")
    c.inc()
    c.inc(4)
    c.inc(2, route="/p")
    assert c.value() == 5
    assert c.value(route="/p") == 2
    with pytest.raises(ValueError, match="decrease"):
        c.inc(-1)
    g = reg.gauge("t_depth")
    g.set(7)
    g.inc(3)
    g.dec()
    assert g.value() == 9


def test_registry_get_or_create_and_type_conflict():
    reg = MetricsRegistry()
    assert reg.counter("x_total") is reg.counter("x_total")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad name")


def test_histogram_quantiles_log_spaced():
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_seconds")
    assert h.buckets == LATENCY_BUCKETS_S
    assert h.quantile(0.5) == 0.0  # empty
    for ms in (1, 1, 2, 4, 100):
        h.observe(ms / 1e3)
    # p50 crosses in the bucket containing ~1-2 ms; interpolation keeps it
    # inside the crossing bucket's bounds
    p50 = h.quantile(0.50)
    assert 0.8e-3 <= p50 <= 3.3e-3
    assert h.quantile(1.0) >= 0.05
    s = h.summary(scale=1e3)
    assert s["count"] == 5 and s["sum"] == pytest.approx(108.0)
    assert s["p99"] >= s["p95"] >= s["p50"] > 0
    # an observation past the last bucket lands in +Inf and the quantile
    # clamps to the last finite bound
    h.observe(100.0)
    assert h.quantile(1.0) == LATENCY_BUCKETS_S[-1]
    with pytest.raises(ValueError, match="quantile"):
        h.quantile(1.5)


def test_histogram_rejects_bad_buckets():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="increasing"):
        reg.histogram("t_bad", buckets=(1.0, 1.0, 2.0))


def test_prometheus_exposition_golden():
    """Exact text-format output for a small fixed registry: names sorted,
    HELP/TYPE headers, label escaping, cumulative histogram buckets with
    the +Inf terminal, _sum/_count."""
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "total requests")
    c.inc(3)
    c.inc(2, route="/p")
    reg.gauge("t_depth", "queue depth").set(7)
    h = reg.histogram("t_lat_seconds", "latency", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.05, 5.0):
        h.observe(v)
    # exposition-format escaping (satellite pin): label values escape
    # backslash, double-quote, and newline; HELP text escapes ONLY
    # backslash and newline — a double quote stays literal there (HELP is
    # not a quoted string in the format)
    esc = reg.counter("t_esc_total", 'say "hi"\\no\nwrap')
    esc.inc(1, path='a"b\\c\nd')
    expected = (
        "# HELP t_depth queue depth\n"
        "# TYPE t_depth gauge\n"
        "t_depth 7\n"
        "# HELP t_esc_total say \"hi\"\\\\no\\nwrap\n"
        "# TYPE t_esc_total counter\n"
        't_esc_total{path="a\\"b\\\\c\\nd"} 1\n'
        "# HELP t_lat_seconds latency\n"
        "# TYPE t_lat_seconds histogram\n"
        't_lat_seconds_bucket{le="0.001"} 1\n'
        't_lat_seconds_bucket{le="0.01"} 1\n'
        't_lat_seconds_bucket{le="0.1"} 2\n'
        't_lat_seconds_bucket{le="+Inf"} 3\n'
        "t_lat_seconds_sum 5.0505\n"
        "t_lat_seconds_count 3\n"
        "# HELP t_requests_total total requests\n"
        "# TYPE t_requests_total counter\n"
        "t_requests_total 3\n"
        't_requests_total{route="/p"} 2\n'
    )
    assert reg.exposition() == expected


def test_exposition_escapes_label_values():
    reg = MetricsRegistry()
    reg.counter("t_esc_total").inc(1, path='a"b\\c\nd')
    text = reg.exposition()
    assert 't_esc_total{path="a\\"b\\\\c\\nd"} 1' in text
    # escaping order: backslash first, so a pre-escaped-looking value is
    # not double-mangled into an escape sequence
    reg.gauge("t_esc2").set(1, v="\\n")
    assert 't_esc2{v="\\\\n"} 1' in reg.exposition()


def test_registry_thread_safety_exact_counts():
    reg = MetricsRegistry()
    c = reg.counter("t_conc_total")
    h = reg.histogram("t_conc_seconds")

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.001)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 8000
    assert h.summary()["count"] == 8000


# --------------------------------------------------------------------- #
# label-cardinality guard (round 14): per-tenant labels must not become
# an unbounded series leak


def test_label_cardinality_bound_pins_and_rolls_up():
    import warnings

    reg = MetricsRegistry(max_label_sets=3)
    c = reg.counter("t_card_total", "bounded")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for i in range(8):
            c.inc(tenant=f"t{i}")
    # the first three sets are admitted; the five overflow increments all
    # land in ONE reserved rollup series
    for i in range(3):
        assert c.value(tenant=f"t{i}") == 1
    assert c.value(tenant="other") == 5
    assert c.value(tenant="t5") == 0  # never admitted as its own series
    # one-time warning per metric, not per overflowing write
    card_warns = [w for w in caught
                  if issubclass(w.category, RuntimeWarning)
                  and "max_label_sets" in str(w.message)]
    assert len(card_warns) == 1
    # admitted series keep updating after the bound is hit
    c.inc(tenant="t0")
    assert c.value(tenant="t0") == 2


def test_label_cardinality_histogram_and_per_metric_override():
    reg = MetricsRegistry()  # generous registry default...
    h = reg.histogram("t_card_seconds", "bounded", max_label_sets=2)
    with pytest.warns(RuntimeWarning, match="max_label_sets"):
        for i in range(4):
            h.observe(0.001 * (i + 1), tenant=f"t{i}")
    assert h.summary(tenant="t0")["count"] == 1
    # t2 and t3 aggregated into the rollup
    assert h.summary(tenant="other")["count"] == 2
    # default-bound metrics on the same registry are unaffected
    c = reg.counter("t_card_free_total")
    for i in range(10):
        c.inc(tenant=f"t{i}")
    assert c.value(tenant="other") == 0


def test_label_cardinality_rollup_exposition():
    reg = MetricsRegistry(max_label_sets=1)
    c = reg.counter("t_card_expo_total", "rollup exposition")
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        c.inc(tenant="real")
        c.inc(2, tenant="leaky-1")
        c.inc(3, tenant="leaky-2")
    text = reg.exposition()
    assert 't_card_expo_total{tenant="real"} 1' in text
    # the reserved rollup series is a first-class Prometheus series with
    # the SAME label name and the reserved value
    assert 't_card_expo_total{tenant="other"} 5' in text
    assert "leaky" not in text


# --------------------------------------------------------------------- #
# span tracer


def test_span_nesting_across_threads():
    """Each thread keeps its own span stack: concurrent nested spans land
    on their own tids, children contained in their parents, no cross-thread
    bleed of the 'active span' (instants tag the right parent)."""
    tracer = Tracer()
    barrier = threading.Barrier(2, timeout=10)

    def work(name):
        with tracer.span(f"{name}.outer"):
            barrier.wait()
            with tracer.span(f"{name}.inner"):
                tracer.instant(f"{name}.mark")
            barrier.wait()

    threads = [threading.Thread(target=work, args=(n,)) for n in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    counts = tracer.counts()
    for name in ("a.outer", "a.inner", "b.outer", "b.inner",
                 "a.mark", "b.mark"):
        assert counts[name] == 1, counts
    events = tracer.chrome_events()
    spans = {e["name"]: e for e in events if e["ph"] == "X"}
    instants = {e["name"]: e for e in events if e["ph"] == "i"}
    for side in ("a", "b"):
        outer, inner = spans[f"{side}.outer"], spans[f"{side}.inner"]
        assert outer["tid"] == inner["tid"]
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
        # the instant fired inside this thread's inner span, not the
        # concurrent sibling's
        assert instants[f"{side}.mark"]["args"]["in_span"] == f"{side}.inner"
        assert instants[f"{side}.mark"]["tid"] == inner["tid"]
    assert spans["a.outer"]["tid"] != spans["b.outer"]["tid"]


def test_disabled_span_is_shared_noop_and_zero_alloc():
    """The whole point of the disabled path: module-level span()/instant()
    allocate NOTHING (shared singleton, no clock read, no event) so leaving
    instrumentation in hot loops is free."""
    import tracemalloc

    assert not trace_mod.enabled()
    assert trace_mod.span("x") is trace_mod.span("y")  # shared singleton
    sp = trace_mod.span("x")
    assert sp.fence(42) == 42  # passthrough
    assert sp.tag(a=1) is sp

    def loop():
        for _ in range(200):
            with trace_mod.span("hot"):
                pass
            trace_mod.instant("mark")

    loop()  # warm any lazy caches before measuring
    tracemalloc.start()
    try:
        filters = [tracemalloc.Filter(True, trace_mod.__file__)]
        before = tracemalloc.take_snapshot().filter_traces(filters)
        loop()
        after = tracemalloc.take_snapshot().filter_traces(filters)
    finally:
        tracemalloc.stop()
    grown = sum(max(s.size_diff, 0)
                for s in after.compare_to(before, "lineno"))
    assert grown == 0, f"disabled span path allocated {grown} bytes"


def test_enable_disable_idempotent_and_global_span():
    try:
        t1 = trace_mod.enable()
        t2 = trace_mod.enable()
        assert t1 is t2
        assert trace_mod.enabled()
        with trace_mod.span("g.outer", {"k": 1}):
            trace_mod.instant("g.mark")
        assert t1.counts() == {"g.outer": 1, "g.mark": 1}
    finally:
        out = trace_mod.disable()
    assert out is t1
    assert trace_mod.disable() is None  # second disable is a no-op
    assert not trace_mod.enabled()


def test_span_records_on_exception_and_tags_error():
    tracer = Tracer()
    with pytest.raises(RuntimeError):
        with tracer.span("boom"):
            raise RuntimeError("x")
    events = tracer.chrome_events()
    ev = [e for e in events if e["ph"] == "X"][0]
    assert ev["name"] == "boom" and ev["args"]["error"] == "RuntimeError"
    # the stack popped despite the exception: a new span is top-level again
    with tracer.span("next"):
        assert tracer.active_span().name == "next"


def test_span_fence_blocks_device_value():
    import jax.numpy as jnp

    tracer = Tracer()
    with tracer.span("fenced") as sp:
        out = sp.fence(jnp.ones((8, 8)) * 2)
    assert float(out[0, 0]) == 2.0
    assert tracer.counts() == {"fenced": 1}


def test_max_events_drops_and_counts():
    clock = ManualClock()
    tracer = Tracer(clock=clock, max_events=3)
    for i in range(5):
        clock.advance(1.0)
        tracer.instant(f"e{i}")
    assert len(tracer.chrome_events()) - 1 == 3  # +1 thread_name metadata
    assert tracer.dropped_events == 2


def test_lane_tree_allocates_non_overlapping_lanes():
    """Two overlapping request trees land on different lanes; a later
    non-overlapping one reuses lane 0; children clamp into the parent."""
    clock = ManualClock()
    tracer = Tracer(clock=clock)
    tracer.lane_tree("r0", 0.0, 1.0, children=[("c0", 0.0, 0.4)])
    tracer.lane_tree("r1", 0.5, 2.0)   # overlaps r0 → lane 1
    tracer.lane_tree("r2", 1.5, 3.0)   # lane 0 free again (ended at 1.0)
    events = [e for e in tracer.chrome_events() if e["ph"] == "X"]
    by_name = {e["name"]: e for e in events}
    assert by_name["r0"]["tid"] == by_name["r2"]["tid"]
    assert by_name["r1"]["tid"] != by_name["r0"]["tid"]
    assert by_name["c0"]["tid"] == by_name["r0"]["tid"]


def test_chrome_export_valid_json_monotonic_ts(tmp_path):
    clock = ManualClock()
    tracer = Tracer(clock=clock)
    for i in range(4):
        with tracer.span(f"s{i}"):
            clock.advance(0.01)
        tracer.instant(f"i{i}")
    path = str(tmp_path / "trace.json")
    n = tracer.export_chrome(path)
    with open(path) as fh:
        doc = json.load(fh)  # valid JSON
    events = doc["traceEvents"]
    assert len(events) == n
    ts = [e["ts"] for e in events if e["ph"] in ("X", "i")]
    assert ts == sorted(ts)  # monotonic timeline
    assert all(e["ts"] >= 0 for e in events if "ts" in e)
    # metadata names every tid used by real events
    named = {e["tid"] for e in events if e["ph"] == "M"}
    used = {e["tid"] for e in events if e["ph"] != "M"}
    assert used <= named


def test_jsonl_exporter_mirrors_events(tmp_path):
    from dist_svgd_tpu.utils.metrics import JsonlLogger

    path = str(tmp_path / "spans.jsonl")
    clock = ManualClock()
    with JsonlLogger(path=path) as logger:
        tracer = Tracer(clock=clock, jsonl=logger)
        with tracer.span("a", {"k": 1}):
            clock.advance(0.5)
            tracer.instant("m")
    lines = [json.loads(x) for x in open(path)]
    kinds = {(r["kind"], r["name"]) for r in lines}
    assert kinds == {("instant", "m"), ("span", "a"),
                     ("process", f"pid-{os.getpid()}")}
    # the process-identity header leads the stream (a stitcher labels the
    # file before reading any span) and carries the clock anchor
    assert lines[0]["kind"] == "process"
    assert lines[0]["role"] == "process"
    assert isinstance(lines[0]["anchor_unix_s"], float)
    assert lines[0]["anchor_trace_s"] == 0.0
    span_rec = [r for r in lines if r["kind"] == "span"][0]
    assert span_rec["dur"] == pytest.approx(0.5)


# --------------------------------------------------------------------- #
# serving integration: registry + lane trees


def _tiny_engine(rng, registry=None):
    from dist_svgd_tpu.serving import PredictiveEngine

    parts = rng.normal(size=(16, 5)).astype(np.float32)
    return PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16,
                            registry=registry)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def test_batcher_shed_increments_registry_counter(rng):
    """Satellite regression pin: an Overloaded shed increments
    svgd_serve_shed_total (and the queue-depth gauge tracks the rows)."""
    from dist_svgd_tpu.serving import MicroBatcher, Overloaded

    reg = MetricsRegistry()
    eng = _tiny_engine(rng, registry=reg)
    bat = MicroBatcher(eng.predict, max_batch=4, max_queue_rows=4,
                       registry=reg, autostart=False)
    depth = reg.gauge("svgd_serve_queue_depth_rows")
    bat.submit(np.zeros((4, 4), np.float32))  # fills the bounded queue
    assert depth.value(batcher=bat.metrics_instance) == 4
    with pytest.raises(Overloaded):
        bat.submit(np.zeros((1, 4), np.float32))
    assert reg.counter("svgd_serve_shed_total").value() == 1
    bat.start()
    bat.close(drain=True)
    assert depth.value(batcher=bat.metrics_instance) == 0
    assert reg.counter("svgd_serve_requests_total").value() == 1
    assert reg.histogram(
        "svgd_serve_request_latency_seconds").summary()["count"] == 1
    # per-instance gauge label: a second batcher on the same registry
    # reports its own depth instead of overwriting this one's — while the
    # counters keep aggregating across instances
    bat2 = MicroBatcher(eng.predict, max_batch=4, registry=reg,
                        autostart=False)
    bat2.submit(np.zeros((2, 4), np.float32))
    assert depth.value(batcher=bat2.metrics_instance) == 2
    assert depth.value(batcher=bat.metrics_instance) == 0
    bat2.start()
    bat2.close(drain=True)
    assert reg.counter("svgd_serve_requests_total").value() == 2


def test_engine_bucket_counters_in_registry(rng):
    reg = MetricsRegistry()
    eng = _tiny_engine(rng, registry=reg)
    eng.predict(np.zeros((3, 4), np.float32))   # miss (compile bucket 4)
    eng.predict(np.zeros((4, 4), np.float32))   # hit (same bucket)
    assert reg.counter("svgd_engine_bucket_misses_total").value() == 1
    assert reg.counter("svgd_engine_bucket_hits_total").value() == 1


def test_request_lane_trees_under_tracing(rng, global_tracer):
    """Acceptance shape: request spans contain queue-wait / coalesce /
    dispatch children inside the parent interval, on a lane tid."""
    from dist_svgd_tpu.serving import MicroBatcher

    reg = MetricsRegistry()
    eng = _tiny_engine(rng, registry=reg)
    eng.warmup()
    bat = MicroBatcher(eng.predict, max_batch=8, max_wait_ms=1.0,
                       registry=reg)
    try:
        for _ in range(3):
            bat.submit(rng.normal(size=(2, 4)).astype(np.float32)).result(
                timeout=10)
    finally:
        bat.close(drain=True)
    events = [e for e in global_tracer.chrome_events() if e["ph"] == "X"]
    reqs = [e for e in events if e["name"] == "serve.request"]
    assert len(reqs) == 3
    for req in reqs:
        t0, t1 = req["ts"], req["ts"] + req["dur"]
        children = [e for e in events
                    if e["tid"] == req["tid"] and e is not req
                    and t0 - 1e-3 <= e["ts"] and
                    e["ts"] + e["dur"] <= t1 + 1e-3]
        names = {c["name"] for c in children}
        assert {"serve.queue_wait", "serve.coalesce",
                "serve.dispatch"} <= names, names
        assert req["args"]["rows"] == 2
    # the engine's live spans rode the worker thread alongside
    names = {e["name"] for e in events}
    assert "engine.predict" in names and "engine.dispatch" in names


# --------------------------------------------------------------------- #
# resilience integration


def test_supervisor_registry_counters(tmp_path):
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp
    from dist_svgd_tpu.resilience import FaultPlan, RaiseAt, RunSupervisor
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    reg = MetricsRegistry()
    parts = init_particles_per_shard(0, 16, 2, 2)
    ds = dt.DistSampler(2, lambda th, _: gmm_logp(th), None, parts,
                        exchange_particles=True, exchange_scores=False,
                        include_wasserstein=False)
    sup = RunSupervisor(ds, 8, 0.05,
                        checkpoint_dir=str(tmp_path / "ck"),
                        checkpoint_every=4, segment_steps=4,
                        sleep=lambda s: None, registry=reg,
                        faults=FaultPlan(RaiseAt(4)))
    report = sup.run()
    assert report["status"] == "completed"
    assert reg.counter("svgd_train_restarts_total").value(
        kind="transient") == 1
    assert reg.counter("svgd_train_steps_total").value() == 8
    # initial + step-4 (pre- and post-retry saves collapse onto the grid)
    # + final checkpoint all recorded with walls
    ck = reg.histogram("svgd_train_checkpoint_seconds").summary()
    assert ck["count"] == report["checkpoints"] >= 2
    seg = reg.histogram("svgd_train_segment_seconds").summary()
    assert seg["count"] == report["segments"] == 2


def test_supervisor_spans_under_tracing(tmp_path, global_tracer):
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp
    from dist_svgd_tpu.resilience import RunSupervisor
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    parts = init_particles_per_shard(0, 16, 2, 2)
    ds = dt.DistSampler(2, lambda th, _: gmm_logp(th), None, parts,
                        exchange_particles=True, exchange_scores=False,
                        include_wasserstein=False)
    sup = RunSupervisor(ds, 8, 0.05, checkpoint_dir=str(tmp_path / "ck"),
                        checkpoint_every=4, segment_steps=4,
                        sleep=lambda s: None, registry=MetricsRegistry())
    sup.run()
    counts = global_tracer.counts()
    assert counts.get("train.segment") == 2
    assert counts.get("train.checkpoint", 0) >= 2
    # segment spans wrap the sampler's per-dispatch step-chunk spans
    assert counts.get("train.step_chunk", 0) >= 2


def test_steptimer_span_bridge(global_tracer):
    from dist_svgd_tpu.utils.metrics import StepTimer

    timer = StepTimer(span_name="bench.lap")
    timer.mark()
    timer.mark()
    assert global_tracer.counts().get("bench.lap") == 2


# --------------------------------------------------------------------- #
# trace_report


def test_trace_report_summarizes_chrome_and_jsonl(tmp_path):
    import trace_report

    clock = ManualClock()
    tracer = Tracer(clock=clock)
    with tracer.span("outer"):
        clock.advance(0.004)
        with tracer.span("inner"):
            clock.advance(0.006)
            tracer.instant("xla_compile")
    path = str(tmp_path / "t.json")
    tracer.export_chrome(path)
    spans, instants = trace_report.load_events(path)
    report = trace_report.summarize(spans, instants)
    assert report["spans"]["outer"]["count"] == 1
    assert report["spans"]["outer"]["p50_ms"] == pytest.approx(10.0)
    assert report["spans"]["inner"]["p50_ms"] == pytest.approx(6.0)
    # self-time: outer minus its child
    assert report["spans"]["outer"]["self_ms"] == pytest.approx(4.0)
    assert report["compiles"] == 1
    assert report["compile_spans"] == {"inner": 1}
    assert "outer" in trace_report.render(report)

    # JSONL form round-trips through the same summary
    from dist_svgd_tpu.utils.metrics import JsonlLogger

    jl_path = str(tmp_path / "t.jsonl")
    clock2 = ManualClock()
    with JsonlLogger(path=jl_path) as logger:
        tr2 = Tracer(clock=clock2, jsonl=logger)
        with tr2.span("a"):
            clock2.advance(0.002)
    spans2, instants2 = trace_report.load_events(jl_path)
    report2 = trace_report.summarize(spans2, instants2)
    assert report2["spans"]["a"]["p50_ms"] == pytest.approx(2.0)
    assert report2["compiles"] == 0


def test_trace_report_cli_json(tmp_path, capsys):
    import trace_report

    tracer = Tracer(clock=ManualClock())
    with tracer.span("x"):
        pass
    path = str(tmp_path / "t.json")
    tracer.export_chrome(path)
    assert trace_report.main([path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["n_spans"] == 1 and "x" in out["spans"]


def test_trace_report_cli_bad_inputs(tmp_path, capsys):
    """Missing / empty / corrupt / truncated inputs exit nonzero with ONE
    line on stderr — never an unhandled traceback (satellite pin)."""
    import trace_report

    missing = str(tmp_path / "nope.json")
    assert trace_report.main([missing]) == 2
    err = capsys.readouterr().err
    assert err.startswith("trace_report:") and err.count("\n") == 1

    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert trace_report.main([str(empty)]) == 2
    assert capsys.readouterr().err.startswith("trace_report:")

    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text('{"traceEvents": [{"ph": "X", "na')  # truncated doc
    assert trace_report.main([str(corrupt)]) == 2
    assert capsys.readouterr().err.startswith("trace_report:")

    corrupt_jsonl = tmp_path / "corrupt.jsonl"
    corrupt_jsonl.write_text(
        '{"kind": "span", "name": "a", "ts": 0.0, "dur": 1.0}\n{"kind": bro')
    assert trace_report.main([str(corrupt_jsonl)]) == 2
    assert capsys.readouterr().err.startswith("trace_report:")

    # structurally valid but zero trace events: a distinct, clear error
    zero = tmp_path / "zero.json"
    zero.write_text('{"traceEvents": []}\n')
    assert trace_report.main([str(zero)]) == 1
    assert "no trace events" in capsys.readouterr().err

    # a non-bundle handed to --postmortem is refused, not half-rendered
    good = tmp_path / "good.json"
    tracer2 = Tracer(clock=ManualClock())
    with tracer2.span("x"):
        pass
    tracer2.export_chrome(str(good))
    assert trace_report.main([str(good), "--postmortem"]) == 2
    assert "postmortem" in capsys.readouterr().err


def test_trace_report_postmortem_render(tmp_path, capsys):
    """A flight-recorder bundle renders (human + --json) with reason,
    context, diagnostics, metrics, and ring events."""
    import trace_report

    from dist_svgd_tpu.telemetry import FlightRecorder

    reg = MetricsRegistry()
    reg.counter("t_restarts_total").inc(2)
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path), registry=reg,
                         clock=lambda: 99.0)
    rec.record("diagnostics", ksd=1.25, ess=4.0)
    rec.record("guard_violation", t=8, reason="posterior drift")
    path = rec.dump("guard_violation", {"t": 8, "step_size": 0.05})
    assert trace_report.main([path, "--postmortem"]) == 0
    out = capsys.readouterr().out
    assert "postmortem: guard_violation" in out
    assert "context.step_size = 0.05" in out
    assert "ksd = 1.25" in out
    assert "t_restarts_total = 2" in out
    assert "guard_violation" in out.splitlines()[-1] or "guard_violation" in out
    assert trace_report.main([path, "--postmortem", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["header"]["reason"] == "guard_violation"
    assert doc["diagnostics"]["ksd"] == 1.25
    assert any(e["kind"] == "guard_violation" for e in doc["events"])
