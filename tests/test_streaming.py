"""Streaming subsystem (dist_svgd_tpu/streaming/): seeded event-time
sources with deterministic drift windows, the bounded ingest buffer's
loud drop accounting, the fixed-shape RowRing corpus, and the
StreamingSupervisor's segment lifecycle — bitwise kill→resume mid-stream,
drift-triggered re-fit escalation, rejected hot reloads rolling back, and
zero steady-state recompiles.  Everything runs on CPU with manual clocks
(the measured real-clock loop lives in tools/freshness_drill.py)."""

import numpy as np
import pytest

import dist_svgd_tpu as dt
from dist_svgd_tpu.models.logreg import make_logreg_split
from dist_svgd_tpu.resilience import DriftAt, GuardConfig
from dist_svgd_tpu.streaming import (
    CovertypeReplayStream,
    GrowingCorpusStream,
    LabelFlipStream,
    MeanShiftStream,
    RowRing,
    StreamBuffer,
    StreamingSupervisor,
)
from dist_svgd_tpu.telemetry import MetricsRegistry
from dist_svgd_tpu.telemetry.diagnostics import (
    DiagnosticsConfig,
    PosteriorDiagnostics,
    ReloadPolicy,
)


class ManualClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt_s):
        self.t += dt_s


def no_sleep(_s):
    pass


# --------------------------------------------------------------------- #
# sources: purity, event-time arithmetic, drift windows


def test_source_batches_pure_and_timestamped():
    s = GrowingCorpusStream(batch_rows=8, dim=3, seed=7, period_s=2.0,
                            start_time=10.0)
    a, b = s.batch_at(5), s.batch_at(5)
    assert np.array_equal(a.x, b.x) and np.array_equal(a.y, b.y)
    assert a.event_time == 10.0 + 5 * 2.0
    assert set(np.unique(a.y)) <= {-1.0, 1.0}
    # a second instance with the same seed replays the same bytes
    s2 = GrowingCorpusStream(batch_rows=8, dim=3, seed=7, period_s=2.0,
                             start_time=10.0)
    assert np.array_equal(s2.batch_at(5).x, a.x)
    # different ordinals are independent draws
    assert not np.array_equal(s.batch_at(6).x, a.x)
    # due() is inclusive at the event time
    assert not s.due(5, 19.999)
    assert s.due(5, 20.0)


def test_drifting_generators_shift_and_flip():
    base = GrowingCorpusStream(batch_rows=64, dim=4, seed=1)
    shift = MeanShiftStream(batch_rows=64, dim=4, seed=1, rate=0.5)
    assert np.array_equal(shift.batch_at(0).x, base.batch_at(0).x)
    d = shift.batch_at(6).x - base.batch_at(6).x
    assert np.allclose(d, 3.0, atol=1e-6)
    flip = LabelFlipStream(batch_rows=64, dim=4, seed=1, rate=0.1,
                           max_frac=0.3)
    flipped = np.sum(flip.batch_at(2).y != base.batch_at(2).y)
    assert flipped == round(0.2 * 64)
    capped = np.sum(flip.batch_at(9).y != base.batch_at(9).y)
    assert capped == round(0.3 * 64)


def test_drift_fault_window_applies_only_inside():
    fault = DriftAt(2, kind="mean_shift", magnitude=5.0, until=4)
    clean = GrowingCorpusStream(batch_rows=8, dim=3, seed=3)
    faulty = GrowingCorpusStream(batch_rows=8, dim=3, seed=3,
                                 faults=(fault,))
    for o in (0, 1, 4, 5):
        assert np.array_equal(faulty.batch_at(o).x, clean.batch_at(o).x)
    for o in (2, 3):
        assert np.allclose(faulty.batch_at(o).x - clean.batch_at(o).x, 5.0)
    # faults replay bitwise too
    again = GrowingCorpusStream(batch_rows=8, dim=3, seed=3,
                                faults=(DriftAt(2, kind="mean_shift",
                                                magnitude=5.0, until=4),))
    assert np.array_equal(again.batch_at(3).x, faulty.batch_at(3).x)


def test_drift_fault_label_flip_and_validation():
    clean = GrowingCorpusStream(batch_rows=10, dim=2, seed=0)
    flip = GrowingCorpusStream(
        batch_rows=10, dim=2, seed=0,
        faults=(DriftAt(0, kind="label_flip", magnitude=0.5),))
    b, fb = clean.batch_at(0), flip.batch_at(0)
    assert np.array_equal(b.x, fb.x)
    assert np.sum(b.y != fb.y) == 5
    with pytest.raises(ValueError, match="unknown drift kind"):
        DriftAt(0, kind="spin")
    with pytest.raises(ValueError, match="flip fraction"):
        DriftAt(0, kind="label_flip", magnitude=1.5)
    with pytest.raises(ValueError, match="until"):
        DriftAt(5, until=5)
    with pytest.raises(TypeError, match="DriftAt"):
        GrowingCorpusStream(batch_rows=4, dim=2, faults=(object(),))


def test_bounded_replay_source_exhausts_loudly():
    s = CovertypeReplayStream(n_rows=100, batch_rows=32, seed=0)
    assert s.num_batches == 3
    assert s.due(2, 1e9) and not s.due(3, 1e9)
    with pytest.raises(IndexError, match="past the bounded source"):
        s.batch_at(3)
    # replay slices are row-order contiguous
    b0, b1 = s.batch_at(0), s.batch_at(1)
    assert b0.x.shape == (32, s.dim)
    assert not np.array_equal(b0.x, b1.x)


# --------------------------------------------------------------------- #
# buffer: loud drop-oldest, watermark accounting


def test_buffer_drops_oldest_loudly_never_silently():
    reg = MetricsRegistry()
    clock = ManualClock(0.0)
    s = GrowingCorpusStream(batch_rows=4, dim=2, seed=0, period_s=1.0,
                            start_time=1.0)
    buf = StreamBuffer(s, capacity=2, registry=reg, clock=clock)
    clock.advance(5.0)  # ordinals 0..4 all due at once
    assert buf.poll() == 5
    assert buf.dropped == 3
    assert reg.counter("svgd_stream_dropped_total").value() == 3.0
    assert reg.counter("svgd_stream_batches_total").value() == 5.0
    kept = buf.take()
    assert [b.ordinal for b in kept] == [3, 4]  # newest survive, in order
    assert buf.watermark == s.event_time(4)
    assert reg.gauge("svgd_stream_watermark").value() == s.event_time(4)
    assert len(buf) == 0
    # nothing new due → no-op poll
    assert buf.poll() == 0 and buf.dropped == 3


def test_buffer_seek_fast_forwards_cursor():
    clock = ManualClock(100.0)
    s = GrowingCorpusStream(batch_rows=4, dim=2, seed=0, period_s=1.0)
    buf = StreamBuffer(s, capacity=64, registry=MetricsRegistry(),
                       clock=clock)
    buf.seek(50)
    buf.poll()
    assert [b.ordinal for b in buf.take()][0] == 50
    buf.seek(10)  # seek never rewinds
    assert buf.next_ordinal > 50


# --------------------------------------------------------------------- #
# RowRing: constant shapes forever


def test_row_ring_tiles_then_slides():
    ring = RowRing(8, 2)
    with pytest.raises(ValueError, match="before any rows"):
        ring.data()
    x0 = np.arange(6, dtype=np.float32).reshape(3, 2)
    ring.extend(x0, np.array([1.0, -1.0, 1.0]))
    x, y = ring.data()
    assert x.shape == (8, 2) and y.shape == (8,)  # tiled to capacity
    assert np.array_equal(x[:3], x0) and np.array_equal(x[3:6], x0)
    # fill past capacity → exact sliding window of the newest 8 rows
    x1 = np.arange(100, 120, dtype=np.float32).reshape(10, 2)
    ring.extend(x1, np.ones(10))
    x, y = ring.data()
    assert x.shape == (8, 2)
    assert set(map(tuple, x)) == set(map(tuple, x1[-8:]))
    assert ring.written == 13


def test_row_ring_oversized_extend_keeps_newest():
    ring = RowRing(4, 1)
    ring.extend(np.arange(10, dtype=np.float32).reshape(10, 1),
                np.ones(10))
    x, _ = ring.data()
    assert sorted(x.ravel().tolist()) == [6.0, 7.0, 8.0, 9.0]
    assert ring.written == 10


def test_row_ring_state_roundtrip_bitwise():
    ring = RowRing(5, 3)
    rng = np.random.default_rng(0)
    ring.extend(rng.normal(size=(7, 3)).astype(np.float32),
                np.ones(7))
    state = ring.state_dict()
    other = RowRing(5, 3)
    other.load_state_dict(state)
    for a, b in zip(ring.data(), other.data()):
        assert np.array_equal(a, b)
    wrong = RowRing(6, 3)
    with pytest.raises(ValueError, match="ring checkpoint shape"):
        wrong.load_state_dict(state)
    ring.extend(np.zeros((1, 3), np.float32), np.ones(1))
    assert not np.array_equal(ring.data()[0], other.data()[0])


def test_row_ring_rejects_bad_shapes():
    ring = RowRing(4, 3)
    with pytest.raises(ValueError, match="expected x"):
        ring.extend(np.zeros((2, 2), np.float32), np.ones(2))
    with pytest.raises(ValueError, match="expected x"):
        ring.extend(np.zeros((2, 3), np.float32), np.ones(3))


# --------------------------------------------------------------------- #
# pipeline: segment lifecycle on a tiny stack

DIM = 3
ROWS = 16
CORPUS = 32


def _stack(root, clock, registry, *, seed=0, faults=(), reloader=None,
           diag=None, steps=2, refit_steps=6, n=16):
    source = GrowingCorpusStream(batch_rows=ROWS, dim=DIM, seed=seed,
                                 period_s=1.0, start_time=1.0,
                                 faults=faults)
    buffer = StreamBuffer(source, capacity=8, registry=registry,
                          clock=clock)
    ring = RowRing(CORPUS, DIM)
    likelihood, prior = make_logreg_split()
    sampler = dt.Sampler(
        DIM + 1, likelihood, kernel=dt.RBF(1.0),
        data=(np.zeros((CORPUS, DIM), np.float32),
              np.ones((CORPUS,), np.float64)),
        batch_size=8, log_prior=prior)
    sup = StreamingSupervisor(
        sampler, 0.05, buffer=buffer, ring=ring, steps_per_segment=steps,
        refit_steps=refit_steps, drift_diagnostics=diag, reloader=reloader,
        checkpoint_dir=str(root), checkpoint_every=steps,
        segment_steps=steps, n=n, seed=seed, registry=registry,
        clock=clock, sleep=no_sleep)
    return source, buffer, sup


def test_streaming_supervisor_rejects_fulldata_sampler(tmp_path):
    likelihood, prior = make_logreg_split()
    full = dt.Sampler(DIM + 1, likelihood, kernel=dt.RBF(1.0),
                      data=(np.zeros((8, DIM), np.float32),
                            np.ones((8,), np.float64)),
                      log_prior=prior)
    with pytest.raises(ValueError, match="minibatch"):
        StreamingSupervisor(
            full, 0.05, buffer=None, ring=None, steps_per_segment=2,
            checkpoint_dir=str(tmp_path), segment_steps=2, n=8)


def test_segment_ingests_trains_checkpoints(tmp_path):
    reg = MetricsRegistry()
    clock = ManualClock(0.0)
    _, buffer, sup = _stack(tmp_path, clock, reg)
    clock.advance(2.0)  # ordinals 0 and 1 due
    seg = sup.run_segment_once()
    assert seg["batches"] == 2 and seg["rows"] == 2 * ROWS
    assert seg["t"] == 2 and seg["steps"] == 2
    assert seg["watermark"] == 2.0
    assert seg["dropped_total"] == 0
    assert reg.counter("svgd_stream_segments_total").value() == 1.0
    assert reg.counter("svgd_stream_rows_total").value() == 2 * ROWS
    # a segment with no due batches still trains on the held corpus
    seg2 = sup.run_segment_once()
    assert seg2["batches"] == 0 and seg2["t"] == 4


def test_bitwise_kill_resume_mid_stream(tmp_path):
    def run(root, n_segments, *, resume_first=False, t0=0.0):
        reg = MetricsRegistry()
        clock = ManualClock(t0)
        _, buffer, sup = _stack(root, clock, reg)
        for i in range(n_segments):
            clock.advance(1.0)
            sup.run_segment_once(resume=(resume_first and i == 0))
        return np.asarray(sup.particles), sup.t, buffer.next_ordinal, clock.t

    root_a = tmp_path / "a"
    root_b = tmp_path / "b"
    p_a, t_a, ord_a, _ = run(root_a, 4)
    # run B: 2 segments, hard kill (process state dropped), cold resume
    _, _, _, t_kill = run(root_b, 2)
    p_b, t_b, ord_b, _ = run(root_b, 2, resume_first=True, t0=t_kill)
    assert t_b == t_a and ord_b == ord_a
    assert np.array_equal(p_b, p_a)  # bitwise, not just close


def test_drift_breach_escalates_to_refit(tmp_path):
    reg = MetricsRegistry()
    clock = ManualClock(0.0)
    diag = PosteriorDiagnostics(
        DiagnosticsConfig(every_steps=1, row_chunk=32, max_points=32),
        registry=reg)
    _, _, sup = _stack(tmp_path, clock, reg, diag=diag)
    clock.advance(1.0)
    first = sup.run_segment_once()  # detector unarmed at t=0
    assert not first["refit"]
    # arm an always-trip guard: any finite (or NaN) KSD breaches
    sup.drift_guard = GuardConfig(max_ksd=-1.0)
    assert sup.drift_guard.max_ksd == -1.0
    clock.advance(1.0)
    seg = sup.run_segment_once()
    assert seg["refit"] and seg["drift"].startswith("posterior drift")
    assert seg["steps"] == 6  # refit_steps, not steps_per_segment
    assert reg.counter("svgd_stream_refits_total").value() == 1.0
    # disarm → back to incremental segments
    sup.drift_guard = None
    clock.advance(1.0)
    assert not sup.run_segment_once()["refit"]


def test_segment_publishes_through_hot_reloader(tmp_path):
    from dist_svgd_tpu.serving import CheckpointHotReloader, PredictiveEngine
    from dist_svgd_tpu.utils.rng import as_key, init_particles

    reg = MetricsRegistry()
    clock = ManualClock(0.0)
    engine = PredictiveEngine(
        "logreg", np.asarray(init_particles(as_key(0), 16, DIM + 1)),
        min_bucket=4, max_bucket=8, registry=reg)
    reloader = CheckpointHotReloader(engine, str(tmp_path), key="particles")
    _, _, sup = _stack(tmp_path, clock, reg, reloader=reloader)
    clock.advance(1.0)
    seg = sup.run_segment_once()
    assert seg["reload_step"] == seg["t"]
    assert not seg["reload_rejected"]
    assert seg["freshness_s"] is not None and seg["freshness_s"] >= 0.0
    assert engine.stats()["ensemble_tag"] == f"step_{seg['t']}"
    # the served generation stamps the serving watermark — the freshness
    # SLO's second gauge
    assert reg.gauge("svgd_serving_watermark").value() == seg["watermark"]
    assert reg.histogram("svgd_freshness_seconds").summary()["count"] == 1


def test_rejected_reload_rolls_back_never_forward(tmp_path):
    from dist_svgd_tpu.serving import CheckpointHotReloader, PredictiveEngine
    from dist_svgd_tpu.utils.rng import as_key, init_particles

    reg = MetricsRegistry()
    clock = ManualClock(0.0)
    # an impossible health floor: every candidate generation is rejected
    engine = PredictiveEngine(
        "logreg", np.asarray(init_particles(as_key(0), 16, DIM + 1)),
        min_bucket=4, max_bucket=8, registry=reg,
        reload_policy=ReloadPolicy(min_ess_frac=1.5, max_points=16))
    tag0 = engine.stats()["ensemble_tag"]
    reloader = CheckpointHotReloader(engine, str(tmp_path), key="particles")
    _, _, sup = _stack(tmp_path, clock, reg, reloader=reloader)
    clock.advance(1.0)
    seg = sup.run_segment_once()
    assert seg["reload_rejected"] and seg["reload_step"] is None
    assert seg["freshness_s"] is None  # nothing new served → no sample
    st = engine.stats()
    assert st["ensemble_tag"] == tag0  # still on the prior generation
    assert st["reload_rejects"] == 1 and st["reloads"] == 0
    assert not reg.gauge("svgd_serving_watermark").has()


def test_zero_recompiles_across_steady_segments(tmp_path):
    from tools.jaxlint.sentry import retrace_sentry

    reg = MetricsRegistry()
    clock = ManualClock(0.0)
    _, _, sup = _stack(tmp_path, clock, reg)
    clock.advance(1.0)
    sup.run_segment_once()  # first segment pays the compile
    with retrace_sentry("streaming-steady") as sentry:
        for _ in range(3):
            clock.advance(1.0)
            sup.run_segment_once()
    if not sentry.supported:
        pytest.skip("retrace sentry unsupported on this jax")
    # the RowRing keeps the traced data shape constant: ingesting three
    # more segments must not retrace the scan
    assert sentry.compiles == 0, sentry.report()
