"""Unit tests: closed-form kernel values and gradients (SURVEY.md §4 unit tier)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu.ops.kernels import (
    RBF,
    kernel_grad_matrix,
    kernel_matrix,
    median_bandwidth,
    squared_distances,
)

from _oracle import rbf as oracle_rbf, drbf_dx as oracle_drbf


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def test_squared_distances_matches_bruteforce(rng):
    x = rng.normal(size=(5, 3))
    y = rng.normal(size=(7, 3))
    got = np.asarray(squared_distances(jnp.asarray(x), jnp.asarray(y)))
    want = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-12)
    assert (got >= 0).all()


def test_rbf_scalar_matches_reference_formula(rng):
    k = RBF(1.0)
    x, y = rng.normal(size=3), rng.normal(size=3)
    got = float(k(jnp.asarray(x), jnp.asarray(y)))
    assert got == pytest.approx(oracle_rbf(x, y), rel=1e-12)


def test_rbf_matrix_matches_scalar(rng):
    k = RBF(2.5)
    x = rng.normal(size=(4, 2))
    y = rng.normal(size=(6, 2))
    mat = np.asarray(k.matrix(jnp.asarray(x), jnp.asarray(y)))
    for i in range(4):
        for j in range(6):
            assert mat[i, j] == pytest.approx(float(k(jnp.asarray(x[i]), jnp.asarray(y[j]))), rel=1e-12)


def test_kernel_grad_matrix_matches_analytic(rng):
    """Generic autograd path must equal the closed form −2(x−y)k for RBF."""
    x = rng.normal(size=(3, 2))
    y = rng.normal(size=(4, 2))
    k = RBF(1.0)
    g = np.asarray(kernel_grad_matrix(k, jnp.asarray(x), jnp.asarray(y)))
    for i in range(3):
        for j in range(4):
            np.testing.assert_allclose(g[i, j], oracle_drbf(x[i], y[j]), rtol=1e-10)


def test_generic_kernel_matrix_fallback(rng):
    """A plain callable (no .matrix) goes through the vmap fallback."""
    x = rng.normal(size=(3, 2))
    y = rng.normal(size=(4, 2))

    def plain(a, b):
        return jnp.exp(-jnp.sum((a - b) ** 2))

    got = np.asarray(kernel_matrix(plain, jnp.asarray(x), jnp.asarray(y)))
    want = np.asarray(RBF(1.0).matrix(jnp.asarray(x), jnp.asarray(y)))
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_median_bandwidth_positive_and_scales(rng):
    x = jnp.asarray(rng.normal(size=(50, 4)))
    h = float(median_bandwidth(x))
    assert h > 0
    h10 = float(median_bandwidth(10.0 * x))
    assert h10 == pytest.approx(100.0 * h, rel=1e-6)


def test_median_bandwidth_excludes_diagonal_and_jits():
    """n=2 at distance a: off-diagonal median is a², not a²/2 — and the
    heuristic must be traceable under jit (used inside jitted steps)."""
    import math

    x = jnp.asarray([[0.0], [3.0]])
    want = 9.0 / math.log(3.0)
    assert float(median_bandwidth(x)) == pytest.approx(want, rel=1e-10)
    assert float(jax.jit(median_bandwidth)(x)) == pytest.approx(want, rel=1e-10)


def test_rbf_rejects_bad_bandwidth():
    with pytest.raises(ValueError):
        RBF(0.0)


def test_median_bandwidth_subsample_estimates_full(rng):
    """Above max_points the median is estimated on an evenly-strided
    subsample, with log(n+1) still using the full count — the estimate must
    land near the exact value on iid data."""
    x = jnp.asarray(rng.normal(size=(600, 3)))
    exact = float(median_bandwidth(x, max_points=600))
    sub = float(median_bandwidth(x, max_points=128))
    assert sub == pytest.approx(exact, rel=0.15)


def test_sampler_median_kernel_equals_precomputed(rng):
    """kernel='median' on Sampler resolves per run from the initial
    particles and reproduces an explicit RBF(h) run bitwise."""
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp
    from dist_svgd_tpu.utils.rng import as_key, init_particles

    init = init_particles(as_key(3), 24, 1)
    h = float(median_bandwidth(init))
    assert h != 1.0
    a = dt.Sampler(1, gmm_logp, kernel="median")
    b = dt.Sampler(1, gmm_logp, kernel=RBF(h))
    got, _ = a.run(24, 10, 0.3, seed=3, record=False)
    want, _ = b.run(24, 10, 0.3, seed=3, record=False)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert a._kernel == RBF(h)


def test_distsampler_median_kernel_resolves_at_construction(rng):
    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.gmm import gmm_logp

    parts = jnp.asarray(rng.normal(size=(16, 2)))
    h = float(median_bandwidth(parts))
    ds = dt.DistSampler(4, gmm_logp, "median", parts, include_wasserstein=False)
    assert ds._kernel == RBF(h)
    ref = dt.DistSampler(4, gmm_logp, RBF(h), parts, include_wasserstein=False)
    np.testing.assert_array_equal(
        np.asarray(ds.make_step(0.1)), np.asarray(ref.make_step(0.1))
    )
