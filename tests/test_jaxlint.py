"""jaxlint: fixture corpus, escape hatch, the tier-1 zero-finding gate, and
the runtime retrace sentry.

Three layers:

1. **Fixture corpus** (``tests/jaxlint_fixtures/``): at least one positive
   and one negative snippet per rule, pinned file-by-file — a rule change
   that stops catching its positive (or starts flagging its negative)
   fails here, not in production review.
2. **The gate**: the analyzer runs over ``dist_svgd_tpu/``, ``tools/`` and
   ``experiments/`` exactly as ``python -m tools.jaxlint`` does and must
   report ZERO non-allowlisted findings — the baseline every future PR
   inherits.  The allowlist itself is policy-checked (no package-tree
   entries).
3. **Sentry**: XLA-compile counting is exercised on CPU — first call
   compiles, steady state counts zero, a new shape counts again — plus
   the serving engine's steady-state zero-compile contract (the round-9
   pad/slice retrace fix stays fixed).
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.jaxlint import allowlist as allowlist_mod  # noqa: E402
from tools.jaxlint import cli, lint_paths, lint_source, load_rules  # noqa: E402
from tools.jaxlint.sentry import assert_no_recompiles, retrace_sentry  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "jaxlint_fixtures")
GATED_TREES = [os.path.join(REPO_ROOT, p)
               for p in ("dist_svgd_tpu", "tools", "experiments")]

ALL_RULES = ("JL001", "JL002", "JL003", "JL004", "JL005", "JL006")


def lint_fixture(name):
    path = os.path.join(FIXTURES, name)
    with open(path) as fh:
        return lint_source(path, fh.read())


def rules_in(findings):
    return {f.rule for f in findings}


# --------------------------------------------------------------------- #
# 1. fixture corpus: ≥ 1 positive + 1 negative per rule

#: fixture file -> (rules that MUST fire, rules that MUST NOT fire)
EXPECTATIONS = {
    "jl001_pos.py": ({"JL001"}, set()),
    "jl001_neg.py": (set(), {"JL001"}),
    "jl002_pos.py": ({"JL002"}, set()),
    "jl002_neg.py": (set(), {"JL002"}),
    "jl003_pos.py": ({"JL003"}, set()),
    "jl003_neg.py": (set(), {"JL003"}),
    "jl004_pos.py": ({"JL004"}, set()),
    "jl004_neg.py": (set(), {"JL004"}),
    "jl005_pos.py": ({"JL005"}, set()),
    "jl005_neg.py": (set(), set(ALL_RULES)),
    "jl006_pos.py": ({"JL006"}, set()),
    "jl006_neg.py": (set(), set(ALL_RULES)),
}


@pytest.mark.parametrize("name", sorted(EXPECTATIONS))
def test_fixture(name):
    must, must_not = EXPECTATIONS[name]
    found = rules_in(lint_fixture(name))
    missing = must - found
    assert not missing, (
        f"{name}: rules {sorted(missing)} did not fire; findings: "
        f"{[f.format() for f in lint_fixture(name)]}"
    )
    spurious = found & must_not
    assert not spurious, (
        f"{name}: rules {sorted(spurious)} fired on a negative fixture: "
        f"{[f.format() for f in lint_fixture(name) if f.rule in spurious]}"
    )


def test_every_rule_has_positive_and_negative_fixture():
    """The corpus shape itself is pinned: adding rule JL006 without
    fixtures fails here."""
    registered = {r.RULE_ID for r in load_rules()}
    assert registered == set(ALL_RULES)
    for rule in registered:
        stem = rule.lower()
        for suffix in ("_pos.py", "_neg.py"):
            assert os.path.exists(os.path.join(FIXTURES, stem + suffix)), (
                f"missing fixture {stem + suffix}"
            )


def test_positive_findings_carry_location_and_message():
    findings = lint_fixture("jl003_pos.py")
    assert findings, "jl003_pos.py must produce findings"
    for f in findings:
        assert f.path.endswith("jl003_pos.py")
        assert f.line > 0
        assert f.rule in ALL_RULES
        assert f.message
        # file:line: RULE msg — the clickable format
        assert f.format().startswith(f"{f.path}:{f.line}: {f.rule} ")


# --------------------------------------------------------------------- #
# escape hatch

def test_escape_hatch_suppresses_exactly_its_named_rule():
    findings = lint_fixture("escape_hatch.py")
    jl003_lines = [f.line for f in findings if f.rule == "JL003"]
    # line with `disable=JL003` is suppressed; line with `disable=JL005`
    # still reports its JL003 finding (the hatch names ONE rule)
    with open(os.path.join(FIXTURES, "escape_hatch.py")) as fh:
        lines = fh.read().splitlines()
    suppressed_line = next(i for i, l in enumerate(lines, 1)
                           if "disable=JL003" in l)
    kept_line = next(i for i, l in enumerate(lines, 1)
                     if "disable=JL005" in l)
    assert suppressed_line not in jl003_lines
    assert kept_line in jl003_lines


def test_escape_hatch_multiple_rules_one_comment():
    src = (
        "import jax\nimport numpy as np\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = np.asarray(x); log.append(x)  # jaxlint: disable=JL003,JL005\n"
        "    return x\n"
    )
    assert lint_source("inline.py", src) == []


# --------------------------------------------------------------------- #
# 2. the tier-1 gate: zero non-allowlisted findings over the repo

def test_allowlist_policy_is_clean():
    assert allowlist_mod.validate() == []


def test_allowlist_has_no_stale_entries():
    """Round 22: an entry that waives nothing is dead weight waiting to
    waive the WRONG future finding — the full-tree lint must match every
    entry or the entry must go."""
    stale = allowlist_mod.stale_entries(lint_paths(GATED_TREES))
    assert stale == [], (
        "stale allowlist entries (delete them):\n"
        + "\n".join(repr(e) for e in stale)
    )


def test_stale_entries_detects_unmatched_and_keeps_matched():
    from tools.jaxlint.core import Finding

    findings = [Finding("pkg/tools/foo.py", 7, "JL003", "m")]
    allow = [
        ("tools/foo.py", "JL003", 7, "matched: stays"),
        ("tools/foo.py", "JL003", 8, "wrong line: stale"),
        ("tools/foo.py", "JL001", None, "wrong rule: stale"),
        ("tools/gone.py", "JL003", None, "file gone: stale"),
        ("tools/foo.py", "JL003", None, "line-free match: stays"),
    ]
    stale = allowlist_mod.stale_entries(findings, allow)
    assert [e[3] for e in stale] == [
        "wrong line: stale", "wrong rule: stale", "file gone: stale",
    ]


def test_repo_has_zero_nonallowlisted_findings():
    findings = [
        f for f in lint_paths(GATED_TREES)
        if not allowlist_mod.is_allowlisted(f.path, f.rule, f.line)
    ]
    assert not findings, (
        "jaxlint found new violations (fix them, or add a per-line "
        "`# jaxlint: disable=RULE` with justification — allowlist entries "
        "only for tools//experiments/):\n"
        + "\n".join(f.format() for f in findings)
    )


def test_cli_json_over_repo_exits_zero(capsys):
    rc = cli.main(["--json"] + GATED_TREES)
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == []
    assert {r["rule"] for r in out["rules"]} == set(ALL_RULES)


def test_cli_reports_fixture_findings(capsys):
    rc = cli.main(["--json", os.path.join(FIXTURES, "jl002_pos.py")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["rule"] for f in out["findings"]} == {"JL002"}
    assert all(f["path"].endswith("jl002_pos.py") for f in out["findings"])


def test_cli_list_rules(capsys):
    rc = cli.main(["--list-rules"])
    text = capsys.readouterr().out
    assert rc == 0
    for rule in ALL_RULES:
        assert rule in text


def test_cli_format_github_annotations(capsys):
    """--format=github emits one ::error workflow command per finding,
    with file=/line=/title= properties CI renders inline."""
    rc = cli.main(["--format=github", os.path.join(FIXTURES, "jl002_pos.py")])
    out = capsys.readouterr().out
    assert rc == 1
    lines = [l for l in out.splitlines() if l]
    assert lines and all(l.startswith("::error ") for l in lines)
    assert all("title=JL002" in l and "line=" in l for l in lines)
    assert all("jl002_pos.py" in l for l in lines)


def test_cli_format_github_clean_tree_is_silent(capsys):
    rc = cli.main(["--format=github", os.path.join(FIXTURES, "jl003_neg.py")])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.strip() == ""


def test_cli_format_json_matches_json_alias(capsys):
    target = os.path.join(FIXTURES, "jl002_pos.py")
    rc1 = cli.main(["--format=json", target])
    doc1 = json.loads(capsys.readouterr().out)
    rc2 = cli.main(["--json", target])
    doc2 = json.loads(capsys.readouterr().out)
    assert (rc1, doc1) == (rc2, doc2)
    assert doc1["stale_allowlist"] == []  # subset run: stale not judged


def test_report_render_shared_by_auditor():
    """The renderer accepts program-level findings (pseudo-paths, line 0)
    — the shared reporting path the program auditor uses."""
    import io

    from tools.jaxlint.core import Finding
    from tools.jaxlint.report import render

    f = Finding("plan://sampler.scan", 0, "XP003", "donation dropped")
    buf = io.StringIO()
    render([f], "github", buf)
    line = buf.getvalue().strip()
    assert line.startswith("::error ")
    assert "line=1" in line  # clamped: workflow commands need line >= 1
    assert "title=XP003" in line
    buf = io.StringIO()
    render([f], "json", buf, cards=[{"label": "sampler.scan"}])
    doc = json.loads(buf.getvalue())
    assert doc["findings"][0]["rule"] == "XP003"
    assert doc["cards"] == [{"label": "sampler.scan"}]


# --------------------------------------------------------------------- #
# 3. runtime retrace sentry

def _fresh_jitted():
    import jax

    return jax.jit(lambda x: x * 2 + 1)


def test_sentry_counts_first_compile_and_steady_state_zero():
    import jax.numpy as jnp

    f = _fresh_jitted()
    with retrace_sentry("cold") as cold:
        f(jnp.ones(3)).block_until_ready()
    if not cold.supported:
        pytest.skip("jax.monitoring events unavailable on this jax")
    assert cold.compiles >= 1
    with retrace_sentry("steady") as steady:
        for _ in range(3):
            f(jnp.ones(3)).block_until_ready()
    assert steady.compiles == 0
    assert steady.traces == 0


def test_sentry_catches_shape_retrace():
    import jax.numpy as jnp

    f = _fresh_jitted()
    f(jnp.ones(3)).block_until_ready()
    with retrace_sentry("retrace") as sentry:
        f(jnp.ones(4)).block_until_ready()  # new shape: must re-trace
    if not sentry.supported:
        pytest.skip("jax.monitoring events unavailable on this jax")
    assert sentry.compiles >= 1


def test_assert_no_recompiles_helper():
    import jax.numpy as jnp

    f = _fresh_jitted()
    f(jnp.ones(3)).block_until_ready()  # warm
    out = assert_no_recompiles(f, jnp.ones(3), label="steady")
    assert out.shape == (3,)
    with retrace_sentry("probe") as probe:
        pass
    if not probe.supported:
        pytest.skip("jax.monitoring events unavailable on this jax")
    with pytest.raises(AssertionError, match="compiled"):
        assert_no_recompiles(f, jnp.ones(5), label="cold-shape")


def test_serving_engine_steady_state_compiles_zero():
    """The round-9 retrace fix, pinned: after warmup, mixed request sizes
    must not compile ANYTHING (bucket kernels, pads, or slices)."""
    import numpy as np

    from dist_svgd_tpu.serving import PredictiveEngine

    rng = np.random.default_rng(0)
    eng = PredictiveEngine(
        "logreg", rng.normal(size=(64, 8)).astype(np.float32),
        min_bucket=4, max_bucket=16,
    )
    eng.warmup()
    with retrace_sentry("serve steady state") as sentry:
        for b in (1, 3, 4, 7, 16, 2, 5, 11):
            out = eng.predict(rng.normal(size=(b, 7)).astype(np.float32))
            assert out["mean"].shape == (b,)
    if not sentry.supported:
        pytest.skip("jax.monitoring events unavailable on this jax")
    assert sentry.compiles == 0, sentry.report()
    assert eng.stats()["bucket_misses"] == 3  # warmup's 4..16, nothing since


def test_scope_covers_round19_multihost_tools():
    """The two round-19 tools files sit inside the gated ``tools/`` tree —
    the repo gate above lints them — and each passes standalone with zero
    non-allowlisted findings (JL002 RNG discipline included: the worker's
    same-seed full init draws through numpy, never a raw PRNGKey)."""
    for name in ("multihost_train.py", "multihost_worker.py"):
        path = os.path.join(REPO_ROOT, "tools", name)
        assert os.path.exists(path), path
        assert any(path.startswith(tree) for tree in GATED_TREES)
        findings = [
            f for f in lint_paths([path])
            if not allowlist_mod.is_allowlisted(f.path, f.rule, f.line)
        ]
        assert not findings, "\n".join(f.format() for f in findings)
