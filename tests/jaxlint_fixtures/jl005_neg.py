"""JL005 negative: carry-threaded accumulation; jax.debug effects."""
import jax


@jax.jit
def accumulate(c0, xs):
    def body(carry, x):
        carry = carry + x  # local rebind: fine
        jax.debug.print("carry {c}", c=carry)  # sanctioned effect path
        return carry, None

    out, _ = jax.lax.scan(body, c0, xs)
    return out


class Model:
    def drive(self, p):
        out = accumulate(p, p)
        self.cache = out  # host side: a real value, not a tracer
        return out
