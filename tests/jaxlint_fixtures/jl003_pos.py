"""JL003 positive: host syncs inside jitted / scanned code."""
import jax
import numpy as np


@jax.jit
def bad_step(p):
    s = float(p.mean())  # EXPECT JL003: concretizes a tracer
    host = np.asarray(p)  # EXPECT JL003: host pull per step
    m = p.sum().item()  # EXPECT JL003: device->host sync
    return p * s + host.shape[0] + m


def scan_drive(p):
    def body(c, _):
        snapshot = np.asarray(c)  # EXPECT JL003: scan body is traced
        return c + snapshot.mean(), None

    out, _ = jax.lax.scan(body, p, None, length=3)
    return out
