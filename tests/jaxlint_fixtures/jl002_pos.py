"""JL002 positive: key reuse (linear + loop) and ad-hoc construction."""
import jax
from jax.random import PRNGKey


def double_draw(key):
    a = jax.random.normal(key, (3,))
    b = jax.random.normal(key, (3,))  # EXPECT JL002: key consumed twice
    return a + b


def loop_draw(key):
    outs = []
    for _ in range(4):
        outs.append(jax.random.normal(key, (2,)))  # EXPECT JL002: same stream per iter
    return outs


def adhoc_key():
    return jax.random.PRNGKey(0)  # EXPECT JL002: construct via utils.rng


def adhoc_typed_key():
    return jax.random.key(0)  # EXPECT JL002: new-style constructor too


def adhoc_from_import():
    return PRNGKey(0)  # EXPECT JL002: bare from-imported constructor
