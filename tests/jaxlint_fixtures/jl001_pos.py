"""JL001 positive: jit-in-loop and literal divergence across call sites.

Fixture corpus — parsed by the analyzer, never imported or executed.
"""
import jax

step = jax.jit(lambda p, eps: p * eps)


def drive(p):
    p = step(p, 0.1)
    p = step(p, 0.2)  # EXPECT JL001: second distinct scalar at arg 1
    return p


def sweep(fns, x):
    outs = []
    for fn in fns:
        compiled = jax.jit(fn)  # EXPECT JL001: jit wrapped per iteration
        outs.append(compiled(x))
    return outs
