"""JL002 negative: split/fold_in discipline (the blessed patterns)."""
import jax

from dist_svgd_tpu.utils.rng import as_key, draw_minibatch


def fresh_draws(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (3,))
    b = jax.random.normal(k2, (3,))
    return a + b


def folded_loop(key):
    outs = []
    for i in range(4):
        outs.append(jax.random.normal(jax.random.fold_in(key, i), (2,)))
    return outs


def rebound_loop(key):
    for _ in range(4):
        key, sub = jax.random.split(key)
        _ = jax.random.normal(sub, (2,))
    return key


def blessed(seed, data):
    key = as_key(seed)
    batch, scale = draw_minibatch(key, data, 100, 10)
    return batch, scale


def key(name):
    # a generic local helper that happens to be called `key`: NOT a PRNG
    # constructor (it was not imported from jax.random)
    return name.lower()


def cache_lookup(cache, name):
    return cache[key(name)]
