"""JL004 negative: consistent locking; __init__ exempt; unguarded-only ok."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # construction precedes sharing: exempt
        self._label = "idle"

    def bump(self):
        with self._lock:
            self._n += 1

    def reset(self):
        with self._lock:
            self._n = 0

    def rename(self, label):
        # only ever assigned without the lock -> a single-threaded-by-
        # contract attribute, not the rule's business
        self._label = label
