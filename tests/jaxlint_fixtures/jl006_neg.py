"""JL006 negative: config attrs, lazy caches, builder methods, string-keyed
manifest fields, and non-checkpointed classes are all out of scope."""


class Trainer:
    def __init__(self):
        self._particles = None
        self._t = 0
        self._seed = 0          # config: only ever set in __init__
        self._step_fn = None    # compiled-program cache
        self._bank_key = None   # persisted via the manifest string key

    def step(self):
        if self._step_fn is None:
            # lazy-build idiom: rebuilt on demand, not trajectory state
            self._step_fn = lambda p: [x + 1 for x in p]
        self._particles = self._step_fn(self._particles or [])
        self._t += 1
        self._bank_key = self._t * 7

    def rebuild_programs(self):
        # mutates ONLY unpersisted attrs: a builder, no co-mutation signal
        self._step_fn = lambda p: [x + 2 for x in p]

    def state_dict(self):
        state = {"particles": self._particles, "t": self._t}
        state["bank_key"] = getattr(self, "_bank_key")
        return state

    def load_state_dict(self, state):
        self._particles = state["particles"]
        self._t = state["t"]


class NotCheckpointed:
    def __init__(self):
        self._x = 0

    def step(self):
        self._x += 1
