"""Escape-hatch fixture: a disable comment suppresses exactly its rule."""
import jax
import numpy as np


@jax.jit
def pinned(x):
    a = np.asarray(x)  # jaxlint: disable=JL003
    b = np.asarray(x)  # jaxlint: disable=JL005
    return x + a.shape[0] + b.shape[0]
