"""JL004 positive: attribute assigned both under and outside the lock."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def reset(self):
        self._n = 0  # EXPECT JL004: bare write to lock-guarded state
