"""JL006 positive: trajectory state mutated alongside persisted fields but
absent from the checkpoint protocol."""


class Trainer:
    def __init__(self):
        self._particles = None
        self._t = 0
        self._bandwidth = 1.0

    def step(self):
        self._particles = [p + 1 for p in self._particles or []]
        self._t += 1
        # EXPECT JL006: evolves with the persisted trajectory, never saved
        self._bandwidth = self._bandwidth * 0.99

    def state_dict(self):
        return {"particles": self._particles, "t": self._t}

    def load_state_dict(self, state):
        self._particles = state["particles"]
        self._t = state["t"]
