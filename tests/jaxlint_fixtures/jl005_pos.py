"""JL005 positive: tracer leaks and dead side effects under jit/scan."""
import jax

log = []


@jax.jit
def leaky(x):
    log.append(x)  # EXPECT JL005: closure mutation at trace time
    print("tracing", x)  # EXPECT JL005: trace-time print
    return x * 2


class Model:
    def trace_me(self, p):
        @jax.jit
        def step(x):
            self.cache = x  # EXPECT JL005: tracer stored on self
            return x * 2

        return step(p)
