"""JL003 negative: host fetches on the driver side; static-arg casts."""
import functools

import jax
import numpy as np


@functools.partial(jax.jit, static_argnames=("gain",))
def scaled(p, gain: float):
    return p * float(gain)  # static arg: sanctioned trace-time cast


def driver(p):
    out = scaled(p, 2.0)
    host = np.asarray(out)  # outside any trace: a deliberate fetch
    return float(host.mean())
