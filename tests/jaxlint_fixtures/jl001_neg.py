"""JL001 negative: hoisted jit, stable literals, array-boxed scalars."""
import jax
import jax.numpy as jnp

step = jax.jit(lambda p, eps: p * eps)
compiled_once = jax.jit(lambda x: x + 1)


def drive(p):
    p = step(p, 0.1)
    p = step(p, 0.1)  # same literal: one trace
    p = step(p, jnp.asarray(0.2))  # device array: no per-value retrace
    return p


def sweep(fns, x):
    outs = []
    for _ in range(3):
        outs.append(compiled_once(x))  # jit lives outside the loop
    return outs
