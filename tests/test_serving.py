"""Serving subsystem (dist_svgd_tpu/serving/): engine bucket cache and
checkpoint cold start, micro-batcher edge cases (driven through the
injectable clock — no real waits beyond a few ms), HTTP front end, and the
end-to-end train → checkpoint → serve bitwise acceptance test.
"""

import json
import math
import threading
import urllib.error
import urllib.request
from concurrent.futures import CancelledError

import numpy as np
import pytest

import jax.numpy as jnp

from dist_svgd_tpu.models.logreg import posterior_predictive_prob
from dist_svgd_tpu.serving import (
    MicroBatcher,
    Overloaded,
    PredictionServer,
    PredictiveEngine,
)
from dist_svgd_tpu.serving.engine import bucket_for
from dist_svgd_tpu.utils.checkpoint import CheckpointManager, save_state


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _logreg_engine(rng, n=32, k=4, **kw):
    parts = rng.normal(size=(n, 1 + k)).astype(np.float32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("max_bucket", 64)
    return PredictiveEngine("logreg", parts, **kw), parts


# --------------------------------------------------------------------- #
# injectable time: tests drive max_wait_ms expiry without real sleeps


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make_fake_wait(clock):
    """Timed condition waits advance the fake clock instead of sleeping;
    untimed waits stay real (they wake on submit's notify)."""

    def wait(cond, timeout):
        if timeout is None:
            return threading.Condition.wait(cond)
        clock.t += timeout
        return False

    return wait


def make_batcher(dispatch, **kw):
    clock = ManualClock()
    kw.setdefault("clock", clock)
    kw.setdefault("wait", make_fake_wait(clock))
    kw.setdefault("autostart", False)
    return MicroBatcher(dispatch, **kw), clock


# --------------------------------------------------------------------- #
# engine: buckets + compile cache


def test_bucket_for():
    assert [bucket_for(b, 4) for b in (1, 3, 4, 5, 8, 9, 17)] == [
        4, 4, 4, 8, 8, 16, 32,
    ]
    with pytest.raises(ValueError):
        bucket_for(0, 4)


def test_engine_pads_exactly(rng):
    """Padding to the bucket and slicing back is bitwise-invisible: every
    request size gives the same rows as one direct full-batch call."""
    eng, parts = _logreg_engine(rng)
    x = rng.normal(size=(11, 4)).astype(np.float32)
    ref = np.asarray(jnp.mean(posterior_predictive_prob(
        jnp.asarray(parts), jnp.asarray(x)), axis=0))
    for a, b in ((0, 1), (1, 4), (4, 11)):
        out = eng.predict(x[a:b])
        assert out["mean"].shape == (b - a,)
        np.testing.assert_array_equal(out["mean"], ref[a:b])


def test_engine_bucket_cache_hits_and_misses(rng):
    eng, _ = _logreg_engine(rng)
    for b in (1, 2, 3, 4):  # all land in bucket 4: 1 miss, 3 hits
        eng.predict(np.zeros((b, 4), np.float32))
    st = eng.stats()
    assert st["compiled_buckets"] == [4]
    assert (st["bucket_misses"], st["bucket_hits"]) == (1, 3)
    eng.predict(np.zeros((5, 4), np.float32))  # bucket 8: second miss
    assert eng.stats()["compiled_buckets"] == [4, 8]
    # traffic mix over the whole range compiles at most log2 buckets
    for b in range(1, 65):
        eng.predict(np.zeros((b, 4), np.float32))
    assert len(eng.stats()["compiled_buckets"]) <= math.ceil(math.log2(64)) + 1


def test_engine_rejects_oversize_and_bad_shapes(rng):
    eng, _ = _logreg_engine(rng, max_bucket=16)
    with pytest.raises(ValueError, match="max_bucket"):
        eng.predict(np.zeros((17, 4), np.float32))
    with pytest.raises(ValueError, match="expected"):
        eng.predict(np.zeros((3, 5), np.float32))
    with pytest.raises(ValueError, match="unknown model"):
        PredictiveEngine("mystery", np.zeros((4, 3)))


def test_engine_warmup_precompiles(rng):
    eng, _ = _logreg_engine(rng, min_bucket=4, max_bucket=32)
    assert eng.warmup() == [4, 8, 16, 32]
    misses = eng.stats()["bucket_misses"]
    eng.predict(np.zeros((13, 4), np.float32))
    assert eng.stats()["bucket_misses"] == misses  # steady state: no compiles


def test_engine_non_pow2_max_bucket_normalised(rng):
    """max_bucket=100 rounds up to 128, so warmup() provably covers every
    reachable bucket — a 100-row request must NOT compile post-warmup."""
    eng, _ = _logreg_engine(rng, min_bucket=4, max_bucket=100)
    assert eng.max_bucket == 128
    assert eng.warmup()[-1] == 128
    misses = eng.stats()["bucket_misses"]
    eng.predict(np.zeros((100, 4), np.float32))
    assert eng.stats()["bucket_misses"] == misses
    with pytest.raises(ValueError, match="max_bucket"):
        eng.predict(np.zeros((129, 4), np.float32))


def test_engine_bnn_kernel_matches_direct(rng):
    from dist_svgd_tpu.models import bnn

    nf, nh, n = 3, 4, 10
    parts = rng.normal(size=(n, bnn.num_params(nf, nh))).astype(np.float32)
    x = rng.normal(size=(5, nf)).astype(np.float32)
    eng = PredictiveEngine("bnn", parts, n_features=nf, n_hidden=nh,
                           y_mean=2.0, y_std=3.0)
    out = eng.predict(x)
    preds = np.stack([
        np.asarray(bnn.predict(jnp.asarray(p), jnp.asarray(x), nf, nh))
        for p in parts
    ])
    mean = preds.mean(0) * 3.0 + 2.0
    var = preds.var(0) * 9.0 + np.mean(np.exp(-parts[:, -2])) * 9.0
    np.testing.assert_allclose(out["mean"], mean, rtol=1e-5)
    np.testing.assert_allclose(out["std"], np.sqrt(var), rtol=1e-5)


def test_engine_bnn_requires_layout():
    with pytest.raises(ValueError, match="requires n_features"):
        PredictiveEngine("bnn", np.zeros((4, 10), np.float32))
    with pytest.raises(ValueError, match="num_params"):
        PredictiveEngine("bnn", np.zeros((4, 10), np.float32), n_features=3)


def test_engine_gmm_kde_matches_direct(rng):
    n, d, h = 20, 2, 0.7
    parts = rng.normal(size=(n, d)).astype(np.float32)
    x = rng.normal(size=(6, d)).astype(np.float32)
    eng = PredictiveEngine("gmm", parts, kde_bandwidth=h)
    out = eng.predict(x)
    sq = ((x[:, None, :] - parts[None]) ** 2).sum(-1)
    logk = -0.5 * sq / h**2 - d * np.log(h) - 0.5 * d * np.log(2 * np.pi)
    ref = np.log(np.exp(logk).sum(1)) - np.log(n)
    np.testing.assert_allclose(out["log_density"], ref, rtol=1e-5)


# --------------------------------------------------------------------- #
# engine: checkpoint cold start (all three layouts)


@pytest.mark.slow  # the orbax-backed save's fixed ~2 s import/manifest
# cost buys no coverage the manager-root and multiprocess cold-start
# tests below don't already give (runtime-budget audit, round 11)
def test_from_checkpoint_single_save(tmp_path, rng):
    parts = rng.normal(size=(8, 3)).astype(np.float32)
    save_state(str(tmp_path / "c"), {"particles": parts, "t": 3})
    eng = PredictiveEngine.from_checkpoint(str(tmp_path / "c"), "logreg")
    np.testing.assert_array_equal(np.asarray(eng.particles), parts)


def test_from_checkpoint_manager_root_skips_corrupt_newest(tmp_path, rng):
    """Cold start survives a run killed mid-save: the corrupt newest step is
    skipped with a warning and the previous one serves."""
    import os

    parts = rng.normal(size=(8, 3)).astype(np.float32)
    mgr = CheckpointManager(str(tmp_path / "root"), every=1)
    mgr.save(1, {"particles": parts, "t": 1})
    os.makedirs(os.path.join(mgr.root, "step_2"))  # partial write
    with pytest.warns(UserWarning, match="skipping unloadable"):
        eng = PredictiveEngine.from_checkpoint(str(tmp_path / "root"), "logreg")
    np.testing.assert_array_equal(np.asarray(eng.particles), parts)


def test_from_checkpoint_multiprocess_blocks(tmp_path, rng):
    """A list of per-process block files is ONE multi-host save: the global
    ensemble reassembles regardless of which process's file comes first."""
    rows = rng.normal(size=(8, 3)).astype(np.float32)
    a = str(tmp_path / "p0")
    b = str(tmp_path / "p1")
    save_state(a, {"particles": rows[:4], "particles_start": np.int64(0),
                   "t": np.int64(2)})
    save_state(b, {"particles": rows[4:], "particles_start": np.int64(4),
                   "t": np.int64(2)})
    eng = PredictiveEngine.from_checkpoint([b, a], "logreg")
    np.testing.assert_array_equal(np.asarray(eng.particles), rows)


def test_from_checkpoint_errors(tmp_path):
    with pytest.raises(FileNotFoundError):
        PredictiveEngine.from_checkpoint(str(tmp_path / "nope"), "logreg")
    save_state(str(tmp_path / "c"), {"other": np.ones((2, 2))})
    with pytest.raises(KeyError, match="particles"):
        PredictiveEngine.from_checkpoint(str(tmp_path / "c"), "logreg")
    CheckpointManager(str(tmp_path / "empty_root"), every=1)
    with pytest.raises(ValueError, match="empty"):
        # no step dirs -> treated as a save_state dir, which it isn't either
        PredictiveEngine.from_checkpoint(str(tmp_path / "empty_root"), "logreg")


# --------------------------------------------------------------------- #
# batcher edge cases (ISSUE satellite): all through the injectable clock


def _echo_dispatch(calls):
    """Dispatch that records batch sizes and returns row indices, so scatter
    correctness is visible in the results."""

    def dispatch(x):
        calls.append(x.shape[0])
        return {"val": x[:, 0].copy()}

    return dispatch


def test_partial_flush_on_max_wait_expiry(rng):
    """A lone small request must not wait forever for co-travellers: the
    max_wait_ms deadline flushes a partial batch."""
    calls = []
    bat, clock = make_batcher(_echo_dispatch(calls), max_batch=64, max_wait_ms=5.0)
    fut = bat.submit(np.arange(3, dtype=np.float32)[:, None])
    bat.start()
    out = fut.result(timeout=10)
    np.testing.assert_array_equal(out["val"], [0, 1, 2])
    assert calls == [3]  # flushed well under max_batch
    assert clock.t >= 5e-3  # and only after the wait window expired
    bat.close()


def test_oversize_request_splits_not_deadlocks(rng):
    """A single request > max_batch splits into max_batch-row chunks and
    reassembles in order — it can never wait for an impossible batch slot."""
    calls = []
    bat, _ = make_batcher(_echo_dispatch(calls), max_batch=8, max_wait_ms=1.0)
    x = np.arange(20, dtype=np.float32)[:, None]
    fut = bat.submit(x)
    bat.start()
    out = fut.result(timeout=10)
    np.testing.assert_array_equal(out["val"], np.arange(20))
    assert calls == [8, 8, 4]
    bat.close()


def test_bucket_boundary_batches(rng):
    """Exactly-at and one-past the coalescing ceiling: 16 rows ride one
    dispatch, 17 rows split 16+1; engine buckets follow suit (16 stays in
    the 16-bucket, 17 pads to 32) without extra compiles thereafter."""
    eng, _ = _logreg_engine(rng, min_bucket=4, max_bucket=32)
    bat, _ = make_batcher(eng.predict, max_batch=16, max_wait_ms=1.0)
    futs = [bat.submit(np.zeros((8, 4), np.float32)) for _ in range(2)]
    bat.start()
    for f in futs:
        f.result(timeout=10)
    st = bat.stats()
    assert (st["batches"], st["batch_occupancy_max"]) == (1, 16)
    assert eng.stats()["compiled_buckets"] == [16]

    # one past: 17 rows -> 16 + 1, second batch pads into bucket 4
    f17 = bat.submit(np.zeros((17, 4), np.float32))
    f17.result(timeout=10)
    st = bat.stats()
    assert st["batches"] == 3 and st["batch_occupancy_max"] == 16
    assert eng.stats()["compiled_buckets"] == [4, 16]
    bat.close()


def test_shed_on_overflow_is_clean(rng):
    """Past max_queue_rows, submit fails fast with Overloaded — the client
    gets an immediate clean error, never a hang — and nothing already
    queued is lost."""
    calls = []
    bat, _ = make_batcher(
        _echo_dispatch(calls), max_batch=4, max_wait_ms=1.0, max_queue_rows=8
    )
    f1 = bat.submit(np.ones((4, 1), np.float32))
    f2 = bat.submit(np.ones((4, 1), np.float32))
    with pytest.raises(Overloaded, match="queue full"):
        bat.submit(np.ones((1, 1), np.float32))
    assert bat.stats()["shed"] == 1
    bat.start()
    for f in (f1, f2):
        assert f.result(timeout=10)["val"].shape == (4,)
    bat.close()


def test_close_drains_queued_requests():
    calls = []
    bat, _ = make_batcher(_echo_dispatch(calls), max_batch=4, max_wait_ms=1.0)
    futs = [bat.submit(np.full((2, 1), i, np.float32)) for i in range(3)]
    bat.start()
    bat.close(drain=True)
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result(timeout=1)["val"], [i, i])
    with pytest.raises(RuntimeError, match="closed"):
        bat.submit(np.ones((1, 1), np.float32))


def test_close_without_drain_cancels():
    bat, _ = make_batcher(_echo_dispatch([]), max_batch=4, max_wait_ms=1.0)
    fut = bat.submit(np.ones((2, 1), np.float32))
    bat.close(drain=False)
    with pytest.raises(CancelledError):
        fut.result(timeout=1)


def test_dispatch_error_propagates_to_futures():
    def boom(x):
        raise RuntimeError("device on fire")

    bat, _ = make_batcher(boom, max_batch=4, max_wait_ms=1.0)
    fut = bat.submit(np.ones((2, 1), np.float32))
    bat.start()
    with pytest.raises(RuntimeError, match="device on fire"):
        fut.result(timeout=10)
    assert bat.stats()["dispatch_errors"] == 1
    bat.close()


def test_batcher_validates_args():
    with pytest.raises(ValueError, match="max_batch"):
        MicroBatcher(lambda x: {}, max_batch=0, autostart=False)
    with pytest.raises(ValueError, match="max_queue_rows"):
        MicroBatcher(lambda x: {}, max_batch=8, max_queue_rows=4, autostart=False)
    bat = MicroBatcher(lambda x: {}, autostart=False)
    with pytest.raises(ValueError, match="non-empty"):
        bat.submit(np.zeros((0, 3), np.float32))
    bat.close()


# --------------------------------------------------------------------- #
# the end-to-end acceptance test (ISSUE 2): train -> checkpoint -> serve


def test_end_to_end_bitwise(tmp_path, rng):
    """Train a small logreg ensemble, checkpoint it, serve it through the
    batcher under concurrent mixed-size requests, and pin:

    (a) served predictions bitwise-equal a direct
        posterior_predictive_prob call on the same ensemble;
    (b) at most ceil(log2(max_batch)) + 1 distinct compiled shapes;
    (c) batch occupancy > 1 under concurrent load.
    """
    from dist_svgd_tpu import Sampler
    from dist_svgd_tpu.models.logreg import make_logreg_logp

    k = 6
    x_train = rng.normal(size=(40, k))
    t_train = np.where(rng.normal(size=40) > 0, 1.0, -1.0)
    sampler = Sampler(1 + k, make_logreg_logp(x_train, t_train))
    final, _ = sampler.run(48, 15, 1e-2, seed=3, record=False)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), every=5)
    mgr.save(15, {"particles": np.asarray(final), "t": 15})

    max_batch = 32
    engine = PredictiveEngine.from_checkpoint(
        str(tmp_path / "ckpt"), "logreg", min_bucket=4, max_bucket=max_batch
    )
    bat, _ = make_batcher(engine.predict, max_batch=max_batch, max_wait_ms=2.0)

    x_test = rng.normal(size=(37, k)).astype(np.float32)
    sizes = [1, 3, 4, 7, 2, 16, 1, 3]
    assert sum(sizes) == len(x_test)
    offsets = np.cumsum([0] + sizes)
    # all requests queued before the worker starts: concurrent arrival,
    # deterministic coalescing
    futs = [
        bat.submit(x_test[offsets[i]:offsets[i + 1]]) for i in range(len(sizes))
    ]
    bat.start()
    served = np.concatenate([f.result(timeout=30)["mean"] for f in futs])
    bat.close()

    # (a) bitwise equality with the one-shot helper on the same ensemble
    direct = np.asarray(jnp.mean(
        posterior_predictive_prob(engine.particles, jnp.asarray(x_test)), axis=0
    ))
    np.testing.assert_array_equal(served, direct)

    # (b) the bucket cache bounds traced shapes at ceil(log2) of max_batch
    st = engine.stats()
    assert st["bucket_misses"] == len(st["compiled_buckets"])
    assert st["bucket_misses"] <= math.ceil(math.log2(max_batch)) + 1

    # (c) the batcher actually coalesced concurrent requests
    bst = bat.stats()
    assert bst["requests"] == len(sizes)
    assert bst["batch_occupancy_mean"] > 1
    assert bst["requests_per_batch_mean"] > 1


# --------------------------------------------------------------------- #
# HTTP front end


def _get(url, path):
    return json.loads(urllib.request.urlopen(url + path, timeout=10).read())


def _post(url, path, doc):
    req = urllib.request.Request(
        url + path, json.dumps(doc).encode(), {"Content-Type": "application/json"}
    )
    return json.loads(urllib.request.urlopen(req, timeout=10).read())


def test_server_routes_and_drain(rng):
    eng, parts = _logreg_engine(rng)
    with PredictionServer(eng, port=0, max_batch=16, max_wait_ms=2.0) as srv:
        health = _get(srv.url, "/healthz")
        assert health["status"] == "ok"
        assert health["n_particles"] == 32 and health["feature_dim"] == 4

        x = rng.normal(size=(3, 4)).astype(np.float32)
        out = _post(srv.url, "/predict", {"inputs": x.tolist()})["outputs"]
        ref = np.asarray(jnp.mean(posterior_predictive_prob(
            jnp.asarray(parts), jnp.asarray(x)), axis=0))
        np.testing.assert_allclose(out["mean"], ref, rtol=1e-6)
        assert len(out["var"]) == 3

        # single-row shorthand
        one = _post(srv.url, "/predict", {"inputs": x[0].tolist()})["outputs"]
        assert len(one["mean"]) == 1

        metrics = _get(srv.url, "/metrics.json")
        assert metrics["http_requests"] == 2
        assert metrics["batcher"]["requests"] == 2
        assert metrics["engine"]["model"] == "logreg"

        # /metrics is now Prometheus text exposition of the shared registry
        prom = urllib.request.urlopen(srv.url + "/metrics", timeout=10)
        assert prom.headers["Content-Type"].startswith("text/plain")
        text = prom.read().decode()
        assert "# TYPE svgd_serve_requests_total counter" in text
        assert "svgd_serve_request_latency_seconds_bucket" in text
    # graceful drain: batcher closed behind the context manager
    with pytest.raises(RuntimeError, match="closed"):
        srv.batcher.submit(x)


def test_server_error_codes(rng):
    eng, _ = _logreg_engine(rng)
    with PredictionServer(eng, port=0, max_wait_ms=1.0) as srv:
        for body, want in ((b"not json", 400), (b'{"no_inputs": 1}', 400)):
            req = urllib.request.Request(
                srv.url + "/predict", body, {"Content-Type": "application/json"}
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == want
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/nope", timeout=10)
        assert ei.value.code == 404
        assert _get(srv.url, "/metrics.json")["http_errors"] == 2


def test_server_concurrent_load_coalesces(rng):
    """Acceptance (c) over real HTTP: concurrent requests land in shared
    batches — /metrics shows occupancy > 1."""
    eng, _ = _logreg_engine(rng)
    # 80 ms window: every thread below submits well inside it
    with PredictionServer(eng, port=0, max_batch=64, max_wait_ms=80.0) as srv:
        barrier = threading.Barrier(8)
        errs = []

        def fire():
            try:
                barrier.wait(timeout=10)
                _post(srv.url, "/predict",
                      {"inputs": np.zeros((2, 4)).tolist()})
            except Exception as e:  # pragma: no cover - diagnostic
                errs.append(e)

        threads = [threading.Thread(target=fire) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        m = _get(srv.url, "/metrics.json")
        assert m["batcher"]["requests"] == 8
        assert m["batcher"]["batch_occupancy_mean"] > 1
        assert m["batcher"]["requests_per_batch_mean"] > 1


def test_server_sheds_with_429_retry_after(rng):
    """Overload surfaces as HTTP 429 with a computed Retry-After (round
    15: a shed is load, not failure — a router must not burn retries on
    it), not a hung connection: the batcher never starts, so queued rows
    accumulate until the bound trips."""
    eng, _ = _logreg_engine(rng)
    bat, _ = make_batcher(eng.predict, max_batch=4, max_queue_rows=4,
                          max_wait_ms=1.0)
    srv = PredictionServer(eng, port=0, batcher=bat).start()
    try:
        t = threading.Thread(
            target=lambda: _post(srv.url, "/predict",
                                 {"inputs": np.zeros((4, 4)).tolist()})
        )
        t.start()  # fills the queue (worker not started -> stays queued)
        poll = threading.Event()
        for _ in range(1000):  # ≤ 5 s, normally a handful of ms
            if bat.stats()["queued_rows"] >= 4:
                break
            poll.wait(0.005)
        assert bat.stats()["queued_rows"] >= 4
        with pytest.raises(urllib.error.HTTPError) as ei:
            req = urllib.request.Request(
                srv.url + "/predict",
                json.dumps({"inputs": np.zeros((4, 4)).tolist()}).encode(),
                {"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 429
        # the batcher's drain estimate rides the response: integral
        # delta-seconds header (ceil, >= 1) + the precise body field
        assert int(ei.value.headers["Retry-After"]) >= 1
        body = json.loads(ei.value.read())
        assert body["retry_after_s"] > 0
        bat.start()
        t.join(timeout=10)
    finally:
        bat.start()
        srv.shutdown()


def test_overloaded_retry_after_scales_with_queue_depth(rng):
    """The Overloaded hint is (1 + ceil(queued/max_batch)) coalescing
    windows — deeper backlog, later retry."""
    eng, _ = _logreg_engine(rng)
    bat, _ = make_batcher(eng.predict, max_batch=4, max_queue_rows=8,
                          max_wait_ms=10.0)
    bat.submit(np.zeros((8, 4), np.float32))  # fill: worker never started
    with pytest.raises(Overloaded) as ei:
        bat.submit(np.zeros((1, 4), np.float32))
    # 8 queued rows = 2 batches -> (1 + 2) * 10 ms
    assert ei.value.retry_after_s == pytest.approx(0.030)
    bat.start()
    bat.close(drain=True)


def test_shutdown_flips_healthz_before_socket_close(rng):
    """Drain-signal ordering pin (round 15): shutdown() must advertise
    503 "draining" on /healthz while the socket still answers — a fleet
    router probing health then stops routing BEFORE the address dies.
    The spy wraps the httpd's shutdown (the first socket-closing step) and
    performs a live GET from inside it."""
    eng, _ = _logreg_engine(rng)
    srv = PredictionServer(eng, port=0, max_wait_ms=1.0).start()
    seen = {}
    orig_shutdown = srv._httpd.shutdown

    def spy():
        # at this instant the socket has NOT been closed yet: a real GET
        # must succeed and must already read as draining
        try:
            urllib.request.urlopen(srv.url + "/healthz", timeout=10)
            seen["code"] = 200
        except urllib.error.HTTPError as e:
            seen["code"] = e.code
            seen["body"] = json.loads(e.read())
        orig_shutdown()

    srv._httpd.shutdown = spy
    srv.shutdown()
    assert seen["code"] == 503
    assert seen["body"]["status"] == "draining"


# --------------------------------------------------------------------- #
# serve_bench emits the BENCH-style row


def test_serve_bench_row_schema():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import serve_bench

    row = serve_bench.run_bench(
        model="logreg", n_particles=64, n_features=4, clients=4, requests=40,
        rows=(1, 4), max_batch=16, max_wait_ms=1.0,
        open_rate=2000.0, open_requests=20,
    )
    for key in ("metric", "value", "unit", "p50_ms", "p99_ms",
                "queue_wait_p50_ms", "device_p50_ms", "batch_occupancy_mean",
                "recompiles", "bucket_hit_rate", "shed", "open_loop",
                "serve_latency_p99", "latency_hist_ms", "telemetry",
                "ksd", "ess", "ess_frac", "slo_status",
                "diagnostics_overhead"):
        assert key in row, key
    assert row["metric"] == "serve_throughput"
    assert row["value"] > 0
    assert row["recompiles"] == 0  # warmup precedes the timed window
    # registry-histogram percentiles cover every resolved request (closed
    # loop + open loop) from the run's fresh registry
    assert row["latency_hist_ms"]["count"] == 60
    assert row["serve_latency_p99"] == row["latency_hist_ms"]["p99"] > 0
    assert row["telemetry"]["tracing"] is False
    # the retrace sentry's independent raw-XLA-compile count over the same
    # window (None only when jax.monitoring is unavailable)
    assert row["sentry_compiles"] in (0, None)
    assert row["open_loop"]["completed"] == 20
    # posterior-health stamp (round 11): serve-side diagnostics are
    # score-free (ksd stays null — the fault_recovery row measures it),
    # and an unloaded bench window must satisfy the default serving SLOs
    assert row["ksd"] is None
    assert row["ess"] > 1 and 0 < row["ess_frac"] <= 1
    assert row["slo_status"] == "ok"
    assert 0 <= row["diagnostics_overhead"] < 1
    json.dumps(row)  # one BENCH-style JSON line, serialisable as-is


# --------------------------------------------------------------------- #
# checkpoint hot reload (round 8: train-while-serving)


def test_engine_reload_swaps_atomically(rng):
    eng, parts1 = _logreg_engine(rng)
    x = rng.normal(size=(5, 4)).astype(np.float32)
    before = eng.predict(x)
    parts2 = rng.normal(size=(48, 5)).astype(np.float32)  # n may change
    info = eng.reload(parts2, tag="gen2")
    assert info["n_particles"] == 48
    # the compiled buckets were rebuilt for the new ensemble BEFORE the
    # swap, so the first post-reload predict is a cache hit, not a miss
    misses = eng.stats()["bucket_misses"]
    after = eng.predict(x)
    assert eng.stats()["bucket_misses"] == misses
    eng2 = PredictiveEngine("logreg", parts2, min_bucket=4, max_bucket=64)
    np.testing.assert_array_equal(after["mean"], eng2.predict(x)["mean"])
    assert not np.array_equal(before["mean"], after["mean"])
    st = eng.stats()
    assert st["reloads"] == 1 and st["ensemble_tag"] == "gen2"


def test_engine_reload_rejects_layout_change(rng):
    eng, _ = _logreg_engine(rng)
    with pytest.raises(ValueError, match="incompatible"):
        eng.reload(rng.normal(size=(32, 9)).astype(np.float32))
    with pytest.raises(ValueError, match="incompatible"):
        eng.reload(rng.normal(size=(32,)).astype(np.float32))


def test_engine_reload_under_concurrent_predicts(rng):
    """Predicts racing a reload each see ONE consistent ensemble (old or
    new) — the (particles, kernels) pair swaps under a single lock."""
    eng, parts1 = _logreg_engine(rng, n=64)
    parts2 = rng.normal(size=(64, 5)).astype(np.float32)
    x = rng.normal(size=(4, 4)).astype(np.float32)
    want_old = eng.predict(x)["mean"]
    eng2 = PredictiveEngine("logreg", parts2, min_bucket=4, max_bucket=64)
    want_new = eng2.predict(x)["mean"]
    results, errors = [], []

    def hammer():
        try:
            for _ in range(30):
                results.append(eng.predict(x)["mean"])
        except Exception as e:  # pragma: no cover - failure surface
            errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    eng.reload(parts2)
    for t in threads:
        t.join()
    assert not errors
    for mean in results:
        assert (np.array_equal(mean, want_old)
                or np.array_equal(mean, want_new))


def test_hot_reloader_polls_and_swaps(tmp_path, rng):
    from dist_svgd_tpu.serving import CheckpointHotReloader

    parts1 = rng.normal(size=(16, 5)).astype(np.float32)
    parts2 = rng.normal(size=(16, 5)).astype(np.float32)
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root, every=1, backend="npz")
    mgr.save(10, {"particles": parts1})
    eng = PredictiveEngine.from_checkpoint(root, "logreg", min_bucket=4,
                                           max_bucket=16)
    hr = CheckpointHotReloader(eng, root)
    assert hr.loaded_step == 10
    assert hr.poll_once() is None  # nothing newer
    mgr.save(20, {"particles": parts2})
    assert hr.poll_once() == 20
    assert hr.poll_once() is None  # already serving step 20
    x = rng.normal(size=(3, 4)).astype(np.float32)
    eng2 = PredictiveEngine("logreg", parts2, min_bucket=4, max_bucket=16)
    np.testing.assert_array_equal(eng.predict(x)["mean"],
                                  eng2.predict(x)["mean"])
    assert eng.stats()["ensemble_tag"] == "step_20"


def test_hot_reloader_corrupt_newest_keeps_serving(tmp_path, rng):
    """A half-written newest step dir must not break the live server: the
    poll skips it (restore fallback would land on the already-served step)
    and retries next time."""
    import os as _os

    from dist_svgd_tpu.serving import CheckpointHotReloader

    parts1 = rng.normal(size=(16, 5)).astype(np.float32)
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root, every=1, backend="npz")
    mgr.save(1, {"particles": parts1})
    eng = PredictiveEngine.from_checkpoint(root, "logreg", min_bucket=4,
                                           max_bucket=16)
    hr = CheckpointHotReloader(eng, root)
    bad = _os.path.join(root, "step_2")
    _os.makedirs(bad)
    with open(_os.path.join(bad, "junk"), "w") as fh:
        fh.write("partial write")
    with pytest.warns(UserWarning, match="skipping unloadable"):
        assert hr.poll_once() is None
    assert hr.loaded_step == 1
    assert eng.stats()["reloads"] == 0


def test_hot_reloader_missing_key_raises(tmp_path, rng):
    from dist_svgd_tpu.serving import CheckpointHotReloader

    root = str(tmp_path / "root")
    mgr = CheckpointManager(root, every=1, backend="npz")
    mgr.save(1, {"particles": rng.normal(size=(8, 5)).astype(np.float32)})
    eng = PredictiveEngine.from_checkpoint(root, "logreg", min_bucket=4,
                                           max_bucket=16)
    hr = CheckpointHotReloader(eng, root)
    mgr.save(2, {"other": np.zeros((8, 5), np.float32)})
    with pytest.raises(KeyError, match="particles"):
        hr.poll_once()


def test_hot_reloader_baseline_is_engine_loaded_step(tmp_path, rng):
    """A save landing between the engine's cold start and the reloader's
    construction must NOT be marked already-served: the baseline is the
    step the engine actually loaded (engine.checkpoint_step), not the
    root's latest at construction time."""
    from dist_svgd_tpu.serving import CheckpointHotReloader

    root = str(tmp_path / "root")
    mgr = CheckpointManager(root, every=1, backend="npz")
    parts1 = rng.normal(size=(16, 5)).astype(np.float32)
    mgr.save(10, {"particles": parts1})
    eng = PredictiveEngine.from_checkpoint(root, "logreg", min_bucket=4,
                                           max_bucket=16)
    assert eng.checkpoint_step == 10
    # the race: training writes step 20 before the reloader attaches
    parts2 = rng.normal(size=(16, 5)).astype(np.float32)
    mgr.save(20, {"particles": parts2})
    hr = CheckpointHotReloader(eng, root)
    assert hr.loaded_step == 10
    assert hr.poll_once() == 20  # the raced save is served, not skipped
