"""Ring (ppermute) exchange implementation and blockwise/chunked φ.

The ring path must be *exactly* semantics-equivalent to the gather path
(SURVEY.md §5 long-context row: blockwise φ accumulation with
ppermute-rotated particle blocks generalises the reference's ring mode,
dsvgd/distsampler.py:131-150, from "interact with one block" to "interact
with all blocks, one at a time").  Differences are float summation order
only, so tolerances are tight under x64.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu import DistSampler
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.models.logreg import logreg_logp
from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.svgd import phi, phi_chunked
from dist_svgd_tpu.parallel.mesh import make_mesh

from test_distsampler import make_gaussian_problem


GATHER_MODES = [("all_scores", True), ("all_particles", False)]


@pytest.mark.parametrize("name,exch_s", GATHER_MODES)
@pytest.mark.parametrize("backend", ["shard_map", "vmap"])
def test_ring_matches_gather(name, exch_s, backend):
    """Multi-step ring trajectories equal the gather implementation."""
    rng = np.random.default_rng(17)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    mesh = make_mesh(S) if backend == "shard_map" else None
    if backend == "shard_map":
        assert mesh is not None
    outs = {}
    for impl in ("gather", "ring"):
        ds = DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=True, exchange_scores=exch_s,
            include_wasserstein=False, mesh=mesh, exchange_impl=impl,
        )
        for _ in range(4):
            out = ds.make_step(0.05)
        outs[impl] = np.asarray(out)
    np.testing.assert_allclose(outs["ring"], outs["gather"], rtol=1e-10, atol=1e-12)


@pytest.mark.parametrize("name,exch_s", GATHER_MODES)
@pytest.mark.parametrize("impl", ["gather", "ring"])
def test_shard_data_matches_replicated(name, exch_s, impl):
    """Sharding the data rows over the mesh is a pure layout change: the
    trajectory equals the replicated-data path in every all_* variant."""
    rng = np.random.default_rng(23)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    outs = {}
    for shard_data in (False, True):
        ds = DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=True, exchange_scores=exch_s,
            include_wasserstein=False, exchange_impl=impl,
            shard_data=shard_data,
        )
        for _ in range(3):
            out = ds.make_step(0.05)
        outs[shard_data] = np.asarray(out)
    np.testing.assert_allclose(outs[True], outs[False], rtol=1e-10, atol=1e-12)


def test_shard_data_drops_remainder_rows():
    """Indivisible row counts shard the first S·(rows//S) rows — the same
    rows the replicated path's slicing uses (reference drop policy,
    experiments/logreg.py:35)."""
    rng = np.random.default_rng(29)
    S = 4
    particles, (x, t), _ = make_gaussian_problem(rng, n_rows=24, num_shards=S)
    ragged = (jnp.concatenate([x, x[:3]]), jnp.concatenate([t, t[:3]]))
    outs = []
    for shard_data in (False, True):
        ds = DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=ragged,
            exchange_particles=True, exchange_scores=True,
            include_wasserstein=False, shard_data=shard_data,
        )
        outs.append(np.asarray(ds.make_step(0.05)))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-12)


def test_ring_rejects_partitions_and_shard_data():
    parts = jnp.zeros((8, 2))
    with pytest.raises(ValueError):
        DistSampler(
            2, gmm_logp, None, parts,
            exchange_particles=False, exchange_scores=False,
            include_wasserstein=False, shard_data=True,
        )
    with pytest.raises(ValueError):
        DistSampler(2, gmm_logp, None, parts, exchange_impl="bogus")


def test_ring_single_shard():
    """S=1 ring degenerates to the plain step (perm [(0,0)] self-loop)."""
    rng = np.random.default_rng(7)
    particles, data, _ = make_gaussian_problem(rng, num_shards=1)
    outs = {}
    for impl in ("gather", "ring"):
        ds = DistSampler(
            1, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=True, exchange_scores=True,
            include_wasserstein=False, exchange_impl=impl,
        )
        outs[impl] = np.asarray(ds.make_step(0.05))
    np.testing.assert_allclose(outs["ring"], outs["gather"], rtol=1e-12)


@pytest.mark.parametrize("chunk_size", [4, 5, 16, 100])
def test_phi_chunked_matches_phi(chunk_size):
    """Chunked accumulation (including ragged tails and chunk > m) equals the
    one-shot φ."""
    rng = np.random.default_rng(13)
    y = jnp.asarray(rng.normal(size=(6, 3)))
    x = jnp.asarray(rng.normal(size=(16, 3)))
    s = jnp.asarray(rng.normal(size=(16, 3)))
    want = np.asarray(phi(y, x, s))
    got = np.asarray(phi_chunked(y, x, s, chunk_size=chunk_size))
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-14)


@pytest.mark.parametrize("chunk_k,chunk_m", [(4, 5), (7, 16), (100, 3), (6, 100)])
def test_phi_blockwise_matches_phi(chunk_k, chunk_m):
    """Both-axes chunked accumulation (ragged tails in k and m, chunks larger
    than the axis) equals the one-shot φ — the XLA fallback for n past what
    phi_chunked's (chunk, k) Gram block can hold."""
    from dist_svgd_tpu.ops.svgd import phi_blockwise

    rng = np.random.default_rng(17)
    y = jnp.asarray(rng.normal(size=(13, 3)))
    x = jnp.asarray(rng.normal(size=(19, 3)))
    s = jnp.asarray(rng.normal(size=(19, 3)))
    want = np.asarray(phi(y, x, s))
    got = np.asarray(phi_blockwise(y, x, s, chunk_k=chunk_k, chunk_m=chunk_m))
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-14)
    # jit-traceable (the sampler-loop context it exists for)
    got_jit = np.asarray(jax.jit(
        lambda a, b, c: phi_blockwise(a, b, c, chunk_k=chunk_k, chunk_m=chunk_m)
    )(y, x, s))
    np.testing.assert_allclose(got_jit, want, rtol=1e-12, atol=1e-14)


def test_xla_dispatch_switches_to_blockwise_past_threshold(monkeypatch):
    """resolve_phi_fn's 'xla' path selects phi_blockwise above
    XLA_BLOCKWISE_MIN_PAIRS (both paths must agree numerically — verified by
    lowering the threshold so a small shape crosses it)."""
    from dist_svgd_tpu.ops import pallas_svgd
    from dist_svgd_tpu.ops.kernels import RBF
    from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn

    rng = np.random.default_rng(23)
    y = jnp.asarray(rng.normal(size=(12, 3)))
    x = jnp.asarray(rng.normal(size=(9, 3)))
    s = jnp.asarray(rng.normal(size=(9, 3)))
    want = np.asarray(resolve_phi_fn(RBF(1.0), "xla")(y, x, s))
    monkeypatch.setattr(pallas_svgd, "XLA_BLOCKWISE_MIN_PAIRS", 10)
    got = np.asarray(resolve_phi_fn(RBF(1.0), "xla")(y, x, s))
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-14)


def test_phi_chunked_generic_kernel():
    """Chunked path also supports non-analytic (autograd-fallback) kernels."""
    rng = np.random.default_rng(19)
    y = jnp.asarray(rng.normal(size=(4, 2)))
    x = jnp.asarray(rng.normal(size=(10, 2)))
    s = jnp.asarray(rng.normal(size=(10, 2)))

    def imq(a, b):  # inverse multiquadric
        return 1.0 / jnp.sqrt(1.0 + jnp.sum((a - b) ** 2))

    want = np.asarray(phi(y, x, s, kernel=imq))
    got = np.asarray(phi_chunked(y, x, s, kernel=imq, chunk_size=4))
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_ring_with_wasserstein_runs():
    """Ring impl composes with the W2 term (state bookkeeping unaffected)."""
    rng = np.random.default_rng(37)
    S = 2
    particles, data, _ = make_gaussian_problem(rng, n=6, d=2, n_rows=8, num_shards=S)
    ds = DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=True, exchange_scores=True,
        include_wasserstein=True, wasserstein_solver="sinkhorn",
        exchange_impl="ring",
    )
    for _ in range(3):
        out = ds.make_step(0.05, h=0.5)
    assert bool(jnp.isfinite(out).all())
