"""DistSampler vs the literal-semantics reference oracle (SURVEY.md §4:
single-device vs sharded equivalence, distributed-without-hardware via the
8-virtual-CPU-device mesh)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu import DistSampler
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.models.logreg import logreg_logp
from dist_svgd_tpu.ops.svgd import svgd_step
from dist_svgd_tpu.parallel.mesh import make_mesh

from _oracle import RefDistOracle


def make_gaussian_problem(rng, n=8, d=2, n_rows=24, num_shards=4):
    """Shared fixture: Bayesian-logreg-like problem with sharded data.

    Returns (particles, data, score_of) where score_of(rank, x) matches what
    each reference rank's logp closure computes on its local slice.
    """
    particles = rng.normal(size=(n, d))
    x = rng.normal(size=(n_rows, d - 1))
    t = np.where(rng.normal(size=n_rows) > 0, 1.0, -1.0)
    data = (jnp.asarray(x), jnp.asarray(t))
    per = n_rows // num_shards
    grad = jax.grad(logreg_logp, argnums=0)

    def score_of(rank, theta):
        sl = slice(rank * per, (rank + 1) * per)
        return np.asarray(grad(jnp.asarray(theta), (data[0][sl], data[1][sl])))

    return particles, data, score_of


MODES = [
    ("all_scores", True, True),
    ("all_particles", True, False),
    ("partitions", False, False),
]


@pytest.mark.parametrize("update_rule", ["jacobi", "gauss_seidel"])
@pytest.mark.parametrize("name,exch_p,exch_s", MODES)
@pytest.mark.parametrize("backend", ["shard_map", "vmap"])
def test_modes_match_oracle(name, exch_p, exch_s, backend, update_rule):
    """Three steps of every exchange mode equal the oracle on both backends,
    for both the TPU-native Jacobi update and the reference's literal
    Gauss–Seidel in-place sweep (dsvgd/distsampler.py:194-200)."""
    rng = np.random.default_rng(11)
    S = 4
    particles, data, score_of = make_gaussian_problem(rng, num_shards=S)
    mesh = make_mesh(S) if backend == "shard_map" else None
    if backend == "shard_map":
        assert mesh is not None

    ds = DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=exch_p, exchange_scores=exch_s,
        include_wasserstein=False, mesh=mesh, update_rule=update_rule,
    )
    oracle = RefDistOracle(
        S, score_of, particles,
        exchange_particles=exch_p, exchange_scores=exch_s,
        score_scale=S if not exch_s else 1.0,  # N_global/N_local = S
        update_rule=update_rule,
    )
    for _ in range(3):
        got = np.asarray(ds.make_step(0.05))
        want = oracle.make_step(0.05)
        np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)


def test_backends_agree():
    """shard_map on a real mesh and vmap emulation produce identical states."""
    rng = np.random.default_rng(3)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    runs = []
    for mesh in (make_mesh(S), None):
        ds = DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=False, mesh=mesh,
        )
        for _ in range(4):
            ds.make_step(0.05)
        runs.append(np.asarray(ds.particles))
    np.testing.assert_allclose(runs[0], runs[1], rtol=1e-12)


def test_single_shard_equals_global_step():
    """S=1 must equal the plain fused Jacobi step on the full set.

    Note a deliberate divergence from the reference: with S=1 and
    exchange_scores=True the reference reads an uninitialised score buffer
    (make_step skips the exchange, dsvgd/distsampler.py:182, but _phi_hat
    still indexes self._scores); we compute the correct local scores instead.
    """
    rng = np.random.default_rng(5)
    parts = rng.normal(size=(6, 1))
    ds = DistSampler(
        1, gmm_logp, None, jnp.asarray(parts), include_wasserstein=False
    )
    got = np.asarray(ds.make_step(0.1))
    scores = jax.vmap(lambda x: jax.grad(gmm_logp)(x))(jnp.asarray(parts))
    want = np.asarray(svgd_step(jnp.asarray(parts), scores, 0.1))
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_all_scores_equals_global_for_prior_free_logp():
    """Property (SURVEY.md §4): with a logp that is purely additive in the
    data (no prior term), the all_scores psum reconstructs the exact global
    score, so the sharded step equals the global full-data step."""
    rng = np.random.default_rng(9)
    S, n, d, rows = 4, 8, 2, 16
    parts = rng.normal(size=(n, d))
    x = rng.normal(size=(rows, d))

    def lik_only(theta, data):
        return -0.5 * jnp.sum((data[0] @ theta) ** 2)  # no prior term

    data = (jnp.asarray(x),)
    ds = DistSampler(
        S, lik_only, None, jnp.asarray(parts), data=data,
        exchange_particles=True, exchange_scores=True, include_wasserstein=False,
    )
    got = np.asarray(ds.make_step(0.01))

    full_score = jax.vmap(lambda p: jax.grad(lik_only)(p, data))(jnp.asarray(parts))
    want = np.asarray(svgd_step(jnp.asarray(parts), full_score, 0.01))
    np.testing.assert_allclose(got, want, rtol=1e-10)


def test_partitions_ownership_rotation():
    """owned_block follows the reference ring: after t steps rank r updates
    logical block (r - t) mod S (dsvgd/distsampler.py:131-150)."""
    rng = np.random.default_rng(2)
    S = 4
    particles, data, score_of = make_gaussian_problem(rng, num_shards=S)
    ds = DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=False, exchange_scores=False, include_wasserstein=False,
    )
    oracle = RefDistOracle(
        S, score_of, particles,
        exchange_particles=False, exchange_scores=False,
        score_scale=S, update_rule="jacobi",
    )
    for _ in range(5):
        ds.make_step(0.05)
        oracle.make_step(0.05)
    per = ds.num_particles // S
    for r in range(S):
        b = oracle.block_of_rank(r)
        np.testing.assert_allclose(
            np.asarray(ds.owned_block(r)),
            oracle.global_particles[b * per : (b + 1) * per],
            rtol=1e-10,
        )


@pytest.mark.parametrize("name,exch_p,exch_s", MODES)
def test_wasserstein_modes_match_oracle(name, exch_p, exch_s):
    """Multi-step trajectories with the LP W2 term, including the reference's
    previous-particles snapshot warts, match the oracle in every mode."""
    rng = np.random.default_rng(21)
    S = 2
    particles, data, score_of = make_gaussian_problem(rng, n=6, d=2, n_rows=8, num_shards=S)
    ds = DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=exch_p, exchange_scores=exch_s,
        include_wasserstein=True, wasserstein_solver="lp",
    )
    oracle = RefDistOracle(
        S, score_of, particles,
        exchange_particles=exch_p, exchange_scores=exch_s,
        include_wasserstein=True,
        score_scale=S if not exch_s else 1.0,
        update_rule="jacobi",
    )
    for _ in range(3):
        got = np.asarray(ds.make_step(0.05, h=0.5))
        want = oracle.make_step(0.05, h=0.5)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-8)


@pytest.mark.parametrize("name,exch_p,exch_s", MODES)
def test_wasserstein_gauss_seidel_matches_oracle(name, exch_p, exch_s):
    """GS sweep + LP W2 term (make_step path — the host LP cannot live in a
    scan) matches the oracle in every mode.  The scanned GS+W2 composition
    (sinkhorn) is pinned against this eager path below
    (test_run_steps_wasserstein_gauss_seidel_matches_eager)."""
    rng = np.random.default_rng(23)
    S = 2
    particles, data, score_of = make_gaussian_problem(rng, n=6, d=2, n_rows=8, num_shards=S)
    ds = DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=exch_p, exchange_scores=exch_s,
        include_wasserstein=True, wasserstein_solver="lp",
        update_rule="gauss_seidel",
    )
    oracle = RefDistOracle(
        S, score_of, particles,
        exchange_particles=exch_p, exchange_scores=exch_s,
        include_wasserstein=True,
        score_scale=S if not exch_s else 1.0,
        update_rule="gauss_seidel",
    )
    for _ in range(3):
        got = np.asarray(ds.make_step(0.05, h=0.5))
        want = oracle.make_step(0.05, h=0.5)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-8)


def test_gauss_seidel_constructor_constraints():
    parts = jnp.zeros((4, 1))
    with pytest.raises(ValueError, match="gather"):
        DistSampler(2, gmm_logp, None, parts, include_wasserstein=False,
                    update_rule="gauss_seidel", exchange_impl="ring")
    with pytest.raises(ValueError, match="update_rule"):
        DistSampler(2, gmm_logp, None, parts, include_wasserstein=False,
                    update_rule="typo")


@pytest.mark.parametrize(
    "name,exch_p,exch_s",
    [("all_scores", True, True), ("all_particles", True, False),
     ("partitions", False, False)],
)
def test_run_steps_wasserstein_gauss_seidel_matches_eager(name, exch_p, exch_s):
    """Scanned GS+W2: the carried-snapshot Sinkhorn path composes with the
    literal Gauss–Seidel sweep, and the scanned trajectory equals the eager
    make_step one (whose GS+W2 semantics are oracle-pinned above) in every
    mode."""
    rng = np.random.default_rng(37)
    S = 2
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, n_rows=8, num_shards=S)

    def build():
        return DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=exch_p, exchange_scores=exch_s,
            include_wasserstein=True, wasserstein_solver="sinkhorn",
            sinkhorn_eps=0.05, sinkhorn_iters=50,
            update_rule="gauss_seidel",
        )

    eager = build()
    for _ in range(4):
        want = eager.make_step(0.05, h=0.5)
    scanned = build()
    got = scanned.run_steps(4, 0.05, h=0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-6)
    np.testing.assert_allclose(
        scanned._previous, eager._previous, rtol=2e-6, atol=1e-12
    )
    # mixing afterwards (scan → eager) stays on-trajectory
    np.testing.assert_allclose(
        np.asarray(scanned.make_step(0.05, h=0.5)),
        np.asarray(eager.make_step(0.05, h=0.5)),
        rtol=2e-6,
    )


def test_run_steps_equals_eager_gauss_seidel():
    """The scanned dispatch reproduces eager GS make_step trajectories (the
    bound per-shard step is shared, so the scan must be semantics-neutral)."""
    rng = np.random.default_rng(29)
    S = 2
    particles, data, _ = make_gaussian_problem(rng, n=6, d=2, n_rows=8, num_shards=S)
    kw = dict(
        data=data, exchange_particles=True, exchange_scores=False,
        include_wasserstein=False, update_rule="gauss_seidel",
    )
    eager = DistSampler(S, logreg_logp, None, jnp.asarray(particles), **kw)
    scanned = DistSampler(S, logreg_logp, None, jnp.asarray(particles), **kw)
    for _ in range(4):
        eager.make_step(0.05)
    got = scanned.run_steps(4, 0.05)
    np.testing.assert_allclose(np.asarray(got), np.asarray(eager.particles), rtol=1e-12)


def test_explicit_scale_factors():
    """N_local/N_global are importance-scale factors (reference constructor
    args); N_global defaults to N_local·S when only N_local is given, and an
    explicit pair produces exactly that ratio in the score scale."""
    parts = jnp.zeros((4, 1))
    ds = DistSampler(2, gmm_logp, None, parts, N_local=100, include_wasserstein=False)
    assert ds._score_scale == pytest.approx(2.0)  # N_global defaults to 200
    ds2 = DistSampler(2, gmm_logp, None, parts, N_local=50, N_global=400,
                      include_wasserstein=False)
    assert ds2._score_scale == pytest.approx(8.0)


def test_scale_factors_do_not_change_data_slicing():
    """Explicit N_local must not move the physical data slices: the sharded
    step with N_local == rows (scale S·rows/rows... ) still slices rows//S
    per shard.  Compare against manually scaled oracle scores."""
    rng = np.random.default_rng(31)
    S = 2
    particles, data, score_of = make_gaussian_problem(rng, n=4, d=2, n_rows=8, num_shards=S)
    rows = 8
    ds = DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        N_local=rows, N_global=rows,  # scale factor 1 instead of derived S
        exchange_particles=True, exchange_scores=False, include_wasserstein=False,
    )
    oracle = RefDistOracle(
        S, score_of, particles,
        exchange_particles=True, exchange_scores=False,
        score_scale=1.0, update_rule="jacobi",
    )
    got = np.asarray(ds.make_step(0.05))
    want = oracle.make_step(0.05)
    np.testing.assert_allclose(got, want, rtol=1e-10)


def test_sinkhorn_solver_tracks_lp():
    """The on-device batched Sinkhorn path stays close to the exact LP path
    over a short trajectory."""
    rng = np.random.default_rng(41)
    S = 2
    particles, data, _ = make_gaussian_problem(rng, n=6, d=2, n_rows=8, num_shards=S)
    outs = {}
    for solver in ("lp", "sinkhorn"):
        ds = DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=True, exchange_scores=True,
            include_wasserstein=True, wasserstein_solver=solver,
            sinkhorn_eps=0.002, sinkhorn_iters=2000,
        )
        for _ in range(3):
            out = ds.make_step(0.05, h=0.5)
        outs[solver] = np.asarray(out)
    np.testing.assert_allclose(outs["sinkhorn"], outs["lp"], atol=5e-3)


def test_datafree_target_all_modes_run():
    """GMM-style targets (data=None) run in every mode without data plumbing."""
    rng = np.random.default_rng(4)
    parts = jnp.asarray(rng.normal(size=(16, 1)))
    for _, exch_p, exch_s in MODES:
        ds = DistSampler(
            4, gmm_logp, None, parts,
            exchange_particles=exch_p, exchange_scores=exch_s,
            include_wasserstein=False,
        )
        out = ds.make_step(0.1)
        assert bool(jnp.isfinite(out).all())


@pytest.mark.parametrize("name,exch_p,exch_s", MODES)
@pytest.mark.parametrize(
    "batch_size,exchange_impl,shard_data",
    [
        (None, "gather", False),
        (3, "gather", False),
        (None, "ring", False),     # ppermute rotation under the scan
        (None, "gather", True),    # sharded data arg through the scan
    ],
)
def test_run_steps_equals_eager_make_step(
    name, exch_p, exch_s, batch_size, exchange_impl, shard_data
):
    """One scanned run_steps(K) dispatch reproduces K make_step calls exactly
    (same step-counter rotation and per-step minibatch key stream)."""
    if shard_data and name == "partitions":
        pytest.skip("shard_data is rejected in partitions mode")
    if exchange_impl == "ring" and name == "partitions":
        pytest.skip("ring impl only affects the all_* modes")
    rng = np.random.default_rng(17)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)

    def build():
        return DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=exch_p, exchange_scores=exch_s,
            include_wasserstein=False, batch_size=batch_size, seed=5,
            exchange_impl=exchange_impl, shard_data=shard_data,
        )

    eager = build()
    for _ in range(4):
        want = eager.make_step(0.05)
    scanned = build()
    got = scanned.run_steps(4, 0.05)
    # The two paths are separately compiled XLA programs (standalone step vs
    # scan body); allow last-ulp reassociation differences rather than
    # demanding bitwise equality across backends.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-6)
    assert scanned._t == eager._t
    # mixing afterwards stays on the same trajectory
    np.testing.assert_allclose(
        np.asarray(scanned.make_step(0.05)),
        np.asarray(eager.make_step(0.05)),
        rtol=2e-6,
    )


def test_run_steps_rejects_lp_wasserstein():
    """The host-LP W2 path stays make_step-only."""
    rng = np.random.default_rng(2)
    particles, data, _ = make_gaussian_problem(rng, num_shards=2)
    ds = DistSampler(
        2, logreg_logp, None, jnp.asarray(particles), data=data,
        include_wasserstein=True, wasserstein_solver="lp",
    )
    with pytest.raises(ValueError, match="sinkhorn"):
        ds.run_steps(3, 0.05)
    # ring impl is a no-op in partitions mode, so scanned W2 must accept it
    ds2 = DistSampler(
        2, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=False, exchange_scores=False,
        include_wasserstein=True, wasserstein_solver="sinkhorn",
        sinkhorn_iters=20, exchange_impl="ring",
    )
    out = ds2.run_steps(3, 0.05, h=0.5)
    assert bool(jnp.isfinite(out).all())


@pytest.mark.parametrize("name,exch_p,exch_s", MODES)
def test_run_steps_wasserstein_matches_eager(name, exch_p, exch_s):
    """Scanned Sinkhorn-W2 trajectories (previous snapshots carried on
    device) equal the eager make_step path, including the no-W2 first step
    and the per-mode snapshot warts."""
    rng = np.random.default_rng(31)
    S = 2
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, n_rows=8, num_shards=S)

    def build():
        return DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=exch_p, exchange_scores=exch_s,
            include_wasserstein=True, wasserstein_solver="sinkhorn",
            sinkhorn_eps=0.05, sinkhorn_iters=50,
        )

    eager = build()
    for _ in range(4):
        want = eager.make_step(0.05, h=0.5)
    scanned = build()
    got = scanned.run_steps(4, 0.05, h=0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-6)
    np.testing.assert_allclose(
        scanned._previous, eager._previous, rtol=2e-6, atol=1e-12
    )
    # mixing afterwards (scan → eager vs eager → eager) stays on-trajectory
    np.testing.assert_allclose(
        np.asarray(scanned.make_step(0.05, h=0.5)),
        np.asarray(eager.make_step(0.05, h=0.5)),
        rtol=2e-6,
    )
    # and eager → scan continues identically too
    np.testing.assert_allclose(
        np.asarray(scanned.run_steps(2, 0.05, h=0.5)),
        np.asarray([eager.make_step(0.05, h=0.5) for _ in range(2)][-1]),
        rtol=2e-6,
    )


def test_run_steps_record_matches_eager_history():
    """record=True returns the reference-convention pre-update snapshots —
    exactly the per-step particle states the eager loop observes."""
    rng = np.random.default_rng(23)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)

    def build():
        return DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=False, exchange_scores=False,  # partitions
            include_wasserstein=False, seed=9,
        )

    eager = build()
    want = [np.asarray(eager.particles)]
    for _ in range(5):
        want.append(np.asarray(eager.make_step(0.05)))

    scanned = build()
    final, hist = scanned.run_steps(5, 0.05, record=True)
    got = np.concatenate([np.asarray(hist), np.asarray(final)[None]])
    np.testing.assert_allclose(got, np.stack(want), rtol=2e-6)
