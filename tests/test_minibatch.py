"""Minibatched stochastic scores (writeup.tex:214-231 approximation;
BASELINE.json config 4).

Key exactness property: drawing B = N rows *without replacement* is a
permutation of the full dataset, and every likelihood here is a sum over
rows, so the minibatch score equals the full-data score exactly (scale
N/B = 1).  That turns the stochastic path into a deterministic test.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu import DistSampler, Sampler
from dist_svgd_tpu.models.logreg import logreg_logp, make_logreg_logp

from test_distsampler import make_gaussian_problem


def _problem(rng, n_rows=24):
    d = 3
    x = rng.normal(size=(n_rows, d - 1))
    t = np.where(rng.normal(size=n_rows) > 0, 1.0, -1.0)
    return (jnp.asarray(x), jnp.asarray(t)), d


def test_full_batch_equals_full_data_sampler():
    rng = np.random.default_rng(101)
    data, d = _problem(rng)
    n_rows = data[0].shape[0]
    full = Sampler(d, make_logreg_logp(*data))
    mb = Sampler(d, logreg_logp, data=data, batch_size=n_rows)
    f1, _ = full.run(8, 5, 0.05, seed=3, record=False)
    f2, _ = mb.run(8, 5, 0.05, seed=3, record=False)
    np.testing.assert_allclose(np.asarray(f2), np.asarray(f1), rtol=1e-10)


def test_separate_prior_full_batch():
    """log_prior split: lik-only logp + separate prior at B=N equals the
    fused logp (prior scale is 1 so the split is algebraically neutral)."""
    rng = np.random.default_rng(103)
    data, d = _problem(rng)
    n_rows = data[0].shape[0]

    def lik_only(theta, batch):
        x, t = batch
        z = (x @ theta[1:]) * t.reshape(-1)
        return -jnp.sum(jnp.logaddexp(0.0, -z))

    def prior(theta):
        alpha = jnp.exp(theta[0])
        w = theta[1:]
        k = w.shape[0]
        return -alpha + 0.5 * k * theta[0] - 0.5 * k * jnp.log(2 * jnp.pi) \
            - 0.5 * alpha * jnp.dot(w, w)

    init = jnp.asarray(rng.normal(size=(6, d)))  # float64 under x64: the two
    # gradient groupings are algebraically equal, so only summation-order
    # noise separates them — tight at double precision
    fused = Sampler(d, logreg_logp, data=data, batch_size=n_rows)
    split = Sampler(d, lik_only, data=data, batch_size=n_rows, log_prior=prior)
    f1, _ = fused.run(6, 4, 0.05, seed=1, record=False, initial_particles=init)
    f2, _ = split.run(6, 4, 0.05, seed=1, record=False, initial_particles=init)
    np.testing.assert_allclose(np.asarray(f2), np.asarray(f1), rtol=1e-10)


def test_minibatch_scores_unbiased():
    """E[minibatch score] = full-data score for the log_prior-split estimator
    (the fused-logp variant deliberately N/B-scales the prior too — the
    reference's importance-scaling convention — and is *not* unbiased)."""
    rng = np.random.default_rng(107)
    data, d = _problem(rng, n_rows=12)
    n_rows = 12
    B = 4

    def lik_only(theta, batch):
        xb, tb = batch
        z = (xb @ theta[1:]) * tb.reshape(-1)
        return -jnp.sum(jnp.logaddexp(0.0, -z))

    def prior(theta):
        alpha = jnp.exp(theta[0])
        w = theta[1:]
        k = w.shape[0]
        return -alpha + 0.5 * k * theta[0] - 0.5 * k * jnp.log(2 * jnp.pi) \
            - 0.5 * alpha * jnp.dot(w, w)

    theta = jnp.asarray(rng.normal(size=(d,)))
    full_score = np.asarray(jax.grad(logreg_logp)(theta, data))

    sampler = Sampler(d, lik_only, data=data, batch_size=B, log_prior=prior)
    keys = jax.random.split(jax.random.PRNGKey(0), 4000)
    draws = jax.vmap(lambda k: sampler._minibatch_scores(theta[None], k)[0])(keys)
    mean = np.asarray(jnp.mean(draws, axis=0))
    se = np.asarray(jnp.std(draws, axis=0)) / np.sqrt(len(keys))
    np.testing.assert_allclose(mean, full_score, atol=5 * np.max(se) + 1e-8)


def test_minibatch_deterministic_per_seed():
    rng = np.random.default_rng(109)
    data, d = _problem(rng)
    outs = []
    for _ in range(2):
        s = Sampler(d, logreg_logp, data=data, batch_size=6)
        f, _ = s.run(8, 5, 0.05, seed=42, record=False)
        outs.append(np.asarray(f))
    np.testing.assert_array_equal(outs[0], outs[1])
    s2 = Sampler(d, logreg_logp, data=data, batch_size=6)
    f3, _ = s2.run(8, 5, 0.05, seed=43, record=False)
    assert not np.allclose(outs[0], np.asarray(f3))


@pytest.mark.parametrize("exch_s", [True, False])
@pytest.mark.parametrize("impl", ["gather", "ring"])
def test_dist_full_batch_equals_full_data(exch_s, impl):
    """DistSampler with per-shard B = rows_per_shard equals the non-minibatch
    path in every all_* variant (permutation invariance per shard)."""
    rng = np.random.default_rng(113)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    rows_per_shard = data[0].shape[0] // S
    outs = {}
    for bs in (None, rows_per_shard):
        ds = DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=True, exchange_scores=exch_s,
            include_wasserstein=False, exchange_impl=impl, batch_size=bs,
        )
        for _ in range(3):
            out = ds.make_step(0.05)
        outs[bs] = np.asarray(out)
    np.testing.assert_allclose(outs[rows_per_shard], outs[None], rtol=1e-10)


def test_dist_minibatch_ring_equals_gather():
    """Same seed ⇒ same per-shard batches ⇒ ring ≡ gather holds even with
    stochastic scores."""
    rng = np.random.default_rng(127)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    outs = {}
    for impl in ("gather", "ring"):
        ds = DistSampler(
            S, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=True, exchange_scores=True,
            include_wasserstein=False, exchange_impl=impl,
            batch_size=3, seed=5,
        )
        for _ in range(3):
            out = ds.make_step(0.05)
        outs[impl] = np.asarray(out)
    np.testing.assert_allclose(outs["ring"], outs["gather"], rtol=1e-10)


def test_dist_partitions_minibatch_runs():
    rng = np.random.default_rng(131)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, num_shards=S)
    ds = DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=False, exchange_scores=False,
        include_wasserstein=False, batch_size=3,
    )
    for _ in range(3):
        out = ds.make_step(0.05)
    assert bool(jnp.isfinite(out).all())


def test_batch_size_validation():
    rng = np.random.default_rng(137)
    data, d = _problem(rng, n_rows=8)
    with pytest.raises(ValueError):
        Sampler(d, logreg_logp, data=data, batch_size=9)
    with pytest.raises(ValueError):
        Sampler(d, logreg_logp, batch_size=4)  # no data
    with pytest.raises(ValueError):
        DistSampler(
            2, logreg_logp, None, jnp.zeros((4, d)), data=data,
            include_wasserstein=False, batch_size=5,  # > 8 // 2 local rows
        )
