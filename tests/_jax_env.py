"""Shared CPU-environment plumbing for the test harness.

This image boots every interpreter with an `axon` TPU PJRT plugin
pre-registered via sitecustomize and `JAX_PLATFORMS=axon` exported.  CPU-only
test processes must (a) force the platform to cpu through jax.config (the env
var may be pre-set to axon) and (b) drop the axon backend factory before any
client initialises — leaving it registered makes CPU-only init block on the
TPU tunnel.  Used by conftest.py (the pytest process) and mh_worker.py
(federation subprocesses) so the workaround lives in one place.
"""

import os
import re


def setup_cpu(device_count: int = 8, enable_x64: bool = True) -> None:
    """Force this process onto ``device_count`` virtual CPU devices.

    Must be called before any other JAX use.  Safe to call before
    ``jax.distributed.initialize`` — nothing here touches a device.
    Any inherited ``--xla_force_host_platform_device_count`` is replaced
    (not skipped), so the requested count always wins while unrelated
    inherited XLA flags are preserved.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={device_count}"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    if enable_x64:
        jax.config.update("jax_enable_x64", True)

    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)
