"""Dataset-ingestion tests — in particular the *real* ``benchmarks.mat``
branch of the loader, which the mounted LFS-pointer file can never exercise
(VERDICT r1 missing item 2).  A tiny ``scipy.io.savemat`` fixture reproduces
the reference's data contract (struct fields X/t/train/test, 1-based fold
indices — reference experiments/logreg.py:28-33)."""

import numpy as np
import pytest

from dist_svgd_tpu.utils.datasets import Fold, load_benchmark


@pytest.fixture
def tiny_mat(tmp_path):
    """A benchmarks.mat-shaped file with two datasets and known contents."""
    savemat = pytest.importorskip("scipy.io").savemat
    rng = np.random.default_rng(7)
    out = {}
    contents = {}
    for name, (n, dim) in {"banana": (30, 2), "titanic": (24, 3)}.items():
        x = rng.normal(size=(n, dim)).astype(np.float64)
        t = np.where(rng.normal(size=(n, 1)) > 0, 1.0, -1.0)
        n_train = 2 * n // 3
        folds = np.stack([rng.permutation(n) for _ in range(4)])
        train = folds[:, :n_train] + 1  # 1-based, the .mat convention
        test = folds[:, n_train:] + 1
        ds = np.empty((1, 1), dtype=[
            ("x", "O"), ("t", "O"), ("train", "O"), ("test", "O")])
        ds[0, 0] = (x, t, train, test)
        out[name] = ds
        contents[name] = (x, t, train, test)
    path = tmp_path / "benchmarks.mat"
    savemat(str(path), out)
    return str(path), contents


def test_real_mat_branch_reproduces_reference_indexing(tiny_mat):
    """``X[train - 1][fold]`` with 1-based indices, per dataset struct."""
    path, contents = tiny_mat
    for name in ("banana", "titanic"):
        x, t, train, test = contents[name]
        for fold in (0, 2):
            got = load_benchmark(name, fold, mat_path=path)
            np.testing.assert_allclose(got.x_train, x[train - 1][fold], rtol=1e-6)
            np.testing.assert_allclose(got.t_train, t[train - 1][fold])
            np.testing.assert_allclose(got.x_test, x[test - 1][fold], rtol=1e-6)
            np.testing.assert_allclose(got.t_test, t[test - 1][fold])


def test_real_mat_branch_matches_synthetic_interface(tiny_mat):
    """The real-file branch returns the same Fold interface (shapes ranks,
    dtypes, ±1 labels) as the synthetic fallback, so drivers are oblivious
    to which branch served them."""
    path, _ = tiny_mat
    real = load_benchmark("banana", 1, mat_path=path)
    synth = load_benchmark("banana", 1, mat_path=None)
    for f in (real, synth):
        assert isinstance(f, Fold)
        assert f.x_train.dtype == np.float32
        assert f.t_train.dtype == np.float64
        assert f.x_train.ndim == 2
        assert f.x_train.shape[0] == f.t_train.shape[0]
        assert f.x_test.shape[1] == f.x_train.shape[1]
        assert set(np.unique(f.t_train)) <= {-1.0, 1.0}


def test_lfs_pointer_falls_back_to_synthetic(tmp_path):
    """A Git-LFS pointer file (the state of the mounted reference dataset,
    .gitattributes:2) must not be parsed as a .mat — fall back."""
    p = tmp_path / "benchmarks.mat"
    p.write_bytes(
        b"version https://git-lfs.github.com/spec/v1\n"
        b"oid sha256:47c19e0000\nsize 8912086\n"
    )
    got = load_benchmark("banana", 3, mat_path=str(p))
    want = load_benchmark("banana", 3, mat_path=None)
    np.testing.assert_array_equal(got.x_train, want.x_train)
