"""Serving fleet (dist_svgd_tpu/serving/fleet.py): consistent-hash
routing with bounded load, the replica circuit breaker (active probes,
passive scoring, SLO burn, half-open readmission), the forwarding
robustness kit (deadline propagation, idempotency-aware retries, 429
backpressure, tail hedging, graceful 503), and the process-level fault
fakes — every failover path on CPU, clock-injectable, no real sockets
except the two HTTP-front-door tests.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from dist_svgd_tpu.resilience import (
    Backoff,
    PartitionAt,
    ReplicaHangAt,
    ReplicaKillAt,
    SlowReplicaAt,
)
from dist_svgd_tpu.serving import fleet
from dist_svgd_tpu.telemetry.metrics import MetricsRegistry


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _body(tenant="t0", rows=1):
    return json.dumps({"inputs": [[0.1, 0.2]] * rows,
                       "tenant": tenant}).encode()


def make_fleet(n=3, *, clock=None, faults=(), predict=None, registry=None,
               tenants=(), fail_threshold=2, passive_fail_threshold=3,
               open_cooldown_s=2.0, **router_kw):
    """3 loopback replicas + fake transport + clock-injected router.
    Returns (router, replicas dict, transport, clock, sleeps list)."""
    clock = clock or ManualClock()
    reg = registry or MetricsRegistry()
    replicas = {f"r{i}": fleet.LoopbackReplica(
        f"r{i}", predict_fn=predict, tenants=tenants, clock=clock)
        for i in range(n)}
    transport = fleet.FakeTransport(replicas, faults=faults,
                                    advance=clock.advance)
    rs = fleet.ReplicaSet(
        list(replicas), transport, fail_threshold=fail_threshold,
        passive_fail_threshold=passive_fail_threshold,
        open_cooldown_s=open_cooldown_s, probe_interval_s=0.05,
        clock=clock, registry=reg)
    sleeps = []

    def fake_sleep(s):
        sleeps.append(s)
        clock.advance(s)

    router_kw.setdefault("backoff", Backoff(base_s=0.01, factor=2.0,
                                            max_s=0.1, jitter_frac=0.0))
    router = fleet.FleetRouter(
        list(replicas), transport=transport, replica_set=rs,
        clock=clock, sleep=fake_sleep, registry=reg, **router_kw)
    return router, replicas, transport, clock, sleeps


# --------------------------------------------------------------------- #
# consistent hashing


def test_ring_deterministic_and_complete():
    ring = fleet._HashRing(["a", "b", "c"], vnodes=16)
    order1 = ring.order("tenant-42")
    assert sorted(order1) == ["a", "b", "c"]
    assert ring.order("tenant-42") == order1  # deterministic
    # different tenants spread their homes over multiple replicas
    homes = {ring.order(f"t{i}")[0] for i in range(50)}
    assert len(homes) >= 2


def test_ring_stable_failover_chain():
    """Ring order is a property of the tenant, not of replica health —
    a tenant returns to the same home after its replica recovers."""
    ring = fleet._HashRing(["a", "b", "c"], vnodes=16)
    for t in ("x", "y", "z"):
        assert ring.order(t)[0] == ring.order(t)[0]


def test_bounded_load_overflow():
    """A replica past load_factor × fair share overflows to the next ring
    candidate; the overflow is a preference, not a refusal."""
    clock = ManualClock()
    reg = MetricsRegistry()
    reps = {r: fleet.LoopbackReplica(r) for r in ("a", "b")}
    rs = fleet.ReplicaSet(list(reps), fleet.FakeTransport(reps),
                          clock=clock, registry=reg)
    # pile 4 in-flight requests onto a
    for _ in range(4):
        assert rs.begin_request("a")
    # fair share at load_factor=1.0 is ceil((4+1)/2) = 3 < 5 -> a refuses
    assert not rs.begin_request("a", load_factor=1.0)
    assert rs.begin_request("b", load_factor=1.0)


# --------------------------------------------------------------------- #
# SLO classification (what the router reads off /slo)


def test_classify_slo_verdicts():
    assert fleet.classify_slo({"status": "ok", "ts": 10.0}) == "healthy"
    assert fleet.classify_slo({"status": "breach"}) == "burning"
    # no_data / unknown statuses, garbage, and missing docs are UNKNOWN —
    # never healthy
    assert fleet.classify_slo({"status": "no_data"}) == "unknown"
    assert fleet.classify_slo({}) == "unknown"
    assert fleet.classify_slo(None) == "unknown"
    assert fleet.classify_slo("not a dict") == "unknown"


def test_classify_slo_staleness_reads_unknown_never_healthy():
    """A stale 'ok' (or a verdict with no timestamp at all) must read
    unknown: stale good news is no news."""
    fresh = {"status": "ok", "ts": 100.0}
    assert fleet.classify_slo(fresh, now_s=105.0, max_age_s=30.0) == "healthy"
    assert fleet.classify_slo(fresh, now_s=200.0, max_age_s=30.0) == "unknown"
    no_ts = {"status": "ok"}
    assert fleet.classify_slo(no_ts, now_s=200.0, max_age_s=30.0) == "unknown"
    # a stale breach is also unknown (don't eject on old bad news either)
    stale_bad = {"status": "breach", "ts": 0.0}
    assert fleet.classify_slo(stale_bad, now_s=100.0,
                              max_age_s=10.0) == "unknown"


# --------------------------------------------------------------------- #
# circuit breaker: active probes


def test_probe_failures_eject_after_threshold():
    router, reps, tr, clock, _ = make_fleet(fail_threshold=2)
    rs = router.replica_set
    tr.kill("r1")
    rs.probe_once()
    assert rs.state("r1") == fleet.CLOSED  # one strike is not an outage
    rs.probe_once()
    assert rs.state("r1") == fleet.OPEN
    _, rid, _, to, reason = list(rs.state_changes)[-1]
    assert (rid, to, reason) == ("r1", "open", "probe_failures")


def test_slo_burn_ejects_immediately():
    router, reps, tr, clock, _ = make_fleet()
    reps["r2"].slo_status = "breach"
    router.replica_set.probe_once()
    assert router.replica_set.state("r2") == fleet.OPEN
    assert list(router.replica_set.state_changes)[-1][4] == "slo_burn"
    # unknown slo must NOT eject (and not re-admit)
    reps["r1"].slo_status = "no_data"
    router.replica_set.probe_once()
    assert router.replica_set.state("r1") == fleet.CLOSED


def test_draining_probe_ejects_in_one_sweep():
    """Drain is a deliberate signal, not a flaky probe: one strike."""
    router, reps, tr, clock, _ = make_fleet(fail_threshold=3)
    reps["r0"].draining = True
    router.replica_set.probe_once()
    assert router.replica_set.state("r0") == fleet.OPEN
    assert list(router.replica_set.state_changes)[-1][4] == "draining"


def test_half_open_readmission_cycle():
    router, reps, tr, clock, _ = make_fleet(fail_threshold=1,
                                            open_cooldown_s=2.0)
    rs = router.replica_set
    tr.kill("r1")
    rs.probe_once()
    assert rs.state("r1") == fleet.OPEN
    # cooldown not elapsed: stays open, probes skip it
    clock.advance(1.0)
    rs.probe_once()
    assert rs.state("r1") == fleet.OPEN
    # cooldown elapsed + still dead: half-open trial fails, re-opens
    clock.advance(1.5)
    rs.probe_once()
    assert rs.state("r1") == fleet.OPEN
    transitions = [(frm, to) for _, r, frm, to, _ in rs.state_changes
                   if r == "r1"]
    assert ("open", "half_open") in transitions
    assert ("half_open", "open") in transitions
    # replica restarts: next half-open trial re-admits
    tr.restore("r1")
    clock.advance(2.5)
    rs.probe_once()
    assert rs.state("r1") == fleet.CLOSED
    assert rs.registry.counter(
        "svgd_fleet_readmissions_total").value() == 1


def test_probe_tenant_paths():
    """/healthz/<tenant> probing: a replica missing a probed tenant fails
    its sweep."""
    clock = ManualClock()
    reps = {"a": fleet.LoopbackReplica("a", tenants=("t0",)),
            "b": fleet.LoopbackReplica("b", tenants=("t0", "t1"))}
    rs = fleet.ReplicaSet(list(reps), fleet.FakeTransport(reps),
                          fail_threshold=1, probe_tenants=("t1",),
                          clock=clock, registry=MetricsRegistry())
    rs.probe_once()
    assert rs.state("a") == fleet.OPEN  # 404 on /healthz/t1
    assert rs.state("b") == fleet.CLOSED


# --------------------------------------------------------------------- #
# circuit breaker: passive scoring


def test_passive_failures_eject_without_probes():
    router, reps, tr, clock, _ = make_fleet(passive_fail_threshold=2,
                                            max_retries=2)
    rs = router.replica_set
    tenant = next(t for t in (f"t{i}" for i in range(50))
                  if router.order_for(t)[0] == "r0")
    tr.kill("r0")
    res = router.route(tenant, _body(tenant))
    assert res.status == 200 and res.replica != "r0"
    res = router.route(tenant, _body(tenant))
    assert res.status == 200
    # two passive connect failures opened the circuit — no probe ran
    assert rs.state("r0") == fleet.OPEN
    assert "request_failures" in list(rs.state_changes)[-1][4]


def test_shed_is_not_failure():
    """429s release the in-flight slot but never advance failure counters
    or open the circuit."""
    clock = ManualClock()
    reg = MetricsRegistry()
    reps = {"a": fleet.LoopbackReplica("a")}
    rs = fleet.ReplicaSet(["a"], fleet.FakeTransport(reps),
                          passive_fail_threshold=1, clock=clock,
                          registry=reg)
    for _ in range(5):
        assert rs.begin_request("a")
        rs.record_shed("a", retry_after_s=3.0)
    assert rs.state("a") == fleet.CLOSED
    assert rs.backpressured("a")
    clock.advance(4.0)
    assert not rs.backpressured("a")


# --------------------------------------------------------------------- #
# router: retries, failover, deadline, 429, hedging, 503


def test_retry_absorbs_connect_error_and_fails_over():
    router, reps, tr, clock, _ = make_fleet()
    tenant = "t-failover"
    home = router.order_for(tenant)[0]
    tr.kill(home)
    res = router.route(tenant, _body(tenant))
    assert res.status == 200
    assert res.replica == router.order_for(tenant)[1]
    assert res.attempts == 2
    reg = router.registry
    assert reg.counter("svgd_fleet_retries_total").value(reason="connect") >= 1
    assert reg.counter("svgd_fleet_failovers_total").value(tenant=tenant) == 1


def test_5xx_retries_to_next_replica():
    calls = []

    def predict(inputs, tenant, headers):
        calls.append(tenant)
        if len(calls) == 1:
            raise RuntimeError("boom")  # -> 500 on the first replica
        return {"mean": [0.0] * len(inputs)}

    router, reps, tr, clock, sleeps = make_fleet(predict=predict)
    res = router.route("t0", _body("t0"))
    assert res.status == 200 and res.attempts == 2
    assert router.registry.counter(
        "svgd_fleet_retries_total").value(reason="5xx") == 1
    # the crashing handler tripped exactly one flight recorder
    assert sum(r.flight_trips for r in reps.values()) == 1


def test_429_never_retried_and_retry_after_passes_through():
    home_holder = {}

    def predict(inputs, tenant, headers):
        raise fleet.Shed("queue full", retry_after_s=7.0)

    router, reps, tr, clock, sleeps = make_fleet(predict=predict)
    home_holder["home"] = router.order_for("t0")[0]
    res = router.route("t0", _body("t0"))
    assert res.status == 429
    assert res.attempts == 1          # a shed burns NO retries
    assert res.headers["Retry-After"] == "7"
    assert res.json()["retry_after_s"] == 7.0
    assert sleeps == []               # and no generic backoff sleep either
    assert res.outcome == "shed"


def test_backpressure_steers_next_requests_away():
    """After a 429, the shedding replica is deprioritized until its own
    Retry-After window passes — the router honors the replica's number
    instead of its generic backoff."""

    def predict(inputs, tenant, headers):
        raise fleet.Shed("busy", retry_after_s=5.0)

    router, reps, tr, clock, _ = make_fleet()
    tenant = "t-bp"
    home = router.order_for(tenant)[0]
    reps[home]._predict = predict  # only the home sheds
    res = router.route(tenant, _body(tenant))
    assert res.status == 429 and res.replica == home
    # within the window: the very next request prefers another replica
    res2 = router.route(tenant, _body(tenant))
    assert res2.status == 200 and res2.replica != home
    # after the window: the tenant returns home
    clock.advance(6.0)
    res3 = router.route(tenant, _body(tenant))
    assert res3.replica == home


def test_retry_after_on_503_overrides_generic_backoff():
    """A retryable 5xx carrying Retry-After sets the inter-attempt sleep
    (clamped to the deadline) instead of the exponential schedule."""

    class Hinting503:
        def handle(self, method, path, body, headers):
            if path == "/predict":
                return fleet.Reply(503, {"Retry-After": "0.07"},
                                   b'{"error": "warming up"}')
            return fleet.Reply(200, {}, b'{"status": "ok"}')

    reg = MetricsRegistry()
    clock = ManualClock()
    sleeps = []

    def fake_sleep(s):
        sleeps.append(s)
        clock.advance(s)

    reps = {"a": Hinting503()}
    tr = fleet.FakeTransport(reps, advance=clock.advance)
    rs = fleet.ReplicaSet(["a"], tr, clock=clock, registry=reg)
    router = fleet.FleetRouter(
        ["a"], transport=tr, replica_set=rs, max_retries=2,
        backoff=Backoff(base_s=1.0, factor=2.0, max_s=10.0, jitter_frac=0.0),
        clock=clock, sleep=fake_sleep, registry=reg)
    res = router.route("t0", _body("t0"))
    assert res.status == 503
    assert sleeps and all(s == pytest.approx(0.07) for s in sleeps)


def test_deadline_propagated_downstream_and_504_on_expiry():
    router, reps, tr, clock, sleeps = make_fleet(
        n=1, per_try_timeout_s=1.0, default_deadline_s=1.5)
    # healthy request: the replica sees the remaining budget + attempt id
    res = router.route("t0", _body("t0"), deadline_s=0.8)
    assert res.status == 200
    hdrs = reps["r0"].last_headers
    assert float(hdrs["x-fleet-deadline-s"]) <= 0.8
    assert hdrs["x-fleet-attempt"] == "0"
    # hang the only replica: each attempt burns its timeout on the fake
    # clock until the deadline is gone -> 504, never a hung client
    tr.hang("r0")
    res = router.route("t0", _body("t0"))
    assert res.status == 504
    assert res.outcome == "deadline"
    assert router.registry.counter(
        "svgd_fleet_retries_total").value(reason="timeout") >= 1


def test_downstream_504_is_deadline_not_replica_failure():
    """A replica answering 504 (OUR propagated deadline ran out inside
    its future-wait) is alive: the router passes the answer through
    without burning retries and without scoring a failure that could
    eject a healthy replica under short-deadline traffic."""

    class Deadline504:
        def handle(self, method, path, body, headers):
            if path == "/predict":
                return fleet.Reply(504, {}, b'{"error": "deadline"}')
            return fleet.Reply(200, {}, b'{"status": "ok"}')

    clock = ManualClock()
    reg = MetricsRegistry()
    reps = {"a": Deadline504(), "b": Deadline504()}
    tr = fleet.FakeTransport(reps, advance=clock.advance)
    rs = fleet.ReplicaSet(list(reps), tr, passive_fail_threshold=1,
                          clock=clock, registry=reg)
    router = fleet.FleetRouter(list(reps), transport=tr, replica_set=rs,
                               clock=clock, sleep=clock.advance,
                               registry=reg)
    res = router.route("t0", _body("t0"))
    assert res.status == 504 and res.outcome == "deadline"
    assert res.attempts == 1                      # no retries burned
    assert rs.state(res.replica) == fleet.CLOSED  # no failure scored
    assert reg.counter("svgd_fleet_retries_total").value(reason="5xx") == 0


def test_scheduled_fleet_faults_drive_transport():
    """The resilience/faults.py schedule flavor: ordinal-keyed windows."""
    clock = ManualClock()
    reps = {"a": fleet.LoopbackReplica("a")}
    tr = fleet.FakeTransport(
        reps, faults=[ReplicaKillAt(2, "a", until=4),
                      SlowReplicaAt(5, "a", seconds=0.5)],
        advance=clock.advance)
    assert tr.request("a", "GET", "/healthz").status == 200  # ordinal 1
    with pytest.raises(fleet.ConnectError):
        tr.request("a", "GET", "/healthz")                   # 2: killed
    with pytest.raises(fleet.ConnectError):
        tr.request("a", "GET", "/healthz")                   # 3: killed
    assert tr.request("a", "GET", "/healthz").status == 200  # 4: restarted
    t0 = clock.t
    assert tr.request("a", "GET", "/healthz").status == 200  # 5: slow
    assert clock.t - t0 == pytest.approx(0.5)
    with pytest.raises(fleet.RequestTimeout):
        fleet.FakeTransport(
            reps, faults=[ReplicaHangAt(1, "a")], advance=clock.advance
        ).request("a", "GET", "/healthz", timeout_s=2.0)


def test_partition_is_not_a_crash():
    """Acceptance: PartitionAt trips the SAME ejection path as a kill
    while the replica itself stays alive, serving, and flight-clean."""
    router, reps, tr, clock, _ = make_fleet(fail_threshold=2)
    rs = router.replica_set
    tr.partition("r1")
    rs.probe_once()
    rs.probe_once()
    assert rs.state("r1") == fleet.OPEN  # ejected like a crash
    rep = reps["r1"]
    # ...but the process is untouched: direct (non-router) access works
    direct = rep.handle("GET", "/healthz", None, {})
    assert direct.status == 200
    assert rep.handle("POST", "/predict", _body("t0"), {}).status == 200
    assert rep.flight_trips == 0  # no postmortem, no crash record
    # healing the partition re-admits through half-open like any recovery
    tr.restore("r1")
    clock.advance(rs.open_cooldown_s + 0.1)
    rs.probe_once()
    assert rs.state("r1") == fleet.CLOSED


def test_all_replicas_out_degrades_gracefully():
    router, reps, tr, clock, sleeps = make_fleet(fail_threshold=1)
    rs = router.replica_set
    for r in reps:
        tr.kill(r)
    rs.probe_once()
    for r in reps:
        assert rs.state(r) == fleet.OPEN
    res = router.route("t0", _body("t0"))
    assert res.status == 503
    assert res.outcome == "unroutable"
    assert int(res.headers["Retry-After"]) >= 1
    doc = res.json()
    assert doc["last_known_healthy"] is None or \
        doc["last_known_healthy"]["replica"] in reps
    assert doc["retry_after_s"] > 0
    assert router.registry.counter(
        "svgd_fleet_requests_total").value(outcome="unroutable") == 1


def test_last_known_healthy_hint_carries_recency():
    router, reps, tr, clock, _ = make_fleet(fail_threshold=1)
    rs = router.replica_set
    rs.probe_once()          # everyone sighted healthy at t=0
    clock.advance(10.0)
    for r in reps:
        tr.kill(r)
    rs.probe_once()
    res = router.route("t0", _body("t0"))
    hint = res.json()["last_known_healthy"]
    assert hint["replica"] in reps
    assert hint["age_s"] == pytest.approx(10.0, abs=0.5)


def test_hedging_wins_over_slow_primary():
    """Tail hedging: a slow (not failed) primary is raced by a second
    replica after the hedge delay; first reply wins.  Real (small) waits —
    hedging is genuinely concurrent."""
    release = threading.Event()

    def predict(inputs, tenant, headers):
        return {"mean": [0.0] * len(inputs)}

    reg = MetricsRegistry()
    reps = {f"r{i}": fleet.LoopbackReplica(f"r{i}") for i in range(2)}
    tenant = "t-hedge"

    def slow_predict(inputs, tenant_, headers):
        release.wait(timeout=5.0)
        return {"mean": [9.9] * len(inputs)}

    tr = fleet.FakeTransport(reps)
    rs = fleet.ReplicaSet(list(reps), tr, registry=reg)
    router = fleet.FleetRouter(
        list(reps), transport=tr, replica_set=rs, registry=reg,
        hedge=True, hedge_delay_s=0.02, per_try_timeout_s=5.0)
    home, backup = router.order_for(tenant)
    reps[home]._predict = slow_predict
    reps[backup]._predict = predict
    try:
        res = router.route(tenant, _body(tenant))
        assert res.status == 200
        assert res.replica == backup
        assert res.hedged
        assert reg.counter("svgd_fleet_hedges_total").value() == 1
    finally:
        release.set()
        router.shutdown()


def test_misroutes_stay_zero_and_state_gauge_tracks():
    router, reps, tr, clock, _ = make_fleet(fail_threshold=1)
    rs = router.replica_set
    reg = router.registry
    gauge = reg.gauge("svgd_fleet_replica_state")
    assert gauge.value(replica="r0") == 0
    tr.kill("r0")
    rs.probe_once()
    assert gauge.value(replica="r0") == 2  # open
    clock.advance(rs.open_cooldown_s + 0.1)
    assert rs.state("r0") == fleet.HALF_OPEN
    assert gauge.value(replica="r0") == 1
    for _ in range(10):
        router.route("t0", _body("t0"))
    assert reg.counter("svgd_fleet_misroutes_total").value() == 0


def test_route_lane_tree_emitted():
    from dist_svgd_tpu.telemetry import trace as trace_mod

    router, reps, tr, clock, _ = make_fleet()
    tenant = "t-trace"
    tr.kill(router.order_for(tenant)[0])  # force one retry into the tree
    tracer = trace_mod.enable()
    try:
        res = router.route(tenant, _body(tenant))
        assert res.status == 200
        events = [e for e in tracer.chrome_events() if e.get("ph") == "X"]
        names = [e["name"] for e in events]
    finally:
        trace_mod.disable()
    assert "fleet.route" in names
    assert names.count("fleet.attempt") == 2  # failed + served
    assert "fleet.forward" in names
    # one trace id tags the route AND both sibling attempts — the join
    # key the cross-process stitcher reassembles trees on
    route = [e for e in events if e["name"] == "fleet.route"][0]
    trace_id = route["args"]["trace"]
    assert trace_id and len(trace_id) == 16
    attempts = [e for e in events if e["name"] == "fleet.attempt"]
    assert all(a["args"]["trace"] == trace_id for a in attempts)


def test_trace_header_propagated_downstream():
    """Every forward attempt sends X-Fleet-Trace; a caller-supplied id is
    passed through untouched, an absent one is minted per request."""
    router, reps, tr, clock, _ = make_fleet()
    tenant = "t-hdr"
    home = router.order_for(tenant)[0]
    res = router.route(tenant, _body(tenant), trace="feed0000deadbeef")
    assert res.status == 200
    assert reps[home].last_headers["x-fleet-trace"] == "feed0000deadbeef"
    res = router.route(tenant, _body(tenant))
    minted = reps[home].last_headers["x-fleet-trace"]
    assert len(minted) == 16 and minted != "feed0000deadbeef"
    res = router.route(tenant, _body(tenant))
    assert reps[home].last_headers["x-fleet-trace"] != minted  # per request


def test_router_http_front_door_passes_trace_header():
    import urllib.request

    replicas = {f"r{i}": fleet.LoopbackReplica(f"r{i}") for i in range(2)}
    transport = fleet.FakeTransport(replicas)
    router = fleet.FleetRouter(
        list(replicas), transport=transport, registry=MetricsRegistry(),
        probe_interval_s=5.0, port=0).start()
    try:
        req = urllib.request.Request(
            router.url + "/predict", _body("t0"),
            {"Content-Type": "application/json",
             "X-Fleet-Trace": "0123456789abcdef"})
        doc = json.loads(urllib.request.urlopen(req, timeout=5).read())
        served_by = doc["replica"]
        assert replicas[served_by].last_headers["x-fleet-trace"] == \
            "0123456789abcdef"
    finally:
        router.shutdown()


# --------------------------------------------------------------------- #
# acceptance: rolling kill under load, detection + readmission budgets


def test_acceptance_kill_one_replica_loses_nothing():
    """ISSUE-11 acceptance, tier-1 flavor: 3 replicas under steady load,
    kill one — zero non-shed requests lost (retries absorb), detection
    within 2 probe sweeps, and the killed replica re-admitted through
    half-open after restart."""
    router, reps, tr, clock, _ = make_fleet(
        fail_threshold=2, passive_fail_threshold=3, open_cooldown_s=1.0)
    rs = router.replica_set
    tenants = [f"t{i}" for i in range(12)]
    statuses = []

    def burst():
        for t in tenants:
            statuses.append(router.route(t, _body(t)).status)

    burst()                       # steady state
    victim = router.order_for(tenants[0])[0]
    t_kill = clock.t
    tr.kill(victim)
    burst()                       # in-flight loss window: retries absorb
    clock.advance(0.05)
    rs.probe_once()               # detection within <= 2 sweeps
    clock.advance(0.05)
    rs.probe_once()
    assert rs.state(victim) == fleet.OPEN
    ts_open = next(ts for ts, r, _f, to, _why in rs.state_changes
                   if r == victim and to == "open")
    assert ts_open - t_kill <= 2 * 0.05 + 1e-9
    burst()                       # degraded but fully served
    # restart + half-open readmission
    tr.restore(victim)
    clock.advance(rs.open_cooldown_s + 0.01)
    rs.probe_once()
    assert rs.state(victim) == fleet.CLOSED
    burst()                       # the tenant's home serves again
    assert statuses and all(s == 200 for s in statuses)
    home_again = router.route(tenants[0], _body(tenants[0]))
    assert home_again.replica == victim


# --------------------------------------------------------------------- #
# HTTP front door (real sockets, fake backend)


def _get(url, path):
    with urllib.request.urlopen(url + path, timeout=10) as r:
        return json.loads(r.read()), r.status, dict(r.headers)


def test_router_http_front_door():
    reg = MetricsRegistry()
    reps = {f"r{i}": fleet.LoopbackReplica(f"r{i}") for i in range(3)}
    tr = fleet.FakeTransport(reps)
    rs = fleet.ReplicaSet(list(reps), tr, probe_interval_s=0.05,
                          registry=reg)
    with fleet.FleetRouter(list(reps), transport=tr, replica_set=rs,
                           registry=reg, port=0) as router:
        url = router.url
        req = urllib.request.Request(
            url + "/predict", _body("web-tenant"),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            doc = json.loads(r.read())
        assert r.status == 200 and "outputs" in doc
        health, code, _ = _get(url, "/healthz")
        assert code == 200
        assert health["replicas_closed"] == 3
        assert health["role"] == "fleet-router"
        stats, _, _ = _get(url, "/replicas")
        assert set(stats) == set(reps)
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "svgd_fleet_requests_total" in text
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url + "/nope", timeout=10)
        assert ei.value.code == 404


def test_router_http_failover_and_shed_passthrough():
    reg = MetricsRegistry()
    reps = {f"r{i}": fleet.LoopbackReplica(f"r{i}") for i in range(2)}
    tr = fleet.FakeTransport(reps)
    rs = fleet.ReplicaSet(list(reps), tr, registry=reg)
    with fleet.FleetRouter(
            list(reps), transport=tr, replica_set=rs, registry=reg,
            backoff=Backoff(base_s=0.001, max_s=0.002), port=0) as router:
        tenant = "shedder"
        home = router.order_for(tenant)[0]
        tr.kill(home)  # HTTP request rides the failover path
        req = urllib.request.Request(
            router.url + "/predict", _body(tenant),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        tr.restore(home)
        # now the home sheds: the 429 + Retry-After passes through HTTP
        reps[home]._predict = lambda i, t, h: (_ for _ in ()).throw(
            fleet.Shed("full", retry_after_s=3.0))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                router.url + "/predict", _body(tenant),
                {"Content-Type": "application/json"}), timeout=10)
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) == 3


# --------------------------------------------------------------------- #
# validation


def test_validation_errors():
    with pytest.raises(ValueError, match="at least one replica"):
        fleet.ReplicaSet([], fleet.FakeTransport({}),
                         registry=MetricsRegistry())
    with pytest.raises(ValueError, match="thresholds"):
        fleet.ReplicaSet(["a"], fleet.FakeTransport({}), fail_threshold=0,
                         registry=MetricsRegistry())
    with pytest.raises(ValueError, match="transport"):
        fleet.FleetRouter(["a"])
    with pytest.raises(ValueError, match="vnodes"):
        fleet._HashRing(["a"], vnodes=0)
    with pytest.raises(fleet.ConnectError, match="unknown replica"):
        fleet.FakeTransport({}).request("ghost", "GET", "/healthz")
