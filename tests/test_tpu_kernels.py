"""Real-hardware (Mosaic) pinning of the Pallas kernels — the `tpu` tier.

Every other Pallas test runs ``interpret=True`` on CPU, which checks the
kernel *logic* but not Mosaic lowering (tile padding, VMEM budgets, MXU
precision modes, bf16x3 splits).  This module runs the same
kernel-vs-XLA-path comparisons on the real chip, so a Mosaic-only
regression is a red test instead of a bench-reading exercise
(round-3 verdict item 3; SURVEY.md §4 test-pyramid mandate).

Run with ``DSVGD_TPU_TESTS=1 python -m pytest tests -m tpu`` on a TPU host
(see conftest.py — the default CPU-mesh run auto-skips these).  Tolerances
are relative to ``max|want|``: both sides are f32 programs whose reduction
orders differ, so elementwise rtol on near-zero entries is the wrong
yardstick; the documented error budgets are 4.4e-4 (exact-f32 φ floor at
the covertype shape), 1.4e-3 (bf16x3 big-d tier), ~3e-4 (small-d bf16 exp)
— docs/notes.md.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module", autouse=True)
def require_tpu():
    import jax

    try:
        ok = jax.default_backend() == "tpu"
    except Exception:  # backend init failure (pool unavailable)
        ok = False
    if not ok:
        pytest.skip("no TPU backend available")


@pytest.fixture
def rng():
    return np.random.default_rng(47)


def _close(got, want, rel, what=""):
    got, want = np.asarray(got), np.asarray(want)
    assert np.isfinite(got).all(), f"{what}: non-finite entries"
    err = np.abs(got - want).max()
    scale = np.abs(want).max()
    assert err <= rel * scale, (
        f"{what}: max|Δ| {err:.3e} > {rel:g} · max|want| {scale:.3e}"
    )


# --------------------------------------------------------------------- #
# φ kernel (ops/pallas_svgd.py) vs the XLA φ (ops/svgd.py)


@pytest.mark.parametrize(
    "k,m,d",
    [
        (1250, 10_000, 3),   # the north-star shard shape (small-d VPU drive)
        (300, 999, 3),       # ragged both axes → edge-tile padding + sentinel
        (130, 257, 7),       # multi-tile ragged at the SMALL_D boundary
        (1250, 10_000, 55),  # big-d variant (MXU distance + drive contractions)
        (200, 500, 200),     # big-d with d padded to 256 lanes
    ],
)
def test_phi_pallas_f32_matches_xla_on_mosaic(rng, k, m, d):
    import jax.numpy as jnp

    from dist_svgd_tpu.ops.kernels import RBF
    from dist_svgd_tpu.ops.pallas_svgd import phi_pallas
    from dist_svgd_tpu.ops.svgd import phi

    h = 1.0 if d <= 8 else float(2 * d)  # keep kernel values O(1) at big d
    y = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    s = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    want = phi(y, x, s, RBF(h))
    got = phi_pallas(y, x, s, bandwidth=h)
    _close(got, want, 1e-3, f"phi f32 ({k},{m},{d})")


@pytest.mark.parametrize("k,m,d", [(1250, 10_000, 3), (1250, 10_000, 55)])
def test_phi_pallas_bf16x3_within_budget_on_mosaic(rng, k, m, d):
    """The reduced-precision tier on real Mosaic: small-d = bf16 exp only
    (~3e-4 budget), big-d = 3-pass bf16x3 MXU splits (1.4e-3 measured;
    docs/notes.md).  2e-2 is the same acceptance multiple the interpreter
    tests use over those budgets."""
    import jax.numpy as jnp

    from dist_svgd_tpu.ops.kernels import RBF
    from dist_svgd_tpu.ops.pallas_svgd import phi_pallas
    from dist_svgd_tpu.ops.svgd import phi

    h = 1.0 if d <= 8 else float(2 * d)
    y = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    s = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    want = phi(y, x, s, RBF(h))
    got = phi_pallas(y, x, s, bandwidth=h, gram_dtype=jnp.bfloat16)
    _close(got, want, 2e-2, f"phi bf16 ({k},{m},{d})")


def test_phi_auto_dispatch_selects_pallas_on_mosaic(rng):
    """'auto' above the pair threshold returns the Pallas kernel's exact
    result (and hence also tracks the XLA path within the f32 budget)."""
    import jax.numpy as jnp

    from dist_svgd_tpu.ops.kernels import RBF
    from dist_svgd_tpu.ops.pallas_svgd import phi_pallas, resolve_phi_fn
    from dist_svgd_tpu.ops.svgd import phi

    k, m, d = 1250, 10_000, 3  # k·m = 1.25e7 ≥ PALLAS_MIN_PAIRS (2^22)
    y = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    s = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    auto = resolve_phi_fn(RBF(1.0), "auto")(y, x, s)
    np.testing.assert_array_equal(
        np.asarray(auto), np.asarray(phi_pallas(y, x, s, bandwidth=1.0))
    )
    _close(auto, phi(y, x, s, RBF(1.0)), 1e-3, "phi auto")


def test_phi_adaptive_bandwidth_pallas_on_mosaic(rng):
    """AdaptiveRBF's rescaling identity composes with the real kernel: the
    adaptive Pallas φ equals a fixed-RBF XLA φ at the resolved bandwidth."""
    import jax.numpy as jnp

    from dist_svgd_tpu.ops.kernels import RBF, AdaptiveRBF, median_bandwidth_approx
    from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn
    from dist_svgd_tpu.ops.svgd import phi

    k, m, d = 1250, 10_000, 3
    y = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    s = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    h = float(median_bandwidth_approx(x))
    want = phi(y, x, s, RBF(h))
    got = resolve_phi_fn(AdaptiveRBF(), "pallas")(y, x, s)
    _close(got, want, 1e-3, "phi adaptive pallas")


# --------------------------------------------------------------------- #
# Sinkhorn W2 kernels (ops/pallas_ot.py) vs the XLA solve (ops/ot.py)


@pytest.mark.parametrize("tol", [None, 1e-2])
def test_sinkhorn_fused_matches_xla_on_mosaic(rng, tol):
    import jax.numpy as jnp

    from dist_svgd_tpu.ops.ot import wasserstein_grad_sinkhorn
    from dist_svgd_tpu.ops.pallas_ot import sinkhorn_grad_fused

    m, n, d = 1250, 10_000, 3  # the north-star W2 shard shape
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    y = jnp.asarray(rng.normal(size=(n, d)), dtype=jnp.float32)
    want, want_g = wasserstein_grad_sinkhorn(
        x, y, eps=0.05, iters=100, tol=tol, return_g=True, impl="xla"
    )
    got, got_g = sinkhorn_grad_fused(
        x, y, eps=0.05, iters=100, tol=tol, return_g=True
    )
    _close(got, want, 5e-3, "fused grad")
    _close(got_g, want_g, 5e-3, "fused dual")


def test_sinkhorn_fused_warm_start_on_mosaic(rng):
    """Warm-start path (soft c-transform reductions) on real Mosaic: feeding
    the previous solve's dual must track the XLA warm solve."""
    import jax.numpy as jnp

    from dist_svgd_tpu.ops.ot import wasserstein_grad_sinkhorn
    from dist_svgd_tpu.ops.pallas_ot import sinkhorn_grad_fused

    m, n, d = 1250, 10_000, 3
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    y = jnp.asarray(rng.normal(size=(n, d)), dtype=jnp.float32)
    _, g0 = wasserstein_grad_sinkhorn(
        x, y, eps=0.05, iters=50, tol=1e-2, return_g=True, impl="xla"
    )
    x2 = x + jnp.asarray(0.01 * rng.normal(size=(m, d)), dtype=jnp.float32)
    want, want_g = wasserstein_grad_sinkhorn(
        x2, y, eps=0.05, iters=100, tol=1e-2, g_init=g0, return_g=True,
        impl="xla",
    )
    got, got_g = sinkhorn_grad_fused(
        x2, y, eps=0.05, iters=100, tol=1e-2, g_init=g0, return_g=True
    )
    _close(got, want, 5e-3, "fused warm grad")
    _close(got_g, want_g, 5e-3, "fused warm dual")


@pytest.mark.parametrize("warm", [False, True])
def test_sinkhorn_streaming_matches_xla_on_mosaic(rng, warm):
    """The O(n·d) streaming solve on real Mosaic (kmat_vec + plan_grad),
    cold and warm-started."""
    import jax.numpy as jnp

    from dist_svgd_tpu.ops.ot import wasserstein_grad_sinkhorn
    from dist_svgd_tpu.ops.pallas_ot import sinkhorn_grad_streaming

    m, n, d = 1250, 10_000, 3
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    y = jnp.asarray(rng.normal(size=(n, d)), dtype=jnp.float32)
    g0 = None
    if warm:
        _, g0 = wasserstein_grad_sinkhorn(
            x, y, eps=0.05, iters=50, tol=1e-2, return_g=True, impl="xla"
        )
    want, want_g = wasserstein_grad_sinkhorn(
        x, y, eps=0.05, iters=100, tol=1e-2, g_init=g0, return_g=True,
        impl="xla", absorb_every=1,  # the streaming tol-exit granularity
    )
    got, got_g = sinkhorn_grad_streaming(
        x, y, eps=0.05, iters=100, tol=1e-2, g_init=g0, return_g=True
    )
    _close(got, want, 5e-3, "streaming grad")
    _close(got_g, want_g, 5e-3, "streaming dual")


def test_sinkhorn_auto_dispatch_selects_fused_on_mosaic(rng):
    """impl='auto' at ≥FUSED_SINKHORN_MIN_PAIRS f32 small-d sizes routes to
    the fused Pallas solve on TPU — its result must be exactly the forced
    fused path's."""
    import jax.numpy as jnp

    from dist_svgd_tpu.ops.ot import wasserstein_grad_sinkhorn
    from dist_svgd_tpu.ops.pallas_ot import sinkhorn_grad_fused

    m, n, d = 1250, 10_000, 3  # 1.25e7 pairs ≥ 2^20
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    y = jnp.asarray(rng.normal(size=(n, d)), dtype=jnp.float32)
    auto = wasserstein_grad_sinkhorn(x, y, eps=0.05, iters=60, tol=1e-2)
    forced = sinkhorn_grad_fused(x, y, eps=0.05, iters=60, tol=1e-2)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(forced))


# --------------------------------------------------------------------- #
# End-to-end: the sharded step on the real chip (vmap emulation), pallas
# vs xla φ — the program bench.py times


def test_sharded_step_pallas_vs_xla_on_mosaic(rng):
    import jax.numpy as jnp

    from dist_svgd_tpu import DistSampler
    from dist_svgd_tpu.models.gmm import gmm_logp

    n, d = 4096, 2
    init = jnp.asarray(rng.normal(size=(n, d)), dtype=jnp.float32)
    logp = lambda th, _: gmm_logp(th)

    def run(impl):
        ds = DistSampler(
            8, logp, None, init,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=False, phi_impl=impl,
        )
        return np.asarray(ds.run_steps(3, 0.05))

    _close(run("pallas"), run("xla"), 1e-3, "sharded step")
