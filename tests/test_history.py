"""history_to_dataframe (utils/history.py): reference DataFrame schema, and
the reshape-based value column matching the old per-(t, i) indexing exactly.
"""

import numpy as np
import pytest

from dist_svgd_tpu.utils.history import history_to_dataframe


@pytest.fixture
def history():
    return np.random.default_rng(3).normal(size=(4, 5, 2))


def test_schema_and_values(history):
    df = history_to_dataframe(history)
    T, n, d = history.shape
    assert list(df.columns) == ["timestep", "particle", "value"]
    assert len(df) == T * n
    # the reference layout: row (t * n + i) carries history[t, i]
    for t in range(T):
        for i in range(n):
            row = df.iloc[t * n + i]
            assert row["timestep"] == t and row["particle"] == i
            np.testing.assert_array_equal(row["value"], history[t, i])
    assert df["value"].iloc[0].shape == (d,)


def test_custom_ids_and_no_particle_column(history):
    df = history_to_dataframe(
        history, timesteps=[10, 11, 12, 13], particle_ids=[7, 8, 9, 10, 11]
    )
    assert df["timestep"].iloc[0] == 10 and df["particle"].iloc[-1] == 11
    df2 = history_to_dataframe(history, include_particle_column=False)
    assert list(df2.columns) == ["timestep", "value"]
