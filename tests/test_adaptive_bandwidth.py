"""Per-step adaptive median bandwidth (``kernel='median_step'``).

Covers the sort-free estimator (``median_bandwidth_approx``), the rescaling
identity that lets every bandwidth-1 φ backend serve a traced bandwidth
(``resolve_phi_fn`` + ``AdaptiveRBF``), and sampler integration — an
extension beyond the reference's fixed ``h=1`` (SURVEY.md §0) and the
per-run ``kernel='median'`` resolution.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dist_svgd_tpu import DistSampler, Sampler
from dist_svgd_tpu.models.gmm import gmm_logp
from dist_svgd_tpu.ops.kernels import (
    RBF,
    AdaptiveRBF,
    median_bandwidth,
    median_bandwidth_approx,
)
from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn
from dist_svgd_tpu.ops.svgd import phi, svgd_step


@pytest.fixture
def rng():
    return np.random.default_rng(23)


@pytest.mark.parametrize("n,d", [(40, 2), (300, 5), (120, 55)])
def test_median_bandwidth_approx_matches_exact(rng, n, d):
    """The four-pass counting bracket lands within its probes⁻⁴ resolution
    of the lower middle order statistic of the pairwise distances (the
    documented target — no even-count interpolation)."""
    import math

    x = jnp.asarray(rng.normal(size=(n, d)))
    xs = np.asarray(x)
    sq = np.sort(((xs[:, None, :] - xs[None, :, :]) ** 2).sum(-1).ravel())
    m = n * n - n
    lower_median = sq[n + (m - 1) // 2]  # skip the n diagonal zeros
    want = lower_median / math.log(n + 1.0)
    approx = float(median_bandwidth_approx(x, max_points=n))
    assert approx == pytest.approx(want, rel=1e-3)
    # and it tracks the interpolating exact median to O(1/p²)
    exact = float(median_bandwidth(x, max_points=n))
    assert approx == pytest.approx(exact, rel=2e-2)


def test_median_bandwidth_approx_subsamples_and_jits(rng):
    x = jnp.asarray(rng.normal(size=(600, 3)))
    full = float(median_bandwidth_approx(x, max_points=600))
    sub = float(jax.jit(lambda p: median_bandwidth_approx(p, max_points=128))(x))
    assert sub == pytest.approx(full, rel=0.15)  # iid subsample estimate


def test_median_bandwidth_approx_degenerate_floor():
    """All-identical particles: the 1e-12 floor keeps h positive (the exact
    median would be 0 → a division blow-up downstream)."""
    x = jnp.ones((8, 3))
    assert float(median_bandwidth_approx(x)) > 0.0


def test_adaptive_rbf_validation():
    with pytest.raises(ValueError, match="max_points"):
        AdaptiveRBF(max_points=0)


def test_adaptive_phi_equals_fixed_rbf_at_resolved_bandwidth(rng):
    """The rescaling identity φ_h(y;x,s) = φ₁(y/√h; x/√h, √h·s)/√h is exact:
    the adaptive path must reproduce a fixed-RBF φ evaluated at the same
    bandwidth value."""
    y = jnp.asarray(rng.normal(size=(12, 3)))
    x = jnp.asarray(rng.normal(size=(20, 3)))
    s = jnp.asarray(rng.normal(size=(20, 3)))
    h = float(median_bandwidth_approx(x))
    want = np.asarray(phi(y, x, s, RBF(h)))
    got = np.asarray(resolve_phi_fn(AdaptiveRBF(), "xla")(y, x, s))
    np.testing.assert_allclose(got, want, rtol=1e-10)


def test_adaptive_phi_pallas_matches_xla(rng):
    """AdaptiveRBF composes with the Pallas backend (interpreter on CPU)."""
    y = jnp.asarray(rng.normal(size=(10, 3)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(17, 3)), dtype=jnp.float32)
    s = jnp.asarray(rng.normal(size=(17, 3)), dtype=jnp.float32)
    want = np.asarray(resolve_phi_fn(AdaptiveRBF(), "xla")(y, x, s))
    got = np.asarray(resolve_phi_fn(AdaptiveRBF(), "pallas")(y, x, s))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_sampler_median_step_matches_manual_loop(rng):
    """kernel='median_step' re-resolves h from the *current* particles every
    step: the scanned trajectory equals a manual loop that recomputes the
    approx-median bandwidth and applies a fixed-RBF Jacobi step."""
    init = jnp.asarray(rng.normal(size=(24, 2)))
    sampler = Sampler(2, gmm_logp, kernel="median_step")
    got, _ = sampler.run(24, 5, 0.3, record=False, initial_particles=init)

    parts = init
    score = jax.vmap(jax.grad(gmm_logp))
    for _ in range(5):
        h = float(median_bandwidth_approx(parts))
        parts = svgd_step(parts, score(parts), 0.3, RBF(h))
    np.testing.assert_allclose(np.asarray(got), np.asarray(parts), rtol=1e-8)

    # and the bandwidth actually moved away from both 1.0 and the initial
    # resolution at some point — i.e. per-step adaptivity is observable
    fixed = Sampler(2, gmm_logp, kernel="median")
    ref, _ = fixed.run(24, 5, 0.3, record=False, initial_particles=init)
    assert not np.allclose(np.asarray(got), np.asarray(ref))


@pytest.mark.parametrize(
    "exch_p,exch_s", [(True, True), (True, False), (False, False)]
)
def test_distsampler_median_step_runs_all_modes(rng, exch_p, exch_s):
    """median_step works in every gather-mode exchange strategy, and in the
    ``all_*`` modes (interaction set = gathered global set, identical per
    shard) S=4 equals the single-device adaptive sampler."""
    init = jnp.asarray(rng.normal(size=(16, 2)))
    logp = lambda th, _=None: gmm_logp(th)
    ds = DistSampler(
        4, logp, "median_step", init,
        exchange_particles=exch_p, exchange_scores=exch_s,
        include_wasserstein=False,
    )
    stepped = np.asarray(ds.make_step(0.2))
    assert np.all(np.isfinite(stepped))
    if exch_p and not exch_s:
        # all_particles with data-free logp: every shard scores the gathered
        # global set identically, so S=4 equals the single-device adaptive
        # sampler.  (all_scores' psum deliberately sums the full score S
        # times when there is no data to shard — reference semantics — so
        # no such equality holds there.)
        want, _ = Sampler(2, gmm_logp, kernel="median_step").run(
            16, 1, 0.2, record=False, initial_particles=init
        )
        np.testing.assert_allclose(stepped, np.asarray(want), rtol=1e-8)


def test_distsampler_median_step_scanned_matches_eager(rng):
    """run_steps (one lax.scan dispatch) and make_step produce the same
    adaptive-bandwidth trajectory."""
    init = jnp.asarray(rng.normal(size=(16, 2)))
    logp = lambda th, _=None: gmm_logp(th)

    def make():
        return DistSampler(
            4, logp, "median_step", init,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=False,
        )

    a, b = make(), make()
    a.run_steps(4, 0.2)
    for _ in range(4):
        b.make_step(0.2)
    np.testing.assert_allclose(
        np.asarray(a.particles), np.asarray(b.particles), rtol=1e-8
    )


def test_distsampler_median_step_composes_with_sinkhorn_w2(rng):
    """median_step + the carried-snapshot Sinkhorn W2 term run inside one
    scanned dispatch, and the scanned trajectory equals the eager one."""
    init = jnp.asarray(rng.normal(size=(16, 2)))
    logp = lambda th, _=None: gmm_logp(th)

    def make():
        return DistSampler(
            4, logp, "median_step", init,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=True, wasserstein_solver="sinkhorn",
            sinkhorn_iters=20,
        )

    a, b = make(), make()
    a.run_steps(3, 0.1, h=1.0)
    for _ in range(3):
        b.make_step(0.1, h=1.0)
    np.testing.assert_allclose(
        np.asarray(a.particles), np.asarray(b.particles), rtol=1e-6
    )


def test_median_step_rejected_outside_jacobi(rng):
    init = jnp.asarray(rng.normal(size=(16, 2)))
    logp = lambda th, _=None: gmm_logp(th)
    with pytest.raises(ValueError, match="median_step"):
        Sampler(2, gmm_logp, kernel="median_step", update_rule="gauss_seidel")
    with pytest.raises(ValueError, match="median_step"):
        DistSampler(
            4, logp, "median_step", init,
            include_wasserstein=False, update_rule="gauss_seidel",
        )
    # partitions mode ignores exchange_impl entirely (constructor docstring),
    # so ring + median_step is accepted there
    ds = DistSampler(
        4, logp, "median_step", init,
        exchange_particles=False, exchange_scores=False,
        include_wasserstein=False, exchange_impl="ring",
    )
    assert np.all(np.isfinite(np.asarray(ds.make_step(0.2))))


@pytest.mark.parametrize("exch_s", [False, True])
@pytest.mark.parametrize("n", [16, 24])
def test_median_step_ring_matches_gather(rng, exch_s, n):
    """Ring + median_step resolves the bandwidth from the gather path's
    exact strided subsample (``_ring_median_bandwidth``), so the ring
    trajectory equals the gather one in both ``all_*`` modes — including at
    n=24, where the 4 shards' subsample slices are ragged and the masked
    estimator's padding is exercised (max_points=5 forces stride 5 against
    s=6 blocks)."""
    init = jnp.asarray(rng.normal(size=(n, 2)))
    logp = lambda th, _=None: gmm_logp(th)
    from dist_svgd_tpu.ops.kernels import AdaptiveRBF

    kern = AdaptiveRBF(max_points=5)  # force subsampling at tiny n
    # legacy jax: ring + median_step on a shard_map mesh is refused (XLA
    # sharding-propagation crash — parallel/mesh.py:SHARD_MAP_LEGACY); the
    # vmap emulation runs the identical per-shard code, so the ring ≡ gather
    # property is still exercised there
    from dist_svgd_tpu.parallel.mesh import SHARD_MAP_LEGACY

    mesh = None if SHARD_MAP_LEGACY else "auto"

    def make(impl):
        return DistSampler(
            4, logp, kern, init,
            exchange_particles=True, exchange_scores=exch_s,
            include_wasserstein=False, exchange_impl=impl, mesh=mesh,
        )

    g, r = make("gather"), make("ring")
    g.run_steps(4, 0.2)
    r.run_steps(4, 0.2)
    np.testing.assert_allclose(
        np.asarray(r.particles), np.asarray(g.particles), rtol=1e-8
    )


def test_masked_median_matches_compacted(rng):
    """The masked estimator on a padded point set equals the plain estimator
    on the compacted valid rows (same thresholds, ranks, distances)."""
    from dist_svgd_tpu.ops.kernels import (
        median_bandwidth_approx,
        median_bandwidth_approx_masked,
    )

    pts = jnp.asarray(rng.normal(size=(20, 3)))
    valid = jnp.asarray([True] * 13 + [False] * 7)
    # garbage in the padded rows must not leak into the estimate
    pts = pts.at[13:].set(1e6)
    want = float(median_bandwidth_approx(pts[:13], max_points=13))
    # full_n = 13 so the log(n+1) normaliser matches the compacted call
    got = float(median_bandwidth_approx_masked(pts, valid, 13, 13))
    assert got == pytest.approx(want, rel=1e-12)
