"""Checkpoint/resume (utils/checkpoint.py; SURVEY.md §5): state roundtrips,
manager cadence/retention, and exact-trajectory resume of DistSampler."""

import numpy as np
import jax.numpy as jnp
import pytest

from dist_svgd_tpu import DistSampler
from dist_svgd_tpu.models.logreg import make_logreg_split
from dist_svgd_tpu.utils.checkpoint import (
    CheckpointManager,
    load_state,
    save_state,
)


@pytest.fixture
def rng():
    return np.random.default_rng(31)


def test_save_load_roundtrip(tmp_path, rng):
    state = {
        "particles": rng.normal(size=(6, 3)),
        "previous": rng.normal(size=(2, 6, 3)).astype(np.float32),
        "t": np.asarray(7, dtype=np.int64),
        "none_field": None,  # elided
    }
    path = save_state(str(tmp_path / "ckpt"), state)
    out = load_state(path)
    assert set(out) == {"particles", "previous", "t"}
    np.testing.assert_array_equal(out["particles"], state["particles"])
    np.testing.assert_array_equal(out["previous"], state["previous"])
    assert int(out["t"]) == 7
    assert out["previous"].dtype == np.float32


def test_save_overwrites(tmp_path):
    p = str(tmp_path / "c")
    save_state(p, {"a": np.ones(2)})
    save_state(p, {"b": np.zeros(3)})
    out = load_state(p)
    assert set(out) == {"b"}


def test_manager_cadence_retention_latest(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"), every=5, max_to_keep=2)
    assert not mgr.should_save(0)
    assert not mgr.should_save(3)
    assert mgr.should_save(5)
    assert mgr.latest_step() is None
    assert mgr.restore_latest() is None
    for step in (5, 10, 15):
        mgr.save(step, {"x": np.full(1, step)})
    assert mgr.latest_step() == 15
    # retention: only the newest two step dirs remain
    import os

    kept = sorted(d for d in os.listdir(mgr.root) if d.startswith("step_"))
    assert kept == ["step_10", "step_15"]
    assert float(mgr.restore_latest()["x"][0]) == 15


def test_restore_latest_skips_corrupt_checkpoint(tmp_path):
    """A partial/corrupt newest checkpoint is skipped with a warning and the
    next-oldest intact one is restored (crash-during-save recovery)."""
    import os

    mgr = CheckpointManager(str(tmp_path / "root"), every=1, max_to_keep=5)
    mgr.save(1, {"x": np.full(1, 1.0)})
    mgr.save(2, {"x": np.full(1, 2.0)})
    # simulate a pre-rename-era partial write: empty step dir
    os.makedirs(os.path.join(mgr.root, "step_3"))
    with pytest.warns(UserWarning, match="skipping unloadable checkpoint"):
        out = mgr.restore_latest()
    assert float(out["x"][0]) == 2.0


def test_restore_latest_skips_corrupt_even_without_orbax(tmp_path, monkeypatch):
    """The serving cold-start dependency: an empty (partial-write) newest
    step dir must be classified as corruption BEFORE the orbax fallback
    import, so the manager falls back to the next-newest restorable step
    even in an orbax-less environment (previously: ImportError, fatal)."""
    import builtins
    import os

    real_import = builtins.__import__

    def no_orbax(name, *a, **k):
        if name.startswith("orbax"):
            raise ImportError("test: no orbax")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_orbax)
    mgr = CheckpointManager(str(tmp_path / "root"), every=1, max_to_keep=5)
    mgr.save(1, {"x": np.full(1, 1.0)})  # npz layout (orbax "absent")
    os.makedirs(os.path.join(mgr.root, "step_2"))  # killed mid-save
    with pytest.warns(UserWarning, match="skipping unloadable checkpoint"):
        out = mgr.restore_latest()
    assert float(out["x"][0]) == 1.0


def test_restore_latest_skips_truncated_npz(tmp_path):
    """A truncated state.npz (crash mid-write of a pre-rename-era writer)
    is skipped the same way."""
    import os

    mgr = CheckpointManager(str(tmp_path / "root"), every=1, max_to_keep=5)
    mgr.save(1, {"x": np.full(1, 1.0)})
    bad = os.path.join(mgr.root, "step_2")
    os.makedirs(bad)
    with open(os.path.join(bad, "state.npz"), "wb") as fh:
        fh.write(b"PK\x03\x04 truncated")
    with pytest.warns(UserWarning, match="skipping unloadable checkpoint"):
        out = mgr.restore_latest()
    assert float(out["x"][0]) == 1.0


def test_load_state_diagnoses_missing_and_empty(tmp_path):
    import os

    with pytest.raises(FileNotFoundError, match="no checkpoint directory"):
        load_state(str(tmp_path / "nowhere"))
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    with pytest.raises(ValueError, match="neither layout"):
        load_state(empty)


def test_restore_latest_skips_stray_files_without_orbax(tmp_path, monkeypatch):
    """A corrupt step dir with stray NON-orbax content (no state.npz, no
    orbax markers) is corruption, not an orbax checkpoint: classified before
    the orbax import, so the fallback works orbax-less here too."""
    import builtins
    import os

    real_import = builtins.__import__

    def no_orbax(name, *a, **k):
        if name.startswith("orbax"):
            raise ImportError("test: no orbax")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_orbax)
    mgr = CheckpointManager(str(tmp_path / "root"), every=1, max_to_keep=5)
    mgr.save(1, {"x": np.full(1, 1.0)})
    bad = os.path.join(mgr.root, "step_2")
    os.makedirs(bad)
    with open(os.path.join(bad, "partial.tmp"), "w") as fh:
        fh.write("leftovers")
    with pytest.warns(UserWarning, match="skipping unloadable checkpoint"):
        out = mgr.restore_latest()
    assert float(out["x"][0]) == 1.0


def test_save_crash_leaves_previous_checkpoint_intact(tmp_path, monkeypatch):
    """A crash mid-write hits the .tmp dir, never the final path."""
    p = str(tmp_path / "c")
    save_state(p, {"a": np.ones(2)})

    import dist_svgd_tpu.utils.checkpoint as ckpt_mod

    def boom(*a, **k):
        raise RuntimeError("killed mid-write")

    monkeypatch.setattr(ckpt_mod.np, "savez", boom)
    # force the npz path by making the orbax import fail
    import builtins

    real_import = builtins.__import__

    def no_orbax(name, *a, **k):
        if name.startswith("orbax"):
            raise ImportError("test")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_orbax)
    with pytest.raises(RuntimeError, match="killed mid-write"):
        save_state(p, {"a": np.zeros(3)})
    monkeypatch.undo()
    out = load_state(p)
    np.testing.assert_array_equal(out["a"], np.ones(2))


def test_manager_rejects_nonpositive_every(tmp_path):
    with pytest.raises(ValueError):
        CheckpointManager(str(tmp_path), every=0)


def test_manager_clear(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"), every=1)
    mgr.save(1, {"x": np.ones(1)})
    mgr.save(2, {"x": np.ones(1)})
    mgr.clear()
    assert mgr.latest_step() is None
    assert mgr.restore_latest() is None


def test_restore_latest_propagates_missing_orbax(tmp_path, monkeypatch):
    """An orbax-format checkpoint in an env without orbax must raise, not be
    silently skipped as corruption (which would restart from scratch)."""
    pytest.importorskip("orbax.checkpoint")
    mgr = CheckpointManager(str(tmp_path / "root"), every=1)
    mgr.save(1, {"x": np.ones(1)})  # orbax layout (no state.npz)

    import builtins

    real_import = builtins.__import__

    def no_orbax(name, *a, **k):
        if name.startswith("orbax"):
            raise ImportError("test: no orbax")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_orbax)
    with pytest.raises(ImportError, match="no orbax"):
        mgr.restore_latest()


def _make_sampler(parts, data, mode_kwargs):
    lik, prior = make_logreg_split()
    return DistSampler(
        4, lik, None, parts, data=data, include_wasserstein=False,
        log_prior=prior, batch_size=3, seed=5, **mode_kwargs,
    )


@pytest.mark.parametrize("mode_kwargs", [
    dict(exchange_particles=True, exchange_scores=True),
    dict(exchange_particles=False, exchange_scores=False),  # partitions: t drives rotation
])
def test_resume_reproduces_trajectory(tmp_path, rng, mode_kwargs):
    """3 steps + save + fresh sampler + load + 3 steps == 6 uninterrupted
    steps, bit-for-bit (t restores both the rotation and the minibatch key
    stream)."""
    d = 4
    x = jnp.asarray(rng.normal(size=(24, d - 1)))
    t = jnp.asarray(np.where(rng.normal(size=24) > 0, 1.0, -1.0))
    parts = jnp.asarray(rng.normal(size=(8, d)))

    ref = _make_sampler(parts, (x, t), mode_kwargs)
    for _ in range(6):
        want = ref.make_step(1e-2)

    a = _make_sampler(parts, (x, t), mode_kwargs)
    for _ in range(3):
        a.make_step(1e-2)
    path = save_state(str(tmp_path / "mid"), a.state_dict())

    b = _make_sampler(parts, (x, t), mode_kwargs)
    b.load_state_dict(load_state(path))
    for _ in range(3):
        got = b.make_step(1e-2)

    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_resume_with_wasserstein_previous(tmp_path, rng):
    """The W2 'previous' snapshot survives the roundtrip; trajectories with
    the JKO term resume exactly."""
    d = 3
    x = jnp.asarray(rng.normal(size=(16, d - 1)))
    t = jnp.asarray(np.where(rng.normal(size=16) > 0, 1.0, -1.0))
    parts = jnp.asarray(rng.normal(size=(8, d)))
    lik, prior = make_logreg_split()

    def make():
        return DistSampler(
            4, lik, None, parts, data=(x, t), include_wasserstein=True,
            wasserstein_solver="sinkhorn", sinkhorn_iters=20, log_prior=prior,
        )

    ref = make()
    for _ in range(4):
        want = ref.make_step(1e-2, h=0.5)

    a = make()
    for _ in range(2):
        a.make_step(1e-2, h=0.5)
    path = save_state(str(tmp_path / "w2"), a.state_dict())
    b = make()
    b.load_state_dict(load_state(path))
    assert b._previous is not None
    for _ in range(2):
        got = b.make_step(1e-2, h=0.5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def _make_w2(S, parts, mode_kwargs):
    from dist_svgd_tpu.models.gmm import gmm_logp

    return DistSampler(
        S, lambda th, _=None: gmm_logp(th), None, parts,
        include_wasserstein=True, wasserstein_solver="sinkhorn",
        sinkhorn_iters=20, **mode_kwargs,
    )


def test_resharded_restore_exchanged(rng):
    """Save at S=8, restore at S=4 (single-process reshard-on-restore): the
    particles carry over verbatim and the mixed `previous` stack is rebuilt
    EXACTLY for the new layout — checked against the documented snapshot
    definition (pre-update global with the own block post-update) using
    pre/post states captured independently of the implementation.  The
    carried dual is dropped (its per-block pairing doesn't survive), so the
    first resumed solve cold-starts."""
    n, d = 16, 3
    parts = jnp.asarray(rng.normal(size=(n, d)))
    kw = dict(exchange_particles=True, exchange_scores=False)
    a = _make_w2(8, parts, kw)
    pre = None
    for _ in range(3):
        pre = np.asarray(a.particles).copy()  # state entering the last step
        a.make_step(0.05, h=0.5)
    post = np.asarray(a.particles)
    state = a.state_dict()

    b = _make_w2(4, parts, kw)
    b.load_state_dict(state)
    np.testing.assert_array_equal(np.asarray(b.particles), post)
    s_new = n // 4
    want_prev = np.broadcast_to(pre, (4, n, d)).copy()
    for r in range(4):
        want_prev[r, r * s_new:(r + 1) * s_new] = post[r * s_new:(r + 1) * s_new]
    np.testing.assert_allclose(np.asarray(b._previous), want_prev, rtol=1e-12)
    assert b._w2_g is None  # dual dropped → safe cold start
    assert np.isfinite(np.asarray(b.make_step(0.05, h=0.5))).all()

    # S=8 → S=1 degenerates to the post-update global
    c = _make_w2(1, parts, kw)
    c.load_state_dict(state)
    np.testing.assert_allclose(
        np.asarray(c._previous), post[None], rtol=1e-12
    )


def test_resharded_restore_partitions(rng):
    """partitions-mode reshard: the owned-block stacks are the post-update
    global in block order, so any S_new layout is an exact reshape."""
    n, d = 16, 2
    parts = jnp.asarray(rng.normal(size=(n, d)))
    kw = dict(exchange_particles=False, exchange_scores=False)
    a = _make_w2(8, parts, kw)
    for _ in range(3):
        a.make_step(0.05, h=0.5)
    post = np.asarray(a.particles)
    state = a.state_dict()

    b = _make_w2(4, parts, kw)
    b.load_state_dict(state)
    np.testing.assert_allclose(
        np.asarray(b._previous), post.reshape(4, n // 4, d), rtol=1e-12
    )
    assert np.isfinite(np.asarray(b.make_step(0.05, h=0.5))).all()

    # exchanged-mode save also reshards INTO partitions (post rows are
    # reconstructable from the mixed stacks)
    a2 = _make_w2(8, parts, dict(exchange_particles=True, exchange_scores=False))
    for _ in range(2):
        a2.make_step(0.05, h=0.5)
    post2 = np.asarray(a2.particles)
    b2 = _make_w2(4, parts, kw)
    b2.load_state_dict(a2.state_dict())
    np.testing.assert_allclose(
        np.asarray(b2._previous), post2.reshape(4, n // 4, d), rtol=1e-12
    )


def test_resharded_restore_through_checkpoint_files(tmp_path, rng):
    """The reshard path composes with the on-disk checkpoint layer: save an
    S=8 W2 run with save_state, restore the files into an S=4 sampler, and
    continue — the layout conversion happens at load_state_dict, so the
    file format needs no awareness of it."""
    n, d = 16, 3
    parts = jnp.asarray(rng.normal(size=(n, d)))
    kw = dict(exchange_particles=True, exchange_scores=False)
    a = _make_w2(8, parts, kw)
    for _ in range(3):
        a.make_step(0.05, h=0.5)
    post = np.asarray(a.particles)
    path = save_state(str(tmp_path / "s8"), a.state_dict())

    b = _make_w2(4, parts, kw)
    b.load_state_dict(load_state(path))
    np.testing.assert_array_equal(np.asarray(b.particles), post)
    assert np.asarray(b._previous).shape == (4, n, d)
    assert np.isfinite(np.asarray(b.run_steps(2, 0.05, h=0.5))).all()


def test_resharded_restore_impossible_cases(rng):
    """partitions/S=1 saves never recorded pre-update rows, so restoring
    them into an exchanged S>1 layout must raise, as must garbage shapes."""
    n, d = 16, 2
    parts = jnp.asarray(rng.normal(size=(n, d)))
    a = _make_w2(8, parts, dict(exchange_particles=False, exchange_scores=False))
    for _ in range(2):
        a.make_step(0.05, h=0.5)
    b = _make_w2(4, parts, dict(exchange_particles=True, exchange_scores=False))
    with pytest.raises(ValueError, match="cannot reshard"):
        b.load_state_dict(a.state_dict())
    with pytest.raises(ValueError, match="neither a mixed"):
        b.load_state_dict({
            "particles": np.asarray(parts), "t": 1,
            "previous": np.zeros((3, 5, d)),
        })


def test_load_state_dict_shape_mismatch(rng):
    d = 3
    x = jnp.asarray(rng.normal(size=(16, d - 1)))
    t = jnp.asarray(np.where(rng.normal(size=16) > 0, 1.0, -1.0))
    parts = jnp.asarray(rng.normal(size=(8, d)))
    s = _make_sampler(parts, (x, t), dict(exchange_particles=True, exchange_scores=False))
    with pytest.raises(ValueError, match="checkpoint particles"):
        s.load_state_dict({"particles": np.zeros((4, d)), "t": 1})


def test_assemble_full_state_guards(tmp_path):
    """assemble_full_state (cross-process-count restore): reconstructs the
    global state from one complete multi-host save; rejects mixed saves
    (disagreeing replicated scalars) and non-contiguous block lists."""
    from dist_svgd_tpu.utils.checkpoint import assemble_full_state, save_state

    def save(name, start, t, fill):
        save_state(str(tmp_path / name), {
            "particles": np.full((4, 2), fill, dtype=np.float32),
            "particles_start": np.int64(start),
            "t": np.int64(t),
        })
        return str(tmp_path / name)

    a, b = save("a", 0, 3, 1.0), save("b", 4, 3, 2.0)
    st = assemble_full_state([b, a])  # order-independent (sorted by start)
    assert st["particles"].shape == (8, 2)
    assert int(st["t"]) == 3
    np.testing.assert_array_equal(st["particles"][:4], 1.0)
    np.testing.assert_array_equal(st["particles"][4:], 2.0)

    mixed = save("c", 4, 5, 2.0)  # same layout, later save (t=5)
    with pytest.raises(ValueError, match="disagree"):
        assemble_full_state([a, mixed])

    gap = save("e", 8, 3, 2.0)  # rows 4..7 missing
    with pytest.raises(ValueError, match="contiguous"):
        assemble_full_state([a, gap])


def test_assemble_full_state_mixed_key_presence_is_valueerror(tmp_path):
    """A replicated key present only in SOME files (mixed-version or
    corrupt saves) must raise the 'one complete save?' ValueError — the
    states[0]-only classification used to turn this into a bare KeyError
    when the key was missing from the first file (ADVICE round 5)."""
    from dist_svgd_tpu.utils.checkpoint import assemble_full_state, save_state

    def save(name, state):
        save_state(str(tmp_path / name), state)
        return str(tmp_path / name)

    base = {"particles": np.zeros((4, 2), np.float32),
            "particles_start": np.int64(0), "t": np.int64(1)}
    other = {"particles": np.ones((4, 2), np.float32),
             "particles_start": np.int64(4), "t": np.int64(1),
             "extra_scalar": np.float64(7.0)}  # only in the SECOND file
    a, b = save("a", base), save("b", other)
    with pytest.raises(ValueError, match="complete multi-host save"):
        assemble_full_state([a, b])
    # same failure regardless of file order (the old bug was order-
    # dependent: KeyError only when the poor file came first)
    with pytest.raises(ValueError, match="complete multi-host save"):
        assemble_full_state([b, a])


def test_topology_manifest_process_stamp_roundtrip():
    """Round 19: the manifest carries the writing federation's process
    layout; read_manifest surfaces it (defaulting pre-round-19 files to a
    single-process layout) and rejects an inconsistent stamp."""
    from dist_svgd_tpu.utils.checkpoint import read_manifest, topology_manifest

    man = topology_manifest(8, 64, 2, process_count=4)
    assert int(man["topo_process_count"]) == 4
    np.testing.assert_array_equal(man["topo_granule_shards"], [2, 2, 2, 2])
    got = read_manifest(dict(man))
    assert got["process_count"] == 4
    assert got["granule_shards"].tolist() == [2, 2, 2, 2]

    # pre-round-19 manifest (no process keys): single-process defaults
    legacy = {k: v for k, v in man.items()
              if k not in ("topo_process_count", "topo_granule_shards")}
    got = read_manifest(legacy)
    assert got["process_count"] == 1
    assert got["granule_shards"].tolist() == [8]

    # uneven explicit layout is allowed when it sums correctly...
    man = topology_manifest(8, 64, 2, process_count=2,
                            granule_shards=[6, 2])
    assert read_manifest(dict(man))["granule_shards"].tolist() == [6, 2]
    # ...but a layout that does not add up must be refused
    with pytest.raises(ValueError, match="granule"):
        topology_manifest(8, 64, 2, process_count=2, granule_shards=[6, 3])
    with pytest.raises(ValueError, match="divide"):
        topology_manifest(8, 64, 2, process_count=3)  # 8 % 3 != 0

    # a stamped-but-corrupt manifest reads as None (the corruption gate)
    bad = dict(topology_manifest(8, 64, 2, process_count=4))
    bad["topo_granule_shards"] = np.asarray([2, 2, 2, 3], dtype=np.int64)
    assert read_manifest(bad) is None
