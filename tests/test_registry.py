"""Multi-tenant model registry (dist_svgd_tpu/serving/registry.py):
KernelBucketLRU bounds + hot-tenant protection, quota shed priorities,
tenant lifecycle (add / remove-under-load / corrupt-checkpoint and
rejected-reload isolation), the shared scanner, HTTP routing on the
tenant field, and the serve_multitenant bench row schema.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from dist_svgd_tpu.serving import (
    KernelBucketLRU,
    MicroBatcher,
    ModelRegistry,
    Overloaded,
    PredictionServer,
    PredictiveEngine,
)
from dist_svgd_tpu.telemetry import MetricsRegistry
from dist_svgd_tpu.utils.checkpoint import CheckpointManager


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _registry(**kw):
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("max_wait_ms", 0.5)
    return ModelRegistry(**kw)


def _add_logreg(reg, name, rng, n=16, k=4, **kw):
    parts = rng.normal(size=(n, 1 + k)).astype(np.float32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("max_bucket", 16)
    tenant = reg.add_tenant(name, "logreg", particles=parts, **kw)
    return tenant, parts


# --------------------------------------------------------------------- #
# KernelBucketLRU


def test_lru_bounds_total_buckets_and_counts_evictions(rng):
    met = MetricsRegistry()
    cache = KernelBucketLRU(max_buckets=3)
    engines = [
        PredictiveEngine(
            "logreg", rng.normal(size=(8, 5)).astype(np.float32),
            min_bucket=4, max_bucket=32, registry=met,
            tenant=f"t{i}", kernel_cache=cache)
        for i in range(2)
    ]
    x4 = rng.normal(size=(4, 4)).astype(np.float32)
    x8 = rng.normal(size=(8, 4)).astype(np.float32)
    x16 = rng.normal(size=(16, 4)).astype(np.float32)
    engines[0].predict(x4)
    engines[0].predict(x8)
    engines[1].predict(x4)
    assert cache.stats() == {"size": 3, "max_buckets": 3, "evictions": 0}
    # a 4th distinct bucket evicts the LRU entry: engine0's bucket 4
    engines[1].predict(x8)
    st = cache.stats()
    assert st["size"] == 3 and st["evictions"] == 1
    e0 = engines[0].stats()
    assert e0["bucket_evictions"] == 1
    assert e0["compiled_buckets"] == [8]
    assert e0["bucket_cache_size"] == 1
    # tenant-labelled eviction counter
    assert met.counter("svgd_registry_evictions_total").value(
        tenant="t0") == 1
    # the evicted bucket recompiles on next use (a counted miss), and the
    # pressure rolls on to the new LRU victim
    before = engines[0].stats()["bucket_misses"]
    engines[0].predict(x4)
    assert engines[0].stats()["bucket_misses"] == before + 1
    # predictions still correct after eviction round-trips
    direct = PredictiveEngine(
        "logreg", engines[0].particles, min_bucket=4, max_bucket=32,
        registry=MetricsRegistry())
    np.testing.assert_array_equal(engines[0].predict(x16)["mean"],
                                  direct.predict(x16)["mean"])


def test_lru_forget_drops_without_counting(rng):
    cache = KernelBucketLRU(max_buckets=8)
    eng = PredictiveEngine(
        "logreg", rng.normal(size=(8, 5)).astype(np.float32),
        min_bucket=4, max_bucket=16, registry=MetricsRegistry(),
        kernel_cache=cache)
    eng.warmup()
    assert cache.stats()["size"] == 3
    assert cache.forget(eng) == 3
    assert cache.stats() == {"size": 0, "max_buckets": 8, "evictions": 0}


def test_lru_validates_capacity():
    with pytest.raises(ValueError, match="max_buckets"):
        KernelBucketLRU(max_buckets=0)


def test_hot_tenant_never_recompiles_while_cold_tenants_churn(rng):
    """The satellite regression pin: under cache pressure, eviction must
    never cost a HOT tenant a steady-state recompile.  Cold tenants churn
    compiles (evicting each other), the hot tenant is touched every
    round; its bucket is therefore never the LRU victim, verified by the
    retrace sentry over a hot-only window."""
    from tools.jaxlint.sentry import retrace_sentry

    met = MetricsRegistry()
    cache = KernelBucketLRU(max_buckets=3)
    hot = PredictiveEngine(
        "logreg", rng.normal(size=(8, 5)).astype(np.float32),
        min_bucket=8, max_bucket=8, registry=met, tenant="hot",
        kernel_cache=cache)
    colds = [
        PredictiveEngine(
            "logreg", rng.normal(size=(8, 3 + i)).astype(np.float32),
            min_bucket=8, max_bucket=8, registry=met, tenant=f"cold{i}",
            kernel_cache=cache)
        for i in range(4)
    ]
    xh = rng.normal(size=(5, 4)).astype(np.float32)
    hot.warmup([5])
    # churn: each cold predict compiles (4 cold engines rotating through
    # 2 free slots), but the hot bucket is re-touched between every one
    for round_i in range(8):
        hot.predict(xh)
        cold = colds[round_i % len(colds)]
        cold.predict(rng.normal(
            size=(3, cold.feature_dim)).astype(np.float32))
    assert cache.stats()["evictions"] >= 4  # pressure was real
    assert hot.stats()["bucket_evictions"] == 0
    misses_before = hot.stats()["bucket_misses"]
    with retrace_sentry("hot tenant steady state") as sentry:
        for _ in range(16):
            hot.predict(xh)
    assert hot.stats()["bucket_misses"] == misses_before
    if sentry.supported:
        assert sentry.compiles == 0


# --------------------------------------------------------------------- #
# quota shed priorities (deterministic: paused batcher)


def test_quota_priority_shed_hog_before_polite(rng):
    reg = _registry(max_batch=8, max_queue_rows=32,
                    batcher_autostart=False)
    _add_logreg(reg, "hog", rng, quota_rows=8,
                min_bucket=8, max_bucket=8)
    _add_logreg(reg, "polite", rng, min_bucket=8, max_bucket=8)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    hog_futs = [reg.submit("hog", x) for _ in range(4)]  # 32 rows queued
    # the polite arrival overflows the bounded queue: the hog (4x over
    # its quota of 8) sheds its NEWEST queued request, the polite request
    # is admitted
    polite_fut = reg.submit("polite", x)
    stats = reg.batcher.stats()
    assert stats["quota_sheds"] == {"hog": 1}
    assert stats["tenant_queued"] == {"hog": 24, "polite": 8}
    assert isinstance(hog_futs[3].exception(timeout=1), Overloaded)
    assert "quota" in str(hog_futs[3].exception())
    # an over-quota SUBMITTER is refused outright while the queue is full
    with pytest.raises(Overloaded, match="over its inflight-rows quota"):
        reg.submit("hog", x)
    assert reg.batcher.stats()["quota_sheds"] == {"hog": 2}
    met = reg.metrics
    assert met.counter("svgd_serve_quota_sheds_total").value(
        tenant="hog") == 2
    assert met.counter("svgd_serve_quota_sheds_total").value(
        tenant="polite") == 0
    # drain: everything still queued resolves, including the polite one
    reg.batcher.start()
    assert polite_fut.result(timeout=30)["mean"].shape == (8,)
    for fut in hog_futs[:3]:
        assert fut.result(timeout=30)["mean"].shape == (8,)
    reg.close()


def test_quotas_inert_while_queue_has_room(rng):
    reg = _registry(max_batch=8, max_queue_rows=64,
                    batcher_autostart=False)
    _add_logreg(reg, "hog", rng, quota_rows=8, min_bucket=8, max_bucket=8)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    # 4x over quota, but the queue is not full: no shedding
    futs = [reg.submit("hog", x) for _ in range(4)]
    assert reg.batcher.stats()["quota_sheds"] == {}
    reg.batcher.start()
    for fut in futs:
        assert fut.result(timeout=30)["mean"].shape == (8,)
    reg.close()


def test_batches_never_mix_tenants(rng):
    """One coalesced batch = one tenant: the dispatch sees single-tenant
    batches even when both tenants' chunks are interleaved in the queue."""
    seen = []

    def dispatch(x, tenant):
        seen.append((tenant, x.shape[0]))
        return {"v": np.zeros(x.shape[0], np.float32)}

    bat = MicroBatcher(dispatch, max_batch=64, max_wait_ms=0.0,
                       registry=MetricsRegistry(), autostart=False)
    xa = np.zeros((2, 3), np.float32)
    futs = []
    for i in range(6):
        futs.append(bat.submit(xa, tenant="a" if i % 2 == 0 else "b"))
    bat.start()
    for fut in futs:
        assert fut.result(timeout=10)["v"].shape == (2,)
    bat.close()
    assert sum(rows for _, rows in seen) == 12
    # interleaved a/b/a/b... submits can never share a batch
    assert all(t in ("a", "b") for t, _ in seen)
    assert len(seen) == 6  # every flush stopped at the tenant boundary


# --------------------------------------------------------------------- #
# registry lifecycle


def test_registry_validates_names_and_args(rng):
    reg = _registry()
    with pytest.raises(ValueError, match="invalid tenant name"):
        reg.add_tenant("bad name!", "logreg",
                       particles=np.zeros((4, 3), np.float32))
    # "other" is the metrics cardinality-rollup value: a tenant by that
    # name would alias the rollup series
    with pytest.raises(ValueError, match="reserved"):
        reg.add_tenant("other", "logreg",
                       particles=np.zeros((4, 3), np.float32))
    with pytest.raises(ValueError, match="exactly one of"):
        reg.add_tenant("t", "logreg")
    _add_logreg(reg, "t", rng)
    with pytest.raises(ValueError, match="already registered"):
        _add_logreg(reg, "t", rng)
    with pytest.raises(KeyError, match="unknown tenant"):
        reg.submit("ghost", np.zeros((1, 4), np.float32))
    with pytest.raises(KeyError, match="unknown tenant"):
        reg.remove_tenant("ghost")
    reg.close()
    with pytest.raises(RuntimeError, match="closed"):
        _add_logreg(reg, "late", rng)


def test_ten_tenants_mixed_shapes_concurrent_zero_churn(rng):
    """The ISSUE acceptance core: 10+ tenants of mixed model kinds and
    shapes serve concurrently from one process with ZERO cross-tenant
    recompile churn (sentry-verified), and every tenant's answers are
    bitwise those of a standalone engine on the same ensemble."""
    from dist_svgd_tpu.models.bnn import num_params
    from tools.jaxlint.sentry import retrace_sentry

    met = MetricsRegistry()
    reg = _registry(metrics=met, max_batch=32, max_wait_ms=0.2)
    specs = []
    for i in range(12):
        kind = ("logreg", "bnn", "gmm")[i % 3]
        name = f"{kind}-{i}"
        if kind == "logreg":
            k = 3 + (i % 4)
            parts = rng.normal(size=(12 + i, 1 + k)).astype(np.float32)
            reg.add_tenant(name, "logreg", particles=parts,
                           min_bucket=4, max_bucket=8)
            ref = PredictiveEngine("logreg", parts, min_bucket=4,
                                   max_bucket=8, registry=MetricsRegistry())
        elif kind == "bnn":
            nf = 3 + (i % 2)
            parts = rng.normal(size=(8, num_params(nf, 8))).astype(
                np.float32)
            reg.add_tenant(name, "bnn", particles=parts, n_features=nf,
                           n_hidden=8, min_bucket=4, max_bucket=8)
            ref = PredictiveEngine("bnn", parts, n_features=nf, n_hidden=8,
                                   min_bucket=4, max_bucket=8,
                                   registry=MetricsRegistry())
        else:
            d = 2 + (i % 3)
            parts = rng.normal(size=(10 + i, d)).astype(np.float32)
            reg.add_tenant(name, "gmm", particles=parts,
                           min_bucket=4, max_bucket=8)
            ref = PredictiveEngine("gmm", parts, min_bucket=4, max_bucket=8,
                                   registry=MetricsRegistry())
        x = rng.normal(size=(3, ref.feature_dim)).astype(np.float32)
        specs.append((name, ref, x))
    assert len(reg) == 12
    reg.warm([3])
    misses = {n: reg.tenant(n).engine.stats()["bucket_misses"]
              for n, _, _ in specs}

    errors = []

    def hammer(name, x):
        try:
            for _ in range(6):
                reg.predict(name, x, timeout=60)
        except Exception as e:  # surfaced after join
            errors.append((name, e))

    with retrace_sentry("12-tenant concurrent window") as sentry:
        threads = [threading.Thread(target=hammer, args=(n, x))
                   for n, _, x in specs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert errors == []
    if sentry.supported:
        assert sentry.compiles == 0
    for n, _, _ in specs:
        assert reg.tenant(n).engine.stats()["bucket_misses"] == misses[n]
    # served values bitwise-match standalone engines per tenant
    for n, ref, x in specs:
        got = reg.predict(n, x)
        want = ref.predict(x)
        assert sorted(got) == sorted(want)
        for key in got:
            np.testing.assert_array_equal(got[key], want[key])
    # every serving metric carries the tenant label
    expo = met.exposition()
    for n, _, _ in specs:
        assert f'tenant="{n}"' in expo
    reg.close()


def test_corrupt_newest_checkpoint_isolated_to_its_tenant(tmp_path, rng):
    """One tenant's half-written newest step must leave every other
    tenant's hot reload working — the shared-scanner isolation pin."""
    import os

    roots = {}
    gens = {}
    for name in ("alpha", "beta"):
        root = str(tmp_path / name)
        mgr = CheckpointManager(root, every=1, backend="npz")
        parts = rng.normal(size=(12, 5)).astype(np.float32)
        mgr.save(1, {"particles": parts})
        roots[name] = (root, mgr)
        gens[name] = parts
    reg = _registry()
    for name, (root, _) in roots.items():
        reg.add_tenant(name, "logreg", checkpoint=root, watch=True,
                       min_bucket=4, max_bucket=8)
    # beta's newest is corrupt; alpha has a clean newer step
    alpha_new = rng.normal(size=(12, 5)).astype(np.float32)
    roots["alpha"][1].save(2, {"particles": alpha_new})
    bad = os.path.join(roots["beta"][0], "step_2")
    os.makedirs(bad)
    with open(os.path.join(bad, "junk"), "w") as fh:
        fh.write("partial write")
    with pytest.warns(UserWarning, match="skipping unloadable"):
        swapped = reg.poll_once()
    assert swapped["alpha"] == 2
    assert swapped["beta"] is None
    x = rng.normal(size=(2, 4)).astype(np.float32)
    # alpha serves the new generation, beta keeps serving the old one
    ref_a = PredictiveEngine("logreg", alpha_new, min_bucket=4,
                             max_bucket=8, registry=MetricsRegistry())
    np.testing.assert_array_equal(reg.predict("alpha", x)["mean"],
                                  ref_a.predict(x)["mean"])
    ref_b = PredictiveEngine("logreg", gens["beta"], min_bucket=4,
                             max_bucket=8, registry=MetricsRegistry())
    np.testing.assert_array_equal(reg.predict("beta", x)["mean"],
                                  ref_b.predict(x)["mean"])
    reg.close()


def test_rejected_reload_isolated_to_its_tenant(tmp_path, rng):
    """A health-rejected generation in one tenant (EnsembleRejected) is
    absorbed by its reloader; the other tenant still swaps and serves."""
    from dist_svgd_tpu.telemetry import ReloadPolicy

    roots = {}
    for name in ("guarded", "plain"):
        root = str(tmp_path / name)
        mgr = CheckpointManager(root, every=1, backend="npz")
        mgr.save(1, {"particles":
                     rng.normal(size=(32, 5)).astype(np.float32)})
        roots[name] = (root, mgr)
    reg = _registry()
    reg.add_tenant("guarded", "logreg", checkpoint=roots["guarded"][0],
                   watch=True, min_bucket=4, max_bucket=8,
                   reload_policy=ReloadPolicy(min_ess_frac=0.05,
                                              max_points=32))
    reg.add_tenant("plain", "logreg", checkpoint=roots["plain"][0],
                   watch=True, min_bucket=4, max_bucket=8)
    # guarded gets a collapsed (rejectable) step 2; plain a healthy one
    collapsed = np.tile(rng.normal(size=(1, 5)).astype(np.float32),
                        (32, 1))
    roots["guarded"][1].save(2, {"particles": collapsed})
    plain_new = rng.normal(size=(32, 5)).astype(np.float32)
    roots["plain"][1].save(2, {"particles": plain_new})
    swapped = reg.poll_once()
    assert swapped["plain"] == 2
    assert swapped["guarded"] is None  # rejected, absorbed
    st = reg.stats()["tenants"]
    assert st["guarded"]["reload_rejects"] == 1
    assert st["guarded"]["loaded_step"] == 2  # seen, not re-judged forever
    assert st["guarded"]["reloads"] == 0
    assert st["plain"]["reloads"] == 1
    assert st["guarded"]["reload_errors"] == 0
    # both keep serving
    x = rng.normal(size=(2, 4)).astype(np.float32)
    assert reg.predict("guarded", x)["mean"].shape == (2,)
    assert reg.predict("plain", x)["mean"].shape == (2,)
    reg.close()


def test_scanner_error_isolated_and_counted(tmp_path, rng):
    """A poll that raises for one tenant (missing ensemble key) is counted
    against that tenant only; other tenants still poll and swap."""
    root_ok = str(tmp_path / "ok")
    mgr_ok = CheckpointManager(root_ok, every=1, backend="npz")
    mgr_ok.save(1, {"particles": rng.normal(size=(8, 5)).astype(np.float32)})
    root_bad = str(tmp_path / "bad")
    mgr_bad = CheckpointManager(root_bad, every=1, backend="npz")
    mgr_bad.save(1, {"particles":
                     rng.normal(size=(8, 5)).astype(np.float32)})
    reg = _registry()
    reg.add_tenant("ok", "logreg", checkpoint=root_ok, watch=True,
                   min_bucket=4, max_bucket=8)
    reg.add_tenant("bad", "logreg", checkpoint=root_bad, watch=True,
                   min_bucket=4, max_bucket=8)
    mgr_ok.save(2, {"particles": rng.normal(size=(8, 5)).astype(np.float32)})
    mgr_bad.save(2, {"wrong_key": np.zeros((8, 5), np.float32)})
    swapped = reg.poll_once()
    assert swapped == {"ok": 2, "bad": None}
    st = reg.stats()["tenants"]
    assert st["bad"]["reload_errors"] == 1
    assert st["ok"]["reload_errors"] == 0
    assert reg.metrics.counter("svgd_registry_reload_errors_total").value(
        tenant="bad") == 1
    reg.close()


def test_add_remove_under_load_drains_cleanly(rng):
    """Tenants come and go while traffic flows: a removed tenant's queued
    work flushes (drain=True), in-flight work resolves, other tenants
    never error, and post-removal submits fail cleanly."""
    reg = _registry(max_batch=16, max_wait_ms=0.2)
    _add_logreg(reg, "stay", rng)
    _add_logreg(reg, "go", rng)
    x = rng.normal(size=(2, 4)).astype(np.float32)
    reg.warm([2])
    stop = threading.Event()
    errors = []

    def stay_traffic():
        while not stop.is_set():
            try:
                reg.predict("stay", x, timeout=30)
            except Exception as e:
                errors.append(e)
                return

    t = threading.Thread(target=stay_traffic)
    t.start()
    futs = [reg.submit("go", x) for _ in range(20)]
    reg.remove_tenant("go", drain=True, timeout=30)
    # drained: every pre-removal future resolves with real results
    for fut in futs:
        assert fut.result(timeout=30)["mean"].shape == (2,)
    assert "go" not in reg
    with pytest.raises(KeyError, match="unknown tenant"):
        reg.submit("go", x)
    # a NEW tenant joins under the same load
    _, parts = _add_logreg(reg, "late", rng)
    ref = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16,
                           registry=MetricsRegistry())
    np.testing.assert_array_equal(reg.predict("late", x)["mean"],
                                  ref.predict(x)["mean"])
    stop.set()
    t.join(timeout=30)
    assert errors == []
    assert reg.tenant_names() == ["late", "stay"]
    reg.close()


def test_tenant_pending_rows_covers_collected_batches(rng):
    """The drain condition counts collected-but-unresolved rows, not just
    queued ones: a tenant's queue hitting zero while its last batch is
    inside dispatch must keep the tenant routable."""
    import threading as _threading

    release = _threading.Event()
    entered = _threading.Event()

    def slow_dispatch(x, tenant):
        entered.set()
        release.wait(10)
        return {"v": np.zeros(x.shape[0], np.float32)}

    bat = MicroBatcher(slow_dispatch, max_batch=8, max_wait_ms=0.0,
                       registry=MetricsRegistry())
    fut = bat.submit(np.zeros((4, 3), np.float32), tenant="t")
    assert entered.wait(10)
    # the batch was collected (queued -> 0) but is still in flight
    assert bat.tenant_queued_rows("t") == 0
    assert bat.tenant_pending_rows("t") == 4
    release.set()
    assert fut.result(timeout=10)["v"].shape == (4,)
    assert bat.tenant_pending_rows("t") == 0
    bat.close()


def test_remove_without_drain_cancels_queued(rng):
    from concurrent.futures import CancelledError

    reg = _registry(max_batch=8, batcher_autostart=False)
    _add_logreg(reg, "doomed", rng, min_bucket=8, max_bucket=8)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    futs = [reg.submit("doomed", x) for _ in range(3)]
    reg.remove_tenant("doomed", drain=False)
    for fut in futs:
        assert isinstance(fut.exception(timeout=1), CancelledError)
    assert reg.kernel_cache.stats()["size"] == 0
    reg.batcher.start()
    reg.close()


def test_remove_tenant_drain_wins_scanner_reload_race(tmp_path, rng):
    """``remove_tenant(drain=True)`` racing the scanner thread's hot
    reload: the drain always wins, and a LATE reload (the scanner losing
    the race on its own thread) neither resurrects the tenant nor leaks
    a compiled bucket into the shared ``KernelBucketLRU`` — the reload
    path rebuilds kernels inside the engine only; the shared cache is
    touched exclusively by the serving path."""
    root = str(tmp_path / "race")
    mgr = CheckpointManager(root, every=1, backend="npz")
    mgr.save(1, {"particles": rng.normal(size=(16, 5)).astype(np.float32)})
    reg = _registry()
    tenant = reg.add_tenant("victim", "logreg", checkpoint=root,
                            watch=True, min_bucket=4, max_bucket=4)
    eng = tenant.engine
    x = rng.normal(size=(3, 4)).astype(np.float32)
    reg.predict("victim", x)  # serve once: the bucket enters the LRU
    assert reg.kernel_cache.stats()["size"] == 1
    # scanner thread hammers hot reloads while the main thread removes
    stop = threading.Event()
    reload_errors = []

    def scanner():
        step = 2
        while not stop.is_set():
            try:
                mgr.save(step, {"particles":
                                rng.normal(size=(16, 5))
                                .astype(np.float32)})
                tenant.reloader.poll_once()
                step += 1
            except Exception as e:  # pragma: no cover - the race's loser
                reload_errors.append(e)
                return

    t = threading.Thread(target=scanner)
    t.start()
    reg.remove_tenant("victim", drain=True, timeout=30)
    stop.set()
    t.join(timeout=30)
    assert reload_errors == []
    # drain won and stays won
    assert "victim" not in reg
    with pytest.raises(KeyError, match="unknown tenant"):
        reg.submit("victim", x)
    assert reg.kernel_cache.stats()["size"] == 0
    # one fully-late reload on the detached engine: absorbed, no
    # resurrection, no compiled bucket re-entering the shared LRU
    mgr.save(99, {"particles": rng.normal(size=(16, 5))
                  .astype(np.float32)})
    tenant.reloader.poll_once()
    assert eng.stats()["generation_id"] >= 2  # the reload itself worked
    assert "victim" not in reg
    assert reg.kernel_cache.stats()["size"] == 0
    reg.close()


def test_set_quota_live(rng):
    reg = _registry(batcher_autostart=False, max_batch=8,
                    max_queue_rows=16)
    _add_logreg(reg, "t", rng, min_bucket=8, max_bucket=8)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    reg.submit("t", x)
    reg.submit("t", x)  # queue now full (16 rows), no quota -> no shed
    with pytest.raises(Overloaded, match="queue full \\("):
        reg.submit("t", x)
    reg.set_quota("t", 8)
    with pytest.raises(Overloaded, match="over its inflight-rows quota"):
        reg.submit("t", x)
    with pytest.raises(KeyError):
        reg.set_quota("ghost", 1)
    reg.batcher.start()
    reg.close()


# --------------------------------------------------------------------- #
# HTTP front end over a registry


def _post(url, body, timeout=10):
    req = urllib.request.Request(
        url + "/predict", json.dumps(body).encode(),
        {"Content-Type": "application/json"})
    try:
        return 200, json.loads(urllib.request.urlopen(
            req, timeout=timeout).read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url, path, timeout=10):
    try:
        return 200, json.loads(urllib.request.urlopen(
            url + path, timeout=timeout).read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_server_routes_tenants(rng):
    met = MetricsRegistry()
    reg = _registry(metrics=met)
    _, parts_a = _add_logreg(reg, "a", rng)
    _add_logreg(reg, "b", rng)
    reg.warm([1])
    with PredictionServer(reg, port=0) as srv:
        url = srv.url
        code, body = _post(url, {"tenant": "a", "inputs": [[0.1] * 4]})
        assert code == 200 and body["tenant"] == "a"
        ref = PredictiveEngine("logreg", parts_a, min_bucket=4,
                               max_bucket=16, registry=MetricsRegistry())
        want = ref.predict(np.asarray([[0.1] * 4], np.float32))["mean"][0]
        assert body["outputs"]["mean"][0] == pytest.approx(want, abs=0)
        # unknown tenant -> 404; missing tenant with 2 hosted -> 400
        code, body = _post(url, {"tenant": "ghost", "inputs": [[0.1] * 4]})
        assert code == 404 and "unknown tenant" in body["error"]
        code, body = _post(url, {"inputs": [[0.1] * 4]})
        assert code == 400 and "tenant" in body["error"]
        # /tenants listing
        code, body = _get(url, "/tenants")
        assert code == 200 and sorted(body["tenants"]) == ["a", "b"]
        assert body["tenants"]["a"]["model"] == "logreg"
        # /healthz aggregate + per-tenant detail
        code, body = _get(url, "/healthz")
        assert code == 200 and sorted(body["tenants"]) == ["a", "b"]
        code, body = _get(url, "/healthz/a")
        assert code == 200 and body["tenant"] == "a"
        assert body["bucket_cache_size"] >= 1
        code, _ = _get(url, "/healthz/ghost")
        assert code == 404
        # tenant-labelled http + serving series on /metrics
        text = urllib.request.urlopen(url + "/metrics",
                                      timeout=10).read().decode()
        assert 'svgd_http_requests_total{route="/predict",status="200",' \
               'tenant="a"}' in text
        assert 'tenant="a"' in text and 'tenant="b"' in text


def test_server_single_tenant_default_and_guard(rng):
    reg = _registry()
    _add_logreg(reg, "only", rng)
    with PredictionServer(reg, port=0) as srv:
        # exactly one tenant: the tenant field may be omitted
        code, body = _post(srv.url, {"inputs": [[0.1] * 4]})
        assert code == 200 and body["tenant"] == "only"
    # single-tenant (engine) servers refuse the tenant field loudly
    eng = PredictiveEngine(
        "logreg", rng.normal(size=(8, 5)).astype(np.float32),
        min_bucket=4, max_bucket=16, registry=MetricsRegistry())
    with PredictionServer(eng, port=0,
                          registry=MetricsRegistry()) as srv:
        code, body = _post(srv.url, {"tenant": "x", "inputs": [[0.1] * 4]})
        assert code == 400 and "single-tenant" in body["error"]


# --------------------------------------------------------------------- #
# serve_multitenant bench row


def test_multitenant_bench_row_schema():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import serve_bench

    row = serve_bench.run_multitenant_bench(
        tenants=3, clients=4, requests=48, rows=(1, 2), max_batch=32,
        max_wait_ms=0.5)
    assert row["metric"] == "serve_multitenant"
    assert row["tenants"] == 3
    assert row["completed"] == 48
    assert row["value"] > 0
    assert sorted(row["per_tenant"]) == ["bnn-1", "gmm-2", "logreg-0"]
    for pt in row["per_tenant"].values():
        assert {"model", "rps", "p50_ms", "p99_ms", "hist_p99_ms",
                "requests"} <= set(pt)
        assert pt["requests"] == 16
    assert 0 < row["tenant_fairness"] <= 1.0
    # the steady-state contract and both machinery probes
    assert row["recompiles"] == 0
    assert row["sentry_compiles"] in (0, None)
    assert row["evictions"] >= 1
    assert row["eviction_probe"]["evictions_after"] > \
        row["eviction_probe"]["evictions_before"]
    assert row["quota_sheds"] >= 1
    assert row["quota_probe"]["polite_served"] is True
    assert row["p99_worst_tenant_ms"] >= row["p50_ms"]
