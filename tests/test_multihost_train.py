"""tools/multihost_train.py: the multihost_train row — fake-mode drill in
tier-1 (bitwise multi-process-topology resume, kill-one W−1 elastic resume
on the same step grid, zero post-restart steady-state recompiles), the
FederationSupervisor coordinator loop on scripted workers, per-process
checkpoint split/assemble round-trips, and the real-mode clean refusal on
the legacy-jax CPU multiprocess gap."""

import os
import sys
import types

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import multihost_train

from dist_svgd_tpu.parallel import multihost
from dist_svgd_tpu.parallel.mesh import SHARD_MAP_LEGACY
from dist_svgd_tpu.resilience import (
    FakeWorker,
    FederationDead,
    FederationSupervisor,
    TopologyFault,
    WorkerLossAt,
)
from dist_svgd_tpu.telemetry import MetricsRegistry
from dist_svgd_tpu.utils import checkpoint as ckpt


@pytest.fixture(scope="module")
def fake_row(tmp_path_factory):
    return multihost_train.run_drill(
        mode="fake", processes=4, devcount=2, n=48, num_steps=12,
        checkpoint_every=4,
        root=str(tmp_path_factory.mktemp("mh_drill")))


def test_fake_drill_row_schema(fake_row):
    for key in ("metric", "mode", "processes", "devcount", "shards",
                "shards_after_loss", "updates_per_s_gather",
                "updates_per_s_ring", "ring_step_wall_ms",
                "ring_hops_per_step", "ring_hop_wall_ms",
                "dcn_crossings_per_hop", "variants_ok", "manifest_stamped",
                "single_block_rejected", "resume_bitwise",
                "rng_layout_free", "kill_step", "steps_lost",
                "expected_steps_lost", "killone_max_dev",
                "killone_within_tol", "post_restart_recompiles",
                "federation_restarts", "federation_transitions"):
        assert key in fake_row, key
    assert fake_row["metric"] == "multihost_train"
    assert fake_row["mode"] == "fake"
    assert fake_row["shards"] == 8
    assert fake_row["shards_after_loss"] == 6


def test_fake_drill_passes_its_own_gates(fake_row):
    ok, reasons = multihost_train.row_ok(fake_row)
    assert ok, reasons


def test_fake_drill_resume_is_bitwise_and_layout_free(fake_row):
    # the tentpole invariant: a multi-process-topology checkpoint (split
    # into per-process blocks, saved, assembled) resumes BITWISE equal to
    # the uninterrupted run, and the minibatch RNG root is identical —
    # process layout is an execution detail, not semantics
    assert fake_row["resume_bitwise"] is True
    assert fake_row["rng_layout_free"] is True
    assert fake_row["manifest_stamped"] is True
    assert fake_row["single_block_rejected"] is True


def test_fake_drill_killone_grid_and_recompiles(fake_row):
    # kill between checkpoints: exactly the steps since the last save are
    # lost, the W−1 resume lands back on the same absolute grid within
    # the drill tolerance, and steady state after the restart compiles
    # nothing
    assert fake_row["steps_lost"] == fake_row["expected_steps_lost"] == 2
    assert fake_row["killone_within_tol"] is True
    if fake_row["sentry_supported"]:
        assert fake_row["post_restart_recompiles"] == 0


def test_fake_drill_federation_transition(fake_row):
    assert fake_row["federation_restarts"] == 1
    assert fake_row["federation_final_processes"] == 3
    (tr,) = fake_row["federation_transitions"]
    assert (tr["from_processes"], tr["to_processes"]) == (4, 3)
    assert tr["restart_wall_s"] is not None


def test_fake_drill_comm_profile(fake_row):
    # 8-shard gather ring: 7 hops/step; in-process mesh: one granule, so
    # zero DCN boundary crossings (the granule-major minimum)
    assert fake_row["ring_hops_per_step"] == 7
    assert fake_row["dcn_crossings_per_hop"] == 0
    assert fake_row["updates_per_s_gather"] > 0
    assert fake_row["updates_per_s_ring"] > 0


@pytest.mark.skipif(
    not SHARD_MAP_LEGACY,
    reason="the refusal row only exists on the legacy-jax CPU gap",
)
def test_real_mode_refuses_cleanly_on_legacy_jax():
    row = multihost_train.run_drill(mode="real", processes=2)
    assert row["status"] == "unsupported"
    assert "jax>=0.5" in row["unsupported_reason"]
    ok, reasons = multihost_train.row_ok(row)
    assert ok  # an honest refusal is the contract, not a failure
    assert "unsupported" in reasons[0]


def test_row_ok_fails_on_each_broken_gate(fake_row):
    for key, bad in (("resume_bitwise", False),
                     ("rng_layout_free", False),
                     ("manifest_stamped", False),
                     ("single_block_rejected", False),
                     ("variants_ok", False),
                     ("steps_lost", 99),
                     ("killone_within_tol", False),
                     ("post_restart_recompiles", 3)):
        row = dict(fake_row)
        row[key] = bad
        ok, reasons = multihost_train.row_ok(row)
        assert not ok, key
        assert reasons, key


# ---- FederationSupervisor on scripted workers ------------------------ #


def _fake_clock():
    state = {"t": 0.0}

    def clock():
        state["t"] += 0.01
        return state["t"]

    return clock


def test_federation_clean_finish_no_restarts():
    launches = []

    def launcher(width, attempt):
        launches.append((width, attempt))
        return [FakeWorker(f"w{i}", [None, 0]) for i in range(width)]

    sup = FederationSupervisor(launcher, processes=3,
                               registry=MetricsRegistry(),
                               clock=_fake_clock(), sleep=lambda s: None)
    report = sup.run()
    assert report["status"] == "ok"
    assert report["processes"] == 3
    assert report["restarts"] == 0
    assert report["transitions"] == []
    assert launches == [(3, 0)]


def test_federation_kill_one_relaunches_at_w_minus_1():
    launches = []

    def launcher(width, attempt):
        launches.append((width, attempt))
        if attempt == 0:
            return [FakeWorker(f"w{i}",
                               [None, -9 if i == 1 else None, None, 0])
                    for i in range(width)]
        return [FakeWorker(f"w{i}", [None, 0]) for i in range(width)]

    reg = MetricsRegistry()
    sup = FederationSupervisor(launcher, processes=4, restart_budget=1,
                               registry=reg,
                               clock=_fake_clock(), sleep=lambda s: None)
    report = sup.run()
    assert report["status"] == "ok"
    assert report["processes"] == 3
    assert report["restarts"] == 1
    assert launches == [(4, 0), (3, 1)]
    (tr,) = report["transitions"]
    assert tr["from_processes"] == 4
    assert tr["to_processes"] == 3
    assert tr["lost"] == {"w1": -9}
    assert tr["restart_wall_s"] is not None and tr["restart_wall_s"] > 0
    # the process dimension lands in the shared svgd_elastic_* metrics
    assert reg.gauge("svgd_elastic_processes").value() == 3
    assert reg.counter("svgd_elastic_worker_losses_total").value() == 1
    assert reg.counter(
        "svgd_elastic_federation_restarts_total").value() == 1


def test_federation_restart_budget_exhaustion_raises():
    def launcher(width, attempt):
        # every generation loses its last worker
        return [FakeWorker(f"w{i}",
                           [None, -9 if i == width - 1 else None, None])
                for i in range(width)]

    sup = FederationSupervisor(launcher, processes=4, restart_budget=1,
                               registry=MetricsRegistry(),
                               clock=_fake_clock(), sleep=lambda s: None)
    with pytest.raises(FederationDead, match="budget"):
        sup.run()


def test_federation_min_processes_floor_raises():
    def launcher(width, attempt):
        # three of four die at once: survivors < min_processes
        return [FakeWorker(f"w{i}", [None, -9 if i else None, None])
                for i in range(width)]

    sup = FederationSupervisor(launcher, processes=2, min_processes=2,
                               restart_budget=5,
                               registry=MetricsRegistry(),
                               clock=_fake_clock(), sleep=lambda s: None)
    with pytest.raises(FederationDead, match="min_processes"):
        sup.run()


def test_federation_launcher_width_mismatch_raises():
    sup = FederationSupervisor(
        lambda width, attempt: [FakeWorker("only")],
        processes=3, registry=MetricsRegistry(),
        clock=_fake_clock(), sleep=lambda s: None)
    with pytest.raises(ValueError, match="returned 1 workers"):
        sup.run()


def test_worker_loss_fault_maps_processes_to_shards():
    fault = WorkerLossAt(5, processes=4, lost=1)
    ctx = types.SimpleNamespace(t=5, num_shards=8)
    with pytest.raises(TopologyFault) as ei:
        fault.fire(ctx)
    assert ei.value.surviving == 6
    assert ei.value.lost_devices == 2
    with pytest.raises(ValueError, match="granule layout"):
        fault.fire(types.SimpleNamespace(t=5, num_shards=6))
    with pytest.raises(ValueError, match="processes"):
        WorkerLossAt(5, processes=1)
    with pytest.raises(ValueError, match="lost"):
        WorkerLossAt(5, processes=4, lost=4)


# ---- per-process checkpoint split/assemble --------------------------- #


def _small_state(num_shards=8, n=16):
    sampler = multihost_train.build_sampler(
        n, num_shards, multihost.make_particle_mesh(num_shards))
    sampler.run_steps(2, 0.05)
    return sampler, sampler.state_dict()


def test_split_state_roundtrip_bitwise(tmp_path):
    _, state = _small_state()
    blocks = ckpt.split_state_for_processes(state, 4)
    assert len(blocks) == 4
    paths = []
    for r, blk in enumerate(blocks):
        man = ckpt.read_manifest(blk)
        assert man["process_count"] == 4
        assert man["granule_shards"].tolist() == [2, 2, 2, 2]
        assert blk["particles"].shape[0] == 4  # 16 rows / 8 shards * 2
        assert int(blk["particles_start"]) == r * 4
        paths.append(ckpt.save_state(str(tmp_path / f"rank_{r}"), blk))
    full = ckpt.assemble_full_state(paths)
    for key, val in state.items():
        if key.endswith("_start") or key.startswith("topo_"):
            continue
        if val is None:  # e.g. `previous` with W2 off — dropped on save
            assert full.get(key) is None
            continue
        np.testing.assert_array_equal(np.asarray(full[key]),
                                      np.asarray(val), err_msg=key)


def test_split_state_w1_is_identity_block():
    _, state = _small_state()
    (blk,) = ckpt.split_state_for_processes(state, 1)
    np.testing.assert_array_equal(np.asarray(blk["particles"]),
                                  np.asarray(state["particles"]))
    assert ckpt.read_manifest(blk)["process_count"] == 1


def test_split_state_refusals():
    _, state = _small_state()
    with pytest.raises(ValueError, match="divide"):
        ckpt.split_state_for_processes(state, 3)
    blocks = ckpt.split_state_for_processes(state, 4)
    with pytest.raises(ValueError, match="per-process"):
        ckpt.split_state_for_processes(blocks[1], 2)
    with pytest.raises(ValueError, match="manifest"):
        ckpt.split_state_for_processes({"particles": np.zeros((8, 2))}, 2)


@pytest.mark.slow
@pytest.mark.skipif(
    SHARD_MAP_LEGACY,
    reason="jax < 0.5 CPU backend lacks multiprocess collectives",
)
def test_real_mode_kill_one_drill(tmp_path):
    """The real leg: 2 worker subprocesses rendezvous, train, one takes a
    real SIGKILL after its first complete per-process save, and the
    FederationSupervisor relaunches the survivor with --resume."""
    row = multihost_train.run_drill(
        mode="real", processes=2, devcount=2, n=48, num_steps=8,
        checkpoint_every=4, root=str(tmp_path))
    ok, reasons = multihost_train.row_ok(row)
    assert ok, reasons
    assert row["federation_restarts"] == 1
    assert row["killone_within_tol"]
