"""Cost attribution & telemetry history (round 23): the dispatch
profiler's per-program attribution and fence-once contract, the usage
meter's per-tenant ledger (and its partition identity), the on-disk
telemetry history ring, the change-point anomaly detector's
deterministic fixture verdicts, and the cost-drill accounting gates at
test size.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dist_svgd_tpu.parallel.plan import Plan
from dist_svgd_tpu.telemetry import profile as profile_mod
from dist_svgd_tpu.telemetry import usage as usage_mod
from dist_svgd_tpu.telemetry.history import (
    HistoryRecorder,
    TelemetryHistory,
    list_series,
    series_values,
)
from dist_svgd_tpu.telemetry.metrics import MetricsRegistry
from dist_svgd_tpu.utils.metrics import StepTimer

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))


@pytest.fixture
def rng():
    return np.random.default_rng(11)


@pytest.fixture(autouse=True)
def _switchboards_off():
    """Every test starts and ends with profiler and meter disabled — the
    process-global switchboards must not leak across tests."""
    profile_mod.disable_profiler()
    usage_mod.disable_usage()
    yield
    profile_mod.disable_profiler()
    usage_mod.disable_usage()


def _compiled_double(label="costtest.double"):
    return Plan(None).compile(lambda x: x * 2.0, label=label)


# --------------------------------------------------------------------- #
# dispatch profiler: attribution, switchboard, fence-once
# --------------------------------------------------------------------- #


def test_profiler_attributes_plan_dispatch(rng):
    """One profiled dispatch lands one histogram observation plus exact
    rows/bytes on the program's label."""
    reg = MetricsRegistry()
    fn = _compiled_double("costtest.attr")
    x = jnp.asarray(rng.normal(size=(8, 3)).astype(np.float32))
    fn(x)  # warm outside the profiled window
    profile_mod.enable_profiler(registry=reg)
    try:
        out = fn(x)
        # fence-once: the profiler already fenced this value; StepTimer's
        # fence consumes the note instead of blocking again
        assert profile_mod.fence(out) is out
    finally:
        profile_mod.disable_profiler()
    summary = profile_mod.summary(reg)
    row = summary["costtest.attr"]
    assert row["dispatches"] == 1
    assert row["rows"] == 8
    assert row["bytes"] == 8 * 3 * 4
    assert row["seconds"] > 0.0
    assert profile_mod.attributed_seconds(reg, "costtest.") == pytest.approx(
        row["seconds"])
    assert profile_mod.attributed_seconds(reg, "other.") == 0.0


def test_profiler_disabled_is_passthrough(rng):
    """Disabled profiler: dispatches write nothing anywhere and the
    switchboard reads None."""
    assert profile_mod.get_profiler() is None
    assert not profile_mod.profiler_enabled()
    fn = _compiled_double("costtest.off")
    out = fn(jnp.ones((4, 2), np.float32))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    # a later-enabled profiler starts from a clean slate for this label
    reg = MetricsRegistry()
    profile_mod.enable_profiler(registry=reg)
    profile_mod.disable_profiler()
    assert "costtest.off" not in profile_mod.summary(reg)


def test_profiler_switchboard_idempotent():
    reg = MetricsRegistry()
    p1 = profile_mod.enable_profiler(registry=reg)
    p2 = profile_mod.enable_profiler()
    assert p1 is p2
    assert profile_mod.profiler_enabled()
    assert profile_mod.disable_profiler() is p1
    assert profile_mod.disable_profiler() is None
    assert not profile_mod.profiler_enabled()


def test_profiler_epoch_rebinds_entry_cache(rng):
    """The per-entry fast-path cache is keyed on profiler identity: a new
    profiler epoch (new registry) re-derives it instead of writing into
    the dead registry."""
    fn = _compiled_double("costtest.epoch")
    x = jnp.ones((2, 2), np.float32)
    reg1, reg2 = MetricsRegistry(), MetricsRegistry()
    profile_mod.enable_profiler(registry=reg1)
    fn(x)
    profile_mod.disable_profiler()
    profile_mod.enable_profiler(registry=reg2)
    fn(x)
    fn(x)
    profile_mod.disable_profiler()
    assert profile_mod.summary(reg1)["costtest.epoch"]["dispatches"] == 1
    assert profile_mod.summary(reg2)["costtest.epoch"]["dispatches"] == 2


def test_noop_measure_is_shared_and_zero_alloc():
    """PR-5 discipline: while disabled, measure() hands back ONE shared
    no-op and fence(None) passes through — zero allocations, pinned with
    tracemalloc like the tracer's no-op span."""
    import tracemalloc

    assert profile_mod.measure("a") is profile_mod.measure("b")
    assert profile_mod.fence(None) is None

    def loop():
        for _ in range(200):
            with profile_mod.measure("hot"):
                pass
            profile_mod.fence(None)

    loop()  # warm lazy caches before measuring
    tracemalloc.start()
    try:
        filters = [tracemalloc.Filter(True, profile_mod.__file__)]
        before = tracemalloc.take_snapshot().filter_traces(filters)
        loop()
        after = tracemalloc.take_snapshot().filter_traces(filters)
    finally:
        tracemalloc.stop()
    grown = sum(max(s.size_diff, 0)
                for s in after.compare_to(before, "lineno"))
    assert grown == 0, f"disabled profiler path allocated {grown} bytes"


def test_measure_context_records_host_span():
    reg = MetricsRegistry()
    profile_mod.enable_profiler(registry=reg)
    try:
        with profile_mod.measure("host.section"):
            pass
    finally:
        profile_mod.disable_profiler()
    assert profile_mod.summary(reg)["host.section"]["dispatches"] == 1


def test_fence_exactly_once_with_steptimer(rng, monkeypatch):
    """The double-fencing fix, pinned with a block_until_ready call-count
    spy: profiler fences the dispatch, StepTimer.mark() on the same value
    consumes the note (no second block); without the profiler the timer
    fences itself."""
    calls = []
    real = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda v: calls.append(1) or real(v))

    fn = _compiled_double("costtest.fence")
    x = jnp.asarray(rng.normal(size=(4, 2)).astype(np.float32))
    fn(x)  # warm

    profile_mod.enable_profiler(registry=MetricsRegistry())
    try:
        calls.clear()
        out = fn(x)
        assert len(calls) == 1  # the profiler's fence
        StepTimer().mark(out)
        assert len(calls) == 1  # note consumed: no second fence
        StepTimer().mark(out)
        assert len(calls) == 2  # note was one-shot
    finally:
        profile_mod.disable_profiler()

    calls.clear()
    out = fn(x)
    assert calls == []  # disabled profiler: dispatch not fenced
    StepTimer().mark(out)
    assert len(calls) == 1  # the timer's own fence still happens


# --------------------------------------------------------------------- #
# usage meter
# --------------------------------------------------------------------- #


def test_usage_meter_partitions_totals():
    """Each batch writes exactly one label set, so tenants sum to totals
    exactly — the accounting identity the drill gates within 1%."""
    reg = MetricsRegistry()
    meter = usage_mod.UsageMeter(registry=reg)
    meter.record_batch(tenant="acme", generation=None, rows=10,
                       device_s=0.5, queue_s=0.1, requests=2)
    meter.record_batch(tenant="acme", generation="gen-2", rows=6,
                       device_s=0.25, queue_s=0.0, requests=1)
    meter.record_batch(tenant="globex", generation=None, rows=4,
                       device_s=0.125, queue_s=0.05, requests=1)
    meter.record_batch(tenant=None, generation=None, rows=3,
                       device_s=0.0625, queue_s=0.0, requests=1)
    meter.record_compile(tenant="acme")

    s = usage_mod.usage_summary(reg)
    acme = s["tenants"]["acme"]
    assert acme["device_seconds"] == pytest.approx(0.75)
    assert acme["rows"] == 16
    assert acme["requests"] == 3
    assert acme["compiles"] == 1
    assert acme["generations"]["gen-2"]["rows"] == 6
    assert s["tenants"]["globex"]["device_seconds"] == pytest.approx(0.125)
    assert s["tenants"][usage_mod.DEFAULT_TENANT]["rows"] == 3
    total = sum(t["device_seconds"] for t in s["tenants"].values())
    assert total == pytest.approx(s["totals"]["device_seconds"])
    assert s["totals"]["device_seconds"] == pytest.approx(0.9375)
    assert s["replicas"] == {}


def test_usage_summary_replica_breakdown():
    """Replica-labelled series (a federated registry) feed the per-replica
    breakdown and are excluded from tenants/totals — no double count."""
    reg = MetricsRegistry()
    ctr = reg.counter(usage_mod.DEVICE_SECONDS_TOTAL, "test")
    ctr.inc(1.0, tenant="acme")                    # fleet rollup
    ctr.inc(0.75, tenant="acme", replica="r0")     # per-replica ingest
    ctr.inc(0.25, tenant="acme", replica="r1")
    s = usage_mod.usage_summary(reg)
    assert s["totals"]["device_seconds"] == pytest.approx(1.0)
    assert s["replicas"]["r0"]["acme"]["device_seconds"] == pytest.approx(0.75)
    assert s["replicas"]["r1"]["acme"]["device_seconds"] == pytest.approx(0.25)


def test_usage_switchboard():
    reg = MetricsRegistry()
    assert usage_mod.get_meter() is None
    m1 = usage_mod.enable_usage(registry=reg)
    assert usage_mod.enable_usage() is m1
    assert usage_mod.usage_enabled()
    assert usage_mod.disable_usage() is m1
    assert usage_mod.get_meter() is None


# --------------------------------------------------------------------- #
# serving integration: batcher feeds the meter, engine counts compiles,
# steady state stays recompile-free with both instruments on
# --------------------------------------------------------------------- #


def _tiny_serving(rng, registry, tenants=("acme", "globex")):
    from dist_svgd_tpu.serving.batcher import MicroBatcher
    from dist_svgd_tpu.serving.engine import PredictiveEngine

    engines = {
        t: PredictiveEngine(
            "logreg",
            rng.normal(size=(32, 5)).astype(np.float32),
            min_bucket=8, max_bucket=8, registry=registry, tenant=t)
        for t in tenants
    }
    batcher = MicroBatcher(
        lambda x, tenant=None: engines[tenant].predict(x),
        max_batch=8, max_wait_ms=0.5, registry=registry)
    return engines, batcher


def test_serving_meters_tenants_and_stays_compile_free(rng):
    """End to end at test size: warmed engines behind one batcher, BOTH
    instruments on — per-tenant ledgers match the submitted work, tenant
    device-seconds sum to the batcher's measured dispatch wall, and the
    retrace sentry holds the window at zero compiles."""
    from jaxlint import retrace_sentry

    reg = MetricsRegistry()
    engines, batcher = _tiny_serving(rng, reg)
    try:
        for eng in engines.values():
            eng.warmup()
        x = rng.normal(size=(4, 4)).astype(np.float32)
        batcher.submit(x, tenant="acme").result(timeout=10)  # settle

        usage_before = usage_mod.usage_summary(reg)
        profile_mod.enable_profiler(registry=reg)
        usage_mod.enable_usage(registry=reg)
        try:
            with retrace_sentry("cost test window") as sentry:
                futs = [batcher.submit(x, tenant=t)
                        for _ in range(6) for t in ("acme", "globex")]
                for f in futs:
                    f.result(timeout=10)
        finally:
            profile_mod.disable_profiler()
            usage_mod.disable_usage()

        s = usage_mod.usage_summary(reg)
        for t in ("acme", "globex"):
            before = usage_before["tenants"].get(t, {})
            assert (s["tenants"][t]["requests"]
                    - before.get("requests", 0)) == 6
            assert (s["tenants"][t]["rows"] - before.get("rows", 0)) == 24
            assert s["tenants"][t]["device_seconds"] > 0.0
            assert s["tenants"][t]["compiles"] == before.get("compiles", 0)
        # profiler saw the same dispatches, attributed to the plan label
        prog = profile_mod.summary(reg, "serve.")
        assert sum(r["dispatches"] for r in prog.values()) > 0
        assert sum(r["rows"] for r in prog.values()) > 0
        if sentry.supported:
            assert sentry.compiles == 0
    finally:
        batcher.close()


def test_engine_compile_miss_lands_in_ledger(rng):
    """A cold bucket with metering on books one compile to the engine's
    tenant."""
    from dist_svgd_tpu.serving.engine import PredictiveEngine

    reg = MetricsRegistry()
    eng = PredictiveEngine(
        "logreg", rng.normal(size=(16, 4)).astype(np.float32),
        min_bucket=4, max_bucket=4, registry=reg, tenant="cold")
    usage_mod.enable_usage(registry=reg)
    try:
        eng.predict(rng.normal(size=(2, 3)).astype(np.float32))
    finally:
        usage_mod.disable_usage()
    assert usage_mod.usage_summary(reg)["tenants"]["cold"]["compiles"] >= 1


def test_server_usage_route(rng):
    """/usage answers the meter's summary (metering flag + tenants) over
    the server's own registry."""
    import urllib.request

    from dist_svgd_tpu.serving import PredictionServer
    from dist_svgd_tpu.serving.engine import PredictiveEngine

    eng = PredictiveEngine(
        "logreg", rng.normal(size=(16, 4)).astype(np.float32),
        min_bucket=4, max_bucket=8, tenant="acme")
    with PredictionServer(eng, port=0, max_batch=8, max_wait_ms=1.0) as srv:
        usage_mod.enable_usage(registry=srv.registry)
        try:
            body = json.dumps(
                {"inputs": rng.normal(size=(2, 3)).tolist()}).encode()
            req = urllib.request.Request(
                f"{srv.url}/predict", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 200
            with urllib.request.urlopen(
                    f"{srv.url}/usage", timeout=10) as resp:
                doc = json.loads(resp.read())
        finally:
            usage_mod.disable_usage()
    assert doc["metering"] is True
    # HTTP /predict carries no tenant: the batch books to the default
    # row; the engine's cold-bucket compile books to its own tenant
    row = doc["tenants"][usage_mod.DEFAULT_TENANT]
    assert row["requests"] >= 1
    assert row["rows"] >= 2
    assert row["device_seconds"] > 0.0
    assert doc["tenants"]["acme"]["compiles"] >= 1


def test_model_registry_usage_reads_meter_registry(rng):
    from dist_svgd_tpu.serving.registry import ModelRegistry

    reg = MetricsRegistry()
    mr = ModelRegistry(metrics=MetricsRegistry())
    meter = usage_mod.enable_usage(registry=reg)
    try:
        meter.record_batch(tenant="acme", generation=None, rows=2,
                           device_s=0.01, queue_s=0.0, requests=1)
        doc = mr.usage()
        assert doc["metering"] is True
        assert doc["tenants"]["acme"]["rows"] == 2
    finally:
        usage_mod.disable_usage()
    doc = mr.usage()  # meter off: falls back to its own (empty) registry
    assert doc["metering"] is False
    assert doc["tenants"] == {}


# --------------------------------------------------------------------- #
# telemetry history ring
# --------------------------------------------------------------------- #


def test_history_ring_prunes_and_resumes_seq(tmp_path):
    root = str(tmp_path / "hist")
    hist = TelemetryHistory(root, capacity=3)
    for _ in range(5):
        hist.append({"format": "svgd-telemetry-history-1", "window": {}})
    assert len(hist) == 3
    seqs = [int(os.path.basename(p)[10:18]) for p in hist.paths()]
    assert seqs == [2, 3, 4]  # oldest pruned, numbering monotone
    # a restarted ring re-seats itself after the survivors
    hist2 = TelemetryHistory(root, capacity=3)
    path = hist2.append({"window": {}})
    assert os.path.basename(path) == "telemetry_00000005.json"
    assert [r["seq"] for r in hist2.records()] == [3, 4, 5]


def test_recorder_windows_and_reset_clamp(tmp_path):
    """record_once writes window DELTAS (first record cumulative with
    interval 0), inheriting dump_delta's counter reset-clamp."""
    reg = MetricsRegistry()
    ctr = reg.counter("svgd_test_total", "t")
    clock = iter([100.0, 160.0, 220.0]).__next__
    rec = HistoryRecorder(reg, str(tmp_path / "h"), interval_s=60.0,
                          clock=clock)

    ctr.inc(5)
    r0 = rec.record_once()
    assert r0["interval_s"] == 0.0
    ctr.inc(3)
    r1 = rec.record_once()
    assert r1["interval_s"] == pytest.approx(60.0)

    records = rec.history.records()
    vals = series_values(records, "svgd_test_total", labels={})
    assert vals == [5.0, 3.0]  # cumulative first, then the window delta

    # a counter reset (restart) clamps to a zero window, never negative
    reg._metrics["svgd_test_total"]._series.clear()
    ctr.inc(1)
    r2 = rec.record_once()
    vals = series_values(rec.history.records(), "svgd_test_total", labels={})
    assert vals[-1] == 0.0
    assert r2["interval_s"] == pytest.approx(60.0)


def test_recorder_maybe_record_honours_interval(tmp_path):
    reg = MetricsRegistry()
    rec = HistoryRecorder(reg, str(tmp_path / "h"), interval_s=30.0,
                          clock=lambda: 0.0)
    assert rec.maybe_record(now=0.0) is not None
    assert rec.maybe_record(now=10.0) is None
    assert rec.maybe_record(now=31.0) is not None
    assert len(rec.history) == 2


def test_series_values_histogram_stats(tmp_path):
    reg = MetricsRegistry()
    hist = reg.histogram("svgd_test_seconds", "t")
    rec = HistoryRecorder(reg, str(tmp_path / "h"), clock=lambda: 0.0)
    for v in (0.01, 0.01, 0.02, 0.04):
        hist.observe(v)
    rec.record_once()
    records = rec.history.records()
    assert list_series(records) == [("svgd_test_seconds", "histogram", {})]
    assert series_values(records, "svgd_test_seconds",
                         stat="count") == [4.0]
    assert series_values(records, "svgd_test_seconds",
                         stat="sum") == [pytest.approx(0.08)]
    assert series_values(records, "svgd_test_seconds",
                         stat="mean") == [pytest.approx(0.02)]
    (p99,) = series_values(records, "svgd_test_seconds", stat="p99")
    live = hist.quantile(0.99)
    assert p99 == pytest.approx(live)


# --------------------------------------------------------------------- #
# anomaly report: deterministic fixture verdicts + CLI exit codes
# --------------------------------------------------------------------- #


def _write_fixture_history(root, gauge_values):
    """A history whose svgd_test_gauge traces gauge_values, one record
    per window, with a constant co-recorded counter."""
    reg = MetricsRegistry()
    g = reg.gauge("svgd_test_gauge", "t")
    c = reg.counter("svgd_test_total", "t")
    clock = iter(float(60 * i) for i in range(len(gauge_values))).__next__
    rec = HistoryRecorder(reg, root, interval_s=60.0, clock=clock)
    for v in gauge_values:
        g.set(v)
        c.inc(100)
        rec.record_once()
    return rec.history


CLEAN = [10.0, 10.2, 9.9, 10.1, 10.0, 9.8, 10.1, 10.0, 9.9, 10.2]
STEPPED = CLEAN[:5] + [v + 20.0 for v in CLEAN[5:]]


def test_detect_step_change_fixture_verdicts():
    from anomaly_report import detect_step_change

    assert detect_step_change(CLEAN) is None
    hit = detect_step_change(STEPPED)
    assert hit is not None
    assert hit["split_index"] == 5
    assert hit["shift"] == pytest.approx(20.0, rel=0.05)
    # deterministic: same fixture, same verdict
    assert detect_step_change(STEPPED) == detect_step_change(STEPPED)


def test_analyze_records_flags_injected_step_only(tmp_path):
    from anomaly_report import analyze_records

    clean = _write_fixture_history(str(tmp_path / "clean"), CLEAN).records()
    stepped = _write_fixture_history(
        str(tmp_path / "step"), STEPPED).records()

    assert analyze_records(clean)["anomalies"] == []
    report = analyze_records(stepped)
    assert [a["metric"] for a in report["anomalies"]] == ["svgd_test_gauge"]
    assert report["anomalies"][0]["split_index"] == 5
    # the flat co-recorded counter stays silent even under --rate
    report = analyze_records(stepped, rate=True)
    assert [a["metric"] for a in report["anomalies"]] == ["svgd_test_gauge"]


def test_anomaly_report_cli_exit_codes(tmp_path, capsys):
    from anomaly_report import main as anomaly_main

    clean_dir = str(tmp_path / "clean")
    step_dir = str(tmp_path / "step")
    _write_fixture_history(clean_dir, CLEAN)
    _write_fixture_history(step_dir, STEPPED)

    assert anomaly_main([clean_dir]) == 0
    assert anomaly_main([step_dir]) == 1
    out = json.loads(capsys.readouterr().out.splitlines()[-1]) \
        if anomaly_main([step_dir, "--json"]) == 1 else None
    assert out and out["anomalies"][0]["metric"] == "svgd_test_gauge"
    assert anomaly_main([str(tmp_path / "missing")]) == 2
    assert anomaly_main([str(tmp_path)]) == 2  # dir without records


# --------------------------------------------------------------------- #
# cost drill at test size + row gates
# --------------------------------------------------------------------- #


def test_cost_drill_row_and_accounting(rng):
    import cost_drill

    row = cost_drill.run_drill(
        tenants=(("a", 256), ("b", 128)), n_features=8, max_batch=8,
        requests=24, clients=2, ab_rounds=0, history_windows=2)
    assert row["metric"] == "cost_attribution"
    # the accounting identity holds at any size (same measurement both
    # sides); coverage does NOT — it needs the compute-dominant sizing
    # the full drill uses, so only pin it is a sane fraction here
    assert row["tenant_sum_err_frac"] < 0.01
    assert 0.0 < row["coverage"] <= 1.0
    assert row["recompiles"] == 0
    if row["sentry_supported"]:
        assert row["sentry_compiles"] == 0
    assert row["requests"] == 24
    assert set(row["tenant_device_s"]) == {"a", "b"}
    assert row["tenant_device_s"]["a"] > 0.0
    assert row["history_records"] == 3  # baseline + one per segment
    assert row["profiler_overhead_frac"] == 0.0  # ab_rounds=0
    assert any(p["label"].startswith("serve.")
               for p in row["top_programs"])


def test_cost_drill_row_ok_gates():
    import cost_drill

    good = {"coverage": 0.97, "tenant_sum_err_frac": 0.002,
            "recompiles": 0, "sentry_compiles": 0, "sentry_supported": True}
    ok, why = cost_drill.row_ok(good)
    assert ok and why == []
    for bad, frag in (
            ({**good, "coverage": 0.90}, "coverage"),
            ({**good, "tenant_sum_err_frac": 0.05}, "sum"),
            ({**good, "recompiles": 2}, "recompile"),
            ({**good, "sentry_compiles": 1}, "sentry")):
        ok, why = cost_drill.row_ok(bad)
        assert not ok and any(frag in w for w in why)
    # an unsupported sentry doesn't fail the row on its own
    ok, _ = cost_drill.row_ok(
        {**good, "sentry_supported": False, "sentry_compiles": 3})
    assert ok


# --------------------------------------------------------------------- #
# fleet_status cost columns + trace_report --programs
# --------------------------------------------------------------------- #


def test_fleet_status_cost_rates():
    import fleet_status

    first = {"tenants": {"acme": {"requests_total": 100,
                                  "device_seconds_total": 5.0,
                                  "usage_rows_total": 1000}}}
    second = {"tenants": {"acme": {"requests_total": 140,
                                   "device_seconds_total": 6.0,
                                   "usage_rows_total": 1400},
                          "new": {"requests_total": 10}}}
    rates = fleet_status.derive_rates(first, second, 2.0)
    assert rates["acme"]["rps"] == pytest.approx(20.0)
    assert rates["acme"]["device_s_per_s"] == pytest.approx(0.5)
    assert rates["acme"]["rows_per_s"] == pytest.approx(200.0)
    # a tenant absent from the first poll has no window yet
    assert rates["new"]["rps"] is None
    assert rates["new"]["device_s_per_s"] is None

    u1 = {"replicas": {"r0": {"acme": {"device_seconds": 1.0, "rows": 100},
                              "beta": {"device_seconds": 1.0, "rows": 100}}}}
    u2 = {"replicas": {"r0": {"acme": {"device_seconds": 1.5, "rows": 300},
                              "beta": {"device_seconds": 1.5, "rows": 100}}}}
    rr = fleet_status.derive_replica_rates(u1, u2, 2.0)
    assert rr["r0"]["device_s_per_s"] == pytest.approx(0.5)
    assert rr["r0"]["rows_per_s"] == pytest.approx(100.0)
    assert fleet_status.derive_replica_rates(None, u2, 2.0) == {}


def test_trace_report_programs_view(rng, tmp_path, capsys):
    """--programs renders the top-programs table off a saved registry
    dump (and off a history directory's summed windows)."""
    import trace_report

    reg = MetricsRegistry()
    fn = _compiled_double("serve.tiny")
    x = jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32))
    fn(x)
    profile_mod.enable_profiler(registry=reg)
    try:
        fn(x)
        fn(x)
    finally:
        profile_mod.disable_profiler()

    dump_path = str(tmp_path / "dump.json")
    with open(dump_path, "w") as fh:
        json.dump(reg.dump(), fh)
    report = trace_report.program_rows(
        trace_report.load_program_dumps(dump_path))
    (prog,) = report["programs"]
    assert prog["label"] == "serve.tiny"
    assert prog["dispatches"] == 2
    assert prog["rows"] == 8
    assert prog["share"] == pytest.approx(1.0)
    assert report["total_seconds"] > 0.0

    assert trace_report.main(["--programs", dump_path]) == 0
    out = capsys.readouterr().out
    assert "serve.tiny" in out

    # history-directory input: windows sum
    hist_dir = str(tmp_path / "hist")
    rec = HistoryRecorder(reg, hist_dir, clock=lambda: 0.0)
    rec.record_once()
    report = trace_report.program_rows(
        trace_report.load_program_dumps(hist_dir))
    assert report["programs"][0]["dispatches"] == 2
