"""Adaptive capacity (round 18): the batcher's live-retune seams
(``set_lanes`` / ``set_max_wait_ms`` / quota modes), the SLO window
accessors, and the ``serving/autoscale.py`` controller — every decision
path driven through the injectable clock, no real waiting beyond worker
scheduling.
"""

import threading
import time

import numpy as np
import pytest

from dist_svgd_tpu.serving import (
    AutoscaleController,
    AutoscalePolicy,
    MicroBatcher,
    ModelRegistry,
    Overloaded,
    PredictionServer,
    PredictiveEngine,
)
from dist_svgd_tpu.telemetry import metrics as _metrics
from dist_svgd_tpu.telemetry.slo import (
    CounterWindow,
    HistogramWindow,
    bucket_frac_over,
    bucket_quantile,
    default_serving_slos,
)


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _echo(x):
    return {"y": np.asarray(x).sum(axis=1, keepdims=True)}


def _slow_echo(delay_s):
    def dispatch(x):
        time.sleep(delay_s)
        return _echo(x)

    return dispatch


# --------------------------------------------------------------------- #
# batcher live-retune seams


def test_set_lanes_grows_and_retires_under_load():
    """set_lanes spawns workers live; shrinking retires the high lanes
    (their threads exit) while requests keep resolving; regrowing
    respawns fresh threads for the same lane ids."""
    reg = _metrics.MetricsRegistry()
    b = MicroBatcher(_slow_echo(0.002), max_batch=8, max_wait_ms=1.0,
                     max_queue_rows=128, registry=reg)
    stop, errs = [False], []

    def pound():
        while not stop[0]:
            try:
                b.submit(np.ones((2, 3), np.float32)).result(timeout=10)
            except Exception as e:  # pragma: no cover - diagnostic
                errs.append(e)
                return

    threads = [threading.Thread(target=pound) for _ in range(6)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.05)
        assert b.set_lanes(3) == 1
        time.sleep(0.15)
        st = b.stats()
        assert st["lanes"] == 3
        assert sum(1 for v in st["lane_batches"].values() if v > 0) >= 2
        b.set_lanes(1)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            alive = [ln for ln, t in b._lane_threads.items() if t.is_alive()]
            if alive == [0]:
                break
            time.sleep(0.01)
        assert alive == [0]
        # still serving after retirement
        b.submit(np.ones((2, 3), np.float32)).result(timeout=5)
        b.set_lanes(2)
        time.sleep(0.1)
        alive = sorted(ln for ln, t in b._lane_threads.items()
                       if t.is_alive())
        assert alive == [0, 1]
    finally:
        stop[0] = True
        for t in threads:
            t.join(timeout=5)
        b.close(drain=True)
    assert not errs
    assert reg.gauge("svgd_serve_lanes").value(
        batcher=b.metrics_instance) == 2
    with pytest.raises(ValueError):
        b.set_lanes(0)


def test_set_max_wait_live_and_gauge():
    b = MicroBatcher(_echo, max_batch=8, max_wait_ms=4.0,
                     max_queue_rows=64, registry=_metrics.MetricsRegistry(),
                     autostart=False)
    assert b.max_wait_ms == 4.0
    assert b.set_max_wait_ms(1.0) == 4.0
    assert b.max_wait_ms == 1.0
    assert b.registry.gauge("svgd_serve_max_wait_ms").value(
        batcher=b.metrics_instance) == 1.0
    with pytest.raises(ValueError):
        b.set_max_wait_ms(-1.0)
    b.start()
    b.close(drain=True)


def test_retry_after_reads_live_knobs_at_shed_time():
    """Round-18 regression pin: the Overloaded drain estimate must
    describe the batcher as it runs NOW — after a set_max_wait_ms or
    set_lanes retune, the next shed's Retry-After reflects the live
    window, queue depth, and lane count (a stale construction-time hint
    would mis-steer every backpressure-honoring client)."""
    b = MicroBatcher(_echo, max_batch=4, max_wait_ms=10.0,
                     max_queue_rows=8, autostart=False,
                     registry=_metrics.MetricsRegistry())
    b.submit(np.zeros((8, 3), np.float32))  # fill: workers never started
    with pytest.raises(Overloaded) as ei:
        b.submit(np.zeros((1, 3), np.float32))
    # 8 rows = 2 batches, 1 lane -> (1 + 2) * 10 ms (the round-15 pin)
    assert ei.value.retry_after_s == pytest.approx(0.030)
    b.set_max_wait_ms(2.0)
    with pytest.raises(Overloaded) as ei:
        b.submit(np.zeros((1, 3), np.float32))
    assert ei.value.retry_after_s == pytest.approx(0.006)  # live window
    b.set_lanes(2)  # not started: no threads spawn, but the estimate
    # honors the lane target (2 batches drain in 1 window across 2 lanes)
    with pytest.raises(Overloaded) as ei:
        b.submit(np.zeros((1, 3), np.float32))
    assert ei.value.retry_after_s == pytest.approx(0.004)
    assert not any(t.is_alive() for t in b._lane_threads.values())
    b.start()
    b.close(drain=True)


def test_admission_quota_mode():
    """'admission' refuses an over-quota tenant at submit time with queue
    room to spare (counted as a quota shed); 'overflow' (default)
    admits the same request — the round-14 inert-until-full contract is
    unchanged until a controller opts in."""
    quotas = {"hog": 8}
    b = MicroBatcher(lambda x, tenant=None: _echo(x), max_batch=8,
                     max_wait_ms=1.0, max_queue_rows=64, quotas=quotas,
                     autostart=False, registry=_metrics.MetricsRegistry())
    assert b.quota_mode == "overflow"
    b.submit(np.zeros((8, 3), np.float32), tenant="hog")  # at quota, queued
    # overflow mode: queue has room -> over-quota submit still admitted
    b.submit(np.zeros((4, 3), np.float32), tenant="hog")
    assert b.tenant_queued_rows("hog") == 12
    assert b.set_quota_mode("admission") == "overflow"
    with pytest.raises(Overloaded) as ei:
        b.submit(np.zeros((1, 3), np.float32), tenant="hog")
    assert "admission-enforced" in str(ei.value)
    assert ei.value.retry_after_s > 0
    # under-quota tenants and tenant-less requests are untouched
    b.submit(np.zeros((2, 3), np.float32), tenant="polite")
    b.submit(np.zeros((2, 3), np.float32))
    assert b.stats()["quota_sheds"]["hog"] == 1
    with pytest.raises(ValueError):
        b.set_quota_mode("bogus")
    b.set_quota_mode("overflow")
    b.start()
    b.close(drain=True)


# --------------------------------------------------------------------- #
# SLO window accessors


def test_bucket_helpers():
    bounds = [0.01, 0.1, 1.0]
    counts = [10, 80, 10, 0]
    assert bucket_frac_over(bounds, counts, 1.0) == pytest.approx(0.0)
    assert bucket_frac_over(bounds, counts, 0.01) == pytest.approx(0.9)
    # interpolated: halfway through the middle bucket
    assert bucket_frac_over(bounds, counts, 0.055) == pytest.approx(
        1.0 - (10 + 40) / 100)
    assert bucket_quantile(bounds, counts, 0.5) == pytest.approx(0.055)
    assert bucket_frac_over(bounds, [0, 0, 0, 0], 0.5) == 0.0
    assert bucket_quantile(bounds, [0, 0, 0, 5], 0.99) == pytest.approx(1.0)


def test_histogram_and_counter_windows_are_deltas():
    reg = _metrics.MetricsRegistry()
    h = reg.histogram("svgd_serve_request_latency_seconds", "t")
    c = reg.counter("svgd_serve_shed_total", "t")
    hw = HistogramWindow(reg, "svgd_serve_request_latency_seconds")
    cw = CounterWindow(reg, "svgd_serve_shed_total")
    for _ in range(10):
        h.observe(0.005)
    c.inc(3)
    w = hw.poll(threshold_s=0.1)
    assert w["count"] == 10 and w["frac_over"] == pytest.approx(0.0)
    assert cw.poll() == 3.0
    # second poll sees only the delta
    for _ in range(4):
        h.observe(0.5)
    w = hw.poll(threshold_s=0.1)
    assert w["count"] == 4
    assert w["frac_over"] == pytest.approx(1.0)
    assert w["p99_s"] > 0.1
    assert cw.poll() == 0.0
    # a controller's windows never disturb the /slo engine's own windows
    slo = default_serving_slos(reg, p99_ms=100.0)
    doc = slo.evaluate()
    assert doc["objectives"]["serve_p99"]["window_count"] == 14


def test_slo_engine_mirror_off_and_burn_accessors():
    reg = _metrics.MetricsRegistry()
    h = reg.histogram("svgd_serve_request_latency_seconds", "t")
    for _ in range(20):
        h.observe(0.5)  # far over the objective
    mirrored = default_serving_slos(reg, p99_ms=10.0)
    silent = default_serving_slos(reg, p99_ms=10.0, mirror_metrics=False)
    assert silent.last is None and silent.burn_rates() == {}
    d1 = mirrored.evaluate()
    d2 = silent.evaluate()
    assert d1["status"] == d2["status"] == "breach"
    assert silent.last is d2
    assert silent.burn_rates()["serve_p99"] > 1.0
    # only the mirroring engine wrote verdict series
    breaches = reg.counter("svgd_slo_breaches_total")
    assert breaches.value(slo="serve_p99") == 1.0


# --------------------------------------------------------------------- #
# controller decision paths (injectable clock, explicit step())


def _make_controller(policy=None, **kw):
    reg = _metrics.MetricsRegistry()
    bat = MicroBatcher(_echo, max_batch=8, max_wait_ms=2.0,
                       max_queue_rows=100, registry=reg, autostart=False)
    clock = [0.0]
    c = AutoscaleController(
        bat, metrics=reg,
        policy=policy or AutoscalePolicy(
            lanes_max=4, max_wait_ms_max=16.0, p99_target_ms=50.0,
            cooldown_s=1.0, up_consecutive=1, down_consecutive=3),
        clock=lambda: clock[0], **kw)
    hist = reg.histogram("svgd_serve_request_latency_seconds", "t")
    return c, bat, hist, clock


def test_scale_up_on_burn_then_bounded():
    c, bat, hist, clock = _make_controller()
    for _ in range(50):
        hist.observe(0.005)
    r = c.step()
    assert not r["overload"] and r["actions"] == []
    # sustained burn scales up one notch per cooldown, to the bounds
    for i in range(12):
        clock[0] += 1.1
        for _ in range(50):
            hist.observe(0.300)
        c.step()
    assert bat.lanes == 4 and bat.max_wait_ms == 16.0  # bounded, no runaway
    st = c.status()
    assert st["bounds"] == {"lanes": [1, 4], "max_wait_ms": [2.0, 16.0]}
    assert st["actions"] >= 2
    bat.start()
    bat.close(drain=True)


def test_cooldown_blocks_immediate_repeat():
    c, bat, hist, clock = _make_controller()
    for _ in range(50):
        hist.observe(0.300)
    r = c.step()
    assert r["overload"] and any("lanes" in a for a in r["actions"])
    for _ in range(50):
        hist.observe(0.300)
    r = c.step()  # same instant: cooldown holds
    assert r["overload"] and r["actions"] == []
    bat.start()
    bat.close(drain=True)


def test_hysteresis_no_flap_and_baseline_floor():
    """Scale-down needs down_consecutive calm windows; an in-between
    window resets the streak; scale-down stops at the construction
    baseline, not the absolute minimum."""
    c, bat, hist, clock = _make_controller()
    reqs = c.metrics.counter("svgd_serve_requests_total", "t")
    # drive up to lanes 2 / wait 4
    reqs.inc(500)
    for _ in range(50):
        hist.observe(0.300)
    clock[0] += 1.1
    c.step()
    assert bat.lanes == 2
    # demand released, quiet: calm windows accumulate the down streak
    for i in range(2):
        clock[0] += 1.1
        reqs.inc(10)
        for _ in range(5):
            hist.observe(0.004)
        r = c.step()
        assert r["calm"]
        assert r["actions"] == []  # streak not yet at down_consecutive
    # a boundary window: demand back near the overload level while burn
    # sits between the thresholds (2/301 over the 50 ms target -> ~0.66)
    # and the p99 exceeds the window floor — neither overload nor calm
    clock[0] += 1.1
    reqs.inc(450)
    for _ in range(295):
        hist.observe(0.004)
    for _ in range(4):
        hist.observe(0.020)
    for _ in range(2):
        hist.observe(0.060)
    r = c.step()
    assert not r["overload"] and not r["calm"], r
    assert r["actions"] == []
    # the reset means the next TWO calm windows still do not act
    for i in range(2):
        clock[0] += 1.1
        reqs.inc(10)
        for _ in range(5):
            hist.observe(0.004)
        r = c.step()
        assert r["actions"] == [], r
    # third consecutive calm window acts
    clock[0] += 1.1
    reqs.inc(10)
    for _ in range(5):
        hist.observe(0.004)
    r = c.step()
    assert any("lanes 2->1" in a for a in r["actions"])
    assert bat.lanes == 1
    # already at baseline: further calm never goes below
    for i in range(5):
        clock[0] += 1.1
        for _ in range(5):
            hist.observe(0.004)
        c.step()
    assert bat.lanes == 1 and bat.max_wait_ms == 2.0
    bat.start()
    bat.close(drain=True)


def test_demand_guard_holds_wide_window_while_burst_serves_well():
    """A wide window serving a burst WELL has a quiet burn — the demand
    guard must keep the provisioning until the offered rate actually
    falls (and release within a few steps once it does)."""
    c, bat, hist, clock = _make_controller()
    reqs = c.metrics.counter("svgd_serve_requests_total", "t")
    # overload at high request rate
    for _ in range(2):
        clock[0] += 1.1
        reqs.inc(500)
        for _ in range(50):
            hist.observe(0.300)
        c.step()
    assert bat.max_wait_ms > 2.0
    wide = bat.max_wait_ms
    # burst continues at the same rate, now served well (low burn):
    # NOT calm — the guard holds
    for _ in range(6):
        clock[0] += 1.1
        reqs.inc(500)
        for _ in range(50):
            hist.observe(0.004)
        r = c.step()
        assert not r["calm"], r
    assert bat.max_wait_ms == wide
    # demand falls: released after the decay + consecutive calm windows
    for _ in range(10):
        clock[0] += 1.1
        reqs.inc(50)
        for _ in range(5):
            hist.observe(0.004)
        c.step()
    assert bat.max_wait_ms < wide
    bat.start()
    bat.close(drain=True)


def test_shed_signal_is_overload_and_window_floor_is_not():
    c, bat, hist, clock = _make_controller()
    shed = c.metrics.counter("svgd_serve_shed_total", "t")
    shed.inc(3)
    r = c.step()
    assert r["overload"] and r["shed_delta"] == 3.0
    # p99 within 2*window + slack reads as the controller's own floor,
    # never burn-overload — even with the burn rate itself sky-high
    c2, bat2, hist2, clock2 = _make_controller(
        policy=AutoscalePolicy(lanes_max=4, max_wait_ms_max=16.0,
                               p99_target_ms=10.0, cooldown_s=1.0))
    bat2.set_max_wait_ms(16.0)
    for _ in range(50):
        hist2.observe(0.020)  # every obs over the 10 ms target (burn >> 1)
        # but p99 ~25 ms < 2*16 + 10 slack: self-inflicted window latency
    r = c2.step()
    assert r["burn"] > 1.0
    assert not r["overload"] and r["window_floor_ok"]
    for b in (bat, bat2):
        b.start()
        b.close(drain=True)


def test_quota_retune_tightens_and_restores(rng):
    """Overload tightens every quota'd tenant to ceil(base*frac) and
    flips the batcher to admission enforcement; calm restores both."""
    metrics = _metrics.MetricsRegistry()
    reg = ModelRegistry(metrics=metrics, batcher_autostart=False)
    parts = rng.normal(size=(16, 5)).astype(np.float32)
    reg.add_tenant("a", "logreg", particles=parts, quota_rows=10)
    reg.add_tenant("b", "logreg", particles=parts.copy())  # no quota
    clock = [0.0]
    c = AutoscaleController(
        reg.batcher, metrics=metrics, model_registry=reg,
        policy=AutoscalePolicy(p99_target_ms=50.0, cooldown_s=0.0,
                               down_consecutive=2,
                               quota_tighten_frac=0.5),
        clock=lambda: clock[0])
    hist = metrics.histogram("svgd_serve_request_latency_seconds", "t")
    for _ in range(50):
        hist.observe(0.300)
    clock[0] += 1.0
    c.step()
    assert reg.tenant("a").quota_rows == 5
    assert reg.tenant("b").quota_rows is None
    assert reg.batcher.quota_mode == "admission"
    assert c.quota_scale == 0.5
    for _ in range(6):
        clock[0] += 1.0
        for _ in range(3):
            hist.observe(0.002)
        c.step()
    assert reg.tenant("a").quota_rows == 10
    assert reg.batcher.quota_mode == "overflow"
    assert c.quota_scale == 1.0
    reg.close(drain=False)


def test_controller_primes_windows_on_existing_registry():
    """Attached to a registry with history, the first control step judges
    the delta since construction — not the registry's whole past as one
    giant overload window."""
    reg = _metrics.MetricsRegistry()
    h = reg.histogram("svgd_serve_request_latency_seconds", "t")
    shed = reg.counter("svgd_serve_shed_total", "t")
    for _ in range(500):
        h.observe(5.0)  # ancient awful history
    shed.inc(100)
    bat = MicroBatcher(_echo, max_batch=8, max_wait_ms=2.0,
                       max_queue_rows=64, registry=reg, autostart=False)
    c = AutoscaleController(bat, metrics=reg, clock=lambda: 0.0)
    r = c.step()
    assert not r["overload"]
    assert r["shed_delta"] == 0.0 and r["window_count"] == 0
    bat.start()
    bat.close(drain=True)


def test_status_and_server_route(rng):
    """/autoscale serves the controller's status; 404 without one; the
    server lifecycle starts and stops the control thread."""
    import json as _json
    import urllib.error
    import urllib.request

    parts = rng.normal(size=(16, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16)
    srv = PredictionServer(eng, port=0, max_wait_ms=1.0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/autoscale", timeout=10)
        assert ei.value.code == 404
    finally:
        srv.shutdown()

    eng2 = PredictiveEngine("logreg", parts.copy(), min_bucket=4,
                            max_bucket=16)
    srv2 = PredictionServer(eng2, port=0, max_wait_ms=1.0,
                            autoscale=True).start()
    try:
        assert srv2.autoscale._thread is not None  # started with serve
        doc = _json.loads(urllib.request.urlopen(
            srv2.url + "/autoscale", timeout=10).read())
        assert doc["lanes"] == 1
        assert doc["bounds"]["lanes"][1] >= 1
        assert "last_signals" in doc
    finally:
        srv2.shutdown()
    assert srv2.autoscale._thread is None  # stopped on shutdown
