"""Pallas fused φ kernel (ops/pallas_svgd.py) vs the XLA path (ops/svgd.py),
run under the Pallas interpreter on CPU (SURVEY.md §4's
distributed-without-hardware stance, applied to kernels)."""

import numpy as np
import jax.numpy as jnp
import pytest

from dist_svgd_tpu.ops.kernels import RBF
from dist_svgd_tpu.ops.pallas_svgd import phi_pallas
from dist_svgd_tpu.ops.svgd import phi


@pytest.fixture
def rng():
    return np.random.default_rng(41)


@pytest.mark.parametrize(
    "k,m,d",
    [
        (8, 8, 2),       # single tile, tiny
        (50, 37, 3),     # ragged both axes (padding + column mask)
        (40, 100, 55),   # m > tile? no — exercises multi-col padding of d
        (130, 257, 7),   # multiple tiles with ragged edges (bk=bm=128 via min)
    ],
)
def test_phi_pallas_matches_xla(rng, k, m, d):
    y = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    s = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    want = np.asarray(phi(y, x, s, RBF(1.0)))
    got = np.asarray(
        phi_pallas(y, x, s, bandwidth=1.0, block_k=128, block_m=128, interpret=True)
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("k,m,d", [(50, 37, 3), (40, 60, 55)])
def test_phi_pallas_bf16_gram_within_budget(rng, k, m, d):
    """gram_dtype=bfloat16 (both kernel variants): φ stays within the bf16
    error budget of the exact path (measured 4.4e-4 of max|φ| at the
    10k-particle north star on a v5e — docs/notes.md)."""
    y = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    s = jnp.asarray(rng.normal(size=(m, d)), dtype=jnp.float32)
    h = float(2 * d)  # keep kernel values O(1): h=1 underflows at large d
    want = np.asarray(phi(y, x, s, RBF(h)))
    got = np.asarray(
        phi_pallas(y, x, s, bandwidth=h, block_k=128, block_m=128,
                   interpret=True, gram_dtype=jnp.bfloat16)
    )
    assert np.abs(got - want).max() <= 2e-2 * np.abs(want).max()
    with pytest.raises(ValueError, match="gram_dtype"):
        phi_pallas(y, x, s, interpret=True, gram_dtype=jnp.float16)


def test_auto_block_padding_contract():
    """Default tile selection: a single exact tile below the default size
    (zero padding beyond 8-row alignment), halved tiles above it until the
    zero-padding is ~<=10% (docs/notes.md: a 1024 tile pads a k=1250
    vmap-emulated shard lane 64%, measured as a 5.1M vs 7.4M up/s headline
    regression)."""
    from dist_svgd_tpu.ops.pallas_svgd import _auto_block, _round_up

    assert _auto_block(300, 1024) == 304   # single exact tile
    assert _auto_block(1024, 1024) == 1024
    assert _auto_block(1250, 1024) == 256  # 1280 rows (2.4%), not 2048 (64%)
    assert _auto_block(10_000, 1024) == 1024
    for n in (8, 129, 300, 460, 1030, 1250, 4097, 10_000):
        b = _auto_block(n, 1024)
        padded = _round_up(n, min(b, _round_up(n, 8)))
        assert padded <= 1.15 * n + 8, (n, b, padded)


def test_phi_pallas_nondefault_bandwidth(rng):
    y = jnp.asarray(rng.normal(size=(24, 4)), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(24, 4)), dtype=jnp.float32)
    s = jnp.asarray(rng.normal(size=(24, 4)), dtype=jnp.float32)
    want = np.asarray(phi(y, x, s, RBF(2.5)))
    got = np.asarray(phi_pallas(y, x, s, bandwidth=2.5, interpret=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_phi_pallas_self_interaction_svgd_step(rng):
    """A full Jacobi step using the pallas φ equals the XLA step."""
    parts = jnp.asarray(rng.normal(size=(33, 5)), dtype=jnp.float32)
    scores = jnp.asarray(rng.normal(size=(33, 5)), dtype=jnp.float32)
    eps = 0.05
    want = np.asarray(parts + eps * phi(parts, parts, scores, RBF(1.0)))
    got = np.asarray(
        parts + eps * phi_pallas(parts, parts, scores, interpret=True)
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_phi_pallas_preserves_dtype(rng):
    y = jnp.asarray(rng.normal(size=(8, 2)))  # float64 under x64 tests
    x = jnp.asarray(rng.normal(size=(8, 2)))
    s = jnp.asarray(rng.normal(size=(8, 2)))
    out = phi_pallas(y, x, s, interpret=True)
    assert out.dtype == y.dtype
    assert out.shape == y.shape


def test_pallas_available_is_false_on_cpu():
    from dist_svgd_tpu.ops.pallas_svgd import pallas_available

    assert pallas_available() is False


def test_sampler_phi_impl_pallas_matches_xla(rng):
    """Full Sampler runs agree between implementations (forced pallas uses
    the interpreter on CPU)."""
    from dist_svgd_tpu import Sampler
    from dist_svgd_tpu.models.gmm import gmm_logp

    init = jnp.asarray(rng.normal(size=(12, 1)), dtype=jnp.float32)
    ref, _ = Sampler(1, gmm_logp, phi_impl="xla").run(
        12, 10, 0.5, record=False, initial_particles=init
    )
    got, _ = Sampler(1, gmm_logp, phi_impl="pallas").run(
        12, 10, 0.5, record=False, initial_particles=init
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize(
    "exch_p,exch_s,impl",
    [
        (True, True, "gather"),
        (True, False, "gather"),
        (False, False, "gather"),  # partitions
        (True, True, "ring"),
        (True, False, "ring"),
    ],
)
def test_distsampler_phi_impl_pallas_matches_xla(rng, exch_p, exch_s, impl):
    """Every exchange mode × gather/ring produces the same step with the
    pallas φ (interpreter on CPU) as with the XLA φ."""
    from dist_svgd_tpu import DistSampler
    from dist_svgd_tpu.models.gmm import gmm_logp

    S, n, d = 4, 16, 2
    particles = jnp.asarray(rng.normal(size=(n, d)), dtype=jnp.float32)
    logp = lambda th, _: gmm_logp(th)

    def run(phi_impl):
        ds = DistSampler(
            S, logp, None, particles,
            exchange_particles=exch_p, exchange_scores=exch_s,
            include_wasserstein=False, exchange_impl=impl, phi_impl=phi_impl,
        )
        ds.make_step(0.1)
        return np.asarray(ds.make_step(0.1))

    np.testing.assert_allclose(run("pallas"), run("xla"), rtol=2e-5, atol=2e-6)


def test_distsampler_phi_impl_validation(rng):
    from dist_svgd_tpu import DistSampler
    from dist_svgd_tpu.models.gmm import gmm_logp

    particles = jnp.asarray(rng.normal(size=(8, 2)), dtype=jnp.float32)
    logp = lambda th, _: gmm_logp(th)
    with pytest.raises(ValueError, match="unknown phi_impl"):
        DistSampler(4, logp, None, particles, phi_impl="cuda")
    with pytest.raises(ValueError, match="requires an RBF kernel"):
        DistSampler(
            4, logp, lambda a, b: jnp.exp(-jnp.sum((a - b) ** 2)), particles,
            include_wasserstein=False, phi_impl="pallas",
        )


def test_sampler_phi_impl_validation():
    from dist_svgd_tpu import Sampler
    from dist_svgd_tpu.models.gmm import gmm_logp

    with pytest.raises(ValueError, match="unknown phi_impl"):
        Sampler(1, gmm_logp, phi_impl="cuda")
    with pytest.raises(ValueError, match="requires an RBF kernel"):
        Sampler(1, gmm_logp, kernel=lambda a, b: jnp.exp(-jnp.sum((a - b) ** 2)),
                phi_impl="pallas")
    with pytest.raises(ValueError, match="requires update_rule"):
        Sampler(1, gmm_logp, update_rule="gauss_seidel", phi_impl="pallas")


def test_phi_pallas_under_shard_map(rng):
    """The Pallas kernel traced INSIDE shard_map over a real (virtual-CPU)
    mesh — the multi-chip path.  Every other pallas test runs the kernel
    under jit/vmap; this pins the shard_map composition the TPU mesh would
    use (interpreter off-TPU, same tracing)."""
    import jax

    from dist_svgd_tpu import DistSampler
    from dist_svgd_tpu.models.gmm import gmm_logp

    if len(jax.devices()) < 4:
        pytest.skip("needs a 4-device mesh")
    particles = jnp.asarray(rng.normal(size=(32, 2)), dtype=jnp.float32)
    logp = lambda th, _: gmm_logp(th)

    def run(impl):
        ds = DistSampler(
            4, logp, None, particles, include_wasserstein=False,
            phi_impl=impl, mesh="auto",
        )
        assert ds._mesh is not None  # really shard_map, not vmap emulation
        return np.asarray(ds.run_steps(3, 0.05))

    np.testing.assert_allclose(run("pallas"), run("xla"), rtol=2e-5, atol=2e-6)


def test_measured_block_table_lookup():
    """The shape-keyed measured tile defaults (round 5): nearest measured
    regime in log-shape space; far-from-evidence shapes fall back to the
    padding heuristic (None)."""
    from dist_svgd_tpu.ops.pallas_svgd import _measured_block

    # exact ladder points
    assert _measured_block(1_250, 10_000, True) == (256, 1024)
    assert _measured_block(100_000, 100_000, True) == (1024, 1024)
    assert _measured_block(1_250, 10_000, False) == (256, 1024)
    # nearby shapes snap to the nearest regime (an 11k-lane ~ the 12.5k one)
    assert _measured_block(11_000, 90_000, True) == (512, 1024)
    assert _measured_block(9_000, 11_000, True) == (1024, 1024)
    # far from every measured point: no table hit
    assert _measured_block(64, 64, True) is None
    assert _measured_block(64, 64, False) is None
    # big-d table has one regime; a big-d square at 10k² is within reach of
    # the (1250, 10k) lane on the m axis but >4x off on k+m combined? k:
    # log(10000/1250)=2.08, m: 0 -> total 2.08 <= 2*log(4)=2.77 -> snaps
    assert _measured_block(10_000, 10_000, False) == (256, 1024)
