"""Wasserstein/JKO term: LP parity and Sinkhorn fidelity (SURVEY.md §7.3.2)."""

import numpy as np
import jax.numpy as jnp
import pytest

from dist_svgd_tpu.ops.ot import (
    sinkhorn_plan,
    wasserstein_grad_lp,
    wasserstein_grad_sinkhorn,
)

from _oracle import wasserstein_grad as oracle_wgrad


@pytest.fixture
def rng():
    return np.random.default_rng(13)


def test_lp_matches_oracle_square(rng):
    x = rng.normal(size=(5, 2))
    y = rng.normal(size=(5, 2))
    np.testing.assert_allclose(wasserstein_grad_lp(x, y), oracle_wgrad(x, y), atol=1e-8)


def test_lp_matches_oracle_rectangular(rng):
    """m ≠ n — the distributed case (local block vs full previous set)."""
    x = rng.normal(size=(3, 2))
    y = rng.normal(size=(6, 2))
    np.testing.assert_allclose(wasserstein_grad_lp(x, y), oracle_wgrad(x, y), atol=1e-8)


def test_lp_identity_transport(rng):
    """x == y → optimal plan is the identity matching → zero gradient."""
    x = rng.normal(size=(4, 3))
    np.testing.assert_allclose(wasserstein_grad_lp(x, x), np.zeros_like(x), atol=1e-9)


def test_lp_two_point_matching():
    """Hand-checkable: two points, obvious matching, grad_i = (x_i − y_σ(i))/m
    with the uniform 1/m mass on the matched pair."""
    x = np.array([[0.0, 0.0], [10.0, 0.0]])
    y = np.array([[0.5, 0.0], [9.0, 0.0]])
    g = wasserstein_grad_lp(x, y)
    np.testing.assert_allclose(g, (x - y) / 2.0, atol=1e-9)


def test_sinkhorn_marginals(rng):
    x = jnp.asarray(rng.normal(size=(6, 2)))
    y = jnp.asarray(rng.normal(size=(4, 2)))
    plan = np.asarray(sinkhorn_plan(x, y, eps=0.05, iters=500))
    np.testing.assert_allclose(plan.sum(axis=1), np.full(6, 1 / 6), atol=1e-6)
    np.testing.assert_allclose(plan.sum(axis=0), np.full(4, 1 / 4), atol=1e-6)


def test_sinkhorn_approaches_lp(rng):
    """Small relative eps → Sinkhorn gradient ≈ LP gradient."""
    x = rng.normal(size=(6, 2))
    y = rng.normal(size=(6, 2)) + 0.5
    lp = wasserstein_grad_lp(x, y)
    sk = np.asarray(
        wasserstein_grad_sinkhorn(jnp.asarray(x), jnp.asarray(y), eps=0.002, iters=5000)
    )
    np.testing.assert_allclose(sk, lp, atol=0.05)


def test_sinkhorn_tol_early_exit_matches_converged(rng):
    """The while_loop early exit (tol) lands on the same plan as running the
    fixed-count loop to convergence, and still jits."""
    import jax

    x = jnp.asarray(rng.normal(size=(9, 2)))
    y = jnp.asarray(rng.normal(size=(7, 2)) + 0.3)
    full = np.asarray(sinkhorn_plan(x, y, eps=0.05, iters=2000))
    tol = np.asarray(
        jax.jit(lambda a, b: sinkhorn_plan(a, b, eps=0.05, iters=2000, tol=1e-6))(x, y)
    )
    # tol bounds the per-iteration potential change, not the distance to the
    # fixpoint — the geometric tail adds ~delta/(1-rate), hence the margin
    np.testing.assert_allclose(tol, full, atol=1e-4)
    # marginals hold at the exit point too
    np.testing.assert_allclose(tol.sum(axis=1), np.full(9, 1 / 9), atol=1e-5)
    np.testing.assert_allclose(tol.sum(axis=0), np.full(7, 1 / 7), atol=1e-5)


@pytest.mark.parametrize("tol", [None, 1e-2])
def test_sinkhorn_outlier_row_keeps_its_mass(rng, tol):
    """A particle so far from every target that exp(-C_ij/reg) underflows
    f32 across its whole kernel row must still carry its 1/m of plan mass
    (and hence a nonzero W2 gradient).  The c-transform warm start keeps
    the row's best log-kernel entry at 0, so it never starts dead.

    Regression: without the warm start, the clamp-and-absorb walk recovers
    only ~87·reg per absorption and this exact configuration (m=64 with
    x[0] at squared distance ~3200, eps=0.01, iters=400, larger m pushing
    mean(C) and reg down) corrupted the row outright (zero/NaN mass and a
    zero W2 gradient) — including on the DistSampler production path
    (tol=1e-2)."""
    x = np.asarray(rng.normal(size=(64, 2)))
    x[0] = 40.0
    y = jnp.asarray(rng.normal(size=(32, 2)))
    plan = np.asarray(
        sinkhorn_plan(jnp.asarray(x), y, eps=0.01, iters=400, tol=tol)
    )
    assert np.all(np.isfinite(plan))
    np.testing.assert_allclose(plan.sum(axis=1), np.full(64, 1 / 64), atol=1e-4)
    np.testing.assert_allclose(plan.sum(axis=0), np.full(32, 1 / 32), atol=1e-4)
    grad = np.asarray(
        wasserstein_grad_sinkhorn(jnp.asarray(x), y, eps=0.01, iters=400, tol=tol)
    )
    # the outlier's W2 pull is its 1/m of mass times the ~(40,40) offset to
    # the cloud: Σ_j P_0j (x_0 − y_j) ≈ (1/64)·40 ≈ 0.62 per dim
    assert np.all(grad[0] > 0.5)


def test_sinkhorn_warm_start_zero_matches_cold_at_convergence(rng):
    """g_init of zeros (soft-c-transform start) and the default cold start
    (hard-c-transform start) converge to the same plan — different inits,
    one fixpoint."""
    x = jnp.asarray(rng.normal(size=(6, 2)))
    y = jnp.asarray(rng.normal(size=(5, 2)))
    cold = np.asarray(sinkhorn_plan(x, y, eps=0.05, iters=500))
    warm = np.asarray(
        sinkhorn_plan(x, y, eps=0.05, iters=500, g_init=jnp.zeros(5))
    )
    np.testing.assert_allclose(cold, warm, atol=1e-7)


def test_sinkhorn_warm_start_from_optimum_converges_immediately(rng):
    """Warm-starting from a converged solve's own g reproduces that solve's
    plan under the tol exit — the carried dual is a fixpoint, so the exit
    fires on the first block."""
    x = jnp.asarray(rng.normal(size=(8, 2)))
    y = jnp.asarray(rng.normal(size=(6, 2)) + 0.4)
    full, (_, g) = sinkhorn_plan(
        x, y, eps=0.05, iters=2000, return_potentials=True
    )
    warm = np.asarray(
        sinkhorn_plan(x, y, eps=0.05, iters=2000, tol=1e-6, g_init=g)
    )
    np.testing.assert_allclose(warm, np.asarray(full), atol=1e-6)


def test_sinkhorn_warm_start_garbage_init_is_safe(rng):
    """Any g_init — however wrong — yields a finite plan with correct
    marginals: after the soft c-transform f0 update, every row of the
    initial kernel exp((f0+g0−C)/reg) sums to exactly its marginal, so no
    row can start underflowed (the soft-form analog of the cold start's
    max-pinned-at-zero guarantee).  Uses the outlier configuration that
    kills a zero-init run."""
    x = np.asarray(rng.normal(size=(64, 2)))
    x[0] = 40.0
    y = jnp.asarray(rng.normal(size=(32, 2)))
    garbage = jnp.asarray(rng.normal(size=32) * 1e6)
    plan = np.asarray(
        sinkhorn_plan(jnp.asarray(x), y, eps=0.01, iters=400, tol=1e-2,
                      g_init=garbage)
    )
    assert np.all(np.isfinite(plan))
    np.testing.assert_allclose(plan.sum(axis=1), np.full(64, 1 / 64), atol=1e-4)
    np.testing.assert_allclose(plan.sum(axis=0), np.full(32, 1 / 32), atol=1e-4)


def test_grad_sinkhorn_return_g_roundtrip(rng):
    """return_g=True returns the dual that, fed back as g_init, reproduces
    the gradient (the production warm-start loop's invariant)."""
    x = jnp.asarray(rng.normal(size=(7, 3)))
    y = jnp.asarray(rng.normal(size=(7, 3)) + 0.2)
    grad, g = wasserstein_grad_sinkhorn(x, y, eps=0.05, iters=500, return_g=True)
    again = wasserstein_grad_sinkhorn(
        x, y, eps=0.05, iters=500, tol=1e-6, g_init=g
    )
    np.testing.assert_allclose(np.asarray(again), np.asarray(grad), atol=1e-6)


def test_sinkhorn_tol_respects_iteration_cap(rng):
    """tol far below reachable precision: the iters bound still terminates
    the loop and the result equals the fixed-count plan."""
    x = jnp.asarray(rng.normal(size=(5, 2)))
    y = jnp.asarray(rng.normal(size=(5, 2)))
    capped = np.asarray(sinkhorn_plan(x, y, eps=0.05, iters=3, tol=1e-30))
    fixed = np.asarray(sinkhorn_plan(x, y, eps=0.05, iters=3))
    np.testing.assert_allclose(capped, fixed, rtol=1e-12)
