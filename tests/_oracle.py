"""Literal-semantics numpy oracle of the reference algorithm.

Re-implemented from the structural spec in SURVEY.md §3 (per-pair loops,
in-place sweeps, ring ownership, Wasserstein snapshot warts) — NOT copied from
the reference — to serve as the ground truth the fused TPU implementations are
tested against.  Everything here is deliberately slow, loopy float64 numpy.

Semantics encoded:

- RBF kernel k(x,y) = exp(-||x-y||^2), fixed bandwidth 1.
- φ̂(y) = (1/m) Σ_j [ k(x_j,y)·s_j + ∇_{x_j} k(x_j,y) ].
- Gauss–Seidel sweep: particle i's update sees particles < i updated, and
  per-pair scores are evaluated fresh at the interacting particle's *current*
  value.
- Jacobi sweep: scores and kernels all evaluated at pre-update values
  (the TPU-native mode — used to validate the vectorised step exactly).
- Distributed: S ranks, contiguous particle blocks and data slices;
  `all_particles` (gather + N_g/N_l-scaled local scores), `all_scores`
  (gather + summed local scores, unscaled), `partitions` (ring ownership
  rotation, block-local interactions).
- Wasserstein/JKO: discrete-OT LP between current owned particles and the
  rank's previous-snapshot set; delta += h·w_grad; snapshot rules per mode,
  including the exchanged-mode "own block fresh, other blocks stale" wart.
"""

from __future__ import annotations

import numpy as np
import scipy.optimize


def rbf(x, y):
    d = x - y
    return float(np.exp(-np.dot(d, d)))


def ksd_u_stat(particles, scores, bandwidth=1.0):
    """Kernelized Stein discrepancy, squared, as the U-statistic
    ``1/(n(n−1)) Σ_{i≠j} u_p(x_i, x_j)`` with the repo's RBF convention
    ``k(x, y) = exp(−‖x−y‖²/h)`` (Liu, Lee & Jordan 2016, eq. per-pair
    form) — deliberately loopy float64, the diagnostics ground truth."""
    x = np.asarray(particles, dtype=np.float64)
    s = np.asarray(scores, dtype=np.float64)
    n, d = x.shape
    beta = 2.0 / bandwidth
    total = 0.0
    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            r = x[i] - x[j]
            sq = float(np.dot(r, r))
            k = np.exp(-sq / bandwidth)
            total += k * (
                np.dot(s[i], s[j]) + beta * np.dot(s[i], r)
                - beta * np.dot(s[j], r) + beta * d - beta * beta * sq
            )
    return total / (n * (n - 1))


def kernel_ess(particles, bandwidth=1.0):
    """Kernel-matrix effective sample size: the participation ratio
    ``(tr K)² / ‖K‖_F² = n² / Σᵢⱼ Kᵢⱼ²`` of the Gram matrix — n for
    spread particles (K ≈ I), 1 for a collapsed set (K ≈ 𝟙𝟙ᵀ)."""
    x = np.asarray(particles, dtype=np.float64)
    n = x.shape[0]
    k2 = 0.0
    for i in range(n):
        for j in range(n):
            r = x[i] - x[j]
            k2 += np.exp(-np.dot(r, r) / bandwidth) ** 2
    return n * n / k2


def drbf_dx(x, y):
    """∇_x k(x, y) for the bandwidth-1 RBF."""
    return -2.0 * (x - y) * rbf(x, y)


def phi_hat(y, interacting, pair_score):
    """φ̂(y); `pair_score(j, xj)` returns the score attributed to interacting
    particle j at its current value xj (already scaled per the mode)."""
    total = np.zeros_like(y)
    for j, xj in enumerate(interacting):
        total += drbf_dx(xj, y)
        total += rbf(xj, y) * pair_score(j, xj)
    return total / len(interacting)


def gauss_seidel_sweep(particles, score_of, step_size):
    """Reference single-device sweep: in-place, fresh per-pair scores."""
    parts = np.array(particles, dtype=np.float64)
    for i in range(parts.shape[0]):
        delta = phi_hat(parts[i], parts, lambda j, xj: score_of(xj))
        parts[i] = parts[i] + step_size * delta
    return parts


def jacobi_sweep(particles, score_of, step_size):
    """Simultaneous update; all quantities at pre-update values."""
    parts = np.array(particles, dtype=np.float64)
    scores = [score_of(p) for p in parts]
    new = np.empty_like(parts)
    for i in range(parts.shape[0]):
        delta = phi_hat(parts[i], parts, lambda j, xj: scores[j])
        new[i] = parts[i] + step_size * delta
    return new


def wasserstein_grad(particles, previous):
    """Discrete-OT LP gradient, built the loopy way the reference builds it."""
    x = np.asarray(particles, dtype=np.float64)
    y = np.asarray(previous, dtype=np.float64)
    m, d = x.shape
    n = y.shape[0]
    diffs = np.zeros((m, n, d))
    for i in range(m):
        for j in range(n):
            diffs[i][j] = x[i] - y[j]
    c = np.array([np.dot(diffs[i][j], diffs[i][j]) for i in range(m) for j in range(n)])
    a_eq = np.zeros((m + n, m * n))
    for i in range(m):
        a_eq[i, n * i : n * (i + 1)] = 1
    for j in range(n):
        for k in range(m):
            a_eq[m + j, j + k * n] = 1
    b_eq = np.concatenate([np.full(m, 1.0 / m), np.full(n, 1.0 / n)])
    plan = scipy.optimize.linprog(c, A_eq=a_eq, b_eq=b_eq).x.reshape(m, n)
    return np.sum(plan[:, :, None] * diffs, axis=1)


class RefDistOracle:
    """Simulates the reference's S-rank distributed sampler faithfully.

    `score_of(rank, x)` is the local-data score ∇logp_rank(x) (including any
    prior terms, exactly as each rank's logp closure would compute it).

    `update_rule='jacobi'` evaluates all scores/kernels at pre-update values
    (matches the TPU-native DistSampler exactly); `'gauss_seidel'` replicates
    the reference's in-place sweep.
    """

    def __init__(
        self,
        num_shards,
        score_of,
        particles,
        exchange_particles=True,
        exchange_scores=True,
        include_wasserstein=False,
        score_scale=1.0,
        update_rule="jacobi",
    ):
        assert not (exchange_scores and not exchange_particles)
        self.S = num_shards
        self.score_of = score_of
        self.scale = score_scale
        self.exchange_particles = exchange_particles
        self.exchange_scores = exchange_scores
        self.include_wasserstein = include_wasserstein
        self.update_rule = update_rule

        parts = np.array(particles, dtype=np.float64)
        self.per_shard = parts.shape[0] // num_shards
        self.n = self.per_shard * num_shards
        self.global_particles = parts[: self.n]
        # owner[b] = rank currently updating block b
        self.owner = list(range(num_shards))
        # per-rank previous-particle snapshot for the W2 term
        self.previous = [None] * num_shards

    def _block(self, b):
        s = self.per_shard
        return self.global_particles[b * s : (b + 1) * s]

    def block_of_rank(self, r):
        return self.owner.index(r)

    def make_step(self, step_size, h=1.0):
        S, s = self.S, self.per_shard
        if S > 1 and not self.exchange_particles:
            # ring migration: rank r adopts the block rank r-1 owned
            self.owner = [(r + 1) % S for r in self.owner]

        # per-rank interaction sets and scores, all at post-exchange values
        new_blocks = {}
        for r in range(S):
            b = self.block_of_rank(r)
            own = self._block(b).copy()
            if self.exchange_particles and S >= 1:
                interacting = self.global_particles.copy()
                own_range = (b * s, (b + 1) * s)
            else:
                interacting = own.copy()
                own_range = (0, s)

            if self.exchange_scores and S > 1:
                # summed local-data scores for every interacting particle,
                # computed at pre-update values, no extra scaling
                fixed_scores = [
                    np.sum([self.score_of(rr, p) for rr in range(S)], axis=0)
                    for p in interacting
                ]
                pair_score = lambda j, xj, fs=fixed_scores: fs[j]
            elif self.update_rule == "jacobi":
                pre_scores = [self.scale * self.score_of(r, p) for p in interacting]
                pair_score = lambda j, xj, ps=pre_scores: ps[j]
            else:
                pair_score = lambda j, xj, rr=r: self.scale * self.score_of(rr, xj)

            w_grad = None
            if self.include_wasserstein and self.previous[r] is not None:
                w_grad = wasserstein_grad(own, self.previous[r])

            if self.update_rule == "jacobi":
                frozen = interacting.copy()
                new = own.copy()
                for i in range(s):
                    delta = phi_hat(own[i], frozen, pair_score)
                    if w_grad is not None:
                        delta = delta + h * w_grad[i]
                    new[i] = own[i] + step_size * delta
                new_blocks[b] = (r, new, interacting, own_range)
            else:
                # in-place sweep over the rank's own block inside its view
                view = interacting
                lo, _ = own_range
                for i in range(s):
                    delta = phi_hat(view[lo + i], view, pair_score)
                    if w_grad is not None:
                        delta = delta + h * w_grad[i]
                    view[lo + i] = view[lo + i] + step_size * delta
                new_blocks[b] = (r, view[lo : lo + s].copy(), view, own_range)

        # commit all blocks, then take per-rank previous snapshots
        for b, (r, new, interacting, own_range) in new_blocks.items():
            self.global_particles[b * s : (b + 1) * s] = new
        for b, (r, new, interacting, own_range) in new_blocks.items():
            if not self.include_wasserstein:
                continue
            if self.exchange_particles:
                snap = interacting.copy()
                lo, hi = own_range
                snap[lo:hi] = new  # own block fresh, others stale (the wart)
                self.previous[r] = snap
            else:
                self.previous[r] = new.copy()
        return self.global_particles
