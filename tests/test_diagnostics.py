"""Posterior health observability (telemetry/diagnostics.py, slo.py, the
flight recorder, and their supervisor / serving hooks).

Numerics are pinned against the loopy float64 oracles in ``_oracle.py``
(KSD U-statistic, kernel ESS) at small n; everything else is CPU-shaped
and small-N per the tier-1 budget discipline.
"""

import json
import os
import sys
import tracemalloc
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

# repo root (for tools.jaxlint) and tools/ (for trace_report) — the
# test_telemetry convention
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

import _oracle
from dist_svgd_tpu import telemetry
from dist_svgd_tpu.resilience import GuardConfig, RunSupervisor
from dist_svgd_tpu.resilience.guards import GuardViolation, check_diagnostics
from dist_svgd_tpu.resilience.supervisor import RestartBudgetExhausted
from dist_svgd_tpu.telemetry import diagnostics as diag_mod
from dist_svgd_tpu.telemetry import slo as slo_mod
from dist_svgd_tpu.telemetry.diagnostics import (
    DISABLED,
    DiagnosticsConfig,
    PosteriorDiagnostics,
    ReloadPolicy,
    ensemble_health,
)
from dist_svgd_tpu.telemetry.metrics import MetricsRegistry
from dist_svgd_tpu.telemetry.trace import (
    FlightRecorder,
    install_flight_recorder,
    uninstall_flight_recorder,
)

import dist_svgd_tpu as dt


@pytest.fixture
def recorder(tmp_path):
    rec = install_flight_recorder(FlightRecorder(
        capacity=64, dump_dir=str(tmp_path / "flight"),
        registry=MetricsRegistry()))
    yield rec
    uninstall_flight_recorder()


@pytest.fixture
def rng():
    return np.random.default_rng(3)


# --------------------------------------------------------------------- #
# numerics vs the float64 oracle


def test_ksd_matches_oracle_chunked(rng):
    """Jitted chunked KSD² ≡ the loopy float64 U-statistic (f64 inputs —
    conftest enables x64 — so the comparison is at full precision), with
    a row_chunk that forces padding (14 rows in chunks of 5)."""
    n, d, bw = 14, 3, 1.7
    x = rng.normal(size=(n, d))
    s = -x + 0.1 * rng.normal(size=(n, d))
    want = _oracle.ksd_u_stat(x, s, bandwidth=bw)
    out = diag_mod._ksd_stats(jnp.asarray(x), jnp.asarray(s), bw, 5, False)
    np.testing.assert_allclose(float(out["ksd_sq"]), want, rtol=1e-10)
    assert float(out["ksd"]) == pytest.approx(np.sqrt(max(want, 0.0)))
    # chunk invariance: any row_chunk gives the same sums
    whole = diag_mod._ksd_stats(jnp.asarray(x), jnp.asarray(s), bw, 64, False)
    np.testing.assert_allclose(float(out["ksd_sq"]), float(whole["ksd_sq"]),
                               rtol=1e-12)


def test_kernel_ess_matches_oracle_and_bounds(rng):
    n, d, bw = 12, 2, 1.0
    x = rng.normal(size=(n, d))
    want = _oracle.kernel_ess(x, bandwidth=bw)
    out = diag_mod._kernel_stats(jnp.asarray(x), bw, 5, False)
    np.testing.assert_allclose(float(out["ess"]), want, rtol=1e-10)
    assert 1.0 <= want <= n
    # fully collapsed set → ESS ≈ 1; well-separated set → ESS ≈ n
    collapsed = np.tile(x[:1], (n, 1))
    out_c = diag_mod._kernel_stats(jnp.asarray(collapsed), bw, 5, False)
    assert float(out_c["ess"]) == pytest.approx(1.0)
    spread = 100.0 * np.arange(n, dtype=np.float64)[:, None] * np.ones((1, d))
    out_s = diag_mod._kernel_stats(jnp.asarray(spread), bw, 5, False)
    assert float(out_s["ess"]) == pytest.approx(n)


def test_ksd_separates_converged_from_drifted(rng):
    """For a standard-normal target (score = −θ), samples drawn FROM the
    target score a far smaller KSD than the same samples shifted off it —
    the one-scalar convergence signal the drift guard thresholds."""
    x = rng.normal(size=(64, 2))
    shifted = x + 3.0
    good = float(diag_mod._ksd_stats(jnp.asarray(x), jnp.asarray(-x),
                                     1.0, 32, False)["ksd"])
    bad = float(diag_mod._ksd_stats(jnp.asarray(shifted),
                                    jnp.asarray(-shifted),
                                    1.0, 32, False)["ksd"])
    assert bad > 3 * good


def test_collapse_indicators(rng):
    x = rng.normal(size=(16, 3))
    x[7] = x[3]          # one duplicated particle
    x[:, 1] = 0.25       # one dead dimension
    out = diag_mod._kernel_stats(jnp.asarray(x), 1.0, 8, False)
    assert float(out["min_pairwise_dist"]) == 0.0
    assert float(diag_mod._dim_var_stats(jnp.asarray(x))) == 0.0
    # median pairwise distance tracks the numpy median (counting-bracket
    # resolution: 8⁻⁴ of the range, lower-middle order statistic)
    sq = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    want = np.median(np.sqrt(sq[~np.eye(len(x), dtype=bool)]))
    got = float(out["median_pairwise_dist"])
    assert abs(got - want) / want < 0.05


def test_shard_divergence_detects_shifted_shard(rng):
    """A single drifted shard lights up shard_mean_div while a healthy
    sharded set stays near zero — the exchange-bug detector."""
    S, per, d = 4, 64, 2
    x = rng.normal(size=(S * per, d))
    base = diag_mod._shard_stats(jnp.asarray(x), S)
    shifted = x.copy()
    shifted[2 * per:3 * per] += 6.0
    drift = diag_mod._shard_stats(jnp.asarray(shifted), S)
    assert float(base["shard_mean_div"]) < 0.2
    assert float(drift["shard_mean_div"]) > 4 * float(base["shard_mean_div"])
    assert float(drift["shard_var_div"]) > float(base["shard_var_div"])
    # min_dim_var rides along with the shard pass
    assert float(base["min_dim_var"]) == pytest.approx(
        float(np.var(x, axis=0).min()), rel=1e-6)


def test_compute_subsamples_past_max_points(rng):
    """Past max_points the pairwise stats run on the strided subsample
    (ess_frac normalised by evaluated rows), and repeated computes at one
    shape are steady-state: zero XLA compiles under the retrace sentry."""
    from tools.jaxlint.sentry import retrace_sentry

    x = rng.normal(size=(96, 2))
    pd = PosteriorDiagnostics(
        DiagnosticsConfig(every_steps=4, max_points=32, row_chunk=16,
                          score_fn=lambda th: -th),
        registry=MetricsRegistry())
    rep = pd.compute(x, num_shards=4, step=4)
    assert rep["n"] == 96 and rep["n_eval"] == 32
    assert rep["ess_frac"] == pytest.approx(rep["ess"] / 32)
    for key in ("ksd", "ksd_sq", "ess", "min_pairwise_dist",
                "median_pairwise_dist", "min_dim_var", "shard_mean_div",
                "shard_var_div", "bandwidth", "wall_s"):
        assert key in rep, key
    with retrace_sentry("diagnostics steady state") as sentry:
        for step in (8, 12, 16):
            pd.compute(x, num_shards=4, step=step)
    if sentry.supported:
        assert sentry.compiles == 0


def test_median_bandwidth_mode_and_registry_gauges(rng):
    x = rng.normal(size=(24, 2))
    reg = MetricsRegistry()
    pd = PosteriorDiagnostics(
        DiagnosticsConfig(every_steps=2, bandwidth="median", row_chunk=24),
        registry=reg, wall_clock=lambda: 123.0)
    rep = pd.compute(x, step=6)
    assert rep["bandwidth"] > 0  # resolved per-compute by the median
    assert rep.get("ksd") is None  # no score_fn → score-free report
    assert reg.gauge("svgd_diag_ess").value() == pytest.approx(rep["ess"])
    assert reg.gauge("svgd_diag_last_step").value() == 6
    assert reg.gauge("svgd_diag_last_update_ts").value() == 123.0
    assert reg.counter("svgd_diag_computations_total").value() == 1
    assert pd.last_report is rep
    assert not pd.should_run(5) and pd.should_run(6)


def test_disabled_diagnostics_is_zero_alloc():
    """The DISABLED singleton's per-boundary check allocates nothing —
    the tracer's no-op discipline, tracemalloc-pinned."""
    assert DISABLED.compute(None) is None  # warm any lazy machinery
    tracemalloc.start()
    try:
        before = tracemalloc.get_traced_memory()[0]
        for t in range(200):
            DISABLED.should_run(t)
            DISABLED.compute(None, None, None, None)
        after = tracemalloc.get_traced_memory()[0]
    finally:
        tracemalloc.stop()
    assert after - before == 0
    assert DISABLED.enabled is False and DISABLED.last_report is None


# --------------------------------------------------------------------- #
# drift guard + supervisor integration


def test_check_diagnostics_thresholds():
    cfg = GuardConfig(max_ksd=1.0, min_ess_frac=0.1, min_dim_var=1e-6,
                      max_shard_mean_div=0.5)
    assert cfg.checks_diagnostics
    ok = {"ksd": 0.5, "ess_frac": 0.4, "min_dim_var": 0.1,
          "shard_mean_div": 0.1}
    assert check_diagnostics(ok, cfg) is ok
    with pytest.raises(GuardViolation, match="posterior drift"):
        check_diagnostics({**ok, "ksd": 2.0}, cfg)
    with pytest.raises(GuardViolation, match="particle collapse"):
        check_diagnostics({**ok, "ess_frac": 0.01}, cfg)
    with pytest.raises(GuardViolation, match="dimension collapse"):
        check_diagnostics({**ok, "min_dim_var": 0.0}, cfg)
    with pytest.raises(GuardViolation, match="shard divergence"):
        check_diagnostics({**ok, "shard_mean_div": 2.0}, cfg)
    # NaN statistics trip instead of comparing False
    with pytest.raises(GuardViolation):
        check_diagnostics({**ok, "ksd": float("nan")}, cfg)
    # absent statistics leave their checks inert
    assert check_diagnostics({}, cfg) == {}
    assert not GuardConfig().checks_diagnostics


def _make_supervisor(tmp_path, name, diagnostics=None, guard=None,
                     steps=8, **kw):
    sampler = dt.Sampler(2, lambda th: -0.5 * jnp.sum(th ** 2))
    return RunSupervisor(
        sampler, steps, 0.05, n=12, seed=0,
        checkpoint_dir=os.path.join(str(tmp_path), name),
        checkpoint_every=4, segment_steps=2, sleep=lambda s: None,
        registry=MetricsRegistry(), diagnostics=diagnostics, guard=guard,
        **kw)


def test_supervisor_runs_diagnostics_on_cadence(tmp_path):
    """Diagnostics fire at the first boundary at or past each every_steps
    multiple (every=3 on a 2-step grid → boundaries 4, 6... cross 3 and 6)
    plus the final boundary, the report lands in the run report, and the
    Sampler's own score closure feeds KSD without any config."""
    reg = MetricsRegistry()
    diag = PosteriorDiagnostics(DiagnosticsConfig(every_steps=3,
                                                  row_chunk=12),
                                registry=reg)
    sup = _make_supervisor(tmp_path, "d", diagnostics=diag)
    report = sup.run()
    assert report["status"] == "completed"
    last = report["last_diagnostics"]
    assert last is not None and last["step"] == 8
    assert last["ksd"] >= 0  # score wired from the sampler automatically
    assert reg.counter("svgd_diag_computations_total").value() >= 2


def test_drift_guard_rolls_back_and_exhausts_budget(tmp_path, recorder):
    """An impossible ESS floor trips the drift guard at every replayed
    boundary: rollback + step-size backoff until the restart budget
    exhausts — and every trip plus the final exhaustion dumped postmortem
    bundles through the flight recorder."""
    diag = PosteriorDiagnostics(DiagnosticsConfig(every_steps=2,
                                                  row_chunk=12),
                                registry=MetricsRegistry())
    sup = _make_supervisor(tmp_path, "g", diagnostics=diag,
                           guard=GuardConfig(min_ess_frac=2.0))
    eps0 = sup.step_size
    with pytest.raises(RestartBudgetExhausted):
        sup.run()
    assert sup.step_size < eps0  # backoff applied on each trip
    dumps = sorted(os.listdir(str(recorder._dump_dir)))
    assert any("guard_violation" in d for d in dumps)
    assert any("restart_budget_exhausted" in d for d in dumps)
    # the bundle renders through the CLI
    import trace_report

    bundle = os.path.join(str(recorder._dump_dir), dumps[0])
    assert trace_report.main([bundle, "--postmortem"]) == 0


def test_healthy_run_passes_drift_guard(tmp_path):
    diag = PosteriorDiagnostics(DiagnosticsConfig(every_steps=2,
                                                  row_chunk=12),
                                registry=MetricsRegistry())
    sup = _make_supervisor(tmp_path, "h", diagnostics=diag,
                           guard=GuardConfig(min_ess_frac=1e-4, max_ksd=1e3))
    assert sup.run()["status"] == "completed"
    assert sup.report["restarts"] == 0


def test_fault_dumps_postmortem(tmp_path, recorder):
    """A non-retryable fault (simulated hard kill) dumps the black box on
    the way out — the bundle the next resume's operator reads first."""
    from dist_svgd_tpu.resilience import FaultPlan, HardKillAt, SimulatedHardKill

    sup = _make_supervisor(tmp_path, "k",
                           faults=FaultPlan(HardKillAt(4)))
    with pytest.raises(SimulatedHardKill):
        sup.run()
    dumps = os.listdir(str(recorder._dump_dir))
    assert any("fault" in d for d in dumps)
    header = json.loads(open(
        os.path.join(str(recorder._dump_dir), sorted(dumps)[0])).readline())
    assert header["kind"] == "postmortem"
    assert "SimulatedHardKill" in header["context"]["error"]


# --------------------------------------------------------------------- #
# flight recorder


def test_flight_recorder_ring_and_dump(tmp_path):
    reg = MetricsRegistry()
    reg.counter("t_total").inc(5)
    rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path), registry=reg,
                         clock=lambda: 42.0)
    for i in range(20):
        rec.record("tick", i=i)
    rec.record("diagnostics", ksd=0.5, ess=3.0)
    assert len(rec.events()) == 8  # bounded ring, oldest evicted
    assert rec.last_diagnostics["ksd"] == 0.5
    path = rec.dump("test_reason", {"t": 7})
    assert os.path.basename(path) == "postmortem_001_test_reason.jsonl"
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["kind"] == "postmortem"
    assert lines[0]["reason"] == "test_reason"
    assert lines[0]["context"] == {"t": 7}
    assert lines[1]["kind"] == "metrics" and lines[1]["snapshot"]["t_total"] == 5
    assert lines[2]["kind"] == "diagnostics" and lines[2]["ksd"] == 0.5
    assert [l for l in lines if l["kind"] == "tick"][-1]["i"] == 19
    assert rec.dumps == 1


def test_tracer_feeds_recorder_ring(tmp_path, recorder):
    tracer = telemetry.enable()
    try:
        with telemetry.span("diag.test"):
            pass
        telemetry.instant("mark")
    finally:
        telemetry.disable()
    kinds = [(e["kind"], e.get("name")) for e in recorder.events()]
    assert ("span", "diag.test") in kinds
    assert ("instant", "mark") in kinds


def test_record_flight_noop_without_recorder():
    assert telemetry.flight_recorder() is None
    telemetry.record_flight("orphan", x=1)  # must not raise


def test_install_flight_recorder_idempotent(tmp_path):
    rec = install_flight_recorder(dump_dir=str(tmp_path))
    try:
        assert install_flight_recorder() is rec
    finally:
        assert uninstall_flight_recorder() is rec
    assert uninstall_flight_recorder() is None


# --------------------------------------------------------------------- #
# serving: reload policy + /slo route


def test_reload_policy_rejects_collapsed_ensemble(rng, tmp_path):
    from dist_svgd_tpu.serving import EnsembleRejected, PredictiveEngine

    parts = rng.normal(size=(64, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16,
                          registry=MetricsRegistry(),
                          reload_policy=ReloadPolicy(min_ess_frac=0.05,
                                                     max_points=64))
    eng.predict(rng.normal(size=(3, 4)).astype(np.float32))
    healthy = rng.normal(size=(64, 5)).astype(np.float32)
    eng.reload(healthy, tag="gen2")
    assert eng.stats()["ensemble_tag"] == "gen2"
    assert eng.stats()["ensemble_health"]["ess_frac"] > 0.05
    collapsed = np.tile(healthy[:1], (64, 1))
    with pytest.raises(EnsembleRejected, match="ess_frac"):
        eng.reload(collapsed, tag="gen3")
    st = eng.stats()
    assert st["ensemble_tag"] == "gen2"  # still serving the old generation
    assert st["reload_rejects"] == 1


def test_hot_reloader_skips_rejected_generation(rng, tmp_path):
    from dist_svgd_tpu.serving import CheckpointHotReloader, PredictiveEngine
    from dist_svgd_tpu.utils.checkpoint import CheckpointManager

    parts = rng.normal(size=(32, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=8,
                          registry=MetricsRegistry(),
                          reload_policy=ReloadPolicy(min_ess_frac=0.05,
                                                     max_points=32))
    root = str(tmp_path / "root")
    mgr = CheckpointManager(root, every=1)
    mgr.save(1, {"particles": np.tile(parts[:1], (32, 1))})  # collapsed
    rel = CheckpointHotReloader(eng, root, baseline_step=0)
    assert rel.poll_once() is None       # rejected, not served
    assert rel.loaded_step == 1          # ...but marked seen
    assert eng.stats()["reloads"] == 0
    mgr.save(2, {"particles": rng.normal(size=(32, 5)).astype(np.float32)})
    assert rel.poll_once() == 2          # healthier generation swaps in
    assert eng.stats()["reloads"] == 1


def test_server_slo_route(rng):
    from dist_svgd_tpu.serving import PredictionServer, PredictiveEngine

    reg = MetricsRegistry()
    parts = rng.normal(size=(32, 5)).astype(np.float32)
    eng = PredictiveEngine("logreg", parts, min_bucket=4, max_bucket=16,
                          registry=reg)
    eng.warmup()  # the one traced request must not blow the p99 objective
    srv = PredictionServer(eng, port=0, max_wait_ms=1.0, registry=reg)
    with srv:
        body = json.dumps(
            {"inputs": [[0.1, 0.2, 0.3, 0.4]]}).encode()
        req = urllib.request.Request(
            srv.url + "/predict", body, {"Content-Type": "application/json"})
        assert json.loads(urllib.request.urlopen(req, timeout=10).read())[
            "outputs"]
        doc = json.loads(urllib.request.urlopen(
            srv.url + "/slo", timeout=10).read())
    assert doc["status"] == "ok"
    assert doc["objectives"]["serve_p99"]["status"] in ("ok", "no_data")
    assert set(doc["objectives"]) == {"serve_p99", "shed_rate",
                                      "dispatch_errors"}
    # verdicts mirrored into the scrapeable registry
    assert reg.gauge("svgd_slo_burn_rate").has(slo="shed_rate")


# --------------------------------------------------------------------- #
# SLO engine


def test_latency_objective_burn_and_windowing():
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_seconds", buckets=(0.001, 0.01, 0.1, 1.0))
    eng = slo_mod.SloEngine(reg, [slo_mod.LatencyObjective(
        "p99", "t_lat_seconds", threshold_s=0.1, target=0.9)],
        clock=lambda: 10.0)
    assert eng.evaluate()["objectives"]["p99"]["status"] == "no_data"
    for _ in range(98):
        h.observe(0.005)
    h.observe(0.5)
    h.observe(0.5)
    doc = eng.evaluate()
    row = doc["objectives"]["p99"]
    # 2/100 over a 10% budget → burn 0.2, ok
    assert row["status"] == "ok"
    assert row["burn_rate"] == pytest.approx(0.2)
    assert doc["status"] == "ok"
    # next window: mostly-slow traffic breaches even though the cumulative
    # distribution would still pass — the delta-window discipline
    for _ in range(10):
        h.observe(0.5)
    doc = eng.evaluate()
    row = doc["objectives"]["p99"]
    assert row["status"] == "breach" and row["window_count"] == 10
    assert doc["status"] == "breach"
    assert reg.counter("svgd_slo_breaches_total").value(slo="p99") == 1


def test_ratio_gauge_and_staleness_objectives():
    reg = MetricsRegistry()
    shed = reg.counter("t_shed_total")
    seg = reg.histogram("t_seg_seconds")
    now = [100.0]
    eng = slo_mod.SloEngine(reg, [
        slo_mod.RatioObjective("shed", "t_shed_total", "t_seg_seconds",
                               max_ratio=0.5),
        slo_mod.GaugeCeiling("ksd", "t_ksd", ceiling=1.0),
        slo_mod.StalenessObjective("fresh", "t_ts", max_age_s=60.0),
    ], clock=lambda: now[0])
    doc = eng.evaluate()["objectives"]
    assert doc["shed"]["status"] == "no_data"   # empty denominator window
    assert doc["ksd"]["status"] == "no_data"    # gauge never written
    assert doc["fresh"]["status"] == "no_data"
    for _ in range(4):
        seg.observe(0.1)
    shed.inc(1)
    reg.gauge("t_ksd").set(0.4)
    reg.gauge("t_ts").set(90.0)
    doc = eng.evaluate()["objectives"]
    assert doc["shed"]["status"] == "ok"
    assert doc["shed"]["ratio"] == pytest.approx(0.25)
    assert doc["ksd"]["status"] == "ok"
    assert doc["ksd"]["burn_rate"] == pytest.approx(0.4)
    assert doc["fresh"]["status"] == "ok"
    reg.gauge("t_ksd").set(2.0)
    now[0] = 200.0  # 110 s stale
    shed.inc(3)
    seg.observe(0.1)
    doc = eng.evaluate()["objectives"]
    assert doc["shed"]["status"] == "breach"  # 3 sheds / 1 segment
    assert doc["ksd"]["status"] == "breach"
    assert doc["fresh"]["status"] == "breach"
    # total-outage shape: bad events with a ZERO base window (every
    # request shed → none resolved) is a breach, never no_data
    shed.inc(5)
    doc = eng.evaluate()["objectives"]
    assert doc["shed"]["status"] == "breach"
    assert doc["shed"]["window_den"] == 0 and doc["shed"]["window_num"] == 5
    json.dumps(doc)  # unbounded burn serialises as null, not Infinity


def test_slo_empty_window_reads_no_data_never_ok():
    """Round-15 edge case the fleet router depends on: a quiet evaluation
    window (replica idle between probes) must read no_data — which the
    router classifies as *unknown*, never healthy — and must not breach
    either."""
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_seconds", buckets=(0.001, 0.01, 0.1))
    obj = slo_mod.LatencyObjective("p99", "t_lat_seconds", threshold_s=0.1)
    eng = slo_mod.SloEngine(reg, [obj], clock=lambda: 1.0)
    for _ in range(20):
        h.observe(0.005)
    assert eng.evaluate()["objectives"]["p99"]["status"] == "ok"
    # the traffic stops: every later window is empty, and stays no_data
    # forever — NOT a sticky "ok" from the last lucky window
    for _ in range(3):
        row = eng.evaluate()["objectives"]["p99"]
        assert row["status"] == "no_data"
        assert row["window_count"] == 0
        assert row["burn_rate"] == 0.0


def test_slo_counter_reset_across_replica_restart():
    """A replica restart resets its counters (and histogram buckets) to
    zero; the windowed deltas must clamp at 0 and read no_data — never a
    negative window, never a phantom breach, never a phantom ok."""
    reg1 = MetricsRegistry()
    reg1.counter("t_bad_total").inc(10)
    reg1.counter("t_base_total").inc(100)
    ratio = slo_mod.RatioObjective("errs", "t_bad_total", "t_base_total",
                                   max_ratio=0.5)
    assert ratio.evaluate(reg1, 1.0)["status"] == "ok"
    # the restart: fresh process, same metric names, lower raw values
    reg2 = MetricsRegistry()
    reg2.counter("t_bad_total").inc(2)
    reg2.counter("t_base_total").inc(3)
    row = ratio.evaluate(reg2, 2.0)
    assert row["status"] == "no_data"
    assert row["window_den"] == 0
    # the next window on the restarted replica judges fresh deltas again
    reg2.counter("t_bad_total").inc(1)
    reg2.counter("t_base_total").inc(10)
    row = ratio.evaluate(reg2, 3.0)
    assert row["status"] == "ok" and row["window_den"] == 10
    # same discipline for histogram bucket counts
    reg1.histogram("t_lat_seconds", buckets=(0.01, 0.1))
    lat = slo_mod.LatencyObjective("p99", "t_lat_seconds", threshold_s=0.1)
    for _ in range(50):
        reg1._metrics["t_lat_seconds"].observe(0.005)
    assert lat.evaluate(reg1, 1.0)["status"] == "ok"
    reg3 = MetricsRegistry()
    reg3.histogram("t_lat_seconds", buckets=(0.01, 0.1)).observe(0.005)
    row = lat.evaluate(reg3, 2.0)
    assert row["status"] == "no_data"  # 1 < 50: clamped to an empty window


def test_slo_staleness_reads_unknown_never_healthy():
    """Staleness, end to end: a never-written or stale freshness gauge is
    no_data/breach at the SLO layer, and the fleet router's classifier
    maps anything that is not a fresh verdict to 'unknown' — a stale
    'ok' can never keep a replica admitted on old good news."""
    from dist_svgd_tpu.serving.fleet import classify_slo

    reg = MetricsRegistry()
    obj = slo_mod.StalenessObjective("fresh", "t_ts", max_age_s=10.0)
    eng = slo_mod.SloEngine(reg, [obj], clock=lambda: 100.0)
    row = eng.evaluate()["objectives"]["fresh"]
    assert row["status"] == "no_data"   # never written != healthy
    assert row["status"] != "ok"
    # the router-side mapping of every non-verdict shape
    assert classify_slo({"status": "no_data"}) == "unknown"
    assert classify_slo(None) == "unknown"
    assert classify_slo({"status": "ok", "ts": 50.0},
                        now_s=100.0, max_age_s=10.0) == "unknown"
    # only a FRESH ok reads healthy
    assert classify_slo({"status": "ok", "ts": 95.0},
                        now_s=100.0, max_age_s=10.0) == "healthy"


def test_default_slo_sets_and_duplicate_names():
    reg = MetricsRegistry()
    serving = slo_mod.default_serving_slos(reg, p99_ms=50.0)
    assert {o.name for o in serving.objectives} == {
        "serve_p99", "shed_rate", "dispatch_errors"}
    training = slo_mod.default_training_slos(reg, max_ksd=2.0,
                                             diag_max_age_s=300.0)
    assert {o.name for o in training.objectives} == {
        "guard_trip_rate", "ksd_ceiling", "diag_freshness"}
    with pytest.raises(ValueError, match="duplicate"):
        slo_mod.SloEngine(reg, [slo_mod.GaugeCeiling("x", "g", 1.0),
                                slo_mod.GaugeCeiling("x", "g2", 1.0)])


# --------------------------------------------------------------------- #
# ensemble_health + ReloadPolicy unit behaviour


def test_ensemble_health_and_reload_policy_judgement(rng):
    x = rng.normal(size=(200, 3)).astype(np.float32)
    h = ensemble_health(x, max_points=50)
    assert h["n_eval"] == 50
    assert 0 < h["ess_frac"] <= 1 and h["min_dim_var"] > 0
    pol = ReloadPolicy(min_ess_frac=0.05, max_ess_drop_frac=0.5,
                       min_dim_var=1e-8, max_points=50)
    assert pol.judge(h, None) == []
    # relative drop: candidate at less than half the baseline's ess_frac
    bad = dict(h, ess_frac=h["ess_frac"] * 0.3)
    reasons = pol.judge(bad, h)
    assert reasons and "dropped past" in reasons[0]
    # NaN statistics reject rather than comparing False
    assert pol.judge(dict(h, ess_frac=float("nan")), None)


def test_config_validation():
    with pytest.raises(ValueError, match="every_steps"):
        DiagnosticsConfig(every_steps=0)
    with pytest.raises(ValueError, match="bandwidth"):
        DiagnosticsConfig(bandwidth=-1.0)
    with pytest.raises(ValueError, match="row_chunk"):
        DiagnosticsConfig(row_chunk=0)
    with pytest.raises(ValueError, match="n >= 2"):
        PosteriorDiagnostics(registry=MetricsRegistry()).compute(
            np.zeros((1, 2)))
    with pytest.raises(ValueError, match="n>=2"):
        ensemble_health(np.zeros((1, 2)))
