"""The exchanged-mode W2 pairing option (round-5: the measured memory cliff
past n=400k gets an auto-route to the partitions-style block pairing, not a
silent 20× regression — VERDICT r04 item 2).

``w2_pairing='block'`` keeps φ interacting with the gathered global set but
pairs each shard's W2 solve block-(b+1)-ring style with ``(n/S, d)`` carried
state.  Pinned here: the exact semantics (oracle), eager ≡ scanned parity,
the auto-route threshold + warnings, the composition rejections, and
checkpoint reshard behaviour across pairings."""

import numpy as np
import jax.numpy as jnp
import pytest

import dist_svgd_tpu.distsampler as distsampler_mod
from dist_svgd_tpu import DistSampler
from dist_svgd_tpu.models.logreg import logreg_logp
from dist_svgd_tpu.ops.ot import wasserstein_grad_sinkhorn

from test_distsampler import make_gaussian_problem

SINK = dict(sinkhorn_eps=0.05, sinkhorn_iters=50)


def build(particles, data, S, pairing="auto", exch_p=True, w2=True, **kw):
    return DistSampler(
        S, logreg_logp, None, jnp.asarray(particles), data=data,
        exchange_particles=exch_p, exchange_scores=False,
        include_wasserstein=w2, wasserstein_solver="sinkhorn",
        w2_pairing=pairing, **SINK, **kw,
    )


def test_block_pairing_oracle_semantics():
    """Step 2 under block pairing = the no-W2 twin's step plus
    ``eps·h·sinkhorn_grad(block_b, snapshot_{(b+1) mod S})`` — the
    partitions-style pairing computed directly from the ops layer."""
    rng = np.random.default_rng(7)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, n=16, d=2, num_shards=S)
    eps, h = 0.05, 0.7

    w2s = build(particles, data, S, pairing="block")
    twin = build(particles, data, S, w2=False)

    # step 1: no previous snapshot yet → W2 inert, trajectories coincide
    s1 = np.asarray(w2s.make_step(eps, h=h))
    np.testing.assert_allclose(s1, np.asarray(twin.make_step(eps, h=h)),
                               rtol=1e-10)
    # the snapshot is the post-update own-block stack, (S, n/S, d)
    assert w2s._previous.shape == (S, 16 // S, 2)
    np.testing.assert_allclose(w2s._previous.reshape(16, 2), s1, rtol=1e-12)

    # step 2: oracle = twin step + eps·h·blockwise ring-rolled solve
    n_loc = 16 // S
    cur = s1.reshape(S, n_loc, 2)
    w_grad = np.stack([
        np.asarray(wasserstein_grad_sinkhorn(
            jnp.asarray(cur[b]), jnp.asarray(cur[(b + 1) % S]),
            eps=SINK["sinkhorn_eps"], iters=SINK["sinkhorn_iters"],
            tol=1e-2, g_init=jnp.zeros(n_loc),
        ))
        for b in range(S)
    ])
    want = np.asarray(twin.make_step(eps, h=h)) + eps * h * w_grad.reshape(16, 2)
    got = np.asarray(w2s.make_step(eps, h=h))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-10)


def test_block_pairing_scanned_matches_eager():
    """run_steps (carried snapshots + duals on device) ≡ make_step under
    block pairing, including the step-1 W2 gate and cross-driver mixing."""
    rng = np.random.default_rng(31)
    S = 2
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, n_rows=8,
                                               num_shards=S)
    eager = build(particles, data, S, pairing="block")
    for _ in range(4):
        want = eager.make_step(0.05, h=0.5)
    scanned = build(particles, data, S, pairing="block")
    got = scanned.run_steps(4, 0.05, h=0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-6)
    np.testing.assert_allclose(np.asarray(scanned._previous),
                               np.asarray(eager._previous), rtol=2e-6)
    np.testing.assert_allclose(
        np.asarray(scanned.run_steps(2, 0.05, h=0.5)),
        np.asarray([eager.make_step(0.05, h=0.5) for _ in range(2)][-1]),
        rtol=2e-6,
    )


def test_auto_routes_above_threshold(monkeypatch):
    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, num_shards=2)
    monkeypatch.setattr(distsampler_mod, "W2_GLOBAL_PAIRING_MAX_N", 4)
    with pytest.warns(UserWarning, match="routing the Wasserstein term"):
        ds = DistSampler(
            2, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=True, wasserstein_solver="sinkhorn", **SINK,
        )
    assert ds._w2_pairing == "block"
    assert ds._prev_shape() == (2, 4, 2)
    # forcing the reference pairing still works, with the cliff warning
    with pytest.warns(UserWarning, match="HBM cliff"):
        forced = DistSampler(
            2, logreg_logp, None, jnp.asarray(particles), data=data,
            exchange_particles=True, exchange_scores=False,
            include_wasserstein=True, wasserstein_solver="sinkhorn",
            w2_pairing="global", **SINK,
        )
    assert forced._w2_pairing == "global"
    assert forced._prev_shape() == (2, 8, 2)


def test_auto_stays_global_below_threshold():
    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, num_shards=2)
    ds = build(particles, data, 2, pairing="auto")
    assert ds._w2_pairing == "global"
    assert ds._prev_shape() == (2, 8, 2)
    # without the W2 term the option is inert — no warning at any n
    off = build(particles, data, 2, pairing="auto", w2=False)
    assert off._prev_shape() == (2, 8, 2)


def test_partitions_rejects_global_pairing():
    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, num_shards=2)
    with pytest.raises(ValueError, match="partitions"):
        build(particles, data, 2, pairing="global", exch_p=False)
    # block/auto are its native pairing — accepted
    ds = build(particles, data, 2, pairing="block", exch_p=False)
    assert ds._block_w2


def test_partitions_accepts_any_pairing_when_w2_off():
    """With the W2 term off the option is FULLY inert, as documented —
    generic config code passing the same kwargs with W2 disabled must not
    get a spurious partitions-mode rejection (ADVICE round 5)."""
    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, num_shards=2)
    for pairing in ("auto", "global", "block"):
        ds = build(particles, data, 2, pairing=pairing, exch_p=False,
                   w2=False)
        assert ds.w2_pairing == "block"  # the mode's native pairing
        assert np.isfinite(np.asarray(ds.make_step(0.05))).all()
    # typos still rejected, W2 on or off
    with pytest.raises(ValueError, match="w2_pairing"):
        build(particles, data, 2, pairing="bogus", exch_p=False, w2=False)


def test_state_dict_records_resolved_pairing():
    """The RESOLVED pairing (after 'auto' routing) travels with the
    checkpoint, so runs straddling the auto-switch boundary stay
    distinguishable; restoring under a different resolution warns."""
    from dist_svgd_tpu.distsampler import W2_PAIRING_CODES

    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, num_shards=2)
    g = build(particles, data, 2, pairing="global")
    assert g.w2_pairing == "global"
    state = g.state_dict()
    assert W2_PAIRING_CODES[int(np.asarray(state["w2_pairing"]))] == "global"
    # same-pairing restore: silent
    twin = build(particles, data, 2, pairing="global")
    twin.load_state_dict(state)
    # cross-pairing restore: the exact reshard still happens, with a warning
    blk = build(particles, data, 2, pairing="block")
    g.make_step(0.05, h=0.5)
    with pytest.warns(UserWarning, match="different W2 functionals"):
        blk.load_state_dict(g.state_dict())


def test_unknown_pairing_rejected():
    rng = np.random.default_rng(3)
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, num_shards=2)
    with pytest.raises(ValueError, match="w2_pairing"):
        build(particles, data, 2, pairing="rowwise")


def test_checkpoint_reshard_across_pairings():
    """Global-pairing saves restore into block-pairing samplers (post blocks
    are recoverable); the reverse needs pre-update rows the block save never
    recorded and must raise."""
    rng = np.random.default_rng(5)
    S = 2
    particles, data, _ = make_gaussian_problem(rng, n=8, d=2, num_shards=S)

    glob = build(particles, data, S, pairing="global")
    for _ in range(2):
        glob.make_step(0.05, h=0.5)
    state = glob.state_dict()

    blk = build(particles, data, S, pairing="block")
    blk.load_state_dict(state)
    assert np.asarray(blk._previous).shape == (S, 4, 2)
    # the rebuilt stack is the post-update global, re-blocked
    np.testing.assert_allclose(
        np.asarray(blk._previous).reshape(8, 2),
        np.asarray(glob._previous)[np.arange(S).repeat(4),
                                   np.arange(8)],  # own rows = post rows
        rtol=1e-12,
    )
    # dual dropped on reshard → first resumed solve cold-starts
    assert blk._w2_g is None

    blk2 = build(particles, data, S, pairing="block")
    for _ in range(2):
        blk2.make_step(0.05, h=0.5)
    glob2 = build(particles, data, S, pairing="global")
    with pytest.raises(ValueError, match="pre-update rows"):
        glob2.load_state_dict(blk2.state_dict())


def test_block_pairing_composes_with_ring_exchange():
    """Round-5 composition cell: ring exchange + block W2 pairing — the
    fully O(n/S)-memory exchanged W2 step.  Ring ≡ gather must hold for
    the whole scanned W2 trajectory, and the global pairing must still
    reject the ring implementation (its snapshot is the gathered set)."""
    rng = np.random.default_rng(17)
    S = 4
    particles, data, _ = make_gaussian_problem(rng, n=16, d=2, num_shards=S)

    gather = build(particles, data, S, pairing="block")
    ring = build(particles, data, S, pairing="block", exchange_impl="ring")
    want = gather.run_steps(4, 0.05, h=0.5)
    got = ring.run_steps(4, 0.05, h=0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-6)
    np.testing.assert_allclose(np.asarray(ring._previous),
                               np.asarray(gather._previous), rtol=2e-6)

    glob_ring = build(particles, data, S, pairing="global",
                      exchange_impl="ring")
    with pytest.raises(ValueError, match="w2_pairing='block'"):
        glob_ring.run_steps(2, 0.05, h=0.5)


def test_single_shard_ring_w2_degenerates_cleanly():
    """S=1 + ring + W2 runs for every pairing and equals the gather path
    exactly — all pairings degenerate to the same whole-array snapshot
    there, and the step builds it without a gather (the guard exempts
    S=1 instead of demanding w2_pairing='block' the config already has,
    round-5 review finding)."""
    rng = np.random.default_rng(9)
    particles, data, _ = make_gaussian_problem(rng, n=12, d=2, num_shards=1)
    for pairing in ("block", "auto", "global"):
        ring = build(particles, data, 1, pairing=pairing,
                     exchange_impl="ring")
        gather = build(particles, data, 1, pairing=pairing)
        np.testing.assert_allclose(
            np.asarray(ring.run_steps(4, 0.05, h=0.5)),
            np.asarray(gather.run_steps(4, 0.05, h=0.5)),
            rtol=1e-6,
        )
