"""Fault drills: the tools/fault_drill.py row (tier-1, injected faults
only) and the slow-tier REAL-signal drills — a worker process killed with
SIGTERM/SIGKILL mid-run and resumed (tests/resilience_worker.py), plus the
multi-process federation kill-one-worker leg (skipped on legacy jax whose
CPU backend lacks multiprocess collectives)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "resilience_worker.py")

sys.path.insert(0, os.path.join(REPO, "tools"))

from test_multihost import needs_cpu_multiprocess  # noqa: E402


def test_fault_drill_row_schema(tmp_path):
    """tools/fault_drill.py at smoke scale: every recovery flag true, one
    serialisable BENCH-style row.  (The < 5% checkpoint-overhead acceptance
    holds at the tool's DEFAULT workload — measured in docs/notes.md round
    8 — not at this test's smoke sizes.)"""
    import fault_drill

    row = fault_drill.run_drill(
        n=64, num_steps=12, checkpoint_every=4, segment_steps=2,
        root=str(tmp_path), diag_overhead=False,
    )
    for key in ("metric", "platform", "step_wall_ms",
                "checkpoint_overhead_pct", "kill_step",
                "last_checkpoint_step", "steps_lost", "recovery_wall_s",
                "resumed_bitwise_identical", "retry_backoff_recovered",
                "nan_rollback_recovered", "overhead_under_5pct",
                "ksd", "ess", "ess_frac", "slo_status",
                "diagnostics_overhead"):
        assert key in row, key
    assert row["metric"] == "fault_recovery"
    assert row["kill_step"] == 10 and row["last_checkpoint_step"] == 8
    assert row["steps_lost"] == 2
    assert row["resumed_bitwise_identical"]
    assert row["retry_backoff_recovered"]
    assert row["nan_rollback_recovered"]
    # posterior-health fields (round 11): the baseline run's diagnostics
    # (GMM score is closed-form, so the KSD column is real here) plus the
    # training-SLO verdict over the whole drill registry
    assert row["ksd"] > 0 and row["ess"] > 1
    assert 0 < row["ess_frac"] <= 1
    assert row["slo_status"] == "ok"
    assert row["diagnostics_overhead"] is None  # diag_overhead=False
    assert row["diagnostics_per_run"] >= 1
    json.dumps(row)


# --------------------------------------------------------------------- #
# slow tier: real processes, real signals


def _spawn_worker(args, outdir):
    env = dict(os.environ)
    env.update({"PYTHONPATH": "", "JAX_PLATFORMS": "cpu"})
    return subprocess.Popen(
        [sys.executable, WORKER] + args + [str(outdir)],
        cwd=os.path.join(REPO, "tests"), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _wait_for_step(root, step, timeout=120):
    deadline = time.time() + timeout
    path = os.path.join(root, f"step_{step}")
    while time.time() < deadline:
        if os.path.isdir(path):
            return
        time.sleep(0.05)
    raise AssertionError(f"checkpoint {path} never appeared")


def _uninterrupted_reference():
    """In-process supervised run with the worker's exact geometry (the
    pacing does not touch the trajectory)."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import resilience_worker as rw

    from dist_svgd_tpu.resilience import RunSupervisor

    ds = rw.build_sampler()
    sup = RunSupervisor(ds, rw.STEPS, rw.EPS, segment_steps=rw.SEGMENT)
    assert sup.run()["status"] == "completed"
    return np.asarray(sup.particles)


@pytest.mark.slow
@pytest.mark.parametrize("sig,graceful", [
    pytest.param(signal.SIGTERM, True, id="sigterm_graceful"),
    pytest.param(signal.SIGKILL, False, id="sigkill_hard"),
])
def test_kill_worker_then_resume_bitwise(tmp_path, sig, graceful):
    """Kill a real supervised worker process mid-run (SIGTERM: graceful
    boundary checkpoint; SIGKILL: nothing — resume from the last periodic
    save), relaunch with --resume, and the final state must equal the
    uninterrupted run's bitwise."""
    want = _uninterrupted_reference()
    proc = _spawn_worker(["single"], tmp_path)
    try:
        _wait_for_step(os.path.join(str(tmp_path), "ckpt"), 8)
        proc.send_signal(sig)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    if graceful:
        assert proc.returncode == 0, err
        report = json.load(open(os.path.join(str(tmp_path), "report.json")))
        assert report["status"] == "preempted"
    else:
        assert proc.returncode != 0  # SIGKILL: no cleanup, no report
        assert not os.path.exists(os.path.join(str(tmp_path), "report.json"))
    proc2 = _spawn_worker(["single", "--resume", "--pace", "0.0"], tmp_path)
    out, err = proc2.communicate(timeout=180)
    assert proc2.returncode == 0, err
    report = json.load(open(os.path.join(str(tmp_path), "report.json")))
    assert report["status"] == "completed"
    assert report["resumed_from"] is not None
    got = np.load(os.path.join(str(tmp_path), "final.npy"))
    np.testing.assert_array_equal(want, got)


@pytest.mark.slow
@needs_cpu_multiprocess
def test_federation_kill_one_worker_then_resume(tmp_path):
    """Multi-process federation fault drill: two jax.distributed ranks run
    one supervised DistSampler over a shared mesh with per-process
    checkpoint roots; rank 1 is SIGTERMed mid-run (kill-one-worker — the
    surviving rank cannot make collective progress and is reaped), then the
    federation relaunches resuming from the newest step present in EVERY
    rank's root and must finish with the uninterrupted federation's exact
    global state."""
    def coord():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return f"127.0.0.1:{s.getsockname()[1]}"

    def launch(outdir, extra):
        c = coord()
        return [
            _spawn_worker(
                ["fed", "--rank", str(r), "--nprocs", "2",
                 "--coordinator", c] + extra, outdir,
            )
            for r in range(2)
        ]

    def finish(procs, timeout=300):
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            assert p.returncode == 0, err

    # reference: uninterrupted federation
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    finish(launch(ref_dir, ["--pace", "0.0"]))
    want = np.concatenate([
        np.load(os.path.join(str(ref_dir), f"rows_{r}.npy"))
        for r in range(2)
    ])

    # kill rank 1 mid-run; reap rank 0 (it cannot collect without its peer)
    kill_dir = tmp_path / "kill"
    kill_dir.mkdir()
    procs = launch(kill_dir, [])
    try:
        for r in range(2):
            _wait_for_step(os.path.join(str(kill_dir), f"ckpt_rank{r}"), 8)
        procs[1].send_signal(signal.SIGTERM)
        procs[1].communicate(timeout=120)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()

    # resume from the newest step BOTH roots hold
    def steps(r):
        root = os.path.join(str(kill_dir), f"ckpt_rank{r}")
        return {int(d.split("_")[1]) for d in os.listdir(root)
                if d.startswith("step_") and os.path.isdir(
                    os.path.join(root, d))}

    common = max(steps(0) & steps(1))
    assert common >= 8
    # worker --resume-from loads each rank's own block of that step and
    # runs (unmanaged) to completion on the same absolute grid
    finish(launch(kill_dir, ["--pace", "0.0", "--resume-from", str(common)]))
    got = np.concatenate([
        np.load(os.path.join(str(kill_dir), f"rows_{r}.npy"))
        for r in range(2)
    ])
    np.testing.assert_array_equal(want, got)


def test_fault_drill_rejects_unreachable_kill_step(tmp_path):
    import fault_drill

    with pytest.raises(ValueError, match="kill_step"):
        fault_drill.run_drill(n=64, num_steps=24, checkpoint_every=16,
                              segment_steps=4, root=str(tmp_path))
