"""Benchmark harness — prints ONE JSON line with the primary metric.

Output contract (round 5): stdout carries exactly one **compact** JSON line,
guaranteed ≤ :data:`_MAX_STDOUT_BYTES` (< the driver's 2,000-byte stdout
tail), containing the headline fields (``metric``/``value``/``unit``/
``vs_baseline``), the roofline fraction, the convergence summary (incl. the
flagship ``w2``/``partitions``/``partitions_w2`` rows), the configs-4/5
acceptance results, and the TPU test tier — so the driver's captured record
always parses whole, under any truncation strategy.  The round-4 record lost
its own headline number this way: the full JSON line grew past the tail
window and the ``value`` field (printed near the front) was cut off,
``"parsed": null``.  The FULL record still exists, twice: pretty-printed to
``bench_detail.json`` next to this file (deliberately NOT gitignored — the
driver commits stray files at round end, making the full record part of the
round's evidence) and as one JSON line on stderr.

Primary metric (BASELINE.md): SVGD particle-updates/sec **plus
steps-to-target-accuracy** on distributed Bayesian logistic regression
(banana fold 42).  The reference's published numbers (notes.md:120-135,
reproduced in BASELINE.md) top out at **421 updates/sec** at world size 8
(50 particles, 500 iterations, CPU); world size 1 is 12.5 up/s.
``vs_baseline`` is measured-updates/sec divided by the reference's best (421).

The headline number runs the **north-star path** (BASELINE.json): the 10k
particle array sharded over 8 shards in ``all_particles`` exchange mode —
each shard updates its block against the ``lax.all_gather``-ed global set —
driven through ``DistSampler.run_steps`` (one ``lax.scan`` dispatch for the
whole trajectory).  On the single-chip pool this executes the identical SPMD
program under vmap emulation — an honest single-chip number.  Round-2
interleaved A/B measurement put the emulated sharded step at parity with the
unsharded one (wall ratio 0.82–1.16 across repeats, within the pool's noise
band; the round-1 "2× emulation gap" did not reproduce — docs/notes.md).
The unsharded single-device number is reported alongside for context.

The convergence half of the metric runs the same 10k-particle config until
the ensemble posterior-predictive accuracy reaches the sklearn
LogisticRegression baseline − 0.01 (the reference's acceptance comparison,
experiments/logreg_plots.py:37-57).  Round-4 protocol: per dataset — ALL
SEVEN of the reference's benchmark suite (its grid.sh cross-product) — the
stepsize is tuned on a held-out seed and the reported
``steps_to_target_acc_median`` / ``_spread`` aggregate five *different*
seeds — per-dataset rows in ``convergence``, the way the reference's
acceptance comparison is per-fold.  Two extra flagship rows run the same
protocol on banana with the ``--wasserstein`` term (sinkhorn, scanned,
h=10 — the reference driver's weight) and in ``partitions`` exchange mode,
so the optional JKO term and the ring-migration family carry acceptance
evidence, not just throughput.  ``wall_to_target_acc_s`` times the
flagship (banana) median-step trajectory as pure scanned dispatches.
Compile time is excluded by warming the scan, then resetting the sampler
state via ``state_dict``/``load_state_dict``.

Timing is the best of 3 fenced samples, each the mean wall of an
adaptively-sized chain of state-chained scan runs under one trailing fetch
(~1 s of device work per sample, so the tunnel's fixed ~0.1 s per-sample
round trip amortises away — the round-3 protocol; the TPU pool behind the
tunnel has ±40% session variance with within-session spikes, and per-call
eager timing is round-trip-bound and useless — docs/notes.md and
``_timed_chain``).
"""

import json
import os
import sys
import time


REFERENCE_BEST_UPDATES_PER_SEC = 421.0  # notes.md:129 (ws=8) via BASELINE.md

#: stdout budget for the one compact line (the driver keeps the LAST 2,000
#: bytes of stdout; leave margin for the trailing newline and any stray
#: warning a library prints to stdout despite our best efforts)
_MAX_STDOUT_BYTES = 1900

#: The φ "roofline" the headline fraction is measured against is NOT a
#: recorded constant: it is the bare φ kernel itself, re-timed on the
#: north-star shapes in the SAME session (:func:`_phi_kernel_pairs_per_sec`)
#: — the shared pool swings ±40% between sessions, so step-vs-kernel from
#: the same session is the only ratio where the noise cancels and a change
#: means a genuine utilisation loss (round-4 VERDICT item 6; the memory
#: note's interleaved-A/B discipline applied to MFU).
N_PARTICLES = 10_000
N_ITERS = 500
NUM_SHARDS = 8

TARGET_ACC_MARGIN = 0.01   # target = sklearn baseline − margin
CONV_EVAL_EVERY = 5        # steps between accuracy checks (one scan program).
                           # The detection loop only finds S = steps-to-
                           # target; wall_to_target is then re-measured as
                           # S-step scanned dispatches with no eval fetches
                           # (pure trajectory cost, _timed_chain protocol)
CONV_MAX_STEPS = 2_000

# Robust convergence protocol (round 3): the round-2 metric was one tuned
# seed-0 banana trajectory — a sampler regression hurting only other
# seeds/folds would have passed.  Now: per dataset, the stepsize is chosen
# on a TUNING seed (grid below, fewest steps wins) and the reported numbers
# are the median/spread of steps-to-target over five DIFFERENT seeds, per
# dataset — mirroring the reference's per-fold acceptance comparison
# (experiments/logreg_plots.py:27-57).  Round 4 extends acceptance to the
# FULL 7-dataset benchmark suite (the reference's grid.sh cross-product,
# /root/reference/grid.sh:1-13) plus two flagship-config rows on banana:
# ``w2`` (the --wasserstein sinkhorn scanned config, h=10.0 — the
# reference driver's weight, experiments/logreg.py:83) and ``partitions``
# (the ring exchange mode) — so every exchange family and the optional
# JKO term have a convergence acceptance, not just a throughput number.
CONV_DATASETS = (
    ("banana", 42), ("diabetis", 1), ("german", 1), ("image", 1),
    ("splice", 1), ("titanic", 1), ("waveform", 1),
)
CONV_TUNE_SEED = 0
CONV_SEEDS = (1, 2, 3, 4, 5)
CONV_STEP_GRID = (0.05, 0.1, 0.2, 0.3, 0.5)
CONV_W2_H = 10.0  # reference experiments/logreg.py:83

#: Flagship-config convergence rows (banana fold, non-north-star configs).
#: Excluded from the headline 7-dataset median; reported per-row.
FLAGSHIP_CONV_ROWS = ("w2", "partitions", "partitions_w2")


def _init_platform():
    """Prefer the real TPU; fall back to CPU (honestly labelled) when the
    chip pool is unavailable."""
    import jax

    try:
        devs = jax.devices()
        return jax.devices()[0].platform, devs
    except Exception as e:  # TPU pool unavailable — rerun on CPU
        print(f"[bench] default backend failed ({type(e).__name__}); CPU fallback", file=sys.stderr)
        from dist_svgd_tpu.utils.platform import force_cpu_backend

        force_cpu_backend()
        return "cpu", jax.devices()


def _fence(x):
    """Force completion with a real device→host round trip.

    ``block_until_ready`` alone is NOT a reliable fence through the axon
    tunnel: the first post-warmup call can return immediately while the scan
    is still in flight (measured: block 0.00 s, then a 3.8 s fetch).  A
    scalar fetch cannot lie."""
    import numpy as np

    np.asarray(x)[0, 0]


#: Fixed per-fenced-sample tunnel round trip (dispatch RPC + scalar fetch),
#: measured ~0.06–0.1 s on the axon relay regardless of workload size
#: (tools/profile_step_floor.py: an empty 1000-iter scan and a single
#: elementwise op cost the same ~95 ms when fenced individually).
_TUNNEL_RT_S = 0.08


def _timed_chain(fn, reps=None, samples=3, target_s=1.0):
    """Best (min) of ``samples`` fenced timings, each the mean wall of
    ``reps`` state-chained runs with one trailing fetch.

    ``fn()`` must return an array whose value depends on the previous call's
    output (e.g. ``run_steps`` advancing sampler state), so the runs execute
    sequentially and cannot be elided.  ``reps=None`` sizes the chain so
    each sample does ~``target_s`` of estimated device work: the tunnel's
    *fixed* per-sample round trip (~0.1 s — dispatch RPC + scalar fetch,
    the same for an empty scan and a 500-step trajectory,
    tools/profile_step_floor.py) then amortises away and the per-rep
    number reflects sustained device throughput rather than RPC latency.
    Round-2 measured a 100-iter small-config dispatch at "0.56 ms/step"
    that this decomposition shows was ≥95% fixed round trip (the marginal
    per-dispatch cost is ~0.2 ms, per-step compute ~2 µs at config-1
    scale).  Chained dispatches pipeline through the relay, so a rep costs
    its execution, not a fresh round trip.  Taking the min across samples
    discards transient slowdowns of the shared TPU pool (±40% between
    sessions, spikes within one — docs/notes.md); the reported number is
    the best *sustained* throughput, still honest because every sample is
    multi-run and fenced."""
    if reps is None:
        # min of 2 estimation runs: a pool spike during a single estimate
        # would mis-size the chain for every sample (the same
        # spike-rejection the timed samples get from min-of-3)
        est = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            _fence(fn())
            est = min(est, time.perf_counter() - t0)  # run + fixed round trip
        marginal = max(est - _TUNNEL_RT_S, 2e-3)
        reps = max(2, min(512, round(target_s / marginal)))
    best = float("inf")
    for _ in range(samples):
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = fn()
        _fence(out)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def _make_sharded(fold, phi_impl="auto", wasserstein=False,
                  mode="all_particles", n=None):
    """The flagship sharded-sampler config, in ONE place — bench rows, the
    perf gate (tools/perf_regress.py), and the large-n tools all build from
    here so a config change cannot silently diverge between them."""
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import logreg_logp
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    data = (jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1)))
    d = 1 + fold.x_train.shape[1]
    particles = init_particles_per_shard(0, n or N_PARTICLES, d, NUM_SHARDS)
    return dt.DistSampler(
        NUM_SHARDS, logreg_logp, None, particles, data=data,
        exchange_particles=(mode != "partitions"), exchange_scores=False,
        include_wasserstein=wasserstein, wasserstein_solver="sinkhorn",
        phi_impl=phi_impl,
    )


def _conv_protocol(fold, fold_idx, sampler, acc_target, h=1.0):
    """The round-3 acceptance protocol for ONE config: tune the stepsize on
    the held-out :data:`CONV_TUNE_SEED` (fewest steps wins, each later grid
    point capped at the incumbent), then report median/spread of
    steps-to-target over :data:`CONV_SEEDS`.  Returns ``(row, state_for,
    best_eps)`` — the latter two feed the flagship wall-clock row."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import statistics

    from dist_svgd_tpu.models.logreg import ensemble_test_accuracy
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    x_test = jnp.asarray(fold.x_test)
    t_test = jnp.asarray(fold.t_test.reshape(-1))
    acc_fn = jax.jit(lambda p: ensemble_test_accuracy(p, x_test, t_test))
    d = 1 + fold.x_train.shape[1]

    def state_for(seed):
        # fresh per-seed init through the resume path: one sampler (and one
        # compiled scan program) serves every seed and stepsize; resetting
        # via load_state_dict also clears the W2 snapshot/dual carry (the
        # dict has no 'previous'/'w2_g' keys)
        return {
            "particles": np.asarray(
                init_particles_per_shard(seed, N_PARTICLES, d, NUM_SHARDS)
            ),
            "t": 0,
        }

    def run_to_target(seed, eps, max_steps=CONV_MAX_STEPS):
        sampler.load_state_dict(state_for(seed))
        steps = 0
        while steps < max_steps:
            sampler.run_steps(CONV_EVAL_EVERY, eps, h=h)
            steps += CONV_EVAL_EVERY
            if float(acc_fn(sampler.particles)) >= acc_target:
                return steps
        return None

    best_eps, best_steps = None, None
    for eps in CONV_STEP_GRID:
        cap = CONV_MAX_STEPS if best_steps is None else best_steps
        s = run_to_target(CONV_TUNE_SEED, eps, max_steps=cap)
        if s is not None and (best_steps is None or s < best_steps):
            best_eps, best_steps = eps, s
    if best_eps is None:
        return (
            {"fold": fold_idx, "steps_median": None,
             "note": "target unreached at every tuning stepsize"},
            state_for, None,
        )

    runs = [run_to_target(seed, best_eps) for seed in CONV_SEEDS]
    reached = [s for s in runs if s is not None]
    row = {
        "fold": fold_idx,
        "stepsize": best_eps,
        "seeds": len(CONV_SEEDS),
        "unreached": len(runs) - len(reached),
        "steps_median": statistics.median(reached) if reached else None,
        "steps_min": min(reached) if reached else None,
        "steps_max": max(reached) if reached else None,
        "_reached": reached,
    }
    return row, state_for, best_eps


def _steps_to_target(_fold_unused=None) -> dict:
    """Median steps-to-target over :data:`CONV_SEEDS` × :data:`CONV_DATASETS`
    (all 7 reference benchmark datasets) on the north-star config, plus the
    ``w2`` (--wasserstein sinkhorn scanned, h=10) and ``partitions`` flagship
    rows on banana; stepsize tuned per config on the held-out
    :data:`CONV_TUNE_SEED` (module docstring / CONV_DATASETS comment)."""
    import statistics

    from dist_svgd_tpu.utils.datasets import load_benchmark

    try:
        from sklearn.linear_model import LogisticRegression
    except ImportError:  # pragma: no cover
        return {"steps_to_target_acc_median": None, "note": "sklearn unavailable"}

    def sk_target(fold):
        clf = LogisticRegression()
        clf.fit(fold.x_train, fold.t_train.reshape(-1))
        baseline = float(clf.score(fold.x_test, fold.t_test.reshape(-1)))
        return baseline, baseline - TARGET_ACC_MARGIN

    per_dataset = {}
    all_steps = []
    banana = None  # (sampler, state_for, best_eps, median) for the wall row
    banana_fold = None  # (fold, baseline, target) reused by the flagship rows
    for name, fold_idx in CONV_DATASETS:
        fold = load_benchmark(name, fold_idx)
        baseline, target = sk_target(fold)
        sampler = _make_sharded(fold)
        row, state_for, best_eps = _conv_protocol(fold, fold_idx, sampler, target)
        all_steps.extend(row.pop("_reached", []))
        row = {"sklearn_acc": round(baseline, 4),
               "target_acc": round(target, 4), **row}
        per_dataset[name] = row
        if name == "banana":
            banana_fold = (fold, baseline, target)
            if row.get("steps_median") is not None:
                banana = (sampler, state_for, best_eps, row["steps_median"])

    # flagship-config rows on the banana fold: the reference's optional
    # --wasserstein term (sinkhorn, scanned, h=10) and the partitions
    # (ring-migration) exchange mode — acceptance, not just throughput,
    # for both (round-4 protocol; these do not enter the headline median,
    # which stays the 7-dataset north-star-config aggregate)
    fold, baseline, target = banana_fold
    for label, kwargs, h in (
        ("w2", dict(wasserstein=True), CONV_W2_H),
        ("partitions", dict(mode="partitions"), 1.0),
        # the COMBINED mode — ring-migration exchange with the JKO term,
        # the exact pairing the 1M-particle row relies on (round-4 VERDICT
        # item 4: it had dryrun + oracle + throughput evidence only)
        ("partitions_w2", dict(mode="partitions", wasserstein=True), CONV_W2_H),
    ):
        row, _, _ = _conv_protocol(
            fold, CONV_DATASETS[0][1], _make_sharded(fold, **kwargs),
            target, h=h,
        )
        row.pop("_reached", None)
        per_dataset[label] = {
            "dataset": CONV_DATASETS[0][0], "sklearn_acc": round(baseline, 4),
            "target_acc": round(target, 4), **row,
        }

    # wall for the flagship dataset at its median step count: S-step scanned
    # dispatches with no eval fetches (pure trajectory cost — the detection
    # loop's per-eval tunnel round trips are measurement, not trajectory)
    wall = None
    if banana is not None:
        sampler, state_for, eps, med = banana
        # a fractional median (even seed count reached) rounds to the
        # CONV_EVAL_EVERY grid the detection ran on, never truncating below
        steps_wall = max(
            CONV_EVAL_EVERY,
            int(round(med / CONV_EVAL_EVERY)) * CONV_EVAL_EVERY,
        )
        sampler.load_state_dict(state_for(CONV_SEEDS[0]))
        run = lambda: sampler.run_steps(steps_wall, eps)
        _fence(run())  # compile, untimed
        sampler.load_state_dict(state_for(CONV_SEEDS[0]))
        wall = _timed_chain(run)

    medians = [v["steps_median"] for k, v in per_dataset.items()
               if k not in FLAGSHIP_CONV_ROWS
               and v.get("steps_median") is not None]
    return {
        "steps_to_target_acc_median": (
            statistics.median(all_steps) if all_steps else None
        ),
        "steps_to_target_acc_spread": (
            [min(all_steps), max(all_steps)] if all_steps else None
        ),
        "steps_to_target_acc_per_dataset_medians": medians,
        "wall_to_target_acc_s": None if wall is None else round(wall, 3),
        "convergence": per_dataset,
    }


def _make_phi_kernel_bench(d: int):
    """Runner for the bare autotuned φ kernel on the north-star shapes —
    the same-session roofline the headline step's utilisation fraction is
    measured against (module comment above).  Returns ``(run_one,
    pairs_per_dispatch)``; ``run_one`` is state-chained across calls (repo
    timing protocol) and also feeds ``tools/perf_regress.py``'s interleaved
    rounds."""
    import jax
    import jax.numpy as jnp

    from dist_svgd_tpu.ops.kernels import RBF
    from dist_svgd_tpu.ops.pallas_svgd import resolve_phi_fn
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    phi_fn = resolve_phi_fn(RBF(1.0), "auto", batch_hint=NUM_SHARDS)
    n_loc = N_PARTICLES // NUM_SHARDS
    x = init_particles_per_shard(0, N_PARTICLES, d, NUM_SHARDS)
    xs = jnp.stack(jnp.array_split(x, NUM_SHARDS))  # (S, n_loc, d) lanes
    s = jnp.ones_like(x)  # stand-in scores: φ cost is score-independent
    sweeps = 200  # scan length per dispatch (~0.15 s of φ work)

    @jax.jit
    def sweep(blocks):
        def body(y, _):
            out = jax.vmap(lambda yb: phi_fn(yb, x, s))(y)
            # output feeds the next rep's input: reps cannot be elided
            return y + 1e-6 * out, None

        return jax.lax.scan(body, blocks, None, length=sweeps)[0]

    state = {"x": xs}

    def run_one():
        state["x"] = sweep(state["x"])  # state-chained across dispatches
        return state["x"]

    return run_one, NUM_SHARDS * n_loc * N_PARTICLES * sweeps


def _phi_kernel_pairs_per_sec(d: int) -> float:
    """Sustained pairs/s of :func:`_make_phi_kernel_bench`'s runner."""
    run_one, pairs = _make_phi_kernel_bench(d)
    _fence(run_one())  # compile, untimed
    return pairs / _timed_chain(run_one)


def _config45_acceptance():
    """Configs 4/5 accuracy acceptance, IN the driver's evidence (round-4
    VERDICT item 2 of "what's weak"): the covertype steps-to-sklearn-target
    and BNN steps-to-beat-BayesianRidge protocols live in
    ``experiments/bench_suite.py`` (``--acceptance``); run them here so a
    config-4/5 accuracy regression turns into a null/red field in BENCH_r*,
    not just in a tool nobody re-ran.  Returns ``(covertype_row, bnn_row)``
    dicts (an ``error`` key instead, never an exception — the headline
    numbers must survive an acceptance harness failure)."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "experiments"))
    try:
        from bench_suite import bench_covertype_minibatch

        ct = bench_covertype_minibatch(2, acceptance=True)
        ct_row = {k: ct.get(k) for k in
                  ("sklearn_acc", "target_acc", "steps_to_target", "final_acc")}
    except Exception as e:  # pragma: no cover — never break the bench
        ct_row = {"error": f"{type(e).__name__}: {e}"[:120]}
    try:
        from bench_suite import bench_bnn

        bn = bench_bnn(2, acceptance=True)
        bnn_row = {k: bn.get(k) for k in
                   ("bayesridge_rmse", "steps_to_target", "final_rmse")}
    except Exception as e:  # pragma: no cover
        bnn_row = {"error": f"{type(e).__name__}: {e}"[:120]}
    return ct_row, bnn_row


def _compact_summary(out: dict) -> dict:
    """The one-line stdout record: every headline + acceptance field, none
    of the bulk.  Kept ≤ :data:`_MAX_STDOUT_BYTES` by dropping optional
    keys (never the metric contract fields) if a long error string ever
    bloats it."""
    conv = out.get("convergence") or {}

    def med(k):
        return (conv.get(k) or {}).get("steps_median")

    compact = {
        "metric": "particle_updates_per_sec",
        "value": out["value"],
        "unit": out["unit"],
        "vs_baseline": out["vs_baseline"],
        "platform": out["platform"],
        "n_particles": out["n_particles"],
        "num_shards": out["num_shards"],
        "wall_s": out["wall_s"],
        "pairs_per_sec": out.get("pairs_per_sec"),
        "fraction_of_phi_roofline": out.get("fraction_of_phi_roofline"),
        "covertype_bf16x3_speedup": out.get("covertype_bf16x3_speedup"),
        "w2_sinkhorn_ms_per_step": out.get("w2_sinkhorn_ms_per_step"),
        "w2_streaming_100k_ms_per_step": out.get("w2_streaming_100k_ms_per_step"),
        "single_device_updates_per_sec": out.get("single_device_updates_per_sec"),
        "steps_to_target_acc_median": out.get("steps_to_target_acc_median"),
        "steps_to_target_acc_spread": out.get("steps_to_target_acc_spread"),
        "convergence_rows": len(conv) or None,
        "convergence_unreached_total": (
            sum((r or {}).get("unreached") or 0 for r in conv.values())
            if conv else None
        ),
        "flagship_steps_median": (
            {k: med(k) for k in FLAGSHIP_CONV_ROWS if k in conv} or None
        ),
        "covertype_acceptance": out.get("covertype_acceptance"),
        "bnn_acceptance": out.get("bnn_acceptance"),
        "tpu_test_tier": out.get("tpu_test_tier"),
        "detail": "bench_detail.json + stderr (full record)",
    }
    droppable = ("detail", "single_device_updates_per_sec",
                 "steps_to_target_acc_spread", "flagship_steps_median",
                 "covertype_bf16x3_speedup", "w2_streaming_100k_ms_per_step",
                 "w2_sinkhorn_ms_per_step", "pairs_per_sec",
                 # last resorts — real evidence, but a record that does not
                 # parse carries none at all
                 "covertype_acceptance", "bnn_acceptance")
    for key in droppable:
        if len(json.dumps(compact)) <= _MAX_STDOUT_BYTES:
            break
        compact.pop(key, None)
    # belt-and-braces: every droppable key gone and still over budget can
    # only mean a runaway string field — truncate the longest ones in place
    # rather than emit a line the driver's tail window would cut mid-JSON
    while len(json.dumps(compact)) > _MAX_STDOUT_BYTES:
        key = max((k for k, v in compact.items() if isinstance(v, str)),
                  key=lambda k: len(compact[k]), default=None)
        if key is None or len(compact[key]) <= 40:
            break  # nothing left to shrink (unreachable for real records)
        compact[key] = compact[key][: max(40, len(compact[key]) // 2)]
    return compact


def _run_tpu_test_tier() -> str:
    """Run the real-Mosaic pytest tier (``DSVGD_TPU_TESTS=1 pytest -m tpu``,
    tests/test_tpu_kernels.py) in a subprocess and return its one-line
    result — so every BENCH_r* carries the hardware-pinning evidence, not
    just throughput numbers (the round-3 verdict's ask: a Mosaic-only
    kernel regression should be a red test in the driver's record)."""
    import os
    import re
    import subprocess

    env = dict(os.environ, DSVGD_TPU_TESTS="1")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "tests", "-m", "tpu", "-q",
             "--no-header", "-p", "no:cacheprovider"],
            capture_output=True, timeout=900, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        tail = (proc.stdout or b"").decode(errors="replace").strip().splitlines()
        summary = next(
            (ln for ln in reversed(tail) if re.search(r"\d+ (passed|failed)", ln)),
            tail[-1] if tail else "no output",
        ).strip("= ")
        if proc.returncode != 0 or "passed" not in summary:
            # a tier that failed, errored out, or never ran (e.g. a TPU
            # runtime that refuses a second process's backend init → the
            # tests all auto-skip) must not read as benign evidence.
            # Bounded: the summary can be an arbitrary last stdout line
            # (crash traceback), and an unbounded string would push the
            # compact record past the driver's tail window
            err_tail = (proc.stderr or b"").decode(errors="replace").strip()
            return (f"NOT GREEN (exit {proc.returncode}): {summary[:300]}"
                    + (f" | stderr: {err_tail[-200:]}" if err_tail else ""))
        return summary[:300]
    except subprocess.TimeoutExpired:
        return "TIMEOUT after 900 s"
    except Exception as e:  # pragma: no cover — never break the bench
        return f"tier run failed: {type(e).__name__}: {e}"


def main():
    platform, devs = _init_platform()

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import make_logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark

    fold = load_benchmark("banana", 42)
    d = 1 + fold.x_train.shape[1]
    on_cpu = platform == "cpu"
    n_iters = N_ITERS if not on_cpu else 50  # CPU: measure less, same metric

    # --- headline: the sharded north-star path (BASELINE.json) -----------
    sharded = _make_sharded(fold)
    _fence(sharded.run_steps(n_iters, 3e-3))  # compile, untimed
    wall = _timed_chain(lambda: sharded.run_steps(n_iters, 3e-3))
    sharded_ups = N_PARTICLES * n_iters / wall
    # same-session φ-kernel roofline, measured back-to-back with the step it
    # normalises (see the utilisation comment below) — TPU only
    roofline = _phi_kernel_pairs_per_sec(d) if platform == "tpu" else None

    # --- the bf16x3 fast tier, benched on its home ground: a big-d
    # (covertype, d=55) minibatched config where both MXU contractions run
    # as 3-pass bf16x3 splits (measured 1.3× vs exact f32 there —
    # docs/notes.md).  The small-d north star's drive has no MXU, so bf16
    # is parity-at-best there and is NOT reported (round-3 verdict: no
    # uninterpreted losing rows); the f32 counterpart runs interleaved so
    # the speedup ratio is same-session, not cross-session noise
    ct_bf16_ups = ct_f32_ups = None
    if platform == "tpu":  # off-TPU the pallas path runs the interpreter
        import jax.numpy as jnp

        import dist_svgd_tpu as dt_mod
        from dist_svgd_tpu.models.logreg import logreg_likelihood, logreg_prior
        from dist_svgd_tpu.utils.datasets import load_covertype
        from dist_svgd_tpu.utils.rng import init_particles_per_shard

        cx, ct_lab = load_covertype(50_000)
        ct_data = (jnp.asarray(cx), jnp.asarray(ct_lab))
        ct_d = 1 + cx.shape[1]
        ct_parts = init_particles_per_shard(0, N_PARTICLES, ct_d, NUM_SHARDS)

        def make_ct(phi_impl):
            return dt_mod.DistSampler(
                NUM_SHARDS, logreg_likelihood, None, ct_parts, data=ct_data,
                exchange_particles=True, exchange_scores=False,
                include_wasserstein=False, shard_data=True, batch_size=256,
                log_prior=logreg_prior, phi_impl=phi_impl,
            )

        ct_iters = 100
        ct16, ct32 = make_ct("pallas_bf16"), make_ct("pallas")
        _fence(ct16.run_steps(ct_iters, 1e-4))  # compile, untimed
        _fence(ct32.run_steps(ct_iters, 1e-4))
        ct_bf16_wall = _timed_chain(lambda: ct16.run_steps(ct_iters, 1e-4))
        ct_f32_wall = _timed_chain(lambda: ct32.run_steps(ct_iters, 1e-4))
        ct_bf16_ups = N_PARTICLES * ct_iters / ct_bf16_wall
        ct_f32_ups = N_PARTICLES * ct_iters / ct_f32_wall

    # --- the reference's flagship optional term: --wasserstein (JKO) ------
    # (dsvgd/distsampler.py:103-129).  Scanned Sinkhorn path with the
    # warm-started duals (carried g in the scan state); 100 iters is enough
    # to time a per-step cost that is ~25x the plain step's.  TPU only —
    # the CPU fallback would time the backend, not the framework
    w2_ups = w2_ms = None
    if platform == "tpu":
        w2_iters = 100
        w2 = _make_sharded(fold, wasserstein=True)
        _fence(w2.run_steps(w2_iters, 3e-3, h=10.0))  # compile, untimed
        w2_wall = _timed_chain(lambda: w2.run_steps(w2_iters, 3e-3, h=10.0))
        w2_ups = N_PARTICLES * w2_iters / w2_wall
        w2_ms = w2_wall / w2_iters * 1e3

    # --- streaming W2 at 100k particles, warm-started (round 4): each
    # shard's (12.5k, 100k) solve is past the HBM cliff (a 5 GB kernel
    # matrix), so 'auto' streams kernel tiles from coordinates
    # (ops/pallas_ot.py:sinkhorn_grad_streaming) with the carried dual
    # warm-starting consecutive solves — the warm win harvested exactly
    # where solves are most expensive (vs the 322 ms cold solve,
    # docs/notes.md large-n section; tools/w2_bench.py --n 100000
    # --no-fixed measures the cold/warm pair)
    w2s_ms = None
    if platform == "tpu":
        k100 = 5
        w2s = _make_sharded(fold, wasserstein=True, n=100_000)
        _fence(w2s.run_steps(k100, 3e-3, h=10.0))  # compile, untimed
        w2s_wall = _timed_chain(lambda: w2s.run_steps(k100, 3e-3, h=10.0))
        w2s_ms = w2s_wall / k100 * 1e3

    # --- context: single-device unsharded step ---------------------------
    # reps chain through initial_particles so each run depends on the
    # previous one's output (_timed_chain's precondition: no rep can be
    # elided, overlapped, or served from a relay cache)
    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))

    def chained_runner(sampler, n, iters):
        state = {"out": None}

        def run_one():
            state["out"] = sampler.run(
                n, iters, 3e-3, seed=0,
                record=False, initial_particles=state["out"],
            )[0]
            return state["out"]

        return run_one

    run_one = chained_runner(dt.Sampler(d, logp), N_PARTICLES, n_iters)
    _fence(run_one())  # compile, untimed
    single_wall = _timed_chain(run_one)
    single_ups = N_PARTICLES * n_iters / single_wall

    # --- reference's exact headline config (50 particles, 500 iters) -----
    small_run = chained_runner(dt.Sampler(d, logp), 50, 500)
    _fence(small_run())
    small_wall = _timed_chain(small_run)

    # --- convergence half of the metric (TPU only — 10k particles on the
    # CPU fallback would take minutes and measure nothing new) ------------
    conv = _steps_to_target() if not on_cpu else {"steps_to_target_acc_median": None}

    # --- configs 4/5 accuracy acceptance (TPU only — the harness runs
    # thousands of 10k-particle minibatched steps) -----------------------
    ct_acc = bnn_acc = None
    if platform == "tpu":
        ct_acc, bnn_acc = _config45_acceptance()

    # machine-checked utilisation: the north-star step computes n² kernel
    # pairs per iteration (8 shards × (n/8 local × n global)); its fraction
    # of the SAME-SESSION bare-φ-kernel rate (measured above, back-to-back
    # with the step) is the auditable MFU story — pool noise hits both
    # numbers together and cancels (TPU only: the CPU fallback's φ path is
    # not the Pallas kernel)
    pairs_per_sec = N_PARTICLES * N_PARTICLES * n_iters / wall

    out = {
        "metric": "particle_updates_per_sec (BayesLR banana, 10k particles, "
                  "8-shard all_particles north star)",
        "value": round(sharded_ups, 1),
        "unit": "updates/sec",
        "vs_baseline": round(sharded_ups / REFERENCE_BEST_UPDATES_PER_SEC, 2),
        "platform": platform,
        "n_particles": N_PARTICLES,
        "n_iters_measured": n_iters,
        "num_shards": NUM_SHARDS,
        "emulated_shards": len(devs) < NUM_SHARDS,
        "wall_s": round(wall, 3),
        "pairs_per_sec": round(pairs_per_sec, 1),
        "phi_roofline_pairs_per_sec": (
            None if roofline is None else round(roofline, 1)
        ),
        "fraction_of_phi_roofline": (
            None if roofline is None else round(pairs_per_sec / roofline, 3)
        ),
        "covertype_acceptance": ct_acc,
        "bnn_acceptance": bnn_acc,
        "covertype_bf16x3_updates_per_sec": (
            None if ct_bf16_ups is None else round(ct_bf16_ups, 1)
        ),
        "covertype_f32_updates_per_sec": (
            None if ct_f32_ups is None else round(ct_f32_ups, 1)
        ),
        "covertype_bf16x3_speedup": (
            None if ct_bf16_ups is None else round(ct_bf16_ups / ct_f32_ups, 3)
        ),
        "w2_sinkhorn_updates_per_sec": None if w2_ups is None else round(w2_ups, 1),
        "w2_sinkhorn_ms_per_step": None if w2_ms is None else round(w2_ms, 2),
        "w2_streaming_100k_ms_per_step": None if w2s_ms is None else round(w2s_ms, 2),
        "single_device_updates_per_sec": round(single_ups, 1),
        "single_device_wall_s": round(single_wall, 3),
        "ref_headline_config_wall_s": round(small_wall, 3),
        "ref_headline_config_ref_wall_s": 2007.11,
    }
    out.update(conv)
    # hardware-pinning evidence rides along with the numbers (TPU only;
    # the subprocess runs after every measurement so it cannot contaminate
    # the timed sections — two concurrent TPU workloads measured 6× noise,
    # docs/notes.md timing protocol)
    if platform == "tpu":
        out["tpu_test_tier"] = _run_tpu_test_tier()

    # full record: pretty file + one stderr line; stdout gets ONLY the
    # compact line (≤ _MAX_STDOUT_BYTES, module docstring's output contract)
    detail_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_detail.json")
    try:
        with open(detail_path, "w") as fh:
            json.dump(out, fh, indent=2)
            fh.write("\n")
    except OSError as e:  # read-only checkout: stderr still has it
        print(f"[bench] could not write {detail_path}: {e}", file=sys.stderr)
    print(json.dumps(out), file=sys.stderr)
    print(json.dumps(_compact_summary(out)))


if __name__ == "__main__":
    main()
