"""Benchmark harness — prints ONE JSON line with the primary metric.

Primary metric (BASELINE.md): SVGD particle-updates/sec **plus
steps-to-target-accuracy** on distributed Bayesian logistic regression
(banana fold 42).  The reference's published numbers (notes.md:120-135,
reproduced in BASELINE.md) top out at **421 updates/sec** at world size 8
(50 particles, 500 iterations, CPU); world size 1 is 12.5 up/s.
``vs_baseline`` is measured-updates/sec divided by the reference's best (421).

The headline number runs the **north-star path** (BASELINE.json): the 10k
particle array sharded over 8 shards in ``all_particles`` exchange mode —
each shard updates its block against the ``lax.all_gather``-ed global set —
driven through ``DistSampler.run_steps`` (one ``lax.scan`` dispatch for the
whole trajectory).  On the single-chip pool this executes the identical SPMD
program under vmap emulation — an honest single-chip number.  Round-2
interleaved A/B measurement put the emulated sharded step at parity with the
unsharded one (wall ratio 0.82–1.16 across repeats, within the pool's noise
band; the round-1 "2× emulation gap" did not reproduce — docs/notes.md).
The unsharded single-device number is reported alongside for context.

The convergence half of the metric runs the same 10k-particle config until
the ensemble posterior-predictive accuracy reaches the sklearn
LogisticRegression baseline − 0.01 (the reference's acceptance comparison,
experiments/logreg_plots.py:37-57).  Round-3 protocol: per dataset
(banana/diabetis/waveform), the stepsize is tuned on a held-out seed and
the reported ``steps_to_target_acc_median`` / ``_spread`` aggregate five
*different* seeds — per-dataset rows in ``convergence``, the way the
reference's acceptance comparison is per-fold.  ``wall_to_target_acc_s``
times the flagship (banana) median-step trajectory as pure scanned
dispatches.  Compile time is excluded by warming the scan, then resetting
the sampler state via ``state_dict``/``load_state_dict``.

Timing is the best of 3 fenced samples, each the mean wall of an
adaptively-sized chain of state-chained scan runs under one trailing fetch
(~1 s of device work per sample, so the tunnel's fixed ~0.1 s per-sample
round trip amortises away — the round-3 protocol; the TPU pool behind the
tunnel has ±40% session variance with within-session spikes, and per-call
eager timing is round-trip-bound and useless — docs/notes.md and
``_timed_chain``).
"""

import json
import sys
import time


REFERENCE_BEST_UPDATES_PER_SEC = 421.0  # notes.md:129 (ws=8) via BASELINE.md
N_PARTICLES = 10_000
N_ITERS = 500
NUM_SHARDS = 8

TARGET_ACC_MARGIN = 0.01   # target = sklearn baseline − margin
CONV_EVAL_EVERY = 5        # steps between accuracy checks (one scan program).
                           # The detection loop only finds S = steps-to-
                           # target; wall_to_target is then re-measured as
                           # S-step scanned dispatches with no eval fetches
                           # (pure trajectory cost, _timed_chain protocol)
CONV_MAX_STEPS = 2_000

# Robust convergence protocol (round 3): the round-2 metric was one tuned
# seed-0 banana trajectory — a sampler regression hurting only other
# seeds/folds would have passed.  Now: per dataset, the stepsize is chosen
# on a TUNING seed (grid below, fewest steps wins) and the reported numbers
# are the median/spread of steps-to-target over five DIFFERENT seeds, per
# dataset — mirroring the reference's per-fold acceptance comparison
# (experiments/logreg_plots.py:27-57).
CONV_DATASETS = (("banana", 42), ("diabetis", 1), ("waveform", 1))
CONV_TUNE_SEED = 0
CONV_SEEDS = (1, 2, 3, 4, 5)
CONV_STEP_GRID = (0.05, 0.1, 0.2, 0.3, 0.5)


def _init_platform():
    """Prefer the real TPU; fall back to CPU (honestly labelled) when the
    chip pool is unavailable."""
    import jax

    try:
        devs = jax.devices()
        return jax.devices()[0].platform, devs
    except Exception as e:  # TPU pool unavailable — rerun on CPU
        print(f"[bench] default backend failed ({type(e).__name__}); CPU fallback", file=sys.stderr)
        from dist_svgd_tpu.utils.platform import force_cpu_backend

        force_cpu_backend()
        return "cpu", jax.devices()


def _fence(x):
    """Force completion with a real device→host round trip.

    ``block_until_ready`` alone is NOT a reliable fence through the axon
    tunnel: the first post-warmup call can return immediately while the scan
    is still in flight (measured: block 0.00 s, then a 3.8 s fetch).  A
    scalar fetch cannot lie."""
    import numpy as np

    np.asarray(x)[0, 0]


#: Fixed per-fenced-sample tunnel round trip (dispatch RPC + scalar fetch),
#: measured ~0.06–0.1 s on the axon relay regardless of workload size
#: (tools/profile_step_floor.py: an empty 1000-iter scan and a single
#: elementwise op cost the same ~95 ms when fenced individually).
_TUNNEL_RT_S = 0.08


def _timed_chain(fn, reps=None, samples=3, target_s=1.0):
    """Best (min) of ``samples`` fenced timings, each the mean wall of
    ``reps`` state-chained runs with one trailing fetch.

    ``fn()`` must return an array whose value depends on the previous call's
    output (e.g. ``run_steps`` advancing sampler state), so the runs execute
    sequentially and cannot be elided.  ``reps=None`` sizes the chain so
    each sample does ~``target_s`` of estimated device work: the tunnel's
    *fixed* per-sample round trip (~0.1 s — dispatch RPC + scalar fetch,
    the same for an empty scan and a 500-step trajectory,
    tools/profile_step_floor.py) then amortises away and the per-rep
    number reflects sustained device throughput rather than RPC latency.
    Round-2 measured a 100-iter small-config dispatch at "0.56 ms/step"
    that this decomposition shows was ≥95% fixed round trip (the marginal
    per-dispatch cost is ~0.2 ms, per-step compute ~2 µs at config-1
    scale).  Chained dispatches pipeline through the relay, so a rep costs
    its execution, not a fresh round trip.  Taking the min across samples
    discards transient slowdowns of the shared TPU pool (±40% between
    sessions, spikes within one — docs/notes.md); the reported number is
    the best *sustained* throughput, still honest because every sample is
    multi-run and fenced."""
    if reps is None:
        # min of 2 estimation runs: a pool spike during a single estimate
        # would mis-size the chain for every sample (the same
        # spike-rejection the timed samples get from min-of-3)
        est = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            _fence(fn())
            est = min(est, time.perf_counter() - t0)  # run + fixed round trip
        marginal = max(est - _TUNNEL_RT_S, 2e-3)
        reps = max(2, min(512, round(target_s / marginal)))
    best = float("inf")
    for _ in range(samples):
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = fn()
        _fence(out)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def _make_sharded(fold, phi_impl="auto", wasserstein=False):
    import jax.numpy as jnp

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import logreg_logp
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    data = (jnp.asarray(fold.x_train), jnp.asarray(fold.t_train.reshape(-1)))
    d = 1 + fold.x_train.shape[1]
    particles = init_particles_per_shard(0, N_PARTICLES, d, NUM_SHARDS)
    return dt.DistSampler(
        NUM_SHARDS, logreg_logp, None, particles, data=data,
        exchange_particles=True, exchange_scores=False,
        include_wasserstein=wasserstein, wasserstein_solver="sinkhorn",
        phi_impl=phi_impl,
    )


def _steps_to_target(_fold_unused=None) -> dict:
    """Median steps-to-target over :data:`CONV_SEEDS` × :data:`CONV_DATASETS`
    on the north-star config, stepsize tuned per dataset on the held-out
    :data:`CONV_TUNE_SEED` (module docstring / CONV_DATASETS comment)."""
    import statistics

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dist_svgd_tpu.models.logreg import ensemble_test_accuracy
    from dist_svgd_tpu.utils.datasets import load_benchmark
    from dist_svgd_tpu.utils.rng import init_particles_per_shard

    try:
        from sklearn.linear_model import LogisticRegression
    except ImportError:  # pragma: no cover
        return {"steps_to_target_acc_median": None, "note": "sklearn unavailable"}

    per_dataset = {}
    all_steps = []
    banana = None  # (sampler, state_for, best_eps, median) for the wall row
    for name, fold_idx in CONV_DATASETS:
        fold = load_benchmark(name, fold_idx)
        clf = LogisticRegression()
        clf.fit(fold.x_train, fold.t_train.reshape(-1))
        baseline = float(clf.score(fold.x_test, fold.t_test.reshape(-1)))
        target = baseline - TARGET_ACC_MARGIN

        x_test = jnp.asarray(fold.x_test)
        t_test = jnp.asarray(fold.t_test.reshape(-1))
        acc_fn = jax.jit(lambda p: ensemble_test_accuracy(p, x_test, t_test))
        sampler = _make_sharded(fold)
        d = 1 + fold.x_train.shape[1]

        def state_for(seed, d=d):
            # fresh per-seed init through the resume path: one sampler (and
            # one compiled scan program) serves every seed and stepsize.
            # d bound by default arg: this closure escapes the dataset loop
            # (the banana wall row below) and must not see a later d
            return {
                "particles": np.asarray(
                    init_particles_per_shard(seed, N_PARTICLES, d, NUM_SHARDS)
                ),
                "t": 0,
            }

        def run_to_target(seed, eps, max_steps=CONV_MAX_STEPS):
            sampler.load_state_dict(state_for(seed))
            steps = 0
            while steps < max_steps:
                sampler.run_steps(CONV_EVAL_EVERY, eps)
                steps += CONV_EVAL_EVERY
                if float(acc_fn(sampler.particles)) >= target:
                    return steps
            return None

        # stepsize: fewest tuning-seed steps wins (ties → smaller stepsize);
        # the tuning seed is NOT among the reported seeds.  Each grid point
        # is capped at the current winner's step count — a stepsize that
        # cannot beat it has nothing left to prove, and an early diverging
        # candidate would otherwise burn CONV_MAX_STEPS of eval round trips
        best_eps, best_steps = None, None
        for eps in CONV_STEP_GRID:
            cap = CONV_MAX_STEPS if best_steps is None else best_steps
            s = run_to_target(CONV_TUNE_SEED, eps, max_steps=cap)
            if s is not None and (best_steps is None or s < best_steps):
                best_eps, best_steps = eps, s
        if best_eps is None:
            per_dataset[name] = {
                "fold": fold_idx, "sklearn_acc": round(baseline, 4),
                "target_acc": round(target, 4), "steps_median": None,
                "note": "target unreached at every tuning stepsize",
            }
            continue

        runs = [run_to_target(seed, best_eps) for seed in CONV_SEEDS]
        reached = [s for s in runs if s is not None]
        all_steps.extend(reached)
        med = statistics.median(reached) if reached else None
        per_dataset[name] = {
            "fold": fold_idx,
            "sklearn_acc": round(baseline, 4),
            "target_acc": round(target, 4),
            "stepsize": best_eps,
            "seeds": len(CONV_SEEDS),
            "unreached": len(runs) - len(reached),
            "steps_median": med,
            "steps_min": min(reached) if reached else None,
            "steps_max": max(reached) if reached else None,
        }
        if name == "banana":
            banana = (sampler, state_for, best_eps, med)

    # wall for the flagship dataset at its median step count: S-step scanned
    # dispatches with no eval fetches (pure trajectory cost — the detection
    # loop's per-eval tunnel round trips are measurement, not trajectory)
    wall = None
    if banana is not None and banana[3] is not None:
        sampler, state_for, eps, med = banana
        # a fractional median (even seed count reached) rounds to the
        # CONV_EVAL_EVERY grid the detection ran on, never truncating below
        steps_wall = max(
            CONV_EVAL_EVERY,
            int(round(med / CONV_EVAL_EVERY)) * CONV_EVAL_EVERY,
        )
        sampler.load_state_dict(state_for(CONV_SEEDS[0]))
        run = lambda: sampler.run_steps(steps_wall, eps)
        _fence(run())  # compile, untimed
        sampler.load_state_dict(state_for(CONV_SEEDS[0]))
        wall = _timed_chain(run)

    medians = [v["steps_median"] for v in per_dataset.values()
               if v.get("steps_median") is not None]
    return {
        "steps_to_target_acc_median": (
            statistics.median(all_steps) if all_steps else None
        ),
        "steps_to_target_acc_spread": (
            [min(all_steps), max(all_steps)] if all_steps else None
        ),
        "steps_to_target_acc_per_dataset_medians": medians,
        "wall_to_target_acc_s": None if wall is None else round(wall, 3),
        "convergence": per_dataset,
    }


def main():
    platform, devs = _init_platform()

    import dist_svgd_tpu as dt
    from dist_svgd_tpu.models.logreg import make_logreg_logp
    from dist_svgd_tpu.utils.datasets import load_benchmark

    fold = load_benchmark("banana", 42)
    d = 1 + fold.x_train.shape[1]
    on_cpu = platform == "cpu"
    n_iters = N_ITERS if not on_cpu else 50  # CPU: measure less, same metric

    # --- headline: the sharded north-star path (BASELINE.json) -----------
    sharded = _make_sharded(fold)
    _fence(sharded.run_steps(n_iters, 3e-3))  # compile, untimed
    wall = _timed_chain(lambda: sharded.run_steps(n_iters, 3e-3))
    sharded_ups = N_PARTICLES * n_iters / wall

    # --- context: the same sharded config on the reduced-precision kernel
    # (opt-in phi_impl='pallas_bf16'; at this small-d shape that is the
    # bf16-exp variant, ~3e-4 phi error — converges to the
    # same accuracy at the bench stepsize, docs/notes.md; reported as
    # context, never as the exact-math headline)
    bf16_ups = None
    if platform == "tpu":  # off-TPU the pallas path runs the interpreter
        sharded16 = _make_sharded(fold, phi_impl="pallas_bf16")
        _fence(sharded16.run_steps(n_iters, 3e-3))
        bf16_wall = _timed_chain(lambda: sharded16.run_steps(n_iters, 3e-3))
        bf16_ups = N_PARTICLES * n_iters / bf16_wall

    # --- the reference's flagship optional term: --wasserstein (JKO) ------
    # (dsvgd/distsampler.py:103-129).  Scanned Sinkhorn path with the
    # warm-started duals (carried g in the scan state); 100 iters is enough
    # to time a per-step cost that is ~25x the plain step's.  TPU only —
    # the CPU fallback would time the backend, not the framework
    w2_ups = w2_ms = None
    if platform == "tpu":
        w2_iters = 100
        w2 = _make_sharded(fold, wasserstein=True)
        _fence(w2.run_steps(w2_iters, 3e-3, h=10.0))  # compile, untimed
        w2_wall = _timed_chain(lambda: w2.run_steps(w2_iters, 3e-3, h=10.0))
        w2_ups = N_PARTICLES * w2_iters / w2_wall
        w2_ms = w2_wall / w2_iters * 1e3

    # --- context: single-device unsharded step ---------------------------
    # reps chain through initial_particles so each run depends on the
    # previous one's output (_timed_chain's precondition: no rep can be
    # elided, overlapped, or served from a relay cache)
    logp = make_logreg_logp(fold.x_train, fold.t_train.reshape(-1))

    def chained_runner(sampler, n, iters):
        state = {"out": None}

        def run_one():
            state["out"] = sampler.run(
                n, iters, 3e-3, seed=0,
                record=False, initial_particles=state["out"],
            )[0]
            return state["out"]

        return run_one

    run_one = chained_runner(dt.Sampler(d, logp), N_PARTICLES, n_iters)
    _fence(run_one())  # compile, untimed
    single_wall = _timed_chain(run_one)
    single_ups = N_PARTICLES * n_iters / single_wall

    # --- reference's exact headline config (50 particles, 500 iters) -----
    small_run = chained_runner(dt.Sampler(d, logp), 50, 500)
    _fence(small_run())
    small_wall = _timed_chain(small_run)

    # --- convergence half of the metric (TPU only — 10k particles on the
    # CPU fallback would take minutes and measure nothing new) ------------
    conv = _steps_to_target() if not on_cpu else {"steps_to_target_acc_median": None}

    out = {
        "metric": "particle_updates_per_sec (BayesLR banana, 10k particles, "
                  "8-shard all_particles north star)",
        "value": round(sharded_ups, 1),
        "unit": "updates/sec",
        "vs_baseline": round(sharded_ups / REFERENCE_BEST_UPDATES_PER_SEC, 2),
        "platform": platform,
        "n_particles": N_PARTICLES,
        "n_iters_measured": n_iters,
        "num_shards": NUM_SHARDS,
        "emulated_shards": len(devs) < NUM_SHARDS,
        "wall_s": round(wall, 3),
        "sharded_bf16_updates_per_sec": None if bf16_ups is None else round(bf16_ups, 1),
        "w2_sinkhorn_updates_per_sec": None if w2_ups is None else round(w2_ups, 1),
        "w2_sinkhorn_ms_per_step": None if w2_ms is None else round(w2_ms, 2),
        "single_device_updates_per_sec": round(single_ups, 1),
        "single_device_wall_s": round(single_wall, 3),
        "ref_headline_config_wall_s": round(small_wall, 3),
        "ref_headline_config_ref_wall_s": 2007.11,
    }
    out.update(conv)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
